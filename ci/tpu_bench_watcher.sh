#!/bin/bash
# Round-5 tunnel watcher: probe TPU enumeration every cycle; at each
# healthy window capture in two stages and commit each immediately
# (VERDICT r4 "Next round" #1: capture EARLY and OFTEN, not at round end):
#   1. the default HEADLINE bench (~30 s warm) -> BENCH_FULL_r05_headline.json
#      — the scoreboard number, grabbed first because wedge windows can be
#      shorter than the full section list (round 5 saw a 90 s window);
#   2. the full section list -> BENCH_FULL_r05.json. bench.py flushes the
#      artifact after EVERY section AND merges with the artifact's prior
#      contents (union by metric name, newest wins), so a wedge mid-run
#      still leaves the finished sections and a later, shorter window
#      cannot clobber an earlier, richer capture; this script just
#      commits whatever exists after each attempt.
# Exits after a fully-successful full bench+commit; a supervising loop may
# restart it for later re-captures.
set -u
cd /root/repo
LOG=${1:-/tmp/tpu_watcher.log}
ART=${2:-BENCH_FULL_r05.json}
HEADLINE_ART=BENCH_FULL_r05_headline.json
echo "[watcher] start $(date -u +%FT%TZ) artifact=$ART" >> "$LOG"
while true; do
    if timeout 90 python -c "import jax; jax.devices()" >> "$LOG" 2>&1; then
        echo "[watcher] tunnel healthy $(date -u +%FT%TZ); headline first" >> "$LOG"
        # Liveness gate: BOTH fallback forms (cached replay AND the
        # zero-value no-cached-artifact line) exit 1, so rc==0 is the
        # live-measurement signal; the provenance check in the rewriter
        # below is a second, belt-and-braces gate.
        if HL=$(timeout 900 python bench.py 2>> "$LOG"); then
            if python - "$HL" <<'EOF' >> "$LOG" 2>&1
import json, sys, datetime
entry = json.loads(sys.argv[1])
if entry.get("provenance") == "cached" or not entry.get("value"):
    raise SystemExit(f"not a live measurement: {entry}")
entry["provenance"] = "live"
entry["measured_at"] = datetime.datetime.now(
    datetime.timezone.utc).strftime("%Y-%m-%dT%H:%M:%SZ")
entry["note"] = ("Round-5 live headline captured by ci/tpu_bench_watcher.sh "
                 "at a healthy tunnel window (headline-first staging).")
json.dump([entry], open("BENCH_FULL_r05_headline.json", "w"), indent=1)
EOF
            then
                if git add "$HEADLINE_ART" >> "$LOG" 2>&1 \
                   && git commit -m "Live TPU headline capture: $HEADLINE_ART" \
                          --only "$HEADLINE_ART" >> "$LOG" 2>&1; then
                    echo "[watcher] headline captured + committed $(date -u +%FT%TZ)" >> "$LOG"
                else
                    # Commit can legitimately no-op (identical re-capture);
                    # log and continue to the full bench either way.
                    echo "[watcher] headline commit no-op/failed $(date -u +%FT%TZ)" >> "$LOG"
                fi
            else
                echo "[watcher] headline rewrite rejected $(date -u +%FT%TZ); retrying next cycle" >> "$LOG"
                sleep 180
                continue
            fi
        else
            echo "[watcher] headline not live (rc=$?) $(date -u +%FT%TZ); retrying next cycle" >> "$LOG"
            sleep 180
            continue
        fi
        echo "[watcher] running bench --full" >> "$LOG"
        # bench.py itself merges with any existing artifact at every
        # per-section flush (newest wins per metric), so a re-run after a
        # partial capture EXTENDS the artifact; this script only commits
        # whatever exists afterward — a partial capture is chip evidence.
        timeout 5400 python bench.py --full --artifact "$ART" >> "$LOG" 2>&1
        rc=$?
        if [ -s "$ART" ]; then
            n=$(python -c "import json;print(len(json.load(open('$ART'))))" 2>> "$LOG")
            if [ "$rc" -eq 0 ]; then
                msg="Live TPU bench capture: $ART"
            else
                msg="Live TPU bench capture (partial, ${n:-?} entries, wedge mid-run): $ART"
            fi
            if git add "$ART" >> "$LOG" 2>&1 \
               && git commit -m "$msg" --only "$ART" >> "$LOG" 2>&1; then
                echo "[watcher] bench committed rc=$rc entries=${n:-?} $(date -u +%FT%TZ)" >> "$LOG"
            else
                echo "[watcher] bench commit no-op/failed $(date -u +%FT%TZ)" >> "$LOG"
            fi
            if [ "$rc" -eq 0 ]; then
                exit 0
            fi
        else
            echo "[watcher] no artifact exists rc=$rc $(date -u +%FT%TZ); retrying next cycle" >> "$LOG"
        fi
    else
        echo "[watcher] probe unhealthy $(date -u +%FT%TZ)" >> "$LOG"
    fi
    sleep 180
done
