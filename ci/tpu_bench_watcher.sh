#!/bin/bash
# Round-5 tunnel watcher: probe TPU enumeration every cycle; at the FIRST
# healthy window run the full bench and commit the artifact immediately
# (VERDICT r4 "Next round" #1: capture EARLY and OFTEN, not at round end).
# Exits after a successful bench+commit; a supervising loop may restart it
# for later re-captures.
set -u
cd /root/repo
LOG=${1:-/tmp/tpu_watcher.log}
ART=${2:-BENCH_FULL_r05.json}
echo "[watcher] start $(date -u +%FT%TZ) artifact=$ART" >> "$LOG"
while true; do
    if timeout 90 python -c "import jax; jax.devices()" >> "$LOG" 2>&1; then
        echo "[watcher] tunnel healthy $(date -u +%FT%TZ); running bench --full" >> "$LOG"
        if timeout 5400 python bench.py --full --artifact "$ART" >> "$LOG" 2>&1; then
            git add "$ART" 2>> "$LOG"
            git commit -m "Live TPU bench capture: $ART" --only "$ART" >> "$LOG" 2>&1
            echo "[watcher] bench captured + committed $(date -u +%FT%TZ)" >> "$LOG"
            exit 0
        else
            echo "[watcher] bench run failed rc=$? $(date -u +%FT%TZ); retrying next cycle" >> "$LOG"
        fi
    else
        echo "[watcher] probe unhealthy $(date -u +%FT%TZ)" >> "$LOG"
    fi
    sleep 180
done
