#!/usr/bin/env bash
# KinD integration e2e — the reference flow (reference .github/workflows/
# odh_notebook_controller_integration_test.yaml:120-220) for this repo:
#   KinD cluster → Gateway-API CRDs → manager images built+loaded →
#   self-signed webhook serving certs → `make deploy` → create a Notebook
#   CR → assert the webhook mutated it and the StatefulSet exists.
#
# Skips (exit 0 with a notice) when docker/kind/kubectl are unavailable so
# the same script is safe on laptops and in restricted runners.
set -euo pipefail

NS=kubeflow-tpu-system
CLUSTER=kubeflow-tpu-e2e
GATEWAY_API_VERSION=${GATEWAY_API_VERSION:-v1.1.0}
IMG_NOTEBOOK=kubeflow-tpu/notebook-controller:latest
IMG_PLATFORM=kubeflow-tpu/platform-notebook-controller:latest

for tool in docker kind kubectl; do
  if ! command -v "$tool" >/dev/null 2>&1; then
    echo "SKIP: $tool not available; KinD e2e requires docker+kind+kubectl"
    exit 0
  fi
done

cleanup() { kind delete cluster --name "$CLUSTER" >/dev/null 2>&1 || true; }
trap cleanup EXIT

echo "--- kind cluster"
kind create cluster --name "$CLUSTER" --wait 120s

echo "--- Gateway API CRDs (HTTPRoute / ReferenceGrant)"
kubectl apply -f "https://github.com/kubernetes-sigs/gateway-api/releases/download/${GATEWAY_API_VERSION}/standard-install.yaml"

echo "--- build + load manager images"
docker build -q -f Containerfile.notebook-manager -t "$IMG_NOTEBOOK" .
docker build -q -f Containerfile.platform-manager -t "$IMG_PLATFORM" .
kind load docker-image --name "$CLUSTER" "$IMG_NOTEBOOK" "$IMG_PLATFORM"

echo "--- self-signed webhook serving certs"
CERT_DIR=$(mktemp -d)
SVC=platform-notebook-controller-webhook
openssl req -x509 -newkey rsa:2048 -nodes -days 1 \
  -keyout "$CERT_DIR/tls.key" -out "$CERT_DIR/tls.crt" \
  -subj "/CN=${SVC}.${NS}.svc" \
  -addext "subjectAltName=DNS:${SVC}.${NS}.svc,DNS:${SVC}.${NS}.svc.cluster.local"
kubectl create namespace "$NS"
kubectl -n "$NS" create secret tls webhook-server-cert \
  --cert="$CERT_DIR/tls.crt" --key="$CERT_DIR/tls.key"

echo "--- deploy (kustomize default overlay)"
make deploy

echo "--- patch webhook caBundle with the self-signed CA"
CA_BUNDLE=$(base64 -w0 <"$CERT_DIR/tls.crt")
kubectl patch mutatingwebhookconfiguration platform-notebook-controller-mutating \
  --type=json -p "[{\"op\":\"add\",\"path\":\"/webhooks/0/clientConfig/caBundle\",\"value\":\"${CA_BUNDLE}\"}]"
kubectl patch validatingwebhookconfiguration platform-notebook-controller-validating \
  --type=json -p "[{\"op\":\"add\",\"path\":\"/webhooks/0/clientConfig/caBundle\",\"value\":\"${CA_BUNDLE}\"}]"

echo "--- wait for managers (reference bound: Ready within 100s)"
kubectl -n "$NS" rollout status deployment/notebook-controller --timeout=100s
kubectl -n "$NS" rollout status deployment/platform-notebook-controller --timeout=100s

echo "--- create a Notebook CR, assert admission + reconcile"
kubectl create namespace e2e-user
kubectl -n e2e-user apply -f config/samples/cpu_notebook.yaml
NB=$(kubectl -n e2e-user get notebooks -o jsonpath='{.items[0].metadata.name}')

# The mutating webhook ran: TPU/env mutation stamps the reconciliation
# lock annotation on CREATE (removed by the platform reconciler later).
kubectl -n e2e-user get notebook "$NB" -o jsonpath='{.metadata.annotations}' | grep -q kubeflow-resource-stopped \
  || { echo "FAIL: mutating webhook did not stamp the reconciliation lock"; exit 1; }

echo "--- wait for the controller to emit the StatefulSet"
for i in $(seq 1 60); do
  if kubectl -n e2e-user get statefulset "$NB" >/dev/null 2>&1; then
    echo "OK: StatefulSet $NB exists"
    kubectl -n e2e-user get statefulset "$NB" -o wide
    exit 0
  fi
  sleep 3
done
echo "FAIL: StatefulSet $NB never appeared"
kubectl -n "$NS" logs deployment/notebook-controller --tail=50 || true
exit 1
