#!/usr/bin/env python
"""Regenerate config/ from kubeflow_tpu.deploy (reference ci/generate_code.sh
keeps generated artifacts in sync; tests/test_manifests.py fails on drift).

``--verify`` checks the committed tree against the generators WITHOUT
writing anything, and exits 1 listing any stale/missing files — the drift
gate used by CI and ``make verify-manifests``.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from kubeflow_tpu.deploy.render import render_all, write_all  # noqa: E402


def _orphans(root: Path, rendered: dict) -> list[str]:
    """Files under config/ that no generator produces anymore — a renamed
    generator must not leave its old output behind where kustomize or
    kubectl apply -f could still ship it."""
    known = set(rendered)
    return sorted(
        str(p.relative_to(root))
        for p in (root / "config").rglob("*")
        if p.is_file() and str(p.relative_to(root)) not in known
    )


def verify(root: Path) -> int:
    rendered = render_all()
    stale = []
    for rel, content in rendered.items():
        path = root / rel
        if not path.exists():
            stale.append(f"missing: {rel}")
        elif path.read_text() != content:
            stale.append(f"drifted: {rel}")
    stale += [f"orphaned: {rel}" for rel in _orphans(root, rendered)]
    if stale:
        for line in stale:
            print(line, file=sys.stderr)
        print(
            f"{len(stale)} generated file(s) out of sync; "
            "run `python ci/generate_manifests.py` and commit the result",
            file=sys.stderr,
        )
        return 1
    print("config/ is in sync with kubeflow_tpu.deploy generators")
    return 0


if __name__ == "__main__":
    root = Path(__file__).resolve().parent.parent
    if "--verify" in sys.argv[1:]:
        sys.exit(verify(root))
    for path in write_all(root):
        print(f"wrote {path.relative_to(root)}")
    for rel in _orphans(root, render_all()):
        (root / rel).unlink()
        print(f"pruned {rel}")
