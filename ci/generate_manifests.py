#!/usr/bin/env python
"""Regenerate config/ from kubeflow_tpu.deploy (reference ci/generate_code.sh
keeps generated artifacts in sync; tests/test_manifests.py fails on drift)."""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from kubeflow_tpu.deploy.render import write_all  # noqa: E402

if __name__ == "__main__":
    root = Path(__file__).resolve().parent.parent
    for path in write_all(root):
        print(f"wrote {path.relative_to(root)}")
