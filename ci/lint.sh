#!/usr/bin/env bash
# Static-analysis gate, two tiers:
#   1. kftpu-lint — the in-repo AST engine (kubeflow_tpu/analysis): cross-
#      module contract checks (env contract, metric registry, annotation
#      vocabulary, chaos parity) plus concurrency lints. JSON mode; any
#      unsuppressed finding fails the build. Required — it runs on the
#      same Python the tests use.
#   2. semgrep — the pattern tier (semgrep.yaml). Optional: skipped with a
#      notice when the tool is unavailable, mirroring ci/kind_e2e.sh.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "--- kftpu-lint (AST engine, JSON mode)"
out=$(mktemp)
if ! python -m kubeflow_tpu.analysis kubeflow_tpu/ --format json > "$out"; then
  echo "FAIL: unsuppressed kftpu-lint findings:"
  python -m kubeflow_tpu.analysis kubeflow_tpu/ || true
  rm -f "$out"
  exit 1
fi
python - "$out" <<'EOF'
import json, sys
report = json.load(open(sys.argv[1]))
print(
    f"kftpu-lint: {report['checked_files']} files checked, "
    f"{report['unsuppressed']} unsuppressed, "
    f"{report['suppressed']} suppressed"
)
EOF
rm -f "$out"

if command -v semgrep >/dev/null 2>&1; then
  echo "--- semgrep (pattern tier)"
  semgrep scan --config semgrep.yaml --error --quiet kubeflow_tpu/
else
  echo "SKIP: semgrep not available; the AST engine above is the required tier"
fi
