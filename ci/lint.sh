#!/usr/bin/env bash
# Static-analysis gate, two tiers:
#   1. kftpu-lint — the in-repo AST engine (kubeflow_tpu/analysis): cross-
#      module contract checks (env contract, metric registry, annotation
#      vocabulary, chaos parity) plus interprocedural concurrency and JAX
#      hot-path rules. JSON mode; any gating finding (unsuppressed,
#      unbaselined, in-diff) fails the build. Required — it runs on the
#      same Python the tests use.
#   2. semgrep — the pattern tier (semgrep.yaml). Optional: skipped with a
#      notice when the tool is unavailable, mirroring ci/kind_e2e.sh.
#
# Modes:
#   bash ci/lint.sh                  full-repo gate (the tier-1 bar: the
#                                    checked-in baseline is empty, so this
#                                    is "zero gating findings anywhere")
#   LINT_PR_MODE=1 bash ci/lint.sh   PR gate: --diff origin/main..HEAD —
#                                    findings outside the PR's changed
#                                    lines never gate (rule-rollout safe)
#   LINT_DIFF_RANGE=a..b             explicit range, overrides PR mode
#   LINT_SARIF=path.sarif            SARIF 2.1.0 artifact destination
#                                    (default kftpu-lint.sarif, for code-
#                                    scanning upload)
set -euo pipefail
cd "$(dirname "$0")/.."

diff_args=()
if [[ -n "${LINT_DIFF_RANGE:-}" ]]; then
  diff_args=(--diff "$LINT_DIFF_RANGE")
elif [[ "${LINT_PR_MODE:-0}" == "1" ]]; then
  if git rev-parse --verify --quiet origin/main >/dev/null; then
    diff_args=(--diff origin/main..HEAD)
  else
    echo "WARN: LINT_PR_MODE=1 but origin/main is unknown; full-repo gate"
  fi
fi

sarif_out="${LINT_SARIF:-kftpu-lint.sarif}"
echo "--- kftpu-lint (SARIF artifact: $sarif_out)"
python -m kubeflow_tpu.analysis kubeflow_tpu/ --sarif "${diff_args[@]+"${diff_args[@]}"}" \
  > "$sarif_out" || true

echo "--- kftpu-lint (AST engine, JSON gate${diff_args[0]:+, ${diff_args[*]}})"
out=$(mktemp)
trap 'rm -f "$out"' EXIT
if ! python -m kubeflow_tpu.analysis kubeflow_tpu/ --format json \
    "${diff_args[@]+"${diff_args[@]}"}" > "$out"; then
  echo "FAIL: gating kftpu-lint findings:"
  python -m kubeflow_tpu.analysis kubeflow_tpu/ "${diff_args[@]+"${diff_args[@]}"}" || true
  exit 1
fi
python - "$out" <<'EOF'
import json, sys
report = json.load(open(sys.argv[1]))
print(
    f"kftpu-lint: {report['checked_files']} files checked, "
    f"{report['gating']} gating "
    f"({report['suppressed']} suppressed, "
    f"{report['baselined']} baselined, "
    f"{report['out_of_diff']} outside diff)"
)
EOF

if command -v semgrep >/dev/null 2>&1; then
  echo "--- semgrep (pattern tier)"
  semgrep scan --config semgrep.yaml --error --quiet kubeflow_tpu/
else
  echo "SKIP: semgrep not available; the AST engine above is the required tier"
fi
