# Dev-workflow entrypoints, mirroring the reference's per-component Makefiles
# (reference: components/notebook-controller/Makefile,
#  components/odh-notebook-controller/Makefile — targets test/test-chaos/
#  manifests/deploy/run/docker-build).
#
# The reference runs its envtest suite twice with SET_PIPELINE_RBAC=false/true
# (odh Makefile:116-126); `make test` does the same here.

PYTHON ?= python
IMG_NOTEBOOK ?= kubeflow-tpu/notebook-manager:latest
IMG_PLATFORM ?= kubeflow-tpu/platform-manager:latest

export JAX_PLATFORMS ?= cpu
export XLA_FLAGS ?= --xla_force_host_platform_device_count=8

.PHONY: all test test-chaos test-e2e manifests verify-manifests run-notebook \
	run-platform loadtest bench native lint build-images deploy dryrun help

all: test

help:
	@grep -E '^[a-z-]+:' Makefile | sed 's/:.*//' | sort -u

test: ## Full suite, twice: SET_PIPELINE_RBAC=false then true (reference parity)
	SET_PIPELINE_RBAC=false $(PYTHON) -m pytest tests/ -x -q
	SET_PIPELINE_RBAC=true $(PYTHON) -m pytest tests/ -x -q

test-chaos: ## Chaos tier only (reference: make test-chaos, odh Makefile:111-114)
	$(PYTHON) -m pytest tests/test_chaos_catalog.py tests/test_k8s_fake.py -q

test-e2e: ## In-process e2e lifecycle suite (reference: e2e/ on a live cluster)
	$(PYTHON) -m pytest tests/test_e2e.py -q

manifests: ## Regenerate config/ tree (reference: make manifests / ci/generate_code.sh)
	$(PYTHON) ci/generate_manifests.py

verify-manifests: ## Fail if config/ drifted from the generators (CI gate)
	$(PYTHON) ci/generate_manifests.py --verify

run-notebook: ## Run the core lifecycle manager locally (reference: make run)
	$(PYTHON) -m kubeflow_tpu.cmd.notebook_manager

run-platform:
	$(PYTHON) -m kubeflow_tpu.cmd.platform_manager --kube-rbac-proxy-image=$(IMG_PLATFORM)

loadtest: ## Notebook churn benchmark (reference: loadtest/start_notebooks.py)
	$(PYTHON) loadtest/start_notebooks.py -n 50

bench: ## Headline TPU benchmark — one JSON line
	$(PYTHON) bench.py

bench-smoke: ## Every bench section at toy shapes on CPU (executability gate)
	BENCH_SMOKE=1 $(PYTHON) bench.py --full

dryrun: ## Multi-chip sharding compile check on a virtual 8-device mesh
	$(PYTHON) -c "import __graft_entry__ as g; g.dryrun_multichip(8)"

lint: ## kftpu-lint: AST engine with cross-module contract checks (+ semgrep if present)
	bash ci/lint.sh

lint-baseline: ## Regenerate kftpu-lint's baseline (rule rollout only — the standing bar is empty)
	$(PYTHON) -m kubeflow_tpu.analysis kubeflow_tpu/ --update-baseline

native: ## Build native C++ components (data loader, slice prober)
	$(MAKE) -C native

build-images: ## Container images for both managers (reference: make docker-build)
	docker build -f Containerfile.notebook-manager -t $(IMG_NOTEBOOK) .
	docker build -f Containerfile.platform-manager -t $(IMG_PLATFORM) .

deploy: manifests ## Apply the kustomize default overlay (reference: make deploy)
	kubectl apply -k config/default
