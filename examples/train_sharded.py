#!/usr/bin/env python
"""Sharded training with checkpoint/resume — the preemption-recovery loop.

In a notebook on a controller-spawned slice this is cell-by-cell:
bootstrap the slice, build a mesh, shard the train state, train with
periodic checkpoints; after a preemption the SAME script resumes from
the newest checkpoint (the control plane recreated the pods, orbax
restores the state).
"""

from __future__ import annotations

import argparse
import pathlib
import sys

# Runnable straight from a checkout (pip install not required in-notebook).
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
import tempfile


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="tiny")
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=64)
    ap.add_argument("--ckpt-dir", default=None)
    ap.add_argument("--sp-impl", default="ring",
                    choices=["ring", "ulysses", "zigzag"])
    ap.add_argument("--data", default=None,
                    help="uint32 token corpus (data.write_token_file "
                         "format); omitted = synthetic random tokens")
    ap.add_argument("--fp8", action="store_true",
                    help="train with fp8 matmul operands (delayed "
                         "scaling; bf16 master weights — models/fp8.py). "
                         "Numerics identical everywhere; the matmul-rate "
                         "win engages where the MXU has fp8 lanes")
    args = ap.parse_args()

    import jax

    from kubeflow_tpu.runtime.bootstrap import honor_jax_platforms_env

    honor_jax_platforms_env()  # JAX_PLATFORMS=cpu must win over TPU plugins

    from kubeflow_tpu.models import llama as L
    from kubeflow_tpu.models.train import make_train_step, shard_state
    from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh
    from kubeflow_tpu.runtime import bootstrap
    from kubeflow_tpu.runtime.checkpoint import CheckpointManager

    rt = bootstrap()  # no-op on single host; DCN init on a slice
    n = jax.device_count()
    print(f"slice up: {n} devices, worker {rt.worker_id}/{rt.num_workers}")

    # Simple axis split: fsdp gets the devices; add tp/sp to taste. The
    # batch is padded up to a multiple of the mesh's batch axis (fsdp
    # shards the batch dim too).
    plan = MeshPlan(make_mesh(fsdp=n))
    if args.batch % n:
        args.batch = ((args.batch + n - 1) // n) * n
        print(f"batch rounded up to {args.batch} (multiple of {n} devices)")
    cfg = L.LLAMA_CONFIGS[args.config]
    init_state, step = make_train_step(
        cfg, plan, sp_impl=args.sp_impl, fp8=args.fp8
    )
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    if args.fp8:
        from kubeflow_tpu.models.fp8 import wrap_params_fp8

        params = wrap_params_fp8(params)
    state = shard_state(plan, init_state(params))

    ckpt_dir = args.ckpt_dir or tempfile.mkdtemp(prefix="kftpu-ckpt-")
    ckpt = CheckpointManager(ckpt_dir, save_interval_steps=2)
    state, resumed = ckpt.restore_latest(state)
    start = resumed or 0
    if resumed:
        print(f"resumed from step {resumed} (preemption recovery)")

    loader = None
    if args.data:
        import numpy as np

        from kubeflow_tpu.data import device_put_global, sharded_loader

        # start_batch: the resumed run must not re-read the batches the
        # lost run already consumed (exact-resume data discipline).
        # sharded_loader gives THIS host its global_batch/num_processes
        # rows from a process-disjoint stream.
        loader = sharded_loader(
            args.data, args.batch, args.seq, start_batch=start
        )
    key = jax.random.PRNGKey(1)
    for i in range(start, args.steps):
        if loader is not None:
            # Assemble the per-host rows into the GLOBAL batch laid out
            # over the mesh — on one host this is a plain device_put.
            local = np.remainder(loader.next(), cfg.vocab_size).astype(
                np.int32
            )
            tokens = device_put_global(
                local, plan.mesh, jax.sharding.PartitionSpec(
                    ("dp", "fsdp"), "sp"
                )
            )
        else:
            # fold_in(i): per-step keys are a function of the STEP, so a
            # resumed run continues the stream instead of replaying it.
            tokens = jax.random.randint(
                jax.random.fold_in(key, i), (args.batch, args.seq), 0,
                cfg.vocab_size,
            )
        state, loss = step(state, tokens)
        ckpt.save(i + 1, state)
        print(f"step {i + 1}: loss {float(loss):.4f}")
    if loader is not None:
        loader.close()
    ckpt.wait()
    print(f"done; checkpoints in {ckpt_dir}")


if __name__ == "__main__":
    main()
