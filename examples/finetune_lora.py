#!/usr/bin/env python
"""LoRA fine-tune a Llama-family model, then export merged weights.

With --checkpoint, loads real HF weights (safetensors dir); otherwise
random-init tiny for a smoke run. Only the adapters carry gradients and
optimizer state (~0.1% of the model at rank 8), so a 7B fine-tune fits
next to its frozen bf16 base on one v5e chip.
"""

from __future__ import annotations

import argparse
import pathlib
import sys

# Runnable straight from a checkout (pip install not required in-notebook).
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="tiny")
    ap.add_argument("--checkpoint", default=None)
    ap.add_argument("--rank", type=int, default=8)
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--export", default=None, help="write merged HF state dict (.npz)")
    args = ap.parse_args()

    import jax

    from kubeflow_tpu.runtime.bootstrap import honor_jax_platforms_env

    honor_jax_platforms_env()  # JAX_PLATFORMS=cpu must win over TPU plugins
    import numpy as np

    from kubeflow_tpu.models import llama as L
    from kubeflow_tpu.models.convert import load_hf_checkpoint, params_to_hf_state_dict
    from kubeflow_tpu.models.lora import (
        LoraConfig,
        init_lora_params,
        lora_param_count,
        make_lora_train_step,
        merge_lora,
    )

    if args.checkpoint:
        cfg, params = load_hf_checkpoint(args.checkpoint)
    else:
        cfg = L.LLAMA_CONFIGS[args.config]
        params = L.init_params(cfg, jax.random.PRNGKey(0))

    lcfg = LoraConfig(rank=args.rank)
    lora = init_lora_params(cfg, lcfg, jax.random.PRNGKey(1))
    print(
        f"base {cfg.param_count()/1e6:.1f}M params frozen; "
        f"training {lora_param_count(cfg, lcfg)/1e3:.1f}K adapter params"
    )

    init_state, step = make_lora_train_step(cfg, lcfg, learning_rate=args.lr)
    state = init_state(lora)
    key = jax.random.PRNGKey(2)
    for i in range(args.steps):
        key, sub = jax.random.split(key)
        tokens = jax.random.randint(sub, (4, 64), 0, cfg.vocab_size)
        state, loss = step(state, params, tokens)
        print(f"step {i + 1}: loss {float(loss):.4f}")

    merged = merge_lora(params, state["lora"], lcfg)
    if args.export:
        sd = params_to_hf_state_dict(cfg, merged)
        np.savez(args.export, **sd)
        print(f"merged HF state dict → {args.export}")


if __name__ == "__main__":
    main()
