#!/usr/bin/env python
"""Batched generation service loop: quantize, bucket, generate.

With --checkpoint, loads real HF weights and (optionally) the matching
tokenizer for text I/O; otherwise random-init tiny and raw token IDs.
"""

from __future__ import annotations

import argparse
import pathlib
import sys

# Runnable straight from a checkout (pip install not required in-notebook).
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="tiny")
    ap.add_argument("--checkpoint", default=None)
    ap.add_argument("--int8", action="store_true")
    ap.add_argument("--int4", action="store_true",
                    help="group-wise int4 weights (~4x fewer HBM bytes)")
    ap.add_argument("--fp8", action="store_true",
                    help="e4m3 weight-only (2x fewer HBM bytes; operands "
                         "upcast at the matmul like int8 — use for format "
                         "consistency with fp8-trained checkpoints)")
    ap.add_argument("--kv8", action="store_true",
                    help="int8 KV cache (halves per-token cache reads and "
                         "cache HBM; composes with --int8/--int4 weights "
                         "and with --paged/--tp/--sp)")
    ap.add_argument("--paged", action="store_true",
                    help="serve through the paged block-pool KV cache")
    ap.add_argument("--speculative", type=int, default=0, metavar="N",
                    help="speculative serving with a truncated-layer "
                         "draft (first N layers of the target; greedy "
                         "only); composes with --paged/--kv8/--tp")
    ap.add_argument("--num-blocks", type=int, default=64,
                    help="block-pool size for --paged (16-token blocks)")
    ap.add_argument("--prompt-cache", action="store_true",
                    help="(--paged) share identical prompts' KV blocks "
                         "and skip their re-prefill")
    ap.add_argument("--prefix-cache", action="store_true",
                    help="(--paged) position-0-anchored admission: share "
                         "common PREFIX blocks across different-length "
                         "prompts, prefill only the unmatched tail")
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel shards (continuous batching)")
    ap.add_argument("--sp", type=int, default=1,
                    help="sequence-parallel KV-cache shards (continuous)")
    ap.add_argument("--max-new-tokens", type=int, default=16)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--prompt", action="append", default=None,
                    help="text prompt (needs --checkpoint tokenizer); repeatable")
    args = ap.parse_args()
    if sum((args.int8, args.int4, args.fp8)) > 1:
        raise SystemExit("--int8/--int4/--fp8 are mutually exclusive")
    if args.prompt_cache and args.prefix_cache:
        raise SystemExit("--prompt-cache and --prefix-cache are mutually "
                         "exclusive (prefix subsumes identical prompts)")

    import jax

    from kubeflow_tpu.runtime.bootstrap import honor_jax_platforms_env

    honor_jax_platforms_env()  # JAX_PLATFORMS=cpu must win over TPU plugins

    from kubeflow_tpu.models import llama as L
    from kubeflow_tpu.models.convert import load_hf_checkpoint
    from kubeflow_tpu.models.quant import quantize_params
    from kubeflow_tpu.models.serving import GenerationConfig, batch_generate

    tokenizer = None
    if args.checkpoint:
        cfg, params = load_hf_checkpoint(args.checkpoint)
        try:
            from transformers import AutoTokenizer

            tokenizer = AutoTokenizer.from_pretrained(args.checkpoint)
        except Exception as err:
            if args.prompt:
                # Text prompts are unusable without the tokenizer — fail
                # loudly rather than silently serving random token IDs.
                raise SystemExit(
                    f"--prompt given but tokenizer load failed: {err}"
                )
            print(f"# tokenizer unavailable ({err}); serving token IDs")
    else:
        cfg = L.LLAMA_CONFIGS[args.config]
        params = L.init_params(cfg, jax.random.PRNGKey(0))

    from kubeflow_tpu.models.quant import quant_bits_from_env

    # CLI flags win; otherwise the notebook runtime option applies (the
    # webhook projects the tpu-quantization annotation into
    # KUBEFLOW_TPU_QUANT — this is the consuming end of that contract).
    bits = (
        "fp8" if args.fp8
        else 4 if args.int4
        else 8 if args.int8
        else quant_bits_from_env()
    )
    if bits:
        params = quantize_params(params, free_source=True, bits=bits)
        label = bits if bits == "fp8" else f"int{bits}"
        print(f"{label} weight-only quantization applied")
    kv_bits = 8 if args.kv8 else 0

    if tokenizer is not None and args.prompt:
        prompts = [tokenizer(p)["input_ids"] for p in args.prompt]
        eos = tokenizer.eos_token_id
    else:
        import numpy as np

        rng = np.random.default_rng(0)
        prompts = [list(rng.integers(3, cfg.vocab_size, size=n))
                   for n in (5, 11, 8)]
        eos = 2
    gen = GenerationConfig(
        max_new_tokens=args.max_new_tokens,
        temperature=args.temperature,
        top_p=0.95 if args.temperature else 1.0,
        eos_id=eos,
    )
    plan = None
    if args.tp > 1 or args.sp > 1:
        from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh

        if args.paged and args.sp > 1:
            # The block pool has no contiguous sequence axis to shard.
            raise SystemExit("--paged supports --tp but not --sp "
                             "(use continuous batching for sp)")
        if args.speculative and args.sp > 1:
            # Chunked draft/verify has no split-KV sp merge.
            raise SystemExit("--speculative supports --tp but not --sp "
                             "(use plain continuous batching for sp)")
        n = args.tp * args.sp
        plan = MeshPlan(make_mesh(tp=args.tp, sp=args.sp,
                                  devices=jax.devices()[:n]))

    if args.speculative:
        from kubeflow_tpu.models.speculative import (
            SpeculativeContinuousBatcher,
            SpeculativePagedBatcher,
            truncated_draft,
        )

        if args.temperature:
            raise SystemExit("--speculative is greedy-only (temperature 0)")
        dparams, dcfg = truncated_draft(params, cfg, args.speculative)
        bucket = 16 * ((max(len(p) for p in prompts) + 15) // 16)
        if args.paged:
            sb = SpeculativePagedBatcher(
                params, cfg, dparams, dcfg, gen=gen,
                slots=min(4, len(prompts)), num_blocks=args.num_blocks,
                block_size=16, prompt_bucket=bucket,
                key=jax.random.PRNGKey(0), plan=plan, kv_bits=kv_bits,
                prompt_cache=args.prompt_cache,
                prefix_cache=args.prefix_cache,
            )
        else:
            k_spec = 4
            sb = SpeculativeContinuousBatcher(
                params, cfg, dparams, dcfg, gen=gen,
                slots=min(4, len(prompts)),
                cache_len=bucket + gen.max_new_tokens + k_spec + 1,
                prompt_bucket=bucket, key=jax.random.PRNGKey(0),
                k_spec=k_spec, plan=plan, kv_bits=kv_bits,
            )
        rids = [sb.submit(p) for p in prompts]
        results = sb.run()
        outs = [results[r] for r in rids]
        print(f"speculative ({args.speculative}-layer draft, "
              f"{'paged' if args.paged else 'continuous'}): acceptance "
              f"{sb.acceptance_rate:.2f}")
    elif args.paged:
        from kubeflow_tpu.models.paged import PagedBatcher

        bucket = 16 * ((max(len(p) for p in prompts) + 15) // 16)
        pb = PagedBatcher(
            params, cfg, gen=gen, slots=min(4, len(prompts)),
            num_blocks=args.num_blocks, block_size=16, prompt_bucket=bucket,
            key=jax.random.PRNGKey(0), plan=plan,
            kv_bits=kv_bits, prompt_cache=args.prompt_cache,
            prefix_cache=args.prefix_cache,
        )
        rids = [pb.submit(p) for p in prompts]
        results = pb.run()
        outs = [results[r] for r in rids]
        print(f"paged: {pb.free_blocks}/{args.num_blocks - 1} blocks free after run")
    elif plan is not None:
        # Multi-host serving: params shard over tp, the KV cache's
        # sequence axis over sp (split-KV shard_map decode). Token-exact
        # with the single-device batcher.
        from kubeflow_tpu.models.continuous import ContinuousBatcher

        bucket = 16 * ((max(len(p) for p in prompts) + 15) // 16)
        cache_len = args.sp * -(-(bucket + gen.max_new_tokens) // args.sp)
        cb = ContinuousBatcher(
            params, cfg, gen=gen, slots=min(4, len(prompts)),
            cache_len=cache_len, prompt_bucket=bucket,
            key=jax.random.PRNGKey(0), plan=plan,
            kv_bits=kv_bits,
        )
        rids = [cb.submit(p) for p in prompts]
        results = cb.run()
        outs = [results[r] for r in rids]
        print(f"sharded serving: tp={args.tp} sp={args.sp} over "
              f"{args.tp * args.sp} devices")
    else:
        outs = batch_generate(params, cfg, prompts, gen,
                              key=jax.random.PRNGKey(0),
                              kv_bits=kv_bits)
    for i, out in enumerate(outs):
        if tokenizer is not None and args.prompt:
            print(f"[{i}] {tokenizer.decode(out)}")
        else:
            print(f"[{i}] {len(out)} tokens: {out[:16]}{'...' if len(out) > 16 else ''}")


if __name__ == "__main__":
    main()
