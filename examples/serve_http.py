"""Serve a model over HTTP from inside a TPU notebook.

The in-notebook complement to the controller's network plumbing: the
webhook/NetworkPolicy stack exposes notebook ports; this gives one of
them an OpenAI-completions-shaped inference endpoint over the
continuous-batching engines.

    python examples/serve_http.py --config tiny --port 8000 &
    curl -s localhost:8000/v1/completions \
      -d '{"prompt": [1, 2, 3, 4], "max_tokens": 8}'
    curl -s localhost:8000/stats

``--checkpoint`` loads HF weights + tokenizer (text prompts + decoded
text in responses); without it, a random-init model serves token ids —
enough to exercise the transport end to end.
"""

from __future__ import annotations

import argparse
import pathlib
import signal
import sys
import threading

# Runnable straight from a checkout (pip install not required in-notebook).
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="tiny")
    ap.add_argument("--checkpoint", default=None)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=None,
                    help="default: the webhook-projected "
                         "KUBEFLOW_TPU_SERVING_PORT, else 8000")
    ap.add_argument("--slots", type=int, default=4)
    ap.add_argument("--cache-len", type=int, default=1024)
    ap.add_argument("--prompt-bucket", type=int, default=64)
    ap.add_argument("--max-new-tokens", type=int, default=128)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--admit-chunk", type=int, default=None,
                    help="(continuous engine) admit prompts in N-token "
                         "pieces with decode steps between them — "
                         "neighbors' latency stops paying for admissions")
    ap.add_argument("--int8", action="store_true",
                    help="int8 weight-only quantization")
    ap.add_argument("--paged", action="store_true",
                    help="serve through the paged block-pool engine")
    ap.add_argument("--num-blocks", type=int, default=256)
    ap.add_argument("--max-queue-depth", type=int, default=64,
                    help="pending requests past this shed with 429 "
                         "instead of blocking handler threads")
    ap.add_argument("--deadline-s", type=float, default=None,
                    help="default per-request deadline; expired requests "
                         "free their slot and get 504 with partials")
    ap.add_argument("--drain-s", type=float, default=5.0,
                    help="SIGTERM drain budget before stragglers are "
                         "force-aborted")
    args = ap.parse_args()
    if args.paged and args.admit_chunk:
        raise SystemExit("--admit-chunk is a continuous-engine feature; "
                         "drop it or drop --paged")

    import jax

    from kubeflow_tpu.runtime.bootstrap import honor_jax_platforms_env

    honor_jax_platforms_env()

    from kubeflow_tpu.models import llama as L
    from kubeflow_tpu.models.serving import GenerationConfig
    from kubeflow_tpu.models.server import (
        InferenceServer,
        serving_port_from_env,
        serving_tp_from_env,
    )

    if args.port is None:
        args.port = serving_port_from_env()

    tokenizer = None
    if args.checkpoint:
        from kubeflow_tpu.models.convert import load_hf_checkpoint

        cfg, params = load_hf_checkpoint(args.checkpoint)
        try:
            from transformers import AutoTokenizer

            tokenizer = AutoTokenizer.from_pretrained(args.checkpoint)
        except Exception as err:
            print(f"no tokenizer ({err}); serving token ids only",
                  flush=True)
    else:
        cfg = L.LLAMA_CONFIGS[args.config]
        params = L.init_params(cfg, jax.random.PRNGKey(0))
    if args.int8:
        from kubeflow_tpu.models.quant import quantize_params

        params = quantize_params(params, free_source=True)

    # Tensor-parallel replica: KUBEFLOW_TPU_SERVING_TP spans this replica's
    # engine over a tp-degree mesh — weights shard on the tp axis and the
    # paged KV pool head-shards (per-chip pool bytes drop by the degree)
    # while the replica stays one HTTP endpoint. Every rejection here fires
    # at startup, before any weight lands on a device.
    from kubeflow_tpu.models.tp_serving import serving_plan

    try:
        tp = serving_tp_from_env()
        plan = serving_plan(tp, cfg=cfg)
    except ValueError as err:
        raise SystemExit(str(err))
    if plan is not None:
        if args.admit_chunk:
            raise SystemExit(
                "--admit-chunk is a single-chip continuous-engine feature; "
                "drop it or unset KUBEFLOW_TPU_SERVING_TP")
        print(f"tensor-parallel replica: tp={tp} "
              f"(mesh axes {plan.axes}, head-sharded KV pool)", flush=True)

    gen = GenerationConfig(max_new_tokens=args.max_new_tokens,
                           temperature=args.temperature)
    if args.paged:
        from kubeflow_tpu.models.paged import PagedBatcher
        from kubeflow_tpu.models.server import (
            kv_pool_from_env,
            lora_cache_from_env,
            ragged_from_env,
            spec_from_env,
        )

        # Fail fast on a garbled KUBEFLOW_TPU_LORA_CACHE_SLOTS even though
        # this example serves a single base model: the var is consumed by
        # multi-LoRA engines (MultiLoraPagedBatcher — see
        # loadtest/serve_fleet.py --multilora) and a typo should surface
        # at startup, not when adapters are first registered.
        lora_cache_slots = lora_cache_from_env()
        if lora_cache_slots:
            print(f"lora cache slots={lora_cache_slots} (no adapters "
                  "registered by this example; knob applies to "
                  "multi-LoRA engines)", flush=True)

        # HBM-economy knobs arrive via the webhook-projected env
        # (KUBEFLOW_TPU_KV_BITS / _HBM_FRACTION / _KV_SWAP_BYTES), so a
        # replica runs a quantized, HBM-sized, swap-enabled pool with no
        # CLI flags. A swap tier only holds demoted PREFIX leaves —
        # enabling it implies the prefix cache.
        kv_kw = kv_pool_from_env()
        ragged, token_budget = ragged_from_env()
        draft_len, adaptive = spec_from_env()
        if draft_len > 0:
            # Speculation is a scheduling mode of the ragged engine:
            # each slot contributes (1 + draft_len) verify rows to the
            # fused dispatch, so the env knob requires ragged mode.
            if not ragged:
                raise SystemExit(
                    "KUBEFLOW_TPU_SPEC_DRAFT_LEN needs the ragged "
                    "engine (set KUBEFLOW_TPU_SERVING_RAGGED=1)")
            from kubeflow_tpu.models.speculative import (
                SpeculativePagedBatcher,
                truncated_draft,
            )

            if set(kv_kw) - {"kv_bits"}:
                raise SystemExit(
                    "speculative serving supports KUBEFLOW_TPU_KV_BITS "
                    "but not the HBM sizing / swap-tier knobs; unset "
                    "KUBEFLOW_TPU_HBM_FRACTION / _KV_SWAP_BYTES")
            d_params, d_cfg = truncated_draft(
                params, cfg, max(1, cfg.n_layers // 4))
            engine = SpeculativePagedBatcher(
                params, cfg, d_params, d_cfg, gen=gen,
                slots=args.slots, num_blocks=args.num_blocks,
                prompt_bucket=args.prompt_bucket,
                k_spec=draft_len, adaptive=adaptive,
                ragged=True, token_budget=token_budget,
                kv_bits=kv_kw.get("kv_bits", 0), plan=plan,
            )
        else:
            engine = PagedBatcher(
                params, cfg, gen=gen, slots=args.slots,
                num_blocks=args.num_blocks,
                prompt_bucket=args.prompt_bucket,
                ragged=ragged, token_budget=token_budget,
                prefix_cache=kv_kw.get("swap_bytes", 0) > 0,
                plan=plan, **kv_kw,
            )
    else:
        from kubeflow_tpu.models.continuous import ContinuousBatcher

        engine = ContinuousBatcher(
            params, cfg, gen=gen, slots=args.slots,
            cache_len=args.cache_len, prompt_bucket=args.prompt_bucket,
            admit_chunk=args.admit_chunk, plan=plan,
        )

    srv = InferenceServer(engine, host=args.host, port=args.port,
                          tokenizer=tokenizer,
                          model_name=args.checkpoint or args.config,
                          max_queue_depth=args.max_queue_depth,
                          default_deadline_s=args.deadline_s,
                          drain_s=args.drain_s).start()
    print(f"serving {args.config} on http://{srv.host}:{srv.port} "
          f"({'paged' if args.paged else 'continuous'}, "
          f"{args.slots} slots)", flush=True)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    srv.stop()


if __name__ == "__main__":
    main()
