#!/bin/sh
# Copy the junit report out of a conformance runner pod
# (reference analogue: conformance/1.7/report-pod.sh).
set -eu
APP=$1
NAMESPACE=${2:-kftpu-conformance}
REPORT_DIR=${3:-/tmp/kftpu-conformance}

POD=$(kubectl get pods -n "$NAMESPACE" -l "app=$APP" \
  -o jsonpath='{.items[0].metadata.name}')
kubectl cp "$NAMESPACE/$POD:/report/$APP.xml" "$REPORT_DIR/$APP.xml"
echo "report: $REPORT_DIR/$APP.xml"
