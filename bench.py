#!/usr/bin/env python
"""Benchmark: in-notebook Llama decode throughput per TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Method (single chip, the BASELINE.md "Llama-2-7B tokens/sec/chip" metric):
- random-init Llama-2-7B in bf16 directly on device (13.5 GB on a 16 GB
  v5e), KV cache bs=1,
- generation runs as ONE compiled program (prefill + N greedy decode steps
  fused via lax.scan — kubeflow_tpu.models.llama.generate_tokens), so
  host↔device dispatch latency is excluded by construction,
- decode tokens/sec = (N2 - N1) / (t(N2) - t(N1)) with N2 = 2·N1, which
  also cancels the prefill cost; timing forces a host readback because
  block_until_ready does not synchronize through the axon tunnel.

vs_baseline: BASELINE.json carries no reference number ("reference
tokens/sec/chip", published == {}). The denominator used here is 30 tok/s
per chip — ~50% of the bs=1 HBM roofline on v5e (819 GB/s / 13.5 GB per
token ≈ 61 tok/s), i.e. what a solid reference implementation achieves at
batch 1. vs_baseline > 1.0 beats that.

Falls back to smaller configs if the chip cannot hold 7B (the metric name
always states what actually ran).
"""

from __future__ import annotations

import json
import sys
import time

BASELINE_TOK_S_PER_CHIP = 30.0

# (config name, prompt len, decode steps, cache len, baseline tok/s or None)
# Only the 7B config has a meaningful denominator; the tiny fallback reports
# vs_baseline 0.0 rather than dividing a toy model's throughput by the 7B
# baseline.
ATTEMPTS = [
    ("llama-2-7b", 128, 64, 512, BASELINE_TOK_S_PER_CHIP),
    ("tiny", 128, 256, 1024, None),  # last-resort fallback: still prints a line
]


def run_decode_bench(
    cfg_name: str, prompt_len: int, steps: int, cache_len: int,
    int8: bool = False,
):
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models import llama as L

    cfg = L.LLAMA_CONFIGS[cfg_name]
    key = jax.random.PRNGKey(0)
    params = L.init_params(cfg, key)
    jax.block_until_ready(params)
    if int8:
        # Weight-only int8 (models/quant.py): halves HBM traffic per
        # decoded token. free_source: bf16+int8 don't coexist in 16 GB.
        from kubeflow_tpu.models.quant import quantize_params

        params = quantize_params(params, free_source=True)
    prompt = jax.random.randint(
        jax.random.PRNGKey(1), (1, prompt_len), 0, cfg.vocab_size
    )

    def timed_generate(n_steps: int) -> float:
        # Warm up / compile this (cfg, steps) program. The KV cache is
        # allocated INSIDE the compiled program (models.llama.generate), so
        # no donation is needed and XLA picks the cache layout freely.
        toks = L.generate(params, cfg, prompt, steps=n_steps, cache_len=cache_len)
        int(toks[0, -1])  # host readback = real sync
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            toks = L.generate(
                params, cfg, prompt, steps=n_steps, cache_len=cache_len
            )
            int(toks[0, -1])
            times.append(time.perf_counter() - t0)
        return min(times)

    t1 = timed_generate(steps)
    t2 = timed_generate(2 * steps)
    decode_s_per_tok = (t2 - t1) / steps
    return 1.0 / decode_s_per_tok


def main() -> int:
    import jax

    int8 = "--int8" in sys.argv[1:]
    device = jax.devices()[0]
    kind = getattr(device, "device_kind", str(device))
    last_err = None
    for cfg_name, prompt_len, steps, cache_len, baseline in ATTEMPTS:
        try:
            tok_s = run_decode_bench(
                cfg_name, prompt_len, steps, cache_len, int8=int8
            )
            print(
                json.dumps(
                    {
                        "metric": (
                            f"{cfg_name} greedy decode tokens/sec/chip "
                            f"(bs=1, {'int8 weights' if int8 else 'bf16'}, "
                            f"fused loop, {kind})"
                        ),
                        "value": round(tok_s, 2),
                        "unit": "tokens/sec/chip",
                        "vs_baseline": (
                            round(tok_s / baseline, 3) if baseline else 0.0
                        ),
                    }
                )
            )
            return 0
        except Exception as err:  # OOM or compile failure → try smaller
            last_err = err
            print(f"# bench attempt {cfg_name} failed: {err}", file=sys.stderr)
    print(
        json.dumps(
            {
                "metric": "llama decode tokens/sec/chip (all attempts failed)",
                "value": 0.0,
                "unit": "tokens/sec/chip",
                "vs_baseline": 0.0,
            }
        )
    )
    print(f"# last error: {last_err}", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
