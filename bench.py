#!/usr/bin/env python
"""Benchmark: in-notebook Llama decode throughput per TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline",
"provenance"}. ``provenance`` is ``live`` for a measurement taken now,
``cached`` for the last-measured-headline fallback, ``smoke`` for toy CI
shapes; every record written to a BENCH_*.json artifact carries it.

``--full`` additionally measures prefill tokens/sec, the pallas flash
kernel's forward and forward+backward TFLOP/s, and a training-step MFU on
a ~1.1B-param config that fits one 16 GB chip with AdamW state — written
as comment lines on stderr plus a JSON artifact (``--artifact PATH``,
default BENCH_FULL.json) so the headline stdout stays one line.

``--mixed`` replaces the bs=1 headline with the ragged mixed
prefill/decode serving throughput: PagedBatcher(ragged=True) fusing every
active slot's decode token plus the admitting slot's prompt chunk into one
dispatch per step (run_mixed_bench).

Hang-proofing (ROADMAP item 5, promoted from ci/tpu_bench_watcher.sh):
device enumeration is probed in a subprocess with a hard per-probe
deadline and retried across BENCH_RETRY_CYCLES windows; BENCH_DEADLINE_S
bounds the whole live run in a child process, falling back to the cached
headline on expiry.

Method (single chip, the BASELINE.md "Llama-2-7B tokens/sec/chip" metric):
- random-init Llama-2-7B in bf16 directly on device (13.5 GB on a 16 GB
  v5e), KV cache bs=1,
- generation runs as ONE compiled program (prefill + N greedy decode steps
  fused via lax.scan — kubeflow_tpu.models.llama.generate_tokens), so
  host↔device dispatch latency is excluded by construction,
- decode tokens/sec = (N2 - N1) / (t(N2) - t(N1)) with N2 = 2·N1, which
  also cancels the prefill cost; timing forces a host readback because
  block_until_ready does not synchronize through the axon tunnel.

vs_baseline: BASELINE.json carries no reference number ("reference
tokens/sec/chip", published == {}). The denominator used here is 30 tok/s
per chip — ~50% of the bs=1 HBM roofline on v5e (819 GB/s / 13.5 GB per
token ≈ 61 tok/s), i.e. what a solid reference implementation achieves at
batch 1. vs_baseline > 1.0 beats that.

Falls back to smaller configs if the chip cannot hold 7B (the metric name
always states what actually ran).
"""

from __future__ import annotations

import json
import os
import sys
import time


def _smoke_enabled() -> bool:
    """BENCH_SMOKE truthiness: explicit 0/false must mean OFF (an operator
    forcing a real-chip run must not be routed to the CPU toy path)."""
    return os.environ.get("BENCH_SMOKE", "").strip().lower() not in (
        "", "0", "false", "no",
    )

BASELINE_TOK_S_PER_CHIP = 30.0

# (config name, prompt len, decode steps, cache len, baseline tok/s or None)
# Only the 7B config has a meaningful denominator; the tiny fallback reports
# vs_baseline 0.0 rather than dividing a toy model's throughput by the 7B
# baseline.
ATTEMPTS = [
    ("llama-2-7b", 128, 64, 512, BASELINE_TOK_S_PER_CHIP),
    ("tiny", 128, 256, 1024, None),  # last-resort fallback: still prints a line
]


def run_decode_bench(
    cfg_name: str, prompt_len: int, steps: int, cache_len: int,
    quant_bits: int = 0, kv_bits: int = 0,
):
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models import llama as L

    cfg = L.LLAMA_CONFIGS[cfg_name]
    key = jax.random.PRNGKey(0)
    params = L.init_params(cfg, key)
    jax.block_until_ready(params)
    if quant_bits:
        # Weight-only quantization (models/quant.py): int8 halves, int4
        # quarters the HBM traffic per decoded token. free_source: the
        # bf16 and quantized trees don't coexist in 16 GB.
        from kubeflow_tpu.models.quant import quantize_params

        params = quantize_params(params, free_source=True, bits=quant_bits)
    prompt = jax.random.randint(
        jax.random.PRNGKey(1), (1, prompt_len), 0, cfg.vocab_size
    )

    def timed_generate(n_steps: int) -> float:
        # Warm up / compile this (cfg, steps) program. The KV cache is
        # allocated INSIDE the compiled program (models.llama.generate), so
        # no donation is needed and XLA picks the cache layout freely.
        toks = L.generate(params, cfg, prompt, steps=n_steps,
                          cache_len=cache_len, kv_bits=kv_bits)
        int(toks[0, -1])  # host readback = real sync
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            toks = L.generate(
                params, cfg, prompt, steps=n_steps, cache_len=cache_len,
                kv_bits=kv_bits,
            )
            int(toks[0, -1])
            times.append(time.perf_counter() - t0)
        return min(times)

    t1 = timed_generate(steps)
    t2 = timed_generate(2 * steps)
    decode_s_per_tok = (t2 - t1) / steps
    return 1.0 / decode_s_per_tok


# The round-5 live bs=1 headline (BENCH_FULL_r05_headline.json): the number
# the ragged mixed-batch mode exists to beat — batching N sequences into one
# fused dispatch must buy more throughput than serving them one at a time.
R05_LIVE_HEADLINE_TOK_S = 48.9


def run_mixed_bench(cfg_name: str, quant_bits: int = 0, smoke: bool = False):
    """Ragged mixed prefill/decode serving throughput (``--mixed``).

    Drives PagedBatcher(ragged=True): every engine step is ONE fused
    dispatch carrying each active slot's decode token plus the admitting
    slot's next prompt chunk, under a per-step token budget. Two request
    waves over the slots (alternating short and bucket-length prompts)
    keep admissions landing mid-decode, so the measured steady state is
    genuinely mixed — not decode-only with a prefill preamble.

    Two-point timing (d2 vs d1 decode steps per request, identical
    admission work in both runs) cancels prefill and compile exactly as in
    run_decode_bench. Returns (tokens/sec, mean batch fill, shape dict).
    """
    import jax

    from kubeflow_tpu.models import llama as L
    from kubeflow_tpu.models.paged import PagedBatcher
    from kubeflow_tpu.models.serving import GenerationConfig

    cfg = L.LLAMA_CONFIGS[cfg_name]
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    jax.block_until_ready(params)
    if quant_bits:
        from kubeflow_tpu.models.quant import quantize_params

        params = quantize_params(params, free_source=True, bits=quant_bits)
    slots = 4 if smoke else 8
    short, bucket = (8, 32) if smoke else (32, 128)
    d1, d2 = (4, 8) if smoke else (32, 64)
    budget = 64 if smoke else 512
    block_size = 16
    nreq = 2 * slots
    rng = jax.random.randint(
        jax.random.PRNGKey(1), (nreq, bucket), 3, cfg.vocab_size
    )
    prompts = [
        list(map(int, row))[: (short if i % 2 else bucket)]
        for i, row in enumerate(rng)
    ]
    # Pool sized for one full wave at the LONGEST run (headroom_tokens pins
    # max_blocks — and with it every compiled shape — across timing points).
    per_seq = -(-(bucket + d2) // block_size) + 1
    num_blocks = slots * per_seq + 2

    def timed(steps: int):
        pb = PagedBatcher(
            params, cfg,
            gen=GenerationConfig(max_new_tokens=steps, eos_id=-1),
            slots=slots, num_blocks=num_blocks, block_size=block_size,
            prompt_bucket=bucket, headroom_tokens=d2 - steps,
            ragged=True, token_budget=budget,
        )
        for p in prompts:
            pb.submit(p)
        t0 = time.perf_counter()
        pb.run()
        return time.perf_counter() - t0, pb

    timed(2)  # compile the ragged step (shapes are steps-independent)
    t1, _ = timed(d1)
    t2, pb = timed(d2)
    tok_s = nreq * (d2 - d1) / (t2 - t1)
    fill = (pb.ragged_tokens / max(1, pb.ragged_steps)) / budget
    return tok_s, fill, {
        "slots": slots, "token_budget": budget, "requests": nreq,
        "short": short, "bucket": bucket,
    }


V5E_PEAK_BF16 = 197e12  # FLOP/s per chip


def _sync(x) -> float:
    """Force completion with a host readback (block_until_ready does not
    synchronize through the axon tunnel)."""
    import jax.numpy as jnp

    return float(jnp.asarray(x).reshape(-1)[0])


def _bench_fn(fn, *args, n=3):
    import time as _t

    out = fn(*args)
    _sync(out)
    times = []
    for _ in range(n):
        t0 = _t.perf_counter()
        _sync(fn(*args))
        times.append(_t.perf_counter() - t0)
    return min(times)


def _load_prev_entries(path: str) -> list:
    """Entries of an existing artifact, [] for missing/corrupt/non-list
    files — a torn or foreign file must never abort a live capture."""
    import os

    if not os.path.exists(path):
        return []
    try:
        with open(path) as f:
            prev = json.load(f)
    except (OSError, ValueError):
        return []
    if not isinstance(prev, list):
        return []
    return [e for e in prev if isinstance(e, dict)]


def _merge_entries(new: list, prev: list) -> list:
    """Union by metric name, ``new`` wins — lets a re-run EXTEND a partial
    artifact instead of resetting it (wedge windows are shorter than the
    section list; each window banks what it reached)."""
    have = {e.get("metric") for e in new}
    return new + [e for e in prev if e.get("metric") not in have]


_COMPILE_CACHE_DIR: str | None = None


def _compile_cache_setup() -> str | None:
    """Persistent compilation cache across capture windows (the ROADMAP
    item 5 remainder): with KUBEFLOW_TPU_COMPILE_CACHE_DIR set, every
    program XLA compiles during a bench run is written to that directory
    and reloaded by the NEXT window — so a watcher retry (or a deadline
    re-run after a wedge) pays seconds of cache hits instead of minutes
    of recompiles, and spends its window measuring. Records then stamp
    the dir (``compile_cache``) so an artifact says whether its numbers
    could have been warmed. Off by default: a cold, fully-live compile is
    the honest default for a first measurement."""
    global _COMPILE_CACHE_DIR
    from kubeflow_tpu.webhook.tpu_env import KUBEFLOW_TPU_COMPILE_CACHE_DIR

    cache_dir = os.environ.get(KUBEFLOW_TPU_COMPILE_CACHE_DIR, "").strip()
    if not cache_dir:
        return None
    import jax

    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    except (OSError, AttributeError, ValueError) as err:
        print(f"# compile cache disabled ({err})", file=sys.stderr)
        return None
    # Cache EVERYTHING, however small or fast to compile: the bench's toy
    # smoke shapes fall under the default thresholds, and a warmup that
    # skips them warms nothing. Knob names vary across jax versions;
    # absent ones just keep their defaults.
    for knob, val in (
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(knob, val)
        except (AttributeError, ValueError):
            pass
    _COMPILE_CACHE_DIR = cache_dir
    print(f"# compile cache: {cache_dir}", file=sys.stderr)
    return cache_dir


def _trace_summary():
    """Span-count + p95 engine-step span duration from the installed
    trace ring (``KUBEFLOW_TPU_TRACE_*`` on), or None when tracing is
    off. Stamped into emitted records so a benchmark artifact carries
    the per-step span view that explains its own numbers."""
    from kubeflow_tpu.observability import tracing

    ring = tracing.trace_ring()
    if ring is None:
        return None
    spans = ring.snapshot()
    steps = sorted(
        s["duration_ms"] for s in spans if s["name"] == "engine.step"
    )
    return {
        "spans": len(spans),
        "engine_step_spans": len(steps),
        "p95_step_span_ms": (
            steps[min(len(steps) - 1, int(0.95 * len(steps)))]
            if steps else 0.0
        ),
    }


def _host_kind() -> str:
    """``tpu`` or ``cpu`` — which hardware actually produced a record.
    Stamped next to ``provenance`` so a smoke artifact from a CPU CI
    runner can never be mistaken for a chip measurement (and vice
    versa: a live TPU number replayed later still says where it ran)."""
    try:
        import jax

        return "tpu" if jax.default_backend() in ("tpu", "axon") else "cpu"
    except Exception:
        return "cpu"


def _stamp_provenance(entries: list, provenance: str = "live") -> list:
    """Every record written to a BENCH_*.json carries an explicit
    ``provenance: live|cached`` field. setdefault, not overwrite: entries
    replayed by the cached fallback already say "cached", and entries
    carried forward from a previous artifact keep whatever that capture
    recorded about itself (including the ``host`` it was measured on).
    When the persistent compilation cache is on, records additionally
    carry the cache dir — a warmed measurement is self-describing too,
    and a traced run stamps its span summary."""
    trace = _trace_summary()
    host = _host_kind()
    for e in entries:
        e.setdefault("provenance", provenance)
        e.setdefault("host", host)
        # Mesh shape next to host: bench engines are single-device
        # unless the entry stamped its own axes (tensor-parallel
        # serving replicas write e.g. {"tp": 4}); a mesh number must
        # never be conflated with a single-chip one.
        e.setdefault("mesh", {"tp": 1})
        if _COMPILE_CACHE_DIR is not None:
            e.setdefault("compile_cache", _COMPILE_CACHE_DIR)
        if trace is not None:
            e.setdefault("trace_summary", trace)
    return entries


def run_full_bench(results: list, artifact: str | None = None) -> None:
    """Prefill / kernel / training measurements (stderr + artifact).

    ``BENCH_SMOKE=1`` shrinks every section to toy shapes so the WHOLE
    bench executes on CPU in CI — round 4 shipped sections that had never
    run anywhere because the chip was unreachable all round; this mode
    proves executability, leaving only OOM/perf as chip-day risk. Smoke
    numbers are meaningless and never written to a BENCH_FULL artifact
    (main() refuses --artifact under smoke).

    ``artifact`` (chip runs only): the results list is flushed to this
    path after EVERY section — the axon tunnel's healthy windows have
    been shorter than the full section list twice now (r4: all round;
    r5: 90 s), and an end-of-run-only write turns a mid-run wedge into
    zero recorded measurements. Each flush is atomic (tmp+rename) so a
    kill -9 mid-write cannot leave a torn JSON for the cached-headline
    scanner to trip on."""
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models import llama as L
    from kubeflow_tpu.models.train import make_train_step, shard_state
    from kubeflow_tpu.ops.attention import flash_attention
    from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh

    smoke = _smoke_enabled()
    failed_sections: list = []
    # The two model scales sections draw from: the headline 7B and the
    # ~1.1B that fits one chip with AdamW state.
    big = "tiny" if smoke else "llama-2-7b"
    mid_cfg = (
        L.LLAMA_CONFIGS["tiny"] if smoke
        else L.LlamaConfig(dim=2048, n_layers=16, n_heads=16, n_kv_heads=16,
                           ffn_hidden=5504, max_seq_len=2048)
    )

    def report(metric, value, unit, extra=""):
        results.append({"metric": metric, "value": round(value, 2), "unit": unit})
        print(f"# {metric}: {value:.2f} {unit} {extra}", file=sys.stderr)

    # Entries from a PREVIOUS run of this artifact: carried through every
    # flush (newest wins per metric) so re-running after a partial capture
    # extends the artifact instead of resetting it to [headline] — the
    # merge lives HERE, next to the flush that would otherwise clobber,
    # not in any particular caller.
    carried = (
        _load_prev_entries(artifact)
        if artifact is not None and not smoke else []
    )

    def flush():
        if artifact is None or smoke:
            return
        import os

        tmp = artifact + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(
                    _stamp_provenance(_merge_entries(results, carried)),
                    f, indent=1,
                )
            os.replace(tmp, artifact)
        except OSError as err:
            print(f"# incremental flush to {artifact} failed: {err}",
                  file=sys.stderr)

    def section(fn):
        """Sections are independent measurements: one OOM (e.g. 7B prefill
        on a small chip) must not abort the ones that still fit; each
        section's allocations are collected before the next starts.
        Failures are RECORDED so smoke mode can fail the run — the CI
        gate's whole point is that a section that cannot execute turns
        red, not into a stderr comment."""
        import gc

        try:
            fn()
        except Exception as err:
            failed_sections.append(fn.__name__)
            print(f"# bench section {fn.__name__} failed: {err}", file=sys.stderr)
        flush()
        gc.collect()

    flush()  # persist the headline before the first (long) section

    def kernel_section():
        R = 2 if smoke else 20
        H = 2 if smoke else 32
        for S in ((256,) if smoke else (2048, 4096, 8192)):
            q = jax.random.normal(jax.random.PRNGKey(0), (1, H, S, 128), jnp.bfloat16)
            k = jax.random.normal(jax.random.PRNGKey(1), (1, H, S, 128), jnp.bfloat16)
            v = jax.random.normal(jax.random.PRNGKey(2), (1, H, S, 128), jnp.bfloat16)

            impl = "auto" if smoke else "pallas"  # no pallas on smoke CPU

            def rep_fwd(q, k, v):
                def body(i, o):
                    return flash_attention(q + 0.0 * o, k, v, causal=True, impl=impl)
                return jax.lax.fori_loop(0, R, body, q)

            t = _bench_fn(jax.jit(rep_fwd), q, k, v) / R
            flops = 4 * H * S * S * 128 * 0.5  # causal
            report(f"flash fwd S={S} TFLOP/s", flops / t / 1e12, "TFLOP/s",
                   f"({flops / t / V5E_PEAK_BF16 * 100:.0f}% MFU)")

            def rep_bwd(q, k, v):
                def one(q):
                    o = flash_attention(q, k, v, causal=True, impl=impl)
                    return jnp.sum(o.astype(jnp.float32))
                def body(i, g):
                    return jax.grad(one)(q + 0.0 * g)
                return jax.lax.fori_loop(0, R, body, q)

            t = _bench_fn(jax.jit(rep_bwd), q, k, v) / R
            flops = 4 * H * S * S * 128 * 0.5 * 3.5  # fwd-in-grad + 2.5x bwd
            report(f"flash fwd+bwd S={S} TFLOP/s", flops / t / 1e12, "TFLOP/s",
                   f"({flops / t / V5E_PEAK_BF16 * 100:.0f}% MFU)")

    def masked_kernel_section():
        # The padded-batch (serving) kernel variant: first-class hardware
        # exercise of the int8-mask Mosaic lowering, not just interpret.
        R, S, H = (2, 256, 2) if smoke else (20, 2048, 32)
        impl = "auto" if smoke else "pallas"
        q = jax.random.normal(jax.random.PRNGKey(0), (1, H, S, 128), jnp.bfloat16)
        k = jax.random.normal(jax.random.PRNGKey(1), (1, H, S, 128), jnp.bfloat16)
        v = jax.random.normal(jax.random.PRNGKey(2), (1, H, S, 128), jnp.bfloat16)
        kv_mask = jnp.ones((1, S), bool).at[0, : S // 4].set(False)

        def rep(q, k, v):
            def body(i, o):
                return flash_attention(
                    q + 0.0 * o, k, v, causal=True, impl=impl,
                    kv_mask=kv_mask,
                )
            return jax.lax.fori_loop(0, R, body, q)

        t = _bench_fn(jax.jit(rep), q, k, v) / R
        flops = 4 * H * S * S * 128 * 0.5
        report(f"flash fwd kv_mask S={S} TFLOP/s", flops / t / 1e12, "TFLOP/s",
               f"({flops / t / V5E_PEAK_BF16 * 100:.0f}% MFU)")

    def train_section():
        # ~1.1B config fits one 16 GB chip with AdamW state.
        tcfg = mid_cfg
        plan = MeshPlan(make_mesh(devices=jax.devices()[:1]))
        batch, seq = (2, 128) if smoke else (4, 2048)
        tokens = jax.random.randint(jax.random.PRNGKey(2), (batch, seq), 0,
                                    tcfg.vocab_size)
        n_params = tcfg.param_count()
        flops = 6 * n_params * batch * seq  # fwd 2N + bwd 4N per token
        import gc
        import time as _t

        def measure_step(**kw) -> float:
            """Train-step time for one make_train_step config. Fresh
            params/state per variant, freed before returning, so four
            11 GB optimizer states never coexist."""
            t_params = L.init_params(tcfg, jax.random.PRNGKey(0))
            init_state, step = make_train_step(tcfg, plan, **kw)
            state = shard_state(plan, init_state(t_params))
            del t_params
            state, loss = step(state, tokens)  # compile + first step
            _sync(loss)
            times = []
            for _ in range(3):
                t0 = _t.perf_counter()
                state, loss = step(state, tokens)
                _sync(loss)
                times.append(_t.perf_counter() - t0)
            del state
            gc.collect()
            return min(times)

        # Headline: the default config (chunked CE, full remat).
        t = measure_step()
        report(
            f"train step MFU (1.1B, bs={batch}, S={seq})",
            flops / t / V5E_PEAK_BF16 * 100, "% MFU",
            f"({flops / t / 1e12:.1f} TFLOP/s, {batch * seq / t:.0f} tokens/sec)",
        )
        # Variants: where does the remaining time go, and does a cheaper
        # remat policy fit? Each OOM-guards independently.
        for name, kw in (
            ("dense-CE", dict(loss_chunk=0)),
            ("remat=dots", dict(remat="dots")),
            ("remat=none", dict(remat="none")),
        ):
            try:
                tv = measure_step(**kw)
                report(
                    f"train step MFU [{name}] (1.1B, bs={batch}, S={seq})",
                    flops / tv / V5E_PEAK_BF16 * 100, "% MFU",
                    f"({batch * seq / tv:.0f} tokens/sec)",
                )
            except Exception as err:
                print(f"# train variant {name} failed: {err}", file=sys.stderr)
                gc.collect()

        # Attribution: fwd-only layer stack, CE head, grad, optimizer.
        from kubeflow_tpu.models.train import chunked_causal_lm_loss

        t_params = L.init_params(tcfg, jax.random.PRNGKey(0))
        hid_fn = jax.jit(lambda p, t: L.forward_hidden(p, tcfg, t))
        t_hidden = _bench_fn(hid_fn, t_params, tokens)
        loss_fn = jax.jit(
            lambda p, t: chunked_causal_lm_loss(p, tcfg, t)
        )
        t_loss = _bench_fn(loss_fn, t_params, tokens)
        grad_fn = jax.jit(
            jax.value_and_grad(lambda p, t: chunked_causal_lm_loss(p, tcfg, t))
        )
        t_grad = _bench_fn(lambda p, t: grad_fn(p, t)[0], t_params, tokens)
        report("train profile fwd(hidden) ms", t_hidden * 1e3, "ms",
               f"({2 * n_params * batch * seq / t_hidden / 1e12:.1f} TFLOP/s fwd)")
        report("train profile CE-head ms", (t_loss - t_hidden) * 1e3, "ms")
        report("train profile bwd ms", (t_grad - t_loss) * 1e3, "ms")
        report("train profile optimizer+update ms", (t - t_grad) * 1e3, "ms")

    def batched_section():
        # Batched-serving throughput: the continuous-batching stack's
        # steady-state decode rate at bs=8 on int8 weights (bf16 7B +
        # an 8-slot cache does not fit 16 GB). Two-point measurement
        # cancels prefill; eos_id=-1 disables retirement so all 8 slots
        # decode every step.
        from kubeflow_tpu.models.quant import quantize_params
        from kubeflow_tpu.models.serving import GenerationConfig, batch_generate

        cfg = L.LLAMA_CONFIGS[big]
        params = quantize_params(
            L.init_params(cfg, jax.random.PRNGKey(0)), free_source=True
        )
        bs, plen = (2, 16) if smoke else (8, 128)
        d1, d2 = (8, 16) if smoke else (64, 128)
        rng = jax.random.randint(
            jax.random.PRNGKey(1), (bs, plen), 3, cfg.vocab_size
        )
        prompts = [list(map(int, row)) for row in rng]

        def timed(steps: int) -> float:
            g = GenerationConfig(max_new_tokens=steps, eos_id=-1)
            batch_generate(params, cfg, prompts, g)  # compile + warm
            times = []
            for _ in range(3):
                t0 = time.perf_counter()
                batch_generate(params, cfg, prompts, g)
                times.append(time.perf_counter() - t0)
            return min(times)

        t1, t2 = timed(d1), timed(d2)
        tok_s = bs * (d2 - d1) / (t2 - t1)
        report(
            f"{big} int8 batched decode tokens/sec/chip (bs={bs})",
            tok_s, "tokens/sec",
            "(continuous-batching steady state, all slots active)",
        )

    def long_context_section():
        # Long-context decode: at a 4096-slot cache the per-token cache
        # read (~2.1 GB bf16 on 7B) rivals useful weight traffic; the
        # int8 KV cache halves it. Reuses the headline harness (same
        # warm-up/min-of-N/two-point method) at a 2048-token prompt.
        plen, steps, C = (32, 4, 128) if smoke else (2048, 32, 4096)
        for kv_bits, label in ((0, "bf16 KV"), (8, "int8 KV")):
            tok_s = run_decode_bench(big, plen, steps, C, kv_bits=kv_bits)
            report(
                f"{big} long-ctx decode tokens/sec ({plen}-tok prompt, "
                f"cache {C}, {label})",
                tok_s, "tokens/sec",
            )

    def spec_section():
        # Speculative decoding's recorded numbers: acceptance rate and
        # tok/s on the 1.1B config with a SELF-draft (acceptance 1.0 →
        # the upper-bound speedup of the verification pipeline itself;
        # real drafts land between this and plain decode).
        from kubeflow_tpu.models.speculative import speculative_generate

        tcfg = mid_cfg
        params = L.init_params(tcfg, jax.random.PRNGKey(0))
        bs, plen, steps = (2, 8, 8) if smoke else (4, 32, 64)
        prompt = jax.random.randint(
            jax.random.PRNGKey(1), (bs, plen), 0, tcfg.vocab_size
        )

        def timed_plain():
            toks = L.generate(params, tcfg, prompt, steps=steps, cache_len=256)
            _sync(toks)
            import time as _t

            t0 = _t.perf_counter()
            toks = L.generate(params, tcfg, prompt, steps=steps, cache_len=256)
            _sync(toks)
            return _t.perf_counter() - t0

        def timed_spec():
            speculative_generate(params, tcfg, params, tcfg, prompt,
                                 steps=steps, cache_len=256, k_spec=4)
            import time as _t

            t0 = _t.perf_counter()
            _, stats = speculative_generate(
                params, tcfg, params, tcfg, prompt,
                steps=steps, cache_len=256, k_spec=4,
            )
            return _t.perf_counter() - t0, stats

        t_plain = timed_plain()
        t_spec, stats = timed_spec()
        report(
            f"spec decode tokens/sec (1.1B self-draft, bs={bs}, k=4)",
            bs * steps / t_spec, "tokens/sec",
            f"(plain fused {bs * steps / t_plain:.1f} tok/s, acceptance "
            f"{stats['acceptance_rate']:.2f})",
        )
        results.append({
            "metric": "spec decode acceptance rate (self-draft)",
            "value": round(stats["acceptance_rate"], 3), "unit": "ratio",
        })

    def spec_curve_section():
        # Acceptance-vs-speedup CURVE: the self-draft line above is the
        # pipeline's upper bound (acceptance 1.0); real deployment value
        # lives below it. Degrade the draft by mixing Gaussian noise into
        # the target's weights (per-leaf, scaled to the leaf's std) at two
        # strengths and record (acceptance, realized tok/s) at each — two
        # honest points between the ceiling and plain decode.
        from kubeflow_tpu.models.speculative import speculative_generate

        tcfg = mid_cfg
        params = L.init_params(tcfg, jax.random.PRNGKey(0))
        bs, plen, steps = (2, 8, 8) if smoke else (4, 32, 64)
        prompt = jax.random.randint(
            jax.random.PRNGKey(1), (bs, plen), 0, tcfg.vocab_size
        )

        def degrade(sigma: float, key):
            leaves, treedef = jax.tree_util.tree_flatten(params)
            keys = jax.random.split(key, len(leaves))
            noisy = [
                w + sigma * jnp.std(w) * jax.random.normal(k, w.shape, w.dtype)
                for w, k in zip(leaves, keys)
            ]
            return jax.tree_util.tree_unflatten(treedef, noisy)

        from kubeflow_tpu.models.speculative import truncated_draft

        half = max(1, tcfg.n_layers // 2)
        variants = [
            (f"noisy sigma={s}",
             lambda s=s: (degrade(s, jax.random.PRNGKey(int(s * 1e4))),
                          tcfg))
            for s in (0.005, 0.05)
        ] + [
            # The deployment-shaped draft: the target's own first half of
            # layers, zero training, zero extra checkpoint.
            (f"truncated {half}-layer",
             lambda: truncated_draft(params, tcfg, half)),
        ]
        for label, make in variants:
            draft, dcfg = make()
            # warm/compile, then time.
            speculative_generate(params, tcfg, draft, dcfg, prompt,
                                 steps=steps, cache_len=256, k_spec=4)
            t0 = time.perf_counter()
            _, stats = speculative_generate(
                params, tcfg, draft, dcfg, prompt,
                steps=steps, cache_len=256, k_spec=4,
            )
            dt = time.perf_counter() - t0
            report(
                f"spec decode tokens/sec (1.1B {label} draft, bs={bs}, k=4)",
                bs * steps / dt, "tokens/sec",
                f"(acceptance {stats['acceptance_rate']:.2f})",
            )
            results.append({
                "metric": f"spec decode acceptance rate ({label})",
                "value": round(stats["acceptance_rate"], 3), "unit": "ratio",
            })
            del draft

    def spec_serving_section():
        # Speculative SERVING throughput — the engine (continuous
        # batching + paged pool + per-slot acceptance), not the raw
        # speculative_generate loop: truncated-half-layer draft over the
        # block pool, two-point timing so admit prefills cancel.
        from kubeflow_tpu.models.serving import GenerationConfig
        from kubeflow_tpu.models.speculative import (
            SpeculativePagedBatcher, truncated_draft,
        )

        tcfg = mid_cfg
        params = L.init_params(tcfg, jax.random.PRNGKey(0))
        half = max(1, tcfg.n_layers // 2)
        draft, dcfg = truncated_draft(params, tcfg, half)
        bs, plen = (2, 16) if smoke else (4, 32)
        s1, s2 = (4, 8) if smoke else (24, 72)
        rng = jax.random.randint(
            jax.random.PRNGKey(1), (bs, plen), 3, tcfg.vocab_size
        )
        prompts = [list(map(int, row)) for row in rng]

        def timed(steps: int):
            # headroom pins max_blocks (hence tables/kv_mask/draft-cache
            # shapes and every compiled program) constant across the
            # timing points — otherwise compile time lands inside t1/t2
            # and does NOT cancel in the subtraction.
            sb = SpeculativePagedBatcher(
                params, tcfg, draft, dcfg,
                gen=GenerationConfig(max_new_tokens=steps, eos_id=-1),
                slots=bs, num_blocks=64, block_size=16, prompt_bucket=plen,
                k_spec=4, headroom_tokens=s2 - steps,
            )
            for p in prompts:
                sb.submit(p)
            t0 = time.perf_counter()
            sb.run()
            return time.perf_counter() - t0, sb.acceptance_rate

        timed(2)  # compile admit + verify round (same shapes as below)
        t1, _ = timed(s1)
        t2, rate = timed(s2)
        report(
            f"spec-paged serving tokens/sec (1.1B, {half}-layer draft, "
            f"bs={bs}, k=4)",
            bs * (s2 - s1) / (t2 - t1), "tokens/sec",
            f"(acceptance {rate:.2f}, block pool 64x16)",
        )
        results.append({
            "metric": "spec-paged serving acceptance rate "
                      f"({half}-layer draft)",
            "value": round(rate, 3), "unit": "ratio",
        })

    def paged_kernel_section():
        # Paged-serving decode: gathered-view path vs the pallas
        # paged-attention kernel (ops/paged_attention.py). The gathered
        # path materializes (B, Hkv, MAXB*BS, D) per layer per step; the
        # kernel DMAs each slot's live blocks straight from the pool —
        # the delta IS the gather's HBM cost. int8 weights (as in
        # batched_section: bf16 7B + pool won't fit 16 GB), bf16 pool
        # (the kernel's supported format).
        from kubeflow_tpu.models.paged import PagedBatcher
        from kubeflow_tpu.models.quant import quantize_params
        from kubeflow_tpu.models.serving import GenerationConfig

        cfg = L.LLAMA_CONFIGS[big]
        params = quantize_params(
            L.init_params(cfg, jax.random.PRNGKey(0)), free_source=True
        )
        bs, plen = (2, 16) if smoke else (8, 128)
        d1, d2 = (4, 8) if smoke else (48, 112)
        nblocks = 16 if smoke else 192
        rng = jax.random.randint(
            jax.random.PRNGKey(1), (bs, plen), 3, cfg.vocab_size
        )
        prompts = [list(map(int, row)) for row in rng]

        def timed(steps: int, attn_kernel: bool) -> float:
            # headroom pins max_blocks (and so every compiled shape)
            # across the two timing points; min-of-2 after a compile run.
            times = []
            for _ in range(2):
                pb = PagedBatcher(
                    params, cfg,
                    gen=GenerationConfig(max_new_tokens=steps, eos_id=-1),
                    slots=bs, num_blocks=nblocks, block_size=16,
                    prompt_bucket=max(16, plen),
                    headroom_tokens=d2 - steps,
                    attn_kernel=attn_kernel,
                )
                for p in prompts:
                    pb.submit(p)
                t0 = time.perf_counter()
                pb.run()
                times.append(time.perf_counter() - t0)
            return min(times)

        for attn_kernel, label in ((False, "gathered"), (True, "kernel")):
            timed(2, attn_kernel)  # compile both step shapes
            t1 = timed(d1, attn_kernel)
            t2 = timed(d2, attn_kernel)
            report(
                f"{big} int8 paged decode tokens/sec (bs={bs}, "
                f"{label} attention)",
                bs * (d2 - d1) / (t2 - t1), "tokens/sec",
                f"(block pool {nblocks}x16)",
            )

        # Dense engine, same question: ContinuousBatcher at a roomy
        # cache_len with short fills — XLA reads all C slots per step,
        # the length-bounded kernel reads each slot's filled prefix.
        from kubeflow_tpu.models.continuous import ContinuousBatcher

        C = 128 if smoke else 2048

        def timed_dense(steps: int, attn_kernel: bool) -> float:
            # Compiled shapes depend on slots/cache_len/prompt_bucket
            # only (the dense cache is fixed-size), so different steps
            # values share one executable and compile time cancels.
            times = []
            for _ in range(2):
                cb = ContinuousBatcher(
                    params, cfg,
                    gen=GenerationConfig(max_new_tokens=steps, eos_id=-1),
                    slots=bs, cache_len=C, prompt_bucket=max(16, plen),
                    attn_kernel=attn_kernel,
                )
                for p in prompts:
                    cb.submit(p)
                t0 = time.perf_counter()
                cb.run()
                times.append(time.perf_counter() - t0)
            return min(times)

        for attn_kernel, label in ((False, "xla"), (True, "kernel")):
            timed_dense(2, attn_kernel)
            t1 = timed_dense(d1, attn_kernel)
            t2 = timed_dense(d2, attn_kernel)
            report(
                f"{big} int8 dense decode tokens/sec (bs={bs}, cache {C}, "
                f"{label} attention)",
                bs * (d2 - d1) / (t2 - t1), "tokens/sec",
                "(length-bounded cache reads)" if attn_kernel else
                "(XLA reads all cache slots)",
            )

    def decode_attr_section():
        # Decode-step ATTRIBUTION (bs=1 bf16 7B, the headline config):
        # where does the per-token time go? Each component is timed as a
        # standalone jitted program over the same shapes the fused decode
        # uses; their sum vs the fused per-token time splits the budget
        # into memory-bound compute vs dispatch/fusion residual — the
        # r03 "48.9 measured vs 61 roofline" question, answered with the
        # same nested-difference technique as the train profile above.
        cfg = L.LLAMA_CONFIGS[big]
        C, plen, steps = (64, 16, 4) if smoke else (512, 128, 32)
        # The SAME harness that produces the headline number, so the
        # attribution decomposes exactly what the scoreboard reports
        # (run before this section's own params exist — two 7B copies
        # don't share a chip).
        t_full = 1.0 / run_decode_bench(big, plen, steps, C)
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        prompt = jax.random.randint(
            jax.random.PRNGKey(1), (1, plen), 0, cfg.vocab_size
        )

        cache = L.init_kv_cache(cfg, 1, C)
        cache = L.prime_kv_cache(params, cfg, prompt, cache)
        pos = jnp.asarray(plen, jnp.int32)
        q = jnp.ones((1, cfg.n_heads, 1, cfg.head_dim), cfg.dtype)
        tok = jnp.ones((1, 1), jnp.int32)

        def attn_only(cache, pos):
            # The cache READ: per-layer GQA decode attention, fixed q.
            def body(o, cache_l):
                o = o + L._gqa_decode_attention(
                    q, cache_l["k"], cache_l["v"], pos
                )
                return o, None

            o, _ = jax.lax.scan(body, jnp.zeros_like(q), cache)
            return o

        t_attn = _bench_fn(jax.jit(attn_only), cache, pos)

        def weights_only(params, tok):
            # The weight READ: embed + per-layer qkv/wo/mlp + lm head,
            # attention replaced by q (hk/hv folded in as a scalar bias
            # so XLA cannot dead-code-eliminate the wk/wv matmuls).
            x = L._embed(params, cfg, tok)
            cos, sin = L.rope_frequencies(cfg, jnp.asarray([plen]))

            def body(x, layer):
                h = L._norm(x, layer["attn_norm"], cfg)
                hq, hk, hv = L._qkv(h, layer)
                qh = L.apply_rope(L._split_heads(hq, cfg.n_heads), cos, sin)
                qh = qh + (jnp.mean(hk) + jnp.mean(hv)).astype(qh.dtype)
                x = x + L._mm(L._merge_heads(qh), layer["wo"])
                h = L._norm(x, layer["mlp_norm"], cfg)
                x = x + L._mlp(layer, h, cfg)
                return x, None

            x, _ = jax.lax.scan(body, x, params["layers"])
            return L._lm_head_logits(
                L._norm(x[:, 0], params["final_norm"], cfg), params
            )

        t_weights = _bench_fn(jax.jit(weights_only), params, tok)
        logits = jnp.zeros((1, cfg.vocab_size), cfg.dtype)
        t_sample = _bench_fn(
            jax.jit(lambda l, k: L.sample_logits(l, k, 0.0, 0, 1.0)),
            logits, jax.random.PRNGKey(0),
        )
        resid = t_full - t_attn - t_weights - t_sample
        report("decode attr full fused ms/token", t_full * 1e3, "ms",
               f"({1.0 / t_full:.1f} tok/s)")
        report("decode attr attention cache-read ms", t_attn * 1e3, "ms")
        report("decode attr weights(qkv/mlp/head) ms", t_weights * 1e3, "ms")
        report("decode attr sampling ms", t_sample * 1e3, "ms")
        report("decode attr residual (dispatch/cache-write/fusion) ms",
               resid * 1e3, "ms",
               "(negative = fused program beats the sum of its parts)")

    def batched_longctx_section():
        # Batched LONG-CONTEXT serving with the int8 KV cache — the shape
        # the format exists for: an 8-slot × 3072-token bf16 cache is
        # 12.9 GB (cannot share a 16 GB chip with int8 weights); int8
        # halves it to 6.4 GB and fits. Steady-state decode via the
        # two-point method (admit prefills cancel in the subtraction).
        from kubeflow_tpu.models.continuous import ContinuousBatcher
        from kubeflow_tpu.models.quant import quantize_params
        from kubeflow_tpu.models.serving import GenerationConfig

        cfg = L.LLAMA_CONFIGS[big]
        params = quantize_params(
            L.init_params(cfg, jax.random.PRNGKey(0)), free_source=True
        )
        bs, plen, C = (2, 32, 128) if smoke else (8, 2048, 3072)
        s1, s2 = (4, 12) if smoke else (16, 80)
        rng = jax.random.randint(
            jax.random.PRNGKey(1), (bs, plen), 3, cfg.vocab_size
        )
        prompts = [list(map(int, row)) for row in rng]

        def timed(steps: int, kv_bits: int) -> float:
            cb = ContinuousBatcher(
                params, cfg,
                gen=GenerationConfig(max_new_tokens=steps, eos_id=-1),
                slots=bs, cache_len=C, prompt_bucket=plen, kv_bits=kv_bits,
            )
            for p in prompts:
                cb.submit(p)
            t0 = time.perf_counter()
            cb.run()
            return time.perf_counter() - t0

        timed(4, 8)  # compile admit + step
        t1, t2 = timed(s1, 8), timed(s2, 8)
        report(
            f"{big} int8-KV batched long-ctx decode tokens/sec "
            f"(bs={bs}, {plen}-tok prompts, cache {C})",
            bs * (s2 - s1) / (t2 - t1), "tokens/sec",
            "(int8 weights + int8 KV: 6.4 GB cache vs 12.9 GB bf16)",
        )
        try:
            timed(4, 0)
            bf16_fits = 1.0
        except Exception as err:
            bf16_fits = 0.0
            print(f"# bf16 KV at the same shape: does not fit ({err})"[:200],
                  file=sys.stderr)
        results.append({
            "metric": f"bf16 KV fits bs={bs} cache={C} alongside weights",
            "value": bf16_fits, "unit": "bool",
        })

    def prefill_section():
        cfg = L.LLAMA_CONFIGS[big]
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        S = 128 if smoke else 2048
        prompt = jax.random.randint(jax.random.PRNGKey(1), (1, S), 0, cfg.vocab_size)

        def prefill_logits(params, prompt):
            cache = L.init_kv_cache(cfg, 1, S)
            logits, _ = L._prefill_impl(params, cfg, prompt, cache)
            return logits

        t = _bench_fn(jax.jit(prefill_logits), params, prompt)
        n_params = cfg.param_count()
        flops = 2 * n_params * S  # forward ~2·N per token
        report(f"{big} prefill tokens/sec/chip (bs=1, S={S})", S / t,
               "tokens/sec",
               f"({flops / t / 1e12:.1f} TFLOP/s, {flops / t / V5E_PEAK_BF16 * 100:.0f}% MFU)")

    section(kernel_section)
    section(masked_kernel_section)
    section(train_section)
    section(batched_section)
    section(spec_section)
    section(spec_curve_section)
    section(spec_serving_section)
    section(paged_kernel_section)
    section(decode_attr_section)
    # Biggest-HBM sections LAST (7B prefill, then 7B + 4096-slot cache):
    # an OOM on a small chip must not rob the sections above of their
    # measurement, and the riskiest section must rob nobody.
    section(prefill_section)
    section(long_context_section)
    # Riskiest-last discipline: this section deliberately ATTEMPTS a
    # bf16 shape expected to OOM (to record that int8 KV is what makes
    # the shape fit), so nothing may run after it.
    section(batched_longctx_section)
    if smoke and failed_sections:
        # On a chip, a failed section is a lost measurement (reported,
        # run continues). In smoke, a failed section is a BUG the gate
        # exists to catch — fail loudly.
        raise RuntimeError(
            f"smoke: sections failed: {', '.join(failed_sections)}"
        )


def _device_watchdog(probes: int = 4, timeout_s: int = 120) -> str:
    """Probe device enumeration in a SUBPROCESS with a timeout: a wedged
    axon tunnel hangs jax.devices() inside C++ where no Python timeout can
    reach, and the bench must emit its JSON line rather than hang the
    driver. Healthy enumeration takes seconds.

    A wedged tunnel is usually TRANSIENT (round 3's scoreboard was zeroed
    by a single 300 s hang that had cleared by the next manual run), so one
    probe is not evidence the chip is gone: retry with backoff, each probe
    subprocess-isolated so a hung probe cannot wedge this process. Returns
    "" as soon as any probe succeeds, else the last failure reason so a
    broken env is distinguishable from a wedged tunnel. Robustness posture
    mirrors the reference culler, which never turns a probe error into a
    verdict (culling_controller.go:277-322)."""
    import subprocess
    import time as _t

    backoff = (0, 15, 30, 45)
    # First probe gets the full timeout (covers slow-but-healthy cold
    # tunnels); retries get half — a wedge that lasts 120 s rarely clears
    # by 180 s, and the already-broken case must not double the driver's
    # bench latency. Worst case ≈ 120 + 3·60 + 90 s sleep ≈ 6.5 min.
    last = "no probes ran"
    for i in range(probes):
        if i:
            _t.sleep(backoff[min(i, len(backoff) - 1)])
        budget = timeout_s if i == 0 else max(30, timeout_s // 2)
        try:
            probe = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=budget, capture_output=True,
            )
        except subprocess.TimeoutExpired:
            last = f"hung (> {budget}s, probe {i + 1}/{probes})"
            print(f"# device probe {i + 1}/{probes}: {last}", file=sys.stderr)
            continue
        if probe.returncode == 0:
            return ""
        lines = probe.stderr.decode(errors="replace").strip().splitlines()
        last = "failed: " + (lines[-1] if lines else f"exit {probe.returncode}")
        print(f"# device probe {i + 1}/{probes}: {last}", file=sys.stderr)
    return last


def _cached_headline(quant_bits: int = 0, kv_bits: int = 0):
    """Most recent BENCH_FULL* artifact headline entry matching the
    requested config, for the cached-provenance fallback: when every
    device probe fails, the honest scoreboard line is the last measured
    number explicitly marked cached — not 0.0, which reads as "the
    framework decodes zero tokens/sec". Searches next to this script (where
    round artifacts are committed) AND the cwd (where ``--full`` writes by
    default when invoked from elsewhere). A cached number must not be
    served for a DIFFERENT config: the weight dtype is matched on its full
    token ("intN weights" / "bf16" — a bare "int8" would false-match the
    ", int8 KV" cache label), and the KV-cache format must agree too.
    Returns (entry, filename) or (None, None)."""
    import glob
    import os

    here = os.path.dirname(os.path.abspath(__file__))
    want = f"int{quant_bits} weights" if quant_bits else "bf16"
    seen = set()
    paths = []
    for d in (here, os.getcwd()):
        for p in glob.glob(os.path.join(d, "BENCH_FULL*.json")):
            rp = os.path.realpath(p)
            if rp not in seen:
                seen.add(rp)
                paths.append(p)
    # Mtime alone mis-orders artifacts restored by a checkout (git stamps
    # them all identically): break ties by the round suffix in the name,
    # so BENCH_FULL_r05_headline.json beats BENCH_FULL_r03.json instead
    # of an older round shadowing the live headline.
    import re

    def _round_of(p):
        m = re.search(r"_r(\d+)", os.path.basename(p))
        return int(m.group(1)) if m else -1

    paths.sort(key=lambda p: (os.path.getmtime(p), _round_of(p)),
               reverse=True)
    for path in paths:
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            continue
        if not (isinstance(data, list) and data and isinstance(data[0], dict)):
            continue
        entry = data[0]
        metric = str(entry.get("metric", ""))
        if (
            entry.get("value") and "tokens/sec" in str(entry.get("unit"))
            and want in metric
            and (", int8 KV" in metric) == bool(kv_bits)
        ):
            return entry, os.path.basename(path)
    return None, None


def _emit_cached_or_zero(reason: str, quant_bits: int = 0,
                         kv_bits: int = 0) -> int:
    """Terminal fallback when no live measurement is possible. Emits the
    last measured headline for the same config with explicit
    ``provenance: cached`` so the scoreboard shows the real capability
    number, but keeps rc 1 so the environment failure stays
    machine-detectable (a dead tunnel must never look like a passing run
    to anything gating on exit status)."""
    cached, src = _cached_headline(quant_bits, kv_bits)
    if cached is not None:
        out = dict(cached)
        out["metric"] = f"{out['metric']} [CACHED from {src}]"
        out["provenance"] = "cached"
        out["cached_from"] = src
        out["live_failure"] = reason
        out.setdefault("vs_baseline", 0.0)
        print(json.dumps(out))
        print(
            f"# live measurement unavailable ({reason}); emitted last "
            f"measured headline from {src} with provenance=cached",
            file=sys.stderr,
        )
        return 1
    print(
        json.dumps(
            {
                "metric": f"llama decode tokens/sec/chip ({reason}; "
                          "no cached artifact)",
                "value": 0.0,
                "unit": "tokens/sec/chip",
                "vs_baseline": 0.0,
            }
        )
    )
    return 1


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return max(0, int(raw))
    except ValueError:
        print(f"# ignoring non-integer {name}={raw!r}", file=sys.stderr)
        return default


def _deadline_guard(quant_bits: int, kv_bits: int):
    """``BENCH_DEADLINE_S``: hard wall-clock bound on the WHOLE live run,
    promoted from ci/tpu_bench_watcher.sh's ``timeout 900 python bench.py``
    staging. A wedge can strike MID-MEASUREMENT, inside C++ where no
    in-process timeout fires (the device watchdog only guards enumeration),
    so the bounded run executes in a child process; on expiry the parent
    emits the cached-provenance fallback line. Returns the child's rc, or
    None when this process should run the bench itself (no deadline set,
    or this IS the child)."""
    import subprocess

    raw = os.environ.get("BENCH_DEADLINE_S", "").strip()
    if not raw or os.environ.get("_BENCH_DEADLINE_CHILD"):
        return None
    try:
        budget = float(raw)
    except ValueError:
        print(f"error: BENCH_DEADLINE_S must be a number, got {raw!r}",
              file=sys.stderr)
        return 2
    env = dict(os.environ, _BENCH_DEADLINE_CHILD="1")
    try:
        return subprocess.run(
            [sys.executable, os.path.abspath(__file__)] + sys.argv[1:],
            env=env, timeout=budget,
        ).returncode
    except subprocess.TimeoutExpired:
        print(f"# live bench exceeded BENCH_DEADLINE_S={raw}s; killed",
              file=sys.stderr)
        return _emit_cached_or_zero(
            f"live run exceeded BENCH_DEADLINE_S={raw}s", quant_bits, kv_bits
        )


def _run_mixed_main(device, quant_bits: int, smoke: bool,
                    artifact: str | None) -> int:
    """``--mixed``: the ragged mixed prefill/decode headline. Falls back
    from the 7B config (int8 weights unless --intN was given: bf16 7B plus
    a block pool don't share a 16 GB chip, exactly as in
    paged_kernel_section) to tiny, like ATTEMPTS; a CPU backend goes
    straight to tiny — random-initializing 7B on host CPU is minutes of
    init for a number that says nothing about the chip."""
    kind = getattr(device, "device_kind", str(device))
    attempts = [("llama-2-7b", quant_bits or 8), ("tiny", quant_bits)]
    if smoke or device.platform == "cpu":
        attempts = [("tiny", 0 if smoke else quant_bits)]
    last_err = None
    for cfg_name, qbits in attempts:
        try:
            tok_s, fill, shape = run_mixed_bench(cfg_name, qbits, smoke=smoke)
        except Exception as err:
            last_err = err
            print(f"# mixed bench attempt {cfg_name} failed: {err}",
                  file=sys.stderr)
            continue
        wlabel = f"int{qbits} weights" if qbits else "bf16"
        prov = "smoke" if smoke else "live"
        entry = {
            "metric": (
                f"{cfg_name} ragged mixed prefill+decode tokens/sec/chip "
                f"(bs={shape['slots']}, token_budget={shape['token_budget']}, "
                f"{wlabel}, one fused dispatch per step, {kind})"
            ),
            "value": round(tok_s, 2),
            "unit": "tokens/sec/chip",
            # The comparison this mode exists for: the r05 bs=1 live
            # headline. Only meaningful on the headline-class model.
            "vs_baseline": (
                round(tok_s / R05_LIVE_HEADLINE_TOK_S, 3)
                if cfg_name == "llama-2-7b" else 0.0
            ),
            "provenance": prov,
        }
        fill_entry = {
            "metric": (
                f"{cfg_name} ragged mixed batch fill (bs={shape['slots']}, "
                f"token_budget={shape['token_budget']})"
            ),
            "value": round(fill, 4),
            "unit": "ratio",
            "provenance": prov,
        }
        trace = _trace_summary()
        if trace is not None:
            entry["trace_summary"] = trace
            fill_entry["trace_summary"] = trace
        print(json.dumps(entry))
        print(f"# {fill_entry['metric']}: {fill:.4f}", file=sys.stderr)
        if artifact is not None and not smoke:
            merged = _stamp_provenance(_merge_entries(
                [entry, fill_entry], _load_prev_entries(artifact)))
            try:
                with open(artifact + ".tmp", "w") as f:
                    json.dump(merged, f, indent=1)
                os.replace(artifact + ".tmp", artifact)
                print(f"# wrote {artifact}", file=sys.stderr)
            except OSError as err:
                print(f"# could not write {artifact}: {err}", file=sys.stderr)
        return 0
    print(f"# last error: {last_err}", file=sys.stderr)
    return _emit_cached_or_zero(f"all mixed attempts failed: {last_err}",
                                quant_bits, 0)


def main() -> int:
    # Usage errors first: they must not pay (or be masked by) a device probe.
    if "--int8" in sys.argv[1:] and "--int4" in sys.argv[1:]:
        print("error: --int8 and --int4 are mutually exclusive", file=sys.stderr)
        return 2
    if "--mixed" in sys.argv[1:] and "--full" in sys.argv[1:]:
        print("error: --mixed and --full are mutually exclusive",
              file=sys.stderr)
        return 2
    quant_bits = 8 if "--int8" in sys.argv[1:] else (
        4 if "--int4" in sys.argv[1:] else 0
    )
    kv_bits = 8 if "--kv8" in sys.argv[1:] else 0
    full = "--full" in sys.argv[1:]
    mixed = "--mixed" in sys.argv[1:]
    artifact = "BENCH_FULL.json"
    artifact_requested = False
    args = sys.argv[1:]
    for i, arg in enumerate(args):
        if arg == "--artifact":
            if i + 1 >= len(args):
                print("error: --artifact requires a path", file=sys.stderr)
                return 2
            artifact = args[i + 1]
            artifact_requested = True
        elif arg.startswith("--artifact="):
            artifact = arg.split("=", 1)[1]
            artifact_requested = True

    import os

    # Tracing is opt-in via the KUBEFLOW_TPU_TRACE_* contract vars: when
    # set, engine steps are spanned and every emitted record carries a
    # trace_summary stamp (_stamp_provenance).
    from kubeflow_tpu.observability import tracing

    tracing.configure_from_env()

    smoke = _smoke_enabled()
    if smoke and artifact_requested:
        # Smoke numbers are toy-shape executability checks, never
        # measurements; refusing the artifact keeps them out of the
        # cached-headline search space.
        print("error: --artifact is not allowed under BENCH_SMOKE",
              file=sys.stderr)
        return 2

    if not os.path.isabs(artifact) and os.sep not in artifact:
        # Bare default/filename artifacts land next to this script so the
        # cached-headline fallback finds them regardless of the driver's cwd.
        artifact = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                artifact)

    rc = _deadline_guard(quant_bits, kv_bits)
    if rc is not None:
        return rc

    if smoke:
        # Smoke never touches the chip: force the CPU backend BEFORE jax
        # initializes (the axon plugin ignores JAX_PLATFORMS, and a wedged
        # tunnel hangs enumeration) and skip the device watchdog.
        import jax

        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", 1)
        except AttributeError:
            pass  # older jax: one CPU device is already the default
    else:
        # Watcher-cycle retry, promoted from ci/tpu_bench_watcher.sh: the
        # shell watcher slept between probe cycles because round-3/5 wedges
        # cleared within a few windows, so one failed watchdog pass is not
        # the final word on the tunnel. BENCH_RETRY_CYCLES extra probe
        # windows (default 1), BENCH_RETRY_SLEEP_S apart (default 60),
        # each pass itself subprocess-isolated per probe with a hard
        # per-probe deadline (_device_watchdog).
        cycles = _env_int("BENCH_RETRY_CYCLES", 1)
        sleep_s = _env_int("BENCH_RETRY_SLEEP_S", 60)
        reason = _device_watchdog()
        for cycle in range(cycles):
            if not reason:
                break
            print(f"# probe window failed ({reason}); retry cycle "
                  f"{cycle + 1}/{cycles} in {sleep_s}s", file=sys.stderr)
            time.sleep(sleep_s)
            reason = _device_watchdog(probes=2)
        if reason:
            return _emit_cached_or_zero(f"device enumeration {reason}",
                                        quant_bits, kv_bits)

    import jax
    _compile_cache_setup()  # before any trace: first compile must bank
    device = jax.devices()[0]
    kind = getattr(device, "device_kind", str(device))
    if mixed:
        return _run_mixed_main(
            device, quant_bits, smoke,
            artifact if artifact_requested else None,
        )
    last_err = None
    src_attempts = [("tiny", 16, 8, 64, None)] if smoke else ATTEMPTS
    attempts = [
        (cfg_name, prompt_len, steps, cache_len, baseline, force_xla)
        for cfg_name, prompt_len, steps, cache_len, baseline in src_attempts
        # Safety net for the headline metric: if a config fails with the
        # pallas prefill kernel (e.g. a Mosaic lowering regression), retry
        # it on the XLA path before shrinking the model. Decode tok/s is
        # measured by a two-point difference that cancels prefill, so the
        # fallback does not change what the number means.
        for force_xla in (False, True)
    ]
    for cfg_name, prompt_len, steps, cache_len, baseline, force_xla in attempts:
        try:
            if force_xla:
                from kubeflow_tpu.ops.attention import force_xla_fallback

                force_xla_fallback(True)
                # Drop any cached executable from the failed attempt — the
                # jit cache does not key on the fallback flag.
                jax.clear_caches()
                print(f"# retrying {cfg_name} with XLA attention fallback",
                      file=sys.stderr)
            tok_s = run_decode_bench(
                cfg_name, prompt_len, steps, cache_len,
                quant_bits=quant_bits, kv_bits=kv_bits,
            )
            headline = {
                "metric": (
                    f"{cfg_name} greedy decode tokens/sec/chip "
                    f"(bs=1, "
                    f"{f'int{quant_bits} weights' if quant_bits else 'bf16'}"
                    f"{', int8 KV' if kv_bits else ''}, "
                    f"fused loop, {kind})"
                ),
                "value": round(tok_s, 2),
                "unit": "tokens/sec/chip",
                "vs_baseline": (
                    round(tok_s / baseline, 3) if baseline else 0.0
                ),
                # Explicit measurement provenance on the LIVE path too, so
                # every emitted record is self-describing (the cached
                # fallback already says "cached"); smoke's toy numbers are
                # labelled as such and never reach an artifact.
                "provenance": "smoke" if smoke else "live",
                **({"compile_cache": _COMPILE_CACHE_DIR}
                   if _COMPILE_CACHE_DIR else {}),
            }
            trace = _trace_summary()
            if trace is not None:
                headline["trace_summary"] = trace
            print(json.dumps(headline))
            if full:
                results = [headline]
                try:
                    run_full_bench(results, artifact=None if smoke else artifact)
                except Exception as err:
                    print(f"# full bench failed partway: {err}", file=sys.stderr)
                    if smoke:
                        # The gate must turn red when a section cannot
                        # execute — that is its entire purpose.
                        return 1
                if smoke:
                    # Executability proven; toy numbers must not persist
                    # where the cached-headline fallback could find them.
                    print("# BENCH_SMOKE: artifact write skipped",
                          file=sys.stderr)
                    return 0
                # The artifact write must never invalidate a measurement
                # that already succeeded (a read-only repo checkout would
                # otherwise turn the printed headline into an "attempt
                # failed" re-run): fall back to cwd, then to stderr-only.
                # Merge-aware like run_full_bench's incremental flush —
                # entries a previous partial run measured and this run
                # did not re-reach must survive the final write too.
                for target in (artifact, os.path.basename(artifact)):
                    merged = _stamp_provenance(_merge_entries(
                        results, _load_prev_entries(target)))
                    try:
                        with open(target + ".tmp", "w") as f:
                            json.dump(merged, f, indent=1)
                        os.replace(target + ".tmp", target)
                        print(f"# wrote {target}", file=sys.stderr)
                        break
                    except OSError as err:
                        print(f"# could not write {target}: {err}",
                              file=sys.stderr)
            return 0
        except Exception as err:  # OOM or compile failure → try smaller
            last_err = err
            print(f"# bench attempt {cfg_name} failed: {err}", file=sys.stderr)
    print(f"# last error: {last_err}", file=sys.stderr)
    return _emit_cached_or_zero(f"all attempts failed: {last_err}", quant_bits,
                                kv_bits)


if __name__ == "__main__":
    sys.exit(main())
