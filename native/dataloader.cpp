// Host-side prefetching token data-loader.
//
// TPU training is device-bound; the host's only job on the data path is to
// have the next (batch, seq) int32 window ready before the device asks.
// This loader mmaps a binary uint32 token corpus and assembles randomly
// sampled batches on a background thread into a bounded queue, so batch
// assembly overlaps device compute (the reference has no data plane at all
// — SURVEY.md §2.5; this is the framework's in-notebook input pipeline).
//
// C ABI (consumed via ctypes from kubeflow_tpu/data/loader.py):
//   dl_open(path, batch, seq, seed, prefetch, start_batch)
//           -> opaque handle (NULL on error); start_batch fast-forwards
//              the sample stream by that many batches (checkpoint resume
//              must not re-read the batches the lost run already
//              consumed — O(log n) GF(2) matrix jump, mirroring the
//              Python fallback's _xorshift_skip bit-for-bit)
//   dl_num_tokens(h) -> corpus size in tokens
//   dl_next(h, out)  -> fills batch*seq int32s; 0 on success
//   dl_close(h)
//
// Determinism: one producer thread + a fixed-seed xorshift64* stream means
// the batch sequence is a pure function of (corpus, batch, seq, seed).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Loader {
  const uint32_t* tokens = nullptr;
  size_t n_tokens = 0;
  size_t map_len = 0;
  int fd = -1;
  int batch = 0;
  int seq = 0;
  uint64_t rng = 0;
  size_t capacity = 0;

  std::mutex mu;
  std::condition_variable cv_full, cv_empty;
  std::deque<std::vector<int32_t>> queue;
  std::atomic<bool> stop{false};
  std::thread producer;

  uint64_t next_rand() {
    // xorshift64*
    rng ^= rng >> 12;
    rng ^= rng << 25;
    rng ^= rng >> 27;
    return rng * 0x2545F4914F6CDD1DULL;
  }

  void produce() {
    while (!stop.load(std::memory_order_relaxed)) {
      std::vector<int32_t> buf(static_cast<size_t>(batch) * seq);
      const size_t max_start = n_tokens - static_cast<size_t>(seq);
      for (int b = 0; b < batch; ++b) {
        const size_t start = next_rand() % (max_start + 1);
        std::memcpy(buf.data() + static_cast<size_t>(b) * seq,
                    tokens + start, static_cast<size_t>(seq) * sizeof(int32_t));
      }
      std::unique_lock<std::mutex> lock(mu);
      cv_full.wait(lock, [this] { return queue.size() < capacity || stop; });
      if (stop) return;
      queue.push_back(std::move(buf));
      cv_empty.notify_one();
    }
  }
};

// One xorshift64 state transition (the output multiply does not feed the
// state, so resume-skip only needs this part).
uint64_t xs_step(uint64_t x) {
  x ^= x >> 12;
  x ^= x << 25;
  x ^= x >> 27;
  return x;
}

// The transition is linear over GF(2); column i of its matrix is the image
// of basis state 1<<i. Applying a matrix is then an XOR-fold of the columns
// selected by the state's set bits.
uint64_t xs_apply(const uint64_t* col, uint64_t x) {
  uint64_t y = 0;
  while (x) {
    y ^= col[__builtin_ctzll(x)];
    x &= x - 1;
  }
  return y;
}

// Advance by n transitions in O(log n) square-and-multiply — bit-identical
// to n sequential xs_step calls (the Python side cross-checks), but a
// resume at batch 1e8 costs ~64 squarings instead of stalling dl_open for
// minutes inside an O(n) loop.
uint64_t xs_jump(uint64_t state, uint64_t n) {
  uint64_t m[64], sq[64];
  for (int i = 0; i < 64; ++i) m[i] = xs_step(1ULL << i);
  while (n) {
    if (n & 1) state = xs_apply(m, state);
    for (int i = 0; i < 64; ++i) sq[i] = xs_apply(m, m[i]);
    std::memcpy(m, sq, sizeof m);
    n >>= 1;
  }
  return state;
}

}  // namespace

extern "C" {

// Must match loader.py _ABI_VERSION: the Python side refuses (and
// rebuilds) a library whose ABI does not match, so a stale cached .so
// can never silently drop a newly added argument.
int dl_abi_version() { return 2; }

void* dl_open(const char* path, int batch, int seq, uint64_t seed,
              int prefetch, uint64_t start_batch) {
  if (batch <= 0 || seq <= 0 || prefetch <= 0) return nullptr;
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (::fstat(fd, &st) != 0 || st.st_size < static_cast<off_t>(seq) * 4) {
    ::close(fd);
    return nullptr;
  }
  void* map = ::mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (map == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  auto* h = new Loader();
  h->tokens = static_cast<const uint32_t*>(map);
  h->n_tokens = static_cast<size_t>(st.st_size) / 4;
  h->map_len = st.st_size;
  h->fd = fd;
  h->batch = batch;
  h->seq = seq;
  h->rng = seed ? seed : 0x9E3779B97F4A7C15ULL;
  // Resume skip: O(log n) jump over the skipped draws. The Python caller
  // rejects negative start_batch before it can wrap through c_uint64.
  h->rng = xs_jump(h->rng, start_batch * static_cast<uint64_t>(batch));
  h->capacity = prefetch;
  h->producer = std::thread([h] { h->produce(); });
  return h;
}

long dl_num_tokens(void* handle) {
  return handle ? static_cast<long>(static_cast<Loader*>(handle)->n_tokens) : -1;
}

int dl_next(void* handle, int32_t* out) {
  if (!handle || !out) return 1;
  auto* h = static_cast<Loader*>(handle);
  std::vector<int32_t> buf;
  {
    std::unique_lock<std::mutex> lock(h->mu);
    h->cv_empty.wait(lock, [h] { return !h->queue.empty() || h->stop; });
    if (h->queue.empty()) return 1;
    buf = std::move(h->queue.front());
    h->queue.pop_front();
    h->cv_full.notify_one();
  }
  std::memcpy(out, buf.data(), buf.size() * sizeof(int32_t));
  return 0;
}

void dl_close(void* handle) {
  if (!handle) return;
  auto* h = static_cast<Loader*>(handle);
  {
    std::lock_guard<std::mutex> lock(h->mu);
    h->stop = true;
  }
  h->cv_full.notify_all();
  h->cv_empty.notify_all();
  if (h->producer.joinable()) h->producer.join();
  ::munmap(const_cast<uint32_t*>(h->tokens), h->map_len);
  ::close(h->fd);
  delete h;
}

}  // extern "C"
