// Concurrent slice-activity prober.
//
// The culler must observe Jupyter activity on EVERY host of a TPU slice
// before each idleness verdict (reference culling_controller.go:244-322
// probes one pod; this framework generalizes to N hosts — SURVEY.md §7
// step 5). Sequential probing makes the reconcile latency O(hosts ×
// timeout) — a v5p-512 slice with 64 hosts and a 5s timeout could wedge a
// reconcile for minutes when hosts are partitioned. This prober issues all
// HTTP GETs concurrently from a thread pool, so wall time is one timeout
// regardless of slice size.
//
// Plain HTTP/1.0 over raw sockets: in-cluster pod traffic, same as the
// reference culler's http.Get. No TLS by design (NetworkPolicies scope who
// may reach 8888).
//
// C ABI (ctypes, kubeflow_tpu/controller/prober.py):
//   pr_probe(urls, n, timeout_ms, bodies, body_cap, statuses) -> 0
//     urls:      array of n C strings "http://host:port/path"
//     bodies:    n * body_cap char buffer; body i at offset i*body_cap,
//                NUL-terminated, truncated to body_cap-1
//     statuses:  per-URL HTTP status, or -1 connect/timeout, -2 bad URL
//
// Determinism/safety: no globals, no signals; each probe owns its socket.

#include <atomic>
#include <cerrno>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <memory>
#include <mutex>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include <netdb.h>
#include <poll.h>
#include <sys/socket.h>
#include <sys/types.h>
#include <unistd.h>
#include <fcntl.h>

namespace {

struct Url {
  std::string host;
  std::string port;
  std::string path;
};

bool parse_url(const char* raw, Url* out) {
  std::string s(raw);
  const std::string scheme = "http://";
  if (s.rfind(scheme, 0) != 0) return false;
  s = s.substr(scheme.size());
  size_t slash = s.find('/');
  std::string hostport = slash == std::string::npos ? s : s.substr(0, slash);
  out->path = slash == std::string::npos ? "/" : s.substr(slash);
  size_t colon = hostport.rfind(':');
  if (colon == std::string::npos) {
    out->host = hostport;
    out->port = "80";
  } else {
    out->host = hostport.substr(0, colon);
    out->port = hostport.substr(colon + 1);
  }
  return !out->host.empty();
}

int64_t now_ms() {
  return std::chrono::duration_cast<std::chrono::milliseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

// Milliseconds left before the absolute deadline; <= 0 means expired.
int remaining_ms(int64_t deadline) {
  int64_t left = deadline - now_ms();
  if (left <= 0) return 0;
  if (left > INT32_MAX) left = INT32_MAX;
  return static_cast<int>(left);
}

// getaddrinfo has no timeout parameter, and a hung resolver (kube-dns
// partition — precisely when the culler probes a partitioned slice) would
// otherwise wedge a worker thread past any deadline. Run it in a helper
// thread and wait with a deadline; on timeout the helper is detached and
// cleans up after itself whenever libc eventually returns.
struct ResolveState {
  std::mutex mu;
  std::condition_variable cv;
  bool done = false;
  bool abandoned = false;
  addrinfo* res = nullptr;
};

addrinfo* resolve_with_deadline(const std::string& host,
                                const std::string& port, int64_t deadline) {
  auto st = std::make_shared<ResolveState>();
  std::thread worker([st, host, port]() {
    addrinfo hints{};
    hints.ai_family = AF_UNSPEC;
    hints.ai_socktype = SOCK_STREAM;
    addrinfo* res = nullptr;
    int rc = getaddrinfo(host.c_str(), port.c_str(), &hints, &res);
    std::lock_guard<std::mutex> lock(st->mu);
    st->done = true;
    if (st->abandoned) {
      // Probe gave up; nobody will read res.
      if (rc == 0 && res) freeaddrinfo(res);
    } else {
      st->res = rc == 0 ? res : nullptr;
    }
    st->cv.notify_one();
  });
  std::unique_lock<std::mutex> lock(st->mu);
  // now_ms() is steady_clock-based, so the deadline converts directly.
  auto abs_deadline = std::chrono::steady_clock::time_point(
      std::chrono::duration_cast<std::chrono::steady_clock::duration>(
          std::chrono::milliseconds(deadline)));
  bool finished =
      st->cv.wait_until(lock, abs_deadline, [&] { return st->done; });
  if (finished) {
    worker.join();
    return st->res;
  }
  st->abandoned = true;
  lock.unlock();
  worker.detach();  // bounded leak: one blocked resolver thread, self-freeing
  return nullptr;
}

// Connect before the absolute deadline; returns fd or -1. The deadline is
// shared across resolution AND every resolved address — a probe never gets
// more than its overall budget.
int connect_deadline(const Url& u, int64_t deadline) {
  addrinfo* res = resolve_with_deadline(u.host, u.port, deadline);
  if (!res) return -1;
  int fd = -1;
  for (addrinfo* ai = res; ai; ai = ai->ai_next) {
    int left = remaining_ms(deadline);
    if (left <= 0) break;
    fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    fcntl(fd, F_SETFL, fcntl(fd, F_GETFL) | O_NONBLOCK);
    int rc = connect(fd, ai->ai_addr, ai->ai_addrlen);
    if (rc == 0) break;
    if (errno == EINPROGRESS) {
      pollfd pfd{fd, POLLOUT, 0};
      if (poll(&pfd, 1, left) == 1 && (pfd.revents & POLLOUT)) {
        int err = 0;
        socklen_t len = sizeof(err);
        getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &len);
        if (err == 0) break;
      }
    }
    close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  return fd;
}

// Read until EOF or the absolute deadline; appends to buf. Every poll gets
// only the REMAINING budget, so a host that trickles bytes cannot extend
// the probe past timeout_ms (the per-poll-restart pathology).
bool read_all(int fd, int64_t deadline, std::string* buf) {
  char chunk[4096];
  for (;;) {
    int left = remaining_ms(deadline);
    if (left <= 0) return false;
    pollfd pfd{fd, POLLIN, 0};
    int pr = poll(&pfd, 1, left);
    if (pr <= 0) return false;  // timeout or error
    ssize_t n = recv(fd, chunk, sizeof(chunk), 0);
    if (n < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) continue;
      return false;
    }
    if (n == 0) return true;  // orderly EOF
    buf->append(chunk, static_cast<size_t>(n));
    if (buf->size() > (16u << 20)) return true;  // 16 MiB safety cap
  }
}

// One probe: returns HTTP status (>0), -1 network failure, -2 bad URL.
// timeout_ms is the OVERALL budget for resolve+connect+send+read.
int probe_one(const char* raw_url, int timeout_ms, char* body_out,
              int body_cap) {
  if (body_cap > 0) body_out[0] = '\0';
  Url u;
  if (!parse_url(raw_url, &u)) return -2;
  const int64_t deadline = now_ms() + timeout_ms;
  int fd = connect_deadline(u, deadline);
  if (fd < 0) return -1;

  std::string req = "GET " + u.path + " HTTP/1.0\r\nHost: " + u.host +
                    "\r\nConnection: close\r\n\r\n";
  size_t sent = 0;
  while (sent < req.size()) {
    int left = remaining_ms(deadline);
    if (left <= 0) { close(fd); return -1; }
    pollfd pfd{fd, POLLOUT, 0};
    if (poll(&pfd, 1, left) <= 0) { close(fd); return -1; }
    ssize_t n = send(fd, req.data() + sent, req.size() - sent, MSG_NOSIGNAL);
    if (n < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK) continue;
      close(fd);
      return -1;
    }
    sent += static_cast<size_t>(n);
  }

  std::string resp;
  bool ok = read_all(fd, deadline, &resp);
  close(fd);
  if (!ok && resp.empty()) return -1;

  // "HTTP/1.x NNN ..."
  int status = -1;
  size_t sp = resp.find(' ');
  if (sp != std::string::npos && resp.size() >= sp + 4)
    status = std::atoi(resp.c_str() + sp + 1);
  if (status <= 0) return -1;

  size_t body_at = resp.find("\r\n\r\n");
  if (body_at != std::string::npos && body_cap > 0) {
    size_t n = resp.size() - (body_at + 4);
    if (n > static_cast<size_t>(body_cap - 1)) n = body_cap - 1;
    std::memcpy(body_out, resp.data() + body_at + 4, n);
    body_out[n] = '\0';
  }
  return status;
}

}  // namespace

extern "C" {

int pr_probe(const char** urls, int n, int timeout_ms, char* bodies,
             int body_cap, int* statuses) {
  if (n <= 0) return 0;
  if (!urls || !bodies || !statuses || body_cap <= 0 || timeout_ms <= 0)
    return -1;
  // One thread per URL, capped: slice host counts are ≤ 64 for v5p-512 and
  // each host contributes 2 URLs (kernels+terminals), so 128 covers the
  // largest slice in ONE wave — the "one timeout regardless of slice size"
  // guarantee. Probes are poll-bound, so a flat pool beats an event loop
  // on simplicity.
  const int max_threads = 128;
  std::vector<std::thread> pool;
  std::atomic<int> next{0};
  int workers = n < max_threads ? n : max_threads;
  for (int w = 0; w < workers; ++w) {
    pool.emplace_back([&]() {
      for (;;) {
        int i = next.fetch_add(1);
        if (i >= n) return;
        statuses[i] = probe_one(urls[i], timeout_ms,
                                bodies + static_cast<size_t>(i) * body_cap,
                                body_cap);
      }
    });
  }
  for (auto& t : pool) t.join();
  return 0;
}

}  // extern "C"
