"""Multislice (MEGASCALE) notebooks: N slices, one notebook.

Covers spec generation (per-slice StatefulSets + env), the end-to-end
lifecycle on the fake control plane, runtime bootstrap id math, culler
fan-out across slices, and the validating-webhook immutability rule.
"""

from __future__ import annotations

import pytest

from kubeflow_tpu.api import annotations as ann
from kubeflow_tpu.api.notebook import Notebook, TPUSpec, new_notebook
from kubeflow_tpu.controller.culling import CullerConfig, CullingReconciler
from kubeflow_tpu.controller.notebook import (
    ControllerConfig,
    generate_headless_service,
    generate_statefulset,
    slice_sts_names,
)
from kubeflow_tpu.k8s.errors import WebhookDeniedError
from kubeflow_tpu.runtime.bootstrap import runtime_from_env
from tests.harness import make_env


def _ms_notebook(name="ms", namespace="ns", slices=2, accelerator="v5e",
                 topology="4x4", **kw):
    return new_notebook(
        name, namespace, image="jax-notebook:latest",
        tpu=TPUSpec(accelerator=accelerator, topology=topology,
                    slice_count=slices),
        **kw,
    )


def _env_of(sts: dict, name: str) -> dict:
    for c in sts["spec"]["template"]["spec"]["containers"]:
        if c.get("name") == name:
            return {e["name"]: e.get("value") for e in c.get("env", [])}
    raise AssertionError("primary container missing")


class TestSpecGeneration:
    def test_one_sts_per_slice_with_distinct_selectors(self):
        nb = Notebook(_ms_notebook(slices=3))
        topo = nb.tpu.slice_topology()
        names, selectors = [], []
        for j in range(3):
            sts = generate_statefulset(
                nb, topo, ControllerConfig(), slice_id=j, slice_count=3
            )
            names.append(sts["metadata"]["name"])
            selectors.append(sts["spec"]["selector"]["matchLabels"]["statefulset"])
            assert sts["spec"]["replicas"] == topo.hosts
            assert sts["spec"]["podManagementPolicy"] == "Parallel"
        assert names == ["ms", "ms-s1", "ms-s2"]
        # Selectors must differ or the StatefulSets adopt each other's pods.
        assert selectors == names

    def test_megascale_env_varies_per_slice(self):
        nb = Notebook(_ms_notebook(slices=2))
        topo = nb.tpu.slice_topology()
        envs = [
            _env_of(
                generate_statefulset(
                    nb, topo, ControllerConfig(), slice_id=j, slice_count=2
                ),
                "ms",
            )
            for j in range(2)
        ]
        assert envs[0]["MEGASCALE_SLICE_ID"] == "0"
        assert envs[1]["MEGASCALE_SLICE_ID"] == "1"
        for env in envs:
            assert env["MEGASCALE_NUM_SLICES"] == "2"
            assert env["TPU_HOSTS_PER_SLICE"] == str(topo.hosts)
            assert env["JAX_NUM_PROCESSES"] == str(2 * topo.hosts)
            # One coordinator for both planes: slice 0, host 0.
            assert env["MEGASCALE_COORDINATOR_ADDRESS"].startswith("ms-0.ms-hosts.")
            assert env["JAX_COORDINATOR_ADDRESS"].startswith("ms-0.ms-hosts.")
        # Hostnames are slice-local (libtpu's view is per-slice).
        assert envs[0]["TPU_WORKER_HOSTNAMES"].split(",")[0].startswith("ms-0.")
        assert envs[1]["TPU_WORKER_HOSTNAMES"].split(",")[0].startswith("ms-s1-0.")

    def test_single_slice_has_no_megascale_env(self):
        nb = Notebook(_ms_notebook(slices=1))
        topo = nb.tpu.slice_topology()
        sts = generate_statefulset(nb, topo, ControllerConfig())
        env = _env_of(sts, "ms")
        assert "MEGASCALE_SLICE_ID" not in env
        assert sts["metadata"]["name"] == "ms"

    def test_headless_service_spans_all_slices(self):
        nb = Notebook(_ms_notebook(slices=2))
        topo = nb.tpu.slice_topology()
        svc = generate_headless_service(nb, topo)
        # Notebook-name label selects every slice's pods into one subdomain.
        assert svc["spec"]["selector"] == {"notebook-name": "ms"}

    def test_slice_sts_names(self):
        assert slice_sts_names("nb", 1) == ["nb"]
        assert slice_sts_names("nb", 3) == ["nb", "nb-s1", "nb-s2"]


class TestLifecycle:
    def _make_env(self):
        # One pool big enough for 2 slices x 4 hosts of v5e 4x4.
        return make_env(
            webhooks=True,
            platform=True,
            node_pools=(("tpu-v5-lite-podslice", "4x4", 8, 4),),
        )

    def test_multislice_comes_up_and_reports_status(self):
        env = self._make_env()
        env.cluster.create(_ms_notebook(name="ms", namespace="u", slices=2))
        env.manager.run_until_idle()
        pods = env.cluster.list("Pod", "u")
        names = sorted(p["metadata"]["name"] for p in pods)
        assert names == [
            "ms-0", "ms-1", "ms-2", "ms-3",
            "ms-s1-0", "ms-s1-1", "ms-s1-2", "ms-s1-3",
        ]
        nb = env.cluster.get("Notebook", "ms", "u")
        tpu = nb["status"]["tpu"]
        assert tpu["hosts"] == 8
        assert tpu["readyHosts"] == 8
        assert tpu["slices"] == 2
        assert tpu["hostsPerSlice"] == 4
        assert tpu["sliceHealth"] == "Healthy"

    def test_stop_scales_every_slice_to_zero(self):
        env = self._make_env()
        env.cluster.create(_ms_notebook(name="ms", namespace="u", slices=2))
        env.manager.run_until_idle()

        nb = env.cluster.get("Notebook", "ms", "u")
        nb["metadata"].setdefault("annotations", {})[ann.STOP] = (
            "2026-07-29T00:00:00Z"
        )
        env.cluster.update(nb)
        env.manager.run_until_idle()

        for sts in env.cluster.list("StatefulSet", "u"):
            assert sts["spec"]["replicas"] == 0
        assert env.cluster.list("Pod", "u") == []

    def test_slice_count_shrink_prunes_extra_sts(self):
        env = self._make_env()
        env.cluster.create(_ms_notebook(name="ms", namespace="u", slices=2))
        env.manager.run_until_idle()
        assert len(env.cluster.list("StatefulSet", "u")) == 2

        nb = env.cluster.get("Notebook", "ms", "u")
        nb["metadata"].setdefault("annotations", {})[ann.STOP] = (
            "2026-07-29T00:00:00Z"
        )
        env.cluster.update(nb)
        env.manager.run_until_idle()
        nb = env.cluster.get("Notebook", "ms", "u")
        nb["spec"]["tpu"]["sliceCount"] = 1
        env.cluster.update(nb)
        env.manager.run_until_idle()

        stses = env.cluster.list("StatefulSet", "u")
        assert [s["metadata"]["name"] for s in stses] == ["ms"]

    def test_prune_refuses_uncontrolled_statefulset(self):
        """A user-created STS that merely carries the notebook-name label
        must survive pruning (same no-adopt posture as reconcile)."""
        env = self._make_env()
        env.cluster.create(_ms_notebook(name="ms", namespace="u", slices=1))
        # Foreign STS labeled like slice 1 of "ms" but owned by nobody.
        env.cluster.create({
            "apiVersion": "apps/v1",
            "kind": "StatefulSet",
            "metadata": {
                "name": "ms-s9",
                "namespace": "u",
                "labels": {ann.NOTEBOOK_NAME_LABEL: "ms"},
            },
            "spec": {"replicas": 1, "template": {"spec": {"containers": []}}},
        })
        env.manager.run_until_idle()

        names = {s["metadata"]["name"]
                 for s in env.cluster.list("StatefulSet", "u")}
        assert "ms-s9" in names  # not pruned
        events = [e for e in env.cluster.list("Event", "u")
                  if e.get("reason") == "StatefulSetConflict"]
        assert events

    def test_restart_deletes_pods_of_every_slice(self):
        env = self._make_env()
        env.cluster.create(_ms_notebook(name="ms", namespace="u", slices=2))
        env.manager.run_until_idle()
        before = {p["metadata"]["uid"] for p in env.cluster.list("Pod", "u")}

        nb = env.cluster.get("Notebook", "ms", "u")
        nb["metadata"].setdefault("annotations", {})[ann.RESTART] = "true"
        env.cluster.update(nb)
        env.manager.run_until_idle()

        after = {p["metadata"]["uid"] for p in env.cluster.list("Pod", "u")}
        assert len(after) == 8
        assert before.isdisjoint(after)  # every pod replaced


class TestNameCollisions:
    def test_long_name_plus_slice_suffix_falls_back(self):
        from kubeflow_tpu.controller.notebook import slice_sts_name

        env = make_env(node_pools=(("tpu-v5-lite-podslice", "4x4", 8, 4),))
        # 52 chars fits bare, but "-s1" pushes slice 1 over the limit:
        # slice 0 keeps the bare name, slice 1 gets the hashed fallback.
        name = "n" * 52
        env.cluster.create(_ms_notebook(name=name, namespace="u", slices=2))
        env.manager.run_until_idle()

        names = {s["metadata"]["name"] for s in env.cluster.list("StatefulSet", "u")}
        s1 = slice_sts_name(name, 1)
        assert names == {name, s1}
        assert s1.endswith("-s1") and len(s1) <= 52 and s1 != f"{name}-s1"
        events = [
            e for e in env.cluster.list("Event", "u")
            if e.get("reason") == "LongNameFallback"
        ]
        assert events
        # Both slices actually scheduled (8 pods).
        assert len(env.cluster.list("Pod", "u")) == 8

    def test_single_slice_52_char_name_still_allowed(self):
        env = make_env(node_pools=(("tpu-v5-lite-podslice", "4x4", 8, 4),))
        name = "n" * 52
        env.cluster.create(_ms_notebook(name=name, namespace="u", slices=1))
        env.manager.run_until_idle()
        assert len(env.cluster.list("StatefulSet", "u")) == 1

    def test_never_adopts_sibling_notebooks_sts(self):
        """Notebook 'foo' (sliceCount 2) must not seize the STS of a
        notebook literally named 'foo-s1'."""
        env = make_env(node_pools=(("tpu-v5-lite-podslice", "4x4", 16, 4),))
        env.cluster.create(_ms_notebook(name="foo-s1", namespace="u", slices=1))
        env.manager.run_until_idle()
        sibling_sts = env.cluster.get("StatefulSet", "foo-s1", "u")
        sibling_uid = sibling_sts["metadata"]["ownerReferences"][0]["uid"]

        env.cluster.create(_ms_notebook(name="foo", namespace="u", slices=2))
        env.manager.run_until_idle()

        sts = env.cluster.get("StatefulSet", "foo-s1", "u")
        assert sts["metadata"]["ownerReferences"][0]["uid"] == sibling_uid
        env_vars = {
            e["name"]
            for c in sts["spec"]["template"]["spec"]["containers"]
            for e in c.get("env", [])
        }
        assert "MEGASCALE_SLICE_ID" not in env_vars  # spec never overwritten
        conflicts = [
            e for e in env.cluster.list("Event", "u")
            if e.get("reason") == "StatefulSetConflict"
        ]
        assert conflicts


class TestPreemptionRecovery:
    def test_slice1_host_preemption_recovers_whole_notebook(self):
        env = make_env(
            webhooks=True, platform=True,
            node_pools=(("tpu-v5-lite-podslice", "4x4", 8, 4),),
        )
        env.cluster.create(_ms_notebook(name="ms", namespace="u", slices=2))
        env.manager.run_until_idle()

        # Preempt a host in slice 1.
        victim = env.cluster.get("Pod", "ms-s1-2", "u")
        victim["status"]["phase"] = "Failed"
        victim["status"]["reason"] = "Preempted"
        env.cluster.update_status(victim)
        env.manager.run_until_idle()

        # Recovered: 8 Running pods again, interruption cleared, both
        # events emitted.
        pods = env.cluster.list("Pod", "u")
        assert len(pods) == 8
        assert all(p["status"]["phase"] == "Running" for p in pods)
        nb = env.cluster.get("Notebook", "ms", "u")
        assert "tpu-slice-interrupted" not in str(
            nb["metadata"].get("annotations", {})
        )
        assert nb["status"]["tpu"]["sliceHealth"] == "Healthy"
        reasons = {e.get("reason") for e in env.cluster.list("Event", "u")}
        assert {"SliceInterrupted", "SliceRecovered"} <= reasons


class TestValidation:
    def test_slice_count_change_denied_while_running(self):
        env = make_env(
            webhooks=True, platform=True,
            node_pools=(("tpu-v5-lite-podslice", "4x4", 8, 4),),
        )
        env.cluster.create(_ms_notebook(name="ms", namespace="u", slices=2))
        env.manager.run_until_idle()
        nb = env.cluster.get("Notebook", "ms", "u")
        nb["spec"]["tpu"]["sliceCount"] = 4
        with pytest.raises(WebhookDeniedError, match="cannot change"):
            env.cluster.update(nb)

    def test_zero_slice_count_denied_at_admission(self):
        env = make_env(webhooks=True)
        with pytest.raises(WebhookDeniedError, match="sliceCount"):
            env.cluster.create(_ms_notebook(name="ms", namespace="u", slices=0))


class TestRuntimeBootstrap:
    def test_process_id_math(self):
        rt = runtime_from_env(
            {
                "TPU_WORKER_ID": "2",
                "TPU_HOSTS_PER_SLICE": "4",
                "MEGASCALE_SLICE_ID": "1",
                "MEGASCALE_NUM_SLICES": "2",
                "JAX_NUM_PROCESSES": "8",
                "TPU_WORKER_HOSTNAMES": "a,b,c,d",
                "JAX_COORDINATOR_ADDRESS": "ms-0.ms-hosts.u.svc.cluster.local:8476",
            }
        )
        assert rt.worker_id == 2  # slice-local, what libtpu sees
        assert rt.process_id == 6  # global: 1*4 + 2
        assert rt.num_workers == 8
        assert rt.num_slices == 2
        assert not rt.is_coordinator

    def test_slice0_host0_is_coordinator(self):
        rt = runtime_from_env(
            {
                "TPU_WORKER_ID": "0",
                "TPU_HOSTS_PER_SLICE": "4",
                "MEGASCALE_SLICE_ID": "0",
                "MEGASCALE_NUM_SLICES": "2",
            }
        )
        assert rt.is_coordinator and rt.process_id == 0

    def test_single_slice_unchanged(self):
        rt = runtime_from_env(
            {
                "TPU_WORKER_ID": "1",
                "TPU_WORKER_HOSTNAMES": "a,b,c,d",
                "JAX_NUM_PROCESSES": "4",
            }
        )
        assert rt.process_id == rt.worker_id == 1
        assert rt.num_slices == 1


class TestCullerFanout:
    def test_host_dns_covers_every_slice(self):
        env = make_env(
            culling=True,
            node_pools=(("tpu-v5-lite-podslice", "4x4", 8, 4),),
        )
        culler = env.culler
        nb = Notebook(_ms_notebook(name="ms", namespace="u", slices=2))
        hosts = culler._host_dns(nb)
        assert len(hosts) == 8
        assert hosts[0].startswith("ms-0.ms-hosts.u.svc.")
        assert hosts[4].startswith("ms-s1-0.ms-hosts.u.svc.")
