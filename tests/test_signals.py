"""Fleet telemetry plane (observability/signals.py + slo.py): aligned
window rings under a fake clock — counter rates, gauge bad-windows,
streaming-quantile histograms with per-window reservoir overwrite — the
SLO burn-rate engine (multi-window alerting, hysteresis latch,
min-events guard, metric + span emission), bounded tenant buckets, the
stall→profile capture hook (flight.StallProfiler with an injected
trace_fn), FleetTelemetry's /stats delta ingestion, the gateway's
/debug/signals + /debug/slo surfaces over fake replicas, and one real
2-replica fleet pass asserting the relay-measured TTFT p95 agrees with
the client-measured p95.
"""

from __future__ import annotations

import contextlib
import http.client
import json
import math
import pathlib
import threading
import time
import urllib.request

import pytest

from kubeflow_tpu.metrics.metrics import Metrics
from kubeflow_tpu.observability.flight import (
    FlightRecorder,
    StallProfiler,
    stall_profiler_from_env,
)
from kubeflow_tpu.observability.signals import (
    TENANT_OTHER,
    FleetTelemetry,
    SignalHub,
    SignalsConfig,
    TenantBuckets,
    signals_from_env,
)
from kubeflow_tpu.observability.slo import (
    Objective,
    SLOEngine,
    default_objectives,
    slo_from_env,
)
from kubeflow_tpu.observability.tracing import (
    InMemoryExporter,
    TracerProvider,
    set_tracer_provider,
)
from kubeflow_tpu.webhook import tpu_env


class _Clock:
    """Mutable fake monotonic clock."""

    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


def _wait_for(fn, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = fn()
        if out:
            return out
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {fn}")


# -- tenant buckets ----------------------------------------------------------


class TestTenantBuckets:
    def test_first_k_keep_their_name_rest_fold_to_other(self):
        tb = TenantBuckets(top_k=2)
        assert tb.bucket("alice") == "alice"
        assert tb.bucket("bob") == "bob"
        assert tb.bucket("carol") == TENANT_OTHER
        assert tb.bucket("dave") == TENANT_OTHER

    def test_assignment_is_stable_never_relabels(self):
        tb = TenantBuckets(top_k=1)
        assert tb.bucket("a") == "a"
        assert tb.bucket("b") == TENANT_OTHER
        # Re-lookups return the original assignment, even for 'other'.
        assert tb.bucket("a") == "a"
        assert tb.bucket("b") == TENANT_OTHER
        assert tb.buckets() == ["a", TENANT_OTHER]

    def test_top_k_validation(self):
        with pytest.raises(ValueError):
            TenantBuckets(top_k=0)


# -- counter series ----------------------------------------------------------


class TestCounterWindows:
    def test_window_alignment_is_epoch_based(self):
        """Events straddling a 10s boundary land in different windows:
        a 10s horizon at t=10.1 sees only the second event."""
        hub = SignalHub(window_s=10.0, windows=12, clock=_Clock())
        hub.inc("req", now=9.9)
        hub.inc("req", now=10.1)
        assert hub.counter_sum("req", over_s=10.0, now=10.1) == 1.0
        assert hub.counter_sum("req", over_s=20.0, now=10.1) == 2.0

    def test_rate_denominator_is_the_requested_span(self):
        """Idle windows count as genuinely idle, not unknown: 1 event
        over a 60s horizon is 1/60 events per second."""
        hub = SignalHub(window_s=10.0, windows=12, clock=_Clock())
        hub.inc("req", now=100.0)
        assert hub.rate("req", over_s=60.0, now=100.0) == pytest.approx(
            1.0 / 60.0
        )

    def test_rate_span_clamps_to_ring_reach(self):
        hub = SignalHub(window_s=10.0, windows=12, clock=_Clock())
        hub.inc("req", value=6.0, now=60.0)
        # The ring only covers 120s; an enormous horizon can't dilute.
        assert hub.rate("req", over_s=1e9, now=60.0) == pytest.approx(
            6.0 / 120.0
        )

    def test_events_expire_with_their_windows(self):
        hub = SignalHub(window_s=10.0, windows=12, clock=_Clock())
        hub.inc("req", now=5.0)
        assert hub.counter_sum("req", now=5.0) == 1.0
        # 130s later the event's window is outside the 120s horizon.
        assert hub.counter_sum("req", now=135.0) == 0.0
        # The lifetime total survives ring expiry.
        assert hub.counter_total("req") == 1.0

    def test_children_are_independent_series(self):
        hub = SignalHub(window_s=10.0, windows=12, clock=_Clock())
        hub.inc("req", now=5.0)
        hub.inc("req", child="a", now=5.0)
        hub.inc("req", child="a", now=5.0)
        assert hub.counter_sum("req", now=5.0) == 1.0
        assert hub.counter_sum("req", child="a", now=5.0) == 2.0
        assert hub.counter_children("req") == ["a"]

    def test_unknown_series_query_defaults(self):
        hub = SignalHub(window_s=10.0, windows=12, clock=_Clock())
        assert hub.rate("nope", now=0.0) == 0.0
        assert hub.counter_sum("nope", now=0.0) == 0.0
        assert hub.quantile("nope", 0.95, now=0.0) is None
        assert hub.gauge_last("nope") is None
        assert hub.fraction_over("nope", 1.0, now=0.0) == (0.0, 0)
        assert hub.event_count("nope", now=0.0) == 0

    def test_hub_validation(self):
        with pytest.raises(ValueError):
            SignalHub(window_s=0.0)
        with pytest.raises(ValueError):
            SignalHub(windows=1)
        with pytest.raises(ValueError):
            SignalHub(samples_per_window=0)


# -- gauge series ------------------------------------------------------------


class TestGaugeWindows:
    def test_windows_over_counts_bad_and_observed(self):
        hub = SignalHub(window_s=10.0, windows=12, clock=_Clock())
        hub.set_gauge("depth", 1.0, now=5.0)    # window 0: bad
        hub.set_gauge("depth", 0.1, now=15.0)   # window 1: fine
        bad, total = hub.gauge_windows_over("depth", 0.5, now=15.0)
        assert (bad, total) == (1, 2)

    def test_last_write_in_a_window_wins(self):
        hub = SignalHub(window_s=10.0, windows=12, clock=_Clock())
        hub.set_gauge("depth", 9.0, now=5.0)
        hub.set_gauge("depth", 0.1, now=6.0)  # same window, overwrites
        bad, total = hub.gauge_windows_over("depth", 0.5, now=6.0)
        assert (bad, total) == (0, 1)
        assert hub.gauge_last("depth") == 0.1

    def test_aggregates_across_children(self):
        """A fleet window is bad when ANY replica exceeded the line."""
        hub = SignalHub(window_s=10.0, windows=12, clock=_Clock())
        hub.set_gauge("qwait", 1.0, child="ep1", now=5.0)
        hub.set_gauge("qwait", 0.1, child="ep2", now=5.0)
        bad, total = hub.gauge_windows_over("qwait", 0.5, now=5.0)
        assert (bad, total) == (1, 2)
        assert hub.gauge_children("qwait") == {"ep1": 1.0, "ep2": 0.1}


# -- histogram series --------------------------------------------------------


class TestHistogramQuantiles:
    def test_nearest_rank_is_exact_at_small_n(self):
        hub = SignalHub(window_s=10.0, windows=12, clock=_Clock())
        for v in range(1, 101):
            hub.observe("lat", float(v), now=5.0)
        assert hub.quantile("lat", 0.50, now=5.0) == 50.0
        assert hub.quantile("lat", 0.95, now=5.0) == 95.0
        assert hub.quantile("lat", 0.99, now=5.0) == 99.0
        assert hub.quantile("lat", 1.00, now=5.0) == 100.0

    def test_single_sample_answers_every_quantile(self):
        hub = SignalHub(window_s=10.0, windows=12, clock=_Clock())
        hub.observe("lat", 0.42, now=5.0)
        for q in (0.01, 0.5, 0.95, 1.0):
            assert hub.quantile("lat", q, now=5.0) == 0.42

    def test_reservoir_overwrites_oldest_past_the_cap(self):
        """Past samples_per_window the window keeps the most recent
        samples (ring overwrite), while events() reports true counts."""
        hub = SignalHub(
            window_s=10.0, windows=12, clock=_Clock(), samples_per_window=3
        )
        for v in (1.0, 2.0, 3.0, 4.0, 5.0):
            hub.observe("lat", v, now=5.0)
        # 4.0 overwrote 1.0, 5.0 overwrote 2.0: reservoir = {3, 4, 5}.
        assert hub.quantile("lat", 1.0, now=5.0) == 5.0
        assert hub.quantile("lat", 0.01, now=5.0) == 3.0
        assert hub.event_count("lat", now=5.0) == 5

    def test_merges_across_windows_and_expires(self):
        hub = SignalHub(window_s=10.0, windows=12, clock=_Clock())
        hub.observe("lat", 1.0, now=5.0)
        hub.observe("lat", 3.0, now=15.0)
        assert hub.quantile("lat", 1.0, over_s=20.0, now=15.0) == 3.0
        assert hub.quantile("lat", 0.01, over_s=20.0, now=15.0) == 1.0
        # A 10s horizon at t=15 only covers the second window.
        assert hub.quantile("lat", 0.01, over_s=10.0, now=15.0) == 3.0
        # Beyond the ring span, everything is gone.
        assert hub.quantile("lat", 0.5, now=200.0) is None

    def test_fraction_over(self):
        hub = SignalHub(window_s=10.0, windows=12, clock=_Clock())
        for v in (0.1, 0.2, 0.9, 1.1):
            hub.observe("lat", v, now=5.0)
        frac, held = hub.fraction_over("lat", 0.5, now=5.0)
        assert frac == pytest.approx(0.5)
        assert held == 4


# -- SLO objectives + burn-rate engine ---------------------------------------


def _slo_hub():
    """A hub whose ring covers the engine's default 30m slow window."""
    return SignalHub(window_s=10.0, windows=180, clock=_Clock())


class TestObjectiveValidation:
    def test_bad_kind(self):
        with pytest.raises(ValueError):
            Objective("x", "histogram", "lat")

    def test_ratio_needs_total_signal(self):
        with pytest.raises(ValueError):
            Objective("x", "ratio", "bad")

    def test_latency_needs_positive_threshold(self):
        with pytest.raises(ValueError):
            Objective("x", "latency", "lat", threshold=0.0)

    def test_budget_bounds(self):
        with pytest.raises(ValueError):
            Objective("x", "ratio", "bad", total_signal="all", budget=0.0)
        with pytest.raises(ValueError):
            Objective("x", "ratio", "bad", total_signal="all", budget=1.5)

    def test_engine_validation(self):
        hub = _slo_hub()
        obj = Objective("x", "latency", "lat", threshold=0.5)
        with pytest.raises(ValueError):
            SLOEngine(hub, (obj, obj))  # duplicate names
        with pytest.raises(ValueError):
            SLOEngine(hub, (obj,), fast_windows=(300.0, 60.0))
        with pytest.raises(ValueError):
            SLOEngine(hub, (obj,), fast_windows=(60.0, 300.0),
                      slow_window=200.0)
        with pytest.raises(ValueError):
            SLOEngine(hub, (obj,), clear_factor=1.0)

    def test_default_objectives_shape(self):
        objs = {o.name: o for o in default_objectives(ttft_p95_s=0.25)}
        assert set(objs) == {
            "ttft_p95", "inter_token_p95", "error_ratio", "queue_wait_p95"
        }
        assert objs["ttft_p95"].threshold == 0.25
        assert objs["error_ratio"].total_signal == "requests"
        assert objs["queue_wait_p95"].kind == "gauge"


class TestBurnRates:
    def test_latency_burn_is_bad_fraction_over_budget(self):
        hub = _slo_hub()
        eng = SLOEngine(
            hub,
            (Objective("ttft", "latency", "ttft_s", threshold=0.5,
                       budget=0.05),),
            clock=hub.clock,
        )
        now = 5000.0
        for _ in range(90):
            hub.observe("ttft_s", 0.1, now=now)
        for _ in range(10):
            hub.observe("ttft_s", 0.9, now=now)
        rep = eng.evaluate(now=now)
        burn = rep["objectives"]["ttft"]["burn"]
        # 10% bad / 5% budget = burn 2.0 over every horizon.
        assert burn["60s"] == pytest.approx(2.0)
        assert burn["300s"] == pytest.approx(2.0)
        assert burn["1800s"] == pytest.approx(2.0)
        assert not rep["objectives"]["ttft"]["fast_alert"]
        # Burn 2.0 does hit the slow threshold (default slow_burn=2.0).
        assert rep["objectives"]["ttft"]["slow_alert"]

    def test_min_events_guard_no_traffic_is_not_an_outage(self):
        hub = _slo_hub()
        eng = SLOEngine(
            hub,
            (Objective("ttft", "latency", "ttft_s", threshold=0.5,
                       budget=0.05),),
            min_events=10, clock=hub.clock,
        )
        now = 5000.0
        for _ in range(5):  # 100% bad but below min_events
            hub.observe("ttft_s", 9.0, now=now)
        rep = eng.evaluate(now=now)
        assert rep["objectives"]["ttft"]["burn"]["60s"] == 0.0
        assert rep["breaching"] == []

    def test_ratio_burn(self):
        hub = _slo_hub()
        eng = SLOEngine(
            hub,
            (Objective("err", "ratio", "bad_requests",
                       total_signal="requests", budget=0.10),),
            clock=hub.clock,
        )
        now = 5000.0
        hub.inc("requests", value=50.0, now=now)
        hub.inc("bad_requests", value=10.0, now=now)
        rep = eng.evaluate(now=now)
        # 20% bad / 10% budget = burn 2.0.
        assert rep["objectives"]["err"]["burn"]["60s"] == pytest.approx(2.0)

    def test_gauge_burn_needs_two_observed_windows(self):
        hub = _slo_hub()
        eng = SLOEngine(
            hub,
            (Objective("qw", "gauge", "replica_queue_wait_p95_s",
                       threshold=0.25, budget=0.5),),
            clock=hub.clock,
        )
        now = 5000.0
        hub.set_gauge("replica_queue_wait_p95_s", 1.0, child="ep1", now=now)
        # One observed window: a single scrape can't claim 100% badness.
        assert eng.evaluate(now=now)["objectives"]["qw"]["burn"]["60s"] == 0.0
        hub.set_gauge(
            "replica_queue_wait_p95_s", 0.1, child="ep1", now=now + 10.0
        )
        rep = eng.evaluate(now=now + 10.0)
        # 1 bad of 2 observed windows / budget 0.5 = burn 1.0.
        assert rep["objectives"]["qw"]["burn"]["60s"] == pytest.approx(1.0)

    def test_fast_alert_requires_both_fast_windows(self):
        """A 1m spike diluted by a healthy 5m window must not page: the
        second fast window is the blip filter."""
        hub = _slo_hub()
        eng = SLOEngine(
            hub,
            (Objective("ttft", "latency", "ttft_s", threshold=0.5,
                       budget=0.05),),
            clock=hub.clock,
        )
        t1 = 10000.0
        for _ in range(200):  # healthy traffic ~3 minutes ago
            hub.observe("ttft_s", 0.01, now=t1 - 200.0)
        for _ in range(20):   # 100%-bad burst just now
            hub.observe("ttft_s", 2.0, now=t1)
        rep = eng.evaluate(now=t1)
        obj = rep["objectives"]["ttft"]
        assert obj["burn"]["60s"] == pytest.approx(20.0)   # 1.0 / 0.05
        assert obj["burn"]["300s"] < 2.0                   # diluted
        assert not obj["fast_alert"]
        assert not obj["breaching"]

    def test_fast_alert_fires_when_both_windows_burn(self):
        hub = _slo_hub()
        eng = SLOEngine(
            hub,
            (Objective("ttft", "latency", "ttft_s", threshold=0.5,
                       budget=0.05),),
            clock=hub.clock,
        )
        now = 10000.0
        for _ in range(20):
            hub.observe("ttft_s", 2.0, now=now)
        rep = eng.evaluate(now=now)
        obj = rep["objectives"]["ttft"]
        assert obj["fast_alert"] and obj["breaching"]
        assert obj["breaches_total"] == 1
        assert rep["breaching"] == ["ttft"]


class TestBreachHysteresis:
    def _engine(self):
        hub = _slo_hub()
        eng = SLOEngine(
            hub,
            (Objective("err", "ratio", "bad_requests",
                       total_signal="requests", budget=0.05),),
            clock=hub.clock,
        )
        return hub, eng

    def test_latch_holds_until_burns_fall_below_clear_factor(self):
        hub, eng = self._engine()
        t0 = 20000.0
        hub.inc("requests", value=100.0, now=t0)
        hub.inc("bad_requests", value=100.0, now=t0)
        rep = eng.evaluate(now=t0)
        assert rep["objectives"]["err"]["breaching"]
        assert rep["objectives"]["err"]["breaches_total"] == 1

        # 2 minutes on: the 1m window is clean (fast_alert off) but the
        # 5m window still burns 20 >= clear_factor*14.4 — stays latched,
        # and the latch does NOT count a second breach.
        rep = eng.evaluate(now=t0 + 120.0)
        obj = rep["objectives"]["err"]
        assert not obj["fast_alert"]
        assert obj["breaching"]
        assert obj["breaches_total"] == 1

        # Past the ring horizon every burn is 0 — the latch clears.
        rep = eng.evaluate(now=t0 + 2000.0)
        obj = rep["objectives"]["err"]
        assert not obj["breaching"]
        assert obj["breaches_total"] == 1

        # A fresh storm is a fresh breach.
        hub.inc("requests", value=100.0, now=t0 + 3000.0)
        hub.inc("bad_requests", value=100.0, now=t0 + 3000.0)
        rep = eng.evaluate(now=t0 + 3000.0)
        assert rep["objectives"]["err"]["breaches_total"] == 2

    def test_breach_emits_metrics_once_and_burn_gauges_every_pass(self):
        metrics = Metrics()
        hub = _slo_hub()
        eng = SLOEngine(
            hub,
            (Objective("err", "ratio", "bad_requests",
                       total_signal="requests", budget=0.05),),
            clock=hub.clock, metrics=metrics,
        )
        t0 = 20000.0
        hub.inc("requests", value=100.0, now=t0)
        hub.inc("bad_requests", value=100.0, now=t0)
        eng.evaluate(now=t0)
        eng.evaluate(now=t0 + 1.0)  # still breaching: no second count
        assert metrics.slo_breach_total.labels(
            objective="err"
        )._value.get() == 1.0
        assert metrics.slo_burn_rate.labels(
            objective="err", window="60s"
        )._value.get() == pytest.approx(20.0)
        assert metrics.slo_burn_rate.labels(
            objective="err", window="1800s"
        )._value.get() == pytest.approx(20.0)

    def test_fresh_breach_emits_one_slo_span_with_burns(self):
        exporter = InMemoryExporter()
        set_tracer_provider(TracerProvider(exporter))
        try:
            hub, eng = self._engine()
            t0 = 20000.0
            hub.inc("requests", value=100.0, now=t0)
            hub.inc("bad_requests", value=100.0, now=t0)
            eng.evaluate(now=t0)
            eng.evaluate(now=t0 + 1.0)  # latched, no second span
            spans = exporter.by_name("slo.breach")
            assert len(spans) == 1
            (span,) = spans
            assert span.attributes["slo.objective"] == "err"
            (evt,) = [e for e in span.events if e["name"] == "slo.burn"]
            assert evt["attributes"]["60s"] == pytest.approx(20.0)
        finally:
            set_tracer_provider(TracerProvider())


# -- env parsing -------------------------------------------------------------


class TestEnvParsing:
    def test_signals_off_by_default(self, monkeypatch):
        monkeypatch.delenv(tpu_env.KUBEFLOW_TPU_SIGNALS_ENABLE,
                           raising=False)
        assert signals_from_env() is None
        assert FleetTelemetry.from_env() is None

    def test_signals_enable_with_knobs(self, monkeypatch):
        monkeypatch.setenv(tpu_env.KUBEFLOW_TPU_SIGNALS_ENABLE, "true")
        monkeypatch.setenv(tpu_env.KUBEFLOW_TPU_SIGNALS_WINDOW_S, "2.5")
        monkeypatch.setenv(tpu_env.KUBEFLOW_TPU_SIGNALS_WINDOWS, "50")
        monkeypatch.setenv(tpu_env.KUBEFLOW_TPU_SIGNALS_TENANTS, "4")
        cfg = signals_from_env()
        assert cfg == SignalsConfig(window_s=2.5, windows=50,
                                    top_k_tenants=4)

    def test_signals_garbage_raises(self, monkeypatch):
        monkeypatch.setenv(tpu_env.KUBEFLOW_TPU_SIGNALS_ENABLE, "yes")
        with pytest.raises(ValueError):
            signals_from_env()
        monkeypatch.setenv(tpu_env.KUBEFLOW_TPU_SIGNALS_ENABLE, "1")
        monkeypatch.setenv(tpu_env.KUBEFLOW_TPU_SIGNALS_WINDOWS, "abc")
        with pytest.raises(ValueError):
            signals_from_env()
        monkeypatch.setenv(tpu_env.KUBEFLOW_TPU_SIGNALS_WINDOWS, "1")
        with pytest.raises(ValueError):
            signals_from_env()

    def test_slo_env_thresholds_are_milliseconds(self, monkeypatch):
        monkeypatch.setenv(tpu_env.KUBEFLOW_TPU_SLO_TTFT_P95_MS, "250")
        monkeypatch.setenv(tpu_env.KUBEFLOW_TPU_SLO_FAST_BURN, "10")
        objectives, kwargs = slo_from_env()
        objs = {o.name: o for o in objectives}
        assert objs["ttft_p95"].threshold == pytest.approx(0.25)
        assert kwargs["fast_burn"] == 10.0
        assert kwargs["slow_burn"] == 2.0

    def test_slo_env_garbage_raises(self, monkeypatch):
        monkeypatch.setenv(tpu_env.KUBEFLOW_TPU_SLO_TTFT_P95_MS, "fast")
        with pytest.raises(ValueError):
            slo_from_env()
        monkeypatch.delenv(tpu_env.KUBEFLOW_TPU_SLO_TTFT_P95_MS)
        monkeypatch.setenv(tpu_env.KUBEFLOW_TPU_SLO_ERROR_BUDGET, "2.0")
        with pytest.raises(ValueError):
            slo_from_env()


# -- FleetTelemetry ----------------------------------------------------------


def _telemetry(**cfg_kw):
    cfg = SignalsConfig(**{"window_s": 10.0, "windows": 12, **cfg_kw})
    clock = _Clock(1000.0)
    return FleetTelemetry(cfg, objectives=(), clock=clock), clock


class TestFleetTelemetry:
    def test_replica_counter_deltas_rebase_and_survive_restart(self):
        tel, clock = _telemetry()
        # First sight establishes the base only — a gateway restart must
        # not count the replica's whole cumulative history as new.
        tel.ingest_replica("ep1", {"served": 10})
        assert tel.hub.counter_total("fleet_served") == 0.0
        tel.ingest_replica("ep1", {"served": 25})
        assert tel.hub.counter_total("fleet_served") == 15.0
        # Replica restart: cumulative counter rebased near zero — count
        # its fresh total, never a negative delta.
        tel.ingest_replica("ep1", {"served": 5})
        assert tel.hub.counter_total("fleet_served") == 20.0
        # A second endpoint keeps its own base.
        tel.ingest_replica("ep2", {"served": 100})
        assert tel.hub.counter_total("fleet_served") == 20.0

    def test_replica_gauges_are_per_endpoint(self):
        tel, _ = _telemetry()
        tel.ingest_replica("ep1", {
            "queued": 3, "active_slots": 2,
            "queue_wait_s": {"p95": 0.2},
            "prefix_cache": {"hit_ratio": 0.75},
        })
        hub = tel.hub
        assert hub.gauge_last("replica_queue_depth", child="ep1") == 3.0
        assert hub.gauge_last("replica_queue_wait_p95_s",
                              child="ep1") == 0.2
        assert hub.gauge_last("replica_prefix_hit_ratio",
                              child="ep1") == 0.75

    def test_non_numeric_stats_are_ignored(self):
        tel, _ = _telemetry()
        tel.ingest_replica("ep1", {"served": "n/a", "queued": None,
                                   "tokens_generated": True})
        tel.ingest_replica("ep1", {"served": "n/a"})
        assert tel.hub.counter_total("fleet_served") == 0.0
        assert tel.hub.counter_total("fleet_tokens") == 0.0
        assert tel.hub.gauge_last("replica_queue_depth",
                                  child="ep1") is None
        tel.ingest_replica("ep1", None)  # scrape failed: no-op

    def test_snapshot_has_fleet_and_tenant_breakdowns(self):
        tel, clock = _telemetry()
        tel.observe_request("t1", ok=True, ttft_s=0.1,
                            inter_token=[0.01, 0.02], e2e_s=0.3)
        tel.observe_request("t2", ok=False)
        tel.observe_shed("t3")
        tel.ingest_ring(2)
        snap = tel.snapshot()
        assert snap["enabled"] is True
        fleet = snap["fleet"]
        assert fleet["ttft_s"] == {"p50": 0.1, "p95": 0.1, "count": 1}
        assert fleet["inter_token_s"]["count"] == 2
        assert fleet["ring_size"] == 2.0
        # Sheds count as requests AND bad_requests (the error-ratio SLO
        # sees them), so requests_per_s covers all three tenants.
        assert fleet["requests_per_s"] == pytest.approx(3.0 / 120.0)
        tenants = snap["tenants"]
        assert set(tenants) == {"t1", "t2", "t3"}
        assert tenants["t1"]["ttft_p95_s"] == 0.1
        assert tenants["t2"]["errors"] == 1.0
        assert tenants["t3"]["shed"] == 1.0

    def test_tenants_fold_past_top_k(self):
        tel, _ = _telemetry(top_k_tenants=1)
        tel.observe_request("t1", ok=True)
        tel.observe_request("t2", ok=True)
        tel.observe_shed("t3")
        snap = tel.snapshot()
        assert set(snap["tenants"]) == {"t1", TENANT_OTHER}
        assert snap["tenants"][TENANT_OTHER]["requests"] == 2.0

    def test_scrape_ages_track_fresh_ingests_only(self):
        """The autoscaler's staleness freeze reads these: the age must
        grow from the last FRESH ingest — a failed scrape (None) must
        not refresh it and mask a wedged /stats endpoint."""
        tel, clock = _telemetry()
        tel.ingest_replica("ep1", {"served": 1})
        clock.t += 4.0
        tel.ingest_replica("ep2", {"served": 1})
        assert tel.scrape_ages() == {"ep1": 4.0, "ep2": 0.0}
        clock.t += 2.0
        tel.ingest_replica("ep1", None)  # failed scrape: age keeps aging
        assert tel.scrape_ages()["ep1"] == 6.0
        snap = tel.snapshot()
        assert snap["fleet"]["last_scrape_age_s"] == {
            "ep1": 6.0, "ep2": 2.0,
        }

    def test_forget_replica_drops_age_and_counter_base(self):
        tel, clock = _telemetry()
        tel.ingest_replica("ep1", {"served": 50})
        tel.ingest_replica("ep1", {"served": 60})
        assert tel.hub.counter_total("fleet_served") == 10.0
        tel.forget_replica("ep1")
        assert tel.scrape_ages() == {}
        # Re-added after removal: first sight is base-only again, so a
        # departed replica's history is never double counted.
        tel.ingest_replica("ep1", {"served": 100})
        assert tel.hub.counter_total("fleet_served") == 10.0
        tel.forget_replica("ep-never-seen")  # idempotent

    def test_autoscale_actions_are_windowed_in_the_snapshot(self):
        tel, clock = _telemetry()
        tel.observe_autoscale("up")
        tel.observe_autoscale("hold")
        tel.observe_autoscale("hold")
        with pytest.raises(ValueError):
            tel.observe_autoscale("explode")
        snap = tel.snapshot(over_s=60.0)
        fleet = snap["fleet"]
        assert fleet["autoscale_up_per_s"] == pytest.approx(
            1.0 / 60.0, abs=1e-6
        )
        assert fleet["autoscale_hold_per_s"] == pytest.approx(
            2.0 / 60.0, abs=1e-6
        )
        assert fleet["autoscale_down_per_s"] == 0.0
        assert fleet["autoscale_freeze_per_s"] == 0.0


# -- stall -> profile capture hook -------------------------------------------


def _fake_trace(calls, fail=False):
    @contextlib.contextmanager
    def trace(log_dir, name):
        if fail:
            raise RuntimeError("no profiler on this host")
        calls.append(name)
        yield pathlib.Path(log_dir) / name
    return trace


class TestStallProfiler:
    def test_capture_once_per_cooldown(self, tmp_path):
        clock = _Clock(100.0)
        calls: list = []
        prof = StallProfiler(tmp_path, cooldown_s=60.0, duration_s=0.01,
                             clock=clock, trace_fn=_fake_trace(calls))
        assert prof.on_stall({"duration_s": 1.0})
        _wait_for(
            lambda: prof.summary()["captures"] == 1 and not prof._active
        )
        # Inside the cooldown every further stall is skipped, not queued.
        assert not prof.on_stall({"duration_s": 1.0})
        assert not prof.on_stall({"duration_s": 1.0})
        clock.t += 120.0
        assert prof.on_stall({"duration_s": 2.0})
        summary = _wait_for(
            lambda: (prof.summary()["captures"] == 2) and prof.summary()
        )
        assert summary["skipped"] == 2
        assert summary["last"]["path"].endswith("stall-002")
        assert summary["last"]["stall"]["duration_s"] == 2.0
        assert calls == ["stall-001", "stall-002"]

    def test_trace_failure_is_contained(self, tmp_path):
        clock = _Clock(100.0)
        prof = StallProfiler(tmp_path, cooldown_s=0.0, duration_s=0.01,
                             clock=clock,
                             trace_fn=_fake_trace([], fail=True))
        assert prof.on_stall({"duration_s": 1.0})
        _wait_for(
            lambda: prof.summary()["last_error"] and not prof._active
        )
        summary = prof.summary()
        assert summary["captures"] == 0
        assert "no profiler" in summary["last_error"]
        # The failed capture released the in-flight slot.
        assert prof.on_stall({"duration_s": 1.0})

    def test_knob_validation(self, tmp_path):
        with pytest.raises(ValueError):
            StallProfiler(tmp_path, cooldown_s=-1.0)
        with pytest.raises(ValueError):
            StallProfiler(tmp_path, duration_s=0.0)

    def test_recorder_invokes_hook_with_the_ledger_entry(self):
        events: list = []
        fr = FlightRecorder(min_samples=2, stall_factor=8.0,
                            clock=_Clock(5.0))
        fr.on_stall = events.append
        for _ in range(4):
            fr.record_step(0.01)
        assert fr.record_step(10.0)
        (info,) = events
        assert info["duration_s"] == 10.0
        assert info["factor"] == pytest.approx(1000.0)

    def test_from_env_gating(self, monkeypatch, tmp_path):
        monkeypatch.delenv(tpu_env.KUBEFLOW_TPU_STALL_PROFILE_DIR,
                           raising=False)
        assert stall_profiler_from_env() is None
        monkeypatch.setenv(tpu_env.KUBEFLOW_TPU_STALL_PROFILE_DIR,
                           str(tmp_path))
        monkeypatch.setenv(
            tpu_env.KUBEFLOW_TPU_STALL_PROFILE_COOLDOWN_S, "5"
        )
        monkeypatch.setenv(tpu_env.KUBEFLOW_TPU_STALL_PROFILE_SECONDS,
                           "0.5")
        prof = stall_profiler_from_env()
        assert prof is not None
        assert prof.log_dir == tmp_path
        assert prof.cooldown_s == 5.0
        assert prof.duration_s == 0.5
        monkeypatch.setenv(tpu_env.KUBEFLOW_TPU_STALL_PROFILE_SECONDS,
                           "soon")
        with pytest.raises(ValueError):
            stall_profiler_from_env()


# -- gateway surfaces over fake replicas -------------------------------------


def _get_json(gw, path):
    with urllib.request.urlopen(
        f"http://{gw.host}:{gw.port}{path}", timeout=30
    ) as resp:
        return json.loads(resp.read())


def _stream_ttft(host, port, payload, headers=None):
    """POST a streaming completion; returns (client-measured TTFT,
    data-line count). The clock starts before connect, like a client."""
    conn = http.client.HTTPConnection(host, port, timeout=120)
    t0 = time.monotonic()
    try:
        conn.request(
            "POST", "/v1/completions", json.dumps(payload).encode(),
            {"Content-Type": "application/json", **(headers or {})},
        )
        resp = conn.getresponse()
        assert resp.status == 200
        ttft, lines = None, 0
        while True:
            line = resp.fp.readline()
            if not line:
                break
            if not line.startswith(b"data:"):
                continue
            if line.strip() == b"data: [DONE]":
                break
            lines += 1
            if ttft is None:
                ttft = time.monotonic() - t0
        return ttft, lines
    finally:
        conn.close()


class TestGatewayTelemetrySurface:
    def test_disabled_by_default_debug_endpoints_say_so(self):
        from tests.test_gateway import _fleet, _teardown

        gw, replicas = _fleet(1)
        try:
            assert gw.telemetry is None
            assert _get_json(gw, "/debug/signals") == {"enabled": False}
            assert _get_json(gw, "/debug/slo") == {"enabled": False}
        finally:
            _teardown(gw, replicas)

    def test_env_enable_builds_telemetry_in_the_gateway(self, monkeypatch):
        from kubeflow_tpu.models.gateway import ServingGateway

        monkeypatch.setenv(tpu_env.KUBEFLOW_TPU_SIGNALS_ENABLE, "1")
        monkeypatch.setenv(tpu_env.KUBEFLOW_TPU_SIGNALS_TENANTS, "3")
        gw = ServingGateway([], port=0)
        try:
            assert gw.telemetry is not None
            assert gw.telemetry.config.top_k_tenants == 3
            # The Prometheus shed label and the per-tenant series share
            # one bucket table.
            assert gw._tenant_buckets is gw.telemetry.tenants
        finally:
            gw._httpd.server_close()

    def test_relay_feeds_stream_and_nonstream_requests(self):
        from tests.test_gateway import _fleet, _post, _teardown

        tel = FleetTelemetry(SignalsConfig(window_s=10.0, windows=12))
        gw, replicas = _fleet(2, gw_kw={"telemetry": tel})
        try:
            status, _body = _post(gw.host, gw.port,
                                  {"prompt": [1, 2, 3], "max_tokens": 3})
            assert status == 200
            ttft, lines = _stream_ttft(
                gw.host, gw.port,
                {"prompt": [4, 5, 6], "max_tokens": 3, "stream": True},
                headers={"x-tenant": "acme"},
            )
            assert ttft is not None and lines == 3
            snap = _get_json(gw, "/debug/signals")
            fleet = snap["fleet"]
            assert fleet["requests_per_s"] > 0
            # Only the stream has a first-token boundary; the JSON
            # round-trip lands in request_s alongside it.
            assert fleet["ttft_s"]["count"] == 1
            assert fleet["ttft_s"]["p95"] == pytest.approx(ttft, abs=0.05)
            assert fleet["inter_token_s"]["count"] == 2  # 3 tokens
            assert fleet["request_s"]["count"] == 2
            assert snap["tenants"]["anonymous"]["requests"] == 1.0
            assert snap["tenants"]["acme"]["requests"] == 1.0
            slo = _get_json(gw, "/debug/slo")
            assert slo["enabled"] is True
            assert set(slo["objectives"]) == {
                "ttft_p95", "inter_token_p95", "error_ratio",
                "queue_wait_p95",
            }
        finally:
            _teardown(gw, replicas)

    def test_probe_loop_ingests_replica_stats(self):
        from tests.test_gateway import _fleet, _post, _teardown

        tel, _ = _telemetry()
        gw, replicas = _fleet(2, gw_kw={"telemetry": tel})
        try:
            _post(gw.host, gw.port, {"prompt": [1, 2, 3], "max_tokens": 2})
            # health_interval_s=0.05: a couple of probe passes scrape
            # /stats into per-replica gauges and fleet counter deltas.
            _wait_for(lambda: len(
                _get_json(gw, "/debug/signals")["fleet"]
                ["replica_prefix_hit_ratio"]) == 2)
            snap = _get_json(gw, "/debug/signals")
            assert snap["fleet"]["ring_size"] == 2.0
            eps = {r.endpoint for r in replicas}
            assert set(
                snap["fleet"]["replica_queue_depth"]
            ) == eps
        finally:
            _teardown(gw, replicas)

    def test_shed_is_labeled_by_bounded_tenant_bucket(self):
        from kubeflow_tpu.models.gateway import GatewayOverloadedError
        from tests.test_gateway import _fleet, _teardown

        metrics = Metrics()
        tel, _ = _telemetry(top_k_tenants=1)
        gw, replicas = _fleet(
            1, gw_kw={"telemetry": tel, "metrics": metrics,
                      "max_inflight": 1},
        )
        try:
            gw._admit("t1")
            gw._admit("t2")  # under its share: admitted, folded to other
            with pytest.raises(GatewayOverloadedError):
                gw._admit("t1")  # over the fair share: shed
            assert metrics.gateway_shed_total.labels(
                tenant="t1"
            )._value.get() == 1.0
            snap = tel.snapshot()
            assert snap["tenants"]["t1"]["shed"] == 1.0
            # t2's admission created no request series; only the shed
            # path feeds telemetry at admission time.
            assert set(snap["tenants"]) == {"t1"}
        finally:
            _teardown(gw, replicas)


# -- real 2-replica fleet: telemetry p95 vs client p95 -----------------------


def _nearest_rank(xs, q):
    xs = sorted(xs)
    return xs[min(len(xs) - 1, max(0, math.ceil(q * len(xs)) - 1))]


class TestRealFleetAgreement:
    """ISSUE-11 acceptance: the relay-measured TTFT p95 on
    /debug/signals agrees with what a client actually measured, through
    real InferenceServer replicas (compile included on both sides)."""

    def test_telemetry_ttft_p95_matches_client_p95(self):
        import jax

        from kubeflow_tpu.models import llama as L
        from kubeflow_tpu.models.continuous import ContinuousBatcher
        from kubeflow_tpu.models.gateway import ServingGateway
        from kubeflow_tpu.models.server import InferenceServer
        from kubeflow_tpu.models.serving import GenerationConfig

        cfg = L.LLAMA_CONFIGS["tiny"]
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        servers = [
            InferenceServer(
                ContinuousBatcher(
                    params, cfg,
                    gen=GenerationConfig(max_new_tokens=4, eos_id=-1),
                    slots=2, cache_len=128, prompt_bucket=16,
                ),
                port=0,
            ).start()
            for _ in range(2)
        ]
        telemetry = FleetTelemetry(
            SignalsConfig(window_s=5.0, windows=360),
            objectives=default_objectives(
                ttft_p95_s=120.0, inter_token_p95_s=60.0,
                queue_wait_p95_s=60.0,
            ),
        )
        gw = ServingGateway(
            [f"{s.host}:{s.port}" for s in servers], port=0,
            block_size=16, health_interval_s=0.2, telemetry=telemetry,
        ).start()
        try:
            ttfts = []
            for i in range(8):
                ttft, lines = _stream_ttft(
                    gw.host, gw.port,
                    {"prompt": [3 + i, 4 + i, 5 + i, 6 + i],
                     "max_tokens": 4, "stream": True},
                )
                assert ttft is not None and lines >= 1
                ttfts.append(ttft)

            snap = _get_json(gw, "/debug/signals")
            fleet = snap["fleet"]
            assert fleet["ttft_s"]["count"] == len(ttfts)
            client_p95 = _nearest_rank(ttfts, 0.95)
            tel_p95 = fleet["ttft_s"]["p95"]
            # Same requests measured at the relay vs at the client: the
            # only gap is loopback connect/send, so 15% with a 25ms
            # floor for scheduler jitter on tiny TTFTs.
            assert tel_p95 == pytest.approx(
                client_p95, rel=0.15, abs=0.025
            )

            # A healthy run must leave the (lenient) SLOs silent.
            slo = _get_json(gw, "/debug/slo")
            assert slo["breaching"] == []
            assert all(
                o["breaches_total"] == 0
                for o in slo["objectives"].values()
            )
        finally:
            gw.stop()
            for s in servers:
                s.stop()
