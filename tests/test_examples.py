"""Examples are executable documentation — run them as smoke tests."""

from __future__ import annotations

import os
import pathlib
import subprocess
import sys

import pytest

EXAMPLES = pathlib.Path(__file__).resolve().parent.parent / "examples"


def _run(script: str, *extra: str) -> subprocess.CompletedProcess:
    env = dict(os.environ)
    # Hard override, not setdefault: the machine may export a TPU platform
    # (JAX_PLATFORMS=axon); example smoke tests must be hermetic on CPU.
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    return subprocess.run(
        [sys.executable, str(EXAMPLES / script), "--config", "tiny", *extra],
        capture_output=True,
        text=True,
        timeout=420,
        env=env,
        cwd=str(EXAMPLES.parent),
    )


def test_train_sharded_runs_and_resumes(tmp_path):
    ckpt = str(tmp_path / "ckpt")
    first = _run("train_sharded.py", "--steps", "4", "--ckpt-dir", ckpt)
    assert first.returncode == 0, first.stderr
    assert "step 4" in first.stdout
    # Second run resumes instead of restarting (preemption recovery).
    second = _run("train_sharded.py", "--steps", "6", "--ckpt-dir", ckpt)
    assert second.returncode == 0, second.stderr
    assert "resumed from step 4" in second.stdout
    assert "step 5" in second.stdout


def test_train_sharded_with_data_corpus_and_resume(tmp_path):
    """--data drives the real input pipeline (native/Python TokenLoader);
    resume passes start_batch so the restored run continues the stream."""
    import numpy as np

    from kubeflow_tpu.data import write_token_file

    corpus = tmp_path / "corpus.bin"
    write_token_file(corpus, np.arange(8192, dtype=np.uint32))
    ckpt = str(tmp_path / "ckpt")
    first = _run("train_sharded.py", "--steps", "4", "--ckpt-dir", ckpt,
                 "--data", str(corpus))
    assert first.returncode == 0, first.stderr
    second = _run("train_sharded.py", "--steps", "6", "--ckpt-dir", ckpt,
                  "--data", str(corpus))
    assert second.returncode == 0, second.stderr
    assert "resumed from step 4" in second.stdout
    assert "step 5" in second.stdout


def test_train_sharded_zigzag_sp(tmp_path):
    res = _run("train_sharded.py", "--steps", "2", "--sp-impl", "zigzag",
               "--ckpt-dir", str(tmp_path / "ck"))
    assert res.returncode == 0, res.stderr
    assert "step 2" in res.stdout


def test_finetune_lora_runs_and_exports(tmp_path):
    out = str(tmp_path / "merged.npz")
    res = _run("finetune_lora.py", "--steps", "3", "--export", out)
    assert res.returncode == 0, res.stderr
    assert "adapter params" in res.stdout
    assert pathlib.Path(out).exists()


@pytest.mark.parametrize(
    "extra", [(), ("--int8",), ("--paged",), ("--tp", "2", "--sp", "2"),
              ("--paged", "--tp", "2"), ("--kv8",), ("--int8", "--kv8"),
              ("--paged", "--kv8"), ("--kv8", "--tp", "2", "--sp", "2"),
              ("--paged", "--kv8", "--tp", "2"), ("--speculative", "1"),
              ("--speculative", "1", "--paged", "--kv8"),
              ("--paged", "--prompt-cache"), ("--paged", "--prefix-cache"),
              ("--speculative", "1", "--paged", "--prefix-cache"),
              ("--fp8",), ("--fp8", "--paged", "--kv8")]
)
def test_serve_batched_runs(extra):
    res = _run("serve_batched.py", "--max-new-tokens", "4", *extra)
    assert res.returncode == 0, res.stderr
    assert "[2]" in res.stdout  # three prompts served


@pytest.mark.parametrize(
    "flags", [("--paged",), ("--admit-chunk", "16")],
    ids=["paged", "admit-chunk"],
)
def test_serve_http_example(flags):
    """serve_http.py answers real HTTP completions (per engine mode)."""
    import json
    import time
    import urllib.request

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, str(EXAMPLES / "serve_http.py"), "--config",
         "tiny", "--port", "0", "--max-new-tokens", "4", *flags],
        env=env, cwd=str(EXAMPLES.parent),
        stdout=subprocess.PIPE, text=True,
    )
    try:
        line = proc.stdout.readline()  # "serving ... on http://host:port"
        port = int(line.rsplit(":", 1)[1].split()[0])
        deadline = time.monotonic() + 60
        while True:
            try:
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/v1/completions",
                    data=json.dumps({"prompt": [1, 2, 3]}).encode(),
                )
                with urllib.request.urlopen(req, timeout=60) as resp:
                    out = json.loads(resp.read())
                break
            except OSError:
                if time.monotonic() > deadline:
                    raise
                time.sleep(1)
        assert len(out["choices"][0]["tokens"]) == 4
    finally:
        proc.terminate()
        proc.wait(timeout=30)


def test_train_sharded_fp8(tmp_path):
    """--fp8 trains with fp8 matmul operands end to end (wrap + OWG
    optimizer partitioning + checkpoint save)."""
    res = _run("train_sharded.py", "--steps", "2", "--fp8",
               "--ckpt-dir", str(tmp_path / "ck"))
    assert res.returncode == 0, res.stderr
    assert "step 2" in res.stdout
