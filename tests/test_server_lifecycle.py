"""Request-lifecycle robustness (models/server.py + _BatcherBase):
admission control / shedding, per-request deadlines, disconnect
cancellation, graceful drain, and engine-crash containment.

Determinism strategy: overload tests stall the engine with a no-op step
(the queue can only grow), deadline tests inject a counting fake clock
into the engine, and the crash test parks two waiters before the step
raises — no sleeps standing in for synchronization.
"""

import http.client
import json
import socket
import threading
import time
import urllib.error
import urllib.request

import pytest

from kubeflow_tpu.models.serving import GenerationConfig
from kubeflow_tpu.models.server import InferenceServer

from tests.test_server import _engine, _get, _post


def _post_status(port, payload, timeout=60.0):
    """(status, body, headers) — 4xx/5xx are outcomes under test."""
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/v1/completions",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read()), dict(resp.headers)
    except urllib.error.HTTPError as err:
        body = json.loads(err.read() or b"{}")
        return err.code, body, dict(err.headers)


def _stall(srv):
    """Replace the engine step with a keep-alive no-op: admitted work
    parks in its slot, everything else piles into the pending queue.
    Returns an Event; set() restores real decoding."""
    lifted = threading.Event()
    real_step = srv.engine._step

    def stalled_step():
        if not lifted.is_set():
            time.sleep(0.005)
            return
        real_step()

    srv.engine._step = stalled_step
    return lifted


def _fill(srv, depth, accepted):
    """Deterministically occupy one slot + ``depth`` queue entries on a
    stalled single-slot server: each background POST is confirmed
    admitted/queued before the next starts, so no admission race can
    over- or undershoot the fill."""
    threads = []

    def accept_post():
        accepted.append(_post_status(
            srv.port, {"prompt": [1, 2, 3], "max_tokens": 2}
        ))

    deadline = time.monotonic() + 30
    t = threading.Thread(target=accept_post, daemon=True)
    t.start()
    threads.append(t)
    while (not any(r is not None for r in srv.engine._by_slot)
           and time.monotonic() < deadline):
        time.sleep(0.005)
    for i in range(depth):
        t = threading.Thread(target=accept_post, daemon=True)
        t.start()
        threads.append(t)
        while (len(srv.engine._queue) <= i
               and time.monotonic() < deadline):
            time.sleep(0.005)
    assert len(srv.engine._queue) == depth, "fill never completed"
    return threads


class TestAdmissionControl:
    def test_queue_full_sheds_429_fast_with_exact_counter(self):
        srv = InferenceServer(
            _engine(slots=1), port=0, max_queue_depth=2
        )
        lifted = _stall(srv)
        srv.start()
        try:
            accepted = []
            threads = _fill(srv, depth=2, accepted=accepted)
            latencies = []
            for _ in range(3):
                t0 = time.monotonic()
                code, body, headers = _post_status(
                    srv.port, {"prompt": [1, 2, 3], "max_tokens": 2}
                )
                latencies.append(time.monotonic() - t0)
                assert code == 429
                assert headers.get("Retry-After") == "1"
                assert "full" in body["error"]
            # The shed path takes no engine lock: even with the engine
            # mid-"step", a full queue answers within the 50ms budget.
            assert max(latencies) < 0.05, latencies
            assert srv._shed == 3
            stats = _get(srv.port, "/stats")
            assert stats["requests_shed"] == 3
            assert stats["max_queue_depth"] == 2

            lifted.set()  # parked work must complete untouched
            for t in threads:
                t.join(timeout=60)
            assert [c for c, _, _ in accepted] == [200, 200, 200]
            assert srv._shed == 3  # sheds counted exactly, no drift
        finally:
            lifted.set()
            srv.stop()

    def test_concurrent_submits_shed_exactly(self):
        srv = InferenceServer(
            _engine(slots=1), port=0, max_queue_depth=2
        )
        lifted = _stall(srv)
        srv.start()
        try:
            accepted = []
            fill_threads = _fill(srv, depth=2, accepted=accepted)
            results = []
            lock = threading.Lock()

            def shed_post():
                out = _post_status(
                    srv.port, {"prompt": [1, 2, 3], "max_tokens": 2}
                )
                with lock:
                    results.append(out[0])

            storm = [threading.Thread(target=shed_post, daemon=True)
                     for _ in range(8)]
            for t in storm:
                t.start()
            for t in storm:
                t.join(timeout=30)
            assert results == [429] * 8
            assert srv._shed == 8
            lifted.set()
            for t in fill_threads:
                t.join(timeout=60)
            assert [c for c, _, _ in accepted] == [200, 200, 200]
        finally:
            lifted.set()
            srv.stop()

    def test_oversized_body_is_413(self):
        srv = InferenceServer(_engine(), port=0, max_body_bytes=256)
        srv.start()
        try:
            code, body, _ = _post_status(
                srv.port, {"prompt": list(range(1000))}
            )
            assert code == 413
            assert "exceeds" in body["error"]
            # A within-limit request still serves.
            code, _, _ = _post_status(
                srv.port, {"prompt": [1, 2], "max_tokens": 2}
            )
            assert code == 200
        finally:
            srv.stop()

    def test_bad_deadline_is_400(self):
        srv = InferenceServer(_engine(), port=0)
        srv.start()
        try:
            for bad in (-1, 0, "soon", True, float("nan")):
                code, _, _ = _post_status(
                    srv.port, {"prompt": [1], "deadline_s": bad}
                )
                assert code == 400, bad
        finally:
            srv.stop()


class TestDeadlines:
    def test_deadline_expiry_mid_decode_engine_side(self):
        """Fake clock advancing 1s per observation: a 3.5s deadline
        yields ~3 tokens, then the slot is retired through the abort
        path — never left decoding past its deadline."""
        eng = _engine(gen=GenerationConfig(max_new_tokens=64))
        t = [0.0]

        def clk():
            t[0] += 1.0
            return t[0]

        eng._clock = clk
        rid = eng.submit([1, 2, 3], deadline_s=3.5)
        results = eng.run()
        aborted = eng.run_aborted()
        assert aborted == {rid: "deadline"}
        assert 1 <= len(results[rid]) <= 4  # partial, not full budget
        assert all(r is None for r in eng._by_slot)  # slot reclaimed
        # The engine is healthy for the next request.
        rid2 = eng.submit([1, 2, 3])
        assert len(eng.run()[rid2]) > 0

    def test_expired_deadline_is_504_with_partials(self):
        srv = InferenceServer(_engine(), port=0)
        srv.start()
        try:
            code, body, _ = _post_status(
                srv.port, {"prompt": [1, 2, 3], "deadline_s": 1e-6}
            )
            assert code == 504
            assert body["error"] == "deadline"
            assert "partial_tokens" in body
            assert srv._deadline_expired == 1
            assert _get(srv.port, "/stats")["deadline_expired"] == 1
            # Slot reclaimed; server still serves.
            out = _post(srv.port, {"prompt": [1, 2], "max_tokens": 2})
            assert len(out["choices"][0]["tokens"]) == 2
        finally:
            srv.stop()

    def test_default_deadline_applies_when_client_sends_none(self):
        srv = InferenceServer(_engine(), port=0, default_deadline_s=1e-6)
        srv.start()
        try:
            code, body, _ = _post_status(srv.port, {"prompt": [1, 2, 3]})
            assert code == 504
            assert body["error"] == "deadline"
        finally:
            srv.stop()

    def test_max_deadline_clamps_client_request(self):
        srv = InferenceServer(_engine(), port=0, max_deadline_s=1e-6)
        srv.start()
        try:
            code, body, _ = _post_status(
                srv.port, {"prompt": [1, 2, 3], "deadline_s": 3600.0}
            )
            assert code == 504
        finally:
            srv.stop()

    def test_engine_rejects_bad_deadlines(self):
        eng = _engine()
        for bad in (0, -1.0, float("inf"), float("nan"), True, "x"):
            with pytest.raises((ValueError, TypeError)):
                eng.submit([1], deadline_s=bad)


class TestDisconnectCancellation:
    def test_disconnect_storm_reclaims_every_slot(self):
        """Acceptance: N streaming clients hang up after their first
        token; the engine converges to zero busy slots with the
        cancelled counter matching the storm size exactly."""
        clients = 4
        # Budget far past what decodes before the FIN registers (a
        # couple of writes): the request must still be mid-decode when
        # the broken pipe cancels it, or there is nothing to reclaim.
        srv = InferenceServer(
            _engine(gen=GenerationConfig(max_new_tokens=100)), port=0
        )
        srv.start()
        try:
            conns = []
            for _ in range(clients):
                c = http.client.HTTPConnection(
                    "127.0.0.1", srv.port, timeout=30
                )
                c.request(
                    "POST", "/v1/completions",
                    json.dumps({"prompt": [1, 2, 3], "stream": True}),
                    {"Content-Type": "application/json"},
                )
                conns.append(c)
            for c in conns:
                resp = c.getresponse()
                while True:  # first token, then hang up without warning
                    line = resp.fp.readline()
                    if not line or line.startswith(b"data:"):
                        break
                # Connection: close responses own the socket; closing
                # the response sends FIN mid-stream — the abrupt
                # disconnect under test.
                resp.close()
                c.close()
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                with srv._lock:
                    busy = (
                        any(r is not None for r in srv.engine._by_slot)
                        or bool(srv.engine._queue)
                        or getattr(srv.engine, "_admitting", None)
                        is not None
                    )
                    cancelled = srv._cancelled
                if not busy and cancelled == clients:
                    break
                time.sleep(0.01)
            assert not busy, "slots still decoding dead work"
            assert cancelled == clients  # counter matches exactly
            assert srv._engine_error is None
            assert _get(srv.port, "/stats")["requests_cancelled"] == clients
            # The freed capacity serves a live client immediately.
            out = _post(srv.port, {"prompt": [1, 2], "max_tokens": 2})
            assert len(out["choices"][0]["tokens"]) == 2
        finally:
            srv.stop()

    def test_gone_nonstream_client_cancels_queued_request(self):
        """A non-stream client that disconnects before the response is
        detected by the completion poll and its request cancelled."""
        srv = InferenceServer(_engine(slots=1), port=0)
        lifted = _stall(srv)
        srv.start()
        try:
            raw = socket.create_connection(("127.0.0.1", srv.port),
                                           timeout=10)
            payload = json.dumps({"prompt": [1, 2, 3]}).encode()
            raw.sendall(
                b"POST /v1/completions HTTP/1.1\r\n"
                b"Host: x\r\nContent-Type: application/json\r\n"
                + f"Content-Length: {len(payload)}\r\n\r\n".encode()
                + payload
            )
            deadline = time.monotonic() + 30
            while not srv._queues and time.monotonic() < deadline:
                time.sleep(0.005)
            assert srv._queues, "request never registered"
            raw.close()  # client gone while the engine is stalled
            # The poll marks it cancelled engine-side (slotted: marked
            # for the next step; still queued: aborted immediately)...
            while (not srv.engine._cancelled and srv._cancelled < 1
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            assert srv.engine._cancelled or srv._cancelled >= 1, (
                "disconnect never detected"
            )
            # ...and the next step (stall lifted) reclaims the slot.
            lifted.set()
            while ((any(r is not None for r in srv.engine._by_slot)
                    or srv._cancelled < 1)
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            assert all(r is None for r in srv.engine._by_slot)
            assert srv._cancelled == 1
        finally:
            lifted.set()
            srv.stop()

    def test_engine_cancel_semantics(self):
        eng = _engine()
        rid_q = eng.submit([1, 2, 3])
        assert eng.cancel(rid_q, "test") is True  # queued: immediate
        assert eng.run_aborted() == {}  # not yet run
        assert not eng._queue
        assert eng.cancel(999) is False  # unknown rid
        rid2 = eng.submit([1, 2, 3])
        out = eng.run()
        assert rid_q not in out or out[rid_q] == []
        assert len(out[rid2]) > 0


class TestGracefulDrain:
    def test_healthz_unready_the_moment_drain_starts(self):
        srv = InferenceServer(_engine(), port=0)
        srv.start()
        try:
            assert _get(srv.port, "/healthz")["status"] == "ok"
            srv._draining = True
            try:
                _get(srv.port, "/healthz")
                assert False, "expected 503"
            except urllib.error.HTTPError as err:
                assert err.code == 503
                assert json.loads(err.read())["status"] == "draining"
        finally:
            srv._draining = False
            srv.stop()

    def test_drain_rejects_new_force_aborts_stragglers(self):
        srv = InferenceServer(_engine(slots=1), port=0, drain_s=0.4)
        lifted = _stall(srv)
        srv.start()
        straggler = {}

        def call():
            straggler["out"] = _post_status(
                srv.port, {"prompt": [1, 2, 3]}, timeout=60
            )

        t = threading.Thread(target=call, daemon=True)
        t.start()
        deadline = time.monotonic() + 30
        while not srv._queues and time.monotonic() < deadline:
            time.sleep(0.005)
        stopper = threading.Thread(target=srv.stop, daemon=True)
        stopper.start()
        while not srv._draining and time.monotonic() < deadline:
            time.sleep(0.005)
        # New arrivals during the drain window: 503 + Retry-After.
        code, body, headers = _post_status(
            srv.port, {"prompt": [1]}, timeout=10
        )
        assert code == 503
        assert headers.get("Retry-After") == "1"
        assert "draining" in body["error"]
        stopper.join(timeout=30)
        t.join(timeout=30)
        # The straggler was force-aborted as an ERROR, not a completion.
        assert straggler["out"][0] == 500
        assert "shutdown" in straggler["out"][1]["error"]
        assert srv._drain_duration is not None
        assert srv._drain_duration >= 0.4  # waited the full window

    def test_drain_lets_inflight_finish(self):
        srv = InferenceServer(_engine(), port=0, drain_s=30.0)
        srv.start()
        result = {}

        def call():
            result["out"] = _post_status(
                srv.port, {"prompt": [1, 2, 3]}, timeout=60
            )

        t = threading.Thread(target=call, daemon=True)
        t.start()
        deadline = time.monotonic() + 30
        while not srv._queues and time.monotonic() < deadline:
            time.sleep(0.002)
        srv.stop()
        t.join(timeout=30)
        assert result["out"][0] == 200
        assert len(result["out"][1]["choices"][0]["tokens"]) == 8
        assert srv._drain_duration is not None
        assert srv._drain_duration < 30.0  # finished, not timed out

    def test_stop_is_idempotent(self):
        srv = InferenceServer(_engine(), port=0)
        srv.start()
        srv.stop()
        srv.stop()  # second call must be a no-op, not an error


class TestEngineCrashContainment:
    def test_crash_aborts_all_waiting_queues_with_cause(self):
        srv = InferenceServer(_engine(slots=1), port=0)
        both_in = threading.Event()

        def crashing_step():
            if not both_in.wait(timeout=0.01):
                return  # keep parking until both waiters registered
            raise RuntimeError("device exploded")

        srv.engine._step = crashing_step
        srv.start()
        results = []
        lock = threading.Lock()

        def call():
            out = _post_status(srv.port, {"prompt": [1, 2, 3]}, timeout=30)
            with lock:
                results.append(out)

        threads = [threading.Thread(target=call, daemon=True)
                   for _ in range(2)]
        for t in threads:
            t.start()
        deadline = time.monotonic() + 30
        while len(srv._queues) < 2 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert len(srv._queues) == 2, "waiters never parked"
        both_in.set()
        for t in threads:
            t.join(timeout=30)
        assert len(results) == 2
        for code, body, _ in results:
            assert code == 500
            assert "device exploded" in body["error"]
        # healthz reports the cause; new submits refuse with 503.
        try:
            _get(srv.port, "/healthz")
            assert False, "expected 503"
        except urllib.error.HTTPError as err:
            assert err.code == 503
            health = json.loads(err.read())
            assert health["status"] == "engine failed"
            assert "device exploded" in health["error"]
        code, body, _ = _post_status(srv.port, {"prompt": [1]}, timeout=10)
        assert code == 503
        assert "device exploded" in body["error"]
        srv.stop()


class TestFinishReason:
    def test_budget_truncation_reports_length(self):
        srv = InferenceServer(_engine(), port=0)
        srv.start()
        try:
            out = _post(srv.port, {"prompt": [1, 2, 3], "max_tokens": 3})
            assert out["choices"][0]["finish_reason"] == "length"
            # The engine-wide budget (8) truncating also reads "length".
            out = _post(srv.port, {"prompt": [1, 2, 3]})
            assert out["choices"][0]["finish_reason"] == "length"
        finally:
            srv.stop()

    def test_stop_sequence_reports_stop(self):
        eng = _engine()
        rid = eng.submit([1, 2, 3, 4])
        full = eng.run()[rid]
        assert len(full) >= 4
        srv = InferenceServer(_engine(), port=0)
        srv.start()
        try:
            out = _post(srv.port, {
                "prompt": [1, 2, 3, 4], "stop": full[2:4],
            })
            got = out["choices"][0]["tokens"]
            # Truncated at (and excluding) the first stop match — with a
            # degenerate greedy continuation that can be earlier than
            # position 2, so assert the prefix property, not the index.
            assert got == full[:len(got)]
            assert len(got) < len(full)
            assert out["choices"][0]["finish_reason"] == "stop"
        finally:
            srv.stop()
