"""HTTP inference server (models/server.py) over the batching engines.

Real sockets, real threads: each test starts the server on an ephemeral
port, speaks actual HTTP with urllib, and asserts token-exactness
against the engine driven directly — the server is transport, not
model, so its output must be bit-identical to run().
"""

import json
import threading
import urllib.error
import urllib.request

import jax
import pytest

from kubeflow_tpu.models import llama as L
from kubeflow_tpu.models.continuous import ContinuousBatcher
from kubeflow_tpu.models.serving import GenerationConfig
from kubeflow_tpu.models.server import InferenceServer

CFG = L.LLAMA_CONFIGS["tiny"]
PARAMS = L.init_params(CFG, jax.random.PRNGKey(0))


def _engine(**kw):
    kw.setdefault("gen", GenerationConfig(max_new_tokens=8))
    kw.setdefault("slots", 2)
    kw.setdefault("cache_len", 128)
    kw.setdefault("prompt_bucket", 16)
    return ContinuousBatcher(PARAMS, CFG, **kw)


def _post(port, payload, path="/v1/completions"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=120) as resp:
        return json.loads(resp.read())


def _get(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=30
    ) as resp:
        return json.loads(resp.read())


@pytest.fixture()
def server():
    srv = InferenceServer(_engine(), port=0).start()
    yield srv
    srv.stop()


class TestCompletions:
    def test_tokens_match_direct_engine_run(self, server):
        prompt = [1, 2, 3, 4, 5]
        out = _post(server.port, {"prompt": prompt})
        direct = _engine()
        rid = direct.submit(prompt)
        want = direct.run()[rid]
        assert out["choices"][0]["tokens"] == want
        assert out["usage"]["completion_tokens"] == len(want)
        assert out["usage"]["prompt_tokens"] == len(prompt)

    def test_per_request_max_tokens(self, server):
        out = _post(server.port, {"prompt": [1, 2, 3], "max_tokens": 3})
        assert len(out["choices"][0]["tokens"]) == 3

    def test_concurrent_requests_share_the_batch(self, server):
        prompts = [[1, 2, 3], [5, 6, 7, 8], [9, 10]]
        results = {}

        def call(i):
            results[i] = _post(server.port, {"prompt": prompts[i]})

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        direct = _engine()
        rids = [direct.submit(p) for p in prompts]
        want = direct.run()
        for i, rid in enumerate(rids):
            assert results[i]["choices"][0]["tokens"] == want[rid], i

    def test_streaming_matches_non_streaming(self, server):
        prompt = [2, 4, 6]
        want = _post(server.port, {"prompt": prompt})["choices"][0]["tokens"]
        req = urllib.request.Request(
            f"http://127.0.0.1:{server.port}/v1/completions",
            data=json.dumps({"prompt": prompt, "stream": True}).encode(),
            headers={"Content-Type": "application/json"},
        )
        tokens, done = [], False
        with urllib.request.urlopen(req, timeout=120) as resp:
            assert resp.headers["Content-Type"] == "text/event-stream"
            for raw in resp:
                line = raw.decode().strip()
                if not line.startswith("data: "):
                    continue
                body = line[len("data: "):]
                if body == "[DONE]":
                    done = True
                    break
                tokens.append(json.loads(body)["token"])
        assert done
        assert tokens == want

    def test_bad_requests(self, server):
        for payload in (
            {"prompt": "text without tokenizer"},
            {"prompt": [1, "a"]},
            {"prompt": []},
            {"prompt": list(range(50))},  # over prompt_bucket
            {"prompt": [1], "max_tokens": 0},
        ):
            with pytest.raises(urllib.error.HTTPError) as err:
                _post(server.port, payload)
            assert err.value.code == 400, payload

    def test_health_models_stats(self, server):
        assert _get(server.port, "/healthz")["status"] == "ok"
        models = _get(server.port, "/v1/models")["data"]
        assert models[0]["id"] == "kubeflow-tpu"
        _post(server.port, {"prompt": [1, 2]})
        stats = _get(server.port, "/stats")
        assert stats["served"] >= 1
        assert stats["slots"] == 2

    def test_results_do_not_accumulate(self, server):
        """A long-running server must deliver results, not hoard them."""
        for _ in range(3):
            _post(server.port, {"prompt": [1, 2, 3], "max_tokens": 2})
        assert server.engine._results == {}
        assert server._queues == {}


class TestRobustness:
    def test_speculative_engine_serves(self):
        """The spec wrappers delegate to an inner engine; hooks must land
        on the object whose _note_token reads them or completions hang."""
        from kubeflow_tpu.models.speculative import (
            SpeculativeContinuousBatcher, truncated_draft,
        )

        draft, dcfg = truncated_draft(PARAMS, CFG, 1)
        spec = SpeculativeContinuousBatcher(
            PARAMS, CFG, draft, dcfg,
            gen=GenerationConfig(max_new_tokens=6),
            slots=2, cache_len=128, prompt_bucket=16, k_spec=2,
        )
        srv = InferenceServer(spec, port=0).start()
        try:
            out = _post(srv.port, {"prompt": [1, 2, 3, 4]})
            assert len(out["choices"][0]["tokens"]) == 6
        finally:
            srv.stop()

    def test_bad_max_tokens_type_is_400(self, server):
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(server.port, {"prompt": [1], "max_tokens": "8"})
        assert err.value.code == 400

    def test_engine_failure_unblocks_and_flips_health(self):
        """A step exception must fail pending requests (500) and turn
        /healthz red — never a silently-dead thread + hung clients."""
        srv = InferenceServer(_engine(), port=0)

        def boom():
            raise RuntimeError("synthetic device loss")

        srv.engine._step = boom
        srv.start()
        try:
            with pytest.raises(urllib.error.HTTPError) as err:
                _post(srv.port, {"prompt": [1, 2, 3]})
            assert err.value.code == 500
            with pytest.raises(urllib.error.HTTPError) as err:
                _get(srv.port, "/healthz")
            assert err.value.code == 503
        finally:
            srv.stop()

    def test_submit_after_engine_death_is_503_not_hang(self):
        srv = InferenceServer(_engine(), port=0)

        def boom():
            raise RuntimeError("synthetic device loss")

        srv.engine._step = boom
        srv.start()
        try:
            with pytest.raises(urllib.error.HTTPError):
                _post(srv.port, {"prompt": [1, 2]})  # kills the engine
            # a NEW request must be refused immediately, not hang on a
            # queue the dead drive thread will never close
            with pytest.raises(urllib.error.HTTPError) as err:
                _post(srv.port, {"prompt": [3, 4]})
            assert err.value.code == 503
        finally:
            srv.stop()

    def test_stop_unblocks_inflight_requests(self):
        """stop() must close pending queues — an in-flight handler
        blocked on q.get() would otherwise hang its client forever."""
        import time

        # Tiny drain budget: this test wants the force-abort path, not
        # a graceful drain of the deliberately-frozen engine.
        srv = InferenceServer(_engine(), port=0, drain_s=0.2)
        srv.start()
        # Freeze the engine so the request stays in flight.
        frozen = threading.Event()

        def slow_step():
            frozen.set()
            time.sleep(0.2)

        srv.engine._step = slow_step
        result = {}

        def call():
            try:
                result["out"] = _post(srv.port, {"prompt": [1, 2, 3]})
            except Exception as err:
                result["err"] = err

        t = threading.Thread(target=call)
        t.start()
        assert frozen.wait(timeout=30)
        srv.stop()
        t.join(timeout=30)
        assert not t.is_alive(), "handler still blocked after stop()"
        # and shutdown truncation reads as an ERROR, not a completion
        assert isinstance(result.get("err"), urllib.error.HTTPError)
        assert result["err"].code == 500

    def test_stop_releases_the_port(self):
        srv = InferenceServer(_engine(), port=0).start()
        port = srv.port
        srv.stop()
        # rebinding the same port must succeed immediately
        srv2 = InferenceServer(_engine(), port=port).start()
        try:
            assert _get(port, "/healthz")["status"] == "ok"
        finally:
            srv2.stop()


class TestEngineHooks:
    def test_run_without_hooks_unchanged(self):
        """The hook plumbing must not change the drive-to-completion
        API: no callbacks set → results land in run() as before."""
        eng = _engine()
        rid = eng.submit([1, 2, 3])
        out = eng.run()
        assert rid in out and len(out[rid]) > 0

    def test_max_new_tokens_clamped_to_engine_max(self):
        eng = _engine()
        rid = eng.submit([1, 2, 3], max_new_tokens=50)  # gen.max is 8
        assert len(eng.run()[rid]) <= 8

    def test_paged_preemption_keeps_per_request_cap(self):
        """A preempted-and-re-admitted request must keep its max_new cap
        — losing it under block pressure would overrun the client's
        budget exactly when the server is loaded."""
        from kubeflow_tpu.models.paged import PagedBatcher

        pb = PagedBatcher(
            PARAMS, CFG, gen=GenerationConfig(max_new_tokens=12),
            slots=2, num_blocks=8, block_size=16, prompt_bucket=16,
        )
        rids = [pb.submit([1, 2, 3, 4], max_new_tokens=3),
                pb.submit([5, 6, 7, 8], max_new_tokens=3),
                pb.submit([9, 10, 11], max_new_tokens=3)]
        out = pb.run()
        for rid in rids:
            assert len(out[rid]) <= 3, out
        # and the preemption continuation itself carries the cap
        pb2 = PagedBatcher(
            PARAMS, CFG, gen=GenerationConfig(max_new_tokens=12),
            slots=2, num_blocks=8, block_size=16, prompt_bucket=16,
        )
        pb2.submit([1, 2, 3], max_new_tokens=3)
        pb2._admit_free_slots()
        slot = next(i for i, r in enumerate(pb2._by_slot) if r is not None)
        pb2._preempt(slot)
        assert pb2._queue[0].max_new == 3


class TestSamplingOptions:
    def test_per_request_temperature_mixes_greedy_and_sampled(self):
        """A batch mixing temperature=0 and temperature>0 rows: the
        greedy row must be bit-identical to an all-greedy server's
        output (its neighbors' sampling must not perturb it)."""
        eng = _engine(slots=2)
        greedy_rid = eng.submit([1, 2, 3, 4], temperature=0.0)
        eng.submit([5, 6, 7], temperature=1.5)
        out = eng.run()

        ref = _engine(slots=2)
        rid2 = ref.submit([1, 2, 3, 4])
        want = ref.run()[rid2]
        assert out[greedy_rid] == want

    def test_per_request_temperature_on_paged(self):
        from kubeflow_tpu.models.paged import PagedBatcher

        pb = PagedBatcher(PARAMS, CFG,
                          gen=GenerationConfig(max_new_tokens=6,
                                               temperature=1.0),
                          slots=2, num_blocks=32, block_size=16,
                          prompt_bucket=16)
        rid = pb.submit([1, 2, 3, 4], temperature=0.0)
        pb.submit([5, 6, 7])  # engine-default sampled
        out = pb.run()

        ref = PagedBatcher(PARAMS, CFG,
                           gen=GenerationConfig(max_new_tokens=6),
                           slots=2, num_blocks=32, block_size=16,
                           prompt_bucket=16)
        ref_rid = ref.submit([1, 2, 3, 4])
        assert out[rid] == ref.run()[ref_rid]

    def test_speculative_rejects_per_request_temperature(self):
        from kubeflow_tpu.models.speculative import (
            SpeculativeContinuousBatcher, truncated_draft,
        )

        draft, dcfg = truncated_draft(PARAMS, CFG, 1)
        spec = SpeculativeContinuousBatcher(
            PARAMS, CFG, draft, dcfg, gen=GenerationConfig(max_new_tokens=4),
            slots=2, cache_len=128, prompt_bucket=16, k_spec=2,
        )
        with pytest.raises(ValueError, match="greedy-only"):
            spec._engine.submit([1, 2, 3], temperature=0.7)
        # the public wrapper surface gives the SAME clean error
        with pytest.raises(ValueError, match="greedy-only"):
            spec.submit([1, 2, 3], temperature=0.7)

    def test_http_temperature_and_n(self, server):
        # n greedy samples are identical; the response carries n choices
        out = _post(server.port, {"prompt": [1, 2, 3], "n": 3,
                                  "temperature": 0})
        assert len(out["choices"]) == 3
        assert [c["index"] for c in out["choices"]] == [0, 1, 2]
        toks = {str(c["tokens"]) for c in out["choices"]}
        assert len(toks) == 1  # greedy => identical
        assert out["usage"]["completion_tokens"] == sum(
            len(c["tokens"]) for c in out["choices"]
        )

    def test_http_rejects_bad_sampling_params(self, server):
        for payload in (
            {"prompt": [1], "temperature": -1},
            {"prompt": [1], "temperature": float("nan")},
            {"prompt": [1], "temperature": float("inf")},
            {"prompt": [1], "temperature": "hot"},
            {"prompt": [1], "n": 0},
            {"prompt": [1], "n": "three"},
            {"prompt": [1], "n": 2, "stream": True},
        ):
            with pytest.raises(urllib.error.HTTPError) as err:
                _post(server.port, payload)
            assert err.value.code == 400, payload


class TestStopAndBias:
    def test_stop_sequence_truncates_and_excludes(self):
        """Stop at the greedy continuation's own tokens: output ends
        BEFORE the stop sequence (OpenAI semantics)."""
        eng = _engine()
        rid = eng.submit([1, 2, 3, 4])
        full = eng.run()[rid]
        assert len(full) >= 4
        stop_seq = full[2:4]
        eng2 = _engine()
        rid2 = eng2.submit([1, 2, 3, 4], stop=[stop_seq])
        got = eng2.run()[rid2]
        assert got == full[:2]

    def test_stop_carries_through_paged_preemption(self):
        from kubeflow_tpu.models.paged import PagedBatcher

        pb = PagedBatcher(PARAMS, CFG, gen=GenerationConfig(max_new_tokens=6),
                          slots=2, num_blocks=32, block_size=16,
                          prompt_bucket=16)
        rid = pb.submit([1, 2, 3], stop=[[99999]])
        pb._admit_free_slots()
        slot = next(i for i, r in enumerate(pb._by_slot) if r is not None)
        pb._preempt(slot)
        assert pb._queue[0].stop == ((99999,),)
        assert pb._queue[0].logit_bias is None

    def test_logit_bias_forces_and_bans(self):
        """+100 forces a token under greedy; banning the greedy token
        changes the output."""
        eng = _engine()
        rid = eng.submit([1, 2, 3], max_new_tokens=4,
                         logit_bias={7: 100.0})
        assert eng.run()[rid] == [7, 7, 7, 7]

        base = _engine()
        b_rid = base.submit([1, 2, 3], max_new_tokens=1)
        first = base.run()[b_rid][0]
        banned = _engine()
        n_rid = banned.submit([1, 2, 3], max_new_tokens=1,
                              logit_bias={first: -100.0})
        assert banned.run()[n_rid][0] != first

    def test_unbiased_neighbor_unaffected(self):
        """A biased row must not perturb its unbiased neighbor (zeroed
        rows in the bias array, not stale ones)."""
        ref = _engine(slots=2)
        r = ref.submit([5, 6, 7], max_new_tokens=4)
        want = ref.run()[r]
        eng = _engine(slots=2)
        eng.submit([1, 2, 3], max_new_tokens=4, logit_bias={7: 100.0})
        rid = eng.submit([5, 6, 7], max_new_tokens=4)
        assert eng.run()[rid] == want

    def test_submit_validates_bias(self):
        eng = _engine()
        with pytest.raises(ValueError, match="vocab"):
            eng.submit([1], logit_bias={10**7: 1.0})
        with pytest.raises(ValueError, match="finite"):
            eng.submit([1], logit_bias={5: float("nan")})

    def test_speculative_rejects_bias(self):
        from kubeflow_tpu.models.speculative import (
            SpeculativeContinuousBatcher, truncated_draft,
        )

        draft, dcfg = truncated_draft(PARAMS, CFG, 1)
        spec = SpeculativeContinuousBatcher(
            PARAMS, CFG, draft, dcfg, gen=GenerationConfig(max_new_tokens=4),
            slots=2, cache_len=128, prompt_bucket=16, k_spec=2,
        )
        with pytest.raises(ValueError, match="logit_bias"):
            spec.submit([1, 2, 3], logit_bias={5: 1.0})

    def test_http_stop_and_bias(self, server):
        out = _post(server.port, {"prompt": [1, 2, 3], "max_tokens": 4,
                                  "logit_bias": {"7": 100}})
        assert out["choices"][0]["tokens"] == [7, 7, 7, 7]
        out2 = _post(server.port, {"prompt": [1, 2, 3], "max_tokens": 4,
                                   "logit_bias": {"7": 100},
                                   "stop": [7, 7]})
        assert out2["choices"][0]["tokens"] == []
        for bad in ({"prompt": [1], "stop": "text"},  # needs tokenizer
                    {"prompt": [1], "logit_bias": ["x"]},
                    {"prompt": [1], "logit_bias": {"abc": 1}},
                    {"prompt": [1], "stop": [[]]}):
            with pytest.raises(urllib.error.HTTPError) as err:
                _post(server.port, bad)
            assert err.value.code == 400, bad


def test_stop_sequence_length_bounded():
    """An unbounded stop sequence would make every decode step do an
    O(len) compare under the engine lock — reject like other inputs."""
    eng = _engine()
    with pytest.raises(ValueError, match="64"):
        eng.submit([1], stop=[[0] * 100000])


class TestLogprobs:
    def test_engine_logprobs_align_with_tokens(self):
        eng = _engine()
        rid = eng.submit([1, 2, 3], max_new_tokens=5)
        toks = eng.run()[rid]
        lps = eng.run_logprobs()[rid]
        assert len(lps) == len(toks)
        assert all(lp <= 0.0 for lp in lps)

    def test_stop_truncation_trims_logprobs_too(self):
        eng = _engine()
        rid = eng.submit([1, 2, 3, 4])
        full = eng.run()[rid]
        eng2 = _engine()
        rid2 = eng2.submit([1, 2, 3, 4], stop=[full[2:4]])
        toks = eng2.run()[rid2]
        assert len(eng2.run_logprobs()[rid2]) == len(toks) == 2

    def test_http_logprobs(self, server):
        out = _post(server.port, {"prompt": [1, 2, 3], "max_tokens": 4,
                                  "logprobs": True})
        ch = out["choices"][0]
        assert len(ch["logprobs"]["token_logprobs"]) == len(ch["tokens"])
        assert all(lp <= 0 for lp in ch["logprobs"]["token_logprobs"])
        plain = _post(server.port, {"prompt": [1, 2, 3], "max_tokens": 4})
        assert "logprobs" not in plain["choices"][0]


def test_logprobs_rejected_where_unsupported(server):
    with pytest.raises(urllib.error.HTTPError) as err:
        _post(server.port, {"prompt": [1], "logprobs": True,
                            "stream": True})
    assert err.value.code == 400

    from kubeflow_tpu.models.speculative import (
        SpeculativeContinuousBatcher, truncated_draft,
    )

    draft, dcfg = truncated_draft(PARAMS, CFG, 1)
    spec = SpeculativeContinuousBatcher(
        PARAMS, CFG, draft, dcfg, gen=GenerationConfig(max_new_tokens=4),
        slots=2, cache_len=128, prompt_bucket=16, k_spec=2,
    )
    srv = InferenceServer(spec, port=0).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(srv.port, {"prompt": [1, 2], "logprobs": True})
        assert err.value.code == 400
    finally:
        srv.stop()


def test_stats_latency_metrics(server):
    for _ in range(3):
        _post(server.port, {"prompt": [1, 2, 3], "max_tokens": 3})
    stats = _get(server.port, "/stats")
    assert stats["tokens_generated"] >= 9
    assert stats["ttft_s"]["p50"] is not None and stats["ttft_s"]["p50"] > 0
    assert stats["e2e_latency_s"]["p95"] >= stats["e2e_latency_s"]["p50"]
    assert stats["tokens_per_sec_lifetime"] > 0


class TestChunkedAdmission:
    def test_token_parity_with_one_shot_admission(self):
        """Chunked admission must emit EXACTLY the one-shot batcher's
        tokens — chunk-causal prefill is numerically the same prefill."""
        prompts = [[1, 2, 3, 4, 5], [7, 8, 9], [11, 12, 13, 14, 15, 16]]
        ref = _engine(slots=2)
        rids = [ref.submit(p) for p in prompts]
        want = ref.run()
        chunked = _engine(slots=2, admit_chunk=4)  # bucket 16 = 4 pieces
        rids2 = [chunked.submit(p) for p in prompts]
        got = chunked.run()
        for r1, r2 in zip(rids, rids2):
            assert got[r2] == want[r1]

    def test_decode_interleaves_with_admission(self):
        """While one slot's admission is mid-flight, the other slot's
        decode steps keep running — the feature's whole point."""
        eng = _engine(slots=2, admit_chunk=4,
                      gen=GenerationConfig(max_new_tokens=12))
        r1 = eng.submit([1, 2, 3])
        # Drive until r1 is decoding, then submit r2 and count r1's
        # progress during r2's 4-piece admission.
        while eng._by_slot[0] is None:
            eng._admit_free_slots()
        r1_req = eng._by_slot[0]
        eng.submit([5, 6, 7, 8])
        before = len(r1_req.tokens)
        for _ in range(4):  # four admission pieces
            eng._admit_free_slots()
            eng._step()
        after = len(r1_req.tokens)
        assert after - before >= 3, "decode stalled during admission"
        out = eng.run()
        assert len(out[r1]) == 12

    def test_validation(self):
        with pytest.raises(ValueError, match="admit_chunk"):
            _engine(admit_chunk=5)  # does not divide bucket 16
        from kubeflow_tpu.models.multilora import (
            MultiLoraBatcher, stack_adapters,
        )
        from kubeflow_tpu.models.lora import LoraConfig, init_lora_params

        lcfg = LoraConfig(rank=4)
        ad = init_lora_params(CFG, lcfg, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="admit_chunk"):
            MultiLoraBatcher(PARAMS, CFG,
                             stack_adapters([ad], CFG, lcfg), lcfg,
                             admit_chunk=4)

    def test_int8_kv_chunked_admission_parity(self):
        ref = _engine(slots=2, kv_bits=8)
        rid = ref.submit([1, 2, 3, 4])
        want = ref.run()[rid]
        eng = _engine(slots=2, kv_bits=8, admit_chunk=8)
        rid2 = eng.submit([1, 2, 3, 4])
        assert eng.run()[rid2] == want
