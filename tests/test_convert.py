"""HF checkpoint conversion: numerics parity against transformers.

The strongest correctness check in the model stack: the same weights must
produce the same logits through our JAX forward as through HF's torch
implementation — covering RoPE convention, GQA head layout, RMSNorm
placement, and the stacked-scan refactor all at once.
"""

from __future__ import annotations

import numpy as np
import pytest

from kubeflow_tpu.models import llama as L
from kubeflow_tpu.models.convert import (
    config_from_hf,
    load_hf_checkpoint,
    params_from_hf_state_dict,
    params_to_hf_state_dict,
)

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def _tiny_hf(n_kv_heads: int = 4, tie: bool = False):
    hf_cfg = transformers.LlamaConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=n_kv_heads,
        max_position_embeddings=128,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        tie_word_embeddings=tie,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(hf_cfg).eval()
    return hf_cfg, model


def _parity_case(n_kv_heads: int, tie: bool = False):
    hf_cfg, model = _tiny_hf(n_kv_heads, tie)
    cfg = config_from_hf(hf_cfg)
    # f32 end-to-end so the comparison tests math, not rounding.
    cfg = L.LlamaConfig(**{**cfg.__dict__, "dtype": np.float32})
    params = params_from_hf_state_dict(cfg, model.state_dict(), np.float32)

    tokens = np.array([[3, 17, 250, 42, 7, 99, 1, 128]], np.int32)
    with torch.no_grad():
        ref = model(torch.from_numpy(tokens).long()).logits.numpy()
    ours = np.asarray(L.forward(params, cfg, tokens))
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)
    return cfg, params, model, tokens


def test_forward_matches_transformers_mha():
    _parity_case(n_kv_heads=4)


def test_forward_matches_transformers_gqa():
    _parity_case(n_kv_heads=2)


def test_tied_embeddings_checkpoint_loads():
    """Real tied checkpoints (safetensors save) STRIP lm_head.weight; the
    loader must fall back to the embedding matrix."""
    hf_cfg, model = _tiny_hf(n_kv_heads=4, tie=True)
    cfg = config_from_hf(hf_cfg)
    sd = dict(model.state_dict())
    sd.pop("lm_head.weight", None)  # what save_pretrained does for tied
    params = params_from_hf_state_dict(cfg, sd, np.float32)
    # Tied trees carry ONE storage: no separate lm_head leaf.
    assert "lm_head" not in params
    tokens = np.array([[3, 17, 250, 42]], np.int32)
    f32_cfg = L.LlamaConfig(**{**cfg.__dict__, "dtype": np.float32})
    with torch.no_grad():
        ref = model(torch.from_numpy(tokens).long()).logits.numpy()
    ours = np.asarray(L.forward(params, f32_cfg, tokens))
    np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)


def test_rope_scaling_llama3_matches_transformers():
    """Llama-3.1-style rope_scaling must be applied, not dropped."""
    hf_cfg = transformers.LlamaConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=256,
        rope_theta=10000.0,
        rope_scaling={
            "rope_type": "llama3",
            "factor": 4.0,
            "low_freq_factor": 1.0,
            "high_freq_factor": 4.0,
            "original_max_position_embeddings": 64,
        },
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(hf_cfg).eval()
    cfg = config_from_hf(hf_cfg)
    assert cfg.rope_scaling is not None and cfg.rope_scaling.factor == 4.0
    f32_cfg = L.LlamaConfig(**{**cfg.__dict__, "dtype": np.float32})
    params = params_from_hf_state_dict(f32_cfg, model.state_dict(), np.float32)
    # Positions past original_max_position_embeddings exercise the
    # stretched low-frequency regime.
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 256, (1, 96)).astype(np.int32)
    with torch.no_grad():
        ref = model(torch.from_numpy(tokens).long()).logits.numpy()
    ours = np.asarray(L.forward(params, f32_cfg, tokens))
    np.testing.assert_allclose(ours, ref, rtol=3e-4, atol=3e-4)


def test_unsupported_rope_scaling_raises():
    with pytest.raises(NotImplementedError, match="rope_scaling"):
        config_from_hf(
            {
                "vocab_size": 256,
                "hidden_size": 64,
                "num_hidden_layers": 2,
                "num_attention_heads": 4,
                "intermediate_size": 128,
                "rope_scaling": {"rope_type": "yarn", "factor": 2.0},
            }
        )


def test_greedy_generation_matches_transformers():
    cfg, params, model, tokens = _parity_case(n_kv_heads=2)
    steps = 8
    with torch.no_grad():
        ref = model.generate(
            torch.from_numpy(tokens).long(),
            max_new_tokens=steps,
            do_sample=False,
            num_beams=1,
        ).numpy()[:, tokens.shape[1]:]
    ours = np.asarray(
        L.generate(params, cfg, tokens, steps=steps,
                   cache_len=tokens.shape[1] + steps)
    )
    np.testing.assert_array_equal(ours, ref)


def test_mistral_sliding_window_matches_transformers():
    """Sequence LONGER than the window exercises the sliding mask."""
    hf_cfg = transformers.MistralConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        sliding_window=8,
        rope_theta=10000.0,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.MistralForCausalLM(hf_cfg).eval()
    cfg = config_from_hf(hf_cfg)
    assert cfg.sliding_window == 8
    f32_cfg = L.LlamaConfig(**{**cfg.__dict__, "dtype": np.float32})
    params = params_from_hf_state_dict(f32_cfg, model.state_dict(), np.float32)
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, 256, (1, 32)).astype(np.int32)  # 32 >> window 8
    with torch.no_grad():
        ref = model(torch.from_numpy(tokens).long()).logits.numpy()
    ours = np.asarray(L.forward(params, f32_cfg, tokens))
    np.testing.assert_allclose(ours, ref, rtol=3e-4, atol=3e-4)
    # Cached decode applies the same window.
    steps = 6
    with torch.no_grad():
        ref_toks = model.generate(
            torch.from_numpy(tokens).long(), max_new_tokens=steps,
            do_sample=False, num_beams=1,
        ).numpy()[:, tokens.shape[1]:]
    ours_toks = np.asarray(
        L.generate(params, f32_cfg, tokens, steps=steps,
                   cache_len=tokens.shape[1] + steps)
    )
    np.testing.assert_array_equal(ours_toks, ref_toks)


def test_gemma_matches_transformers():
    """Gemma: GeGLU + (1+w) norms + scaled/tied embeddings + head_dim
    decoupled from dim//n_heads."""
    hf_cfg = transformers.GemmaConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=1,
        head_dim=32,  # != 64/4
        max_position_embeddings=128,
        rope_theta=10000.0,
        hidden_activation="gelu_pytorch_tanh",
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.GemmaForCausalLM(hf_cfg).eval()
    cfg = config_from_hf(hf_cfg)
    assert cfg.act == "gelu" and cfg.norm_add_unit and cfg.embed_scale
    assert cfg.head_dim == 32 and cfg.tie_embeddings
    f32_cfg = L.LlamaConfig(**{**cfg.__dict__, "dtype": np.float32})
    sd = dict(model.state_dict())
    sd.pop("lm_head.weight", None)  # tied
    params = params_from_hf_state_dict(f32_cfg, sd, np.float32)
    rng = np.random.default_rng(2)
    tokens = rng.integers(0, 256, (1, 16)).astype(np.int32)
    with torch.no_grad():
        ref = model(torch.from_numpy(tokens).long()).logits.numpy()
    ours = np.asarray(L.forward(params, f32_cfg, tokens))
    np.testing.assert_allclose(ours, ref, rtol=3e-4, atol=3e-4)


def test_qwen2_attention_bias_matches_transformers():
    """Qwen2: llama layout + biases on the q/k/v projections."""
    hf_cfg = transformers.Qwen2Config(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        rope_theta=10000.0,
        attn_implementation="eager",
        use_sliding_window=False,
        tie_word_embeddings=False,
    )
    torch.manual_seed(0)
    model = transformers.Qwen2ForCausalLM(hf_cfg).eval()
    # HF inits biases to zero; randomize them so the parity check actually
    # exercises the bias math (real checkpoints have nonzero biases).
    with torch.no_grad():
        for name, p in model.named_parameters():
            if name.endswith("_proj.bias"):
                p.copy_(torch.randn_like(p) * 0.5)
    cfg = config_from_hf(hf_cfg)
    assert cfg.attn_bias and cfg.sliding_window == 0
    f32_cfg = L.LlamaConfig(**{**cfg.__dict__, "dtype": np.float32})
    params = params_from_hf_state_dict(f32_cfg, model.state_dict(), np.float32)
    assert "bq" in params["layers"]
    assert float(np.abs(np.asarray(params["layers"]["bq"])).max()) > 0
    rng = np.random.default_rng(4)
    tokens = rng.integers(0, 256, (1, 12)).astype(np.int32)
    with torch.no_grad():
        ref = model(torch.from_numpy(tokens).long()).logits.numpy()
    ours = np.asarray(L.forward(params, f32_cfg, tokens))
    np.testing.assert_allclose(ours, ref, rtol=3e-4, atol=3e-4)
    # Round-trip export includes the biases.
    exported = params_to_hf_state_dict(f32_cfg, params)
    np.testing.assert_allclose(
        exported["model.layers.0.self_attn.q_proj.bias"],
        model.state_dict()["model.layers.0.self_attn.q_proj.bias"].numpy(),
        rtol=1e-6, atol=1e-6,
    )


def test_qwen2_sliding_window_semantics():
    """HF qwen2 windows only layers >= max_window_layers: the default
    (cutoff == n_layers) means NO window even with use_sliding_window."""
    base = {
        "model_type": "qwen2", "vocab_size": 64, "hidden_size": 64,
        "num_hidden_layers": 4, "num_attention_heads": 4,
        "intermediate_size": 128, "sliding_window": 512,
        "use_sliding_window": True,
    }
    assert config_from_hf({**base, "max_window_layers": 4}).sliding_window == 0
    assert config_from_hf({**base, "max_window_layers": 0}).sliding_window == 512
    with pytest.raises(NotImplementedError, match="max_window_layers"):
        config_from_hf({**base, "max_window_layers": 2})
    # use_sliding_window absent → no window regardless.
    off = dict(base)
    del off["use_sliding_window"]
    assert config_from_hf(off).sliding_window == 0


def test_unsupported_model_type_raises():
    with pytest.raises(NotImplementedError, match="model_type"):
        config_from_hf({"model_type": "phi3", "num_attention_heads": 4,
                        "hidden_size": 64})


def test_config_mapping_fields():
    hf_cfg, _ = _tiny_hf(n_kv_heads=2)
    cfg = config_from_hf(hf_cfg)
    assert cfg.vocab_size == 256
    assert cfg.dim == 64
    assert cfg.n_layers == 2
    assert cfg.n_heads == 4
    assert cfg.n_kv_heads == 2
    assert cfg.ffn_hidden == 128
    assert cfg.head_dim == 16


def test_round_trip_export():
    hf_cfg, model = _tiny_hf()
    cfg = config_from_hf(hf_cfg)
    params = params_from_hf_state_dict(cfg, model.state_dict(), np.float32)
    exported = params_to_hf_state_dict(cfg, params)
    sd = model.state_dict()
    for key, value in exported.items():
        np.testing.assert_allclose(
            value, sd[key].float().numpy(), rtol=1e-6, atol=1e-6
        )


def test_missing_tensor_error_is_actionable():
    hf_cfg, model = _tiny_hf()
    cfg = config_from_hf(hf_cfg)
    sd = dict(model.state_dict())
    del sd["model.layers.1.mlp.up_proj.weight"]
    with pytest.raises(KeyError, match="missing 'model.layers.1.mlp.up_proj"):
        params_from_hf_state_dict(cfg, sd)


def test_load_hf_checkpoint_directory(tmp_path):
    hf_cfg, model = _tiny_hf(n_kv_heads=2)
    model.save_pretrained(tmp_path, safe_serialization=True)
    cfg, params = load_hf_checkpoint(tmp_path, dtype=np.float32)
    assert cfg.n_kv_heads == 2
    ref = params_from_hf_state_dict(
        config_from_hf(hf_cfg), model.state_dict(), np.float32
    )
    np.testing.assert_allclose(
        np.asarray(params["layers"]["wq"]),
        np.asarray(ref["layers"]["wq"]),
        rtol=1e-6,
        atol=1e-6,
    )
