"""int8 KV cache: storage-format quantization for long-context decode.

The cache pytree's structure (scale leaves) drives the format; writes
quantize per (head, position), attention dequantizes in the score/value
einsum epilogues. Prefill attention runs on fresh full-precision K/V —
only what later steps read back is quantized, so the first generated
token is bit-identical and later logits drift only by quantization
error."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models import llama as L


@pytest.fixture(scope="module")
def tiny():
    cfg = L.LLAMA_CONFIGS["tiny"]
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _cache_nbytes(cache):
    return sum(x.nbytes for x in jax.tree_util.tree_leaves(cache))


class TestInt8KVCache:
    def test_structure_and_size(self, tiny):
        cfg, _ = tiny
        full = L.init_kv_cache(cfg, 2, 128)
        q8 = L.init_kv_cache(cfg, 2, 128, kv_bits=8)
        assert q8["k"].dtype == jnp.int8
        assert q8["k_scale"].dtype == jnp.bfloat16
        assert q8["k_scale"].shape == q8["k"].shape[:-1]
        # ~half the bytes (int8 values + 2/head_dim scale overhead).
        ratio = _cache_nbytes(q8) / _cache_nbytes(full)
        assert ratio < 0.6, ratio

    def test_rejects_unknown_bits(self, tiny):
        cfg, _ = tiny
        with pytest.raises(ValueError, match="kv_bits"):
            L.init_kv_cache(cfg, 1, 16, kv_bits=4)

    def test_first_token_bit_identical(self, tiny):
        """Prefill attention never reads the quantized storage, so the
        first sampled token (from prefill logits) matches exactly."""
        cfg, params = tiny
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0,
                                    cfg.vocab_size)
        lt_full, _ = L._prefill_impl(params, cfg, prompt,
                                     L.init_kv_cache(cfg, 2, 32))
        lt_q8, _ = L._prefill_impl(params, cfg, prompt,
                                   L.init_kv_cache(cfg, 2, 32, kv_bits=8))
        np.testing.assert_array_equal(np.asarray(lt_full), np.asarray(lt_q8))

    def test_decode_logits_within_quantization_error(self, tiny):
        """Feed the SAME tokens through bf16-cache and int8-cache decode;
        per-step logits must stay close (int8 cache error, not a wiring
        bug — a masking/pointer mistake shows up orders of magnitude
        larger)."""
        cfg, params = tiny
        prompt = jax.random.randint(jax.random.PRNGKey(2), (1, 10), 0,
                                    cfg.vocab_size)
        ref_logits, ref_cache = L._prefill_impl(
            params, cfg, prompt, L.init_kv_cache(cfg, 1, 32))
        q8_logits, q8_cache = L._prefill_impl(
            params, cfg, prompt, L.init_kv_cache(cfg, 1, 32, kv_bits=8))
        tok = jnp.argmax(ref_logits, axis=-1)[:, None]
        pos = jnp.asarray(10, jnp.int32)
        for step in range(4):
            ref_logits, ref_cache = L._decode_impl(
                params, cfg, tok, ref_cache, pos)
            q8_logits, q8_cache = L._decode_impl(
                params, cfg, tok, q8_cache, pos)
            diff = float(jnp.max(jnp.abs(ref_logits - q8_logits)))
            spread = float(jnp.max(ref_logits) - jnp.min(ref_logits))
            assert diff < 0.05 * max(spread, 1.0), (step, diff, spread)
            tok = jnp.argmax(ref_logits, axis=-1)[:, None]
            pos = pos + 1

    def test_generate_kv8_runs_full_pipeline(self, tiny):
        """The fused generate loop accepts kv_bits=8 end to end and mostly
        tracks the full-precision greedy path on a tiny model."""
        cfg, params = tiny
        prompt = jax.random.randint(jax.random.PRNGKey(3), (1, 8), 0,
                                    cfg.vocab_size)
        full = np.asarray(L.generate(params, cfg, prompt, steps=16,
                                     cache_len=64))
        q8 = np.asarray(L.generate(params, cfg, prompt, steps=16,
                                   cache_len=64, kv_bits=8))
        assert q8.shape == full.shape
        # Greedy paths may legitimately fork after a near-tie; demand
        # agreement on a clear majority, not exactness.
        agree = (full == q8).mean()
        assert agree >= 0.5, f"only {agree:.0%} token agreement"

    def test_batched_per_row_store_quantized(self, tiny):
        """The per-row store (batched speculative path) round-trips
        through the quantized format too."""
        cfg, params = tiny
        cache = L.init_kv_cache(cfg, 2, 32, kv_bits=8)
        toks = jax.random.randint(jax.random.PRNGKey(4), (2, 3), 0,
                                  cfg.vocab_size)
        positions = jnp.asarray([0, 5], jnp.int32)
        logits, cache = L._decode_chunk_batch_impl(
            params, cfg, toks, cache, positions)
        assert logits.shape == (2, 3, cfg.vocab_size)
        # Row 1's rows landed at offset 5, row 0's at 0.
        ks = np.asarray(cache["k_scale"][0])  # layer 0: (B, Hkv, C)
        assert (ks[0, :, 0:3] > 0).all() and (ks[0, :, 3:] == 0).all()
        assert (ks[1, :, 5:8] > 0).all() and (ks[1, :, 0:5] == 0).all()


class TestServingInt8KV:
    """int8 KV through the SERVING stack — every consumer that builds a
    cache accepts kv_bits and keeps (near-)greedy parity with its bf16
    twin. The batched server's cache is exactly the HBM pressure int8 KV
    exists to halve, so the format must reach it, not just bs=1
    generate()."""

    def _agreement(self, a: list, b: list) -> float:
        """Positionwise token agreement over the common prefix length
        (greedy paths may legitimately fork after a near-tie)."""
        n = min(len(a), len(b))
        if n == 0:
            return 1.0
        return sum(x == y for x, y in zip(a[:n], b[:n])) / n

    def _prompts(self, cfg, n, key=61):
        ks = jax.random.split(jax.random.PRNGKey(key), n)
        return [
            [int(t) for t in
             jax.random.randint(k, (4 + 2 * i,), 3, cfg.vocab_size)]
            for i, k in enumerate(ks)
        ]

    def test_batch_generate_kv8(self, tiny):
        from kubeflow_tpu.models.serving import (
            GenerationConfig, batch_generate,
        )

        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=12, eos_id=-1)
        prompts = self._prompts(cfg, 3)
        full = batch_generate(params, cfg, prompts, gen=gen, pad_to=16)
        q8 = batch_generate(params, cfg, prompts, gen=gen, pad_to=16,
                            kv_bits=8)
        assert [len(r) for r in q8] == [len(r) for r in full]
        agree = np.mean([self._agreement(a, b) for a, b in zip(full, q8)])
        assert agree >= 0.5, f"only {agree:.0%} token agreement"

    def test_continuous_batcher_kv8(self, tiny):
        from kubeflow_tpu.models.continuous import ContinuousBatcher
        from kubeflow_tpu.models.serving import GenerationConfig

        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=8, eos_id=-1)
        prompts = self._prompts(cfg, 4)

        def run(kv_bits):
            cb = ContinuousBatcher(params, cfg, gen=gen, slots=2,
                                   cache_len=64, prompt_bucket=16,
                                   kv_bits=kv_bits)
            rids = [cb.submit(p) for p in prompts]
            out = cb.run()
            return cb, [out[r] for r in rids]

        cb8, q8 = run(8)
        assert cb8.cache["k"].dtype == jnp.int8
        assert "k_scale" in cb8.cache
        _, full = run(0)
        agree = np.mean([self._agreement(a, b) for a, b in zip(full, q8)])
        assert agree >= 0.5, f"only {agree:.0%} token agreement"

    def test_paged_batcher_kv8(self, tiny):
        from kubeflow_tpu.models.paged import PagedBatcher
        from kubeflow_tpu.models.serving import GenerationConfig

        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=8, eos_id=-1)
        prompts = self._prompts(cfg, 4)

        def run(kv_bits):
            pb = PagedBatcher(params, cfg, gen=gen, slots=2,
                              num_blocks=24, block_size=8,
                              prompt_bucket=16, kv_bits=kv_bits)
            rids = [pb.submit(p) for p in prompts]
            out = pb.run()
            return pb, [out[r] for r in rids]

        pb8, q8 = run(8)
        assert pb8.pool["k"].dtype == jnp.int8
        assert pb8.free_blocks == 23  # all returned after the run
        _, full = run(0)
        agree = np.mean([self._agreement(a, b) for a, b in zip(full, q8)])
        assert agree >= 0.5, f"only {agree:.0%} token agreement"

    def test_paged_int8_preemption_continuation(self, tiny):
        """Preempt/re-admit (the paged recovery path) works with the int8
        pool too: a deliberately starved pool forces preemptions and
        every request still completes its budget."""
        from kubeflow_tpu.models.paged import PagedBatcher
        from kubeflow_tpu.models.serving import GenerationConfig

        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=10, eos_id=-1)
        pb = PagedBatcher(params, cfg, gen=gen, slots=3, num_blocks=10,
                          block_size=8, prompt_bucket=8, kv_bits=8)
        prompts = self._prompts(cfg, 3, key=67)
        rids = [pb.submit(p[:6]) for p in prompts]
        out = pb.run()
        assert all(len(out[r]) == 10 for r in rids)

    def test_sharded_continuous_kv8_tracks_single_device(self, tiny):
        """tp/sp-sharded int8 serving tracks single-device int8 serving.
        Quantization itself is deterministic and the sp split-KV merge
        carries the scale shards with their values, but tp changes the
        psum reduction order of the activation matmuls FEEDING the cache,
        so a bf16 near-tie may legitimately fork the greedy stream —
        demand strong agreement, not byte-equality (the suite's standard
        for cross-reduction-order comparisons)."""
        from kubeflow_tpu.models.continuous import ContinuousBatcher
        from kubeflow_tpu.models.serving import GenerationConfig
        from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh

        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=6, eos_id=-1)
        prompts = self._prompts(cfg, 3, key=71)

        def run(plan):
            cb = ContinuousBatcher(params, cfg, gen=gen, slots=2,
                                   cache_len=64, prompt_bucket=16,
                                   plan=plan, kv_bits=8)
            rids = [cb.submit(p) for p in prompts]
            out = cb.run()
            return [out[r] for r in rids]

        want = run(None)
        plan = MeshPlan(make_mesh(tp=2, sp=2, devices=jax.devices()[:4]))
        got = run(plan)
        assert [len(r) for r in got] == [len(r) for r in want]
        agree = np.mean([self._agreement(a, b) for a, b in zip(want, got)])
        assert agree >= 0.5, f"only {agree:.0%} token agreement"
