"""Native + fallback token loaders: shape, determinism, cross-equivalence."""

from __future__ import annotations

import numpy as np
import pytest

from kubeflow_tpu.data import TokenLoader, write_token_file


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 32000, size=100_000, dtype=np.uint32)
    path = tmp_path_factory.mktemp("data") / "corpus.bin"
    write_token_file(path, tokens)
    return path, tokens


def test_native_loader_builds_and_samples(corpus):
    path, tokens = corpus
    with TokenLoader(path, batch=8, seq=128, seed=3) as loader:
        assert loader.native, "g++ is baked into this image; native must build"
        assert loader.n_tokens == 100_000
        batch = loader.next()
        assert batch.shape == (8, 128) and batch.dtype == np.int32
        # Every row must be a contiguous corpus window.
        for row in batch:
            starts = np.flatnonzero(tokens[: -128 + 1] == np.uint32(row[0]))
            assert any(
                np.array_equal(tokens[s : s + 128].astype(np.int32), row)
                for s in starts
            )


def test_python_fallback_matches_native_exactly(corpus):
    path, _ = corpus
    with TokenLoader(path, batch=4, seq=64, seed=7) as native:
        if not native.native:
            pytest.skip("no toolchain")
        py = TokenLoader(path, batch=4, seq=64, seed=7, force_python=True)
        assert not py.native
        for _ in range(5):
            np.testing.assert_array_equal(native.next(), py.next())


def test_determinism_per_seed(corpus):
    path, _ = corpus
    a = TokenLoader(path, batch=2, seq=32, seed=11, force_python=True)
    b = TokenLoader(path, batch=2, seq=32, seed=11, force_python=True)
    c = TokenLoader(path, batch=2, seq=32, seed=12, force_python=True)
    first_a, first_b, first_c = a.next(), b.next(), c.next()
    np.testing.assert_array_equal(first_a, first_b)
    assert not np.array_equal(first_a, first_c)


def test_corpus_too_small_rejected(tmp_path):
    path = write_token_file(tmp_path / "tiny.bin", np.arange(10, dtype=np.uint32))
    with pytest.raises(ValueError, match="tokens < seq"):
        TokenLoader(path, batch=1, seq=64)


def test_missing_file_rejected(tmp_path):
    with pytest.raises(FileNotFoundError):
        TokenLoader(tmp_path / "absent.bin", batch=1, seq=8)


def test_loader_feeds_train_step(corpus):
    """End-to-end: loader batches drive a jitted train step."""
    import jax

    from kubeflow_tpu.models import llama as L
    from kubeflow_tpu.models.train import make_train_step, shard_state
    from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh

    path, _ = corpus
    plan = MeshPlan(make_mesh(fsdp=2, tp=2, sp=2, devices=jax.devices()[:8]))
    cfg = L.LLAMA_CONFIGS["tiny"]
    init_state, step = make_train_step(cfg, plan)
    state = shard_state(plan, init_state(L.init_params(cfg, jax.random.PRNGKey(0))))
    with TokenLoader(path, batch=4, seq=128, seed=1) as loader:
        for batch in loader.batches(2):
            # tiny config's vocab is 512; fold the corpus ids into range.
            state, loss = step(state, (batch % cfg.vocab_size).astype(np.int32))
    assert np.isfinite(float(loss))


class TestShardedLoader:
    def test_per_process_shapes_and_disjoint_streams(self, tmp_path):
        from kubeflow_tpu.data.loader import sharded_loader, write_token_file

        p = tmp_path / "corpus.bin"
        write_token_file(p, np.arange(50000, dtype=np.uint32))
        loaders = [
            sharded_loader(p, 16, 32, process_id=i, num_processes=4,
                           force_python=True)
            for i in range(4)
        ]
        batches = [ld.next() for ld in loaders]
        assert all(b.shape == (4, 32) for b in batches)
        # Process-mixed seeds: no two hosts sample the same windows.
        for i in range(4):
            for j in range(i + 1, 4):
                assert not np.array_equal(batches[i], batches[j])

    def test_mid_epoch_restore_keeps_hosts_aligned(self, tmp_path):
        """Checkpoint-resume discipline across hosts: after restoring at a
        global batch cursor k, every host's stream must continue EXACTLY
        where its uninterrupted stream would be (no host replays or skips
        a batch relative to its peers), and cross-host rows must stay
        disjoint — a single mis-stepped host silently trains on the wrong
        global batch forever."""
        from kubeflow_tpu.data.loader import sharded_loader, write_token_file

        p = tmp_path / "corpus.bin"
        write_token_file(p, np.arange(50000, dtype=np.uint32))
        hosts, total, cursor = 4, 7, 3

        full = []
        for i in range(hosts):
            ld = sharded_loader(p, 16, 32, process_id=i, num_processes=hosts,
                                force_python=True)
            full.append([ld.next() for _ in range(total)])

        for i in range(hosts):
            resumed = sharded_loader(p, 16, 32, process_id=i,
                                     num_processes=hosts,
                                     start_batch=cursor, force_python=True)
            for k in range(cursor, total):
                np.testing.assert_array_equal(
                    resumed.next(), full[i][k],
                    err_msg=f"host {i} diverged at global batch {k}",
                )

        # Alignment preserved => disjointness preserved, post-restore too.
        for k in range(cursor, total):
            for i in range(hosts):
                for j in range(i + 1, hosts):
                    assert not np.array_equal(full[i][k], full[j][k])

    def test_indivisible_global_batch_rejected(self, tmp_path):
        from kubeflow_tpu.data.loader import sharded_loader, write_token_file

        p = tmp_path / "corpus.bin"
        write_token_file(p, np.arange(1000, dtype=np.uint32))
        with pytest.raises(ValueError, match="not divisible"):
            sharded_loader(p, 10, 8, process_id=0, num_processes=4)

    def test_device_put_global_shards_over_mesh(self, tmp_path):
        import jax
        from jax.sharding import PartitionSpec as P

        from kubeflow_tpu.data.loader import (
            device_put_global,
            sharded_loader,
            write_token_file,
        )
        from kubeflow_tpu.parallel.mesh import make_mesh

        p = tmp_path / "corpus.bin"
        write_token_file(p, np.arange(50000, dtype=np.uint32))
        mesh = make_mesh(dp=8)
        ld = sharded_loader(p, 8, 16, force_python=True)  # single process
        arr = device_put_global(ld.next().astype(np.int32), mesh, P("dp"))
        assert arr.shape == (8, 16)
        assert len(arr.sharding.device_set) == 8


class TestResumeSkip:
    def test_start_batch_continues_the_stream(self, tmp_path):
        """start_batch=k must reproduce exactly the batches after the
        k-th — for BOTH impls (exact-resume data discipline: a restored
        run must not re-read what the lost run consumed)."""
        import numpy as np

        from kubeflow_tpu.data import TokenLoader, write_token_file

        path = write_token_file(
            tmp_path / "corpus.bin", np.arange(4096, dtype=np.uint32)
        )
        for force_python in (False, True):
            full = TokenLoader(path, batch=3, seq=8,
                               force_python=force_python)
            want = [full.next() for _ in range(6)][4:]
            full.close()
            resumed = TokenLoader(path, batch=3, seq=8, start_batch=4,
                                  force_python=force_python)
            got = [resumed.next(), resumed.next()]
            resumed.close()
            for a, b in zip(want, got):
                np.testing.assert_array_equal(a, b)

    def test_native_and_python_skip_agree(self, tmp_path):
        import numpy as np

        from kubeflow_tpu.data import TokenLoader, write_token_file

        path = write_token_file(
            tmp_path / "corpus.bin", np.arange(4096, dtype=np.uint32)
        )
        nat = TokenLoader(path, batch=2, seq=16, start_batch=7)
        py = TokenLoader(path, batch=2, seq=16, start_batch=7,
                         force_python=True)
        if not nat.native:
            import pytest

            pytest.skip("native loader unavailable")
        for _ in range(3):
            np.testing.assert_array_equal(nat.next(), py.next())
        nat.close()
        py.close()

    def test_example_resume_skips_consumed_batches(self, tmp_path):
        """train_sharded --data resumes with start_batch (and the
        synthetic path folds the step into the key): the resumed run's
        losses must CONTINUE, not replay, which the loader-order check
        below pins down."""
        import numpy as np

        from kubeflow_tpu.data import TokenLoader, write_token_file

        path = write_token_file(
            tmp_path / "c.bin", np.arange(4096, dtype=np.uint32)
        )
        # Contract used by the example: loader(start_batch=s) yields the
        # same stream a fresh loader yields after s next() calls.
        fresh = TokenLoader(path, batch=4, seq=8, force_python=True)
        for _ in range(3):
            fresh.next()
        cont = fresh.next()
        fresh.close()
        res = TokenLoader(path, batch=4, seq=8, start_batch=3,
                          force_python=True)
        np.testing.assert_array_equal(cont, res.next())
        res.close()


class TestSkipInternals:
    def test_gf2_jump_matches_sequential(self):
        """The O(log n) matrix jump must be bit-identical to n sequential
        xorshift64 transitions for awkward n and states."""
        from kubeflow_tpu.data.loader import _MASK, _xorshift_skip

        def seq(state, n):
            for _ in range(n):
                state ^= state >> 12
                state = (state ^ (state << 25)) & _MASK
                state ^= state >> 27
            return state

        for state in (1, 0x9E3779B97F4A7C15, (1 << 63) | 5):
            for n in (0, 1, 2, 7, 63, 64, 1000):
                assert _xorshift_skip(state, n) == seq(state, n), (state, n)

    def test_stale_abi_library_is_rebuilt(self, tmp_path, monkeypatch):
        """A cached .so with the wrong (or missing) ABI version must be
        rebuilt, not silently used with mismatched argtypes."""
        import subprocess

        from kubeflow_tpu.data import loader as ld

        if ld._build_native() is None:
            import pytest

            pytest.skip("no toolchain")
        # Fake stale library: compiles, exports nothing matching v2.
        stale_src = tmp_path / "stale.cpp"
        stale_src.write_text('extern "C" int dl_abi_version() { return 1; }')
        stale_lib = tmp_path / "libstale.so"
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", str(stale_src), "-o",
             str(stale_lib)], check=True, capture_output=True,
        )
        real_lib = ld._LIB
        monkeypatch.setattr(ld, "_LIB", stale_lib)

        def rebuild(force=False):
            # The guard must ask for a FORCE rebuild on ABI mismatch;
            # hand it the real library then.
            return real_lib if force else stale_lib

        monkeypatch.setattr(ld, "_build_native", rebuild)
        lib = ld._load_native()
        assert lib is not None
        assert lib.dl_abi_version() == ld._ABI_VERSION

    def test_rebuild_at_same_path_escapes_dlopen_cache(
        self, tmp_path, monkeypatch
    ):
        """glibc dlopen caches handles per pathname: a rebuilt .so at the
        SAME path, re-CDLLed directly, hands back the already-mapped STALE
        library — the rebuild can then never succeed in the one process
        that needs it. The loader must load the rebuild under a fresh
        dlopen identity."""
        import ctypes
        import shutil
        import subprocess

        from kubeflow_tpu.data import loader as ld

        real = ld._build_native()
        if real is None:
            import pytest

            pytest.skip("no toolchain")
        stale_src = tmp_path / "stale.cpp"
        stale_src.write_text('extern "C" int dl_abi_version() { return 1; }')
        lib_path = tmp_path / "libcache.so"
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", str(stale_src), "-o",
             str(lib_path)], check=True, capture_output=True,
        )
        # Poison the per-path dlopen cache the way a real process does:
        # the first _load_native call maps the stale library.
        ctypes.CDLL(str(lib_path))
        monkeypatch.setattr(ld, "_LIB", lib_path)

        def rebuild(force=False):
            if force:
                # In-place rebuild at the SAME path (the scenario the
                # alias load exists for).
                shutil.copy2(real, lib_path)
            return lib_path

        monkeypatch.setattr(ld, "_build_native", rebuild)
        lib = ld._load_native()
        assert lib is not None
        assert lib.dl_abi_version() == ld._ABI_VERSION

    def test_negative_start_batch_rejected(self, tmp_path):
        """ctypes would wrap a negative into c_uint64 (the native skip
        then never terminates); the Python fallback would silently treat
        it as 0. Both are wrong answers to a corrupted resume offset —
        the loader must reject it up front."""
        import numpy as np
        import pytest

        from kubeflow_tpu.data import TokenLoader, write_token_file

        path = write_token_file(
            tmp_path / "c.bin", np.arange(1024, dtype=np.uint32)
        )
        for force_python in (False, True):
            with pytest.raises(ValueError, match="start_batch"):
                TokenLoader(path, batch=2, seq=8, start_batch=-1,
                            force_python=force_python)

    def test_deep_resume_is_fast_and_consistent(self, tmp_path):
        """Resuming a billion batches in must be an O(log n) jump on BOTH
        backends (an O(n) native loop would stall dl_open for minutes) and
        both must land on the same stream position."""
        import time

        import numpy as np

        from kubeflow_tpu.data import TokenLoader, write_token_file

        path = write_token_file(
            tmp_path / "c.bin", np.arange(4096, dtype=np.uint32)
        )
        t0 = time.monotonic()
        py = TokenLoader(path, batch=4, seq=8, start_batch=10**9,
                         force_python=True)
        nat = TokenLoader(path, batch=4, seq=8, start_batch=10**9)
        assert time.monotonic() - t0 < 30, "deep resume took O(n) time"
        if nat.native:
            np.testing.assert_array_equal(nat.next(), py.next())
        nat.close()
        py.close()
