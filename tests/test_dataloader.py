"""Native + fallback token loaders: shape, determinism, cross-equivalence."""

from __future__ import annotations

import numpy as np
import pytest

from kubeflow_tpu.data import TokenLoader, write_token_file


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 32000, size=100_000, dtype=np.uint32)
    path = tmp_path_factory.mktemp("data") / "corpus.bin"
    write_token_file(path, tokens)
    return path, tokens


def test_native_loader_builds_and_samples(corpus):
    path, tokens = corpus
    with TokenLoader(path, batch=8, seq=128, seed=3) as loader:
        assert loader.native, "g++ is baked into this image; native must build"
        assert loader.n_tokens == 100_000
        batch = loader.next()
        assert batch.shape == (8, 128) and batch.dtype == np.int32
        # Every row must be a contiguous corpus window.
        for row in batch:
            starts = np.flatnonzero(tokens[: -128 + 1] == np.uint32(row[0]))
            assert any(
                np.array_equal(tokens[s : s + 128].astype(np.int32), row)
                for s in starts
            )


def test_python_fallback_matches_native_exactly(corpus):
    path, _ = corpus
    with TokenLoader(path, batch=4, seq=64, seed=7) as native:
        if not native.native:
            pytest.skip("no toolchain")
        py = TokenLoader(path, batch=4, seq=64, seed=7, force_python=True)
        assert not py.native
        for _ in range(5):
            np.testing.assert_array_equal(native.next(), py.next())


def test_determinism_per_seed(corpus):
    path, _ = corpus
    a = TokenLoader(path, batch=2, seq=32, seed=11, force_python=True)
    b = TokenLoader(path, batch=2, seq=32, seed=11, force_python=True)
    c = TokenLoader(path, batch=2, seq=32, seed=12, force_python=True)
    first_a, first_b, first_c = a.next(), b.next(), c.next()
    np.testing.assert_array_equal(first_a, first_b)
    assert not np.array_equal(first_a, first_c)


def test_corpus_too_small_rejected(tmp_path):
    path = write_token_file(tmp_path / "tiny.bin", np.arange(10, dtype=np.uint32))
    with pytest.raises(ValueError, match="tokens < seq"):
        TokenLoader(path, batch=1, seq=64)


def test_missing_file_rejected(tmp_path):
    with pytest.raises(FileNotFoundError):
        TokenLoader(tmp_path / "absent.bin", batch=1, seq=8)


def test_loader_feeds_train_step(corpus):
    """End-to-end: loader batches drive a jitted train step."""
    import jax

    from kubeflow_tpu.models import llama as L
    from kubeflow_tpu.models.train import make_train_step, shard_state
    from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh

    path, _ = corpus
    plan = MeshPlan(make_mesh(fsdp=2, tp=2, sp=2, devices=jax.devices()[:8]))
    cfg = L.LLAMA_CONFIGS["tiny"]
    init_state, step = make_train_step(cfg, plan)
    state = shard_state(plan, init_state(L.init_params(cfg, jax.random.PRNGKey(0))))
    with TokenLoader(path, batch=4, seq=128, seed=1) as loader:
        for batch in loader.batches(2):
            # tiny config's vocab is 512; fold the corpus ids into range.
            state, loss = step(state, (batch % cfg.vocab_size).astype(np.int32))
    assert np.isfinite(float(loss))


class TestShardedLoader:
    def test_per_process_shapes_and_disjoint_streams(self, tmp_path):
        from kubeflow_tpu.data.loader import sharded_loader, write_token_file

        p = tmp_path / "corpus.bin"
        write_token_file(p, np.arange(50000, dtype=np.uint32))
        loaders = [
            sharded_loader(p, 16, 32, process_id=i, num_processes=4,
                           force_python=True)
            for i in range(4)
        ]
        batches = [ld.next() for ld in loaders]
        assert all(b.shape == (4, 32) for b in batches)
        # Process-mixed seeds: no two hosts sample the same windows.
        for i in range(4):
            for j in range(i + 1, 4):
                assert not np.array_equal(batches[i], batches[j])

    def test_indivisible_global_batch_rejected(self, tmp_path):
        from kubeflow_tpu.data.loader import sharded_loader, write_token_file

        p = tmp_path / "corpus.bin"
        write_token_file(p, np.arange(1000, dtype=np.uint32))
        with pytest.raises(ValueError, match="not divisible"):
            sharded_loader(p, 10, 8, process_id=0, num_processes=4)

    def test_device_put_global_shards_over_mesh(self, tmp_path):
        import jax
        from jax.sharding import PartitionSpec as P

        from kubeflow_tpu.data.loader import (
            device_put_global,
            sharded_loader,
            write_token_file,
        )
        from kubeflow_tpu.parallel.mesh import make_mesh

        p = tmp_path / "corpus.bin"
        write_token_file(p, np.arange(50000, dtype=np.uint32))
        mesh = make_mesh(dp=8)
        ld = sharded_loader(p, 8, 16, force_python=True)  # single process
        arr = device_put_global(ld.next().astype(np.int32), mesh, P("dp"))
        assert arr.shape == (8, 16)
        assert len(arr.sharding.device_set) == 8
