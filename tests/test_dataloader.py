"""Native + fallback token loaders: shape, determinism, cross-equivalence."""

from __future__ import annotations

import numpy as np
import pytest

from kubeflow_tpu.data import TokenLoader, write_token_file


@pytest.fixture(scope="module")
def corpus(tmp_path_factory):
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 32000, size=100_000, dtype=np.uint32)
    path = tmp_path_factory.mktemp("data") / "corpus.bin"
    write_token_file(path, tokens)
    return path, tokens


def test_native_loader_builds_and_samples(corpus):
    path, tokens = corpus
    with TokenLoader(path, batch=8, seq=128, seed=3) as loader:
        assert loader.native, "g++ is baked into this image; native must build"
        assert loader.n_tokens == 100_000
        batch = loader.next()
        assert batch.shape == (8, 128) and batch.dtype == np.int32
        # Every row must be a contiguous corpus window.
        for row in batch:
            starts = np.flatnonzero(tokens[: -128 + 1] == np.uint32(row[0]))
            assert any(
                np.array_equal(tokens[s : s + 128].astype(np.int32), row)
                for s in starts
            )


def test_python_fallback_matches_native_exactly(corpus):
    path, _ = corpus
    with TokenLoader(path, batch=4, seq=64, seed=7) as native:
        if not native.native:
            pytest.skip("no toolchain")
        py = TokenLoader(path, batch=4, seq=64, seed=7, force_python=True)
        assert not py.native
        for _ in range(5):
            np.testing.assert_array_equal(native.next(), py.next())


def test_determinism_per_seed(corpus):
    path, _ = corpus
    a = TokenLoader(path, batch=2, seq=32, seed=11, force_python=True)
    b = TokenLoader(path, batch=2, seq=32, seed=11, force_python=True)
    c = TokenLoader(path, batch=2, seq=32, seed=12, force_python=True)
    first_a, first_b, first_c = a.next(), b.next(), c.next()
    np.testing.assert_array_equal(first_a, first_b)
    assert not np.array_equal(first_a, first_c)


def test_corpus_too_small_rejected(tmp_path):
    path = write_token_file(tmp_path / "tiny.bin", np.arange(10, dtype=np.uint32))
    with pytest.raises(ValueError, match="tokens < seq"):
        TokenLoader(path, batch=1, seq=64)


def test_missing_file_rejected(tmp_path):
    with pytest.raises(FileNotFoundError):
        TokenLoader(tmp_path / "absent.bin", batch=1, seq=8)


def test_loader_feeds_train_step(corpus):
    """End-to-end: loader batches drive a jitted train step."""
    import jax

    from kubeflow_tpu.models import llama as L
    from kubeflow_tpu.models.train import make_train_step, shard_state
    from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh

    path, _ = corpus
    plan = MeshPlan(make_mesh(fsdp=2, tp=2, sp=2, devices=jax.devices()[:8]))
    cfg = L.LLAMA_CONFIGS["tiny"]
    init_state, step = make_train_step(cfg, plan)
    state = shard_state(plan, init_state(L.init_params(cfg, jax.random.PRNGKey(0))))
    with TokenLoader(path, batch=4, seq=128, seed=1) as loader:
        for batch in loader.batches(2):
            # tiny config's vocab is 512; fold the corpus ids into range.
            state, loss = step(state, (batch % cfg.vocab_size).astype(np.int32))
    assert np.isfinite(float(loss))
