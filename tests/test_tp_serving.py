"""Tensor-parallel serving replicas (models/tp_serving.py + ``plan=``
on the serving engines).

The contract under test: a "replica" is a MESH, not a chip — weights
NamedSharding-partitioned on the ``tp`` axis, the paged KV pool
HEAD-sharded (per-chip pool bytes drop by exactly the TP degree), the
ragged fused dispatch running the SAME jitted step on every shard with
the two psums GSPMD inserts — and a tp=N replica matches the 1-chip
engine token-for-token. The composition surface rides along: prefill
chunks under a token budget, speculative verify rows, int8 pools,
per-row LoRA adapters, disagg export/import handoff, the fleet KV
peer-fetch tier, and the gateway (which must not be able to tell a
mesh replica from a chip).

Exactness caveat, pinned by the regime below: tp's psum is a DIFFERENT
reduction order than the single-chip matmul, so a top-2 logit gap of
~one bf16 ulp can flip greedy argmax deep into a stream. The prompt
sets + max_new_tokens=4 used here are verified exact at tp=2 AND tp=4
(the dryrun 2g arm re-proves the same regime on every CI run); deeper
streams get the documented greedy-consistency fallback instead
(loadtest/serve_fleet.py --tp).
"""

from __future__ import annotations

import base64
import http.client
import json

import jax
import numpy as np
import pytest

from kubeflow_tpu.models import llama as L
from kubeflow_tpu.models.gateway import ServingGateway, prompt_chain_keys
from kubeflow_tpu.models.lora import LoraConfig, init_lora_params
from kubeflow_tpu.models.multilora import MultiLoraPagedBatcher, stack_adapters
from kubeflow_tpu.models.paged import (
    PagedBatcher,
    _kv_block_bytes,
    pool_blocks_from_hbm,
)
from kubeflow_tpu.models.server import InferenceServer, serving_tp_from_env
from kubeflow_tpu.models.serving import GenerationConfig
from kubeflow_tpu.models.speculative import (
    SpeculativePagedBatcher,
    truncated_draft,
)
from kubeflow_tpu.models.tp_serving import (
    replica_device_groups,
    serving_plan,
    validate_serving_tp,
)
from kubeflow_tpu.webhook.tpu_env import KUBEFLOW_TPU_SERVING_TP

BS = 8
# The pinned parity regime: at max_new_tokens=4 these prompts decode
# token-exactly at tp=2 AND tp=4 on the tiny model. Don't deepen the
# streams casually — token 5 of prompt [3, 41, 90, 7] sits one bf16
# ulp (0.0078 at logit magnitude ~1.6) from its runner-up, and tp=4's
# psum order forks it.
PROMPTS = [[5, 9, 17], [3, 41, 90, 7], [11] * 9]
MAX_NEW = 4

pytestmark = pytest.mark.skipif(
    jax.device_count() < 4,
    reason="tensor-parallel serving needs >= 4 devices (conftest "
           "forces 8 CPU devices under pytest)")


@pytest.fixture(scope="module")
def tiny():
    cfg = L.LLAMA_CONFIGS["tiny"]
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _run(batcher, prompts=PROMPTS):
    rids = [batcher.submit(p) for p in prompts]
    out = batcher.run()
    return [out[r] for r in rids]


def _ragged(tiny, plan=None, kv_bits=0, **kw):
    cfg, params = tiny
    return PagedBatcher(
        params, cfg, gen=GenerationConfig(max_new_tokens=MAX_NEW, eos_id=-1),
        slots=2, num_blocks=24, block_size=BS, prompt_bucket=16,
        ragged=True, attn_kernel=False, kv_bits=kv_bits, plan=plan, **kw,
    )


class TestValidation:
    """Fail-fast startup validation: a bad degree must kill the
    replica before it takes traffic."""

    def test_valid_degrees_pass(self, tiny):
        cfg, _ = tiny  # tiny: n_heads=4, n_kv_heads=4
        for tp in (1, 2, 4):
            assert validate_serving_tp(cfg, tp) == tp

    def test_kv_head_divisibility_is_enforced(self, tiny):
        cfg, _ = tiny
        with pytest.raises(ValueError, match="n_kv_heads"):
            validate_serving_tp(cfg, 3)

    def test_degree_below_one_rejected(self, tiny):
        cfg, _ = tiny
        with pytest.raises(ValueError, match=">= 1"):
            validate_serving_tp(cfg, 0)

    def test_device_count_checked_when_given(self, tiny):
        cfg, _ = tiny
        with pytest.raises(ValueError, match="devices"):
            validate_serving_tp(cfg, 4, n_devices=2)

    def test_tp1_plan_is_none(self, tiny):
        cfg, _ = tiny
        # The classic single-chip engine: zero plan-path overhead.
        assert serving_plan(1, cfg=cfg) is None

    def test_plan_axes_are_pure_tp(self, tiny):
        cfg, _ = tiny
        plan = serving_plan(2, cfg=cfg)
        assert plan.axes == {"tp": 2}
        assert plan.mesh.shape.get("tp") == 2

    def test_plan_needs_enough_devices(self, tiny):
        cfg, _ = tiny
        with pytest.raises(ValueError, match="devices"):
            serving_plan(4, devices=jax.devices()[:2], cfg=cfg)

    def test_replica_device_groups_carve_disjoint_meshes(self):
        devs = jax.devices()[:8]
        groups = replica_device_groups(4, devices=devs)
        assert [len(g) for g in groups] == [4, 4]
        flat = [d for g in groups for d in g]
        assert len(set(flat)) == 8
        # Remainder chips never form a ragged replica.
        assert [len(g) for g in replica_device_groups(3, devices=devs)] \
            == [3, 3]
        with pytest.raises(ValueError):
            replica_device_groups(0)

    def test_env_knob_parses_and_fails_fast(self, monkeypatch):
        monkeypatch.delenv(KUBEFLOW_TPU_SERVING_TP, raising=False)
        assert serving_tp_from_env() == 1
        monkeypatch.setenv(KUBEFLOW_TPU_SERVING_TP, "4")
        assert serving_tp_from_env() == 4
        monkeypatch.setenv(KUBEFLOW_TPU_SERVING_TP, " 2 ")
        assert serving_tp_from_env() == 2
        for bad in ("zero", "0", "-1", "1.5"):
            monkeypatch.setenv(KUBEFLOW_TPU_SERVING_TP, bad)
            with pytest.raises(ValueError, match=KUBEFLOW_TPU_SERVING_TP):
                serving_tp_from_env()


class TestKvBlockBytes:
    """Per-shard pool cost is the global cost over the TP degree —
    exactly, not approximately (head rows divide evenly)."""

    @pytest.mark.parametrize("kv_bits", [0, 8])
    def test_per_shard_cost_divides_by_tp(self, tiny, kv_bits):
        cfg, _ = tiny
        whole = _kv_block_bytes(cfg, BS, kv_bits)
        for tp in (1, 2, 4):
            assert _kv_block_bytes(cfg, BS, kv_bits, tp=tp) * tp == whole

    def test_bad_degrees_rejected(self, tiny):
        cfg, _ = tiny
        for tp in (0, 3):
            with pytest.raises(ValueError, match="n_kv_heads"):
                _kv_block_bytes(cfg, BS, tp=tp)


class TestPoolSharding:
    def test_per_chip_pool_bytes_drop_by_tp_degree(self, tiny):
        """The head-sharded pool holds 1/tp of every leaf per chip —
        asserted against the actual shard layout, not the spec."""
        tp = 4
        eng = _ragged(tiny, plan=serving_plan(tp, cfg=tiny[0]))
        single = _ragged(tiny)
        for name, leaf in eng.pool.items():
            shards = leaf.addressable_shards
            assert len({s.device for s in shards}) == tp
            per_chip = {}
            for s in shards:
                per_chip[s.device] = per_chip.get(s.device, 0) \
                    + s.data.nbytes
            assert set(per_chip.values()) == {leaf.nbytes // tp}, name
            # Global bytes unchanged vs the single-chip pool.
            assert leaf.nbytes == single.pool[name].nbytes, name

    def test_pool_blocks_from_hbm_sizes_off_per_shard_headroom(self, tiny):
        """HBM autosizing under a tp plan divides the per-block cost,
        not the budget: the same per-chip headroom holds tp× more
        blocks because each chip stores only its heads' rows."""
        cfg, _ = tiny

        class Dev:
            def memory_stats(self):
                return {"bytes_limit": 1 << 30, "bytes_in_use": 0}

        one = pool_blocks_from_hbm(cfg, BS, device=Dev())
        four = pool_blocks_from_hbm(cfg, BS, device=Dev(), tp=4)
        assert four == 4 * one  # power-of-two budget: exact


class TestTokenExact:
    """THE tentpole invariant: a tp=N mesh replica emits exactly the
    1-chip engine's stream — across every scheduling mode that rides
    the fused ragged dispatch."""

    @pytest.mark.parametrize("tp", [2, 4])
    def test_ragged_decode(self, tiny, tp):
        want = _run(_ragged(tiny))
        got = _run(_ragged(tiny, plan=serving_plan(tp, cfg=tiny[0])))
        assert got == want

    @pytest.mark.parametrize("tp", [2, 4])
    def test_prefill_chunks_under_token_budget(self, tiny, tp):
        """token_budget=4 forces the 9-token prompt through multiple
        admission chunk rows — the chunked prefill path must shard
        identically to whole-prompt admission."""
        want = _run(_ragged(tiny, token_budget=4))
        got = _run(_ragged(tiny, plan=serving_plan(tp, cfg=tiny[0]),
                           token_budget=4))
        assert got == want
        # And chunking itself never moved the stream.
        assert want == _run(_ragged(tiny))

    def test_int8_kv_pool(self, tiny):
        """kv_bits=8: the quantize/dequantize ladder runs on sharded
        pool leaves (values AND per-row scales split by head)."""
        want = _run(_ragged(tiny, kv_bits=8))
        got = _run(_ragged(tiny, kv_bits=8,
                           plan=serving_plan(4, cfg=tiny[0])))
        assert got == want

    def test_speculative_verify_rows(self, tiny):
        """Spec verify spans inside the fused dispatch: the (B, k+1)
        verify forward, rejection, and KV rollback all run on the
        sharded pool — with a truncated foreign draft so rejection
        fires for real."""
        cfg, params = tiny
        dparams, dcfg = truncated_draft(params, cfg, 1)

        def spec(plan=None):
            return SpeculativePagedBatcher(
                params, cfg, dparams, dcfg,
                gen=GenerationConfig(max_new_tokens=MAX_NEW, eos_id=-1),
                slots=2, num_blocks=40, block_size=BS, prompt_bucket=16,
                k_spec=3, ragged=True, token_budget=16, plan=plan,
            )

        want = _run(spec())
        sb = spec(serving_plan(4, cfg=cfg))
        assert _run(sb) == want
        assert 0.0 <= sb.acceptance_rate <= 1.0

    @pytest.mark.parametrize("tp", [2, 4])
    def test_lora_adapter_rows(self, tiny, tp):
        """Adapter deltas ride every row of the sharded dispatch: base
        weights partition per the plan, the stacked skinny factors stay
        replicated, and a mixed adapter/base batch still matches the
        1-chip engine row for row. (Prompt set differs from PROMPTS:
        under this adapter, [11]*9 has a one-ulp near-tie at token 3
        that forks on psum order — [12]*9 is tie-free.)"""
        cfg, params = tiny
        lcfg = LoraConfig(rank=4, targets=("wq", "wv", "w_down"))
        ad = init_lora_params(cfg, lcfg, jax.random.PRNGKey(1))
        ad = jax.tree_util.tree_map(
            lambda x: x + 0.05 * jax.random.normal(
                jax.random.PRNGKey(101), x.shape, x.dtype),
            ad,
        )
        stacked = stack_adapters([ad], cfg, lcfg)
        prompts = [[5, 9, 17], [3, 41, 90, 7], [12] * 9]
        tags = ["a0", None, "a0"]

        def ml(plan=None):
            return MultiLoraPagedBatcher(
                params, cfg, stacked, lcfg, adapter_names=["a0"],
                gen=GenerationConfig(max_new_tokens=MAX_NEW, eos_id=-1),
                slots=2, num_blocks=24, block_size=BS, prompt_bucket=16,
                ragged=True, plan=plan,
            )

        def run_tagged(b):
            rids = [b.submit(p, adapter=t) for p, t in zip(prompts, tags)]
            out = b.run()
            return [out[r] for r in rids]

        assert run_tagged(ml(serving_plan(tp, cfg=cfg))) \
            == run_tagged(ml())


# ---------------------------------------------------------------------------
# Fleet composition: the mesh replica behind one HTTP endpoint.

PROMPT = [5, 9, 17, 33, 2, 11, 44, 3, 8, 21]  # 10 tokens → 2 blocks


def _legacy(tiny, plan=None, kv_bits=0):
    """The non-ragged prefix-cache engine — the disagg/fleet-KV wire
    paths (export/import requires prefix_cache)."""
    cfg, params = tiny
    return PagedBatcher(
        params, cfg, gen=GenerationConfig(max_new_tokens=8, eos_id=-1),
        slots=2, num_blocks=32, block_size=BS, prompt_bucket=32,
        prefix_cache=True, kv_bits=kv_bits, plan=plan,
    )


def _prefill_payload(engine, prompt):
    out = {}
    engine.on_token = lambda rid, tok: out.setdefault(
        rid, engine.export_blocks(rid))
    rid = engine.submit(prompt, max_new_tokens=1)
    engine.run()
    engine.on_token = None
    return out[rid]


def _reference(tiny, prompt, max_tokens):
    e = _legacy(tiny)
    rid = e.submit(prompt, max_new_tokens=max_tokens)
    return e.run()[rid]


class TestDisaggHandoffThroughTP:
    """/kv/prefill handoff with a mesh replica on one side: the wire
    format is TP-invariant (np.asarray on a sharded leaf gathers), so
    either tier can be tensor-parallel without the other knowing."""

    @pytest.mark.parametrize("kv_bits", [0, 8])
    def test_import_into_tp_replica_byte_exact(self, tiny, kv_bits):
        """Prefill on a 1-chip tier, decode on a tp=2 mesh: every wire
        block re-materializes byte-identically in the HEAD-SHARDED
        pool, and the decode stream matches a fused 1-chip replica."""
        a = _legacy(tiny, kv_bits=kv_bits)
        payload = _prefill_payload(a, PROMPT)
        b = _legacy(tiny, plan=serving_plan(2, cfg=tiny[0]),
                    kv_bits=kv_bits)
        rid = b.import_blocks(payload, max_new_tokens=8)
        slot = next(i for i, r in enumerate(b._by_slot)
                    if r is not None and r.rid == rid)
        blocks = b._by_slot[slot].blocks
        for j, ent in enumerate(payload["blocks"]):
            for name, b64 in ent["data"].items():
                got = np.ascontiguousarray(
                    np.asarray(b.pool[name][:, blocks[j]])).tobytes()
                assert got == base64.b64decode(b64), (kv_bits, j, name)
        got = b.run()[rid]
        c = _legacy(tiny, kv_bits=kv_bits)
        r = c.submit(PROMPT, max_new_tokens=8)
        assert got == c.run()[r]
        assert a.kv_exports == 1 and b.kv_imports == 1

    def test_prefill_on_tp_replica_token_exact(self, tiny):
        """The other side: a tp=2 mesh runs the prefill tier and
        exports; a 1-chip decode tier imports and must land on the
        single-replica stream. (TP prefill KV may differ from 1-chip
        KV by bf16 ulps — psum order — so the contract here is the
        decoded TOKENS, not the payload bytes.)"""
        a = _legacy(tiny, plan=serving_plan(2, cfg=tiny[0]))
        payload = _prefill_payload(a, PROMPT)
        b = _legacy(tiny)
        rid = b.import_blocks(payload, max_new_tokens=8)
        assert b.run()[rid] == _reference(tiny, PROMPT, 8)


def _stream(host, port, prompt, max_tokens=6, timeout=120):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    conn.request(
        "POST", "/v1/completions",
        json.dumps({"prompt": prompt, "max_tokens": max_tokens,
                    "stream": True}).encode(),
        {"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200
    toks, done = [], False
    while True:
        line = resp.fp.readline()
        if not line:
            break
        if line == b"data: [DONE]\n":
            done = True
            break
        if line.startswith(b"data:"):
            body = json.loads(line[5:])
            assert "error" not in body, body
            toks.append(body["token"])
    conn.close()
    assert done, "stream ended without [DONE]"
    return toks


class TestStatsMesh:
    def test_mesh_block_present_only_for_mesh_replicas(self, tiny):
        """/stats advertises the mesh shape for fleet observability —
        and stays byte-compatible (no key at all) for 1-chip engines."""
        srv = InferenceServer(
            _legacy(tiny, plan=serving_plan(2, cfg=tiny[0])),
            port=0, drain_s=0.5).start()
        try:
            conn = http.client.HTTPConnection(srv.host, srv.port,
                                              timeout=30)
            conn.request("GET", "/stats")
            stats = json.loads(conn.getresponse().read())
            conn.close()
            assert stats["mesh"] == {"tp": 2}
            assert "kv_pool" in stats
        finally:
            srv.stop()
        srv = InferenceServer(_legacy(tiny), port=0, drain_s=0.5).start()
        try:
            conn = http.client.HTTPConnection(srv.host, srv.port,
                                              timeout=30)
            conn.request("GET", "/stats")
            stats = json.loads(conn.getresponse().read())
            conn.close()
            assert "mesh" not in stats
        finally:
            srv.stop()


class TestGatewayWithMeshReplica:
    def test_gateway_streams_through_tp_replica_unchanged(self, tiny):
        """Zero gateway-side diffs: a mesh replica is just an endpoint.
        The stream through the gateway matches the 1-chip reference."""
        srv = InferenceServer(
            _legacy(tiny, plan=serving_plan(2, cfg=tiny[0])),
            port=0, drain_s=0.5).start()
        gw = ServingGateway([f"{srv.host}:{srv.port}"], port=0,
                            block_size=BS, health_interval_s=30.0).start()
        gw.probe_once()
        try:
            prompt = [5] + list(range(2, 21))
            assert _stream(gw.host, gw.port, prompt) \
                == _reference(tiny, prompt, 6)
            stats = gw.stats()
            assert all(rep["healthy"]
                       for rep in stats["replicas"].values())
        finally:
            gw.stop()
            srv.stop()

    def test_peer_chain_fetch_into_tp_replica_byte_exact(self, tiny):
        """Fleet KV tier through a mesh: the target (a tp=2 replica)
        imports a 1-chip peer's /kv/chain payload instead of
        re-prefilling — counters flow, the stream matches the 1-chip
        reference, and the imported chain re-exports byte-identically
        from the head-sharded pool."""
        tp_srv = InferenceServer(
            _legacy(tiny, plan=serving_plan(2, cfg=tiny[0])),
            port=0, drain_s=0.5).start()
        peer_srv = InferenceServer(_legacy(tiny), port=0,
                                   drain_s=0.5).start()
        eps = [f"{tp_srv.host}:{tp_srv.port}",
               f"{peer_srv.host}:{peer_srv.port}"]
        gw = ServingGateway(eps, port=0, block_size=BS,
                            health_interval_s=30.0,
                            kv_peer_fanout=2).start()
        gw.probe_once()
        try:
            prompt = None
            for nonce in range(3, 250):
                cand = [nonce] + list(range(2, 21))
                gw._route_key(cand)
                routed = gw._candidates(gw._route_key(cand))
                if routed and routed[0] == eps[0]:
                    prompt = cand
                    break
            assert prompt is not None, "no prompt routed to the tp replica"
            conn = http.client.HTTPConnection(peer_srv.host,
                                              peer_srv.port, timeout=60)
            conn.request(
                "POST", "/v1/completions",
                json.dumps({"prompt": prompt, "max_tokens": 2}).encode(),
                {"Content-Type": "application/json"})
            assert conn.getresponse().status == 200
            conn.close()
            toks = _stream(gw.host, gw.port, prompt)
            assert toks == _reference(tiny, prompt, 6)
            stats = gw.stats()
            assert stats["kv_peer_fetches"] == 1
            assert stats["kv_peer_fetch_failures"] == 0
            assert tp_srv.engine.kv_chain_imports == 1
            assert tp_srv.engine.prefix_hits >= 1
            keys = prompt_chain_keys(prompt, BS)
            from_tp = tp_srv.engine.export_chain(keys)
            from_peer = peer_srv.engine.export_chain(keys)
            assert [b["data"] for b in from_tp["blocks"]] \
                == [b["data"] for b in from_peer["blocks"]]
        finally:
            gw.stop()
            tp_srv.stop()
            peer_srv.stop()
