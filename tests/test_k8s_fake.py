"""Tests for the in-memory API server, manager, and chaos client."""

import pytest

from kubeflow_tpu import k8s
from kubeflow_tpu.k8s import objects as obj_util
from kubeflow_tpu.k8s.manager import Manager, Reconciler, Request, Result


def make_cm(name="cm", ns="default", data=None):
    return {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": {"name": name, "namespace": ns},
        "data": data or {},
    }


class TestCrud:
    def test_create_get_roundtrip(self):
        c = k8s.FakeCluster()
        created = c.create(make_cm(data={"a": "1"}))
        assert created["metadata"]["uid"].startswith("uid-")
        got = c.get("ConfigMap", "cm", "default")
        assert got["data"] == {"a": "1"}

    def test_create_duplicate(self):
        c = k8s.FakeCluster()
        c.create(make_cm())
        with pytest.raises(k8s.AlreadyExistsError):
            c.create(make_cm())

    def test_get_not_found(self):
        c = k8s.FakeCluster()
        with pytest.raises(k8s.NotFoundError):
            c.get("ConfigMap", "nope", "default")
        assert k8s.is_not_found(k8s.NotFoundError("x"))

    def test_stale_resource_version_conflicts(self):
        c = k8s.FakeCluster()
        c.create(make_cm())
        a = c.get("ConfigMap", "cm", "default")
        b = c.get("ConfigMap", "cm", "default")
        a["data"] = {"x": "1"}
        c.update(a)
        b["data"] = {"y": "2"}
        with pytest.raises(k8s.ConflictError):
            c.update(b)

    def test_retry_on_conflict(self):
        c = k8s.FakeCluster()
        c.create(make_cm())
        other = c.get("ConfigMap", "cm", "default")

        attempts = []

        def mutate():
            fresh = c.get("ConfigMap", "cm", "default")
            if not attempts:
                # Interleave a competing write on first attempt only.
                other["data"] = {"competing": "write"}
                c.update(dict(other))
                attempts.append(1)
                fresh["metadata"]["resourceVersion"] = "1"  # force staleness
            fresh.setdefault("data", {})["mine"] = "yes"
            return c.update(fresh)

        k8s.retry_on_conflict(mutate)
        assert c.get("ConfigMap", "cm", "default")["data"]["mine"] == "yes"

    def test_merge_patch_removes_key_with_none(self):
        c = k8s.FakeCluster()
        c.create(make_cm(data={"keep": "1", "drop": "2"}))
        c.patch("ConfigMap", "cm", "default", {"data": {"drop": None}})
        assert c.get("ConfigMap", "cm", "default")["data"] == {"keep": "1"}

    def test_generation_bumps_on_spec_change_only(self):
        c = k8s.FakeCluster()
        nb = {
            "apiVersion": "kubeflow.org/v1",
            "kind": "Notebook",
            "metadata": {"name": "nb", "namespace": "ns"},
            "spec": {"template": {"spec": {"containers": []}}},
        }
        c.create(nb)
        got = c.get("Notebook", "nb", "ns")
        obj_util.annotations_of(got)["x"] = "y"
        c.update(got)
        assert c.get("Notebook", "nb", "ns")["metadata"]["generation"] == 1
        got = c.get("Notebook", "nb", "ns")
        got["spec"]["template"]["spec"]["containers"] = [{"name": "nb"}]
        c.update(got)
        assert c.get("Notebook", "nb", "ns")["metadata"]["generation"] == 2

    def test_status_subresource_isolation(self):
        c = k8s.FakeCluster()
        nb = {
            "apiVersion": "kubeflow.org/v1",
            "kind": "Notebook",
            "metadata": {"name": "nb", "namespace": "ns"},
            "spec": {},
            "status": {"readyReplicas": 0},
        }
        c.create(nb)
        got = c.get("Notebook", "nb", "ns")
        got["status"] = {"readyReplicas": 99}  # must be ignored by update()
        got["spec"] = {"changed": True}
        c.update(got)
        assert c.get("Notebook", "nb", "ns")["status"]["readyReplicas"] == 0
        got = c.get("Notebook", "nb", "ns")
        got["status"] = {"readyReplicas": 3}
        c.update_status(got)
        fresh = c.get("Notebook", "nb", "ns")
        assert fresh["status"]["readyReplicas"] == 3
        assert fresh["spec"] == {"changed": True}


class TestFinalizersAndGC:
    def test_finalizer_blocks_deletion(self):
        c = k8s.FakeCluster()
        cm = make_cm()
        cm["metadata"]["finalizers"] = ["example.com/cleanup"]
        c.create(cm)
        c.delete("ConfigMap", "cm", "default")
        got = c.get("ConfigMap", "cm", "default")
        assert "deletionTimestamp" in got["metadata"]
        got["metadata"]["finalizers"] = []
        c.update(got)
        assert not c.exists("ConfigMap", "cm", "default")

    def test_cascading_gc(self):
        c = k8s.FakeCluster()
        owner = c.create(make_cm("owner"))
        child = make_cm("child")
        obj_util.set_controller_reference(owner, child)
        c.create(child)
        c.delete("ConfigMap", "owner", "default")
        assert not c.exists("ConfigMap", "child", "default")

    def test_label_selector_list(self):
        c = k8s.FakeCluster()
        a = make_cm("a")
        a["metadata"]["labels"] = {"app": "x"}
        b = make_cm("b")
        b["metadata"]["labels"] = {"app": "y"}
        c.create(a)
        c.create(b)
        assert [obj_util.name_of(o) for o in c.list("ConfigMap", "default", {"app": "x"})] == ["a"]


class TestAdmission:
    def test_mutating_webhook_applies(self):
        c = k8s.FakeCluster()

        def add_label(req):
            obj_util.labels_of(req.object)["mutated"] = "true"
            return req.object

        c.register_mutating_webhook("ConfigMap", add_label)
        c.create(make_cm())
        assert c.get("ConfigMap", "cm", "default")["metadata"]["labels"]["mutated"] == "true"

    def test_validating_webhook_denies(self):
        c = k8s.FakeCluster()

        def deny(req):
            raise k8s.WebhookDeniedError("not allowed")

        c.register_validating_webhook("ConfigMap", deny, operations=("CREATE",))
        with pytest.raises(k8s.WebhookDeniedError):
            c.create(make_cm())
        assert not c.exists("ConfigMap", "cm", "default")

    def test_update_webhook_sees_old_object(self):
        c = k8s.FakeCluster()
        seen = {}

        def capture(req):
            if req.operation == "UPDATE":
                seen["old"] = req.old_object["data"]
            return req.object

        c.register_mutating_webhook("ConfigMap", capture)
        c.create(make_cm(data={"v": "1"}))
        got = c.get("ConfigMap", "cm", "default")
        got["data"] = {"v": "2"}
        c.update(got)
        assert seen["old"] == {"v": "1"}


class _CounterReconciler(Reconciler):
    def __init__(self, cluster):
        self.cluster = cluster
        self.calls = []

    def reconcile(self, req: Request) -> Result:
        self.calls.append(req)
        return Result()


class TestManager:
    def test_primary_watch_enqueues(self):
        c = k8s.FakeCluster()
        m = Manager(c)
        r = _CounterReconciler(c)
        m.register(r, for_kind="ConfigMap")
        c.create(make_cm("one"))
        m.run_until_idle()
        assert r.calls == [Request("one", "default")]

    def test_owned_watch_maps_to_owner(self):
        c = k8s.FakeCluster()
        m = Manager(c)
        r = _CounterReconciler(c)
        m.register(r, for_kind="Notebook", owns=("ConfigMap",))
        owner = c.create(
            {
                "apiVersion": "kubeflow.org/v1",
                "kind": "Notebook",
                "metadata": {"name": "nb", "namespace": "ns"},
            }
        )
        m.run_until_idle()
        child = make_cm("child", "ns")
        obj_util.set_controller_reference(owner, child)
        c.create(child)
        m.run_until_idle()
        assert Request("nb", "ns") in r.calls
        assert all(req.name == "nb" for req in r.calls)

    def test_requeue_after_fires_on_tick(self):
        c = k8s.FakeCluster()
        m = Manager(c)

        class Requeuer(Reconciler):
            def __init__(self):
                self.calls = 0

            def reconcile(self, req):
                self.calls += 1
                return Result(requeue_after=30.0)

        r = Requeuer()
        m.register(r, for_kind="ConfigMap")
        c.create(make_cm())
        m.run_until_idle()
        assert r.calls == 1
        m.tick(10.0)
        assert r.calls == 1  # not due yet
        m.tick(25.0)
        assert r.calls == 2  # 35s elapsed > 30s requeue


class TestChaos:
    def test_deterministic_failure_then_recovery(self):
        c = k8s.FakeCluster()
        chaos = k8s.ChaosClient(c)
        fault = chaos.add_fault(
            k8s.FaultConfig(operations=("create",), kinds=("ConfigMap",))
        )
        with pytest.raises(Exception):
            chaos.create(make_cm())
        assert fault.injected_count == 1
        fault.deactivate()
        chaos.create(make_cm())
        assert c.exists("ConfigMap", "cm", "default")

    def test_intermittent_rate(self):
        c = k8s.FakeCluster()
        chaos = k8s.ChaosClient(c, seed=42)
        chaos.add_fault(
            k8s.FaultConfig(operations=("get",), error_rate=0.5)
        )
        c.create(make_cm())
        outcomes = []
        for _ in range(100):
            try:
                chaos.get("ConfigMap", "cm", "default")
                outcomes.append(True)
            except Exception:
                outcomes.append(False)
        assert 20 < sum(outcomes) < 80  # roughly half succeed


class TestFakeKubelet:
    def _mini_sts(self, replicas=2, tpu=None, selector=None):
        container = {"name": "nb", "image": "jupyter"}
        if tpu:
            container["resources"] = {"limits": {"google.com/tpu": tpu}}
        spec = {"containers": [container]}
        if selector:
            spec["nodeSelector"] = selector
        return {
            "apiVersion": "apps/v1",
            "kind": "StatefulSet",
            "metadata": {"name": "nb", "namespace": "ns"},
            "spec": {
                "replicas": replicas,
                "serviceName": "nb-hosts",
                "template": {"metadata": {"labels": {"app": "nb"}}, "spec": spec},
            },
        }

    def test_pods_created_ready_and_indexed(self):
        c = k8s.FakeCluster()
        m = Manager(c)
        k8s.add_cpu_node(c)
        k8s.FakeKubelet(c).register(m)
        c.create(self._mini_sts(replicas=2))
        m.run_until_idle()
        pods = sorted(c.list("Pod", "ns"), key=obj_util.name_of)
        assert [obj_util.name_of(p) for p in pods] == ["nb-0", "nb-1"]
        assert pods[0]["metadata"]["labels"]["apps.kubernetes.io/pod-index"] == "0"
        assert pods[0]["status"]["phase"] == "Running"
        sts = c.get("StatefulSet", "nb", "ns")
        assert sts["status"]["readyReplicas"] == 2

    def test_tpu_scheduling_requires_matching_pool(self):
        c = k8s.FakeCluster()
        m = Manager(c)
        k8s.FakeKubelet(c).register(m)
        sel = {
            "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
            "cloud.google.com/gke-tpu-topology": "4x4",
        }
        c.create(self._mini_sts(replicas=4, tpu="4", selector=sel))
        m.run_until_idle()
        pods = c.list("Pod", "ns")
        assert all(p["status"]["phase"] == "Pending" for p in pods)
        # Adding the pool reschedules the Pending pods without manual cleanup.
        k8s.add_tpu_node_pool(c, "tpu-v5-lite-podslice", "4x4", hosts=4, chips_per_host=4)
        m.run_until_idle()
        pods = c.list("Pod", "ns")
        assert all(p["status"]["phase"] == "Running" for p in pods)
        nodes_used = {p["spec"]["nodeName"] for p in pods}
        assert len(nodes_used) == 4  # one host-pod per TPU node

    def test_scheduling_respects_other_namespace_usage(self):
        """Node TPU capacity is CLUSTER-scoped: pods bound in one
        namespace must count against the allocatable another namespace's
        scheduling sees (guards the per-reconcile scheduling snapshot,
        which lists pods cluster-wide while the hot path lists only the
        reconcile's namespace)."""
        c = k8s.FakeCluster()
        m = Manager(c)
        k8s.FakeKubelet(c).register(m)
        k8s.add_tpu_node_pool(c, "tpu-v5-lite-podslice", "4x4",
                              hosts=4, chips_per_host=4)
        sel = {
            "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
            "cloud.google.com/gke-tpu-topology": "4x4",
        }
        first = self._mini_sts(replicas=4, tpu="4", selector=sel)
        c.create(first)
        m.run_until_idle()
        ns_pods = c.list("Pod", "ns")
        assert len(ns_pods) == 4
        assert all(p["status"]["phase"] == "Running" for p in ns_pods)
        # Same shape in ANOTHER namespace: the pool is fully claimed by
        # ns, so ns2's pods must stay Pending, not double-bind.
        second = self._mini_sts(replicas=4, tpu="4", selector=sel)
        second["metadata"]["namespace"] = "ns2"
        c.create(second)
        m.run_until_idle()
        ns2_pods = c.list("Pod", "ns2")
        assert len(ns2_pods) == 4
        assert all(p["status"]["phase"] == "Pending" for p in ns2_pods)
        # Capacity freed in ns → ns2 schedules.
        for p in list(c.list("Pod", "ns")):
            c.delete("Pod", obj_util.name_of(p), "ns")
        c.delete("StatefulSet", "nb", "ns")
        m.run_until_idle()
        ns2_pods = c.list("Pod", "ns2")
        assert len(ns2_pods) == 4
        assert all(p["status"]["phase"] == "Running" for p in ns2_pods)

    def test_succeeded_pod_releases_capacity_for_other_namespace(self):
        """A pod that turns Succeeded (terminal) releases its node's TPU
        allocatable without being deleted; another StatefulSet's
        Unschedulable pods must wake and bind."""
        c = k8s.FakeCluster()
        m = Manager(c)
        k8s.FakeKubelet(c).register(m)
        k8s.add_tpu_node_pool(c, "tpu-v5-lite-podslice", "4x4",
                              hosts=1, chips_per_host=4)
        sel = {
            "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
            "cloud.google.com/gke-tpu-topology": "4x4",
        }
        first = self._mini_sts(replicas=1, tpu="4", selector=sel)
        c.create(first)
        m.run_until_idle()
        second = self._mini_sts(replicas=1, tpu="4", selector=sel)
        second["metadata"]["namespace"] = "ns2"
        c.create(second)
        m.run_until_idle()
        (pending,) = c.list("Pod", "ns2")
        assert pending["status"]["phase"] == "Pending"
        done = c.get("Pod", "nb-0", "ns")
        done["status"]["phase"] = "Succeeded"
        c.update_status(done)
        m.run_until_idle()
        (woken,) = c.list("Pod", "ns2")
        assert woken["status"]["phase"] == "Running"

    def test_scale_to_zero_deletes_all_pods(self):
        c = k8s.FakeCluster()
        m = Manager(c)
        k8s.add_cpu_node(c)
        k8s.FakeKubelet(c).register(m)
        created = c.create(self._mini_sts(replicas=2))
        m.run_until_idle()
        sts = c.get("StatefulSet", "nb", "ns")
        sts["spec"]["replicas"] = 0
        c.update(sts)
        m.run_until_idle()
        assert c.list("Pod", "ns") == []


class TestReviewRegressions:
    def test_list_cluster_scoped_ignores_namespace_filter(self):
        c = k8s.FakeCluster()
        k8s.add_cpu_node(c, "n1")
        assert len(c.list("Node", namespace="user-ns")) == 1

    def test_kubelet_standalone_replaces_failed_pods(self):
        """Preemption converges without any slice-health controller,
        matching real StatefulSet-controller behavior."""
        from tests.harness import make_env, tpu_notebook

        env = make_env(slice_health=False)
        env.cluster.create(tpu_notebook())
        env.manager.run_until_idle()
        env.kubelet.preempt_pod("nb-1", "ns")
        env.manager.run_until_idle()
        pods = env.cluster.list("Pod", "ns")
        assert len(pods) == 4
        assert all(p["status"]["phase"] == "Running" for p in pods)

    def test_requeue_timers_coalesce_per_request(self):
        c = k8s.FakeCluster()
        m = Manager(c)

        class Requeuer(Reconciler):
            def __init__(self):
                self.calls = 0

            def reconcile(self, req):
                self.calls += 1
                return Result(requeue_after=60.0)

        r = Requeuer()
        m.register(r, for_kind="ConfigMap")
        cm = c.create(make_cm())
        m.run_until_idle()
        # Hammer the object with updates: each triggers a reconcile, each
        # returns requeue_after — timers must coalesce, not accumulate.
        for i in range(5):
            cm = c.get("ConfigMap", "cm", "default")
            cm["data"] = {"i": str(i)}
            c.update(cm)
            m.run_until_idle()
        calls_before = r.calls
        m.tick(61.0)  # exactly one coalesced timer should fire
        assert r.calls == calls_before + 1

    def test_reconcile_errors_surfaced(self):
        c = k8s.FakeCluster()
        m = Manager(c)

        class Failer(Reconciler):
            def reconcile(self, req):
                raise RuntimeError("boom")

        m.register(Failer(), for_kind="ConfigMap")
        c.create(make_cm())
        m.run_until_idle()
        assert len(m.reconcile_errors) == 1
        assert m.reconcile_errors[0][0] == "Failer"

    def test_admission_rewriting_namespace_stores_under_final_key(self):
        c = k8s.FakeCluster()

        def default_ns(req):
            req.object["metadata"]["namespace"] = "defaulted"
            return req.object

        c.register_mutating_webhook("ConfigMap", default_ns)
        c.create(make_cm(ns="original"))
        assert c.exists("ConfigMap", "cm", "defaulted")
        assert not c.exists("ConfigMap", "cm", "original")
