"""Fleet autoscaler tests (models/autoscaler.py).

Fake-clock decision suite — every control-loop invariant exercised
deterministically against a duck-typed gateway/telemetry pair: ramp
claims a warm slice, ebb drains-then-releases, hysteresis + cooldowns +
the fleet-wide rate limit suppress flapping, disagg tiers scale
independently (a long-prompt storm grows prefill only), stale telemetry
freezes scaling, and claim failures back off exponentially and degrade
to hold. Plus one integration pass over a REAL 2-replica
InferenceServer fleet: organic ebb triggers a scale-down mid-stream and
no stream is ever dropped — the release happens only once the drained
replica is empty.
"""

from __future__ import annotations

import http.client
import json
import threading
import time

import pytest

from kubeflow_tpu.models.autoscaler import (
    AutoscalerConfig,
    FleetAutoscaler,
    WarmSliceProvisioner,
    autoscaler_from_env,
)

EP0, EP1, EP2, EP3 = (f"127.0.0.1:{9000 + i}" for i in range(4))


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += dt
        return self.t


def _slo(ttft=(0.0, 0.0), inter=(0.0, 0.0), queue=(0.0, 0.0),
         queue_thr=0.25):
    """An SLO report in the engine's shape: two fast windows + slow."""

    def obj(burns, threshold):
        return {"kind": "latency", "threshold": threshold,
                "burn": {"60s": burns[0], "300s": burns[1], "1800s": 0.0}}

    return {
        "objectives": {
            "ttft_p95": obj(ttft, 0.5),
            "inter_token_p95": obj(inter, 0.2),
            "queue_wait_p95": obj(queue, queue_thr),
        },
        "breaching": [],
    }


class FakeTelemetry:
    def __init__(self, clock):
        self.clock = clock
        self.ages: dict = {}
        self.slo = _slo()
        self.fleet: dict = {}
        self.actions: list = []

    def scrape_ages(self, now=None):
        return dict(self.ages)

    def evaluate_slo(self, now=None):
        return self.slo

    def snapshot(self, now=None):
        return {"fleet": dict(self.fleet)}

    def observe_autoscale(self, action):
        self.actions.append(action)

    def forget_replica(self, ep):
        self.ages.pop(ep, None)


class FakeGateway:
    tier_mode = "fused"

    def __init__(self, telemetry):
        self.telemetry = telemetry
        self.replicas: dict = {}
        self.inflight: dict = {}
        self.begun: list = []
        self.removed: list = []
        self.pins: set = set()

    def migration_pinned(self):
        return frozenset(self.pins)

    def add(self, ep, *, role="fused", slots=4, active=0, queued=0):
        self.replicas[ep] = {
            "role": role, "in_ring": True,
            "stats": {"slots": slots, "active_slots": active,
                      "queued": queued},
        }
        self.telemetry.ages[ep] = 0.0

    def ring_nodes(self):
        return frozenset(ep for ep, r in self.replicas.items()
                         if r["in_ring"])

    def stats(self):
        return {
            "replicas": {ep: dict(r) for ep, r in self.replicas.items()},
            "inflight": dict(self.inflight),
        }

    def begin_drain(self, ep):
        self.begun.append(ep)
        self.replicas[ep]["in_ring"] = False
        return True

    def remove_replica(self, ep):
        self.removed.append(ep)
        self.replicas.pop(ep, None)
        self.telemetry.forget_replica(ep)


class FakeProvisioner:
    def __init__(self):
        self.claim_result = "pool/warm-0"
        self.claims: list = []
        self.drains: list = []
        self.drained_eps: set = set()
        self.releases: list = []

    def scale_up(self, tier, now=None):
        self.claims.append(tier)
        return self.claim_result

    def drain(self, ep):
        self.drains.append(ep)

    def drained(self, ep):
        return ep in self.drained_eps

    def release(self, ep):
        self.releases.append(ep)


def _cfg(**kw):
    base = dict(
        min_replicas=1, max_replicas=4,
        up_consecutive=2, down_consecutive=3,
        up_cooldown_s=5.0, down_cooldown_s=5.0,
        max_actions_per_window=4, actions_window_s=60.0,
        drain_budget_s=30.0, stale_after_s=10.0,
        claim_backoff_base_s=2.0, claim_backoff_max_s=60.0,
        claim_backoff_jitter=0.0,
    )
    base.update(kw)
    return AutoscalerConfig(**base)


def _mk(n=2, *, config=None, tier_mode="fused", roles=None, **add_kw):
    clock = FakeClock()
    tel = FakeTelemetry(clock)
    gw = FakeGateway(tel)
    gw.tier_mode = tier_mode
    eps = [EP0, EP1, EP2, EP3][:n]
    for i, ep in enumerate(eps):
        gw.add(ep, role=(roles[i] if roles else "fused"), **add_kw)
    prov = FakeProvisioner()
    scaler = FleetAutoscaler(
        gw, config or _cfg(), provisioner=prov, clock=clock,
        rng=lambda: 0.0,
    )
    return scaler, gw, tel, prov, clock


def _tick(scaler, clock, n=1, dt=1.0):
    out = []
    for _ in range(n):
        out.extend(scaler.tick())
        clock.advance(dt)
    return out


def _actions(decisions, action):
    return [d for d in decisions if d["action"] == action]


class TestConfig:
    def test_defaults_valid_and_frozen(self):
        cfg = AutoscalerConfig()
        assert cfg.min_replicas <= cfg.max_replicas
        with pytest.raises(Exception):
            cfg.max_replicas = 99  # frozen

    @pytest.mark.parametrize("kw", [
        dict(min_replicas=3, max_replicas=2),
        dict(max_replicas=0),
        dict(down_burn=1.5, up_burn=1.0),
        dict(low_batch_fill=0.9, high_batch_fill=0.5),
        dict(up_consecutive=0),
        dict(up_cooldown_s=-1),
        dict(max_actions_per_window=0),
        dict(actions_window_s=0),
        dict(drain_budget_s=0),
        dict(stale_after_s=0),
        dict(claim_backoff_jitter=-0.1),
        dict(headroom=0.5),
        dict(decision_ring=0),
    ])
    def test_bad_knobs_fail_fast(self, kw):
        with pytest.raises(ValueError, match="AutoscalerConfig"):
            AutoscalerConfig(**kw)


class TestRamp:
    def test_sustained_burn_claims_a_warm_slice(self):
        scaler, gw, tel, prov, clock = _mk(2)
        tel.slo = _slo(ttft=(1.5, 1.2))
        assert _tick(scaler, clock) == []  # streak 1 < up_consecutive
        done = _tick(scaler, clock)
        assert [d["action"] for d in done] == ["scale_up"]
        assert done[0]["endpoint"] == "pool/warm-0"
        assert any("ttft_p95" in r for r in done[0]["reasons"])
        assert prov.claims == ["fused"]
        st = scaler.stats()
        assert st["scale_ups"] == 1
        assert st["claim_attempts"] == 1
        assert st["claim_failures"] == 0
        assert "up" in tel.actions

    def test_one_hot_window_is_not_a_ramp(self):
        """Hysteresis: pressure must PERSIST up_consecutive ticks —
        a blip, a quiet tick, and another blip never scale."""
        scaler, gw, tel, prov, clock = _mk(2)
        for hot in (True, False, True, False, True):
            tel.slo = _slo(ttft=(1.5, 1.2)) if hot else _slo()
            tel.fleet = {}
            _tick(scaler, clock)
        assert prov.claims == []
        assert scaler.stats()["scale_ups"] == 0

    def test_burn_in_one_fast_window_only_is_not_pressure(self):
        scaler, gw, tel, prov, clock = _mk(2)
        tel.slo = _slo(ttft=(1.5, 0.0))  # fast spike, 300s window calm
        _tick(scaler, clock, n=4)
        assert prov.claims == []

    def test_up_cooldown_holds_once_per_episode(self):
        scaler, gw, tel, prov, clock = _mk(2)
        tel.slo = _slo(ttft=(1.5, 1.2))
        _tick(scaler, clock, n=2)
        assert scaler.stats()["scale_ups"] == 1
        # Pressure persists; attempts land inside the 5s cooldown.
        done = _tick(scaler, clock, n=2)
        holds = _actions(done, "hold")
        assert len(holds) == 1  # deduped: one hold per episode
        assert any("cooldown" in r for r in holds[0]["reasons"])
        # Past the cooldown the claim goes through.
        clock.advance(5.0)
        done = _tick(scaler, clock, n=2)
        assert scaler.stats()["scale_ups"] == 2

    def test_rate_limit_is_fleet_wide_and_window_scoped(self):
        scaler, gw, tel, prov, clock = _mk(
            2, config=_cfg(up_cooldown_s=0.001, max_actions_per_window=1,
                           actions_window_s=60.0))
        tel.slo = _slo(ttft=(1.5, 1.2))
        _tick(scaler, clock, n=2)
        assert scaler.stats()["scale_ups"] == 1
        done = _tick(scaler, clock, n=3)
        holds = _actions(done, "hold")
        assert holds and any("rate limit" in r for r in holds[0]["reasons"])
        assert scaler.stats()["scale_ups"] == 1
        clock.advance(61.0)  # the action falls out of the window
        _tick(scaler, clock, n=2)
        assert scaler.stats()["scale_ups"] == 2

    def test_at_max_replicas_holds(self):
        scaler, gw, tel, prov, clock = _mk(
            2, config=_cfg(max_replicas=2))
        tel.slo = _slo(ttft=(1.5, 1.2))
        done = _tick(scaler, clock, n=3)
        holds = _actions(done, "hold")
        assert holds and any("max_replicas" in r for r in holds[0]["reasons"])
        assert prov.claims == []


class TestEbb:
    def test_ebb_drains_then_releases_least_loaded(self):
        scaler, gw, tel, prov, clock = _mk(2)
        gw.replicas[EP0]["stats"]["active_slots"] = 3  # EP1 least loaded
        done = _tick(scaler, clock, n=3)
        downs = _actions(done, "scale_down")
        assert [d["endpoint"] for d in downs] == [EP1]
        assert prov.drains == [EP1]
        assert gw.begun == [EP1]
        assert EP1 not in gw.ring_nodes()  # out of the ring at decision
        assert gw.removed == [] and prov.releases == []  # NOT yet released
        assert scaler.stats()["draining"] == [EP1]
        # Still busy: no release while the provisioner says not drained.
        assert _actions(_tick(scaler, clock), "release") == []
        prov.drained_eps.add(EP1)
        done = _tick(scaler, clock)
        rel = _actions(done, "release")
        assert [d["endpoint"] for d in rel] == [EP1]
        assert any("drained in" in r for r in rel[0]["reasons"])
        assert prov.releases == [EP1]
        assert gw.removed == [EP1]
        assert scaler.stats()["draining"] == []
        assert "down" in tel.actions

    def test_drain_budget_expiry_force_releases(self):
        scaler, gw, tel, prov, clock = _mk(2)
        _tick(scaler, clock, n=3)
        assert scaler.stats()["scale_downs"] == 1
        clock.advance(31.0)  # past drain_budget_s=30, never drained
        done = _tick(scaler, clock)
        rel = _actions(done, "release")
        assert rel and any("budget" in r and "exceeded" in r
                           for r in rel[0]["reasons"])
        assert prov.releases and gw.removed

    def test_queued_work_blocks_ebb(self):
        scaler, gw, tel, prov, clock = _mk(2)
        tel.fleet = {"replica_queue_depth": {EP0: 2, EP1: 0}}
        _tick(scaler, clock, n=5)
        assert scaler.stats()["scale_downs"] == 0

    def test_at_min_replicas_holds(self):
        scaler, gw, tel, prov, clock = _mk(
            2, config=_cfg(min_replicas=2))
        done = _tick(scaler, clock, n=4)
        holds = _actions(done, "hold")
        assert holds and any("min_replicas" in r for r in holds[0]["reasons"])
        assert prov.drains == []

    def test_headroom_guard_never_forces_a_shed(self):
        scaler, gw, tel, prov, clock = _mk(2)  # slots=4 → cap 8 after
        gw.replicas[EP0]["stats"]["active_slots"] = 2  # EP1 least loaded
        gw.inflight = {"tenant-a": 4, "tenant-b": 3}  # 7 × 1.2 > 8
        done = _tick(scaler, clock, n=4)
        holds = _actions(done, "hold")
        assert holds and any("headroom" in r for r in holds[0]["reasons"])
        assert prov.drains == []
        # Load ebbs for real → the same pressure drains.
        gw.inflight = {"tenant-a": 1}
        _tick(scaler, clock, n=2)
        assert prov.drains == [EP1]

    def test_drain_failure_degrades_to_hold(self):
        scaler, gw, tel, prov, clock = _mk(2)
        prov.drain = lambda ep: (_ for _ in ()).throw(RuntimeError("boom"))
        done = _tick(scaler, clock, n=3)
        holds = _actions(done, "hold")
        assert holds and any("drain" in r and "failed" in r
                             for r in holds[-1]["reasons"])
        assert gw.begun == []  # nothing left the ring
        assert scaler.stats()["scale_downs"] == 0


class TestMigrationPin:
    """Scale-down × live migration: a replica a migration is restoring
    onto (gateway.pin_for_migration) must never be picked as the drain
    victim — draining it would release the very slice the migration is
    landing on."""

    def test_pinned_replica_is_never_the_victim(self):
        scaler, gw, tel, prov, clock = _mk(2)
        gw.replicas[EP0]["stats"]["active_slots"] = 3  # EP1 least loaded
        gw.pins.add(EP1)  # ...but a migration is restoring onto it
        done = _tick(scaler, clock, n=3)
        downs = _actions(done, "scale_down")
        assert [d["endpoint"] for d in downs] == [EP0]
        assert prov.drains == [EP0]
        assert EP1 in gw.ring_nodes()  # the restore target held

    def test_all_pinned_holds_until_unpin(self):
        scaler, gw, tel, prov, clock = _mk(2)
        gw.pins.update({EP0, EP1})
        done = _tick(scaler, clock, n=4)
        holds = _actions(done, "hold")
        assert holds and any("migration" in r for r in holds[0]["reasons"])
        assert prov.drains == []
        assert set(gw.ring_nodes()) == {EP0, EP1}
        # Flip done → unpin → the held scale-down proceeds normally.
        gw.pins.clear()
        clock.advance(6.0)  # clear the down cooldown set by nothing: safe
        _tick(scaler, clock, n=3)
        assert len(prov.drains) == 1

    def test_gateway_without_pin_api_still_scales_down(self):
        scaler, gw, tel, prov, clock = _mk(2)
        del FakeGateway.migration_pinned  # simulate an older gateway
        try:
            _tick(scaler, clock, n=3)
            assert len(prov.drains) == 1
        finally:
            FakeGateway.migration_pinned = (
                lambda self: frozenset(self.pins)
            )


class TestDisagg:
    def _mk_disagg(self, **cfg_kw):
        return _mk(4, tier_mode="disagg",
                   roles=["prefill", "prefill", "decode", "decode"],
                   config=_cfg(**cfg_kw))

    def test_long_prompt_storm_grows_prefill_tier_only(self):
        scaler, gw, tel, prov, clock = self._mk_disagg()
        # TTFT burning + a prefill member's queue-wait over threshold;
        # decode inter-token is perfectly calm.
        tel.slo = _slo(ttft=(2.0, 1.6))
        tel.fleet = {"replica_queue_wait_p95_s": {EP0: 0.9}}
        done = _tick(scaler, clock, n=2)
        ups = _actions(done, "scale_up")
        assert [d["tier"] for d in ups] == ["prefill"]
        assert prov.claims == ["prefill"]
        assert scaler.stats()["tier_replicas"] == {
            "prefill": 2, "decode": 2,
        }

    def test_decode_pressure_grows_decode_tier_only(self):
        scaler, gw, tel, prov, clock = self._mk_disagg()
        tel.slo = _slo(inter=(1.4, 1.1))
        done = _tick(scaler, clock, n=2)
        assert [d["tier"] for d in _actions(done, "scale_up")] == ["decode"]
        assert prov.claims == ["decode"]

    def test_decode_queue_wait_never_grows_prefill(self):
        """The fleet-wide queue-wait gauge on a DECODE member must not
        count as prefill pressure — tier routing is per-member."""
        scaler, gw, tel, prov, clock = self._mk_disagg()
        tel.fleet = {"replica_queue_wait_p95_s": {EP2: 0.9}}  # decode ep
        _tick(scaler, clock, n=3)
        assert "prefill" not in prov.claims

    def test_tiers_ebb_independently(self):
        scaler, gw, tel, prov, clock = self._mk_disagg(down_consecutive=2)
        # Decode quiet, prefill burning: decode shrinks, prefill grows.
        tel.slo = _slo(ttft=(2.0, 1.6))
        done = _tick(scaler, clock, n=2)
        by_tier = {(d["tier"], d["action"]) for d in done}
        assert ("prefill", "scale_up") in by_tier
        assert ("decode", "scale_down") in by_tier
        victims = [d["endpoint"] for d in _actions(done, "scale_down")]
        assert victims and all(v in (EP2, EP3) for v in victims)


class TestFreeze:
    def test_stale_scrape_freezes_until_fresh(self):
        scaler, gw, tel, prov, clock = _mk(2)
        tel.slo = _slo(ttft=(1.5, 1.2))
        tel.ages[EP1] = 99.0  # way past stale_after_s=10
        done = _tick(scaler, clock)
        assert [d["action"] for d in done] == ["freeze"]
        assert any("stale" in r for r in done[0]["reasons"])
        st = scaler.stats()
        assert st["frozen"] is True and st["freezes"] == 1
        # One freeze per episode, and streaks reset while frozen.
        assert _tick(scaler, clock, n=3) == []
        assert scaler.stats()["freezes"] == 1
        assert prov.claims == []
        # Fresh signals thaw it; pressure must re-accumulate from zero.
        tel.ages[EP1] = 0.0
        done = _tick(scaler, clock, n=2)
        assert scaler.stats()["frozen"] is False
        assert [d["action"] for d in _actions(done, "scale_up")] == \
            ["scale_up"]
        assert "freeze" in tel.actions

    def test_missing_scrape_and_missing_telemetry_freeze(self):
        scaler, gw, tel, prov, clock = _mk(2)
        del tel.ages[EP0]
        done = _tick(scaler, clock)
        assert [d["action"] for d in done] == ["freeze"]
        assert any("no scrape yet" in r for r in done[0]["reasons"])
        gw.telemetry = None
        done = _tick(scaler, clock)
        assert _actions(done, "freeze") == []  # same episode: no re-log
        assert scaler.stats()["frozen"] is True

    def test_draining_replica_age_never_freezes(self):
        """A drain-pinned replica is not scraped; its growing age must
        not freeze the loop — staleness is judged in-ring only."""
        scaler, gw, tel, prov, clock = _mk(2)
        gw.replicas[EP0]["stats"]["active_slots"] = 1  # EP1 least loaded
        _tick(scaler, clock, n=3)  # quiet fleet → EP1 draining
        assert scaler.stats()["draining"] == [EP1]
        tel.ages[EP1] = 500.0
        _tick(scaler, clock)
        assert scaler.stats()["frozen"] is False


class TestClaimBackoff:
    def test_claim_failure_backs_off_exponentially_and_holds(self):
        scaler, gw, tel, prov, clock = _mk(2)
        prov.claim_result = None
        tel.slo = _slo(ttft=(1.5, 1.2))
        done = _tick(scaler, clock, n=2)
        holds = _actions(done, "hold")
        assert holds and any("claim failed" in r
                             for r in holds[0]["reasons"])
        st = scaler.stats()
        assert st["claim_attempts"] == 1 and st["claim_failures"] == 1
        assert st["scale_ups"] == 0
        # Inside the 2s backoff: no new attempt even under pressure.
        _tick(scaler, clock, n=1)
        assert scaler.stats()["claim_attempts"] == 1
        # Past it: retry → failure #2 → backoff doubles to 4s.
        clock.advance(2.0)
        _tick(scaler, clock)
        assert scaler.stats()["claim_failures"] == 2
        clock.advance(2.0)  # 4s backoff not yet over (1s tick + 2s)
        _tick(scaler, clock)
        assert scaler.stats()["claim_attempts"] == 2
        # Pool recovers → next attempt claims and resets the ladder.
        prov.claim_result = "pool/warm-1"
        clock.advance(10.0)
        _tick(scaler, clock, n=2)
        st = scaler.stats()
        assert st["scale_ups"] == 1 and st["claim_attempts"] == 3

    def test_scale_up_exception_is_a_failure_not_a_crash(self):
        scaler, gw, tel, prov, clock = _mk(2)
        prov.scale_up = lambda tier, now=None: (
            (_ for _ in ()).throw(RuntimeError("pool gone"))
        )
        tel.slo = _slo(ttft=(1.5, 1.2))
        done = _tick(scaler, clock, n=2)
        holds = _actions(done, "hold")
        assert holds and any("pool gone" in r for r in holds[0]["reasons"])
        assert scaler.stats()["claim_failures"] == 1


class TestSurfaces:
    def test_debug_payload_has_config_tiers_and_decisions(self):
        scaler, gw, tel, prov, clock = _mk(2)
        tel.slo = _slo(ttft=(1.5, 1.2))
        _tick(scaler, clock, n=2)
        dbg = scaler.debug()
        assert dbg["config"]["max_replicas"] == 4
        assert dbg["tiers"]["fused"]["size"] == 2
        assert dbg["decisions"][-1]["action"] == "scale_up"
        assert dbg["scale_ups"] == 1

    def test_decision_ring_is_bounded(self):
        scaler, gw, tel, prov, clock = _mk(
            2, config=_cfg(decision_ring=4, up_cooldown_s=0.001,
                           max_actions_per_window=1000,
                           actions_window_s=1.0))
        tel.slo = _slo(ttft=(1.5, 1.2))
        _tick(scaler, clock, n=20)
        assert len(scaler.debug()["decisions"]) <= 4


class TestEnvContract:
    def test_inert_by_default(self, monkeypatch):
        monkeypatch.delenv("KUBEFLOW_TPU_AUTOSCALE_ENABLE", raising=False)
        assert autoscaler_from_env() is None

    def test_enable_with_overrides(self, monkeypatch):
        monkeypatch.setenv("KUBEFLOW_TPU_AUTOSCALE_ENABLE", "1")
        monkeypatch.setenv("KUBEFLOW_TPU_AUTOSCALE_MAX_REPLICAS", "8")
        monkeypatch.setenv("KUBEFLOW_TPU_AUTOSCALE_UP_COOLDOWN_S", "12.5")
        monkeypatch.setenv("KUBEFLOW_TPU_AUTOSCALE_STALE_AFTER_S", "3")
        cfg = autoscaler_from_env()
        assert cfg is not None
        assert cfg.max_replicas == 8
        assert cfg.up_cooldown_s == 12.5
        assert cfg.stale_after_s == 3.0

    @pytest.mark.parametrize("name,value", [
        ("KUBEFLOW_TPU_AUTOSCALE_ENABLE", "maybe"),
        ("KUBEFLOW_TPU_AUTOSCALE_MAX_REPLICAS", "zero"),
        ("KUBEFLOW_TPU_AUTOSCALE_MAX_REPLICAS", "0"),
        ("KUBEFLOW_TPU_AUTOSCALE_DRAIN_BUDGET_S", "-5"),
    ])
    def test_garbage_fails_fast(self, monkeypatch, name, value):
        monkeypatch.setenv("KUBEFLOW_TPU_AUTOSCALE_ENABLE", "1")
        monkeypatch.setenv(name, value)
        with pytest.raises(ValueError, match="KUBEFLOW_TPU_AUTOSCALE"):
            autoscaler_from_env()


class TestProvisionerDrainedProbe:
    def test_unreachable_replica_counts_as_drained(self):
        prov = WarmSliceProvisioner(object(), probe_timeout_s=0.2)
        assert prov.drained("127.0.0.1:1") is True  # nothing listens

    def test_hooks_take_precedence(self):
        seen = []
        prov = WarmSliceProvisioner(
            object(), drain_fn=seen.append,
            drained_fn=lambda ep: False, release_fn=seen.append,
        )
        prov.drain("a:1")
        assert prov.drained("a:1") is False
        prov.release("a:1")
        assert seen == ["a:1", "a:1"]


class TestRealFleetIntegration:
    def test_scale_down_never_drops_a_stream(self):
        """Organic ebb over a REAL 2-replica InferenceServer fleet: the
        autoscaler drains one replica while streams are in flight; every
        stream ends in [DONE] with its full token count, nothing is shed
        or failed, and the slice is released only once the drained
        server emptied (HTTP-poll drained probe)."""
        import jax

        from kubeflow_tpu.models import llama as L
        from kubeflow_tpu.models.gateway import ServingGateway
        from kubeflow_tpu.models.paged import PagedBatcher
        from kubeflow_tpu.models.server import InferenceServer
        from kubeflow_tpu.models.serving import GenerationConfig
        from kubeflow_tpu.observability.signals import (
            FleetTelemetry,
            SignalsConfig,
        )
        from kubeflow_tpu.observability.slo import default_objectives

        cfg = L.LLAMA_CONFIGS["tiny"]
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        tokens = 12
        servers = [
            InferenceServer(
                PagedBatcher(
                    params, cfg,
                    gen=GenerationConfig(max_new_tokens=tokens, eos_id=-1),
                    slots=8, num_blocks=128, block_size=16,
                    prompt_bucket=64,
                ),
                port=0, drain_s=60.0,
            ).start()
            for _ in range(2)
        ]
        by_ep = {f"{s.host}:{s.port}": s for s in servers}
        released: list = []

        def drain_fn(ep):
            # A real teardown is SIGTERM → the server's own graceful
            # drain; in-process that is stop(), which blocks until the
            # in-flight work finishes — so off-thread.
            threading.Thread(target=by_ep[ep].stop, daemon=True).start()

        # Unreachable thresholds: burns stay 0, so the only pressure the
        # loop can see is ebb — exactly the scale-down-mid-stream case.
        telemetry = FleetTelemetry(
            SignalsConfig(window_s=0.5, windows=60),
            objectives=default_objectives(
                ttft_p95_s=1000.0, inter_token_p95_s=1000.0,
                queue_wait_p95_s=1000.0,
            ),
        )
        gw = ServingGateway(
            sorted(by_ep), port=0, block_size=16, health_interval_s=0.1,
            telemetry=telemetry,
            autoscaler_config=AutoscalerConfig(
                min_replicas=1, max_replicas=2, down_consecutive=2,
                down_cooldown_s=0.2, up_cooldown_s=0.2,
                max_actions_per_window=8, actions_window_s=30.0,
                drain_budget_s=60.0, stale_after_s=5.0,
                low_batch_fill=0.94, high_batch_fill=0.95,
            ),
        )
        gw.autoscaler.provisioner = WarmSliceProvisioner(
            gw, drain_fn=drain_fn, release_fn=released.append,
        )
        gw.start()
        streams = 6
        collected: list = [[] for _ in range(streams)]

        def reader(i):
            conn = http.client.HTTPConnection(gw.host, gw.port,
                                              timeout=120.0)
            try:
                conn.request(
                    "POST", "/v1/completions",
                    json.dumps({
                        "prompt": list(range(5 * i + 3, 5 * i + 19)),
                        "stream": True, "max_tokens": tokens,
                    }).encode(),
                    {"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
                while True:
                    line = resp.fp.readline()
                    if not line:
                        break
                    if line.startswith(b"data:"):
                        collected[i].append(line)
                    if line == b"data: [DONE]\n":
                        break
            finally:
                conn.close()

        try:
            threads = [
                threading.Thread(target=reader, args=(i,), daemon=True)
                for i in range(streams)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=180.0)
            assert not any(t.is_alive() for t in threads)
            # Every stream complete: full token count then [DONE].
            for i, lines in enumerate(collected):
                assert lines and lines[-1] == b"data: [DONE]\n", i
                assert not any(b'"error"' in ln for ln in lines), i
                assert len(lines) >= tokens, i
            # The ebb decision landed and the drain ran to release.
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                scaler = gw.stats()["autoscaler"]
                if released and not scaler["draining"]:
                    break
                time.sleep(0.05)
            scaler = gw.stats()["autoscaler"]
            assert scaler["scale_downs"] >= 1
            assert len(released) >= 1
            assert not scaler["draining"]
            assert released[0] not in gw.replica_endpoints()
            stats = gw.stats()
            assert stats["shed"] == 0
            assert stats["failed"] == 0
        finally:
            gw.stop()
            for s in servers:
                try:
                    s.stop()
                except Exception:
                    pass


class TestLockSplit:
    """The tick/_lock split (kftpu-lock-held-await fix): the state lock
    is never held across provisioner I/O, so reader surfaces stay
    responsive mid-tick, and ticks are single-flighted."""

    class _BlockingProvisioner(FakeProvisioner):
        def __init__(self):
            super().__init__()
            self.entered = threading.Event()
            self.unblock = threading.Event()

        def drained(self, ep):
            self.entered.set()
            assert self.unblock.wait(10), "test never unblocked the probe"
            return True

    def _blocked_tick(self):
        scaler, gw, tel, _, clock = _mk(1)
        prov = self._BlockingProvisioner()
        scaler.provisioner = prov
        # A drain already past its budget: tick()'s first move is the
        # drained() probe, which parks on the event.
        scaler._draining[EP0] = {
            "tier": "fused", "since": clock.t - 60.0,
            "deadline": clock.t - 1.0,
        }
        tick_thread = threading.Thread(target=scaler.tick, daemon=True)
        tick_thread.start()
        assert prov.entered.wait(5)
        return scaler, prov, tick_thread

    def test_stats_and_debug_respond_while_probe_blocks(self):
        scaler, prov, tick_thread = self._blocked_tick()
        try:
            got: list = []
            reader = threading.Thread(
                target=lambda: got.append((scaler.stats(), scaler.debug())),
                daemon=True,
            )
            reader.start()
            reader.join(2.0)
            assert got, "stats()/debug() blocked behind a provisioner probe"
            stats, debug = got[0]
            assert EP0 in stats["draining"]
            assert "decisions" in debug
        finally:
            prov.unblock.set()
            tick_thread.join(5.0)
            assert not tick_thread.is_alive()

    def test_overlapping_tick_is_single_flighted(self):
        scaler, prov, tick_thread = self._blocked_tick()
        try:
            # The cadence fires again while the probe is still parked:
            # the overlapping tick must return immediately and empty,
            # not queue behind the slow claim walk.
            assert scaler.tick() == []
        finally:
            prov.unblock.set()
            tick_thread.join(5.0)
            assert not tick_thread.is_alive()
