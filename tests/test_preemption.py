"""Recovery escalation state machine (controller/preemption.py).

FakeClock-driven coverage of the full ladder: interruption marking →
deadline-bounded polling with progress events → escalation (warm-pool
claim, else StatefulSet recreate) → terminal ``SliceRecoveryFailed`` →
late recovery clearing all state and stamping the interruption duration.

The chaos catalog (tests/test_chaos_catalog.py) exercises the same ladder
under storms and apiserver flaps; these tests pin down each individual
transition with exact clock control.
"""

from __future__ import annotations

import copy

from kubeflow_tpu.api import annotations as ann
from kubeflow_tpu.api.notebook import TPUSpec
from kubeflow_tpu.api.slicepool import new_slicepool
from kubeflow_tpu.controller.preemption import (
    RECOVERY_FAILED_CONDITION,
    RecoveryConfig,
)
from kubeflow_tpu.k8s import objects as obj_util
from kubeflow_tpu.k8s.events import events_for

from tests.harness import make_env, tpu_notebook

# Small values so a full ladder (2 escalations + terminal) fits in a few
# hundred simulated seconds.
CFG = RecoveryConfig(
    deadline_s=60.0,
    poll_initial_s=5.0,
    poll_max_s=20.0,
    max_escalations=2,
    terminal_requeue_s=600.0,
)


def _ready_env(node_hosts=4, warm_pool=False, recovery_config=CFG,
               annotations=None):
    env = make_env(
        node_pools=(("tpu-v5-lite-podslice", "4x4", node_hosts, 4),),
        recovery_config=recovery_config,
    )
    if warm_pool:
        env.cluster.create(
            new_slicepool("pool", "ns", TPUSpec("v5e", "4x4"), warm_replicas=1)
        )
        env.manager.run_until_idle()
    env.cluster.create(tpu_notebook(annotations=annotations or {}))
    env.manager.run_until_idle()
    nb = env.cluster.get("Notebook", "nb", "ns")
    assert nb["status"]["readyReplicas"] == 4
    return env


def _interrupt(env, pod="nb-2", kill_node=True):
    """Preempt one host pod; optionally reclaim its node so the replacement
    can never bind (withheld capacity). Preempt BEFORE deleting the node:
    within the pod's MODIFIED event the slice-health map runs before the
    fake kubelet's (registration order), so the Failed pod is observed;
    node-death-first would let the kubelet GC it unseen. This is also the
    physically accurate spot-reclaim order (pod gets DisruptionTarget,
    then the node goes away)."""
    node = env.cluster.get("Pod", pod, "ns")["spec"]["nodeName"]
    node_obj = copy.deepcopy(env.cluster.get("Node", node))
    env.kubelet.preempt_pod(pod, "ns")
    if kill_node:
        env.cluster.delete("Node", node)
    env.manager.run_until_idle()
    return node_obj


def _restore_node(env, node_obj):
    restored = copy.deepcopy(node_obj)
    for key in ("uid", "resourceVersion", "generation", "creationTimestamp"):
        restored["metadata"].pop(key, None)
    env.cluster.create(restored)


def _anns(env):
    return obj_util.annotations_of(env.cluster.get("Notebook", "nb", "ns"))


def _condition(env, cond_type):
    nb = env.cluster.get("Notebook", "nb", "ns")
    for c in nb.get("status", {}).get("conditions", []):
        if c["type"] == cond_type:
            return c
    return None


def _events(env, reason):
    return [
        e for e in events_for(env.cluster, "Notebook", "nb", "ns")
        if e["reason"] == reason
    ]


def _metric(env, name):
    for line in env.metrics.expose().decode().splitlines():
        if line.startswith(name + " "):
            return float(line.split()[-1])
    return 0.0


class TestInterruptionMarking:
    def test_withheld_capacity_marks_state_and_starts_clock(self):
        env = _ready_env()
        t0 = env.clock.now()
        _interrupt(env)

        anns = _anns(env)
        assert ann.TPU_SLICE_INTERRUPTED in anns
        assert float(anns[ann.TPU_RECOVERY_STARTED]) == t0
        assert ann.TPU_RECOVERY_ESCALATIONS not in anns
        # Recovery is timer-driven from here on.
        assert env.manager.next_requeue_in() is not None
        assert env.manager.next_requeue_in() <= CFG.poll_initial_s

    def test_repeat_failures_keep_original_start(self):
        # The deadline measures the whole outage, not the last pod flap.
        env = _ready_env()
        t0 = env.clock.now()
        _interrupt(env, pod="nb-2")
        env.manager.tick(10)
        env.kubelet.preempt_pod("nb-3", "ns")
        env.manager.run_until_idle()
        assert float(_anns(env)[ann.TPU_RECOVERY_STARTED]) == t0

    def test_progress_events_dedup_across_polls(self):
        env = _ready_env()
        _interrupt(env)
        for _ in range(5):
            env.manager.tick(CFG.poll_initial_s)

        progress = _events(env, "SliceRecoveryProgress")
        # Identical ready/total message → one Event object, bumped count.
        assert len(progress) == 1
        assert progress[0]["count"] >= 2
        assert "3/4 hosts Ready" in progress[0]["message"]


class TestTransientRecovery:
    def test_recovery_with_capacity_clears_state_and_stamps_duration(self):
        env = _ready_env()
        # Keep the node: the replacement pod binds right back.
        _interrupt(env, kill_node=False)

        anns = _anns(env)
        assert ann.TPU_SLICE_INTERRUPTED not in anns
        assert ann.TPU_RECOVERY_STARTED not in anns
        assert anns[ann.TPU_LAST_INTERRUPTION_DURATION] == "0s"
        assert _events(env, "SliceRecovered")
        assert _metric(env, "tpu_slice_recovery_seconds_count") == 1.0
        assert _metric(env, "tpu_slice_recovery_escalations_total") == 0.0

    def test_duration_stamp_reflects_outage_length(self):
        env = _ready_env()
        node_obj = _interrupt(env)
        for _ in range(4):  # 40s of withheld capacity, inside the deadline
            env.manager.tick(10)
        _restore_node(env, node_obj)
        env.manager.tick(CFG.poll_max_s)

        anns = _anns(env)
        assert ann.TPU_SLICE_INTERRUPTED not in anns
        stamp = float(anns[ann.TPU_LAST_INTERRUPTION_DURATION].rstrip("s"))
        assert 40 <= stamp <= 60 + CFG.poll_max_s
        recovered = _events(env, "SliceRecovered")
        assert "interruption" in recovered[0]["message"]

    def test_recovery_annotations_never_roll_the_pod_template(self):
        # Lifecycle annotations must stay off the STS pod template, or each
        # interruption would roll every host pod a second time.
        env = _ready_env()
        _interrupt(env, kill_node=False)
        assert ann.TPU_LAST_INTERRUPTION_DURATION in _anns(env)
        env.manager.run_until_idle()
        sts = env.cluster.get("StatefulSet", "nb", "ns")
        tmpl_anns = (
            sts["spec"]["template"]["metadata"].get("annotations", {})
        )
        for key in (
            ann.TPU_SLICE_INTERRUPTED,
            ann.TPU_RECOVERY_STARTED,
            ann.TPU_RECOVERY_ESCALATIONS,
            ann.TPU_LAST_INTERRUPTION_DURATION,
        ):
            assert key not in tmpl_anns


class TestEscalation:
    def test_deadline_claims_warm_slice_and_recovers(self):
        # 8 hosts: 4 for the notebook, 4 provisioned under the warm
        # placeholder. Killing one notebook node leaves the replacement pod
        # unschedulable until the claim frees placeholder capacity.
        env = _ready_env(node_hosts=8, warm_pool=True)
        _interrupt(env)
        for _ in range(20):
            env.manager.tick(10)

        anns = _anns(env)
        assert ann.TPU_SLICE_INTERRUPTED not in anns
        assert ann.TPU_RECOVERY_ESCALATIONS not in anns
        assert ann.TPU_LAST_INTERRUPTION_DURATION in anns
        escalated = _events(env, "SliceRecoveryEscalated")
        assert len(escalated) == 1
        assert "warm slice from pool pool" in escalated[0]["message"]
        assert _events(env, "ClaimedWarmSlice")
        assert _events(env, "SliceRecovered")
        assert _metric(env, "tpu_slice_recovery_escalations_total") == 1.0
        assert _metric(env, "tpu_slice_recovery_seconds_count") == 1.0
        assert not env.manager.reconcile_errors

    def test_deadline_without_pool_recreates_statefulsets(self):
        env = _ready_env()
        old_uid = env.cluster.get("StatefulSet", "nb", "ns")["metadata"]["uid"]
        _interrupt(env)
        env.manager.tick(CFG.deadline_s + 1)

        assert _anns(env)[ann.TPU_RECOVERY_ESCALATIONS] == "1"
        escalated = _events(env, "SliceRecoveryEscalated")
        assert len(escalated) == 1
        assert "recreating StatefulSet" in escalated[0]["message"]
        # The notebook reconciler already re-created the STS from spec.
        sts = env.cluster.get("StatefulSet", "nb", "ns")
        assert sts["metadata"]["uid"] != old_uid
        assert _metric(env, "tpu_slice_recovery_escalations_total") == 1.0

    def test_escalation_rearms_deadline_then_capacity_return_recovers(self):
        env = _ready_env()
        node_obj = _interrupt(env)
        env.manager.tick(CFG.deadline_s + 1)
        assert _anns(env)[ann.TPU_RECOVERY_ESCALATIONS] == "1"
        # Inside the re-armed deadline: still polling, no second escalation.
        env.manager.tick(CFG.poll_initial_s)
        assert _anns(env)[ann.TPU_RECOVERY_ESCALATIONS] == "1"

        _restore_node(env, node_obj)
        for _ in range(4):
            env.manager.tick(CFG.poll_max_s)
        anns = _anns(env)
        assert ann.TPU_SLICE_INTERRUPTED not in anns
        assert ann.TPU_RECOVERY_ESCALATIONS not in anns
        assert ann.TPU_LAST_INTERRUPTION_DURATION in anns
        assert _condition(env, RECOVERY_FAILED_CONDITION) is None


class TestTerminalState:
    def _run_to_terminal(self, env):
        for _ in range(40):
            env.manager.tick(10)
            cond = _condition(env, RECOVERY_FAILED_CONDITION)
            if cond and cond["status"] == "True":
                return cond
        raise AssertionError("never reached SliceRecoveryFailed")

    def test_exhausted_escalations_go_terminal(self):
        env = _ready_env()
        _interrupt(env)
        cond = self._run_to_terminal(env)

        assert cond["reason"] == "RecoveryDeadlineExceeded"
        assert "2 escalations" in cond["message"]
        failed_events = _events(env, RECOVERY_FAILED_CONDITION)
        assert failed_events and failed_events[0]["type"] == "Warning"
        assert _anns(env)[ann.TPU_RECOVERY_ESCALATIONS] == "2"
        assert _metric(env, "tpu_slice_recovery_failed_total") == 1.0
        assert _metric(env, "tpu_slice_recovery_escalations_total") == 2.0

    def test_terminal_state_is_quiet(self):
        # Visible but cheap: one long idle requeue per terminal_requeue_s,
        # no event spam, no status churn.
        env = _ready_env()
        _interrupt(env)
        self._run_to_terminal(env)
        failed_before = len(_events(env, RECOVERY_FAILED_CONDITION))
        calls = env.manager.tick(CFG.terminal_requeue_s)
        assert calls <= 4
        assert len(_events(env, RECOVERY_FAILED_CONDITION)) == failed_before
        assert not env.manager.reconcile_errors

    def test_late_capacity_flips_terminal_condition_and_recovers(self):
        env = _ready_env()
        node_obj = _interrupt(env)
        self._run_to_terminal(env)

        _restore_node(env, node_obj)
        env.manager.tick(CFG.terminal_requeue_s)

        anns = _anns(env)
        assert ann.TPU_SLICE_INTERRUPTED not in anns
        assert ann.TPU_RECOVERY_STARTED not in anns
        assert ann.TPU_RECOVERY_ESCALATIONS not in anns
        assert ann.TPU_LAST_INTERRUPTION_DURATION in anns
        cond = _condition(env, RECOVERY_FAILED_CONDITION)
        # Flipped, not deleted: the transition itself is signal.
        assert cond["status"] == "False"
        assert cond["reason"] == "Recovered"
        assert _events(env, "SliceRecovered")
        assert _metric(env, "tpu_slice_recovery_seconds_count") == 1.0


class TestStopAndConfig:
    def test_stopping_notebook_clears_recovery_state(self):
        env = _ready_env()
        _interrupt(env)
        assert ann.TPU_SLICE_INTERRUPTED in _anns(env)

        nb = env.cluster.get("Notebook", "nb", "ns")
        obj_util.annotations_of(nb)[ann.STOP] = "2026-01-01T00:00:00Z"
        env.cluster.update(nb)
        env.manager.run_until_idle()
        env.manager.tick(CFG.poll_max_s)

        anns = _anns(env)
        for key in (
            ann.TPU_SLICE_INTERRUPTED,
            ann.TPU_RECOVERY_STARTED,
            ann.TPU_RECOVERY_ESCALATIONS,
            ann.TPU_RECOVERY_LAST_ESCALATION,
        ):
            assert key not in anns
        assert ann.STOP in anns

    def test_recovery_config_from_env(self):
        cfg = RecoveryConfig.from_env({
            "SLICE_RECOVERY_DEADLINE_SECONDS": "120",
            "SLICE_RECOVERY_POLL_SECONDS": "2",
            "SLICE_RECOVERY_POLL_MAX_SECONDS": "30",
            "SLICE_RECOVERY_MAX_ESCALATIONS": "1",
            "SLICE_RECOVERY_TERMINAL_REQUEUE_SECONDS": "900",
        })
        assert cfg == RecoveryConfig(120.0, 2.0, 30.0, 1, 900.0)
        assert RecoveryConfig.from_env({}) == RecoveryConfig()


class TestCheckpointAwareEvents:
    """PR 3 links the escalation ladder to the in-pod emergency-save
    window: interruption/escalation events must tell the operator whether
    training state survived and where to resume from."""

    GRACE = {ann.TPU_CHECKPOINT_GRACE: "60"}

    def test_interruption_event_points_at_emergency_checkpoint(self):
        env = _ready_env(annotations=self.GRACE)
        _interrupt(env)
        ev = _events(env, "SliceInterrupted")
        assert len(ev) == 1
        msg = ev[0]["message"]
        assert "resume from the emergency checkpoint in /mnt/checkpoints" in msg
        assert "60s SIGTERM grace" in msg

    def test_interruption_event_without_grace_says_state_gone(self):
        env = _ready_env()
        _interrupt(env)
        assert "in-notebook JAX state is gone" in (
            _events(env, "SliceInterrupted")[0]["message"]
        )

    def test_sts_recreate_event_quotes_termination_grace(self):
        """grace(60) + flush margin(30): the same number the webhook put
        in terminationGracePeriodSeconds, so the event explains the slow
        teardown the ladder just triggered."""
        env = _ready_env(annotations=self.GRACE)
        _interrupt(env)
        env.manager.tick(CFG.deadline_s + 1)
        escalated = _events(env, "SliceRecoveryEscalated")
        assert len(escalated) == 1
        assert ("surviving hosts get 90s termination grace for an "
                "emergency checkpoint") in escalated[0]["message"]

    def test_sts_recreate_event_silent_without_grace(self):
        env = _ready_env()
        _interrupt(env)
        env.manager.tick(CFG.deadline_s + 1)
        escalated = _events(env, "SliceRecoveryEscalated")
        assert len(escalated) == 1
        assert "termination grace" not in escalated[0]["message"]
