"""Test configuration.

Control-plane tests are pure Python. Model/parallel tests run JAX on a
virtual 8-device CPU mesh so multi-chip sharding is exercised without TPU
hardware (the driver separately dry-runs the multi-chip path).

Note: on this machine the TPU is exposed through a platform plugin that
ignores the JAX_PLATFORMS env var, so the CPU override must go through
jax.config before the backend initializes — hence it lives at conftest
import time, before any test imports jax.
"""

import os

# Belt and braces for environments where the env vars DO work.
os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    # JAX >= 0.5 knob; 0.4.x raises AttributeError (the XLA_FLAGS fallback
    # above already provides the 8-device CPU mesh there).
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass
