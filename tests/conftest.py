"""Test configuration.

Control-plane tests are pure Python. Model/parallel tests run JAX on a
virtual 8-device CPU mesh so multi-chip sharding is exercised without TPU
hardware (the driver separately dry-runs the multi-chip path).

The env vars must be set before jax is first imported anywhere in the test
process, hence they live at conftest import time.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
