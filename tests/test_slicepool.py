"""SlicePool tests: warm placeholder lifecycle + notebook claim path.

TPU-native subsystem with no reference counterpart (the reference spawn
path is always cold); the claim flow is asserted end-to-end through the
envtest tier — pool warms a slice, notebook claims it, pods land on the
freed capacity, pool refills.
"""

import pytest

from kubeflow_tpu.api import slicepool as sp
from kubeflow_tpu.api.notebook import TPUSpec
from kubeflow_tpu.api.slicepool import new_slicepool
from kubeflow_tpu.controller import slicepool as ctrl_sp
from kubeflow_tpu.k8s import objects as obj_util
from kubeflow_tpu.k8s.events import events_for

from tests.harness import make_env, tpu_notebook


def _pool(warm=1, topology="4x4", namespace="ns", name="pool"):
    return new_slicepool(
        name, namespace, TPUSpec(accelerator="v5e", topology=topology),
        warm_replicas=warm,
    )


def _warm_stses(env, namespace="ns"):
    return env.cluster.list(
        "StatefulSet", namespace, label_selector={sp.STATE_LABEL: sp.STATE_WARM}
    )


class TestWarmPlaceholders:
    def test_pool_provisions_warm_slices(self):
        env = make_env()
        env.cluster.create(_pool(warm=1))
        env.manager.run_until_idle()

        warm = _warm_stses(env)
        assert len(warm) == 1
        sts = warm[0]
        spec = sts["spec"]["template"]["spec"]
        c = spec["containers"][0]
        assert c["resources"]["limits"]["google.com/tpu"] == "4"
        assert spec["nodeSelector"]["cloud.google.com/gke-tpu-topology"] == "4x4"
        assert sts["spec"]["replicas"] == 4
        assert sts["spec"]["podManagementPolicy"] == "Parallel"
        # Fake kubelet provisions the placeholder pods to Ready; status
        # reflects a fully warm pool.
        pool = env.cluster.get("SlicePool", "pool", "ns")
        assert pool["status"]["readyReplicas"] == 1

    def test_scale_down_retires_extras(self):
        # Each 4x4 warm slice needs its own 4-host node pool.
        env = make_env(
            node_pools=(
                ("tpu-v5-lite-podslice", "4x4", 4, 4),
                ("tpu-v5-lite-podslice", "4x4", 4, 4),
            )
        )
        env.cluster.create(_pool(warm=2))
        env.manager.run_until_idle()
        assert len(_warm_stses(env)) == 2

        pool = env.cluster.get("SlicePool", "pool", "ns")
        pool["spec"]["warmReplicas"] = 1
        env.cluster.update(pool)
        env.manager.run_until_idle()
        assert len(_warm_stses(env)) == 1

    def test_invalid_topology_surfaces_condition(self):
        env = make_env()
        env.cluster.create(_pool(topology="9x9"))
        env.manager.run_until_idle()
        pool = env.cluster.get("SlicePool", "pool", "ns")
        conds = {c["type"]: c["status"] for c in pool["status"]["conditions"]}
        assert conds["TopologyValid"] == "False"
        assert not _warm_stses(env)

    def test_pool_deletion_collects_placeholders(self):
        env = make_env()
        env.cluster.create(_pool(warm=1))
        env.manager.run_until_idle()
        assert _warm_stses(env)
        env.cluster.delete("SlicePool", "pool", "ns")
        env.manager.run_until_idle()
        assert not _warm_stses(env)


class TestAutoscale:
    def _auto_pool(self, lo=0, hi=2, cooldown=300):
        obj = _pool(warm=1)
        obj["spec"]["autoscale"] = {
            "min": lo, "max": hi, "scaleDownAfterSeconds": cooldown,
        }
        return obj

    def test_demand_driven_from_zero(self):
        """min=0: no warm capacity until a miss proves demand; the next
        notebook after the miss finds a warm slice."""
        env = make_env(
            node_pools=(
                ("tpu-v5-lite-podslice", "4x4", 4, 4),
                ("tpu-v5-lite-podslice", "4x4", 4, 4),
            )
        )
        env.cluster.create(self._auto_pool())
        env.manager.run_until_idle()
        assert not _warm_stses(env)  # min=0 → nothing warm yet

        env.cluster.create(tpu_notebook(name="nb1"))  # miss → demand signal
        env.manager.run_until_idle()
        nb1 = env.cluster.get("Notebook", "nb1", "ns")
        assert sp.CLAIMED_FROM not in nb1["metadata"].get("annotations", {})
        pool = env.cluster.get("SlicePool", "pool", "ns")
        assert pool["status"]["autoscaleTarget"] == 1
        assert len(_warm_stses(env)) == 1

        env.cluster.create(tpu_notebook(name="nb2"))  # hit
        env.manager.run_until_idle()
        nb2 = env.cluster.get("Notebook", "nb2", "ns")
        assert nb2["metadata"]["annotations"][sp.CLAIMED_FROM] == "pool"

    def test_idle_scale_down_after_cooldown(self):
        env = make_env()
        env.cluster.create(self._auto_pool(lo=0, hi=2, cooldown=300))
        env.manager.run_until_idle()
        # Force demand, then let it go idle.
        env.cluster.create(tpu_notebook(name="nb1"))
        env.manager.run_until_idle()
        pool = env.cluster.get("SlicePool", "pool", "ns")
        assert pool["status"]["autoscaleTarget"] == 1

        env.manager.tick(301)  # periodic requeue notices idleness
        env.manager.run_until_idle()
        pool = env.cluster.get("SlicePool", "pool", "ns")
        assert pool["status"]["autoscaleTarget"] == 0
        assert not _warm_stses(env)

    def test_concurrent_misses_scale_by_count(self):
        """Three cold spawns before the pool reconciles once must grow the
        target by three — the miss COUNTER, not a collapsed timestamp."""
        env = make_env(
            node_pools=tuple(
                ("tpu-v5-lite-podslice", "4x4", 4, 4) for _ in range(5)
            )
        )
        env.cluster.create(self._auto_pool(lo=0, hi=5))
        env.manager.run_until_idle()
        for i in range(3):
            env.cluster.create(tpu_notebook(name=f"nb{i}"))
        env.manager.run_until_idle()
        pool = env.cluster.get("SlicePool", "pool", "ns")
        assert pool["status"]["autoscaleTarget"] == 3

    def test_fixed_pools_never_stamped(self):
        env = make_env()
        env.cluster.create(_pool(warm=0, name="fixed"))
        env.manager.run_until_idle()
        env.cluster.create(tpu_notebook())
        env.manager.run_until_idle()
        fixed = env.cluster.get("SlicePool", "fixed", "ns")
        anns = fixed["metadata"].get("annotations", {})
        assert sp.LAST_MISS not in anns and sp.MISS_COUNT not in anns

    def test_disabling_autoscale_clears_state(self):
        env = make_env()
        env.cluster.create(self._auto_pool(lo=1, hi=2))
        env.manager.run_until_idle()
        pool = env.cluster.get("SlicePool", "pool", "ns")
        assert pool["status"]["autoscaleTarget"] == 1
        del pool["spec"]["autoscale"]
        pool["spec"]["warmReplicas"] = 1
        env.cluster.update(pool)
        env.manager.run_until_idle()
        pool = env.cluster.get("SlicePool", "pool", "ns")
        assert "autoscaleTarget" not in pool["status"]
        assert "lastScaleTime" not in pool["status"]

    def test_reenable_does_not_resurrect_stale_demand(self):
        """Disable autoscale after misses accrued, then re-enable: the
        target must restart from min, not replay the dead miss counter."""
        env = make_env()
        env.cluster.create(self._auto_pool(lo=0, hi=3))
        env.manager.run_until_idle()
        env.cluster.create(tpu_notebook())  # miss → counter=1, target 1
        env.manager.run_until_idle()
        pool = env.cluster.get("SlicePool", "pool", "ns")
        assert pool["status"]["autoscaleTarget"] == 1

        del pool["spec"]["autoscale"]
        pool["spec"]["warmReplicas"] = 0
        env.cluster.update(pool)
        env.manager.run_until_idle()
        pool = env.cluster.get("SlicePool", "pool", "ns")
        assert sp.MISS_COUNT not in pool["metadata"].get("annotations", {})

        pool["spec"]["autoscale"] = {
            "min": 0, "max": 3, "scaleDownAfterSeconds": 300,
        }
        env.cluster.update(pool)
        env.manager.run_until_idle()
        pool = env.cluster.get("SlicePool", "pool", "ns")
        assert pool["status"]["autoscaleTarget"] == 0

    def test_capped_at_max(self):
        env = make_env(
            node_pools=tuple(
                ("tpu-v5-lite-podslice", "4x4", 4, 4) for _ in range(3)
            )
        )
        env.cluster.create(self._auto_pool(lo=0, hi=1))
        env.manager.run_until_idle()
        for i in range(3):  # repeated misses
            env.cluster.create(tpu_notebook(name=f"nb{i}"))
            env.manager.run_until_idle()
        pool = env.cluster.get("SlicePool", "pool", "ns")
        assert pool["status"]["autoscaleTarget"] == 1


class TestClaimPath:
    def test_notebook_claims_warm_slice_on_contended_capacity(self):
        """The core value proof: ONE slice's worth of nodes, fully held by
        the warm placeholder. The claim must free it, the notebook's pods
        must bind to the (already-provisioned) nodes, and the pool's
        refill placeholder must queue behind them as Pending."""
        env = make_env()  # one 4-host 4x4 pool
        env.cluster.create(_pool(warm=1))
        env.manager.run_until_idle()
        warm_before = _warm_stses(env)
        assert len(warm_before) == 1

        env.cluster.create(tpu_notebook())
        env.manager.run_until_idle()

        # Claimed placeholder is gone; a refill (new generation) exists.
        warm_after = _warm_stses(env)
        assert len(warm_after) == 1
        assert obj_util.name_of(warm_after[0]) != obj_util.name_of(warm_before[0])

        # The notebook got the capacity: all 4 hosts Ready.
        nb = env.cluster.get("Notebook", "nb", "ns")
        assert nb["status"]["readyReplicas"] == 4
        assert nb["metadata"]["annotations"][sp.CLAIMED_FROM] == "pool"
        assert any(
            e["reason"] == "ClaimedWarmSlice"
            for e in events_for(env.cluster, "Notebook", "nb", "ns")
        )
        # The refill is Pending (capacity now belongs to the notebook).
        refill = env.cluster.get("StatefulSet", obj_util.name_of(warm_after[0]), "ns")
        assert refill.get("status", {}).get("readyReplicas", 0) == 0

        text = env.metrics.expose().decode()
        assert "tpu_slicepool_claims_total 1.0" in text

    def test_topology_mismatch_is_a_miss(self):
        env = make_env(
            node_pools=(
                ("tpu-v5-lite-podslice", "4x4", 4, 4),
                ("tpu-v5-lite-podslice", "2x2", 1, 4),
            )
        )
        env.cluster.create(_pool(warm=1, topology="2x2"))
        env.manager.run_until_idle()

        env.cluster.create(tpu_notebook())  # wants 4x4; pool holds 2x2
        env.manager.run_until_idle()

        assert len(_warm_stses(env)) == 1  # untouched
        nb = env.cluster.get("Notebook", "nb", "ns")
        assert sp.CLAIMED_FROM not in nb["metadata"].get("annotations", {})
        text = env.metrics.expose().decode()
        assert "tpu_slicepool_claim_misses_total 1.0" in text

    def test_resume_after_stop_claims_again(self):
        """A culled/stopped notebook released its capacity; resume is a
        fresh 0→N transition and deserves a warm slice too."""
        from kubeflow_tpu.api import annotations as ann
        from kubeflow_tpu.k8s import objects as obj_util2

        env = make_env(
            node_pools=(
                ("tpu-v5-lite-podslice", "4x4", 4, 4),
                ("tpu-v5-lite-podslice", "4x4", 4, 4),
            )
        )
        env.cluster.create(_pool(warm=1))
        env.manager.run_until_idle()
        env.cluster.create(tpu_notebook())
        env.manager.run_until_idle()

        nb = env.cluster.get("Notebook", "nb", "ns")
        nb["metadata"]["annotations"][ann.STOP] = "2026-07-30T00:00:00Z"
        env.cluster.update(nb)
        env.manager.run_until_idle()
        sts = env.cluster.get("StatefulSet", "nb", "ns")
        assert sts["spec"]["replicas"] == 0

        nb = env.cluster.get("Notebook", "nb", "ns")
        obj_util2.remove_annotation(nb, ann.STOP)
        env.cluster.update(nb)
        env.manager.run_until_idle()

        text = env.metrics.expose().decode()
        assert "tpu_slicepool_claims_total 2.0" in text
        nb = env.cluster.get("Notebook", "nb", "ns")
        assert nb["status"]["readyReplicas"] == 4

    def test_no_pools_no_metrics_noise(self):
        env = make_env()
        env.cluster.create(tpu_notebook())
        env.manager.run_until_idle()
        text = env.metrics.expose().decode()
        assert "tpu_slicepool_claim_misses_total 0.0" in text
        assert "tpu_slicepool_claims_total 0.0" in text

    def test_claim_happens_once_not_per_reconcile(self):
        env = make_env(
            node_pools=(
                ("tpu-v5-lite-podslice", "4x4", 4, 4),
                ("tpu-v5-lite-podslice", "4x4", 4, 4),
            )
        )
        env.cluster.create(_pool(warm=2))
        env.manager.run_until_idle()
        env.cluster.create(tpu_notebook())
        env.manager.run_until_idle()
        # Touch the notebook to force more reconciles.
        nb = env.cluster.get("Notebook", "nb", "ns")
        obj_util.set_annotation(nb, "touch", "1")
        env.cluster.update(nb)
        env.manager.run_until_idle()

        text = env.metrics.expose().decode()
        assert "tpu_slicepool_claims_total 1.0" in text
        assert len(_warm_stses(env)) == 2  # claimed one refilled, other kept

    def test_repeated_zero_replica_reconcile_claims_once(self):
        """The claim is keyed on the CLAIMED_FROM intent marker, not on
        observed replicas: a reconcile that runs while the replica update
        is not yet visible (stale cache read, or the STS write failed
        right after the claim) must NOT drain a second placeholder for
        the same scale-up."""
        env = make_env(
            node_pools=(
                ("tpu-v5-lite-podslice", "4x4", 4, 4),
                ("tpu-v5-lite-podslice", "4x4", 4, 4),
            )
        )
        env.cluster.create(_pool(warm=2))
        env.manager.run_until_idle()
        env.cluster.create(tpu_notebook())
        env.manager.run_until_idle()

        # Simulate the not-yet-visible replica update: the STS reads back
        # at replicas 0 while the claim annotation is already recorded.
        sts = env.cluster.get("StatefulSet", "nb", "ns")
        sts["spec"]["replicas"] = 0
        env.cluster.update(sts)
        env.manager.run_until_idle()

        text = env.metrics.expose().decode()
        assert "tpu_slicepool_claims_total 1.0" in text  # no double claim
        # The reconciler restored the replica count (level-triggered).
        sts = env.cluster.get("StatefulSet", "nb", "ns")
        assert sts["spec"]["replicas"] == 4

    def test_claim_marker_cleared_while_stopped(self):
        from kubeflow_tpu.api import annotations as ann

        env = make_env()
        env.cluster.create(_pool(warm=1))
        env.manager.run_until_idle()
        env.cluster.create(tpu_notebook())
        env.manager.run_until_idle()
        nb = env.cluster.get("Notebook", "nb", "ns")
        assert sp.CLAIMED_FROM in nb["metadata"]["annotations"]

        nb["metadata"]["annotations"][ann.STOP] = "2026-07-30T00:00:00Z"
        env.cluster.update(nb)
        env.manager.run_until_idle()
        nb = env.cluster.get("Notebook", "nb", "ns")
        assert sp.CLAIMED_FROM not in nb["metadata"].get("annotations", {})


    def test_fenced_claim_survives_interleaving(self):
        # See TestClaimFencing for the race matrix; this is the smoke
        # check that the normal claim path still works end-to-end with
        # the fence in it (CLAIMED_BY never leaks onto the refill).
        env = make_env(
            node_pools=(
                ("tpu-v5-lite-podslice", "4x4", 4, 4),
                ("tpu-v5-lite-podslice", "4x4", 4, 4),
            )
        )
        env.cluster.create(_pool(warm=1))
        env.manager.run_until_idle()
        env.cluster.create(tpu_notebook())
        env.manager.run_until_idle()
        refill = _warm_stses(env)
        assert len(refill) == 1
        assert sp.CLAIMED_BY not in obj_util.annotations_of(refill[0])

    def test_multislice_notebook_claims_one_placeholder_per_slice(self):
        """Each slice of a multislice notebook is its own warm-capacity
        claim: the per-slice claim markers (CLAIMED_FROM, CLAIMED_FROM.1)
        must not suppress one another."""
        from kubeflow_tpu.api.notebook import TPUSpec, new_notebook

        env = make_env(
            node_pools=(
                ("tpu-v5-lite-podslice", "4x4", 4, 4),
                ("tpu-v5-lite-podslice", "4x4", 4, 4),
                ("tpu-v5-lite-podslice", "4x4", 4, 4),
            )
        )
        env.cluster.create(_pool(warm=2))
        env.manager.run_until_idle()
        assert len(_warm_stses(env)) == 2

        env.cluster.create(new_notebook(
            "ms", "ns", image="jax:latest",
            tpu=TPUSpec(accelerator="v5e", topology="4x4", slice_count=2),
        ))
        env.manager.run_until_idle()

        text = env.metrics.expose().decode()
        assert "tpu_slicepool_claims_total 2.0" in text
        nb = env.cluster.get("Notebook", "ms", "ns")
        anns = nb["metadata"]["annotations"]
        assert anns[sp.CLAIMED_FROM] == "pool"
        assert anns[f"{sp.CLAIMED_FROM}.1"] == "pool"

        # Stop clears BOTH markers.
        from kubeflow_tpu.api import annotations as ann
        nb["metadata"]["annotations"][ann.STOP] = "2026-07-30T00:00:00Z"
        env.cluster.update(nb)
        env.manager.run_until_idle()
        anns = env.cluster.get("Notebook", "ms", "ns")["metadata"].get(
            "annotations", {})
        assert sp.CLAIMED_FROM not in anns
        assert f"{sp.CLAIMED_FROM}.1" not in anns


class _InterposingClient:
    """Delegates everything to the real cluster, but fires ``trap`` once,
    just before the victim's first StatefulSet update (the fence write) —
    the exact window in which a concurrent claimant can race. FakeCluster
    is not thread-safe, so the race is reproduced by deterministic
    interposition rather than by threads (a thread race would exercise
    the fake's missing locks, not the fence)."""

    def __init__(self, cluster, trap):
        self._cluster = cluster
        self._trap = trap
        self.deleted = []

    def __getattr__(self, name):
        return getattr(self._cluster, name)

    def update(self, obj):
        if self._trap is not None and obj.get("kind") == "StatefulSet":
            trap, self._trap = self._trap, None
            trap()
        return self._cluster.update(obj)

    def delete(self, kind, name, namespace=None):
        self.deleted.append((kind, name))
        return self._cluster.delete(kind, name, namespace)


class TestClaimFencing:
    """Satellite invariant: two claimants racing the same warm slice must
    conflict-retry onto DISTINCT slices, or take a clean ClaimLost/miss —
    never both 'successfully' claim one placeholder. The fence is the
    CLAIMED_BY annotation written with the read's resourceVersion; the
    unfenced delete it replaced was check-then-act and let both racers
    win."""

    topo = TPUSpec(accelerator="v5e", topology="4x4").slice_topology()

    def _env(self, warm):
        env = make_env(
            node_pools=tuple(
                ("tpu-v5-lite-podslice", "4x4", 4, 4) for _ in range(warm)
            )
        )
        env.cluster.create(_pool(warm=warm))
        env.manager.run_until_idle()
        assert len(_warm_stses(env)) == warm
        return env

    def test_racing_claimants_get_distinct_slices(self):
        env = self._env(warm=2)
        stolen = []

        def steal():
            before = {obj_util.name_of(s) for s in _warm_stses(env)}
            assert ctrl_sp.claim_warm_slice(
                env.cluster, "ns", self.topo, claimant="adversary",
            ) == "pool"
            after = {obj_util.name_of(s) for s in _warm_stses(env)}
            stolen.extend(before - after)

        client = _InterposingClient(env.cluster, steal)
        assert ctrl_sp.claim_warm_slice(
            client, "ns", self.topo, claimant="victim",
        ) == "pool"

        victim_deleted = [n for k, n in client.deleted if k == "StatefulSet"]
        assert len(stolen) == 1 and len(victim_deleted) == 1
        assert victim_deleted[0] != stolen[0], (
            "both claimants claimed the same placeholder"
        )
        assert not _warm_stses(env)  # exactly two slices for two claimants

    def test_losing_the_last_slice_is_a_clean_miss(self):
        env = self._env(warm=1)

        def steal():
            assert ctrl_sp.claim_warm_slice(
                env.cluster, "ns", self.topo, claimant="adversary",
            ) == "pool"

        client = _InterposingClient(env.cluster, steal)
        assert ctrl_sp.claim_warm_slice(
            client, "ns", self.topo, claimant="victim",
        ) is None
        # The victim never issued a delete for a slice it did not own.
        assert not [n for k, n in client.deleted if k == "StatefulSet"]

    def test_prefenced_placeholder_is_skipped(self):
        """A placeholder carrying someone else's live fence is another
        claimant's slice mid-claim: walk past it, never contest it."""
        env = self._env(warm=2)
        first, second = sorted(_warm_stses(env), key=obj_util.name_of)
        fresh = env.cluster.get(
            "StatefulSet", obj_util.name_of(first), "ns"
        )
        obj_util.set_annotation(fresh, sp.CLAIMED_BY, "other-claimant")
        env.cluster.update(fresh)

        assert ctrl_sp.claim_warm_slice(
            env.cluster, "ns", self.topo, claimant="victim",
        ) == "pool"
        left = _warm_stses(env)
        assert [obj_util.name_of(s) for s in left] == [obj_util.name_of(first)]

    def test_claim_candidate_raises_claimlost_when_deleted(self):
        env = self._env(warm=1)
        chosen = _warm_stses(env)[0]
        env.cluster.delete("StatefulSet", obj_util.name_of(chosen), "ns")
        with pytest.raises(ctrl_sp.ClaimLost):
            ctrl_sp._claim_candidate(env.cluster, chosen, "victim")
