"""kftpu-lint tests: per-rule fixture corpus, suppression syntax, and the
tier-1 zero-unsuppressed-findings gate over kubeflow_tpu/.

The gate is the point of the exercise: the contract rules only protect the
webhook<->runtime env contract (and the metric/annotation vocabularies) if
re-introducing a drifted literal turns the suite red.
"""

import json
import subprocess
from pathlib import Path

import pytest

from kubeflow_tpu.analysis import rule_ids, run_analysis
from kubeflow_tpu.analysis import config as lint_config
from kubeflow_tpu.analysis.__main__ import main as lint_main
from kubeflow_tpu.analysis.baseline import (
    apply_diff_filter,
    changed_lines,
)
from kubeflow_tpu.analysis.core import load_module
from kubeflow_tpu.analysis.engine import REPO_ROOT
from kubeflow_tpu.analysis.index import RepoIndex
from kubeflow_tpu.analysis.rules import ALL_RULES, ChaosParity
from kubeflow_tpu.analysis.sarif import report_to_sarif

FIXTURES = Path(__file__).resolve().parent / "analysis_fixtures"

# fixture stem -> the rule its bad/ variant must trigger
RULE_FOR_FIXTURE = {
    "blocking_in_signal_handler": "blocking-in-signal-handler",
    "lock_held_blocking_call": "lock-held-blocking-call",
    "sleep_in_reconcile": "sleep-in-reconcile",
    "thread_no_daemon": "thread-no-daemon",
    "env_read_unknown": "env-read-unknown",
    "env_literal": "env-literal",
    "metric_unregistered": "metric-unregistered",
    "metric_attr_unregistered": "metric-attr-unregistered",
    "metric_name_scheme": "metric-name-scheme",
    "metric_stats_parity": "metric-stats-parity",
    "span_unended": "span-unended",
    "annotation_literal": "annotation-literal",
    "suppression_hygiene": "suppression-hygiene",
    "undeadlined_claim": "undeadlined-claim",
    "unbounded_fanout": "kftpu-unbounded-fanout",
    "parse_error": "parse-error",
    "lock_order_cycle": "kftpu-lock-order-cycle",
    "lock_held_await": "kftpu-lock-held-await",
    "unguarded_shared_write": "kftpu-unguarded-shared-write",
    "host_sync_hot_path": "kftpu-host-sync-in-hot-path",
    "collective_outside_jit": "kftpu-collective-outside-jit",
}

# Multi-file fixtures: peer modules that exist to complete a cross-file
# scenario (the second half of a lock-order cycle, the thread spawn that
# makes a method an entry). Good-corpus peers must lint clean too.
PEER_FIXTURES = ("lock_order_cycle_peer", "unguarded_shared_write_peer")


@pytest.fixture(scope="module")
def bad_report():
    return run_analysis([FIXTURES / "bad"])


@pytest.fixture(scope="module")
def good_report():
    return run_analysis([FIXTURES / "good"])


def _rules_for(report, stem):
    return {
        f.rule
        for f in report.unsuppressed
        if f.path.endswith(f"/{stem}.py")
    }


class TestFixtureCorpus:
    @pytest.mark.parametrize("stem,rule", sorted(RULE_FOR_FIXTURE.items()))
    def test_bad_fixture_triggers_rule(self, bad_report, stem, rule):
        assert rule in _rules_for(bad_report, stem), (
            f"bad/{stem}.py should trigger {rule}; got "
            f"{sorted(_rules_for(bad_report, stem))}"
        )

    @pytest.mark.parametrize("stem,rule", sorted(RULE_FOR_FIXTURE.items()))
    def test_good_fixture_is_clean(self, good_report, stem, rule):
        assert not _rules_for(good_report, stem), (
            f"good/{stem}.py should be clean; got "
            + "\n".join(
                f.render()
                for f in good_report.unsuppressed
                if f.path.endswith(f"/{stem}.py")
            )
        )

    @pytest.mark.parametrize("stem", PEER_FIXTURES)
    def test_good_peer_fixture_is_clean(self, good_report, stem):
        assert not _rules_for(good_report, stem), (
            f"good/{stem}.py should be clean; got "
            + "\n".join(
                f.render()
                for f in good_report.unsuppressed
                if f.path.endswith(f"/{stem}.py")
            )
        )

    def test_lock_order_cycle_reports_both_witness_paths(self, bad_report):
        findings = [
            f for f in bad_report.unsuppressed
            if f.rule == "kftpu-lock-order-cycle"
        ]
        assert findings, "two-module cycle fixture should fire"
        msg = findings[0].message
        # Both legs of the cycle, each with its own witness acquisition.
        assert "SliceLedgerA._alock" in msg and "TierLedgerB._block" in msg
        assert "lock_order_cycle.py" in msg
        assert "lock_order_cycle_peer.py" in msg

    def test_bad_corpus_covers_at_least_eight_distinct_rules(self, bad_report):
        distinct = {f.rule for f in bad_report.unsuppressed}
        assert len(distinct) >= 8, sorted(distinct)

    def test_every_fixture_rule_is_a_known_rule(self):
        assert set(RULE_FOR_FIXTURE.values()) <= rule_ids()


class TestSuppressions:
    def test_good_suppression_is_recorded_with_justification(self, good_report):
        sups = [
            f for f in good_report.suppressed
            if f.path.endswith("/suppression_hygiene.py")
        ]
        assert sups and sups[0].rule == "sleep-in-reconcile"
        assert "fixture" in sups[0].justification

    def test_unjustified_suppression_does_not_suppress(self, bad_report):
        rules = _rules_for(bad_report, "suppression_hygiene")
        # hygiene fires AND the target finding stays unsuppressed
        assert {"suppression-hygiene", "sleep-in-reconcile"} <= rules

    @pytest.mark.parametrize(
        "comment",
        [
            "# kftpu-lint: disable=sleep-in-reconcile — harness wants wall-time",
            "# kftpu-lint: disable=sleep-in-reconcile -- harness wants wall-time",
            "# kftpu-lint: disable=sleep-in-reconcile: harness wants wall-time",
        ],
    )
    def test_separator_variants_all_parse(self, tmp_path, comment):
        src = f"import time\n\n\ndef reconcile(obj):\n    time.sleep(1)  {comment}\n"
        path = tmp_path / "mod.py"
        path.write_text(src)
        mod = load_module(path, "mod.py", "mod")
        sup = mod.suppression_for("sleep-in-reconcile", 5)
        assert sup is not None and sup.justification == "harness wants wall-time"

    def test_standalone_comment_covers_next_line(self, tmp_path):
        src = (
            "import time\n\n\ndef reconcile(obj):\n"
            "    # kftpu-lint: disable=sleep-in-reconcile — next-line form\n"
            "    time.sleep(1)\n"
        )
        path = tmp_path / "mod.py"
        path.write_text(src)
        mod = load_module(path, "mod.py", "mod")
        assert mod.suppression_for("sleep-in-reconcile", 6) is not None

    def test_malformed_marker_is_flagged(self, tmp_path):
        path = tmp_path / "mod.py"
        path.write_text("X = 1  # kftpu-lint: disable sleep-in-reconcile\n")
        mod = load_module(path, "mod.py", "mod")
        assert getattr(mod, "malformed_suppression_lines", []) == [1]

    def test_unknown_rule_in_suppression_is_flagged(self, tmp_path):
        path = tmp_path / "mod.py"
        path.write_text(
            "X = 1  # kftpu-lint: disable=no-such-rule — reason\n"
        )
        report = run_analysis([path])
        assert any(
            f.rule == "suppression-hygiene" and "no-such-rule" in f.message
            for f in report.unsuppressed
        )


class TestRepoGate:
    def test_repo_has_zero_unsuppressed_findings(self):
        """Tier-1 gate: the whole package must lint clean."""
        report = run_analysis()
        assert not report.unsuppressed, "\n" + "\n".join(
            f.render() for f in report.unsuppressed
        )

    def test_reverting_a_contract_fix_fails_the_gate(self, tmp_path):
        """Re-hardcoding TPU_WORKER_ID in runtime/bootstrap.py (the drift
        this PR fixed) must produce a finding again."""
        src = (REPO_ROOT / "kubeflow_tpu/runtime/bootstrap.py").read_text()
        assert "contract.TPU_WORKER_ID" in src  # the fix this test guards
        reverted = src.replace("contract.TPU_WORKER_ID", '"TPU_WORKER_ID"')
        path = tmp_path / "bootstrap_reverted.py"
        path.write_text(reverted)
        report = run_analysis([path])
        assert any(
            f.rule == "env-literal" and "TPU_WORKER_ID" in f.message
            for f in report.unsuppressed
        )

    def test_reverting_autoscaler_lock_split_fails_the_gate(self, tmp_path):
        """Re-holding the autoscaler state lock across the provisioner's
        drained() HTTP probe (the kftpu-lock-held-await finding this PR
        fixed by splitting _tick_lock from _lock) must fire again."""
        src = (REPO_ROOT / "kubeflow_tpu/models/autoscaler.py").read_text()
        anchor = "                idle = self.provisioner.drained(ep)"
        assert anchor in src  # the fix this test guards
        reverted = src.replace(
            anchor,
            "                with self._lock:\n"
            "                    idle = self.provisioner.drained(ep)",
        )
        path = tmp_path / "autoscaler_reverted.py"
        path.write_text(reverted)
        report = run_analysis([path])
        assert any(
            f.rule == "kftpu-lock-held-await"
            and "FleetAutoscaler._lock" in f.message
            for f in report.unsuppressed
        ), "\n".join(f.render() for f in report.unsuppressed)

    def test_reverting_checkpoint_outcome_guard_fails_the_gate(self, tmp_path):
        """Dropping the _seq_lock guard on the async worker's
        save-outcome writes (the kftpu-unguarded-shared-write finding
        this PR fixed) must fire again."""
        src = (REPO_ROOT / "kubeflow_tpu/runtime/checkpoint.py").read_text()
        anchor = (
            "                    with self._seq_lock:\n"
            "                        self.last_save_error = err\n"
            "                        self.save_failures += 1"
        )
        assert anchor in src  # the fix this test guards
        reverted = src.replace(
            anchor,
            "                    self.last_save_error = err\n"
            "                    self.save_failures += 1",
        )
        path = tmp_path / "checkpoint_reverted.py"
        path.write_text(reverted)
        report = run_analysis([path])
        assert any(
            f.rule == "kftpu-unguarded-shared-write"
            and ("save_failures" in f.message or "last_save_error" in f.message)
            for f in report.unsuppressed
        ), "\n".join(f.render() for f in report.unsuppressed)


class TestChaosParity:
    def _index(self):
        idx = RepoIndex(REPO_ROOT)
        idx.chaos_injection_types = {"pod-kill", "declared-only"}
        idx.chaos_injection_line = 10
        idx.chaos_handler_types = {"pod-kill", "handler-only"}
        idx.chaos_handler_line = 20
        idx.chaos_target_kinds = {"pod-kill", "declared-only", "handler-only"}
        idx.chaos_target_line = 30
        idx.chaos_yaml_types = {"pod-kill": "chaos/experiments/pod-kill.yaml"}
        return idx

    def test_mismatches_in_every_direction(self):
        findings = ChaosParity().check_repo(
            self._index(), {lint_config.CHAOS_CATALOG_MODULE: None}
        )
        messages = "\n".join(f.message for f in findings)
        assert "'handler-only' has no declarative experiment" in messages
        assert "'declared-only' with no registered handler" in messages
        assert "'handler-only' missing from INJECTION_TYPES" in messages
        assert "unknown injection 'handler-only'" in messages

    def test_skipped_when_catalog_not_in_scope(self):
        assert ChaosParity().check_repo(self._index(), {}) == []


class TestCli:
    def test_list_rules(self, capsys):
        assert lint_main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rule in sorted(rule_ids()):
            assert rule in out

    def test_list_rules_cites_incidents_and_docs(self, capsys):
        """Each interprocedural rule carries the PR incident(s) it was
        distilled from and a docs anchor, and --list-rules prints both."""
        assert lint_main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        assert "incident:" in out
        assert "ARCHITECTURE.md#static-analysis" in out
        assert "CONTRIBUTING.md#modeling-locks-and-thread-entry-points" in out
        for rule in ALL_RULES:
            if rule.id in (
                "kftpu-lock-order-cycle",
                "kftpu-lock-held-await",
                "kftpu-unguarded-shared-write",
                "kftpu-host-sync-in-hot-path",
            ):
                assert rule.incidents, f"{rule.id} cites no incident"
                assert rule.docs, f"{rule.id} has no docs link"

    def test_json_output_clean_corpus(self, capsys):
        assert lint_main([str(FIXTURES / "good"), "--format", "json"]) == 0
        data = json.loads(capsys.readouterr().out)
        assert data["unsuppressed"] == 0
        assert data["suppressed"] == 1
        assert data["checked_files"] == len(list((FIXTURES / "good").glob("*.py")))

    def test_nonzero_exit_on_findings(self, capsys):
        assert lint_main([str(FIXTURES / "bad")]) == 1


# The subset of the SARIF 2.1.0 schema that kftpu-lint emits: log-level
# required fields, driver identity, and the result/location/suppression
# shapes viewers depend on. Kept inline so the test has no network or
# vendored-schema dependency.
SARIF_SCHEMA_SUBSET = {
    "type": "object",
    "required": ["version", "runs"],
    "properties": {
        "version": {"const": "2.1.0"},
        "runs": {
            "type": "array",
            "minItems": 1,
            "items": {
                "type": "object",
                "required": ["tool", "results"],
                "properties": {
                    "tool": {
                        "type": "object",
                        "required": ["driver"],
                        "properties": {
                            "driver": {
                                "type": "object",
                                "required": ["name"],
                                "properties": {
                                    "name": {"type": "string"},
                                    "rules": {
                                        "type": "array",
                                        "items": {
                                            "type": "object",
                                            "required": [
                                                "id",
                                                "shortDescription",
                                            ],
                                        },
                                    },
                                },
                            }
                        },
                    },
                    "results": {
                        "type": "array",
                        "items": {
                            "type": "object",
                            "required": ["ruleId", "message", "locations"],
                            "properties": {
                                "ruleId": {"type": "string"},
                                "level": {
                                    "enum": [
                                        "none",
                                        "note",
                                        "warning",
                                        "error",
                                    ]
                                },
                                "message": {
                                    "type": "object",
                                    "required": ["text"],
                                },
                                "baselineState": {
                                    "enum": [
                                        "new",
                                        "unchanged",
                                        "updated",
                                        "absent",
                                    ]
                                },
                                "locations": {
                                    "type": "array",
                                    "minItems": 1,
                                    "items": {
                                        "type": "object",
                                        "required": ["physicalLocation"],
                                        "properties": {
                                            "physicalLocation": {
                                                "type": "object",
                                                "required": [
                                                    "artifactLocation"
                                                ],
                                                "properties": {
                                                    "artifactLocation": {
                                                        "type": "object",
                                                        "required": ["uri"],
                                                    },
                                                    "region": {
                                                        "type": "object",
                                                        "properties": {
                                                            "startLine": {
                                                                "type": "integer",
                                                                "minimum": 1,
                                                            },
                                                            "startColumn": {
                                                                "type": "integer",
                                                                "minimum": 1,
                                                            },
                                                        },
                                                    },
                                                },
                                            }
                                        },
                                    },
                                },
                                "suppressions": {
                                    "type": "array",
                                    "items": {
                                        "type": "object",
                                        "required": ["kind"],
                                        "properties": {
                                            "kind": {
                                                "enum": [
                                                    "inSource",
                                                    "external",
                                                ]
                                            }
                                        },
                                    },
                                },
                            },
                        },
                    },
                },
            },
        },
    },
}


class TestSarif:
    def test_log_validates_against_schema_subset(self):
        jsonschema = pytest.importorskip("jsonschema")
        report = run_analysis([FIXTURES / "bad", FIXTURES / "good"])
        log = report_to_sarif(report, ALL_RULES)
        jsonschema.validate(log, SARIF_SCHEMA_SUBSET)

    def test_suppressions_and_baseline_state(self):
        report = run_analysis([FIXTURES / "bad", FIXTURES / "good"])
        log = report_to_sarif(report, ALL_RULES)
        results = log["runs"][0]["results"]
        suppressed = [r for r in results if "suppressions" in r]
        assert suppressed, "good corpus suppression should appear"
        assert all(
            r["suppressions"][0]["kind"] == "inSource"
            and r["suppressions"][0]["justification"]
            for r in suppressed
        )
        gating = [r for r in results if r.get("baselineState") == "new"]
        assert gating, "bad corpus findings should be baselineState=new"

    def test_cli_sarif_flag_emits_parseable_log(self, capsys):
        assert lint_main([str(FIXTURES / "bad"), "--sarif"]) == 1
        log = json.loads(capsys.readouterr().out)
        assert log["version"] == "2.1.0"
        assert log["runs"][0]["tool"]["driver"]["name"] == "kftpu-lint"


class TestBaselineAndDiff:
    def test_checked_in_baseline_is_empty(self):
        """The repo's standing bar: baseline.json exists for rule rollout
        but must stay empty — findings get fixed or suppressed inline."""
        data = json.loads(
            (REPO_ROOT / "kubeflow_tpu/analysis/baseline.json").read_text()
        )
        assert data["findings"] == []

    def test_update_baseline_then_gate_passes(self, tmp_path, capsys):
        bad = str(FIXTURES / "bad")
        bl = tmp_path / "baseline.json"
        assert lint_main([bad, "--baseline", str(bl), "--update-baseline"]) == 0
        capsys.readouterr()
        data = json.loads(bl.read_text())
        assert data["version"] == 1 and data["findings"]
        assert all(
            e["rule"] and e["path"] and len(e["fingerprint"]) == 16
            for e in data["findings"]
        )
        # Baselined findings no longer gate...
        assert lint_main([bad, "--baseline", str(bl)]) == 0
        capsys.readouterr()
        # ...but --no-baseline restores the hard gate.
        assert lint_main([bad, "--no-baseline"]) == 1
        capsys.readouterr()

    def test_baselined_findings_are_reported_not_hidden(self, tmp_path, capsys):
        bad = str(FIXTURES / "bad")
        bl = tmp_path / "baseline.json"
        lint_main([bad, "--baseline", str(bl), "--update-baseline"])
        capsys.readouterr()
        assert lint_main([bad, "--baseline", str(bl), "--format", "json"]) == 0
        data = json.loads(capsys.readouterr().out)
        assert data["gating"] == 0
        assert data["baselined"] == data["unsuppressed"] > 0

    def test_diff_filter_gates_only_changed_lines(self):
        report = run_analysis([FIXTURES / "bad" / "sleep_in_reconcile.py"])
        finding = next(
            f for f in report.unsuppressed if f.rule == "sleep-in-reconcile"
        )
        apply_diff_filter(report, {finding.path: {finding.line}})
        assert finding in report.gating and report.exit_code == 1

        report2 = run_analysis([FIXTURES / "bad" / "sleep_in_reconcile.py"])
        f2 = next(
            f for f in report2.unsuppressed if f.rule == "sleep-in-reconcile"
        )
        # The PR touched the file, but not the offending line.
        apply_diff_filter(report2, {f2.path: {f2.line + 100}})
        assert f2 in report2.out_of_diff and f2 not in report2.gating

    def test_diff_filter_untouched_file_never_gates(self):
        report = run_analysis([FIXTURES / "bad" / "sleep_in_reconcile.py"])
        apply_diff_filter(report, {})
        assert report.gating == [] and report.exit_code == 0

    def test_changed_lines_parses_a_real_git_range(self, tmp_path):
        def git(*argv):
            subprocess.run(
                ["git", *argv], cwd=tmp_path, check=True, capture_output=True
            )

        git("init", "-q")
        git("config", "user.email", "t@example.com")
        git("config", "user.name", "t")
        mod = tmp_path / "mod.py"
        mod.write_text("a = 1\nb = 2\nc = 3\n")
        git("add", "mod.py")
        git("commit", "-qm", "seed")
        mod.write_text("a = 1\nb = 20\nc = 3\nd = 4\n")
        git("add", "mod.py")
        git("commit", "-qm", "edit")
        changed = changed_lines("HEAD~1..HEAD", tmp_path)
        assert changed == {"mod.py": {2, 4}}

    def test_changed_lines_bad_range_returns_none(self, tmp_path):
        def git(*argv):
            subprocess.run(
                ["git", *argv], cwd=tmp_path, check=True, capture_output=True
            )

        git("init", "-q")
        assert changed_lines("no-such-ref..HEAD", tmp_path) is None
