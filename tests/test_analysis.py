"""kftpu-lint tests: per-rule fixture corpus, suppression syntax, and the
tier-1 zero-unsuppressed-findings gate over kubeflow_tpu/.

The gate is the point of the exercise: the contract rules only protect the
webhook<->runtime env contract (and the metric/annotation vocabularies) if
re-introducing a drifted literal turns the suite red.
"""

from pathlib import Path

import pytest

from kubeflow_tpu.analysis import rule_ids, run_analysis
from kubeflow_tpu.analysis import config as lint_config
from kubeflow_tpu.analysis.__main__ import main as lint_main
from kubeflow_tpu.analysis.core import load_module
from kubeflow_tpu.analysis.engine import REPO_ROOT
from kubeflow_tpu.analysis.index import RepoIndex
from kubeflow_tpu.analysis.rules import ChaosParity

FIXTURES = Path(__file__).resolve().parent / "analysis_fixtures"

# fixture stem -> the rule its bad/ variant must trigger
RULE_FOR_FIXTURE = {
    "blocking_in_signal_handler": "blocking-in-signal-handler",
    "lock_held_blocking_call": "lock-held-blocking-call",
    "sleep_in_reconcile": "sleep-in-reconcile",
    "thread_no_daemon": "thread-no-daemon",
    "env_read_unknown": "env-read-unknown",
    "env_literal": "env-literal",
    "metric_unregistered": "metric-unregistered",
    "metric_attr_unregistered": "metric-attr-unregistered",
    "metric_name_scheme": "metric-name-scheme",
    "metric_stats_parity": "metric-stats-parity",
    "span_unended": "span-unended",
    "annotation_literal": "annotation-literal",
    "suppression_hygiene": "suppression-hygiene",
    "undeadlined_claim": "undeadlined-claim",
    "unbounded_fanout": "kftpu-unbounded-fanout",
    "parse_error": "parse-error",
}


@pytest.fixture(scope="module")
def bad_report():
    return run_analysis([FIXTURES / "bad"])


@pytest.fixture(scope="module")
def good_report():
    return run_analysis([FIXTURES / "good"])


def _rules_for(report, stem):
    return {
        f.rule
        for f in report.unsuppressed
        if f.path.endswith(f"/{stem}.py")
    }


class TestFixtureCorpus:
    @pytest.mark.parametrize("stem,rule", sorted(RULE_FOR_FIXTURE.items()))
    def test_bad_fixture_triggers_rule(self, bad_report, stem, rule):
        assert rule in _rules_for(bad_report, stem), (
            f"bad/{stem}.py should trigger {rule}; got "
            f"{sorted(_rules_for(bad_report, stem))}"
        )

    @pytest.mark.parametrize("stem,rule", sorted(RULE_FOR_FIXTURE.items()))
    def test_good_fixture_is_clean(self, good_report, stem, rule):
        assert not _rules_for(good_report, stem), (
            f"good/{stem}.py should be clean; got "
            + "\n".join(
                f.render()
                for f in good_report.unsuppressed
                if f.path.endswith(f"/{stem}.py")
            )
        )

    def test_bad_corpus_covers_at_least_eight_distinct_rules(self, bad_report):
        distinct = {f.rule for f in bad_report.unsuppressed}
        assert len(distinct) >= 8, sorted(distinct)

    def test_every_fixture_rule_is_a_known_rule(self):
        assert set(RULE_FOR_FIXTURE.values()) <= rule_ids()


class TestSuppressions:
    def test_good_suppression_is_recorded_with_justification(self, good_report):
        sups = [
            f for f in good_report.suppressed
            if f.path.endswith("/suppression_hygiene.py")
        ]
        assert sups and sups[0].rule == "sleep-in-reconcile"
        assert "fixture" in sups[0].justification

    def test_unjustified_suppression_does_not_suppress(self, bad_report):
        rules = _rules_for(bad_report, "suppression_hygiene")
        # hygiene fires AND the target finding stays unsuppressed
        assert {"suppression-hygiene", "sleep-in-reconcile"} <= rules

    @pytest.mark.parametrize(
        "comment",
        [
            "# kftpu-lint: disable=sleep-in-reconcile — harness wants wall-time",
            "# kftpu-lint: disable=sleep-in-reconcile -- harness wants wall-time",
            "# kftpu-lint: disable=sleep-in-reconcile: harness wants wall-time",
        ],
    )
    def test_separator_variants_all_parse(self, tmp_path, comment):
        src = f"import time\n\n\ndef reconcile(obj):\n    time.sleep(1)  {comment}\n"
        path = tmp_path / "mod.py"
        path.write_text(src)
        mod = load_module(path, "mod.py", "mod")
        sup = mod.suppression_for("sleep-in-reconcile", 5)
        assert sup is not None and sup.justification == "harness wants wall-time"

    def test_standalone_comment_covers_next_line(self, tmp_path):
        src = (
            "import time\n\n\ndef reconcile(obj):\n"
            "    # kftpu-lint: disable=sleep-in-reconcile — next-line form\n"
            "    time.sleep(1)\n"
        )
        path = tmp_path / "mod.py"
        path.write_text(src)
        mod = load_module(path, "mod.py", "mod")
        assert mod.suppression_for("sleep-in-reconcile", 6) is not None

    def test_malformed_marker_is_flagged(self, tmp_path):
        path = tmp_path / "mod.py"
        path.write_text("X = 1  # kftpu-lint: disable sleep-in-reconcile\n")
        mod = load_module(path, "mod.py", "mod")
        assert getattr(mod, "malformed_suppression_lines", []) == [1]

    def test_unknown_rule_in_suppression_is_flagged(self, tmp_path):
        path = tmp_path / "mod.py"
        path.write_text(
            "X = 1  # kftpu-lint: disable=no-such-rule — reason\n"
        )
        report = run_analysis([path])
        assert any(
            f.rule == "suppression-hygiene" and "no-such-rule" in f.message
            for f in report.unsuppressed
        )


class TestRepoGate:
    def test_repo_has_zero_unsuppressed_findings(self):
        """Tier-1 gate: the whole package must lint clean."""
        report = run_analysis()
        assert not report.unsuppressed, "\n" + "\n".join(
            f.render() for f in report.unsuppressed
        )

    def test_reverting_a_contract_fix_fails_the_gate(self, tmp_path):
        """Re-hardcoding TPU_WORKER_ID in runtime/bootstrap.py (the drift
        this PR fixed) must produce a finding again."""
        src = (REPO_ROOT / "kubeflow_tpu/runtime/bootstrap.py").read_text()
        assert "contract.TPU_WORKER_ID" in src  # the fix this test guards
        reverted = src.replace("contract.TPU_WORKER_ID", '"TPU_WORKER_ID"')
        path = tmp_path / "bootstrap_reverted.py"
        path.write_text(reverted)
        report = run_analysis([path])
        assert any(
            f.rule == "env-literal" and "TPU_WORKER_ID" in f.message
            for f in report.unsuppressed
        )


class TestChaosParity:
    def _index(self):
        idx = RepoIndex(REPO_ROOT)
        idx.chaos_injection_types = {"pod-kill", "declared-only"}
        idx.chaos_injection_line = 10
        idx.chaos_handler_types = {"pod-kill", "handler-only"}
        idx.chaos_handler_line = 20
        idx.chaos_target_kinds = {"pod-kill", "declared-only", "handler-only"}
        idx.chaos_target_line = 30
        idx.chaos_yaml_types = {"pod-kill": "chaos/experiments/pod-kill.yaml"}
        return idx

    def test_mismatches_in_every_direction(self):
        findings = ChaosParity().check_repo(
            self._index(), {lint_config.CHAOS_CATALOG_MODULE: None}
        )
        messages = "\n".join(f.message for f in findings)
        assert "'handler-only' has no declarative experiment" in messages
        assert "'declared-only' with no registered handler" in messages
        assert "'handler-only' missing from INJECTION_TYPES" in messages
        assert "unknown injection 'handler-only'" in messages

    def test_skipped_when_catalog_not_in_scope(self):
        assert ChaosParity().check_repo(self._index(), {}) == []


class TestCli:
    def test_list_rules(self, capsys):
        assert lint_main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rule in sorted(rule_ids()):
            assert rule in out

    def test_json_output_clean_corpus(self, capsys):
        import json

        assert lint_main([str(FIXTURES / "good"), "--format", "json"]) == 0
        data = json.loads(capsys.readouterr().out)
        assert data["unsuppressed"] == 0
        assert data["suppressed"] == 1
        assert data["checked_files"] == len(list((FIXTURES / "good").glob("*.py")))

    def test_nonzero_exit_on_findings(self, capsys):
        assert lint_main([str(FIXTURES / "bad")]) == 1
