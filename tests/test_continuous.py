"""Continuous batching: slot reuse, admission mid-flight, and per-request
token parity with the fused batch path."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import pytest

from kubeflow_tpu.models import llama as L
from kubeflow_tpu.models.continuous import ContinuousBatcher
from kubeflow_tpu.models.serving import GenerationConfig, batch_generate


@pytest.fixture(scope="module")
def tiny():
    cfg = L.LLAMA_CONFIGS["tiny"]
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _prompts(cfg, n, key=7):
    ks = jax.random.split(jax.random.PRNGKey(key), n)
    out = []
    for i, k in enumerate(ks):
        length = 4 + int(jax.random.randint(k, (), 0, 12))
        out.append([int(t) for t in
                    jax.random.randint(k, (length,), 3, cfg.vocab_size)])
    return out


def _assert_greedy_consistent(params, cfg, prompt, tokens):
    """Each emitted token must be a greedy argmax of the reference forward
    (within bf16 tie tolerance — ties legitimately break differently
    across batch shapes; an off-path token is a REAL cache bug and sits
    far below the max)."""
    full = jnp.asarray([list(prompt) + list(tokens)])
    logits = L.forward(params, cfg, full)[0]
    start = len(prompt) - 1
    for i, tok in enumerate(tokens):
        row = logits[start + i]
        gap = float(row.max() - row[tok])
        assert gap < 0.02, f"token {i} ({tok}) off the greedy path by {gap}"


class TestContinuousBatcher:
    def test_single_request_matches_fused_batch_path(self, tiny):
        """slots=1 reproduces batch_generate token-for-token (identical
        shapes → no bf16 tie ambiguity)."""
        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=8, eos_id=-1)
        prompt = [5, 9, 17, 33]
        ref = batch_generate(params, cfg, [prompt], gen=gen, pad_to=16)[0]
        cb = ContinuousBatcher(params, cfg, gen=gen, slots=1,
                               cache_len=24, prompt_bucket=16)
        rid = cb.submit(prompt)
        assert cb.run()[rid] == [int(t) for t in ref]

    def test_slot_reuse_stays_on_greedy_path(self, tiny):
        """More requests than slots: every request's tokens must follow
        the greedy path of ITS OWN prompt — admission into a recycled
        slot must not contaminate neighbors."""
        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=10, eos_id=-1)  # no early EOS
        prompts = _prompts(cfg, 7)
        cb = ContinuousBatcher(
            params, cfg, gen=gen, slots=3, cache_len=16 + gen.max_new_tokens,
            prompt_bucket=16,
        )
        rids = [cb.submit(p) for p in prompts]
        results = cb.run()
        assert sorted(results) == sorted(rids)
        for rid, prompt in zip(rids, prompts):
            assert len(results[rid]) == gen.max_new_tokens
            _assert_greedy_consistent(params, cfg, prompt, results[rid])

    def test_eos_frees_slot_early(self, tiny):
        """A request hitting EOS retires early and its slot is reused;
        everyone stays on their own greedy path."""
        cfg, params = tiny
        probe = GenerationConfig(max_new_tokens=6, eos_id=-1)
        prompts = _prompts(cfg, 4, key=11)
        # Probe with the SAME slot/batch shapes (bf16 ties break by
        # computation shape, so the probe must mirror the real run), then
        # make prompt 0's step-2 token the eos: request 0 stops after 2.
        probe_cb = ContinuousBatcher(
            params, cfg, gen=probe, slots=2, cache_len=16 + 6,
            prompt_bucket=16,
        )
        probe_rids = [probe_cb.submit(p) for p in prompts]
        probe_out = probe_cb.run()[probe_rids[0]]
        eos = int(probe_out[2])
        gen = GenerationConfig(max_new_tokens=6, eos_id=eos)
        cb = ContinuousBatcher(
            params, cfg, gen=gen, slots=2, cache_len=16 + 6, prompt_bucket=16
        )
        rids = [cb.submit(p) for p in prompts]
        results = cb.run()
        for rid, prompt in zip(rids, prompts):
            out = results[rid]
            assert eos not in out
            assert len(out) <= gen.max_new_tokens
            _assert_greedy_consistent(params, cfg, prompt, out)
            if len(out) < gen.max_new_tokens:
                # Early stop must be warranted: eos is (near-)argmax right
                # after the emitted prefix.
                full = jnp.asarray([list(prompt) + out])
                row = L.forward(params, cfg, full)[0, -1]
                assert float(row.max() - row[eos]) < 0.02
        assert len(results[rids[0]]) < gen.max_new_tokens, "no early retire"

    def test_submit_validation(self, tiny):
        cfg, params = tiny
        cb = ContinuousBatcher(params, cfg, slots=2, cache_len=64,
                               prompt_bucket=16,
                               gen=GenerationConfig(max_new_tokens=8))
        with pytest.raises(ValueError, match="empty"):
            cb.submit([])
        with pytest.raises(ValueError, match="exceeds bucket"):
            cb.submit(list(range(20)))

    def test_run_with_empty_queue_returns_empty(self, tiny):
        cfg, params = tiny
        cb = ContinuousBatcher(params, cfg, slots=2, cache_len=64,
                               prompt_bucket=16,
                               gen=GenerationConfig(max_new_tokens=8))
        assert cb.run() == {}

    def test_constructor_rejects_overflowing_cache(self, tiny):
        cfg, params = tiny
        with pytest.raises(ValueError, match="cache_len"):
            ContinuousBatcher(params, cfg, slots=2, cache_len=64,
                              prompt_bucket=16)  # default max_new=128


class TestShardedServing:
    """tp/sp-sharded continuous batching must stay token-exact with the
    single-device batcher: the plan changes WHERE tensors live (params
    over tp, cache sequence over sp, GSPMD/psum collectives), never what
    the server emits."""

    def _run(self, params, cfg, prompts, plan=None):
        gen = GenerationConfig(max_new_tokens=6, eos_id=-1)
        cb = ContinuousBatcher(
            params, cfg, gen=gen, slots=2, cache_len=128,
            prompt_bucket=16, plan=plan,
        )
        rids = [cb.submit(p) for p in prompts]
        out = cb.run()
        return [out[r] for r in rids]

    def test_tp_sp_sharded_matches_single_device(self, tiny):
        from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh

        cfg, params = tiny
        prompts = _prompts(cfg, 4, key=31)
        want = self._run(params, cfg, prompts)
        plan = MeshPlan(make_mesh(dp=1, fsdp=1, tp=2, sp=2,
                                  devices=jax.devices()[:4]))
        got = self._run(params, cfg, prompts, plan=plan)
        assert want == got

    def test_tp_only_sharded_matches_single_device(self, tiny):
        from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh

        cfg, params = tiny
        prompts = _prompts(cfg, 3, key=37)
        want = self._run(params, cfg, prompts)
        plan = MeshPlan(make_mesh(tp=2, devices=jax.devices()[:2]))
        got = self._run(params, cfg, prompts, plan=plan)
        assert want == got

    def test_sp_indivisible_cache_rejected(self, tiny):
        from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh

        cfg, params = tiny
        plan = MeshPlan(make_mesh(tp=1, sp=3, devices=jax.devices()[:3]))
        gen = GenerationConfig(max_new_tokens=8, eos_id=-1)
        with pytest.raises(ValueError, match="divisible by"):
            ContinuousBatcher(params, cfg, gen=gen, cache_len=128,
                              prompt_bucket=16, plan=plan)

    def test_gqa_sharded_matches_single_device(self, tiny):
        """GQA config through the sp split-KV decode: the UNREPEATED
        cache shard goes straight into sp_decode_attention (group fold
        inside), so rep>1 must stay token-exact too."""
        from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh

        cfg = L.LlamaConfig(vocab_size=256, dim=64, n_layers=2, n_heads=4,
                            n_kv_heads=2, ffn_hidden=128, max_seq_len=256)
        params = L.init_params(cfg, jax.random.PRNGKey(3))
        prompts = _prompts(cfg, 3, key=43)
        want = self._run(params, cfg, prompts)
        plan = MeshPlan(make_mesh(tp=2, sp=2, devices=jax.devices()[:4]))
        got = self._run(params, cfg, prompts, plan=plan)
        assert want == got
