"""AdmissionReview HTTP server: protocol round-trips over real HTTP."""

from __future__ import annotations

import base64
import json
import urllib.request

import pytest

from kubeflow_tpu import k8s
from kubeflow_tpu.api import annotations as ann
from kubeflow_tpu.webhook.mutating import NotebookMutatingWebhook, WebhookConfig
from kubeflow_tpu.webhook.server import (
    MUTATE_PATH,
    VALIDATE_PATH,
    WebhookServer,
    apply_json_patch,
    handle_admission_review,
)
from kubeflow_tpu.webhook.validating import NotebookValidatingWebhook

from tests.harness import tpu_notebook


def _review(obj, operation="CREATE", old=None, uid="uid-1"):
    return {
        "apiVersion": "admission.k8s.io/v1",
        "kind": "AdmissionReview",
        "request": {
            "uid": uid,
            "operation": operation,
            "object": obj,
            "oldObject": old,
        },
    }


@pytest.fixture
def cluster():
    c = k8s.FakeCluster()
    k8s.add_tpu_node_pool(c, "tpu-v5-lite-podslice", "4x4", hosts=4, chips_per_host=4)
    return c


def test_mutate_review_returns_patch(cluster):
    webhook = NotebookMutatingWebhook(cluster, WebhookConfig())
    original = tpu_notebook(name="nb1")
    review = handle_admission_review(
        _review(original), webhook.handle, None
    )
    resp = review["response"]
    assert resp["allowed"] and resp["uid"] == "uid-1"
    patch = json.loads(base64.b64decode(resp["patch"]))
    # Granular RFC 6902 ops, never a whole-root replace (which would
    # clobber concurrent webhook mutations in the admission chain).
    assert all(op["path"] != "" for op in patch)
    patched = apply_json_patch(original, patch)
    assert patched["metadata"]["annotations"][ann.STOP] == ann.RECONCILIATION_LOCK_VALUE
    env_names = {
        e["name"]
        for c in patched["spec"]["template"]["spec"]["containers"]
        for e in c.get("env", [])
    }
    assert "TPU_WORKER_HOSTNAMES" in env_names


def test_validate_review_denies_topology_change(cluster):
    validating = NotebookValidatingWebhook(cluster)
    old = tpu_notebook(name="nb1")
    old["status"] = {"readyReplicas": 4}
    new = tpu_notebook(name="nb1", topology="2x4")
    new["status"] = {"readyReplicas": 4}
    review = handle_admission_review(
        _review(new, operation="UPDATE", old=old), None, validating.handle
    )
    assert not review["response"]["allowed"]
    assert review["response"]["status"]["code"] == 403


def test_handler_exception_fails_closed(cluster):
    def broken(req):
        raise RuntimeError("boom")

    review = handle_admission_review(_review(tpu_notebook()), broken, None)
    assert not review["response"]["allowed"]
    assert review["response"]["status"]["code"] == 500


def test_http_round_trip_both_paths(cluster):
    mutating = NotebookMutatingWebhook(cluster, WebhookConfig())
    validating = NotebookValidatingWebhook(cluster)
    server = WebhookServer(mutating.handle, validating.handle)
    server.start()
    try:
        base = f"http://127.0.0.1:{server.port}"

        body = json.dumps(_review(tpu_notebook(name="nb1"))).encode()
        req = urllib.request.Request(
            base + MUTATE_PATH, data=body, headers={"Content-Type": "application/json"}
        )
        with urllib.request.urlopen(req) as resp:
            out = json.loads(resp.read())
        assert out["response"]["allowed"]
        assert out["response"].get("patch")

        req = urllib.request.Request(
            base + VALIDATE_PATH, data=body, headers={"Content-Type": "application/json"}
        )
        with urllib.request.urlopen(req) as resp:
            out = json.loads(resp.read())
        assert out["response"]["allowed"]

        bad = urllib.request.Request(base + "/nope", data=body)
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(bad)
    finally:
        server.stop()


def test_noop_mutation_returns_no_patch(cluster):
    """An UPDATE that the webhook doesn't change must not emit a patch."""
    webhook = NotebookMutatingWebhook(cluster, WebhookConfig())
    obj = tpu_notebook(name="nb1")
    first = handle_admission_review(_review(obj), webhook.handle, None)
    ops = json.loads(base64.b64decode(first["response"]["patch"]))
    mutated = apply_json_patch(obj, ops)
    second = handle_admission_review(
        _review(mutated, operation="UPDATE", old=mutated), webhook.handle, None
    )
    assert "patch" not in second["response"], second["response"]