"""Speculation as a ragged scheduling mode + multi-LoRA on the paged
engine (models/speculative.py ragged path, models/multilora.py
MultiLoraPagedBatcher, gateway adapter affinity).

The contracts under test:
- a ragged speculative run emits EXACTLY the tokens the plain ragged
  scheduler emits (the spec engine is a throughput change, never a
  semantics change) — over bf16 AND int8 pools;
- a rejected suffix's KV rollback leaves every pool cell outside the
  committed prefix byte-identical to its pre-round contents;
- adapter-salted chain keys never collide across adapters and stay in
  byte parity with the gateway's ``chain_key``;
- (prefix, adapter) affinity routing keeps each replica's working set
  of hot adapters smaller than adapter-oblivious routing does.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

from kubeflow_tpu.models import llama as L
from kubeflow_tpu.models.paged import PagedBatcher
from kubeflow_tpu.models.serving import GenerationConfig
from kubeflow_tpu.models.speculative import (
    SpeculativePagedBatcher,
    truncated_draft,
)


@pytest.fixture(scope="module")
def target():
    cfg = L.LLAMA_CONFIGS["tiny"]
    return cfg, L.init_params(cfg, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def draft(target):
    # A truncated-layer draft: wrong often enough to exercise rejection
    # and rollback on every run (the smoke acceptance rate is ~5%).
    cfg, params = target
    dparams, dcfg = truncated_draft(params, cfg, 1)
    return dcfg, dparams


PROMPTS = [[5, 9, 17, 33], [7, 3, 11], [8, 44, 91, 7, 2]]


def _run(batcher, prompts):
    rids = [batcher.submit(p) for p in prompts]
    out = batcher.run()
    return [out[r] for r in rids]


def _plain(target, kv_bits=0, token_budget=16, max_new=6):
    cfg, params = target
    gen = GenerationConfig(max_new_tokens=max_new, eos_id=-1)
    return PagedBatcher(
        params, cfg, gen=gen, slots=2, num_blocks=40, block_size=8,
        prompt_bucket=16, attn_kernel=False, ragged=True,
        token_budget=token_budget, kv_bits=kv_bits,
    )


def _spec(target, draft, kv_bits=0, k_spec=3, token_budget=16,
          max_new=6, **kw):
    cfg, params = target
    dcfg, dparams = draft
    gen = GenerationConfig(max_new_tokens=max_new, eos_id=-1)
    return SpeculativePagedBatcher(
        params, cfg, dparams, dcfg, gen=gen, slots=2, num_blocks=40,
        block_size=8, prompt_bucket=16, k_spec=k_spec, kv_bits=kv_bits,
        ragged=True, token_budget=token_budget, **kw,
    )


class TestRaggedSpecExactness:
    def test_token_parity_with_plain_ragged(self, target, draft):
        """THE invariant: verify spans inside the fused dispatch must
        not move any request off the plain ragged scheduler's stream —
        with a foreign draft, so rejection + rollback fire for real."""
        want = _run(_plain(target), PROMPTS)
        sb = _spec(target, draft)
        got = _run(sb, PROMPTS)
        assert got == want
        assert 0.0 <= sb.acceptance_rate <= 1.0
        assert sb.rounds > 0
        # Every block returned to the pool after the run (block 0 null).
        assert sb.free_blocks == 39

    def test_token_parity_over_int8_pool(self, target, draft):
        import jax.numpy as jnp

        want = _run(_plain(target, kv_bits=8), PROMPTS[:2])
        sb = _spec(target, draft, kv_bits=8)
        assert sb._pb.pool["k"].dtype == jnp.int8
        got = _run(sb, PROMPTS[:2])
        assert got == want

    def test_self_draft_accepts_everything(self, target):
        want = _run(_plain(target), PROMPTS[:2])
        sb = _spec(target, (target[0], target[1]))
        got = _run(sb, PROMPTS[:2])
        assert got == want
        assert sb.acceptance_rate == 1.0

    @pytest.mark.slow
    def test_adaptive_draft_len_stays_exact(self, target, draft):
        """Acceptance-adaptive span lengths re-shape every round; the
        stream must still be the plain scheduler's, and the draft length
        must stay inside [1, k_spec]."""
        want = _run(_plain(target, token_budget=20, max_new=8), PROMPTS)
        sb = _spec(target, draft, k_spec=4, token_budget=20, max_new=8,
                   adaptive=True)
        got = _run(sb, PROMPTS)
        assert got == want
        assert 1 <= sb.k_cur <= 4
        # A mostly-wrong draft must have decayed the span length.
        assert sb.k_cur < 4

    def test_adaptive_requires_ragged(self, target, draft):
        cfg, params = target
        dcfg, dparams = draft
        with pytest.raises(ValueError, match="adaptive"):
            SpeculativePagedBatcher(
                params, cfg, dparams, dcfg, num_blocks=40,
                adaptive=True,
            )

    def test_budget_must_hold_a_full_house_round(self, target, draft):
        cfg, params = target
        dcfg, dparams = draft
        with pytest.raises(ValueError, match="token_budget"):
            SpeculativePagedBatcher(
                params, cfg, dparams, dcfg, slots=4, k_spec=3,
                num_blocks=40, ragged=True, token_budget=15,  # < 4*(3+1)
            )


class TestRollback:
    def test_rejected_suffix_restores_pool_bytes(self, target, draft):
        """One speculative round against a mostly-wrong draft: after the
        round, every pool cell OUTSIDE the slot's committed prefix must
        be byte-identical to its pre-round contents — the rejected
        suffix's writes are invisible, as if speculation never ran."""
        sb = _spec(target, draft, max_new=8)
        pb = sb._pb
        sb.submit(PROMPTS[0])
        pb._admit_free_slots()
        while all(r is None for r in pb._by_slot):
            pb._step()  # drive admission chunks to completion
        slot, req = next((i, r) for i, r in enumerate(pb._by_slot)
                         if r is not None)
        before = {k: np.asarray(v) for k, v in pb.pool.items()}
        pos0 = int(pb.positions[slot])
        pb._step()  # one speculative round (verify + rollback)
        pos1 = int(pb.positions[slot])
        assert pos1 > pos0  # at least the verify token committed
        committed = {
            (req.blocks[p // pb.block_size], p % pb.block_size)
            for p in range(pos0, pos1)
        }
        # Block 0 is the engine's null sink: padding rows of the pow-2
        # dispatch width write there and nothing ever reads it back.
        committed |= {(0, o) for o in range(pb.block_size)}
        for name, leaf in pb.pool.items():
            diff = np.asarray(leaf) != before[name]
            # (L, NB, Hkv, BS, D)-shaped values and (L, NB, Hkv, BS)
            # scales both reduce to a per-(block, offset) changed mask.
            axes = tuple(i for i in range(diff.ndim) if i not in (1, 3))
            changed = np.argwhere(diff.any(axis=axes))
            got = {(int(b), int(o)) for b, o in changed}
            assert got <= committed, (
                f"pool leaf {name!r}: rollback left bytes changed "
                f"outside the committed prefix: {got - committed}"
            )

    def test_run_with_rejections_returns_all_blocks(self, target, draft):
        sb = _spec(target, draft, max_new=10)
        _run(sb, PROMPTS)
        assert sb.acceptance_rate < 1.0  # rejections actually happened
        assert sb.free_blocks == 39


class TestGreedyGuard:
    @pytest.mark.parametrize("temperature", [0.0, None])
    def test_both_greedy_spellings_accepted(self, target, draft,
                                            temperature):
        cfg, params = target
        dcfg, dparams = draft
        gen = GenerationConfig(max_new_tokens=4, eos_id=-1,
                               temperature=temperature)
        SpeculativePagedBatcher(params, cfg, dparams, dcfg, gen=gen,
                                num_blocks=40)

    def test_sampling_still_rejected(self, target, draft):
        cfg, params = target
        dcfg, dparams = draft
        gen = GenerationConfig(max_new_tokens=4, temperature=0.8)
        with pytest.raises(ValueError, match="greedy-only"):
            SpeculativePagedBatcher(params, cfg, dparams, dcfg, gen=gen,
                                    num_blocks=40)


class TestSpecStatsSurface:
    def test_stats_block_flows_to_http(self, target, draft):
        """/stats grows a ``speculative`` block (rounds, accepted,
        acceptance_rate, draft_len) that the gateway scrape and the
        fleet telemetry counters key on."""
        import json
        import urllib.request

        from kubeflow_tpu.models.server import InferenceServer

        sb = _spec(target, draft, max_new=4)
        srv = InferenceServer(sb, port=0).start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/v1/completions",
                data=json.dumps({"prompt": PROMPTS[0]}).encode(),
            )
            with urllib.request.urlopen(req, timeout=300) as resp:
                assert json.loads(resp.read())["choices"][0]["tokens"]
            with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/stats", timeout=30
            ) as resp:
                stats = json.loads(resp.read())
        finally:
            srv.stop()
        spec = stats["speculative"]
        assert spec["rounds"] > 0
        assert spec["proposed"] > 0
        assert 0.0 <= spec["acceptance_rate"] <= 1.0
        assert spec["draft_len"] == 3


class TestEnvParsers:
    def test_spec_from_env(self, monkeypatch):
        from kubeflow_tpu.models.server import spec_from_env
        from kubeflow_tpu.webhook.tpu_env import (
            KUBEFLOW_TPU_SPEC_ADAPTIVE,
            KUBEFLOW_TPU_SPEC_DRAFT_LEN,
        )

        monkeypatch.delenv(KUBEFLOW_TPU_SPEC_DRAFT_LEN, raising=False)
        monkeypatch.delenv(KUBEFLOW_TPU_SPEC_ADAPTIVE, raising=False)
        assert spec_from_env() == (0, False)
        monkeypatch.setenv(KUBEFLOW_TPU_SPEC_DRAFT_LEN, "4")
        monkeypatch.setenv(KUBEFLOW_TPU_SPEC_ADAPTIVE, "true")
        assert spec_from_env() == (4, True)
        for bad in ("-1", "four", "3.5"):
            monkeypatch.setenv(KUBEFLOW_TPU_SPEC_DRAFT_LEN, bad)
            with pytest.raises(ValueError, match="SPEC_DRAFT_LEN"):
                spec_from_env()
        monkeypatch.setenv(KUBEFLOW_TPU_SPEC_DRAFT_LEN, "4")
        monkeypatch.setenv(KUBEFLOW_TPU_SPEC_ADAPTIVE, "maybe")
        with pytest.raises(ValueError, match="SPEC_ADAPTIVE"):
            spec_from_env()
        # Adaptive without a draft length has no range to adapt over.
        monkeypatch.delenv(KUBEFLOW_TPU_SPEC_DRAFT_LEN)
        monkeypatch.setenv(KUBEFLOW_TPU_SPEC_ADAPTIVE, "1")
        with pytest.raises(ValueError, match="SPEC_ADAPTIVE"):
            spec_from_env()

    def test_lora_cache_from_env(self, monkeypatch):
        from kubeflow_tpu.models.server import lora_cache_from_env
        from kubeflow_tpu.webhook.tpu_env import (
            KUBEFLOW_TPU_LORA_CACHE_SLOTS,
        )

        monkeypatch.delenv(KUBEFLOW_TPU_LORA_CACHE_SLOTS, raising=False)
        assert lora_cache_from_env() == 0
        monkeypatch.setenv(KUBEFLOW_TPU_LORA_CACHE_SLOTS, "16")
        assert lora_cache_from_env() == 16
        for bad in ("-2", "many", "1.5"):
            monkeypatch.setenv(KUBEFLOW_TPU_LORA_CACHE_SLOTS, bad)
            with pytest.raises(ValueError, match="LORA_CACHE_SLOTS"):
                lora_cache_from_env()


class TestAdapterChainKeys:
    def test_adapter_keys_never_cross_hit(self):
        toks = [1, 2, 3, 4]
        keys = {
            PagedBatcher._chain_key(None, toks),
            PagedBatcher._chain_key(None, toks, adapter=0),
            PagedBatcher._chain_key(None, toks, adapter=1),
        }
        assert len(keys) == 3
        # The salt lives in the ROOT: children of different adapters'
        # roots stay disjoint for identical token suffixes too.
        children = {
            PagedBatcher._chain_key(k, [5, 6, 7, 8]) for k in keys
        }
        assert len(children) == 3

    def test_base_model_key_is_legacy_key(self):
        """adapter=None must hash exactly like the pre-adapter engine:
        existing caches and gateway rings stay valid byte for byte."""
        toks = [9, 8, 7, 6]
        assert PagedBatcher._chain_key(None, toks) == \
            PagedBatcher._chain_key(None, toks, adapter=None)

    def test_gateway_parity_including_salt(self):
        from kubeflow_tpu.models.gateway import chain_key

        toks = [1, 2, 3, 4]
        for adapter in (None, 0, 7):
            k_engine = PagedBatcher._chain_key(None, toks,
                                               adapter=adapter)
            assert chain_key(None, toks, adapter=adapter) == k_engine
            assert chain_key(k_engine, [5, 6]) == \
                PagedBatcher._chain_key(k_engine, [5, 6])


class TestAdapterHotCache:
    def test_lru_and_eviction_counters(self):
        from kubeflow_tpu.models.multilora import _AdapterHotCache

        c = _AdapterHotCache(2)
        c.touch(0)
        c.touch(1)
        assert c.stats() == {"slots": 2, "resident": 2, "hits": 0,
                             "misses": 2, "evictions": 0}
        c.touch(0)  # hit → 0 becomes MRU
        c.touch(2)  # full → evicts 1 (the LRU), not 0
        c.touch(0)  # still resident
        st = c.stats()
        assert st["hits"] == 2 and st["misses"] == 3
        assert st["evictions"] == 1 and st["resident"] == 2
        c.touch(1)  # re-load of the evicted adapter is a miss
        assert c.stats()["misses"] == 4

    def test_rejects_zero_slots(self):
        from kubeflow_tpu.models.multilora import _AdapterHotCache

        with pytest.raises(ValueError, match="slots"):
            _AdapterHotCache(0)


class TestGatewayAdapterAffinity:
    def _gateway(self, adapter_affinity):
        from kubeflow_tpu.models.gateway import ServingGateway

        # Routing policy is pure ring arithmetic — no .start() needed.
        return ServingGateway(
            [f"10.0.0.{i}:80" for i in range(4)], port=0,
            affinity="prefix", block_size=4,
            adapter_affinity=adapter_affinity,
        )

    @staticmethod
    def _misses(gw, adapters=16, cache_slots=8, rounds=4):
        """Simulate each replica's bounded hot-adapter cache over the
        gateway's routing decisions: 16 adapters sharing ONE system
        prompt, replicas holding 8 — the aggregate miss count is the
        adapter-thrash the routing policy does (or doesn't) avoid."""
        from collections import OrderedDict

        prompt = list(range(12))  # the shared 3-block system prefix
        caches: dict = {}
        misses = 0
        for _ in range(rounds):
            for a in range(adapters):
                gw._route_key(prompt, adapter=a)  # converge registry
                key = gw._route_key(prompt, adapter=a)
                ep = gw._ring.lookup(key)
                lru = caches.setdefault(ep, OrderedDict())
                if a in lru:
                    lru.move_to_end(a)
                else:
                    misses += 1
                    lru[a] = None
                    if len(lru) > cache_slots:
                        lru.popitem(last=False)
        return misses, caches

    def test_affinity_beats_adapter_oblivious_routing(self):
        """Oblivious routing sends every adapter of a shared prefix to
        ONE replica (16 adapters thrash its 8-slot cache forever);
        folding the adapter into the route key spreads them so each
        replica's share fits — misses collapse to the cold loads."""
        aff_misses, aff_caches = self._misses(self._gateway(True))
        obl_misses, obl_caches = self._misses(self._gateway(False))
        assert len(obl_caches) == 1  # the pathology being fixed
        assert len(aff_caches) > 1
        assert aff_misses < obl_misses
        # Steady state: an oblivious replica churns every round, while
        # affinity's per-replica working sets stop missing after warmup
        # unless the ring hashes >8 adapters onto one replica.
        assert obl_misses == 16 * 4

    def test_adapter_salt_only_applies_when_enabled(self):
        prompt = list(range(12))
        gw = self._gateway(True)
        gw._route_key(prompt)  # warm the prefix registry (converges)
        keys = {gw._route_key(prompt, adapter=a) for a in (None, 0, 1)}
        assert len(keys) == 3  # distinct routes per adapter
        gw_off = self._gateway(False)
        gw_off._route_key(prompt)
        keys_off = {gw_off._route_key(prompt, adapter=a)
                    for a in (None, 0, 1)}
        assert len(keys_off) == 1  # oblivious: adapter never routes
