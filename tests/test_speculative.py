"""Speculative decoding: the exactness guarantee and acceptance stats."""

from __future__ import annotations

import jax
import numpy as np
import pytest

from kubeflow_tpu.models import llama as L
from kubeflow_tpu.models.speculative import speculative_generate


@pytest.fixture(scope="module")
def target():
    cfg = L.LLAMA_CONFIGS["tiny"]
    return cfg, L.init_params(cfg, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def draft():
    # Different weights, same vocab — a realistic (if untrained) draft.
    cfg = L.LlamaConfig(vocab_size=256, dim=64, n_layers=1, n_heads=2,
                        n_kv_heads=2, ffn_hidden=128, max_seq_len=256)
    return cfg, L.init_params(cfg, jax.random.PRNGKey(7))


def _prompt(n=8):
    return jax.random.randint(jax.random.PRNGKey(1), (1, n), 0, 256)


class TestExactness:
    def test_output_equals_target_greedy_with_foreign_draft(self, target, draft):
        """THE speculative-decoding invariant: any draft, same output."""
        tcfg, tparams = target
        dcfg, dparams = draft
        prompt = _prompt()
        steps = 24
        ref = np.asarray(
            L.generate(tparams, tcfg, prompt, steps=steps, cache_len=64)
        )
        out, stats = speculative_generate(
            tparams, tcfg, dparams, dcfg, prompt,
            steps=steps, cache_len=64, k_spec=4,
        )
        np.testing.assert_array_equal(np.asarray(out), ref)
        assert 0.0 <= stats["acceptance_rate"] <= 1.0

    def test_self_draft_accepts_everything(self, target):
        """Draft == target: every proposal must be accepted."""
        tcfg, tparams = target
        prompt = _prompt()
        out, stats = speculative_generate(
            tparams, tcfg, tparams, tcfg, prompt,
            steps=16, cache_len=64, k_spec=4,
        )
        assert stats["acceptance_rate"] == 1.0
        ref = np.asarray(L.generate(tparams, tcfg, prompt, steps=16, cache_len=64))
        np.testing.assert_array_equal(np.asarray(out), ref)

    @pytest.mark.parametrize("k_spec", [1, 2, 6])
    def test_exact_for_any_speculation_depth(self, target, draft, k_spec):
        tcfg, tparams = target
        dcfg, dparams = draft
        prompt = _prompt(5)
        ref = np.asarray(L.generate(tparams, tcfg, prompt, steps=12, cache_len=48))
        out, _ = speculative_generate(
            tparams, tcfg, dparams, dcfg, prompt,
            steps=12, cache_len=48, k_spec=k_spec,
        )
        np.testing.assert_array_equal(np.asarray(out), ref)

    def test_batched_equals_target_greedy_per_row(self, target, draft):
        """Batched rounds with divergent per-row cache pointers: every
        row's output must equal its own target-only greedy decode —
        acceptance lengths differ per row, so this exercises the
        per-row pointer advance and the frozen-row discipline."""
        tcfg, tparams = target
        dcfg, dparams = draft
        prompt = jax.random.randint(jax.random.PRNGKey(5), (4, 8), 0, 256)
        steps = 20
        ref = np.asarray(
            L.generate(tparams, tcfg, prompt, steps=steps, cache_len=64)
        )
        out, stats = speculative_generate(
            tparams, tcfg, dparams, dcfg, prompt,
            steps=steps, cache_len=64, k_spec=4,
        )
        np.testing.assert_array_equal(np.asarray(out), ref)
        assert 0.0 <= stats["acceptance_rate"] <= 1.0

    def test_batched_self_draft_accepts_everything(self, target):
        tcfg, tparams = target
        prompt = jax.random.randint(jax.random.PRNGKey(6), (3, 6), 0, 256)
        out, stats = speculative_generate(
            tparams, tcfg, tparams, tcfg, prompt,
            steps=12, cache_len=48, k_spec=4,
        )
        assert stats["acceptance_rate"] == 1.0
        ref = np.asarray(L.generate(tparams, tcfg, prompt, steps=12, cache_len=48))
        np.testing.assert_array_equal(np.asarray(out), ref)
