"""Speculative decoding: the exactness guarantee and acceptance stats."""

from __future__ import annotations

import jax
import numpy as np
import pytest

from kubeflow_tpu.models import llama as L
from kubeflow_tpu.models.speculative import speculative_generate


@pytest.fixture(scope="module")
def target():
    cfg = L.LLAMA_CONFIGS["tiny"]
    return cfg, L.init_params(cfg, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def draft():
    # Different weights, same vocab — a realistic (if untrained) draft.
    cfg = L.LlamaConfig(vocab_size=256, dim=64, n_layers=1, n_heads=2,
                        n_kv_heads=2, ffn_hidden=128, max_seq_len=256)
    return cfg, L.init_params(cfg, jax.random.PRNGKey(7))


def _prompt(n=8):
    return jax.random.randint(jax.random.PRNGKey(1), (1, n), 0, 256)


class TestExactness:
    def test_output_equals_target_greedy_with_foreign_draft(self, target, draft):
        """THE speculative-decoding invariant: any draft, same output."""
        tcfg, tparams = target
        dcfg, dparams = draft
        prompt = _prompt()
        steps = 24
        ref = np.asarray(
            L.generate(tparams, tcfg, prompt, steps=steps, cache_len=64)
        )
        out, stats = speculative_generate(
            tparams, tcfg, dparams, dcfg, prompt,
            steps=steps, cache_len=64, k_spec=4,
        )
        np.testing.assert_array_equal(np.asarray(out), ref)
        assert 0.0 <= stats["acceptance_rate"] <= 1.0

    def test_self_draft_accepts_everything(self, target):
        """Draft == target: every proposal must be accepted."""
        tcfg, tparams = target
        prompt = _prompt()
        out, stats = speculative_generate(
            tparams, tcfg, tparams, tcfg, prompt,
            steps=16, cache_len=64, k_spec=4,
        )
        assert stats["acceptance_rate"] == 1.0
        ref = np.asarray(L.generate(tparams, tcfg, prompt, steps=16, cache_len=64))
        np.testing.assert_array_equal(np.asarray(out), ref)

    @pytest.mark.parametrize("k_spec", [1, 2, 6])
    def test_exact_for_any_speculation_depth(self, target, draft, k_spec):
        tcfg, tparams = target
        dcfg, dparams = draft
        prompt = _prompt(5)
        ref = np.asarray(L.generate(tparams, tcfg, prompt, steps=12, cache_len=48))
        out, _ = speculative_generate(
            tparams, tcfg, dparams, dcfg, prompt,
            steps=12, cache_len=48, k_spec=k_spec,
        )
        np.testing.assert_array_equal(np.asarray(out), ref)

    def test_batched_equals_target_greedy_per_row(self, target, draft):
        """Batched rounds with divergent per-row cache pointers: every
        row's output must equal its own target-only greedy decode —
        acceptance lengths differ per row, so this exercises the
        per-row pointer advance and the frozen-row discipline."""
        tcfg, tparams = target
        dcfg, dparams = draft
        prompt = jax.random.randint(jax.random.PRNGKey(5), (4, 8), 0, 256)
        steps = 20
        ref = np.asarray(
            L.generate(tparams, tcfg, prompt, steps=steps, cache_len=64)
        )
        out, stats = speculative_generate(
            tparams, tcfg, dparams, dcfg, prompt,
            steps=steps, cache_len=64, k_spec=4,
        )
        np.testing.assert_array_equal(np.asarray(out), ref)
        assert 0.0 <= stats["acceptance_rate"] <= 1.0

    def test_batched_self_draft_accepts_everything(self, target):
        tcfg, tparams = target
        prompt = jax.random.randint(jax.random.PRNGKey(6), (3, 6), 0, 256)
        out, stats = speculative_generate(
            tparams, tcfg, tparams, tcfg, prompt,
            steps=12, cache_len=48, k_spec=4,
        )
        assert stats["acceptance_rate"] == 1.0
        ref = np.asarray(L.generate(tparams, tcfg, prompt, steps=12, cache_len=48))
        np.testing.assert_array_equal(np.asarray(out), ref)


class TestSpeculativeServing:
    def test_serving_stays_on_greedy_path(self, target, draft):
        """The spec batcher is a throughput engine, not a semantics
        change: with more requests than slots and mixed prompt lengths,
        every request's tokens must follow the greedy path of ITS OWN
        prompt. (Tie-tolerant, not token-equal vs the plain batcher: the
        verify chunk computes logits in a different shape, and bf16
        near-ties legitimately break differently across shapes — the
        same standard the continuous/paged suites use for cross-shape
        comparisons.)"""
        from kubeflow_tpu.models.serving import GenerationConfig
        from kubeflow_tpu.models.speculative import (
            SpeculativeContinuousBatcher,
        )
        from tests.test_continuous import _assert_greedy_consistent

        tcfg, tparams = target
        dcfg, dparams = draft
        gen = GenerationConfig(max_new_tokens=8, eos_id=-1)
        ks = jax.random.split(jax.random.PRNGKey(9), 5)
        prompts = [
            [int(t) for t in jax.random.randint(k, (4 + i,), 3, 250)]
            for i, k in enumerate(ks)
        ]
        sb = SpeculativeContinuousBatcher(
            tparams, tcfg, dparams, dcfg, gen=gen, slots=2,
            cache_len=64, prompt_bucket=16, k_spec=4,
        )
        rids = [sb.submit(p) for p in prompts]
        got = sb.run()
        assert len(got) == len(prompts)
        for rid, prompt in zip(rids, prompts):
            assert len(got[rid]) == 8
            _assert_greedy_consistent(tparams, tcfg, prompt, got[rid])
        assert 0.0 <= sb.acceptance_rate <= 1.0

    def test_serving_self_draft_accepts_everything(self, target):
        from kubeflow_tpu.models.serving import GenerationConfig
        from kubeflow_tpu.models.speculative import (
            SpeculativeContinuousBatcher,
        )

        tcfg, tparams = target
        gen = GenerationConfig(max_new_tokens=8, eos_id=-1)
        sb = SpeculativeContinuousBatcher(
            tparams, tcfg, tparams, tcfg, gen=gen, slots=2,
            cache_len=64, prompt_bucket=16,
        )
        rids = [sb.submit([3 + i, 41, 90]) for i in range(3)]
        out = sb.run()
        assert all(len(out[r]) == 8 for r in rids)
        assert sb.acceptance_rate == 1.0

    def test_serving_eos_retires_early(self, target, draft):
        """EOS mid-round retires the slot and drops the round's surplus
        tokens; the freed slot serves the next request."""
        from kubeflow_tpu.models.continuous import ContinuousBatcher
        from kubeflow_tpu.models.serving import GenerationConfig
        from kubeflow_tpu.models.speculative import (
            SpeculativeContinuousBatcher,
        )

        tcfg, tparams = target
        dcfg, dparams = draft
        probe = GenerationConfig(max_new_tokens=6, eos_id=-1)
        prompt = [5, 9, 17]
        cb = ContinuousBatcher(tparams, tcfg, gen=probe, slots=1,
                               cache_len=64, prompt_bucket=16)
        rid = cb.submit(prompt)
        eos = cb.run()[rid][2]  # third emitted token becomes the EOS

        gen = GenerationConfig(max_new_tokens=6, eos_id=eos)
        sb = SpeculativeContinuousBatcher(
            tparams, tcfg, dparams, dcfg, gen=gen, slots=1,
            cache_len=64, prompt_bucket=16,
        )
        r1, r2 = sb.submit(prompt), sb.submit([8, 44, 91, 7])
        out = sb.run()
        assert eos not in out[r1]
        assert len(out[r1]) == 2  # stopped at the EOS
        assert len(out[r2]) <= 6  # second request served after the retire

    def test_serving_rejects_sampling(self, target, draft):
        from kubeflow_tpu.models.serving import GenerationConfig
        from kubeflow_tpu.models.speculative import (
            SpeculativeContinuousBatcher,
        )

        tcfg, tparams = target
        dcfg, dparams = draft
        with pytest.raises(ValueError, match="greedy-only"):
            SpeculativeContinuousBatcher(
                tparams, tcfg, dparams, dcfg,
                gen=GenerationConfig(max_new_tokens=4, temperature=0.8),
                cache_len=256,
            )


class TestShardedSpeculativeServing:
    """Speculative serving composed with a device mesh: tp shards the
    target AND draft params/caches through the same MeshPlan; the spec
    engine's token stream must be exactly the single-device stream (the
    plan changes where tensors live, not what the server emits)."""

    def _run(self, target, draft, plan=None, kv_bits=0):
        from kubeflow_tpu.models.serving import GenerationConfig
        from kubeflow_tpu.models.speculative import (
            SpeculativeContinuousBatcher,
        )

        tcfg, tparams = target
        dcfg, dparams = draft
        gen = GenerationConfig(max_new_tokens=6, eos_id=-1)
        ks = jax.random.split(jax.random.PRNGKey(13), 3)
        prompts = [
            [int(t) for t in jax.random.randint(k, (4 + i,), 3, 250)]
            for i, k in enumerate(ks)
        ]
        sb = SpeculativeContinuousBatcher(
            tparams, tcfg, dparams, dcfg, gen=gen, slots=2,
            cache_len=64, prompt_bucket=16, k_spec=3, plan=plan,
            kv_bits=kv_bits,
        )
        rids = [sb.submit(p) for p in prompts]
        out = sb.run()
        return [out[r] for r in rids], sb.acceptance_rate

    def test_tp_sharded_stays_on_greedy_path(self, target, draft):
        """tp changes the psum reduction order, so a bf16 near-tie may
        legitimately fork vs single-device (same standard as the serving
        suite's cross-shape comparisons): assert every emitted token
        follows the greedy path of its own prompt, not byte-equality."""
        from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh
        from tests.test_continuous import _assert_greedy_consistent

        tcfg, tparams = target
        plan = MeshPlan(make_mesh(tp=2, devices=jax.devices()[:2]))
        got, rate = self._run(target, draft, plan=plan)
        ks = jax.random.split(jax.random.PRNGKey(13), 3)
        prompts = [
            [int(t) for t in jax.random.randint(k, (4 + i,), 3, 250)]
            for i, k in enumerate(ks)
        ]
        for prompt, tokens in zip(prompts, got):
            assert len(tokens) == 6
            _assert_greedy_consistent(tparams, tcfg, prompt, tokens)
        assert 0.0 <= rate <= 1.0

    def test_sp_mesh_rejected_with_reason(self, target, draft):
        from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh

        plan = MeshPlan(make_mesh(tp=1, sp=2, devices=jax.devices()[:2]))
        with pytest.raises(ValueError, match="sp-sharded"):
            self._run(target, draft, plan=plan)

    def test_int8_kv_spec_serving(self, target, draft):
        """kv_bits=8 reaches BOTH the target and draft caches; the spec
        invariant (output == target-alone greedy, for the same cache
        format) holds because verify and plain decode read the same
        quantized storage."""
        import jax.numpy as jnp
        from kubeflow_tpu.models.continuous import ContinuousBatcher
        from kubeflow_tpu.models.serving import GenerationConfig
        from kubeflow_tpu.models.speculative import (
            SpeculativeContinuousBatcher,
        )

        tcfg, tparams = target
        dcfg, dparams = draft
        gen = GenerationConfig(max_new_tokens=6, eos_id=-1)
        sb = SpeculativeContinuousBatcher(
            tparams, tcfg, dparams, dcfg, gen=gen, slots=2,
            cache_len=64, prompt_bucket=16, k_spec=3, kv_bits=8,
        )
        assert sb._cb.cache["k"].dtype == jnp.int8
        assert sb.draft_cache["k"].dtype == jnp.int8
        prompts = [[5, 9, 17, 33], [7, 3, 11]]
        rids = [sb.submit(p) for p in prompts]
        out = sb.run()
        assert all(len(out[r]) == 6 for r in rids)


class TestFrozenRowClamp:
    def test_minimum_cache_len_with_staggered_rows_stays_exact(self):
        """Rows that finish early keep riding rounds with a parked
        pointer; at the MINIMUM legal cache_len surplus acceptances can
        park a pointer at the clamp boundary. Output for every row must
        still equal target-alone greedy (the clamp keeps dead writes
        in-bounds without touching live rows)."""
        tcfg = L.LLAMA_CONFIGS["tiny"]
        tparams = L.init_params(tcfg, jax.random.PRNGKey(0))
        dcfg = L.LlamaConfig(vocab_size=256, dim=64, n_layers=1, n_heads=2,
                             n_kv_heads=2, ffn_hidden=128, max_seq_len=256)
        dparams = L.init_params(dcfg, jax.random.PRNGKey(7))
        s_prompt, steps, k_spec = 8, 12, 4
        prompt = jax.random.randint(jax.random.PRNGKey(3), (3, s_prompt),
                                    0, tcfg.vocab_size)
        cache_len = s_prompt + steps + k_spec  # the exact minimum
        out, stats = speculative_generate(
            tparams, tcfg, dparams, dcfg, prompt, steps=steps,
            cache_len=cache_len, k_spec=k_spec,
        )
        ref = L.generate(tparams, tcfg, prompt, steps=steps,
                         cache_len=cache_len)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
        assert 0.0 <= stats["acceptance_rate"] <= 1.0


class TestSpeculativePagedServing:
    """Speculative decoding composed with the paged block pool: the
    target verifies (B, k+1) chunks THROUGH the block tables, memory
    stays pool-sized, and every emitted token follows the greedy path of
    its own prompt."""

    def _make(self, target, draft, num_blocks=40, k_spec=3, slots=2,
              max_new=8, kv_bits=0, plan=None, key=None):
        from kubeflow_tpu.models.serving import GenerationConfig
        from kubeflow_tpu.models.speculative import SpeculativePagedBatcher

        tcfg, tparams = target
        dcfg, dparams = draft
        gen = GenerationConfig(max_new_tokens=max_new, eos_id=-1)
        return SpeculativePagedBatcher(
            tparams, tcfg, dparams, dcfg, gen=gen, slots=slots,
            num_blocks=num_blocks, block_size=8, prompt_bucket=16,
            k_spec=k_spec, kv_bits=kv_bits, plan=plan, key=key,
        )

    def test_serving_stays_on_greedy_path(self, target, draft):
        from tests.test_continuous import _assert_greedy_consistent

        tcfg, tparams = target
        ks = jax.random.split(jax.random.PRNGKey(21), 5)
        prompts = [
            [int(t) for t in jax.random.randint(k, (4 + i,), 3, 250)]
            for i, k in enumerate(ks)
        ]
        sb = self._make(target, draft)
        rids = [sb.submit(p) for p in prompts]
        got = sb.run()
        for rid, prompt in zip(rids, prompts):
            assert len(got[rid]) == 8
            _assert_greedy_consistent(tparams, tcfg, prompt, got[rid])
        assert 0.0 <= sb.acceptance_rate <= 1.0
        # Every block returned to the pool after the run.
        assert sb.free_blocks == 39

    def test_self_draft_accepts_everything(self, target):
        sb = self._make(target, target)
        rids = [sb.submit([3 + i, 41, 90]) for i in range(3)]
        out = sb.run()
        assert all(len(out[r]) == 8 for r in rids)
        assert sb.acceptance_rate == 1.0

    def test_int8_pool_runs(self, target, draft):
        import jax.numpy as jnp

        sb = self._make(target, draft, kv_bits=8)
        assert sb._pb.pool["k"].dtype == jnp.int8
        assert sb.draft_cache["k"].dtype == jnp.int8
        rids = [sb.submit([5, 9, 17]), sb.submit([7, 3, 11, 2])]
        out = sb.run()
        assert all(len(out[r]) == 8 for r in rids)

    def test_starved_pool_preempts_and_completes(self, target, draft):
        """Pool too small for both slots' spans: preemption re-queues the
        youngest, its continuation re-admits (draft re-prefills via the
        _post_admit hook), and every request still completes its budget
        on the greedy path."""
        from tests.test_continuous import _assert_greedy_consistent

        tcfg, tparams = target
        prompts = [[5, 9, 17, 33], [7, 3, 11], [8, 44, 91, 7, 2]]
        sb = self._make(target, draft, num_blocks=12, max_new=10, slots=2)
        rids = [sb.submit(p) for p in prompts]
        out = sb.run()
        for rid, prompt in zip(rids, prompts):
            assert len(out[rid]) == 10
            _assert_greedy_consistent(tparams, tcfg, prompt, out[rid])

    def test_eos_retires_early_and_frees_blocks(self, target, draft):
        from kubeflow_tpu.models.serving import GenerationConfig
        from kubeflow_tpu.models.speculative import SpeculativePagedBatcher

        tcfg, tparams = target
        dcfg, dparams = draft
        probe = self._make(target, draft, max_new=6)
        r = probe.submit([5, 9, 17])
        eos = probe.run()[r][2]  # third emitted token becomes the EOS

        gen = GenerationConfig(max_new_tokens=6, eos_id=eos)
        sb = SpeculativePagedBatcher(
            tparams, tcfg, dparams, dcfg, gen=gen, slots=1,
            num_blocks=40, block_size=8, prompt_bucket=16, k_spec=3,
        )
        r1, r2 = sb.submit([5, 9, 17]), sb.submit([8, 44, 91, 7])
        out = sb.run()
        assert eos not in out[r1]
        assert len(out[r1]) == 2
        assert len(out[r2]) <= 6
        assert sb.free_blocks == 39

    def test_tp_sharded_stays_on_greedy_path(self, target, draft):
        from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh
        from tests.test_continuous import _assert_greedy_consistent

        tcfg, tparams = target
        prompts = [[5, 9, 17], [3, 41, 90, 7]]
        plan = MeshPlan(make_mesh(tp=2, devices=jax.devices()[:2]))
        sb = self._make(target, draft, plan=plan)
        rids = [sb.submit(p) for p in prompts]
        out = sb.run()
        for rid, prompt in zip(rids, prompts):
            assert len(out[rid]) == 8
            _assert_greedy_consistent(tparams, tcfg, prompt, out[rid])


class TestTruncatedDraft:
    def test_layers_sliced_and_rest_shared(self, target):
        from kubeflow_tpu.models.speculative import truncated_draft

        tcfg, tparams = target
        dparams, dcfg = truncated_draft(tparams, tcfg, 1)
        assert dcfg.n_layers == 1
        assert dparams["layers"]["wq"].shape[0] == 1
        assert dparams["embed"] is tparams["embed"]  # shared, not copied

    def test_bounds_validated(self, target):
        from kubeflow_tpu.models.speculative import truncated_draft

        tcfg, tparams = target
        with pytest.raises(ValueError, match="n_layers"):
            truncated_draft(tparams, tcfg, tcfg.n_layers)
        with pytest.raises(ValueError, match="n_layers"):
            truncated_draft(tparams, tcfg, 0)

    def test_spec_output_stays_target_greedy(self, target):
        """The spec invariant is draft-independent: a truncated-layer
        draft must still yield exactly the target's greedy output."""
        from kubeflow_tpu.models.speculative import truncated_draft

        tcfg, tparams = target
        dparams, dcfg = truncated_draft(tparams, tcfg, 1)
        prompt = _prompt(6)
        ref = np.asarray(L.generate(tparams, tcfg, prompt, steps=12,
                                    cache_len=48))
        out, stats = speculative_generate(
            tparams, tcfg, dparams, dcfg, prompt, steps=12, cache_len=48,
            k_spec=3,
        )
        np.testing.assert_array_equal(np.asarray(out), ref)
        assert 0.0 <= stats["acceptance_rate"] <= 1.0


class TestSpecPagedPromptCache:
    def test_identical_prompts_share_target_blocks(self, target, draft):
        """prompt_cache composes with speculative paged serving: the
        target's prompt blocks are shared on a hit (draft re-prefills its
        own dense cache per slot), and both requests emit the same
        greedy stream."""
        from kubeflow_tpu.models.serving import GenerationConfig
        from kubeflow_tpu.models.speculative import SpeculativePagedBatcher

        tcfg, tparams = target
        dcfg, dparams = draft
        gen = GenerationConfig(max_new_tokens=6, eos_id=-1)
        sb = SpeculativePagedBatcher(
            tparams, tcfg, dparams, dcfg, gen=gen, slots=2, num_blocks=40,
            block_size=8, prompt_bucket=16, k_spec=3, prompt_cache=True,
        )
        prompt = [5, 9, 17, 33]
        r1, r2, r3 = sb.submit(prompt), sb.submit(prompt), sb.submit(prompt)
        out = sb.run()
        assert out[r1] == out[r2] == out[r3]
        assert len(out[r1]) == 6
        assert len(sb._pb._prompt_cache) == 1


class TestSpecPagedPrefixCache:
    def test_common_prefix_shares_target_blocks(self, target, draft):
        """prefix_cache composes with speculative paged serving: the
        position-0-anchored target pool shares common-PREFIX blocks
        across different-length prompts while the dense draft cache
        primes right-anchored per slot; outputs stay on each prompt's
        greedy path (verified against the no-cache spec engine)."""
        from kubeflow_tpu.models.serving import GenerationConfig
        from kubeflow_tpu.models.speculative import SpeculativePagedBatcher

        tcfg, tparams = target
        dcfg, dparams = draft
        gen = GenerationConfig(max_new_tokens=6, eos_id=-1)
        prefix = [5, 9, 17, 33, 41, 2, 77, 13]  # one full block (BS=8)
        prompts = [prefix + [3, 8], prefix + [60, 4, 29, 7, 90]]

        def run(**kw):
            sb = SpeculativePagedBatcher(
                tparams, tcfg, dparams, dcfg, gen=gen, slots=2,
                num_blocks=40, block_size=8, prompt_bucket=16, k_spec=3,
                **kw,
            )
            rids = [sb.submit(p) for p in prompts]
            out = sb.run()
            return [out[r] for r in rids], sb

        want, _ = run()
        got, sb = run(prefix_cache=True)
        assert got == want
        assert len(sb._pb._prefix_entries) >= 1  # the prefix block cached


class TestSpecPagedMultiBlockSpan:
    def test_verify_chunk_wider_than_block(self, target, draft):
        """k_spec+1 > block_size: one verify round spans MULTIPLE new
        blocks; the span-aware allocator must cover them all (multi-pass)
        and the stream must stay on the greedy path."""
        from kubeflow_tpu.models.serving import GenerationConfig
        from kubeflow_tpu.models.speculative import SpeculativePagedBatcher
        from tests.test_continuous import _assert_greedy_consistent

        tcfg, tparams = target
        dcfg, dparams = draft
        gen = GenerationConfig(max_new_tokens=10, eos_id=-1)
        sb = SpeculativePagedBatcher(
            tparams, tcfg, dparams, dcfg, gen=gen, slots=2, num_blocks=48,
            block_size=4, prompt_bucket=16, k_spec=6,  # span 7 > 4
        )
        prompts = [[5, 9, 17, 33], [7, 3, 11]]
        rids = [sb.submit(p) for p in prompts]
        out = sb.run()
        for rid, prompt in zip(rids, prompts):
            assert len(out[rid]) == 10
            _assert_greedy_consistent(tparams, tcfg, prompt, out[rid])
