"""Webhook tests: envtest-with-webhooks tier (reference suite_test.go:122-126
installs both webhooks; specs in notebook_mutating_webhook_test.go)."""

import pytest

from kubeflow_tpu.api import annotations as ann
from kubeflow_tpu.api.notebook import Notebook, TPUSpec
from kubeflow_tpu.k8s import WebhookDeniedError
from kubeflow_tpu.k8s import objects as obj_util

from tests.harness import cpu_notebook, make_env, tpu_notebook


def get_env_var(container, name):
    for e in container.get("env", []):
        if e.get("name") == name:
            return e
    return None


def primary(env, name="nb", ns="ns"):
    nb = Notebook(env.cluster.get("Notebook", name, ns))
    return nb, nb.primary_container()


class TestReconciliationLock:
    def test_create_injects_lock(self):
        env = make_env(webhooks=True)
        env.cluster.create(cpu_notebook())
        nb = env.cluster.get("Notebook", "nb", "ns")
        assert nb["metadata"]["annotations"][ann.STOP] == ann.RECONCILIATION_LOCK_VALUE

    def test_lock_keeps_slice_down_until_released(self):
        env = make_env(webhooks=True)
        env.cluster.create(tpu_notebook())
        env.manager.run_until_idle()
        assert env.cluster.get("StatefulSet", "nb", "ns")["spec"]["replicas"] == 0
        # Platform reconciler releases the lock (simulated here).
        nb = env.cluster.get("Notebook", "nb", "ns")
        obj_util.remove_annotation(nb, ann.STOP)
        env.cluster.update(nb)
        env.manager.run_until_idle()
        assert env.cluster.get("StatefulSet", "nb", "ns")["spec"]["replicas"] == 4

    def test_user_stop_annotation_not_overwritten(self):
        env = make_env(webhooks=True)
        env.cluster.create(cpu_notebook(annotations={ann.STOP: "2026-01-01T00:00:00Z"}))
        nb = env.cluster.get("Notebook", "nb", "ns")
        assert nb["metadata"]["annotations"][ann.STOP] == "2026-01-01T00:00:00Z"


class TestTpuEnvInjection:
    def test_multi_host_env_block(self):
        env = make_env(webhooks=True)
        env.cluster.create(tpu_notebook())
        _, c = primary(env)
        assert get_env_var(c, "TPU_WORKER_ID")["valueFrom"]["fieldRef"]["fieldPath"] == (
            "metadata.labels['apps.kubernetes.io/pod-index']"
        )
        hostnames = get_env_var(c, "TPU_WORKER_HOSTNAMES")["value"].split(",")
        assert len(hostnames) == 4
        assert hostnames[0] == "nb-0.nb-hosts.ns.svc.cluster.local"
        assert get_env_var(c, "TPU_ACCELERATOR_TYPE")["value"] == "v5litepod-16"
        assert get_env_var(c, "TPU_TOPOLOGY")["value"] == "4x4"
        assert get_env_var(c, "TPU_CHIPS_PER_HOST_BOUNDS")["value"] == "2,2,1"
        assert get_env_var(c, "JAX_COORDINATOR_ADDRESS")["value"] == (
            "nb-0.nb-hosts.ns.svc.cluster.local:8476"
        )
        assert get_env_var(c, "JAX_NUM_PROCESSES")["value"] == "4"

    def test_single_host_no_coordinator(self):
        env = make_env(webhooks=True)
        env.cluster.create(tpu_notebook(topology="2x2"))
        _, c = primary(env)
        assert get_env_var(c, "JAX_COORDINATOR_ADDRESS") is None
        assert get_env_var(c, "TPU_WORKER_HOSTNAMES")["value"].count(",") == 0

    def test_cpu_notebook_untouched(self):
        env = make_env(webhooks=True)
        env.cluster.create(cpu_notebook())
        _, c = primary(env)
        assert get_env_var(c, "TPU_WORKER_ID") is None

    def test_resolved_topology_annotation(self):
        env = make_env(webhooks=True)
        env.cluster.create(tpu_notebook())
        nb = env.cluster.get("Notebook", "nb", "ns")
        assert nb["metadata"]["annotations"][ann.TPU_RESOLVED_TOPOLOGY] == (
            "v5litepod-16/4x4"
        )


class TestQuantizationOption:
    def test_annotation_projects_env(self):
        env = make_env(webhooks=True)
        env.cluster.create(
            tpu_notebook(annotations={ann.TPU_QUANTIZATION: "int8"})
        )
        _, c = primary(env)
        assert get_env_var(c, ann.QUANT_ENV_NAME)["value"] == "int8"

    def test_bf16_and_absent_mean_no_env(self):
        env = make_env(webhooks=True)
        env.cluster.create(
            tpu_notebook(annotations={ann.TPU_QUANTIZATION: "bf16"})
        )
        _, c = primary(env)
        assert get_env_var(c, ann.QUANT_ENV_NAME) is None
        env2 = make_env(webhooks=True)
        env2.cluster.create(cpu_notebook())
        _, c2 = primary(env2)
        assert get_env_var(c2, ann.QUANT_ENV_NAME) is None

    def test_removal_drops_env(self):
        env = make_env(webhooks=True)
        env.cluster.create(
            tpu_notebook(annotations={ann.TPU_QUANTIZATION: "int4"})
        )
        nb = env.cluster.get("Notebook", "nb", "ns")
        del nb["metadata"]["annotations"][ann.TPU_QUANTIZATION]
        env.cluster.update(nb)
        _, c = primary(env)
        assert get_env_var(c, ann.QUANT_ENV_NAME) is None

    def test_unknown_value_denied(self):
        env = make_env(webhooks=True)
        with pytest.raises(WebhookDeniedError, match="unknown value"):
            env.cluster.create(
                tpu_notebook(annotations={ann.TPU_QUANTIZATION: "fp4"})
            )

    def test_fp8_value_projects_env(self):
        env = make_env(webhooks=True)
        env.cluster.create(
            tpu_notebook(annotations={ann.TPU_QUANTIZATION: "fp8"})
        )
        _, c = primary(env)
        assert get_env_var(c, ann.QUANT_ENV_NAME)["value"] == "fp8"

    def test_env_consumed_by_runtime(self, monkeypatch):
        from kubeflow_tpu.models.quant import quant_bits_from_env

        monkeypatch.delenv(ann.QUANT_ENV_NAME, raising=False)
        assert quant_bits_from_env() == 0
        monkeypatch.setenv(ann.QUANT_ENV_NAME, "int8")
        assert quant_bits_from_env() == 8
        monkeypatch.setenv(ann.QUANT_ENV_NAME, "int4")
        assert quant_bits_from_env() == 4
        monkeypatch.setenv(ann.QUANT_ENV_NAME, "bf16")
        assert quant_bits_from_env() == 0
        monkeypatch.setenv(ann.QUANT_ENV_NAME, "fp4")
        with pytest.raises(ValueError, match="fp4"):
            quant_bits_from_env()


class TestProfilingOption:
    def test_annotation_projects_env_and_status_address(self):
        env = make_env(webhooks=True)
        env.cluster.create(
            tpu_notebook(annotations={ann.TPU_PROFILING_PORT: "9012"})
        )
        env.manager.run_until_idle()
        _, c = primary(env)
        assert get_env_var(c, ann.PROFILING_ENV_NAME)["value"] == "9012"
        nb = env.cluster.get("Notebook", "nb", "ns")
        assert nb["status"]["tpu"]["profilingServer"] == (
            "nb-0.nb-hosts.ns.svc.cluster.local:9012"
        )

    def test_network_policy_opens_profiling_port(self):
        env = make_env(webhooks=True, platform=True)
        env.cluster.create(
            tpu_notebook(annotations={ann.TPU_PROFILING_PORT: "9012"})
        )
        env.manager.run_until_idle()
        np_obj = env.cluster.get("NetworkPolicy", "nb-ctrl-np", "ns")
        ports = [
            p["port"] for rule in np_obj["spec"]["ingress"]
            for p in rule["ports"]
        ]
        assert 8888 in ports and 9012 in ports

    def test_invalid_port_denied(self):
        env = make_env(webhooks=True)
        for bad in ("80", "notaport", "70000"):
            with pytest.raises(WebhookDeniedError, match="not a port"):
                env.cluster.create(
                    tpu_notebook(annotations={ann.TPU_PROFILING_PORT: bad})
                )

    def test_reserved_in_pod_ports_denied(self):
        """Ports already claimed in-pod (notebook server 8888, rbac proxy
        8443, JAX coordinator 8476, megascale 8081) pass the 1024..65535
        range check but would collide at bootstrap
        (jax.profiler.start_server fails AFTER admission) — deny them at
        admission where the conflict is explainable."""
        env = make_env(webhooks=True)
        for port in ("8888", "8443", "8476", "8081"):
            with pytest.raises(WebhookDeniedError, match="already used in-pod"):
                env.cluster.create(
                    tpu_notebook(annotations={ann.TPU_PROFILING_PORT: port})
                )

    def test_serving_port_projects_env_status_and_network(self):
        """tpu-serving-port mirrors the profiling plumbing end to end:
        env for the HTTP inference server, worker-0 endpoint in status,
        and an opened ctrl NetworkPolicy port."""
        env = make_env(webhooks=True, platform=True)
        env.cluster.create(
            tpu_notebook(annotations={ann.TPU_SERVING_PORT: "8200"})
        )
        env.manager.run_until_idle()
        _, c = primary(env)
        assert get_env_var(c, ann.SERVING_ENV_NAME)["value"] == "8200"
        nb = env.cluster.get("Notebook", "nb", "ns")
        assert nb["status"]["tpu"]["servingEndpoint"] == (
            "nb-0.nb-hosts.ns.svc.cluster.local:8200"
        )
        np_obj = env.cluster.get("NetworkPolicy", "nb-ctrl-np", "ns")
        ports = [
            p["port"] for rule in np_obj["spec"]["ingress"]
            for p in rule["ports"]
        ]
        assert 8200 in ports

    def test_serving_port_invalid_and_collision_denied(self):
        env = make_env(webhooks=True)
        with pytest.raises(WebhookDeniedError, match="not a port"):
            env.cluster.create(
                tpu_notebook(annotations={ann.TPU_SERVING_PORT: "80"})
            )
        with pytest.raises(WebhookDeniedError, match="already used in-pod"):
            env.cluster.create(
                tpu_notebook(annotations={ann.TPU_SERVING_PORT: "8888"})
            )
        # serving and profiling may not claim the same port
        with pytest.raises(WebhookDeniedError, match="same port"):
            env.cluster.create(
                tpu_notebook(annotations={
                    ann.TPU_SERVING_PORT: "9100",
                    ann.TPU_PROFILING_PORT: "9100",
                })
            )

    def test_serving_port_removal_drops_env(self):
        env = make_env(webhooks=True)
        env.cluster.create(
            tpu_notebook(annotations={ann.TPU_SERVING_PORT: "8200"})
        )
        nb = env.cluster.get("Notebook", "nb", "ns")
        del nb["metadata"]["annotations"][ann.TPU_SERVING_PORT]
        env.cluster.update(nb)
        _, c = primary(env)
        assert get_env_var(c, ann.SERVING_ENV_NAME) is None

    def test_serving_port_env_consumed_by_server(self, monkeypatch):
        from kubeflow_tpu.models.server import serving_port_from_env

        monkeypatch.delenv(ann.SERVING_ENV_NAME, raising=False)
        assert serving_port_from_env() == 8000
        monkeypatch.setenv(ann.SERVING_ENV_NAME, "8200")
        assert serving_port_from_env() == 8200
        monkeypatch.setenv(ann.SERVING_ENV_NAME, "not-a-port")
        with pytest.raises(ValueError, match="SERVING_PORT"):
            serving_port_from_env()

    def test_bootstrap_starts_profiler_server(self, monkeypatch):
        # runtime/__init__ re-exports the bootstrap FUNCTION under the same
        # name, shadowing the submodule attribute; resolve the module.
        import importlib

        bs = importlib.import_module("kubeflow_tpu.runtime.bootstrap")

        started = []
        monkeypatch.setattr(bs, "_PROFILER_PORT", None)
        import jax

        monkeypatch.setattr(jax.profiler, "start_server", started.append)
        assert bs.maybe_start_profiler_server({}) is None
        port = bs.maybe_start_profiler_server(
            {ann.PROFILING_ENV_NAME: "9012"}
        )
        assert port == 9012 and started == [9012]
        # Idempotent: a notebook cell re-run must not raise.
        assert bs.maybe_start_profiler_server(
            {ann.PROFILING_ENV_NAME: "9012"}
        ) == 9012
        assert started == [9012]
        # Moving ports mid-process is a lie we refuse to tell.
        with pytest.raises(RuntimeError, match="already listens"):
            bs.maybe_start_profiler_server({ann.PROFILING_ENV_NAME: "9013"})
        # A hand-set invalid env var fails loudly.
        monkeypatch.setattr(bs, "_PROFILER_PORT", None)
        with pytest.raises(ValueError, match="not a port"):
            bs.maybe_start_profiler_server({ann.PROFILING_ENV_NAME: "80"})


class TestImageResolution:
    def _imagestream(self, env):
        env.cluster.create(
            {
                "apiVersion": "image.openshift.io/v1",
                "kind": "ImageStream",
                "metadata": {"name": "jupyter-ds", "namespace": "opendatahub"},
                "spec": {"tags": [{"name": "2026.1", "from": {"name": "spec-img"}}]},
                "status": {
                    "tags": [
                        {
                            "tag": "2026.1",
                            "items": [{"dockerImageReference": "registry/ds@sha256:abc"}],
                        }
                    ]
                },
            }
        )

    def test_resolves_from_status_tag(self):
        env = make_env(webhooks=True)
        self._imagestream(env)
        env.cluster.create(
            cpu_notebook(annotations={ann.LAST_IMAGE_SELECTION: "jupyter-ds:2026.1"})
        )
        _, c = primary(env)
        assert c["image"] == "registry/ds@sha256:abc"

    def test_missing_stream_keeps_image(self):
        env = make_env(webhooks=True)
        env.cluster.create(
            cpu_notebook(annotations={ann.LAST_IMAGE_SELECTION: "nope:1"})
        )
        _, c = primary(env)
        assert c["image"] == "jupyter-minimal:latest"


class TestAuthSidecar:
    def test_injected_with_defaults(self):
        env = make_env(webhooks=True)
        env.cluster.create(cpu_notebook(annotations={ann.INJECT_AUTH: "true"}))
        nb, _ = primary(env)
        sidecar = next(
            c for c in nb.containers if c["name"] == "kube-rbac-proxy"
        )
        assert sidecar["resources"]["requests"]["cpu"] == "100m"
        assert nb.pod_spec["serviceAccountName"] == "nb-auth-proxy"
        vol_names = {v["name"] for v in nb.pod_spec["volumes"]}
        assert {"kube-rbac-proxy-config", "kube-rbac-proxy-tls"} <= vol_names

    def test_resource_annotations_override(self):
        env = make_env(webhooks=True)
        env.cluster.create(
            cpu_notebook(
                annotations={
                    ann.INJECT_AUTH: "true",
                    ann.AUTH_SIDECAR_CPU_REQUEST: "250m",
                    ann.AUTH_SIDECAR_MEMORY_LIMIT: "128Mi",
                }
            )
        )
        nb, _ = primary(env)
        sidecar = next(c for c in nb.containers if c["name"] == "kube-rbac-proxy")
        assert sidecar["resources"]["requests"]["cpu"] == "250m"
        assert sidecar["resources"]["limits"]["memory"] == "128Mi"

    def test_invalid_resource_annotation_denied(self):
        env = make_env(webhooks=True)
        with pytest.raises(WebhookDeniedError):
            env.cluster.create(
                cpu_notebook(
                    annotations={
                        ann.INJECT_AUTH: "true",
                        ann.AUTH_SIDECAR_CPU_REQUEST: "lots-please",
                    }
                )
            )

    def test_sidecar_removed_when_auth_disabled(self):
        env = make_env(webhooks=True)
        env.cluster.create(cpu_notebook(annotations={ann.INJECT_AUTH: "true"}))
        nb = env.cluster.get("Notebook", "nb", "ns")
        del nb["metadata"]["annotations"][ann.INJECT_AUTH]
        env.cluster.update(nb)
        nb, _ = primary(env)
        assert all(c["name"] != "kube-rbac-proxy" for c in nb.containers)


class TestUpdateBlocking:
    def _running_notebook(self, env):
        env.cluster.create(tpu_notebook())
        nb = env.cluster.get("Notebook", "nb", "ns")
        obj_util.remove_annotation(nb, ann.STOP)  # release the lock
        env.cluster.update(nb)
        env.manager.run_until_idle()
        return env.cluster.get("Notebook", "nb", "ns")

    def test_webhook_drift_reverted_on_running_notebook(self):
        env = make_env(webhooks=True)
        nb = self._running_notebook(env)
        image_before = Notebook(nb).primary_container()["image"]
        # A CA bundle appears AFTER the notebook started: mounting it would
        # change the template → must be blocked while running.
        env.cluster.create(
            {
                "apiVersion": "v1",
                "kind": "ConfigMap",
                "metadata": {"name": "workbench-trusted-ca-bundle", "namespace": "ns"},
                "data": {"ca-bundle.crt": "PEMPEM"},
            }
        )
        nb = env.cluster.get("Notebook", "nb", "ns")
        obj_util.set_annotation(nb, "touch", "1")  # metadata-only user update
        env.cluster.update(nb)
        fresh = Notebook(env.cluster.get("Notebook", "nb", "ns"))
        assert get_env_var(fresh.primary_container(), "SSL_CERT_FILE") is None
        assert fresh.primary_container()["image"] == image_before
        pending = fresh.annotations[ann.UPDATE_PENDING]
        assert "trusted-ca" in pending or "volume" in pending or "env" in pending

    def test_user_template_change_allowed_while_running(self):
        env = make_env(webhooks=True)
        nb = self._running_notebook(env)
        nb["spec"]["template"]["spec"]["containers"][0]["image"] = "jax-notebook:v2"
        env.cluster.update(nb)
        fresh = Notebook(env.cluster.get("Notebook", "nb", "ns"))
        assert fresh.primary_container()["image"] == "jax-notebook:v2"
        assert ann.UPDATE_PENDING not in fresh.annotations

    def test_mutations_land_on_stopped_notebook(self):
        env = make_env(webhooks=True)
        env.cluster.create(
            {
                "apiVersion": "v1",
                "kind": "ConfigMap",
                "metadata": {"name": "workbench-trusted-ca-bundle", "namespace": "ns"},
                "data": {"ca-bundle.crt": "PEMPEM"},
            }
        )
        env.cluster.create(tpu_notebook())  # created with lock → stopped
        nb = env.cluster.get("Notebook", "nb", "ns")
        obj_util.set_annotation(nb, "touch", "1")
        env.cluster.update(nb)
        fresh = Notebook(env.cluster.get("Notebook", "nb", "ns"))
        assert get_env_var(fresh.primary_container(), "SSL_CERT_FILE") is not None
        assert ann.UPDATE_PENDING not in fresh.annotations


class TestValidatingWebhook:
    def test_invalid_topology_denied_at_create(self):
        env = make_env(webhooks=True)
        with pytest.raises(WebhookDeniedError, match="invalid spec.tpu"):
            env.cluster.create(tpu_notebook(topology="3x4"))
        assert not env.cluster.exists("Notebook", "nb", "ns")

    def test_tpu_change_denied_while_running(self):
        env = make_env(webhooks=True)
        env.cluster.create(tpu_notebook())
        nb = env.cluster.get("Notebook", "nb", "ns")
        obj_util.remove_annotation(nb, ann.STOP)
        env.cluster.update(nb)
        nb = env.cluster.get("Notebook", "nb", "ns")
        nb["spec"]["tpu"]["topology"] = "4x8"
        with pytest.raises(WebhookDeniedError, match="cannot change"):
            env.cluster.update(nb)

    def test_tpu_change_allowed_when_stopped(self):
        env = make_env(webhooks=True)
        env.cluster.create(tpu_notebook())  # lock → stopped
        nb = env.cluster.get("Notebook", "nb", "ns")
        nb["spec"]["tpu"]["topology"] = "4x8"
        env.cluster.update(nb)
        nb = env.cluster.get("Notebook", "nb", "ns")
        assert nb["spec"]["tpu"]["topology"] == "4x8"

    def test_mlflow_annotation_removal_denied_while_running(self):
        env = make_env(webhooks=True)
        env.cluster.create(
            cpu_notebook(annotations={ann.MLFLOW_INSTANCE: "tracking"})
        )
        nb = env.cluster.get("Notebook", "nb", "ns")
        obj_util.remove_annotation(nb, ann.STOP)
        env.cluster.update(nb)
        nb = env.cluster.get("Notebook", "nb", "ns")
        del nb["metadata"]["annotations"][ann.MLFLOW_INSTANCE]
        with pytest.raises(WebhookDeniedError, match="cannot be removed"):
            env.cluster.update(nb)


class TestMlflowAndProxyEnv:
    def test_mlflow_env_injected(self):
        from kubeflow_tpu.webhook import WebhookConfig

        env = make_env(
            webhooks=True,
            webhook_config=WebhookConfig(
                mlflow_enabled=True, gateway_url="https://gw.example"
            ),
        )
        env.cluster.create(
            cpu_notebook(annotations={ann.MLFLOW_INSTANCE: "team-tracking"})
        )
        _, c = primary(env)
        assert get_env_var(c, "MLFLOW_TRACKING_URI")["value"] == (
            "https://gw.example/mlflow/team-tracking"
        )
        assert get_env_var(c, "MLFLOW_K8S_INTEGRATION")["value"] == "true"

    def test_cluster_proxy_env(self):
        from kubeflow_tpu.webhook import WebhookConfig

        env = make_env(
            webhooks=True,
            webhook_config=WebhookConfig(inject_cluster_proxy_env=True),
        )
        env.cluster.create(
            {
                "apiVersion": "config.openshift.io/v1",
                "kind": "Proxy",
                "metadata": {"name": "cluster"},
                "spec": {"httpProxy": "http://proxy:3128", "noProxy": ".cluster.local"},
            }
        )
        env.cluster.create(cpu_notebook())
        _, c = primary(env)
        assert get_env_var(c, "HTTP_PROXY")["value"] == "http://proxy:3128"
        assert get_env_var(c, "NO_PROXY")["value"] == ".cluster.local"


class TestFeastMount:
    def test_label_gated_mount_and_unmount(self):
        env = make_env(webhooks=True)
        env.cluster.create(
            cpu_notebook(labels={ann.FEAST_INTEGRATION_LABEL: "true"})
        )
        nb, c = primary(env)
        assert any(v["name"] == "feast-config" for v in nb.pod_spec["volumes"])
        assert any(m["name"] == "feast-config" for m in c["volumeMounts"])
        fresh = env.cluster.get("Notebook", "nb", "ns")
        fresh["metadata"]["labels"][ann.FEAST_INTEGRATION_LABEL] = "false"
        env.cluster.update(fresh)
        nb, _ = primary(env)
        assert all(v["name"] != "feast-config" for v in nb.pod_spec.get("volumes", []))


class TestReviewRegressions:
    def test_shrinking_topology_drops_multihost_env(self):
        """4x4 → 2x2 while stopped must remove JAX coordinator env."""
        env = make_env(webhooks=True)
        env.cluster.create(tpu_notebook())  # 4x4, created with lock (stopped)
        nb = env.cluster.get("Notebook", "nb", "ns")
        nb["spec"]["tpu"]["topology"] = "2x2"
        env.cluster.update(nb)
        _, c = primary(env)
        assert get_env_var(c, "JAX_COORDINATOR_ADDRESS") is None
        assert get_env_var(c, "JAX_NUM_PROCESSES") is None
        assert get_env_var(c, "TPU_TOPOLOGY")["value"] == "2x2"

    def test_auth_flip_on_running_notebook_rolls_out(self):
        """Disabling auth on a running notebook must remove the sidecar —
        NOT park it as update-pending while the platform deletes its SA."""
        env = make_env(webhooks=True)
        env.cluster.create(cpu_notebook(annotations={ann.INJECT_AUTH: "true"}))
        nb = env.cluster.get("Notebook", "nb", "ns")
        obj_util.remove_annotation(nb, ann.STOP)  # release lock → running
        env.cluster.update(nb)
        env.manager.run_until_idle()
        nb = env.cluster.get("Notebook", "nb", "ns")
        del nb["metadata"]["annotations"][ann.INJECT_AUTH]
        env.cluster.update(nb)
        fresh = Notebook(env.cluster.get("Notebook", "nb", "ns"))
        assert all(c["name"] != "kube-rbac-proxy" for c in fresh.containers)
        assert ann.UPDATE_PENDING not in fresh.annotations
        assert "serviceAccountName" not in fresh.pod_spec


class TestProfilingPortLayering:
    def test_parser_is_range_only_admission_rejects_reserved(self):
        """parse_profiling_port honors annotations admitted under OLDER
        rules (range-only), while profiling_port_error — the admission
        gate — additionally rejects reserved in-pod ports. A pre-existing
        notebook with port 8888 must keep its NetworkPolicy/status/
        bootstrap behavior; only NEW admissions are denied."""
        from kubeflow_tpu.api import annotations as ann
        from kubeflow_tpu.api import names

        reserved = names.NOTEBOOK_PORT
        assert ann.parse_profiling_port(str(reserved)) == reserved
        assert ann.profiling_port_error(str(reserved)) is not None
        # Range rules stay shared by both.
        for bad in ("80", "0", "70000", "nope", "²"):
            assert ann.parse_profiling_port(bad) is None
            assert ann.profiling_port_error(bad) is not None
        assert ann.parse_profiling_port("9999") == 9999
        assert ann.profiling_port_error("9999") is None


class TestCheckpointOption:
    def test_grace_annotation_projects_env_and_sizes_termination(self):
        """The grace annotation must land in BOTH places the durability
        contract needs: TPU_CHECKPOINT_GRACE_S for bootstrap's SIGTERM
        handler, and terminationGracePeriodSeconds = grace + flush margin
        so the kubelet actually waits for the emergency save."""
        from kubeflow_tpu.deploy.manifests import CHECKPOINT_FLUSH_MARGIN_S

        env = make_env(webhooks=True)
        env.cluster.create(
            tpu_notebook(annotations={ann.TPU_CHECKPOINT_GRACE: "60"})
        )
        env.manager.run_until_idle()
        nb, c = primary(env)
        assert get_env_var(c, ann.CHECKPOINT_GRACE_ENV_NAME)["value"] == "60"
        assert nb.pod_spec["terminationGracePeriodSeconds"] == (
            60 + CHECKPOINT_FLUSH_MARGIN_S
        )

    def test_no_annotation_still_gets_checkpoint_dir_default(self):
        """Every TPU notebook gets the checkpoint dir env (runtime code
        must never hardcode the PVC path); without a grace annotation
        there is no grace env and the pod's grace period is untouched."""
        env = make_env(webhooks=True)
        env.cluster.create(tpu_notebook())
        env.manager.run_until_idle()
        nb, c = primary(env)
        assert get_env_var(c, ann.CHECKPOINT_DIR_ENV_NAME)["value"] == (
            ann.DEFAULT_CHECKPOINT_DIR
        )
        assert get_env_var(c, ann.CHECKPOINT_GRACE_ENV_NAME) is None
        assert "terminationGracePeriodSeconds" not in nb.pod_spec

    def test_dir_annotation_overrides_default(self):
        env = make_env(webhooks=True)
        env.cluster.create(
            tpu_notebook(
                annotations={ann.TPU_CHECKPOINT_DIR: "/data/ckpt "}
            )
        )
        env.manager.run_until_idle()
        _, c = primary(env)
        assert get_env_var(c, ann.CHECKPOINT_DIR_ENV_NAME)["value"] == (
            "/data/ckpt"
        )

    def test_invalid_grace_treated_as_absent(self):
        for bad in ("0", "-5", "3601", "soon", ""):
            env = make_env(webhooks=True)
            env.cluster.create(
                tpu_notebook(annotations={ann.TPU_CHECKPOINT_GRACE: bad})
            )
            env.manager.run_until_idle()
            nb, c = primary(env)
            assert get_env_var(c, ann.CHECKPOINT_GRACE_ENV_NAME) is None, bad
            assert "terminationGracePeriodSeconds" not in nb.pod_spec, bad

    def test_cpu_notebook_gets_no_checkpoint_env(self):
        env = make_env(webhooks=True)
        env.cluster.create(
            cpu_notebook(annotations={ann.TPU_CHECKPOINT_GRACE: "60"})
        )
        _, c = primary(env)
        assert get_env_var(c, ann.CHECKPOINT_DIR_ENV_NAME) is None
        assert get_env_var(c, ann.CHECKPOINT_GRACE_ENV_NAME) is None
