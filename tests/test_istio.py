"""Istio serving mode (USE_ISTIO): per-notebook VirtualService lifecycle.

Reference behavior being reproduced: notebook_controller.go:238 (env
gate), :554-658 (generateVirtualService — prefix match, rewrite with
annotation override, header-set annotation, route to the Service), and
reconcilehelper CopyVirtualService (util.go:199-219). The kubeflow
overlay enables it; standalone/GKE serve through Gateway-API HTTPRoutes.
"""

from __future__ import annotations

from kubeflow_tpu.api import annotations as ann
from kubeflow_tpu.controller.notebook import (
    ControllerConfig,
    generate_virtual_service,
    virtual_service_name,
)

from tests.harness import cpu_notebook, make_env


def _istio_env(**cfg_kw):
    return make_env(
        controller_config=ControllerConfig(use_istio=True, **cfg_kw)
    )


def _vs(env, name="nb", ns="ns"):
    return env.cluster.get("VirtualService", virtual_service_name(name, ns), ns)


class TestVirtualService:
    def test_created_with_reference_shape(self):
        env = _istio_env()
        env.cluster.create(cpu_notebook())
        env.manager.run_until_idle()
        vs = _vs(env)
        assert vs["metadata"]["name"] == "notebook-ns-nb"
        spec = vs["spec"]
        assert spec["hosts"] == ["*"]
        assert spec["gateways"] == ["kubeflow/kubeflow-gateway"]
        http = spec["http"][0]
        assert http["match"][0]["uri"]["prefix"] == "/notebook/ns/nb/"
        assert http["rewrite"]["uri"] == "/notebook/ns/nb/"
        dest = http["route"][0]["destination"]
        assert dest["host"] == "nb.ns.svc.cluster.local"
        assert dest["port"]["number"] == 80
        # Owned: deleted with the notebook.
        assert vs["metadata"]["ownerReferences"][0]["kind"] == "Notebook"

    def test_gateway_and_host_from_config(self):
        env = _istio_env(istio_gateway="mesh/gw", istio_host="nb.example.com")
        env.cluster.create(cpu_notebook())
        env.manager.run_until_idle()
        spec = _vs(env)["spec"]
        assert spec["gateways"] == ["mesh/gw"]
        assert spec["hosts"] == ["nb.example.com"]

    def test_rewrite_annotation_override(self):
        env = _istio_env()
        env.cluster.create(
            cpu_notebook(annotations={ann.REWRITE_URI: "/custom/"})
        )
        env.manager.run_until_idle()
        assert _vs(env)["spec"]["http"][0]["rewrite"]["uri"] == "/custom/"

    def test_headers_annotation_sets_request_headers(self):
        env = _istio_env()
        env.cluster.create(cpu_notebook(annotations={
            ann.HEADERS_REQUEST_SET: '{"X-Forwarded-Prefix": "/notebook/ns/nb"}'
        }))
        env.manager.run_until_idle()
        hdrs = _vs(env)["spec"]["http"][0]["headers"]["request"]["set"]
        assert hdrs == {"X-Forwarded-Prefix": "/notebook/ns/nb"}

    def test_malformed_headers_json_degrades_to_empty(self):
        """Reference behavior: bad JSON → empty header set, reconcile
        proceeds (notebook_controller.go:608-612)."""
        env = _istio_env()
        env.cluster.create(
            cpu_notebook(annotations={ann.HEADERS_REQUEST_SET: "{not json"})
        )
        env.manager.run_until_idle()
        assert _vs(env)["spec"]["http"][0]["headers"]["request"]["set"] == {}

    def test_drifted_spec_restored(self):
        """Level-triggered: an out-of-band spec edit is reverted
        (CopyVirtualService semantics)."""
        env = _istio_env()
        env.cluster.create(cpu_notebook())
        env.manager.run_until_idle()
        vs = _vs(env)
        vs["spec"]["gateways"] = ["rogue/gw"]
        env.cluster.update(vs)
        # Touch the notebook to trigger a reconcile.
        nb = env.cluster.get("Notebook", "nb", "ns")
        nb["metadata"].setdefault("annotations", {})["touch"] = "1"
        env.cluster.update(nb)
        env.manager.run_until_idle()
        assert _vs(env)["spec"]["gateways"] == ["kubeflow/kubeflow-gateway"]

    def test_disabled_by_default(self):
        env = make_env()
        env.cluster.create(cpu_notebook())
        env.manager.run_until_idle()
        assert env.cluster.list("VirtualService", "ns") == []

    def test_config_from_env(self):
        cfg = ControllerConfig.from_env({
            "USE_ISTIO": "true", "ISTIO_GATEWAY": "g/w", "ISTIO_HOST": "h",
        })
        assert cfg.use_istio and cfg.istio_gateway == "g/w"
        assert cfg.istio_host == "h"
        assert not ControllerConfig.from_env({}).use_istio

    def test_long_name_routes_to_derived_service(self):
        """Names past the 63-char Service budget use the hashed fallback
        Service; the VirtualService destination must follow it or Istio
        503s while every child object looks healthy."""
        from kubeflow_tpu.controller.notebook import routing_service_name

        long = "n" * 70
        env = _istio_env()
        env.cluster.create(cpu_notebook(name=long))
        env.manager.run_until_idle()
        vs = env.cluster.get(
            "VirtualService", virtual_service_name(long, "ns"), "ns"
        )
        dest = vs["spec"]["http"][0]["route"][0]["destination"]["host"]
        derived = routing_service_name(long)
        assert derived != long
        assert dest == f"{derived}.ns.svc.cluster.local"
        # And that Service actually exists.
        assert env.cluster.get("Service", derived, "ns")

    def test_generator_is_pure(self):
        from kubeflow_tpu.api.notebook import Notebook

        from tests.harness import cpu_notebook as mk

        nb = Notebook(mk(name="n2", namespace="team"))
        vs = generate_virtual_service(nb, ControllerConfig(use_istio=True))
        assert vs["metadata"]["name"] == "notebook-team-n2"
        assert vs["apiVersion"] == "networking.istio.io/v1beta1"
