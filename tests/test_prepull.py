"""Image pre-pull controller: per-TPU-node coverage, set changes, retry.

TPU-native subsystem with no reference counterpart (the reference's
spawn path pulls images cold — SURVEY.md §6); this is the cold-node
counterpart to SlicePool's warm-node image retention (BASELINE.md's
<90 s p50 spawn budget).
"""

from __future__ import annotations

from kubeflow_tpu import k8s
from kubeflow_tpu.controller.prepull import (
    PREPULL_LABEL,
    RETRY_FAILED_AFTER,
    PrePullConfig,
    PrePullReconciler,
    image_set_digest,
    prepull_pod_name,
)
from kubeflow_tpu.k8s.fixtures import FakePodRunner

from tests.harness import make_env, tpu_notebook

NS = "kubeflow"


def _prepull_env(fail_images=(), images=("workbench:v1",)):
    env = make_env()
    if images:
        env.cluster.create({
            "apiVersion": "v1", "kind": "ConfigMap",
            "metadata": {"name": "notebook-prepull-images", "namespace": NS},
            "data": {f"img{i}": img for i, img in enumerate(images)},
        })
    pre = PrePullReconciler(
        env.cluster, config=PrePullConfig(namespace=NS),
        metrics=env.metrics, clock=env.clock,
    )
    pre.register(env.manager)
    FakePodRunner(env.cluster, fail_images=frozenset(fail_images)).register(
        env.manager
    )
    return env, pre


def _prepull_pods(env):
    return [
        p for p in env.cluster.list("Pod", NS)
        if PREPULL_LABEL in (p["metadata"].get("labels") or {})
    ]


class TestPrePull:
    def test_one_succeeded_pod_per_tpu_node(self):
        env, _ = _prepull_env(images=("workbench:v1", "workbench:v2"))
        env.manager.run_until_idle()
        pods = _prepull_pods(env)
        # Default harness pool: 4 TPU hosts; the CPU node is NOT covered.
        assert len(pods) == 4
        tpu_nodes = {
            n["metadata"]["name"]
            for n in env.cluster.list("Node")
            if "cloud.google.com/gke-tpu-accelerator"
            in (n["metadata"].get("labels") or {})
        }
        assert {p["spec"]["nodeName"] for p in pods} == tpu_nodes
        for p in pods:
            assert p["status"]["phase"] == "Succeeded"
            pulled = [c["image"] for c in p["spec"]["initContainers"]
                      if c["name"].startswith("pull-")]
            assert pulled == ["workbench:v1", "workbench:v2"]
            # The distroless-safe recipe: a copied busybox runs in every
            # target image (deploy.manifests.image_prepuller_daemonset).
            assert p["spec"]["initContainers"][0]["name"] == "copy-busybox"
            # Never consumes chip capacity the scheduler could hand out.
            for c in p["spec"]["containers"] + p["spec"]["initContainers"]:
                limits = c.get("resources", {}).get("limits", {})
                assert "google.com/tpu" not in limits
        assert env.metrics.prepull_nodes_covered._value.get() == 4
        assert env.metrics.prepull_nodes_target._value.get() == 4

    def test_live_tpu_notebook_images_join_the_set(self):
        env, _ = _prepull_env(images=("workbench:v1",))
        env.manager.run_until_idle()
        env.cluster.create(tpu_notebook(name="nb1"))
        env.manager.run_until_idle()
        pods = _prepull_pods(env)
        assert pods, "pods must exist after the roll"
        for p in pods:
            pulled = {c["image"] for c in p["spec"]["initContainers"]
                      if c["name"].startswith("pull-")}
            assert pulled == {"workbench:v1", "jax-notebook:latest"}
            assert p["status"]["phase"] == "Succeeded"

    def test_image_set_change_rolls_pods(self):
        env, _ = _prepull_env(images=("workbench:v1",))
        env.manager.run_until_idle()
        old = {p["metadata"]["name"] for p in _prepull_pods(env)}
        cm = env.cluster.get("ConfigMap", "notebook-prepull-images", NS)
        cm["data"]["img0"] = "workbench:v2"
        env.cluster.update(cm)
        env.manager.run_until_idle()
        new = {p["metadata"]["name"] for p in _prepull_pods(env)}
        assert new and not (new & old)  # full roll, nothing stale left
        digest = image_set_digest(["workbench:v2"])
        assert all(name.endswith(digest) for name in new)

    def test_empty_image_set_removes_all_pods(self):
        env, _ = _prepull_env(images=("workbench:v1",))
        env.manager.run_until_idle()
        assert _prepull_pods(env)
        env.cluster.delete("ConfigMap", "notebook-prepull-images", NS)
        env.manager.run_until_idle()
        assert _prepull_pods(env) == []

    def test_failed_pull_backs_off_then_retries(self):
        env, _ = _prepull_env(
            images=("broken:ref",), fail_images=("broken:ref",)
        )
        env.manager.run_until_idle()
        pods = _prepull_pods(env)
        assert pods and all(p["status"]["phase"] == "Failed" for p in pods)
        first_names = {p["metadata"]["name"] for p in pods}
        # Within the backoff window the Failed pods are KEPT (no thrash).
        env.manager.run_until_idle()
        assert {p["metadata"]["name"] for p in _prepull_pods(env)} == first_names
        assert env.metrics.prepull_nodes_covered._value.get() == 0
        # After the window, they are deleted and re-created (fresh pull
        # attempt — which fails again here, but the attempt happened).
        env.clock.advance(RETRY_FAILED_AFTER + 1)
        env.manager.tick(0)
        env.manager.run_until_idle()
        again = _prepull_pods(env)
        assert again and all(p["status"]["phase"] == "Failed" for p in again)

    def test_manager_gate_wires_prepull(self):
        from kubeflow_tpu.cmd.notebook_manager import build

        cluster = k8s.FakeCluster()
        on = build(cluster, env={"ENABLE_IMAGE_PREPULL": "true"}, argv=[])
        assert on.prepull_reconciler is not None
        assert on.prepull_reconciler.enabled
        # Off still registers (disabled mode must GC leftovers) but
        # maintains nothing.
        off = build(cluster, env={}, argv=[])
        assert off.prepull_reconciler is not None
        assert not off.prepull_reconciler.enabled

    def test_disabling_gate_garbage_collects_pods(self):
        env, _ = _prepull_env(images=("workbench:v1",))
        env.manager.run_until_idle()
        assert _prepull_pods(env)
        # Controller restart with the gate off: same cluster, disabled
        # reconciler — leftover node-pinned pods must be swept.
        pre = PrePullReconciler(
            env.cluster, config=PrePullConfig(namespace=NS),
            clock=env.clock, enabled=False,
        )
        from kubeflow_tpu.k8s.manager import Request
        pre.reconcile(Request("notebook-prepull-images", NS))
        assert _prepull_pods(env) == []
