"""Pipeline (pp) and expert (ep) parallelism: numerics + sharded training.

Runs on the 8-device virtual CPU mesh (tests/conftest.py). Key invariants:
- pipelined layer stack == sequential forward (same params, same tokens)
- MoE with identical experts == the same math as a single dense expert
- pp/ep train steps compile, run, and produce finite decreasing loss
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models import llama as L
from kubeflow_tpu.models import moe as M
from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh
from kubeflow_tpu.parallel import pipeline as pl


@pytest.fixture(scope="module")
def pp_mesh():
    return make_mesh(dp=2, pp=2, sp=2, devices=jax.devices()[:8])


@pytest.fixture(scope="module")
def ep_mesh():
    return make_mesh(dp=2, fsdp=2, ep=2, devices=jax.devices()[:8])


def test_mesh_axis_order_includes_pp_ep():
    mesh = make_mesh(dp=2, fsdp=2, ep=2, devices=jax.devices()[:8])
    assert mesh.shape == {"dp": 2, "fsdp": 2, "ep": 2, "pp": 1, "sp": 1, "tp": 1}


def test_stage_split_round_trip():
    cfg = L.LLAMA_CONFIGS["tiny"]
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    staged = pl.split_layers_into_stages(params["layers"], 2)
    assert staged["wq"].shape[0] == 2
    merged = pl.merge_stages_into_layers(staged)
    np.testing.assert_array_equal(merged["wq"], params["layers"]["wq"])


def test_stage_split_rejects_indivisible():
    cfg = L.LLAMA_CONFIGS["tiny"]
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="not divisible"):
        pl.split_layers_into_stages(params["layers"], 3)


def test_pipeline_forward_matches_sequential(pp_mesh):
    cfg = L.LLAMA_CONFIGS["tiny"]
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab_size)

    expected = L.forward(params, cfg, tokens, attn_impl="xla")

    staged = dict(params)
    staged["layers"] = pl.split_layers_into_stages(params["layers"], 2)
    staged = pl.shard_pipeline_params(staged, pp_mesh)
    got = pl.pipeline_forward(staged, cfg, tokens, pp_mesh, n_micro=2)

    np.testing.assert_allclose(
        np.asarray(got), np.asarray(expected), rtol=2e-2, atol=2e-2
    )


def test_pipeline_train_step_runs_and_improves(pp_mesh):
    cfg = L.LLAMA_CONFIGS["tiny"]
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    staged = dict(params)
    staged["layers"] = pl.split_layers_into_stages(params["layers"], 2)
    staged = pl.shard_pipeline_params(staged, pp_mesh)

    init_state, step = pl.make_pipeline_train_step(cfg, pp_mesh, n_micro=2)
    state = init_state(staged)
    tokens = jax.random.randint(jax.random.PRNGKey(2), (4, 32), 0, cfg.vocab_size)
    losses = []
    for _ in range(3):
        state, loss = step(state, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]  # memorizing one batch must reduce loss
    assert int(state["step"]) == 3


def test_moe_forward_shapes_and_aux():
    cfg = M.MOE_CONFIGS["tiny-moe"]
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    logits, aux = M.forward(params, cfg, tokens)
    assert logits.shape == (2, 16, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    # Perfectly balanced routing gives aux == 1; anything sane is near it.
    assert 0.5 < float(aux) < float(cfg.n_experts)


def test_moe_identical_experts_match_dense_mlp():
    """With every expert holding the same weights, routing is irrelevant:
    the MoE FFN must equal that single expert's SwiGLU output."""
    cfg = M.MOE_CONFIGS["tiny-moe"]
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    layers = params["layers"]
    for name in ("w_gate", "w_up", "w_down"):
        first = layers[name][:, :1]
        layers[name] = jnp.broadcast_to(first, layers[name].shape)

    layer0 = jax.tree.map(lambda x: x[0], layers)
    x = jax.random.normal(jax.random.PRNGKey(3), (2, 8, cfg.dim), cfg.dtype)
    out, _ = M.moe_ffn(layer0, cfg, x)

    wg, wu, wd = (layer0[k][0] for k in ("w_gate", "w_up", "w_down"))
    expected = (jax.nn.silu(x @ wg) * (x @ wu)) @ wd
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(expected, np.float32),
        rtol=2e-2, atol=2e-2,
    )


def test_moe_train_step_expert_parallel(ep_mesh):
    cfg = M.MOE_CONFIGS["tiny-moe"]
    plan = MeshPlan(ep_mesh)
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    init_state, step, shard_state = M.make_moe_train_step(cfg, plan)
    state = shard_state(init_state(params))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab_size)
    losses = []
    for _ in range(3):
        state, loss = step(state, tokens)
        losses.append(float(loss))
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    # Expert weights really live sharded over ep.
    sharding = state["params"]["layers"]["w_gate"].sharding
    assert "ep" in sharding.spec


def test_moe_ep_sharded_matches_unsharded(ep_mesh):
    """EP must be a performance choice, not a numerics choice."""
    cfg = M.MOE_CONFIGS["tiny-moe"]
    params = M.init_params(cfg, jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
    logits_ref, _ = M.forward(params, cfg, tokens)

    plan = MeshPlan(ep_mesh)
    sharded = M.shard_moe_params(plan, params)
    logits_ep, _ = M.forward(sharded, cfg, tokens)
    np.testing.assert_allclose(
        np.asarray(logits_ep), np.asarray(logits_ref), rtol=2e-2, atol=2e-2
    )
