"""Tracing: webhook spans with an in-memory exporter, plus the PR-10
end-to-end request path — W3C traceparent across a REAL gateway→replica
hop, TTFT decomposition into queue_wait + prefill + first_decode spans,
ring-buffer eviction bounds, deterministic sampling, and flight-recorder
stall detection under a fake clock.

Reference analog: opentelemetry_test.go:26-50 installs an in-memory
exporter + real provider; specs assert root-span attributes and the
maybeRestartRunningNotebook child span.
"""

from __future__ import annotations

import hashlib
import json
import time
import urllib.request

import pytest

from kubeflow_tpu.observability.flight import FlightRecorder
from kubeflow_tpu.observability.tracing import (
    InMemoryExporter,
    RingBufferExporter,
    TracerProvider,
    deterministic_sample,
    format_traceparent,
    get_tracer,
    parse_traceparent,
    set_tracer_provider,
)

from tests.harness import make_env, tpu_notebook


@pytest.fixture
def exporter():
    exp = InMemoryExporter()
    set_tracer_provider(TracerProvider(exp))
    yield exp
    set_tracer_provider(TracerProvider())  # restore no-op global


def test_noop_provider_records_nothing():
    tracer = get_tracer("t")
    with tracer.start_span("s", a=1) as span:
        span.set_attribute("b", 2)
        span.add_event("e")
    # No exporter installed: nothing observable, and no error.


def test_span_records_attributes_events_and_errors(exporter):
    tracer = get_tracer("t")
    with pytest.raises(ValueError):
        with tracer.start_span("outer", kind="test") as span:
            span.add_event("evt", {"k": "v"})
            raise ValueError("boom")
    (span,) = exporter.by_name("outer")
    assert span.attributes == {"kind": "test"}
    assert span.events == [{"name": "evt", "attributes": {"k": "v"}}]
    assert span.status == "ERROR"
    assert "boom" in span.status_message


def test_nested_spans_have_parents(exporter):
    tracer = get_tracer("t")
    with tracer.start_span("root") as root:
        with tracer.start_span("child"):
            pass
    child = exporter.by_name("child")[0]
    assert child.parent is root
    assert exporter.by_name("root")[0].parent is None


def test_webhook_emits_root_span_per_admission(exporter):
    env = make_env(webhooks=True)
    env.cluster.create(tpu_notebook(name="nb1"))
    spans = exporter.by_name("mutate-notebook")
    assert len(spans) == 1
    assert spans[0].attributes["notebook"] == "nb1"
    assert spans[0].attributes["namespace"] == "ns"
    assert spans[0].attributes["operation"] == "CREATE"


def test_webhook_update_emits_child_span(exporter):
    env = make_env(webhooks=True)
    env.cluster.create(tpu_notebook(name="nb1"))
    env.manager.run_until_idle()
    exporter.reset()
    nb = env.cluster.get("Notebook", "nb1", "ns")
    nb["metadata"]["labels"] = {"touched": "true"}
    env.cluster.update(nb)
    root = exporter.by_name("mutate-notebook")
    assert root and root[0].attributes["operation"] == "UPDATE"
    child = exporter.by_name("maybe-restart-running-notebook")
    assert child and child[0].parent is root[0]


def test_webhook_records_imagestream_not_found_event(exporter):
    env = make_env(webhooks=True)
    nb = tpu_notebook(name="nb1")
    nb["metadata"]["annotations"] = {
        "notebooks.opendatahub.io/last-image-selection": "missing-stream:2026a"
    }
    env.cluster.create(nb)
    (span,) = exporter.by_name("mutate-notebook")
    assert any(e["name"] == "imagestream-not-found" for e in span.events)


class TestTraceparent:
    def test_round_trip(self, exporter):
        with get_tracer("t").start_span("parent") as span:
            header = format_traceparent(span)
        tid, pid, sampled = parse_traceparent(header)
        assert (tid, pid, sampled) == (span.trace_id, span.span_id, True)

    @pytest.mark.parametrize(
        "header",
        [
            None,
            "",
            "garbage",
            "00-zz-zz-01",
            "00-" + "0" * 32 + "-" + "ab" * 8 + "-01",  # all-zero trace id
            "00-" + "ab" * 16 + "-" + "0" * 16 + "-01",  # all-zero span id
        ],
    )
    def test_malformed_headers_are_dropped(self, header):
        assert parse_traceparent(header) is None

    def test_remote_parent_continues_the_trace(self, exporter):
        tid, pid = "ab" * 16, "cd" * 8
        with get_tracer("t").start_span(
            "local", traceparent=f"00-{tid}-{pid}-01"
        ) as span:
            pass
        assert span.trace_id == tid
        assert span.parent_id == pid
        assert exporter.by_name("local")  # sampled flag honored

    def test_unsampled_remote_parent_propagates_without_recording(
        self, exporter
    ):
        """flags=00 means some upstream hop decided not to sample: this
        hop must agree (no export) but keep the ids flowing."""
        tid, pid = "12" * 16, "34" * 8
        span = get_tracer("t").start_span(
            "local", traceparent=f"00-{tid}-{pid}-00"
        )
        assert span.trace_id == tid
        header = format_traceparent(span)
        assert header.endswith("-00")
        span.end()
        assert not exporter.by_name("local")


class TestSampling:
    @staticmethod
    def _ids(n):
        return [hashlib.sha256(str(i).encode()).hexdigest()[:32]
                for i in range(n)]

    def test_decision_is_deterministic_per_trace_id(self):
        for tid in self._ids(64):
            first = deterministic_sample(tid, 0.3)
            assert all(
                deterministic_sample(tid, 0.3) == first for _ in range(5)
            )

    def test_rate_extremes(self):
        for tid in self._ids(16):
            assert deterministic_sample(tid, 1.0)
            assert not deterministic_sample(tid, 0.0)

    def test_decision_is_monotonic_in_rate(self):
        """A trace sampled at rate r stays sampled at any higher rate —
        components configured with different rates still nest correctly."""
        for tid in self._ids(64):
            sampled_at = [
                r for r in (0.1, 0.3, 0.5, 0.9)
                if deterministic_sample(tid, r)
            ]
            assert sampled_at == sorted(sampled_at)
            if sampled_at:
                assert deterministic_sample(tid, 1.0)

    def test_observed_rate_tracks_configured_rate(self):
        ids = self._ids(2000)
        hit = sum(deterministic_sample(t, 0.25) for t in ids)
        assert 0.15 < hit / len(ids) < 0.35

    def test_unsampled_local_root_still_carries_a_trace_id(self):
        exp = InMemoryExporter()
        set_tracer_provider(TracerProvider(exp, sample_rate=0.0))
        try:
            span = get_tracer("t").start_span("root")
            assert span.trace_id  # X-Request-Id correlation survives
            span.end()
            assert not exp.spans
        finally:
            set_tracer_provider(TracerProvider())


class TestRingBuffer:
    def test_eviction_is_oldest_first_and_bounded(self):
        ring = RingBufferExporter(capacity=8)
        set_tracer_provider(TracerProvider(ring))
        try:
            for i in range(50):
                with get_tracer("t").start_span(f"s{i}"):
                    pass
            assert len(ring) == 8
            assert [s["name"] for s in ring.snapshot()] == [
                f"s{i}" for i in range(42, 50)
            ]
        finally:
            set_tracer_provider(TracerProvider())

    def test_capacity_floor_is_one(self):
        ring = RingBufferExporter(capacity=0)
        assert ring.capacity == 1


class TestFlightRecorder:
    def test_stall_detected_against_rolling_median(self):
        now = [100.0]
        fr = FlightRecorder(
            window=32, stall_factor=8.0, min_samples=4,
            clock=lambda: now[0],
        )
        for _ in range(10):
            assert not fr.record_step(0.01, fill=0.5)
        now[0] = 123.0
        assert fr.record_step(0.5)  # 50x the 10ms median
        snap = fr.snapshot()
        assert snap["stalls"] == 1
        assert snap["last_stall"]["at"] == 123.0
        assert snap["last_stall"]["factor"] == pytest.approx(50.0)
        assert snap["step_s"]["max"] == pytest.approx(0.5)
        assert snap["fill"]["mean"] == pytest.approx(0.5)

    def test_min_samples_guard_spares_compile_steps(self):
        """The first (compile-dominated) steps never flag, and a warm-up
        window full of slow steps doesn't flag the fast steps after it."""
        fr = FlightRecorder(min_samples=4, clock=lambda: 0.0)
        assert not fr.record_step(30.0)  # jit compile, empty window
        assert not fr.record_step(10.0)
        assert not fr.record_step(0.01)
        assert fr.snapshot()["stalls"] == 0

    def test_window_is_bounded(self):
        fr = FlightRecorder(window=16, clock=lambda: 0.0)
        for _ in range(100):
            fr.record_step(0.01)
        snap = fr.snapshot()
        assert snap["steps"] == 100
        assert snap["window"] == 16


def _wait_for(fn, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = fn()
        if out:
            return out
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {fn}")


class TestEndToEndFleet:
    """A real request through a 2-replica fleet yields ONE trace covering
    gateway routing, queue wait, prefill, and first decode — and the span
    sum reconstructs TTFT (ISSUE-10 acceptance: within 10%)."""

    def test_one_trace_spans_gateway_to_engine(self, exporter):
        import jax

        from kubeflow_tpu.models import llama as L
        from kubeflow_tpu.models.continuous import ContinuousBatcher
        from kubeflow_tpu.models.gateway import ServingGateway
        from kubeflow_tpu.models.serving import GenerationConfig
        from kubeflow_tpu.models.server import InferenceServer

        cfg = L.LLAMA_CONFIGS["tiny"]
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        servers = [
            InferenceServer(
                ContinuousBatcher(
                    params, cfg,
                    gen=GenerationConfig(max_new_tokens=4, eos_id=-1),
                    slots=2, cache_len=128, prompt_bucket=16,
                ),
                port=0,
            ).start()
            for _ in range(2)
        ]
        gw = ServingGateway(
            [f"{s.host}:{s.port}" for s in servers], port=0,
            block_size=16, health_interval_s=0.2,
        ).start()
        try:
            req = urllib.request.Request(
                f"http://{gw.host}:{gw.port}/v1/completions",
                data=json.dumps(
                    {"prompt": [3, 4, 5, 6, 7], "max_tokens": 4}
                ).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=120) as resp:
                body = json.loads(resp.read())
                req_id = resp.headers["X-Request-Id"]
            assert body["choices"][0]["tokens"]

            # The root span ends just after the response body is written;
            # poll until the whole chain has been exported.
            _wait_for(lambda: exporter.by_name("first_decode"))
            (gw_root,) = exporter.by_name("gateway.request")
            (route,) = exporter.by_name("gateway.route")
            (srv_root,) = exporter.by_name("server.request")
            (queue,) = exporter.by_name("queue_wait")
            (prefill,) = exporter.by_name("prefill")
            (first_decode,) = exporter.by_name("first_decode")

            # One trace, correctly parented across the HTTP hop.
            chain = [gw_root, route, srv_root, queue, prefill, first_decode]
            assert {s.trace_id for s in chain} == {gw_root.trace_id}
            assert route.parent_id == gw_root.span_id
            assert srv_root.parent_id == route.span_id  # via traceparent
            assert queue.parent_id == srv_root.span_id
            assert prefill.parent_id == srv_root.span_id
            assert req_id == gw_root.trace_id  # client-visible correlation
            assert route.attributes["endpoint"] in {
                f"{s.host}:{s.port}" for s in servers
            }

            # TTFT decomposition: the three phase spans sum to the
            # submit→first-token wall clock the server measured.
            (evt,) = [
                e for e in srv_root.events if e["name"] == "first_token"
            ]
            ttft = evt["attributes"]["ttft_s"]
            span_sum = (
                queue.duration_s
                + prefill.duration_s
                + first_decode.duration_s
            )
            assert span_sum == pytest.approx(ttft, rel=0.10, abs=0.005)
        finally:
            gw.stop()
            for s in servers:
                s.stop()

    def test_client_traceparent_is_continued_by_the_gateway(self, exporter):
        """A caller that already carries a trace context keeps it: the
        gateway's root span joins the caller's trace instead of minting a
        fresh id. Fake replica — only the gateway hop is under test."""
        from tests.test_gateway import _fleet, _teardown

        gw, replicas = _fleet(2)
        tid, pid = "ab" * 16, "cd" * 8
        try:
            req = urllib.request.Request(
                f"http://{gw.host}:{gw.port}/v1/completions",
                data=json.dumps(
                    {"prompt": [1, 2, 3], "max_tokens": 2}
                ).encode(),
                headers={
                    "Content-Type": "application/json",
                    "traceparent": f"00-{tid}-{pid}-01",
                },
            )
            with urllib.request.urlopen(req, timeout=30) as resp:
                resp.read()
                assert resp.headers["X-Request-Id"] == tid
            _wait_for(lambda: exporter.by_name("gateway.request"))
            (gw_root,) = exporter.by_name("gateway.request")
            assert gw_root.trace_id == tid
            assert gw_root.parent_id == pid
        finally:
            _teardown(gw, replicas)


class TestProfiling:
    def test_trace_produces_artifacts(self, tmp_path):
        import jax.numpy as jnp

        from kubeflow_tpu.observability.profiling import trace

        with trace(tmp_path, "t1") as path:
            (jnp.ones((64, 64)) @ jnp.ones((64, 64))).block_until_ready()
        produced = list(path.rglob("*"))
        assert any(p.is_file() for p in produced), produced

    def test_timed_steps_counts_and_progresses(self):
        import jax
        import jax.numpy as jnp

        from kubeflow_tpu.observability.profiling import timed_steps

        @jax.jit
        def step(state, batch):
            new = state + batch.sum()
            return new, new

        state, times = timed_steps(
            step, jnp.zeros(()), [jnp.ones((4,))] * 5
        )
        assert len(times) == 5
        assert float(state) == 20.0
        assert all(t >= 0 for t in times)
