"""Tracing: webhook spans with an in-memory exporter.

Reference analog: opentelemetry_test.go:26-50 installs an in-memory
exporter + real provider; specs assert root-span attributes and the
maybeRestartRunningNotebook child span.
"""

from __future__ import annotations

import pytest

from kubeflow_tpu.observability.tracing import (
    InMemoryExporter,
    TracerProvider,
    get_tracer,
    set_tracer_provider,
)

from tests.harness import make_env, tpu_notebook


@pytest.fixture
def exporter():
    exp = InMemoryExporter()
    set_tracer_provider(TracerProvider(exp))
    yield exp
    set_tracer_provider(TracerProvider())  # restore no-op global


def test_noop_provider_records_nothing():
    tracer = get_tracer("t")
    with tracer.start_span("s", a=1) as span:
        span.set_attribute("b", 2)
        span.add_event("e")
    # No exporter installed: nothing observable, and no error.


def test_span_records_attributes_events_and_errors(exporter):
    tracer = get_tracer("t")
    with pytest.raises(ValueError):
        with tracer.start_span("outer", kind="test") as span:
            span.add_event("evt", {"k": "v"})
            raise ValueError("boom")
    (span,) = exporter.by_name("outer")
    assert span.attributes == {"kind": "test"}
    assert span.events == [{"name": "evt", "attributes": {"k": "v"}}]
    assert span.status == "ERROR"
    assert "boom" in span.status_message


def test_nested_spans_have_parents(exporter):
    tracer = get_tracer("t")
    with tracer.start_span("root") as root:
        with tracer.start_span("child"):
            pass
    child = exporter.by_name("child")[0]
    assert child.parent is root
    assert exporter.by_name("root")[0].parent is None


def test_webhook_emits_root_span_per_admission(exporter):
    env = make_env(webhooks=True)
    env.cluster.create(tpu_notebook(name="nb1"))
    spans = exporter.by_name("mutate-notebook")
    assert len(spans) == 1
    assert spans[0].attributes["notebook"] == "nb1"
    assert spans[0].attributes["namespace"] == "ns"
    assert spans[0].attributes["operation"] == "CREATE"


def test_webhook_update_emits_child_span(exporter):
    env = make_env(webhooks=True)
    env.cluster.create(tpu_notebook(name="nb1"))
    env.manager.run_until_idle()
    exporter.reset()
    nb = env.cluster.get("Notebook", "nb1", "ns")
    nb["metadata"]["labels"] = {"touched": "true"}
    env.cluster.update(nb)
    root = exporter.by_name("mutate-notebook")
    assert root and root[0].attributes["operation"] == "UPDATE"
    child = exporter.by_name("maybe-restart-running-notebook")
    assert child and child[0].parent is root[0]


def test_webhook_records_imagestream_not_found_event(exporter):
    env = make_env(webhooks=True)
    nb = tpu_notebook(name="nb1")
    nb["metadata"]["annotations"] = {
        "notebooks.opendatahub.io/last-image-selection": "missing-stream:2026a"
    }
    env.cluster.create(nb)
    (span,) = exporter.by_name("mutate-notebook")
    assert any(e["name"] == "imagestream-not-found" for e in span.events)


class TestProfiling:
    def test_trace_produces_artifacts(self, tmp_path):
        import jax.numpy as jnp

        from kubeflow_tpu.observability.profiling import trace

        with trace(tmp_path, "t1") as path:
            (jnp.ones((64, 64)) @ jnp.ones((64, 64))).block_until_ready()
        produced = list(path.rglob("*"))
        assert any(p.is_file() for p in produced), produced

    def test_timed_steps_counts_and_progresses(self):
        import jax
        import jax.numpy as jnp

        from kubeflow_tpu.observability.profiling import timed_steps

        @jax.jit
        def step(state, batch):
            new = state + batch.sum()
            return new, new

        state, times = timed_steps(
            step, jnp.zeros(()), [jnp.ones((4,))] * 5
        )
        assert len(times) == 5
        assert float(state) == 20.0
        assert all(t >= 0 for t in times)
