"""Unit tests for TPU slice-topology math (SURVEY.md §7 step 1/2 matrices)."""

import pytest

from kubeflow_tpu.tpu import topology as T


class TestParse:
    def test_2d(self):
        assert T.parse_topology("4x4") == (4, 4)

    def test_3d(self):
        assert T.parse_topology("2x2x4") == (2, 2, 4)

    @pytest.mark.parametrize("bad", ["", "4x", "x4", "axb", "0x4", "-1x2"])
    def test_malformed(self, bad):
        with pytest.raises(T.InvalidTopologyError):
            T.parse_topology(bad)


class TestResolveAccelerator:
    @pytest.mark.parametrize(
        "alias,canonical",
        [
            ("v5e", "v5e"),
            ("V5E", "v5e"),
            ("v5litepod", "v5e"),
            ("tpu-v5-lite-podslice", "v5e"),
            ("trillium", "v6e"),
            ("v5p", "v5p"),
            ("v4", "v4"),
        ],
    )
    def test_aliases(self, alias, canonical):
        assert T.resolve_accelerator(alias).name == canonical

    def test_unknown(self):
        with pytest.raises(T.InvalidTopologyError):
            T.resolve_accelerator("h100")


# The BASELINE.json evaluation matrix and a few more, as a spec-gen table:
# (accelerator, topology, chips, hosts, chips_per_host, type_name)
SLICE_MATRIX = [
    ("v5e", "1x1", 1, 1, 1, "v5litepod-1"),
    ("v5e", "2x2", 4, 1, 4, "v5litepod-4"),
    ("v5e", "2x4", 8, 1, 8, "v5litepod-8"),  # fits one 8-chip host
    ("v5e", "4x4", 16, 4, 4, "v5litepod-16"),  # the north-star config
    ("v5e", "4x8", 32, 8, 4, "v5litepod-32"),
    ("v5e", "8x8", 64, 16, 4, "v5litepod-64"),
    ("v5e", "16x16", 256, 64, 4, "v5litepod-256"),
    ("v5p", "2x2x1", 4, 1, 4, "v5p-8"),
    ("v5p", "2x2x2", 8, 2, 4, "v5p-16"),
    ("v5p", "2x2x4", 16, 4, 4, "v5p-32"),  # BASELINE config 5
    ("v5p", "4x4x4", 64, 16, 4, "v5p-128"),
    ("v4", "2x2x1", 4, 1, 4, "v4-8"),
    ("v4", "2x2x4", 16, 4, 4, "v4-32"),
    ("v6e", "2x2", 4, 1, 4, "v6e-4"),
    ("v6e", "4x4", 16, 4, 4, "v6e-16"),
]


@pytest.mark.parametrize("acc,topo,chips,hosts,cph,tname", SLICE_MATRIX)
def test_slice_matrix(acc, topo, chips, hosts, cph, tname):
    st = T.slice_from_spec(acc, topo)
    assert st.chips == chips
    assert st.hosts == hosts
    assert st.chips_per_host == cph
    assert st.accelerator_type == tname
    assert st.hosts * st.chips_per_host == st.chips


class TestValidation:
    def test_wrong_dimensionality(self):
        with pytest.raises(T.InvalidTopologyError):
            T.slice_from_spec("v5e", "2x2x2")  # v5e is 2-D
        with pytest.raises(T.InvalidTopologyError):
            T.slice_from_spec("v5p", "4x4")  # v5p is 3-D

    def test_untileable(self):
        # 3x4 = 12 chips > 8 single-host max, but 3 doesn't tile into 2x2 hosts
        with pytest.raises(T.InvalidTopologyError):
            T.slice_from_spec("v5e", "3x4")


class TestSchedulingMetadata:
    def test_node_selector(self):
        st = T.slice_from_spec("v5e", "4x4")
        assert st.node_selector() == {
            "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
            "cloud.google.com/gke-tpu-topology": "4x4",
        }

    def test_bounds_multihost_v5e(self):
        st = T.slice_from_spec("v5e", "4x4")
        assert st.host_shape() == (2, 2)
        assert st.host_bounds() == (2, 2)
        assert st.chip_bounds_str() == "2,2,1"
        assert st.host_bounds_str() == "2,2,1"

    def test_bounds_v5p(self):
        st = T.slice_from_spec("v5p", "2x2x4")
        assert st.chip_bounds_str() == "2,2,1"
        assert st.host_bounds_str() == "1,1,4"

    def test_bounds_single_host(self):
        st = T.slice_from_spec("v5e", "2x4")
        assert st.chip_bounds_str() == "2,4,1"
        assert st.host_bounds_str() == "1,1,1"


class TestWorkerHostnames:
    def test_ordering_and_fqdn(self):
        st = T.slice_from_spec("v5e", "4x4")
        names = st.worker_hostnames("nb", "nb-hosts", "user-ns")
        assert len(names) == 4
        assert names[0] == "nb-0.nb-hosts.user-ns.svc.cluster.local"
        assert names[3] == "nb-3.nb-hosts.user-ns.svc.cluster.local"

    def test_single_host(self):
        st = T.slice_from_spec("v5e", "2x2")
        assert len(st.worker_hostnames("nb", "nb-hosts", "ns")) == 1
