"""Pallas paged-attention decode kernel (ops/paged_attention.py).

Numerical agreement with the gathered-view reference path is the whole
contract: the kernel replaces ``pool[tables]`` materialization in the
paged serving engine, so any masking/ordering divergence is a serving
correctness bug, not a perf detail. CPU runs the kernel in interpret
mode (slow but exact).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models.llama import LLAMA_CONFIGS, _gqa_decode_attention
from kubeflow_tpu.ops.paged_attention import paged_decode_attention


def _setup(b=3, hq=8, hkv=4, d=128, bs=16, maxb=6, nb=32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 4)
    q = jax.random.normal(ks[0], (b, hq, d), jnp.bfloat16)
    kp = jax.random.normal(ks[1], (nb, hkv, bs, d), jnp.bfloat16)
    vp = jax.random.normal(ks[2], (nb, hkv, bs, d), jnp.bfloat16)
    tables = jax.random.permutation(ks[3], nb)[: b * maxb].reshape(
        b, maxb
    ).astype(jnp.int32)
    return q, kp, vp, tables


def _reference(q, kp, vp, tables, kv_mask, seq_lens, bs):
    b, maxb = tables.shape
    hkv, d = kp.shape[1], kp.shape[3]
    g = kp[tables].transpose(0, 2, 1, 3, 4).reshape(b, hkv, maxb * bs, d)
    gv = vp[tables].transpose(0, 2, 1, 3, 4).reshape(b, hkv, maxb * bs, d)
    return _gqa_decode_attention(
        q[:, :, None, :], g, gv, seq_lens - 1, kv_mask=kv_mask,
        per_batch=True,
    )[:, :, 0, :]


def _assert_close(out, ref):
    err = float(jnp.max(jnp.abs(
        out.astype(jnp.float32) - ref.astype(jnp.float32)
    )))
    assert err < 2e-2, f"kernel diverges from gathered path: {err}"


class TestKernelVsGathered:
    def test_varied_lengths_and_partial_tail_blocks(self):
        q, kp, vp, tables = _setup()
        seq_lens = jnp.array([17, 40, 96], jnp.int32)  # partial tails
        kv_mask = jnp.arange(6 * 16)[None, :] < seq_lens[:, None]
        out = paged_decode_attention(
            q, kp, vp, tables, kv_mask, seq_lens, 16, interpret=True
        )
        _assert_close(out, _reference(q, kp, vp, tables, kv_mask, seq_lens, 16))

    def test_all_true_mask_rows_rely_on_positional_bound(self):
        """The batcher may mark a whole kv_mask row True and lean on the
        gathered path's `k_pos <= position` causal bound — the kernel
        must apply the same bound, not just the stored mask."""
        q, kp, vp, tables = _setup(seed=1)
        seq_lens = jnp.array([1, 33, 96], jnp.int32)  # incl. 1-token slot
        kv_mask = jnp.ones((3, 6 * 16), bool)
        out = paged_decode_attention(
            q, kp, vp, tables, kv_mask, seq_lens, 16, interpret=True
        )
        _assert_close(out, _reference(q, kp, vp, tables, kv_mask, seq_lens, 16))

    def test_mask_holes_and_whole_masked_blocks(self):
        """Holes inside the valid range (and a fully-masked block, which
        must not NaN the online softmax) match the gathered path."""
        q, kp, vp, tables = _setup(seed=2)
        seq_lens = jnp.array([60, 60, 60], jnp.int32)
        kv_mask = jnp.arange(6 * 16)[None, :] < seq_lens[:, None]
        kv_mask = kv_mask.at[0, 5:9].set(False)
        kv_mask = kv_mask.at[1, 16:32].set(False)  # block 1 fully masked
        out = paged_decode_attention(
            q, kp, vp, tables, kv_mask, seq_lens, 16, interpret=True
        )
        _assert_close(out, _reference(q, kp, vp, tables, kv_mask, seq_lens, 16))

    def test_gqa_grouping(self):
        """Hq > Hkv: each kv head serves its G query rows unrepeated."""
        q, kp, vp, tables = _setup(hq=8, hkv=2, seed=3)
        seq_lens = jnp.array([30, 50, 90], jnp.int32)
        kv_mask = jnp.arange(6 * 16)[None, :] < seq_lens[:, None]
        out = paged_decode_attention(
            q, kp, vp, tables, kv_mask, seq_lens, 16, interpret=True
        )
        _assert_close(out, _reference(q, kp, vp, tables, kv_mask, seq_lens, 16))

    def test_shape_validation(self):
        q, kp, vp, tables = _setup()
        seq_lens = jnp.array([4, 4, 4], jnp.int32)
        kv_mask = jnp.ones((3, 96), bool)
        with pytest.raises(ValueError, match="block size"):
            paged_decode_attention(q, kp, vp, tables, kv_mask, seq_lens, 8,
                                   interpret=True)
        with pytest.raises(ValueError, match="divisible"):
            paged_decode_attention(q[:, :5], kp, vp, tables, kv_mask,
                                   seq_lens, 16, interpret=True)
        # a mask built for a different table layout must be a shape
        # error, not silently-truncated wrong attention
        with pytest.raises(ValueError, match="kv_mask"):
            paged_decode_attention(q, kp, vp, tables,
                                   jnp.ones((3, 2 * 96), bool),
                                   seq_lens, 16, interpret=True)


class TestBatcherIntegration:
    def test_kernel_batcher_matches_gathered_batcher(self):
        """End to end: PagedBatcher(attn_kernel=True) must produce the
        same greedy tokens as the gathered-path batcher."""
        from kubeflow_tpu.models import llama as L
        from kubeflow_tpu.models.paged import PagedBatcher
        from kubeflow_tpu.models.serving import GenerationConfig

        cfg = LLAMA_CONFIGS["tiny"]
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        gen = GenerationConfig(max_new_tokens=8)
        prompts = [[1, 2, 3, 4, 5], [7, 8, 9], [11, 12, 13, 14]]

        def serve(attn_kernel):
            pb = PagedBatcher(params, cfg, gen=gen, slots=2, num_blocks=32,
                              block_size=16, attn_kernel=attn_kernel)
            rids = [pb.submit(p) for p in prompts]
            outs = pb.run()
            return [outs[r] for r in rids]

        ref = serve(False)
        got = serve(True)
        assert got == ref

    def test_kernel_rejects_plan_int8_window(self):
        """Explicit attn_kernel=True with an unsupported composition must
        raise, never silently run the gathered path while reporting the
        kernel is on."""
        import dataclasses

        from kubeflow_tpu.models import llama as L
        from kubeflow_tpu.models.paged import PagedBatcher
        from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh

        cfg = LLAMA_CONFIGS["tiny"]
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        plan = MeshPlan(make_mesh(tp=2, dp=4))
        with pytest.raises(ValueError, match="attn_kernel"):
            PagedBatcher(params, cfg, plan=plan, attn_kernel=True)
        with pytest.raises(ValueError, match="kv_bits"):
            PagedBatcher(params, cfg, kv_bits=8, attn_kernel=True)
        wcfg = dataclasses.replace(cfg, sliding_window=8)
        with pytest.raises(ValueError, match="sliding-window"):
            PagedBatcher(params, wcfg, attn_kernel=True)

    def test_auto_default_off_on_cpu(self):
        from kubeflow_tpu.models import llama as L
        from kubeflow_tpu.models.paged import PagedBatcher

        cfg = LLAMA_CONFIGS["tiny"]
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        pb = PagedBatcher(params, cfg)
        assert pb.attn_kernel is False  # tests force the CPU backend


class TestDenseKernel:
    def test_matches_xla_decode_attention(self):
        from kubeflow_tpu.ops.paged_attention import dense_decode_attention

        ks = jax.random.split(jax.random.PRNGKey(4), 3)
        b, hq, hkv, d, c = 3, 8, 4, 128, 256
        q = jax.random.normal(ks[0], (b, hq, d), jnp.bfloat16)
        kc = jax.random.normal(ks[1], (b, hkv, c, d), jnp.bfloat16)
        vc = jax.random.normal(ks[2], (b, hkv, c, d), jnp.bfloat16)
        seq_lens = jnp.array([1, 100, 256], jnp.int32)
        kv_mask = jnp.ones((b, c), bool).at[1, 10:20].set(False)
        out = dense_decode_attention(q, kc, vc, kv_mask, seq_lens, 64,
                                     interpret=True)
        ref = _gqa_decode_attention(
            q[:, :, None, :], kc, vc, seq_lens - 1, kv_mask=kv_mask,
            per_batch=True,
        )[:, :, 0, :]
        _assert_close(out, ref)

    def test_continuous_batcher_kernel_token_parity(self):
        """ContinuousBatcher(attn_kernel=True) must emit the same greedy
        tokens as the XLA-attention batcher."""
        from kubeflow_tpu.models import llama as L
        from kubeflow_tpu.models.continuous import ContinuousBatcher
        from kubeflow_tpu.models.serving import GenerationConfig

        cfg = LLAMA_CONFIGS["tiny"]
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        gen = GenerationConfig(max_new_tokens=8)
        prompts = [[1, 2, 3, 4, 5], [7, 8, 9], [11, 12, 13, 14]]

        def serve(attn_kernel):
            cb = ContinuousBatcher(params, cfg, gen=gen, slots=2,
                                   cache_len=128, prompt_bucket=16,
                                   attn_kernel=attn_kernel)
            rids = [cb.submit(p) for p in prompts]
            outs = cb.run()
            return [outs[r] for r in rids]

        assert serve(True) == serve(False)

    def test_continuous_rejections_and_auto_off(self):
        import dataclasses

        from kubeflow_tpu.models import llama as L
        from kubeflow_tpu.models.continuous import ContinuousBatcher
        from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh

        cfg = LLAMA_CONFIGS["tiny"]
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="kv_bits"):
            ContinuousBatcher(params, cfg, kv_bits=8, attn_kernel=True)
        with pytest.raises(ValueError, match="plan"):
            ContinuousBatcher(params, cfg, attn_kernel=True,
                              plan=MeshPlan(make_mesh(tp=2, dp=4)))
        wcfg = dataclasses.replace(cfg, sliding_window=8)
        with pytest.raises(ValueError, match="sliding-window"):
            ContinuousBatcher(params, wcfg, attn_kernel=True)
        # explicit True with an indivisible cache_len raises, never a
        # silent XLA fallback
        with pytest.raises(ValueError, match="divisible"):
            ContinuousBatcher(params, cfg, cache_len=1000, attn_kernel=True)
        assert ContinuousBatcher(params, cfg)._attn_kernel == 0  # CPU
