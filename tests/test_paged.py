"""Paged KV cache: block-table decode parity, allocator reuse, preemption.

The paged batcher must stay on the same greedy path as the dense serving
stack — only the storage changed — while completing workloads whose total
KV demand exceeds what fixed-slot allocation could hold.
"""

from __future__ import annotations

import jax
import numpy as np
import pytest

from kubeflow_tpu.models import llama as L
from kubeflow_tpu.models.paged import PagedBatcher
from kubeflow_tpu.models.serving import GenerationConfig, batch_generate

from tests.test_continuous import _assert_greedy_consistent, _prompts


@pytest.fixture(scope="module")
def tiny():
    cfg = L.LLAMA_CONFIGS["tiny"]
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


class TestPagedBatcher:
    def test_single_request_matches_fused_batch_path(self, tiny):
        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=8, eos_id=-1)
        prompt = [5, 9, 17, 33]
        ref = batch_generate(params, cfg, [prompt], gen=gen, pad_to=16)[0]
        pb = PagedBatcher(params, cfg, gen=gen, slots=1, num_blocks=16,
                          block_size=8, prompt_bucket=16)
        rid = pb.submit(prompt)
        assert pb.run()[rid] == [int(t) for t in ref]

    def test_mixed_lengths_stay_on_greedy_path(self, tiny):
        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=6, eos_id=-1)
        pb = PagedBatcher(params, cfg, gen=gen, slots=3, num_blocks=24,
                          block_size=8, prompt_bucket=16)
        prompts = _prompts(cfg, 5)
        rids = [pb.submit(p) for p in prompts]
        results = pb.run()
        assert set(results) == set(rids)
        for rid, prompt in zip(rids, prompts):
            assert len(results[rid]) == 6
            _assert_greedy_consistent(params, cfg, prompt, results[rid])

    def test_blocks_return_to_pool(self, tiny):
        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=4, eos_id=-1)
        pb = PagedBatcher(params, cfg, gen=gen, slots=2, num_blocks=16,
                          block_size=8, prompt_bucket=16)
        assert pb.free_blocks == 15  # block 0 reserved as the null block
        for p in _prompts(cfg, 4):
            pb.submit(p)
        pb.run()
        assert pb.free_blocks == 15  # everything released

    def test_pool_smaller_than_slots_worst_case_still_completes(self, tiny):
        """The paged advantage: 3 slots would need 3*(16+8)=72 token rows
        dense; a 5-usable-block pool (40 rows) still completes every
        request via allocation order + preemption."""
        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=8, eos_id=-1)
        pb = PagedBatcher(params, cfg, gen=gen, slots=3, num_blocks=6,
                          block_size=8, prompt_bucket=16)
        prompts = _prompts(cfg, 4, key=11)
        rids = [pb.submit(p) for p in prompts]
        results = pb.run()
        assert set(results) == set(rids)
        for rid, prompt in zip(rids, prompts):
            assert len(results[rid]) == 8
            _assert_greedy_consistent(params, cfg, prompt, results[rid])

    def test_preempted_request_resumes_on_greedy_path(self, tiny):
        """Force preemption (pool fits ~1.5 requests' full span) and check
        the evicted request's final tokens equal the unconstrained run."""
        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=8, eos_id=-1)
        prompts = [[3 + i, 40 + i, 90 + i, 7] for i in range(2)]

        roomy = PagedBatcher(params, cfg, gen=gen, slots=2, num_blocks=16,
                             block_size=8, prompt_bucket=16)
        rids = [roomy.submit(p) for p in prompts]
        want = roomy.run()

        tight = PagedBatcher(params, cfg, gen=gen, slots=2, num_blocks=5,
                             block_size=8, prompt_bucket=16)
        rids2 = [tight.submit(p) for p in prompts]
        got = tight.run()
        for ra, rb in zip(rids, rids2):
            assert want[ra] == got[rb]

    def test_early_eos_frees_blocks(self, tiny):
        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=32, eos_id=-1)
        pb = PagedBatcher(params, cfg, gen=gen, slots=1, num_blocks=8,
                          block_size=8, prompt_bucket=16)
        # Discover the first emitted token, then rerun treating it as EOS:
        # the request retires immediately and releases its blocks.
        rid = pb.submit([5, 9, 17])
        first = pb.run()[rid][0]
        gen2 = GenerationConfig(max_new_tokens=32, eos_id=first)
        pb2 = PagedBatcher(params, cfg, gen=gen2, slots=1, num_blocks=8,
                           block_size=8, prompt_bucket=16)
        rid2 = pb2.submit([5, 9, 17])
        out = pb2.run()
        assert out[rid2] == []
        assert pb2.free_blocks == 7

    def test_admission_never_thrashes_prefills(self, tiny, monkeypatch):
        """Admission must WAIT for retirements, not preempt running
        requests: evict-to-admit degenerates into preempt → full
        re-prefill → one decode step → preempt again, O(max_new_tokens)
        prefills per request under pressure. Bound: one initial prefill
        per request plus at most one resume per decode-path preemption —
        far below the thrash regime (~max_new_tokens × requests)."""
        from kubeflow_tpu.models import paged as paged_mod

        cfg, params = tiny
        real_admit = paged_mod._paged_admit
        calls = {"n": 0}

        def counting_admit(*a, **k):
            calls["n"] += 1
            return real_admit(*a, **k)

        monkeypatch.setattr(paged_mod, "_paged_admit", counting_admit)
        gen = GenerationConfig(max_new_tokens=8, eos_id=-1)
        # Tight pool: 5 usable blocks, 4 requests of 2-3 blocks each, so
        # the queue is never empty while slots run.
        pb = PagedBatcher(params, cfg, gen=gen, slots=3, num_blocks=6,
                          block_size=8, prompt_bucket=16)
        prompts = _prompts(cfg, 4, key=23)
        rids = [pb.submit(p) for p in prompts]
        results = pb.run()
        assert set(results) == set(rids)
        # 4 initial prefills + decode-path preemption resumes; the thrash
        # regime would be ~4 × 8 = 32.
        assert calls["n"] <= 8, f"{calls['n']} prefills for 4 requests"

    def test_pool_too_small_raises(self, tiny):
        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=8, eos_id=-1)
        pb = PagedBatcher(params, cfg, gen=gen, slots=1, num_blocks=2,
                          block_size=8, prompt_bucket=16)
        pb.submit([1, 2, 3])
        with pytest.raises(RuntimeError, match="pool"):
            pb.run()


class TestShardedPaged:
    def test_tp_sharded_matches_single_device(self, tiny):
        from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh

        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=6, eos_id=-1)
        prompts = _prompts(cfg, 3, key=41)

        def run(plan=None):
            pb = PagedBatcher(params, cfg, gen=gen, slots=2, num_blocks=16,
                              block_size=8, prompt_bucket=16, plan=plan)
            rids = [pb.submit(p) for p in prompts]
            out = pb.run()
            return [out[r] for r in rids]

        want = run()
        plan = MeshPlan(make_mesh(tp=2, devices=jax.devices()[:2]))
        assert want == run(plan=plan)

    def test_sp_mesh_rejected(self, tiny):
        from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh

        cfg, params = tiny
        plan = MeshPlan(make_mesh(tp=1, sp=2, devices=jax.devices()[:2]))
        with pytest.raises(ValueError, match="sp"):
            PagedBatcher(params, cfg, plan=plan)


class TestPromptCache:
    """Identical-prompt block sharing (prompt_cache=True): same padded
    prompt → shared prompt blocks + cached last-position logits; decode
    only ever writes past the bucket boundary, so shared blocks are
    never mutated."""

    def _pb(self, params, cfg, num_blocks=32, max_new=6, slots=2, **kw):
        gen = GenerationConfig(max_new_tokens=max_new, eos_id=-1)
        return PagedBatcher(params, cfg, gen=gen, slots=slots,
                            num_blocks=num_blocks, block_size=8,
                            prompt_bucket=16, prompt_cache=True, **kw)

    def test_identical_prompts_share_blocks_and_tokens(self, tiny):
        cfg, params = tiny
        prompt = [5, 9, 17, 33]
        # Baseline without cache.
        gen = GenerationConfig(max_new_tokens=6, eos_id=-1)
        base = PagedBatcher(params, cfg, gen=gen, slots=2, num_blocks=32,
                            block_size=8, prompt_bucket=16)
        r0 = base.submit(prompt)
        want = base.run()[r0]

        pb = self._pb(params, cfg)
        rids = [pb.submit(prompt) for _ in range(4)]
        out = pb.run()
        for r in rids:
            assert out[r] == want  # byte-identical greedy streams
        # The cache retains the prompt's 2 blocks; everything else freed.
        assert pb.free_blocks == 31 - 2
        # One cached entry whose blocks are held only by the cache now.
        (entry,) = pb._prompt_cache.values()
        assert all(pb._shared_refs[b] == 1 for b in entry["blocks"])

    def test_hit_skips_prefill(self, tiny):
        cfg, params = tiny
        pb = self._pb(params, cfg, slots=1)
        calls = {"n": 0}
        import kubeflow_tpu.models.paged as paged_mod

        real_admit = paged_mod._paged_admit

        def counting_admit(*a, **kw):
            calls["n"] += 1
            return real_admit(*a, **kw)

        paged_mod._paged_admit = counting_admit
        try:
            r1 = pb.submit([5, 9, 17])
            r2 = pb.submit([5, 9, 17])
            out = pb.run()
        finally:
            paged_mod._paged_admit = real_admit
        assert calls["n"] == 1  # second admission reused the blocks
        assert out[r1] == out[r2]

    def test_eviction_under_pressure(self, tiny):
        """Cached prompts yield their blocks before admission stalls or
        preemption fires; distinct prompts keep completing."""
        cfg, params = tiny
        pb = self._pb(params, cfg, num_blocks=10, max_new=6, slots=1)
        prompts = [[3 + i, 41, 90] for i in range(4)]  # all distinct
        rids = [pb.submit(p) for p in prompts]
        out = pb.run()
        assert all(len(out[r]) == 6 for r in rids)

    def test_shared_blocks_survive_user_release(self, tiny):
        """A request finishing decrefs shared blocks but the cache's own
        ref keeps them resident for the next hit; a hit AFTER the first
        user finished still reuses them and still matches."""
        cfg, params = tiny
        prompt = [7, 3, 11, 2]
        pb = self._pb(params, cfg, slots=1)
        r1 = pb.submit(prompt)
        first = pb.run()[r1]
        r2 = pb.submit(prompt)
        second = pb.run()[r2]
        assert first == second

    def test_pad_id_leading_token_does_not_collide(self, tiny):
        """A prompt whose LEADING token equals pad_id left-pads to the
        same bytes as the shorter prompt without it — but their validity
        masks (and so attention and logits) differ. The cache key must
        separate them; each must match its own uncached stream."""
        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=6, eos_id=-1)
        pad = gen.pad_id
        with_lead = [pad, 5, 9]
        without = [5, 9]

        def uncached(prompt):
            pb = PagedBatcher(params, cfg, gen=gen, slots=1, num_blocks=32,
                              block_size=8, prompt_bucket=16)
            r = pb.submit(prompt)
            return pb.run()[r]

        want_a, want_b = uncached(with_lead), uncached(without)
        pb = self._pb(params, cfg, slots=1)
        ra1 = pb.submit(with_lead)
        rb1 = pb.submit(without)
        ra2 = pb.submit(with_lead)
        rb2 = pb.submit(without)
        out = pb.run()
        assert out[ra1] == want_a and out[ra2] == want_a
        assert out[rb1] == want_b and out[rb2] == want_b
        assert len(pb._prompt_cache) == 2  # distinct entries, no collision

    def test_continuations_bypass_cache(self, tiny):
        """Preempted continuations carry generated tokens — request-
        unique, never cached or matched; the starved-pool recovery path
        stays correct with the cache on."""
        cfg, params = tiny
        pb = self._pb(params, cfg, num_blocks=10, max_new=8, slots=2)
        rids = [pb.submit([3 + i, 41, 90, 7]) for i in range(3)]
        out = pb.run()
        assert all(len(out[r]) == 8 for r in rids)

    def test_prompt_cache_over_int8_pool(self, tiny):
        """Cache hits reuse QUANTIZED blocks (values + scale leaves ride
        the same tables); hit streams match the miss stream exactly."""
        cfg, params = tiny
        pb = self._pb(params, cfg, slots=2, kv_bits=8)
        prompt = [5, 9, 17, 33]
        r1, r2, r3 = pb.submit(prompt), pb.submit(prompt), pb.submit(prompt)
        out = pb.run()
        assert out[r1] == out[r2] == out[r3]
        assert len(pb._prompt_cache) == 1


class TestPrefixCache:
    """Prefix-granular sharing (prefix_cache=True): position-0-anchored
    admission makes a common prefix occupy identical blocks at identical
    logical positions regardless of total prompt length, so full prompt
    blocks are shared block-by-block via content-addressed chain hashes
    and only the unmatched tail is prefilled (through the tables)."""

    def _pb(self, params, cfg, num_blocks=32, max_new=6, slots=2,
            prompt_bucket=16, **kw):
        gen = GenerationConfig(max_new_tokens=max_new, eos_id=-1)
        return PagedBatcher(params, cfg, gen=gen, slots=slots,
                            num_blocks=num_blocks, block_size=8,
                            prompt_bucket=prompt_bucket, prefix_cache=True,
                            **kw)

    def test_mutually_exclusive_with_prompt_cache(self, tiny):
        cfg, params = tiny
        with pytest.raises(ValueError, match="mutually exclusive"):
            PagedBatcher(params, cfg, slots=1, num_blocks=16, block_size=8,
                         prompt_bucket=16, prompt_cache=True,
                         prefix_cache=True)

    def test_anchored_layout_stays_on_greedy_path(self, tiny):
        """Anchoring alone (no cache interplay: disjoint prompts) must
        preserve outputs — token i at position i is exactly the layout
        of the unpadded reference forward."""
        cfg, params = tiny
        pb = self._pb(params, cfg, slots=3)
        prompts = _prompts(cfg, 5)
        rids = [pb.submit(p) for p in prompts]
        results = pb.run()
        for rid, prompt in zip(rids, prompts):
            assert len(results[rid]) == 6
            _assert_greedy_consistent(params, cfg, prompt, results[rid])

    def test_common_prefix_shares_blocks_across_lengths(self, tiny):
        """THE case prompt_cache cannot serve: same 8-token prefix,
        different tails AND different total lengths. The second admission
        must match one full block and prefill only its tail."""
        cfg, params = tiny
        import kubeflow_tpu.models.paged as paged_mod

        prefix = [5, 9, 17, 33, 41, 2, 77, 13]  # exactly one block (BS=8)
        a = prefix + [3, 8]           # 10 tokens
        b = prefix + [60, 4, 29, 7, 90]  # 13 tokens
        widths = []
        real = paged_mod._paged_prefix_admit

        def recording(params_, cfg_, chunk, *rest, **kw):
            widths.append(int(chunk.shape[1]))
            return real(params_, cfg_, chunk, *rest, **kw)

        paged_mod._paged_prefix_admit = recording
        try:
            pb = self._pb(params, cfg, slots=1)
            ra, rb = pb.submit(a), pb.submit(b)
            out = pb.run()
        finally:
            paged_mod._paged_prefix_admit = real
        # a: no match -> 2 blocks (16); b: prefix block matched -> only
        # the 5-token tail's block (8).
        assert widths == [16, 8]
        _assert_greedy_consistent(params, cfg, a, out[ra])
        _assert_greedy_consistent(params, cfg, b, out[rb])

    def test_chain_hash_rejects_same_block_different_prefix(self, tiny):
        """Block 1's TOKENS matching is not enough — its chain (block 0)
        differs, so nothing may be shared (KV depends on all prior
        positions through attention)."""
        cfg, params = tiny
        import kubeflow_tpu.models.paged as paged_mod

        common_second = [7, 7, 7, 7, 6, 6, 6, 6]
        a = [1] * 8 + common_second + [5]
        b = [2] * 8 + common_second + [5]
        widths = []
        real = paged_mod._paged_prefix_admit

        def recording(params_, cfg_, chunk, *rest, **kw):
            widths.append(int(chunk.shape[1]))
            return real(params_, cfg_, chunk, *rest, **kw)

        paged_mod._paged_prefix_admit = recording
        try:
            pb = self._pb(params, cfg, slots=1, num_blocks=32,
                          prompt_bucket=24)
            ra, rb = pb.submit(a), pb.submit(b)
            out = pb.run()
        finally:
            paged_mod._paged_prefix_admit = real
        assert widths == [24, 24]  # full prefill both times: zero match
        _assert_greedy_consistent(params, cfg, a, out[ra])
        _assert_greedy_consistent(params, cfg, b, out[rb])

    def test_identical_prompts_share_all_full_blocks(self, tiny):
        """prefix_cache subsumes the identical-prompt case: every full
        block short of the last token's is matched; outputs identical."""
        cfg, params = tiny
        prompt = [5, 9, 17, 33, 41, 2, 77, 13, 8, 1, 22, 4, 19, 3, 55, 6,
                  31]  # 17 tokens: 2 registrable blocks + 1-token tail
        pb = self._pb(params, cfg, slots=1, prompt_bucket=24)
        r1 = pb.submit(prompt)
        first = pb.run()[r1]
        assert len(pb._prefix_entries) == 2
        r2 = pb.submit(prompt)
        second = pb.run()[r2]
        assert first == second
        assert len(pb._prefix_entries) == 2  # matched, not re-registered

    def test_cache_survives_user_release_and_refcounts(self, tiny):
        cfg, params = tiny
        prompt = [5, 9, 17, 33, 41, 2, 77, 13] + [3, 8]
        pb = self._pb(params, cfg, slots=2)
        r1 = pb.submit(prompt)
        pb.run()
        (entry,) = pb._prefix_entries.values()
        # Only the cache's own ref remains after the user retired.
        assert pb._shared_refs[entry["block"]] == 1
        # Registered block held by the cache; tail blocks back in _free.
        assert pb.free_blocks == 31 - 1

    def test_eviction_is_leaf_first(self, tiny):
        """A chain's middle link must never be evicted while its child
        is cached (the tail would be unmatchable garbage)."""
        cfg, params = tiny
        prompt = list(range(3, 3 + 24)) + [2]  # 25 tokens: 3 registrable
        pb = self._pb(params, cfg, slots=1, num_blocks=32,
                      prompt_bucket=32)
        pb.submit(prompt)
        pb.run()
        assert len(pb._prefix_entries) == 3
        by_block = {e["block"]: e for e in pb._prefix_entries.values()}
        assert pb._evict_prefix_leaf()
        remaining = list(pb._prefix_entries.values())
        assert len(remaining) == 2
        # The evicted one was the chain's LEAF: both survivors still have
        # a consistent children count and the root is intact.
        assert [e["children"] for e in remaining] == [1, 0]
        assert all(e["block"] in by_block for e in remaining)

    def test_preempted_continuation_rehits_prefix(self, tiny):
        """Under pool pressure the preempted request's prompt blocks stay
        cached (refcounted), so its re-admission matches them instead of
        re-prefilling the whole effective prompt; everyone completes on
        the greedy path."""
        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=8, eos_id=-1)
        pb = PagedBatcher(params, cfg, gen=gen, slots=3, num_blocks=10,
                          block_size=8, prompt_bucket=16, prefix_cache=True)
        prompts = _prompts(cfg, 4, key=11)
        rids = [pb.submit(p) for p in prompts]
        results = pb.run()
        assert set(results) == set(rids)
        for rid, prompt in zip(rids, prompts):
            assert len(results[rid]) == 8
            _assert_greedy_consistent(params, cfg, prompt, results[rid])

    def test_prefix_cache_over_int8_pool(self, tiny):
        """Shared prefix blocks are QUANTIZED blocks (scale leaves ride
        the same tables); hit and miss streams agree."""
        cfg, params = tiny
        prefix = [5, 9, 17, 33, 41, 2, 77, 13]
        a, b = prefix + [3, 8], prefix + [60, 4, 29]
        pb = self._pb(params, cfg, slots=1, kv_bits=8)
        ra, rb = pb.submit(a), pb.submit(b)
        out = pb.run()
        base = self._pb(params, cfg, slots=1, kv_bits=8)
        rb2 = base.submit(b)
        assert out[rb] == base.run()[rb2]  # hit stream == miss stream
        assert len(out[ra]) == 6

    def test_long_prompt_admits_in_fixed_pieces(self, tiny):
        """A tail longer than admit_chunk prefills in fixed-width pieces
        (compile- and memory-bounded, the paged analog of
        prefill_chunked) and stays on the greedy path; a prefix hit
        shortens the piece walk to the remainder."""
        cfg, params = tiny
        import kubeflow_tpu.models.paged as paged_mod

        prompt = [int(t) % 200 + 3 for t in range(40)]  # 5 blocks (BS=8)
        longer = prompt[:32] + [9, 9, 9]  # shares 4 full blocks
        widths = []
        real = paged_mod._paged_prefix_admit

        def recording(params_, cfg_, chunk, *rest, **kw):
            widths.append(int(chunk.shape[1]))
            return real(params_, cfg_, chunk, *rest, **kw)

        paged_mod._paged_prefix_admit = recording
        try:
            pb = self._pb(params, cfg, slots=1, num_blocks=32,
                          prompt_bucket=48, admit_chunk=16)
            r1 = pb.submit(prompt)
            out1 = pb.run()[r1]
            r2 = pb.submit(longer)
            out2 = pb.run()[r2]
        finally:
            paged_mod._paged_prefix_admit = real
        assert widths[:3] == [16, 16, 8]  # 40-token miss: 16+16+8
        assert widths[3:] == [8]  # 4 blocks matched; only the remainder
        _assert_greedy_consistent(params, cfg, prompt, out1)
        _assert_greedy_consistent(params, cfg, longer, out2)

    def test_bad_admit_chunk_rejected(self, tiny):
        cfg, params = tiny
        with pytest.raises(ValueError, match="admit_chunk"):
            self._pb(params, cfg, admit_chunk=12)  # not a block multiple

    def test_admit_chunk_default_valid_for_any_block_size(self, tiny):
        """The default admit_chunk rounds itself to a block multiple, so
        configs whose block_size does not divide 256 still construct —
        with and without the prefix cache."""
        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=4, eos_id=-1)
        for prefix in (False, True):
            pb = PagedBatcher(params, cfg, gen=gen, slots=1, num_blocks=6,
                              block_size=96, prompt_bucket=96,
                              prefix_cache=prefix)
            assert pb.admit_chunk % 96 == 0


class TestHostSwap:
    """Host-RAM block swap (swap_bytes > 0): demoted prefix leaves keep
    their KV in host numpy keyed by the same chain hash, so a returning
    chain restores its prefix instead of re-prefilling. The tier is
    byte-budgeted with LRU demotion and refuses mismatched chains."""

    PROMPT = [5, 9, 17, 33, 41, 2, 77, 13] + [3, 8]  # 1 registrable block

    def _pb(self, params, cfg, swap_bytes=1 << 22, num_blocks=16,
            max_new=6, prompt_bucket=16, **kw):
        gen = GenerationConfig(max_new_tokens=max_new, eos_id=-1)
        return PagedBatcher(params, cfg, gen=gen, slots=1,
                            num_blocks=num_blocks, block_size=8,
                            prompt_bucket=prompt_bucket, prefix_cache=True,
                            swap_bytes=swap_bytes, **kw)

    @staticmethod
    def _block_leaves(pb, blk):
        return {n: np.asarray(leaf[:, blk]) for n, leaf in pb.pool.items()}

    def test_negative_budget_rejected(self, tiny):
        cfg, params = tiny
        with pytest.raises(ValueError, match="swap_bytes"):
            self._pb(params, cfg, swap_bytes=-1)

    def test_demote_restore_byte_exact(self, tiny):
        """Evicting a leaf with a swap tier parks its block's leaves in
        host RAM; the returning chain promotes them back bit-identical
        and the admission counts a prefix HIT (no re-prefill)."""
        cfg, params = tiny
        pb = self._pb(params, cfg)
        r1 = pb.submit(self.PROMPT)
        first = pb.run()[r1]
        ((key, ent),) = pb._prefix_entries.items()
        before = self._block_leaves(pb, ent["block"])
        hits0 = pb.prefix_hits
        assert pb._evict_prefix_leaf()
        assert pb.swap_contains(key)
        assert pb.swap_blocks == 1 and pb.kv_swap_out == 1
        assert pb.swap_bytes_used == sum(a.nbytes for a in before.values())
        assert not pb._prefix_entries
        r2 = pb.submit(self.PROMPT)
        second = pb.run()[r2]
        assert second == first  # restored chain stays on the greedy path
        assert pb.kv_swap_in == 1
        assert pb.kv_swap_restored_tokens == pb.block_size
        assert pb.prefix_hits > hits0  # promotion IS a prefix hit
        assert not pb.swap_contains(key) and pb.swap_bytes_used == 0
        ((key2, ent2),) = pb._prefix_entries.items()
        assert key2 == key
        after = self._block_leaves(pb, ent2["block"])
        for name in before:
            np.testing.assert_array_equal(before[name], after[name])

    def test_restored_chain_decode_matches_never_evicted(self, tiny):
        """Control: an engine that never evicted serves the same prompt —
        the swap-restored decode must be token-exact against it."""
        cfg, params = tiny
        pb = self._pb(params, cfg)
        r1 = pb.submit(self.PROMPT)
        pb.run()
        ((key, _),) = pb._prefix_entries.items()
        assert pb._evict_prefix_leaf() and pb.swap_contains(key)
        r2 = pb.submit(self.PROMPT)
        restored = pb.run()[r2]
        control_pb = self._pb(params, cfg)
        rc = control_pb.submit(self.PROMPT)
        control_pb.run()
        rc2 = control_pb.submit(self.PROMPT)  # warm-cache decode, no evict
        assert restored == control_pb.run()[rc2]
        assert control_pb.kv_swap_in == 0
        del rc

    def test_lru_order_under_byte_budget(self, tiny):
        """Three leaves demoted into a two-block budget: the FIRST
        demoted entry is the LRU victim; the later two survive."""
        cfg, params = tiny
        probe = self._pb(params, cfg)
        block_bytes = sum(
            a.nbytes for a in self._block_leaves(probe, 0).values()
        )
        pb = self._pb(params, cfg, swap_bytes=2 * block_bytes,
                      num_blocks=32, prompt_bucket=32)
        prompt = list(range(3, 3 + 24)) + [2]  # 3 registrable blocks
        pb.submit(prompt)
        pb.run()
        assert len(pb._prefix_entries) == 3
        demoted = []
        for _ in range(3):  # leaf-first: deepest chain key demotes first
            keys = set(pb._prefix_entries)
            assert pb._evict_prefix_leaf()
            demoted.extend(keys - set(pb._prefix_entries))
        assert pb.kv_swap_out == 3
        assert not pb.swap_contains(demoted[0])  # oldest popped (LRU)
        assert pb.swap_contains(demoted[1]) and pb.swap_contains(demoted[2])
        assert pb.swap_bytes_used == 2 * block_bytes <= pb.swap_bytes_limit

    def test_single_block_over_budget_is_plain_eviction(self, tiny):
        cfg, params = tiny
        probe = self._pb(params, cfg)
        block_bytes = sum(
            a.nbytes for a in self._block_leaves(probe, 0).values()
        )
        pb = self._pb(params, cfg, swap_bytes=block_bytes - 1)
        pb.submit(self.PROMPT)
        pb.run()
        assert pb._evict_prefix_leaf()
        assert pb.swap_blocks == 0 and pb.kv_swap_out == 0
        assert pb.swap_bytes_used == 0

    def test_mismatched_chain_refused(self, tiny):
        """A swap entry only restores onto the chain it was demoted
        from: a different parent key is a miss and the entry stays."""
        cfg, params = tiny
        pb = self._pb(params, cfg)
        pb.submit(self.PROMPT)
        pb.run()
        ((key, _),) = pb._prefix_entries.items()
        assert pb._evict_prefix_leaf()
        assert pb._swap_promote(key, b"not-the-parent") is None
        assert pb._swap_promote(b"unknown-key", None) is None
        assert pb.swap_contains(key)  # refusal must not consume the entry
        assert pb.kv_swap_in == 0

    def test_different_first_block_does_not_promote(self, tiny):
        """Walk-level refusal: same second-block TOKENS under a different
        first block hash to a different chain — the swap entry must not
        leak KV across chains."""
        cfg, params = tiny
        common_second = [7, 7, 7, 7, 6, 6, 6, 6]
        a = [1] * 8 + common_second + [5]
        b = [2] * 8 + common_second + [5]
        pb = self._pb(params, cfg, num_blocks=32, prompt_bucket=24)
        pb.submit(a)
        pb.run()
        while pb._evict_prefix_leaf():
            pass
        assert pb.swap_blocks == 2
        rb = pb.submit(b)
        out = pb.run()[rb]
        assert pb.kv_swap_in == 0  # nothing matched b's chain
        _assert_greedy_consistent(params, cfg, b, out)

    @pytest.mark.slow  # extra int8-engine compile; heavy for tier-1's wall budget
    def test_swap_over_int8_pool_round_trips(self, tiny):
        """Quantized pools swap all four leaves (values + scales);
        restore is byte-exact and the hit stream matches the miss
        stream."""
        cfg, params = tiny
        pb = self._pb(params, cfg, kv_bits=8)
        r1 = pb.submit(self.PROMPT)
        first = pb.run()[r1]
        ((key, ent),) = pb._prefix_entries.items()
        before = self._block_leaves(pb, ent["block"])
        assert set(before) == {"k", "v", "k_scale", "v_scale"}
        assert pb._evict_prefix_leaf() and pb.swap_contains(key)
        r2 = pb.submit(self.PROMPT)
        assert pb.run()[r2] == first
        ((_, ent2),) = pb._prefix_entries.items()
        after = self._block_leaves(pb, ent2["block"])
        for name in before:
            np.testing.assert_array_equal(before[name], after[name])
