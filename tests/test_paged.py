"""Paged KV cache: block-table decode parity, allocator reuse, preemption.

The paged batcher must stay on the same greedy path as the dense serving
stack — only the storage changed — while completing workloads whose total
KV demand exceeds what fixed-slot allocation could hold.
"""

from __future__ import annotations

import jax
import pytest

from kubeflow_tpu.models import llama as L
from kubeflow_tpu.models.paged import PagedBatcher
from kubeflow_tpu.models.serving import GenerationConfig, batch_generate

from tests.test_continuous import _assert_greedy_consistent, _prompts


@pytest.fixture(scope="module")
def tiny():
    cfg = L.LLAMA_CONFIGS["tiny"]
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


class TestPagedBatcher:
    def test_single_request_matches_fused_batch_path(self, tiny):
        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=8, eos_id=-1)
        prompt = [5, 9, 17, 33]
        ref = batch_generate(params, cfg, [prompt], gen=gen, pad_to=16)[0]
        pb = PagedBatcher(params, cfg, gen=gen, slots=1, num_blocks=16,
                          block_size=8, prompt_bucket=16)
        rid = pb.submit(prompt)
        assert pb.run()[rid] == [int(t) for t in ref]

    def test_mixed_lengths_stay_on_greedy_path(self, tiny):
        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=6, eos_id=-1)
        pb = PagedBatcher(params, cfg, gen=gen, slots=3, num_blocks=24,
                          block_size=8, prompt_bucket=16)
        prompts = _prompts(cfg, 5)
        rids = [pb.submit(p) for p in prompts]
        results = pb.run()
        assert set(results) == set(rids)
        for rid, prompt in zip(rids, prompts):
            assert len(results[rid]) == 6
            _assert_greedy_consistent(params, cfg, prompt, results[rid])

    def test_blocks_return_to_pool(self, tiny):
        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=4, eos_id=-1)
        pb = PagedBatcher(params, cfg, gen=gen, slots=2, num_blocks=16,
                          block_size=8, prompt_bucket=16)
        assert pb.free_blocks == 15  # block 0 reserved as the null block
        for p in _prompts(cfg, 4):
            pb.submit(p)
        pb.run()
        assert pb.free_blocks == 15  # everything released

    def test_pool_smaller_than_slots_worst_case_still_completes(self, tiny):
        """The paged advantage: 3 slots would need 3*(16+8)=72 token rows
        dense; a 5-usable-block pool (40 rows) still completes every
        request via allocation order + preemption."""
        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=8, eos_id=-1)
        pb = PagedBatcher(params, cfg, gen=gen, slots=3, num_blocks=6,
                          block_size=8, prompt_bucket=16)
        prompts = _prompts(cfg, 4, key=11)
        rids = [pb.submit(p) for p in prompts]
        results = pb.run()
        assert set(results) == set(rids)
        for rid, prompt in zip(rids, prompts):
            assert len(results[rid]) == 8
            _assert_greedy_consistent(params, cfg, prompt, results[rid])

    def test_preempted_request_resumes_on_greedy_path(self, tiny):
        """Force preemption (pool fits ~1.5 requests' full span) and check
        the evicted request's final tokens equal the unconstrained run."""
        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=8, eos_id=-1)
        prompts = [[3 + i, 40 + i, 90 + i, 7] for i in range(2)]

        roomy = PagedBatcher(params, cfg, gen=gen, slots=2, num_blocks=16,
                             block_size=8, prompt_bucket=16)
        rids = [roomy.submit(p) for p in prompts]
        want = roomy.run()

        tight = PagedBatcher(params, cfg, gen=gen, slots=2, num_blocks=5,
                             block_size=8, prompt_bucket=16)
        rids2 = [tight.submit(p) for p in prompts]
        got = tight.run()
        for ra, rb in zip(rids, rids2):
            assert want[ra] == got[rb]

    def test_early_eos_frees_blocks(self, tiny):
        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=32, eos_id=-1)
        pb = PagedBatcher(params, cfg, gen=gen, slots=1, num_blocks=8,
                          block_size=8, prompt_bucket=16)
        # Discover the first emitted token, then rerun treating it as EOS:
        # the request retires immediately and releases its blocks.
        rid = pb.submit([5, 9, 17])
        first = pb.run()[rid][0]
        gen2 = GenerationConfig(max_new_tokens=32, eos_id=first)
        pb2 = PagedBatcher(params, cfg, gen=gen2, slots=1, num_blocks=8,
                           block_size=8, prompt_bucket=16)
        rid2 = pb2.submit([5, 9, 17])
        out = pb2.run()
        assert out[rid2] == []
        assert pb2.free_blocks == 7

    def test_admission_never_thrashes_prefills(self, tiny, monkeypatch):
        """Admission must WAIT for retirements, not preempt running
        requests: evict-to-admit degenerates into preempt → full
        re-prefill → one decode step → preempt again, O(max_new_tokens)
        prefills per request under pressure. Bound: one initial prefill
        per request plus at most one resume per decode-path preemption —
        far below the thrash regime (~max_new_tokens × requests)."""
        from kubeflow_tpu.models import paged as paged_mod

        cfg, params = tiny
        real_admit = paged_mod._paged_admit
        calls = {"n": 0}

        def counting_admit(*a, **k):
            calls["n"] += 1
            return real_admit(*a, **k)

        monkeypatch.setattr(paged_mod, "_paged_admit", counting_admit)
        gen = GenerationConfig(max_new_tokens=8, eos_id=-1)
        # Tight pool: 5 usable blocks, 4 requests of 2-3 blocks each, so
        # the queue is never empty while slots run.
        pb = PagedBatcher(params, cfg, gen=gen, slots=3, num_blocks=6,
                          block_size=8, prompt_bucket=16)
        prompts = _prompts(cfg, 4, key=23)
        rids = [pb.submit(p) for p in prompts]
        results = pb.run()
        assert set(results) == set(rids)
        # 4 initial prefills + decode-path preemption resumes; the thrash
        # regime would be ~4 × 8 = 32.
        assert calls["n"] <= 8, f"{calls['n']} prefills for 4 requests"

    def test_pool_too_small_raises(self, tiny):
        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=8, eos_id=-1)
        pb = PagedBatcher(params, cfg, gen=gen, slots=1, num_blocks=2,
                          block_size=8, prompt_bucket=16)
        pb.submit([1, 2, 3])
        with pytest.raises(RuntimeError, match="pool"):
            pb.run()


class TestShardedPaged:
    def test_tp_sharded_matches_single_device(self, tiny):
        from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh

        cfg, params = tiny
        gen = GenerationConfig(max_new_tokens=6, eos_id=-1)
        prompts = _prompts(cfg, 3, key=41)

        def run(plan=None):
            pb = PagedBatcher(params, cfg, gen=gen, slots=2, num_blocks=16,
                              block_size=8, prompt_bucket=16, plan=plan)
            rids = [pb.submit(p) for p in prompts]
            out = pb.run()
            return [out[r] for r in rids]

        want = run()
        plan = MeshPlan(make_mesh(tp=2, devices=jax.devices()[:2]))
        assert want == run(plan=plan)

    def test_sp_mesh_rejected(self, tiny):
        from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh

        cfg, params = tiny
        plan = MeshPlan(make_mesh(tp=1, sp=2, devices=jax.devices()[:2]))
        with pytest.raises(ValueError, match="sp"):
            PagedBatcher(params, cfg, plan=plan)
