"""Fleet gateway tests (models/gateway.py): ring stability, drain
without dropping in-flight streams, bounded re-route, tenant-fair shed,
and prefix-affinity beating random routing — all against fake in-process
replicas that speak the InferenceServer HTTP contract (healthz draining,
/stats prefix_cache, 429/503 shed, SSE streams) without the jax stack,
plus one end-to-end pass over real PagedBatcher(prefix_cache=True)
replicas asserting the new observability counters flow gateway-side.
"""

from __future__ import annotations

import hashlib
import json
import threading
import time
import http.client
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from kubeflow_tpu.models import gateway as gw_mod
from kubeflow_tpu.models.gateway import (
    HashRing,
    PrefixRouter,
    ServingGateway,
    WarmSliceReplicaSource,
    chain_key,
    gateway_from_env,
)


class FakeReplica:
    """In-process InferenceServer stand-in: same endpoint shapes, a
    simulated block-pool prefix cache (bounded LRU over chain keys, the
    engine's registrable-blocks semantics), and switchable misbehavior
    (overload 429, draining 503) — so routing policy is testable without
    compiling a model."""

    def __init__(self, *, block_size: int = 4, cache_blocks: int = 10**9,
                 tokens: int = 3, token_delay_s: float = 0.0):
        self.block_size = block_size
        self.cache_blocks = cache_blocks
        self.tokens = tokens
        self.token_delay_s = token_delay_s
        self.mode = "ok"  # ok | overload | draining
        self.lock = threading.Lock()
        self.chains: OrderedDict = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.attempts = 0
        self.served = 0
        replica = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *args):
                pass

            def _json(self, code, payload, retry_after=None):
                body = json.dumps(payload).encode()
                self.send_response(code)
                if retry_after is not None:
                    self.send_header("Retry-After", str(retry_after))
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.send_header("Connection", "close")
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    if replica.mode == "draining":
                        self._json(503, {"status": "draining"})
                    else:
                        self._json(200, {"status": "ok"})
                elif self.path == "/stats":
                    with replica.lock:
                        h, m = replica.hits, replica.misses
                        self._json(200, {
                            "slots": 8, "active_slots": 0, "queued": 0,
                            "served": replica.served,
                            "prefix_cache": {
                                "hits": h, "misses": m,
                                "evictions": replica.evictions,
                                "cached_blocks": len(replica.chains),
                                "hit_ratio": round(h / (h + m), 4)
                                if h + m else 0.0,
                            },
                        })
                else:
                    self._json(404, {"error": "not found"})

            def do_POST(self):
                with replica.lock:
                    replica.attempts += 1
                length = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(length))
                if replica.mode == "overload":
                    self._json(429, {"error": "pending queue is full"},
                               retry_after=1)
                    return
                if replica.mode == "draining":
                    self._json(503, {"error": "server is draining"},
                               retry_after=1)
                    return
                replica._touch_cache(req.get("prompt") or [])
                toks = list(range(replica.tokens))
                if req.get("stream"):
                    self.send_response(200)
                    self.send_header("Content-Type", "text/event-stream")
                    self.send_header("Connection", "close")
                    self.end_headers()
                    for t in toks:
                        if replica.token_delay_s:
                            time.sleep(replica.token_delay_s)
                        self.wfile.write(
                            b"data: " + json.dumps({"token": t}).encode()
                            + b"\n\n"
                        )
                        self.wfile.flush()
                    self.wfile.write(b"data: [DONE]\n\n")
                    self.wfile.flush()
                else:
                    if replica.token_delay_s:
                        time.sleep(replica.token_delay_s * replica.tokens)
                    self._json(200, {
                        "id": "cmpl-0", "object": "text_completion",
                        "choices": [{"index": 0, "tokens": toks,
                                     "finish_reason": "stop"}],
                        "usage": {},
                    })
                with replica.lock:
                    replica.served += 1

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.httpd.daemon_threads = True
        self.host, self.port = self.httpd.server_address[:2]
        self.endpoint = f"{self.host}:{self.port}"
        self.thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )

    def _touch_cache(self, prompt: list) -> None:
        """The engine's admission accounting: walk full blocks (minus the
        last token's block) through the chain hash, count matched blocks
        as hits and the rest as misses, register, LRU-evict past the
        pool's cache capacity."""
        bs = self.block_size
        registrable = max(0, (len(prompt) - 1) // bs)
        parent = None
        keys = []
        for j in range(registrable):
            parent = chain_key(parent, prompt[j * bs:(j + 1) * bs])
            keys.append(parent)
        with self.lock:
            matched = 0
            for k in keys:
                if k not in self.chains:
                    break
                matched += 1
            self.hits += matched
            self.misses += registrable - matched
            for k in keys:
                self.chains[k] = None
                self.chains.move_to_end(k)
            while len(self.chains) > self.cache_blocks:
                self.chains.popitem(last=False)
                self.evictions += 1

    def start(self) -> "FakeReplica":
        self.thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()


def _post(host, port, payload, timeout=30.0):
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    try:
        conn.request("POST", "/v1/completions",
                     json.dumps(payload).encode(),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        return resp.status, json.loads(resp.read())
    finally:
        conn.close()


def _fleet(n, gw_kw=None, **replica_kw):
    replicas = [FakeReplica(**replica_kw).start() for _ in range(n)]
    gw = ServingGateway(
        [r.endpoint for r in replicas], port=0, block_size=4,
        health_interval_s=0.05, **(gw_kw or {}),
    ).start()
    return gw, replicas


def _teardown(gw, replicas):
    gw.stop()
    for r in replicas:
        r.stop()


class TestHashRing:
    def test_minimal_key_movement_on_join_and_exact_restore_on_leave(self):
        ring = HashRing(vnodes=64)
        for node in ("a:1", "b:1", "c:1"):
            ring.add(node)
        keys = [hashlib.sha1(str(i).encode()).digest() for i in range(2000)]
        before = {k: ring.lookup(k) for k in keys}
        ring.add("d:1")
        after = {k: ring.lookup(k) for k in keys}
        moved = sum(before[k] != after[k] for k in keys)
        # Ideal is 1/4 of the space; vnode variance stays well under 40%,
        # while naive mod-N hashing would move ~3/4.
        assert 0 < moved < 0.4 * len(keys)
        # Every key that moved, moved TO the joiner — existing nodes
        # never trade keys among themselves on a join.
        assert all(after[k] == "d:1" for k in keys if before[k] != after[k])
        ring.remove("d:1")
        assert {k: ring.lookup(k) for k in keys} == before

    def test_successors_distinct_and_budget_bounded(self):
        ring = HashRing(vnodes=8)
        for node in ("a:1", "b:1", "c:1"):
            ring.add(node)
        succ = ring.successors(b"key", 2)
        assert len(succ) == 2 and len(set(succ)) == 2
        assert set(ring.successors(b"key", 10)) == {"a:1", "b:1", "c:1"}
        assert ring.successors(b"key", 1)[0] == ring.lookup(b"key")

    def test_seed_decorrelates_fleets(self):
        keys = [hashlib.sha1(str(i).encode()).digest() for i in range(500)]
        maps = []
        for seed in (0, 1):
            ring = HashRing(vnodes=64, seed=seed)
            for node in ("a:1", "b:1", "c:1"):
                ring.add(node)
            maps.append([ring.lookup(k) for k in keys])
        assert maps[0] != maps[1]


class TestPrefixRouter:
    def test_chain_key_parity_with_paged_engine(self):
        from kubeflow_tpu.models.paged import PagedBatcher

        k0 = chain_key(None, [1, 2, 3, 4])
        assert k0 == PagedBatcher._chain_key(None, [1, 2, 3, 4])
        assert chain_key(k0, [5, 6]) == PagedBatcher._chain_key(k0, [5, 6])

    def test_shared_prefix_converges_to_one_key(self):
        router = PrefixRouter(block_size=4)
        shared = list(range(8))
        first = router.route_key(shared + [100, 101, 102, 103])
        second = router.route_key(shared + [200, 201, 202, 203])
        third = router.route_key(shared + [300, 301, 302, 303])
        assert second == third  # all later traffic co-locates
        # and the converged key is the shared prefix's chain key, which
        # differs from an unrelated prompt's.
        assert router.route_key(list(range(50, 62))) not in (first, second)

    def test_sub_block_prompts_still_route_stably(self):
        router = PrefixRouter(block_size=16)
        assert router.route_key([1, 2, 3]) == router.route_key([1, 2, 3])
        assert router.route_key([1, 2, 3]) != router.route_key([4, 5, 6])


class TestRerouteAndDrain:
    def test_503_reroute_bounded_by_budget(self):
        gw, replicas = _fleet(3, gw_kw={"reroute_budget": 1})
        try:
            for r in replicas:
                r.mode = "overload"
            code, body = _post(gw.host, gw.port,
                               {"prompt": [1, 2, 3, 4], "max_tokens": 2})
            assert code == 429
            assert "re-route budget" in body["error"]
            # budget 1 → primary + exactly one alternate, never the fleet.
            assert sum(r.attempts for r in replicas) == 2
            assert gw.stats()["reroutes"] == 1
        finally:
            _teardown(gw, replicas)

    def test_zero_budget_never_reroutes(self):
        gw, replicas = _fleet(2, gw_kw={"reroute_budget": 0})
        try:
            for r in replicas:
                r.mode = "overload"
            code, _ = _post(gw.host, gw.port, {"prompt": [1, 2, 3, 4]})
            assert code == 429
            assert sum(r.attempts for r in replicas) == 1
            assert gw.stats()["reroutes"] == 0
        finally:
            _teardown(gw, replicas)

    def test_reroute_succeeds_on_next_ring_node(self):
        gw, replicas = _fleet(2, gw_kw={"reroute_budget": 2})
        try:
            # Find which replica a fixed prompt routes to, then drain it:
            # the SAME request must land on the alternate with one 200.
            prompt = list(range(12))
            key = gw._route_key(prompt)
            key = gw._route_key(prompt)  # converged (registry warm)
            primary = gw._candidates(key)[0]
            by_ep = {r.endpoint: r for r in replicas}
            by_ep[primary].mode = "draining"
            code, body = _post(gw.host, gw.port, {"prompt": prompt})
            assert code == 200
            assert body["choices"][0]["tokens"] == [0, 1, 2]
            assert gw.stats()["reroutes"] == 1
            assert gw.stats()["failed"] == 0
        finally:
            _teardown(gw, replicas)

    def test_drain_leaves_ring_without_dropping_inflight_stream(self):
        replica_a = FakeReplica(tokens=8, token_delay_s=0.1).start()
        replica_b = FakeReplica().start()
        gw = ServingGateway([replica_a.endpoint], port=0, block_size=4,
                            health_interval_s=0.05).start()
        try:
            lines = []
            done = threading.Event()

            def stream():
                conn = http.client.HTTPConnection(gw.host, gw.port,
                                                  timeout=30)
                conn.request("POST", "/v1/completions",
                             json.dumps({"prompt": [1, 2, 3, 4, 5],
                                         "stream": True}).encode(),
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                while True:
                    line = resp.fp.readline()
                    if not line:
                        break
                    if line.startswith(b"data:"):
                        lines.append(line)
                    if line == b"data: [DONE]\n":
                        break
                conn.close()
                done.set()

            t = threading.Thread(target=stream, daemon=True)
            t.start()
            # Stream underway on A; B joins, then A drains mid-stream.
            while replica_a.attempts == 0:
                time.sleep(0.005)
            gw.add_replica(replica_b.endpoint)
            replica_a.mode = "draining"
            deadline = time.monotonic() + 5
            while (replica_a.endpoint in gw.ring_nodes()
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            assert replica_a.endpoint not in gw.ring_nodes()
            # New work routes around the draining replica...
            code, _ = _post(gw.host, gw.port, {"prompt": [9, 9, 9, 9]})
            assert code == 200
            assert replica_b.served == 1
            # ...while the in-flight stream finishes COMPLETE: drain
            # never drops bytes already committed to a client.
            assert done.wait(timeout=20)
            assert lines[-1] == b"data: [DONE]\n"
            tokens = [json.loads(l[5:]) for l in lines[:-1]]
            assert [d["token"] for d in tokens] == list(range(8))
            assert gw.stats()["failed"] == 0
        finally:
            gw.stop()
            replica_a.stop()
            replica_b.stop()

    def test_dead_replica_leaves_ring_and_healthz_tracks_fleet(self):
        gw, replicas = _fleet(2)
        try:
            replicas[0].stop()
            deadline = time.monotonic() + 5
            while (replicas[0].endpoint in gw.ring_nodes()
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            assert gw.ring_nodes() == frozenset({replicas[1].endpoint})
            conn = http.client.HTTPConnection(gw.host, gw.port, timeout=5)
            conn.request("GET", "/healthz")
            assert conn.getresponse().status == 200
            conn.close()
            replicas[1].stop()
            deadline = time.monotonic() + 5
            while gw.ring_nodes() and time.monotonic() < deadline:
                time.sleep(0.01)
            conn = http.client.HTTPConnection(gw.host, gw.port, timeout=5)
            conn.request("GET", "/healthz")
            assert conn.getresponse().status == 503
            conn.close()
        finally:
            gw.stop()


class TestTenantFairShed:
    def test_heavy_tenant_sheds_light_tenant_admitted(self):
        gw, replicas = _fleet(
            2, gw_kw={"max_inflight": 4}, token_delay_s=0.15, tokens=2,
        )
        try:
            results = []

            def heavy():
                results.append(_post(
                    gw.host, gw.port,
                    {"prompt": [1, 2, 3, 4], "user": "heavy"},
                ))

            threads = [threading.Thread(target=heavy, daemon=True)
                       for _ in range(4)]
            for t in threads:
                t.start()
            deadline = time.monotonic() + 5
            while (gw.stats()["inflight"].get("heavy", 0) < 4
                   and time.monotonic() < deadline):
                time.sleep(0.005)
            assert gw.stats()["inflight"].get("heavy") == 4
            # Fleet saturated: heavy is AT its share (4/1 tenants) → shed;
            # light is under its share (0 < ceil(4/2)) → admitted.
            shed_code, shed_body = _post(
                gw.host, gw.port, {"prompt": [1, 2, 3, 4], "user": "heavy"}
            )
            light_code, _ = _post(
                gw.host, gw.port, {"prompt": [5, 6, 7, 8], "user": "light"}
            )
            for t in threads:
                t.join(timeout=20)
            assert shed_code == 429
            assert "fair share" in shed_body["error"]
            assert light_code == 200
            stats = gw.stats()
            assert stats["shed"] == 1
            assert all(code == 200 for code, _ in results)
        finally:
            _teardown(gw, replicas)


class TestPrefixAffinity:
    @staticmethod
    def _balanced_prefixes(gw, tenants: int, per_replica: int):
        """Pick 3-block tenant prefixes whose steady-state route key
        (the prefix's own chain key) spreads evenly over THIS arm's
        ring.  Replica ports are ephemeral, so the ring layout differs
        per run; balancing the workload against it keeps the affinity
        arm's per-replica working set inside cache capacity, which is
        the scenario the routing policy exists for."""
        chosen, counts, seed = [], {}, 0
        while len(chosen) < tenants and seed < 10_000:
            prefix = [1000 * seed + i for i in range(12)]
            key = None
            for j in range(3):
                key = chain_key(key, prefix[4 * j:4 * j + 4])
            owner = gw._ring.lookup(key)
            if counts.get(owner, 0) < per_replica:
                counts[owner] = counts.get(owner, 0) + 1
                chosen.append(prefix)
            seed += 1
        assert len(chosen) == tenants
        return chosen

    def _drive(self, affinity: str, tenants: int = 6, rounds: int = 8):
        """Same tenant mix against a fresh cold fleet per arm: 6 tenants
        × a 3-block shared system prompt + unique tails, replicas sized
        so each holds 2 tenants' prefixes — affinity keeps each tenant
        pinned where its chain is warm; random thrashes the LRU."""
        gw, replicas = _fleet(
            3, gw_kw={"affinity": affinity}, cache_blocks=8,
        )
        try:
            prefixes = self._balanced_prefixes(gw, tenants, 2)
            n = 0
            for rnd in range(rounds):
                for t in range(tenants):
                    tail = [10_000 + 1000 * t + 4 * rnd + i
                            for i in range(4)]
                    code, _ = _post(
                        gw.host, gw.port,
                        {"prompt": prefixes[t] + tail, "user": f"t{t}"},
                    )
                    assert code == 200
                    n += 1
            gw.probe_once()  # scrape the replicas' counters
            stats = gw.stats()
            assert stats["requests"] == n
            return stats["fleet_prefix_cache"]
        finally:
            _teardown(gw, replicas)

    def test_affinity_hit_rate_beats_random(self):
        affinity = self._drive("prefix")
        random = self._drive("random")
        assert affinity["hits"] + affinity["misses"] > 0
        assert affinity["hit_ratio"] > random["hit_ratio"]
        # The shared 3 blocks of every non-first round should mostly hit
        # under affinity; cold-start misses bound it away from 1.0.
        assert affinity["hit_ratio"] > 0.5


class TestGatewayConfig:
    def test_gateway_from_env_roundtrip(self, monkeypatch):
        from kubeflow_tpu.webhook import tpu_env as te

        monkeypatch.setenv(te.KUBEFLOW_TPU_GATEWAY_PORT, "0")
        monkeypatch.setenv(te.KUBEFLOW_TPU_GATEWAY_REPLICAS,
                           "127.0.0.1:8001, 127.0.0.1:8002")
        monkeypatch.setenv(te.KUBEFLOW_TPU_GATEWAY_AFFINITY, "random")
        monkeypatch.setenv(te.KUBEFLOW_TPU_GATEWAY_HASH_SEED, "7")
        monkeypatch.setenv(te.KUBEFLOW_TPU_GATEWAY_REROUTE_BUDGET, "3")
        gw = gateway_from_env()
        try:
            assert gw.affinity == "random"
            assert gw.reroute_budget == 3
            assert gw._ring.seed == 7
            assert gw.replica_endpoints() == [
                "127.0.0.1:8001", "127.0.0.1:8002"
            ]
        finally:
            gw.stop()

    @pytest.mark.parametrize("name,value", [
        ("KUBEFLOW_TPU_GATEWAY_PORT", "http"),
        ("KUBEFLOW_TPU_GATEWAY_REPLICAS", "nonsense"),
        ("KUBEFLOW_TPU_GATEWAY_AFFINITY", "sticky"),
        ("KUBEFLOW_TPU_GATEWAY_HASH_SEED", "pi"),
        ("KUBEFLOW_TPU_GATEWAY_REROUTE_BUDGET", "-1"),
    ])
    def test_gateway_from_env_rejects_garbage(self, monkeypatch, name, value):
        from kubeflow_tpu.webhook import tpu_env as te

        monkeypatch.setenv(getattr(te, name), value)
        with pytest.raises(ValueError):
            gateway_from_env()

    def test_rejects_bad_modes_and_budgets(self):
        with pytest.raises(ValueError):
            ServingGateway(affinity="sticky")
        with pytest.raises(ValueError):
            ServingGateway(reroute_budget=-1)
        with pytest.raises(ValueError):
            gw_mod._parse_endpoint("no-port")


class TestWarmSliceSource:
    def test_acquire_claims_warm_slice_and_miss_stamps_demand(self):
        from kubeflow_tpu.api.notebook import TPUSpec
        from kubeflow_tpu.api.slicepool import new_slicepool
        from kubeflow_tpu.api import slicepool as sp

        from tests.harness import make_env

        env = make_env()
        env.cluster.create(new_slicepool(
            "pool", "ns", TPUSpec(accelerator="v5e", topology="4x4"),
            warm_replicas=1,
        ))
        env.manager.run_until_idle()
        topo = TPUSpec(accelerator="v5e", topology="4x4").slice_topology()
        source = WarmSliceReplicaSource(env.cluster, "ns", topo)
        assert source.acquire(now=100.0) == "pool"
        # The placeholder was consumed; a second claim misses and stamps
        # the demand annotations the pool autoscaler reads.
        warm = env.cluster.list(
            "StatefulSet", "ns",
            label_selector={sp.STATE_LABEL: sp.STATE_WARM},
        )
        assert warm == []
        assert source.acquire(now=101.0) is None

    def test_gateway_scale_up_delegates_to_source(self):
        class Source:
            def __init__(self):
                self.calls = 0

            def acquire(self, now=None, pools=None):
                self.calls += 1
                return "pool"

        source = Source()
        gw = ServingGateway(replica_source=source)
        try:
            assert gw.scale_up() == "pool"
            assert source.calls == 1
            assert ServingGateway().scale_up() is None
        finally:
            gw.stop()

    def test_migration_pin_lifecycle(self):
        gw = ServingGateway(["a:1", "b:2"])
        try:
            # Unknown endpoints cannot be pinned (a typo must not
            # silently disable scale-down forever).
            assert gw.pin_for_migration("nope:9") is False
            assert gw.pin_for_migration("a:1") is True
            assert gw.pin_for_migration("a:1") is True  # idempotent
            assert gw.migration_pinned() == frozenset({"a:1"})
            gw.unpin_for_migration("a:1")
            gw.unpin_for_migration("a:1")  # no-op twice
            assert gw.migration_pinned() == frozenset()
            # A pinned replica that leaves the fleet self-cleans: the
            # pin set never accumulates dead endpoints.
            assert gw.pin_for_migration("b:2") is True
            gw.remove_replica("b:2")
            assert gw.migration_pinned() == frozenset()
        finally:
            gw.stop()


class TestRealReplicaIntegration:
    def test_prefix_counters_flow_engine_to_stats_to_gateway(self):
        """End-to-end over REAL replicas: two InferenceServers on
        PagedBatcher(prefix_cache=True) tiny models behind the gateway;
        shared-prefix traffic must produce engine-side hits that surface
        in /stats and aggregate in the gateway's routing report."""
        import jax

        from kubeflow_tpu.models import llama as L
        from kubeflow_tpu.models.paged import PagedBatcher
        from kubeflow_tpu.models.server import InferenceServer
        from kubeflow_tpu.models.serving import GenerationConfig

        cfg = L.LLAMA_CONFIGS["tiny"]
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        block_size = 16
        servers = [
            InferenceServer(
                PagedBatcher(
                    params, cfg,
                    gen=GenerationConfig(max_new_tokens=4, eos_id=-1),
                    slots=2, num_blocks=64, block_size=block_size,
                    prompt_bucket=64, prefix_cache=True,
                ),
                port=0, drain_s=0.5,
            ).start()
            for _ in range(2)
        ]
        gw = ServingGateway(
            [f"{s.host}:{s.port}" for s in servers], port=0,
            block_size=block_size, health_interval_s=0.2,
        ).start()
        try:
            shared = list(range(3, 3 + 2 * block_size))  # 2 full blocks
            for tail in ([40, 41, 42], [50, 51, 52], [60, 61, 62]):
                code, body = _post(
                    gw.host, gw.port,
                    {"prompt": shared + tail, "max_tokens": 3},
                    timeout=120,
                )
                assert code == 200
                assert len(body["choices"][0]["tokens"]) >= 1
            hits = sum(s.engine.prefix_hits for s in servers)
            misses = sum(s.engine.prefix_misses for s in servers)
            # Three admissions sharing 2 full blocks: the first is cold,
            # later ones hit the warm chain (affinity pins them to one
            # replica, so the hits land).
            assert hits >= 2
            assert misses >= 2
            gw.probe_once()
            fleet = gw.stats()["fleet_prefix_cache"]
            assert fleet["hits"] == hits
            assert fleet["misses"] == misses
            assert fleet["hit_ratio"] > 0
        finally:
            gw.stop()
            for s in servers:
                s.stop()
