"""Model stack tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import optax
import pytest

from kubeflow_tpu.models import llama as L
from kubeflow_tpu.models.train import causal_lm_loss, make_train_step, shard_state
from kubeflow_tpu.ops.attention import flash_attention
from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh
from kubeflow_tpu.parallel.ring_attention import make_sharded_ring_attention


@pytest.fixture(scope="module")
def tiny():
    cfg = L.LLAMA_CONFIGS["tiny"]
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


class TestLlama:
    def test_forward_shape_and_dtype(self, tiny):
        cfg, params = tiny
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
        logits = L.forward(params, cfg, tokens)
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert logits.dtype == jnp.float32

    def test_causality(self, tiny):
        """Changing a future token must not change past logits."""
        cfg, params = tiny
        tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, cfg.vocab_size)
        logits_a = L.forward(params, cfg, tokens)
        tokens_b = tokens.at[0, -1].set((tokens[0, -1] + 1) % cfg.vocab_size)
        logits_b = L.forward(params, cfg, tokens_b)
        assert jnp.allclose(logits_a[:, :-1], logits_b[:, :-1], atol=1e-5)

    def test_decode_matches_forward(self, tiny):
        """KV-cache decode must reproduce the full forward exactly."""
        cfg, params = tiny
        prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, cfg.vocab_size)
        logits = L.forward(params, cfg, prompt)
        cache = L.init_kv_cache(cfg, 2, 32)
        cache = L.prime_kv_cache(params, cfg, prompt, cache)
        next_tok = jnp.argmax(logits[:, -1], axis=-1)[:, None]
        step_logits, _ = L.decode_step(
            params, cfg, next_tok, cache, jnp.asarray(16, jnp.int32)
        )
        full = jnp.concatenate([prompt, next_tok], axis=1)
        ref = L.forward(params, cfg, full)[:, -1]
        # bf16 activations: the two compiled paths may round differently at
        # the last bit (2^-8 ≈ 0.0039 relative); anything beyond that is a
        # real cache bug.
        assert float(jnp.max(jnp.abs(step_logits - ref))) < 1e-2

    def test_gqa_forward(self):
        cfg = L.LLAMA_CONFIGS["tiny-gqa"]
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, cfg.vocab_size)
        assert L.forward(params, cfg, tokens).shape == (1, 8, cfg.vocab_size)

    def test_greedy_generate(self, tiny):
        cfg, params = tiny
        prompt = jax.random.randint(jax.random.PRNGKey(3), (1, 8), 0, cfg.vocab_size)
        out = L.greedy_generate(params, cfg, prompt, 6)
        assert out.shape == (1, 6)

    def test_7b_param_count(self):
        assert abs(L.LLAMA_CONFIGS["llama-2-7b"].param_count() / 1e9 - 6.74) < 0.05

    def test_chunked_prefill_matches_single_shot(self, tiny):
        """Long-prompt prefill in chunks: same final logits + cache as the
        one-shot pass (the bounded-memory path for prompts whose full
        (B, S, vocab) logits would not fit HBM)."""
        cfg, params = tiny
        prompt = jax.random.randint(
            jax.random.PRNGKey(5), (2, 64), 0, cfg.vocab_size
        )
        ref_logits, ref_cache = L.prefill(
            params, cfg, prompt, L.init_kv_cache(cfg, 2, 80)
        )
        got_logits, got_cache = L.prefill_chunked(
            params, cfg, prompt, L.init_kv_cache(cfg, 2, 80), chunk=16
        )
        assert float(jnp.max(jnp.abs(ref_logits - got_logits))) < 1e-2
        for key in ("k", "v"):
            assert float(jnp.max(jnp.abs(
                ref_cache[key][..., :64, :] - got_cache[key][..., :64, :]
            ))) < 1e-2

    def test_chunked_prefill_windowed_gqa(self):
        """Sliding-window + GQA config through the chunked path."""
        import dataclasses

        cfg = dataclasses.replace(
            L.LLAMA_CONFIGS["tiny-gqa"], sliding_window=24
        )
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        prompt = jax.random.randint(
            jax.random.PRNGKey(6), (1, 48), 0, cfg.vocab_size
        )
        ref, _ = L.prefill(params, cfg, prompt, L.init_kv_cache(cfg, 1, 48))
        got, _ = L.prefill_chunked(
            params, cfg, prompt, L.init_kv_cache(cfg, 1, 48), chunk=12
        )
        assert float(jnp.max(jnp.abs(ref - got))) < 1e-2

    def test_chunked_prefill_then_decode(self, tiny):
        """Generation continues correctly off a chunk-primed cache."""
        cfg, params = tiny
        prompt = jax.random.randint(
            jax.random.PRNGKey(7), (1, 32), 0, cfg.vocab_size
        )
        logits, cache = L.prefill_chunked(
            params, cfg, prompt, L.init_kv_cache(cfg, 1, 40), chunk=8
        )
        nxt = jnp.argmax(logits, axis=-1)[:, None]
        step_logits, _ = L.decode_step(
            params, cfg, nxt, cache, jnp.asarray(32, jnp.int32)
        )
        full = jnp.concatenate([prompt, nxt], axis=1)
        ref = L.forward(params, cfg, full)[:, -1]
        assert float(jnp.max(jnp.abs(step_logits - ref))) < 1e-2


class TestShardedInference:
    """Multi-chip serving: the SAME forward/generate entry points run
    under tp/dp-sharded params — GSPMD inserts the collectives; no
    separate inference codepath to maintain."""

    def test_forward_matches_unsharded(self, tiny):
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P

        from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh

        cfg, params = tiny
        tokens = jnp.asarray([[5, 9, 17, 33]] * 2)
        ref = np.asarray(L.forward(params, cfg, tokens))
        plan = MeshPlan(make_mesh(dp=2, tp=4))
        sharded = plan.shard_params(params)
        stokens = jax.device_put(
            tokens, NamedSharding(plan.mesh, P(("dp", "fsdp"), None))
        )
        got = np.asarray(L.forward(sharded, cfg, stokens))
        # Sharded matmuls tile reductions differently — bf16 tolerance.
        assert np.abs(got - ref).max() < 5e-2

    def test_fused_generate_matches_unsharded(self, tiny):
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P

        from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh

        cfg, params = tiny
        tokens = jnp.asarray([[5, 9, 17, 33]] * 2)
        ref = np.asarray(L.generate(params, cfg, tokens, steps=6, cache_len=16))
        plan = MeshPlan(make_mesh(dp=2, tp=4))
        sharded = plan.shard_params(params)
        stokens = jax.device_put(
            tokens, NamedSharding(plan.mesh, P(("dp", "fsdp"), None))
        )
        got = np.asarray(
            L.generate(sharded, cfg, stokens, steps=6, cache_len=16)
        )
        assert (got == ref).all()


class TestAttentionOps:
    def test_xla_flash_equivalence_noncausal(self):
        q = jax.random.normal(jax.random.PRNGKey(0), (1, 2, 64, 32))
        k = jax.random.normal(jax.random.PRNGKey(1), (1, 2, 64, 32))
        v = jax.random.normal(jax.random.PRNGKey(2), (1, 2, 64, 32))
        # On CPU the pallas path is skipped; this pins the xla reference.
        out = flash_attention(q, k, v, causal=False, impl="xla")
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(32.0)
        ref = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(scores, -1), v)
        assert float(jnp.max(jnp.abs(out - ref))) < 1e-5

    def test_q_offset_masking(self):
        """q_offset shifts causality for cached decode."""
        q = jax.random.normal(jax.random.PRNGKey(0), (1, 1, 1, 16))
        k = jax.random.normal(jax.random.PRNGKey(1), (1, 1, 8, 16))
        v = jax.random.normal(jax.random.PRNGKey(2), (1, 1, 8, 16))
        # offset 3 → q sees keys 0..3 only
        out_a = flash_attention(q, k, v, causal=True, q_offset=3, impl="xla")
        k_masked = k.at[:, :, 4:].set(99.0)  # poisoning masked keys: no effect
        v_masked = v.at[:, :, 4:].set(99.0)
        out_b = flash_attention(q, k_masked, v_masked, causal=True, q_offset=3, impl="xla")
        assert jnp.allclose(out_a, out_b, atol=1e-6)

    def test_sliding_window_masks_old_keys(self):
        """window=4: position p sees only keys (p-4, p] — poisoning keys
        outside the band must not change the output."""
        q = jax.random.normal(jax.random.PRNGKey(0), (1, 1, 1, 16))
        k = jax.random.normal(jax.random.PRNGKey(1), (1, 1, 16, 16))
        v = jax.random.normal(jax.random.PRNGKey(2), (1, 1, 16, 16))
        # q at position 10 (offset), window 4 → sees keys 7..10 only.
        out_a = flash_attention(q, k, v, causal=True, q_offset=10,
                                impl="xla", window=4)
        k_p = k.at[:, :, :7].set(99.0).at[:, :, 11:].set(99.0)
        v_p = v.at[:, :, :7].set(99.0).at[:, :, 11:].set(99.0)
        out_b = flash_attention(q, k_p, v_p, causal=True, q_offset=10,
                                impl="xla", window=4)
        assert jnp.allclose(out_a, out_b, atol=1e-6)

    def test_window_wider_than_sequence_is_full_causal(self):
        q = jax.random.normal(jax.random.PRNGKey(0), (1, 2, 32, 16))
        k = jax.random.normal(jax.random.PRNGKey(1), (1, 2, 32, 16))
        v = jax.random.normal(jax.random.PRNGKey(2), (1, 2, 32, 16))
        full = flash_attention(q, k, v, causal=True, impl="xla")
        windowed = flash_attention(q, k, v, causal=True, impl="xla", window=64)
        assert jnp.allclose(full, windowed, atol=1e-6)


class TestRingAttention:
    def test_matches_dense_sp8(self):
        mesh = make_mesh(dp=1, fsdp=1, tp=1, sp=8)
        q = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 128, 32))
        k = jax.random.normal(jax.random.PRNGKey(1), (2, 4, 128, 32))
        v = jax.random.normal(jax.random.PRNGKey(2), (2, 4, 128, 32))
        ref = flash_attention(q, k, v, causal=True, impl="xla")
        out = make_sharded_ring_attention(mesh)(q, k, v)
        assert float(jnp.max(jnp.abs(out - ref))) < 1e-4

    def test_composes_with_dp_tp(self):
        mesh = make_mesh(dp=2, fsdp=1, tp=2, sp=2)
        q = jax.random.normal(jax.random.PRNGKey(0), (2, 4, 64, 32))
        k = jax.random.normal(jax.random.PRNGKey(1), (2, 4, 64, 32))
        v = jax.random.normal(jax.random.PRNGKey(2), (2, 4, 64, 32))
        ref = flash_attention(q, k, v, causal=True, impl="xla")
        out = make_sharded_ring_attention(mesh)(q, k, v)
        assert float(jnp.max(jnp.abs(out - ref))) < 1e-4


class TestTraining:
    def test_loss_decreases_on_sharded_mesh(self):
        cfg = L.LLAMA_CONFIGS["tiny"]
        plan = MeshPlan(make_mesh(dp=2, fsdp=1, tp=2, sp=2))
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        init_state, step = make_train_step(cfg, plan)
        state = shard_state(plan, init_state(params))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 64), 0, cfg.vocab_size)
        first = last = None
        for _ in range(5):
            state, loss = step(state, tokens)
            first = first if first is not None else float(loss)
            last = float(loss)
        assert last < first

    def test_fsdp_mesh_also_works(self):
        cfg = L.LLAMA_CONFIGS["tiny"]
        plan = MeshPlan(make_mesh(dp=1, fsdp=4, tp=2, sp=1))
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        init_state, step = make_train_step(cfg, plan)
        state = shard_state(plan, init_state(params))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab_size)
        state, loss = step(state, tokens)
        assert jnp.isfinite(loss)

    def test_loss_is_sane_at_init(self):
        cfg = L.LLAMA_CONFIGS["tiny"]
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, cfg.vocab_size)
        loss = causal_lm_loss(params, cfg, tokens)
        # ~ln(vocab) at random init
        assert abs(float(loss) - jnp.log(cfg.vocab_size)) < 1.0

    def test_chunked_loss_matches_dense(self):
        """chunked_causal_lm_loss is the same lse−target arithmetic as the
        dense loss, value AND gradient — including a non-chunk-aligned
        S−1 tail (S=33 with chunk=8 leaves a tail of 0... S=34 → 33
        positions = 4 chunks + tail 1)."""
        from kubeflow_tpu.models.train import chunked_causal_lm_loss

        cfg = L.LLAMA_CONFIGS["tiny"]
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (2, 34), 0, cfg.vocab_size
        )
        dense, dense_g = jax.value_and_grad(causal_lm_loss)(
            params, cfg, tokens
        )
        for chunk in (8, 16, 64):  # incl. chunk > S−1
            got, got_g = jax.value_and_grad(chunked_causal_lm_loss)(
                params, cfg, tokens, chunk=chunk
            )
            assert abs(float(dense) - float(got)) < 1e-5, chunk
            diffs = jax.tree_util.tree_map(
                lambda a, b: float(jnp.max(jnp.abs(a - b))), dense_g, got_g
            )
            # 1e-3: grads are bf16 (ulp 2^-11 ≈ 4.9e-4 at magnitude ~1);
            # chunked accumulation rounds in a different order.
            assert max(jax.tree_util.tree_leaves(diffs)) < 1e-3, chunk

    def test_remat_policies_agree(self):
        """The three layer-scan remat policies are pure scheduling choices:
        same loss, same grads."""
        from kubeflow_tpu.models.train import chunked_causal_lm_loss

        cfg = L.LLAMA_CONFIGS["tiny"]
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (2, 32), 0, cfg.vocab_size
        )
        ref = ref_g = None
        for remat in ("full", "dots", "none"):
            loss, g = jax.value_and_grad(chunked_causal_lm_loss)(
                params, cfg, tokens, chunk=16, remat=remat
            )
            if ref is None:
                ref, ref_g = loss, g
                continue
            assert abs(float(ref) - float(loss)) < 1e-5, remat
            diffs = jax.tree_util.tree_map(
                lambda a, b: float(jnp.max(jnp.abs(a - b))), ref_g, g
            )
            assert max(jax.tree_util.tree_leaves(diffs)) < 1e-4, remat

    def test_unknown_remat_policy_rejected(self):
        cfg = L.LLAMA_CONFIGS["tiny"]
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, 10)
        with pytest.raises(ValueError, match="remat"):
            L.forward_hidden(params, cfg, tokens, remat="bogus")

    def test_train_step_chunked_matches_dense_loss_path(self):
        """make_train_step(loss_chunk=...) and the dense path take the
        same first step on the same data."""
        cfg = L.LLAMA_CONFIGS["tiny"]
        plan = MeshPlan(make_mesh(dp=2, fsdp=2, tp=2, sp=1))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (4, 64), 0, cfg.vocab_size
        )
        losses = []
        for loss_chunk in (0, 16):
            # Fresh params per variant: the jitted step DONATES its state,
            # so a shared tree would be dead after the first step.
            params = L.init_params(cfg, jax.random.PRNGKey(0))
            init_state, step = make_train_step(
                cfg, plan, loss_chunk=loss_chunk
            )
            state = shard_state(plan, init_state(params))
            _, loss = step(state, tokens)
            losses.append(float(loss))
        assert abs(losses[0] - losses[1]) < 1e-5


class TestRuntimeBootstrap:
    def test_runtime_from_env(self):
        from kubeflow_tpu.runtime import runtime_from_env

        env = {
            "TPU_WORKER_ID": "2",
            "TPU_WORKER_HOSTNAMES": "nb-0.h,nb-1.h,nb-2.h,nb-3.h",
            "JAX_COORDINATOR_ADDRESS": "nb-0.h:8476",
            "JAX_NUM_PROCESSES": "4",
            "TPU_ACCELERATOR_TYPE": "v5litepod-16",
            "TPU_TOPOLOGY": "4x4",
        }
        rt = runtime_from_env(env)
        assert rt.worker_id == 2
        assert rt.num_workers == 4
        assert rt.is_multi_host and not rt.is_coordinator

    def test_single_host_bootstrap_no_distributed(self):
        from kubeflow_tpu.runtime import bootstrap

        rt = bootstrap(env={"TPU_WORKER_ID": "0"}, initialize_distributed=True)
        assert not rt.is_multi_host
        assert not rt.distributed_initialized

    def test_mesh_helper_infers_axis(self):
        from kubeflow_tpu.runtime import runtime_from_env

        rt = runtime_from_env({})
        mesh = rt.mesh(dp=-1, tp=2)
        assert mesh.shape == {"dp": 4, "tp": 2}

    def test_device_count_mismatch_raises(self):
        from kubeflow_tpu.runtime import bootstrap
        import pytest as _pytest

        with _pytest.raises(RuntimeError, match="slice incomplete"):
            bootstrap(env={}, expected_devices=16)


class TestTrainingExtras:
    def test_grad_accum_matches_full_batch(self):
        """4 microbatches must produce the same update as the full batch
        (same data, same order — the accumulation is exact in f32)."""
        import numpy as np

        from kubeflow_tpu.models.train import make_train_step, shard_state
        from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh

        cfg = L.LlamaConfig(vocab_size=64, dim=32, n_layers=1, n_heads=2,
                            n_kv_heads=2, ffn_hidden=64, dtype=jnp.float32)
        plan = MeshPlan(make_mesh(dp=8))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64)
        results = {}
        for accum in (1, 4):
            params = L.init_params(cfg, jax.random.PRNGKey(0))
            init_state, step = make_train_step(cfg, plan, grad_accum=accum)
            state = shard_state(plan, init_state(params))
            state, loss = step(state, tokens)
            results[accum] = (
                float(loss),
                np.asarray(state["params"]["layers"]["wq"]),
            )
        assert abs(results[1][0] - results[4][0]) < 1e-5
        np.testing.assert_allclose(results[1][1], results[4][1],
                                   rtol=1e-4, atol=1e-5)

    def test_indivisible_grad_accum_rejected(self):
        from kubeflow_tpu.models.train import make_train_step, shard_state
        from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh
        import pytest

        cfg = L.LLAMA_CONFIGS["tiny"]
        plan = MeshPlan(make_mesh(dp=8))
        init_state, step = make_train_step(cfg, plan, grad_accum=3)
        state = shard_state(
            plan, init_state(L.init_params(cfg, jax.random.PRNGKey(0)))
        )
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64)
        with pytest.raises(ValueError, match="not divisible"):
            step(state, tokens)

    def test_warmup_then_constant_lr(self):
        """warmup_steps without decay_steps: lr holds at PEAK after
        warmup (never cliffs to an end value)."""
        import optax

        from kubeflow_tpu.models.train import make_optimizer

        # Reconstruct the schedule the optimizer embeds by probing updates
        # with sgd-like normalization: easier to probe the schedule fn via
        # a fresh make and inspecting update magnitudes over steps.
        opt = make_optimizer(lr=1e-2, warmup_steps=5)
        params = {"w": jnp.zeros((1,))}
        state = opt.init(params)
        mags = []
        for _ in range(12):
            updates, state = opt.update({"w": jnp.ones((1,))}, state, params)
            mags.append(float(jnp.abs(updates["w"])[0]))
        assert mags[0] < mags[3] < mags[6]  # ramping through warmup
        # Post-warmup the lr is constant: updates settle at peak scale,
        # NOT at a decayed fraction of it.
        assert abs(mags[-1] - mags[6]) / mags[6] < 0.2

    def test_cosine_decays_after_warmup(self):
        from kubeflow_tpu.models.train import make_optimizer

        opt = make_optimizer(lr=1e-2, warmup_steps=2, decay_steps=10,
                             end_lr_ratio=0.1)
        params = {"w": jnp.zeros((1,))}
        state = opt.init(params)
        mags = []
        for _ in range(14):
            updates, state = opt.update({"w": jnp.ones((1,))}, state, params)
            mags.append(float(jnp.abs(updates["w"])[0]))
        peak = max(mags)
        # Decay over the 10 steps AFTER warmup: the tail is ~end_lr_ratio
        # of peak, not a 1-step cliff right after warmup.
        assert mags[3] > 0.5 * peak  # still high early in the decay
        assert mags[-1] < 0.25 * peak  # decayed by the end

    def test_gradient_clipping_bounds_the_update(self):
        """clip_norm>0 must actually bound what reaches the optimizer.

        Adam normalizes update magnitude (m̂/√ν̂ is scale-invariant for a
        constant-direction gradient), so asserting on adamw's output can't
        distinguish clipped from unclipped. Instead assert on the
        transform the flag installs: the gradient that flows past
        clip_by_global_norm has global norm ≤ clip_norm, and a
        non-normalizing optimizer (SGD) downstream of make_optimizer's
        clip stage produces a bounded step.
        """
        from kubeflow_tpu.models.train import make_optimizer

        params = {"w": jnp.zeros((4,))}
        grads_huge = {"w": jnp.full((4,), 1e6)}

        # 1) The transform itself bounds the global norm.
        clip = optax.clip_by_global_norm(1.0)
        clipped, _ = clip.update(grads_huge, clip.init(params), params)
        assert float(optax.global_norm(clipped)) <= 1.0 + 1e-6
        assert float(optax.global_norm(grads_huge)) > 1e6

        # 2) Sanity on the mechanism (raw optax, not repo code): a
        #    non-normalizing optimizer behind the same clip stage steps at
        #    most lr * clip_norm, while unclipped SGD steps hugely.
        opt_c = optax.chain(optax.clip_by_global_norm(1.0), optax.sgd(1e-2))
        u_c, _ = opt_c.update(grads_huge, opt_c.init(params), params)
        assert float(jnp.abs(u_c["w"]).max()) <= 1e-2 + 1e-8

        opt_u = optax.sgd(1e-2)
        u_u, _ = opt_u.update(grads_huge, opt_u.init(params), params)
        assert float(jnp.abs(u_u["w"]).max()) > 1e3

        # 3) And make_optimizer wires the clip stage in at all: after one
        #    huge-gradient step, adam's second moment ν sees the CLIPPED
        #    gradient (ν ≤ (1-b2)·clip² per element) rather than 1e6².
        def max_nu(clip_norm):
            opt = make_optimizer(lr=1e-2, clip_norm=clip_norm)
            _, state = opt.update(grads_huge, opt.init(params), params)
            nus = [float(jnp.max(s.nu["w"]))
                   for s in jax.tree_util.tree_leaves(
                       state, is_leaf=lambda x: hasattr(x, "nu"))
                   if hasattr(s, "nu")]
            assert nus, "no adam state found in optimizer chain"
            return max(nus)

        assert max_nu(1.0) <= 0.05 * 1.0**2 + 1e-9
        assert max_nu(0.0) > 1e9

    def test_perplexity_of_uniform_model(self):
        from kubeflow_tpu.models.train import evaluate_perplexity

        cfg = L.LlamaConfig(vocab_size=64, dim=32, n_layers=1, n_heads=2,
                            n_kv_heads=2, ffn_hidden=64, dtype=jnp.float32)
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        # Zeroed lm_head → uniform logits → ppl == vocab_size exactly.
        params["lm_head"] = jnp.zeros_like(params["lm_head"])
        batches = [
            jax.random.randint(jax.random.PRNGKey(i), (2, 16), 0, 64)
            for i in range(3)
        ]
        result = evaluate_perplexity(params, cfg, batches)
        assert abs(result["perplexity"] - 64.0) < 0.5
        assert result["tokens"] == 3 * 2 * 15
