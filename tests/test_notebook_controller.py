"""Integration tests for the core Notebook reconciler (envtest tier).

Mirrors the reference's BDD assertions (reference
notebook_controller_bdd_test.go:32-96: STS replica behavior on stop/resume)
and extends them to the TPU slice semantics from SURVEY.md §7 step 2.
"""

from kubeflow_tpu.api import annotations as ann
from kubeflow_tpu.k8s import objects as obj_util
from kubeflow_tpu.k8s.events import events_for

from tests.harness import cpu_notebook, make_env, tpu_notebook


class TestCpuNotebook:
    def test_single_replica_statefulset_and_service(self):
        env = make_env()
        env.cluster.create(cpu_notebook())
        env.manager.run_until_idle()

        sts = env.cluster.get("StatefulSet", "nb", "ns")
        assert sts["spec"]["replicas"] == 1
        assert "podManagementPolicy" not in sts["spec"]
        svc = env.cluster.get("Service", "nb", "ns")
        assert svc["spec"]["ports"][0]["port"] == 80
        assert svc["spec"]["ports"][0]["targetPort"] == 8888
        # No TPU headless service for CPU notebooks.
        assert not env.cluster.exists("Service", "nb-hosts", "ns")

    def test_container_defaults(self):
        env = make_env()
        env.cluster.create(cpu_notebook())
        env.manager.run_until_idle()
        sts = env.cluster.get("StatefulSet", "nb", "ns")
        container = sts["spec"]["template"]["spec"]["containers"][0]
        assert container["workingDir"] == "/home/jovyan"
        assert {"containerPort": 8888, "name": "notebook-port", "protocol": "TCP"} in container["ports"]
        assert {"name": "NB_PREFIX", "value": "/notebook/ns/nb"} in container["env"]
        assert sts["spec"]["template"]["spec"]["securityContext"]["fsGroup"] == 100

    def test_pod_becomes_ready_and_status_mirrors(self):
        env = make_env()
        env.cluster.create(cpu_notebook())
        env.manager.run_until_idle()
        nb = env.cluster.get("Notebook", "nb", "ns")
        assert nb["status"]["readyReplicas"] == 1
        cond_types = {c["type"] for c in nb["status"]["conditions"]}
        assert "Ready" in cond_types
        assert nb["status"]["containerState"].get("running")

    def test_name_too_long_falls_back_to_hashed_sts_name(self):
        """Reference GenerateName fallback (notebook_controller.go:145-149):
        a >52-char name must still produce a working StatefulSet, via a
        deterministic short name, with an event naming the substitution."""
        from kubeflow_tpu.controller.notebook import slice_sts_name

        env = make_env()
        long_name = "x" * 60
        env.cluster.create(cpu_notebook(name=long_name))
        env.manager.run_until_idle()

        sts_name = slice_sts_name(long_name, 0)
        assert sts_name != long_name and len(sts_name) <= 52
        assert not env.cluster.exists("StatefulSet", long_name, "ns")
        sts = env.cluster.get("StatefulSet", sts_name, "ns")
        assert sts["spec"]["replicas"] == 1
        evs = events_for(env.cluster, "Notebook", long_name, "ns")
        assert any(e["reason"] == "LongNameFallback" for e in evs)
        # Deterministic: a second reconcile computes the same name.
        assert slice_sts_name(long_name, 0) == sts_name

        # Routing must still reach the pods: the Service selector targets
        # the FALLBACK statefulset label and all names fit their limits.
        svc = env.cluster.list("Service", "ns")[0]
        assert svc["spec"]["selector"]["statefulset"] == sts_name
        assert len(svc["metadata"]["name"]) <= 63
        assert len(svc["spec"]["ports"][0]["name"]) <= 63
        pod = env.cluster.get("Pod", f"{sts_name}-0", "ns")
        assert (
            pod["metadata"]["labels"]["statefulset"]
            == svc["spec"]["selector"]["statefulset"]
        )
        # The auth-proxy Service name derivation fits too.
        from kubeflow_tpu.api.names import proxy_service_name

        assert len(proxy_service_name(long_name)) <= 63


class TestTpuSlice:
    def test_indexed_statefulset_shape(self):
        env = make_env()
        env.cluster.create(tpu_notebook())  # v5e 4x4 → 4 hosts
        env.manager.run_until_idle()

        sts = env.cluster.get("StatefulSet", "nb", "ns")
        assert sts["spec"]["replicas"] == 4
        assert sts["spec"]["podManagementPolicy"] == "Parallel"
        assert sts["spec"]["serviceName"] == "nb-hosts"
        pod_spec = sts["spec"]["template"]["spec"]
        assert pod_spec["nodeSelector"] == {
            "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
            "cloud.google.com/gke-tpu-topology": "4x4",
        }
        assert any(t["key"] == "google.com/tpu" for t in pod_spec["tolerations"])
        container = pod_spec["containers"][0]
        assert container["resources"]["limits"]["google.com/tpu"] == "4"
        assert container["resources"]["requests"]["google.com/tpu"] == "4"

    def test_headless_service(self):
        env = make_env()
        env.cluster.create(tpu_notebook())
        env.manager.run_until_idle()
        headless = env.cluster.get("Service", "nb-hosts", "ns")
        assert headless["spec"]["clusterIP"] == "None"
        assert headless["spec"]["publishNotReadyAddresses"] is True

    def test_all_hosts_ready_status_healthy(self):
        env = make_env()
        env.cluster.create(tpu_notebook())
        env.manager.run_until_idle()
        nb = env.cluster.get("Notebook", "nb", "ns")
        assert nb["status"]["tpu"] == {
            "hosts": 4,
            "readyHosts": 4,
            "sliceHealth": "Healthy",
            "acceleratorType": "v5litepod-16",
            "jaxCoordinator": "nb-0.nb-hosts.ns.svc.cluster.local:8476",
        }
        assert nb["status"]["readyReplicas"] == 4

    def test_forming_when_pool_too_small(self):
        env = make_env(node_pools=(("tpu-v5-lite-podslice", "4x4", 2, 4),))
        env.cluster.create(tpu_notebook())
        env.manager.run_until_idle()
        nb = env.cluster.get("Notebook", "nb", "ns")
        assert nb["status"]["tpu"]["sliceHealth"] == "Forming"
        assert nb["status"]["tpu"]["readyHosts"] == 2

    def test_invalid_topology_no_statefulset(self):
        env = make_env()
        env.cluster.create(tpu_notebook(topology="3x4"))
        env.manager.run_until_idle()
        assert not env.cluster.exists("StatefulSet", "nb", "ns")
        nb = env.cluster.get("Notebook", "nb", "ns")
        conds = {c["type"]: c for c in nb["status"]["conditions"]}
        assert conds["TPUTopologyValid"]["status"] == "False"
        evs = events_for(env.cluster, "Notebook", "nb", "ns")
        assert any(e["reason"] == "InvalidTPUTopology" for e in evs)

    def test_single_host_v5e4(self):
        env = make_env(node_pools=(("tpu-v5-lite-podslice", "2x2", 1, 4),))
        env.cluster.create(tpu_notebook(topology="2x2"))
        env.manager.run_until_idle()
        sts = env.cluster.get("StatefulSet", "nb", "ns")
        assert sts["spec"]["replicas"] == 1
        nb = env.cluster.get("Notebook", "nb", "ns")
        assert nb["status"]["tpu"]["sliceHealth"] == "Healthy"
        # Single-host slices need no jax coordinator.
        assert "jaxCoordinator" not in nb["status"]["tpu"]


class TestStopResume:
    def test_stop_annotation_scales_whole_slice_to_zero(self):
        env = make_env()
        env.cluster.create(tpu_notebook())
        env.manager.run_until_idle()
        assert len(env.cluster.list("Pod", "ns")) == 4

        nb = env.cluster.get("Notebook", "nb", "ns")
        obj_util.annotations_of(nb)[ann.STOP] = "2026-07-29T00:00:00Z"
        env.cluster.update(nb)
        env.manager.run_until_idle()

        sts = env.cluster.get("StatefulSet", "nb", "ns")
        assert sts["spec"]["replicas"] == 0
        assert env.cluster.list("Pod", "ns") == []  # atomic: no partial slice
        nb = env.cluster.get("Notebook", "nb", "ns")
        assert nb["status"]["tpu"]["sliceHealth"] == "Stopped"

    def test_resume_restores_slice(self):
        env = make_env()
        env.cluster.create(tpu_notebook(annotations={ann.STOP: "t"}))
        env.manager.run_until_idle()
        assert env.cluster.get("StatefulSet", "nb", "ns")["spec"]["replicas"] == 0

        nb = env.cluster.get("Notebook", "nb", "ns")
        obj_util.remove_annotation(nb, ann.STOP)
        env.cluster.update(nb)
        env.manager.run_until_idle()
        assert env.cluster.get("StatefulSet", "nb", "ns")["spec"]["replicas"] == 4
        assert len(env.cluster.list("Pod", "ns")) == 4


class TestRestart:
    def test_restart_annotation_deletes_all_pods_and_clears(self):
        env = make_env()
        env.cluster.create(tpu_notebook())
        env.manager.run_until_idle()
        pods_before = {
            p["metadata"]["uid"] for p in env.cluster.list("Pod", "ns")
        }
        assert len(pods_before) == 4

        nb = env.cluster.get("Notebook", "nb", "ns")
        obj_util.annotations_of(nb)[ann.RESTART] = "true"
        env.cluster.update(nb)
        env.manager.run_until_idle()

        nb = env.cluster.get("Notebook", "nb", "ns")
        assert ann.RESTART not in nb["metadata"].get("annotations", {})
        pods_after = {p["metadata"]["uid"] for p in env.cluster.list("Pod", "ns")}
        assert len(pods_after) == 4
        assert pods_before.isdisjoint(pods_after)  # every host pod replaced


class TestLevelTriggeredRecovery:
    def test_deleted_statefulset_recreated(self):
        env = make_env()
        env.cluster.create(tpu_notebook())
        env.manager.run_until_idle()
        env.cluster.delete("StatefulSet", "nb", "ns")
        env.manager.run_until_idle()
        assert env.cluster.exists("StatefulSet", "nb", "ns")

    def test_deleted_service_recreated(self):
        env = make_env()
        env.cluster.create(cpu_notebook())
        env.manager.run_until_idle()
        env.cluster.delete("Service", "nb", "ns")
        env.manager.run_until_idle()
        assert env.cluster.exists("Service", "nb", "ns")

    def test_spec_change_rolls_template(self):
        """The reconcilehelper sharp-edge fix: template drift triggers Update."""
        env = make_env()
        env.cluster.create(cpu_notebook())
        env.manager.run_until_idle()
        nb = env.cluster.get("Notebook", "nb", "ns")
        nb["spec"]["template"]["spec"]["containers"][0]["image"] = "new-image:v2"
        env.cluster.update(nb)
        env.manager.run_until_idle()
        sts = env.cluster.get("StatefulSet", "nb", "ns")
        assert sts["spec"]["template"]["spec"]["containers"][0]["image"] == "new-image:v2"

    def test_notebook_deletion_cascades(self):
        env = make_env()
        env.cluster.create(tpu_notebook())
        env.manager.run_until_idle()
        env.cluster.delete("Notebook", "nb", "ns")
        env.manager.run_until_idle()
        assert not env.cluster.exists("StatefulSet", "nb", "ns")
        assert not env.cluster.exists("Service", "nb", "ns")
        assert not env.cluster.exists("Service", "nb-hosts", "ns")


class TestEventReemission:
    def test_pod_warning_surfaces_on_notebook(self):
        env = make_env()
        env.cluster.create(tpu_notebook())
        env.manager.run_until_idle()
        # A warning event lands on a slice pod (e.g. image pull failure).
        env.cluster.create(
            {
                "apiVersion": "v1",
                "kind": "Event",
                "metadata": {"name": "nb-2.deadbeef", "namespace": "ns"},
                "involvedObject": {"kind": "Pod", "name": "nb-2", "namespace": "ns"},
                "type": "Warning",
                "reason": "FailedMount",
                "message": "volume timeout",
            }
        )
        env.manager.run_until_idle()
        evs = events_for(env.cluster, "Notebook", "nb", "ns")
        assert any(
            e["reason"] == "FailedMount" and "[nb-2]" in e["message"] for e in evs
        )

    def test_no_duplicate_reemission_across_restarts(self):
        """The lastSeen cursor lives on the Notebook, so a NEW controller
        process (fresh informers, fresh memory) must not re-emit history."""
        env = make_env()
        env.cluster.create(tpu_notebook())
        env.manager.run_until_idle()
        env.cluster.create(
            {
                "apiVersion": "v1",
                "kind": "Event",
                "metadata": {"name": "nb-1.cafe", "namespace": "ns"},
                "involvedObject": {"kind": "Pod", "name": "nb-1", "namespace": "ns"},
                "type": "Warning",
                "reason": "BackOff",
                "message": "restarting failed container",
            }
        )
        env.manager.run_until_idle()

        def surfaced():
            return [
                e for e in events_for(env.cluster, "Notebook", "nb", "ns")
                if e["reason"] == "BackOff"
            ]

        assert len(surfaced()) == 1
        # No dedup marks were written onto the Event object itself.
        stored = env.cluster.get("Event", "nb-1.cafe", "ns")
        assert "re-emitted" not in str(stored.get("metadata", {}).get("annotations", {}))

        # "Restart": a brand-new manager + reconciler over the same cluster.
        env2 = make_env(cluster=env.cluster)
        env2.manager.run_until_idle()
        assert len(surfaced()) == 1, "restarted controller re-emitted history"

    def test_opaque_resource_versions_still_surface_and_dedup(self):
        """The API contract calls resourceVersions OPAQUE; only etcd makes
        them integers. With non-integer rvs the dedup cursor falls back to
        Event lastTimestamp ordering — warnings still surface exactly
        once (controller/notebook.py _event_token)."""
        from kubeflow_tpu.controller.notebook import _cursor_token

        env = make_env()

        class OpaqueRVClient:
            """Simulates an apiserver with non-integer resourceVersions
            on the Event list the re-emitter reads."""

            def __init__(self, inner):
                self._inner = inner

            def __getattr__(self, name):
                return getattr(self._inner, name)

            def list(self, kind, namespace, *a, **kw):
                out = self._inner.list(kind, namespace, *a, **kw)
                if kind == "Event":
                    for e in out:
                        rv = e["metadata"].get("resourceVersion")
                        if rv is not None:
                            e["metadata"]["resourceVersion"] = f"op-{rv}"
                return out

        env.reconciler.client = OpaqueRVClient(env.cluster)
        env.cluster.create(tpu_notebook())
        env.manager.run_until_idle()
        env.cluster.create({
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {"name": "nb-3.feed", "namespace": "ns"},
            "involvedObject": {"kind": "Pod", "name": "nb-3", "namespace": "ns"},
            "type": "Warning",
            "reason": "Evicted",
            "message": "node pressure",
            "lastTimestamp": "2026-07-30T12:00:00Z",
        })
        env.manager.run_until_idle()

        def surfaced():
            return [
                e for e in events_for(env.cluster, "Notebook", "nb", "ns")
                if e["reason"] == "Evicted"
            ]

        assert len(surfaced()) == 1
        # The cursor advanced in the timestamp regime (name tiebreak).
        nb = env.cluster.get("Notebook", "nb", "ns")
        from kubeflow_tpu.api import annotations as ann2

        assert nb["metadata"]["annotations"][ann2.LAST_SEEN_EVENT_RV].startswith(
            ".2026-"
        )
        # A SECOND warning in the same second (timestamp collision) must
        # still surface: the event-name tiebreaker keeps tokens distinct.
        env.cluster.create({
            "apiVersion": "v1",
            "kind": "Event",
            "metadata": {"name": "nb-3.fffe", "namespace": "ns"},
            "involvedObject": {"kind": "Pod", "name": "nb-3", "namespace": "ns"},
            "type": "Warning",
            "reason": "SameSecond",
            "message": "second warning, same timestamp",
            "lastTimestamp": "2026-07-30T12:00:00Z",
        })
        env.manager.run_until_idle()
        assert any(
            e["reason"] == "SameSecond"
            for e in events_for(env.cluster, "Notebook", "nb", "ns")
        )
        # Repeat reconciles do not duplicate.
        nb = env.cluster.get("Notebook", "nb", "ns")
        nb["metadata"].setdefault("annotations", {})["touch"] = "1"
        env.cluster.update(nb)
        env.manager.run_until_idle()
        assert len(surfaced()) == 1
        # Old raw-int cursors normalize into the padded token regime.
        assert _cursor_token("123") == f"{123:020d}"
        assert _cursor_token("") == ""

    def test_anomalous_rvless_event_does_not_poison_integer_cursor(self):
        """One Event with a missing/non-integer rv on an otherwise-etcd
        cluster must not flip the cursor into a regime that suppresses all
        future integer-rv events: timestamp tokens sort BELOW integers, so
        the anomaly is (at worst) dropped, never poisonous."""
        from kubeflow_tpu.controller.notebook import _event_token

        # Regime ordering invariants.
        assert _event_token(
            {"metadata": {"name": "x"}, "lastTimestamp": "2099-01-01T00:00:00Z"}
        ) < _event_token({"metadata": {"name": "y", "resourceVersion": "1"}})

        env = make_env()
        env.cluster.create(tpu_notebook())
        env.manager.run_until_idle()
        # Integer-rv warning surfaces, cursor advances in the int regime.
        env.cluster.create({
            "apiVersion": "v1", "kind": "Event",
            "metadata": {"name": "nb-0.aaaa", "namespace": "ns"},
            "involvedObject": {"kind": "Pod", "name": "nb-0", "namespace": "ns"},
            "type": "Warning", "reason": "First", "message": "m",
        })
        env.manager.run_until_idle()
        # Later integer-rv warnings must still surface.
        env.cluster.create({
            "apiVersion": "v1", "kind": "Event",
            "metadata": {"name": "nb-0.bbbb", "namespace": "ns"},
            "involvedObject": {"kind": "Pod", "name": "nb-0", "namespace": "ns"},
            "type": "Warning", "reason": "Second", "message": "m",
        })
        env.manager.run_until_idle()
        reasons = {
            e["reason"] for e in events_for(env.cluster, "Notebook", "nb", "ns")
        }
        assert {"First", "Second"} <= reasons

    def test_integer_parsing_opaque_rv_does_not_poison_ts_cursor(self):
        """The symmetric poisoning direction: on an opaque-rv cluster one
        rv that HAPPENS to parse as an integer must not promote the cursor
        into the int regime (ints sort above every ts token) and suppress
        all later timestamp-token events — the regime is sticky per
        cursor, cross-regime events are skipped."""
        env = make_env()

        class OpaqueRVClient:
            def __init__(self, inner):
                self._inner = inner

            def __getattr__(self, name):
                return getattr(self._inner, name)

            def list(self, kind, namespace, *a, **kw):
                out = self._inner.list(kind, namespace, *a, **kw)
                if kind == "Event":
                    for e in out:
                        rv = e["metadata"].get("resourceVersion")
                        if rv is not None:
                            e["metadata"]["resourceVersion"] = f"op-{rv}"
                return out

        env.reconciler.client = OpaqueRVClient(env.cluster)
        env.cluster.create(tpu_notebook())
        env.manager.run_until_idle()  # primes the cursor in the ts regime

        def warn(name, reason, ts="2026-07-30T12:00:00Z"):
            env.cluster.create({
                "apiVersion": "v1", "kind": "Event",
                "metadata": {"name": name, "namespace": "ns"},
                "involvedObject": {"kind": "Pod", "name": "nb-0",
                                   "namespace": "ns"},
                "type": "Warning", "reason": reason, "message": "m",
                "lastTimestamp": ts,
            })

        warn("nb-0.warn1", "Before")
        env.manager.run_until_idle()
        # The anomaly: for one reconcile the events surface with BARE
        # integer rvs (as if one opaque rv happened to parse as an int) —
        # drop the wrapper so the reconciler sees the raw assigned ints.
        env.reconciler.client = env.cluster
        warn("nb-0.warn2", "Anomaly")
        env.manager.run_until_idle()
        env.reconciler.client = OpaqueRVClient(env.cluster)
        warn("nb-0.warn3", "After", ts="2026-07-30T12:00:05Z")
        env.manager.run_until_idle()
        reasons = {
            e["reason"] for e in events_for(env.cluster, "Notebook", "nb", "ns")
        }
        assert "Before" in reasons
        assert "After" in reasons, (
            "ts-regime event suppressed after an int-parsing anomaly"
        )
        # Cursor is still a ts-regime token.
        nb = env.cluster.get("Notebook", "nb", "ns")
        assert nb["metadata"]["annotations"][ann.LAST_SEEN_EVENT_RV].startswith(
            "."
        )


class TestMetrics:
    def test_create_and_spawn_latency_observed(self):
        env = make_env()
        env.cluster.create(tpu_notebook())
        env.manager.run_until_idle()
        text = env.metrics.expose().decode()
        assert "notebook_create_total 1.0" in text
        assert "tpu_slice_ready_seconds_count 1.0" in text
        assert "notebook_running 1.0" in text
        assert "tpu_chips_in_use 16.0" in text

    def test_chips_released_on_stop(self):
        env = make_env()
        env.cluster.create(tpu_notebook())
        env.manager.run_until_idle()
        nb = env.cluster.get("Notebook", "nb", "ns")
        obj_util.annotations_of(nb)[ann.STOP] = "t"
        env.cluster.update(nb)
        env.manager.run_until_idle()
        text = env.metrics.expose().decode()
        assert "tpu_chips_in_use 0.0" in text


class TestPrimingRegimeMajority:
    def test_one_int_anomaly_among_ts_events_pins_ts_at_priming(self):
        """An unpinned (fresh) cursor pins to the MAJORITY regime of the
        visible events: on an opaque-rv cluster whose priming view
        contains ONE rv that parses as an integer, the cursor must still
        pin to the timestamp regime — so later ts-token warnings
        surface."""
        env = make_env()

        class MostlyOpaqueRVClient:
            """Opaque (ts-regime) rvs except one anomalous raw integer."""

            def __init__(self, inner, raw_name):
                self._inner = inner
                self._raw = raw_name

            def __getattr__(self, name):
                return getattr(self._inner, name)

            def list(self, kind, namespace, *a, **kw):
                out = self._inner.list(kind, namespace, *a, **kw)
                if kind == "Event":
                    for e in out:
                        rv = e["metadata"].get("resourceVersion")
                        if rv is not None and e["metadata"]["name"] != self._raw:
                            e["metadata"]["resourceVersion"] = f"op-{rv}"
                return out

        env.reconciler.client = MostlyOpaqueRVClient(env.cluster, "nb-0.anom")

        def warn(name, reason, ts):
            env.cluster.create({
                "apiVersion": "v1", "kind": "Event",
                "metadata": {"name": name, "namespace": "ns"},
                "involvedObject": {"kind": "Pod", "name": "nb-0",
                                   "namespace": "ns"},
                "type": "Warning", "reason": reason, "message": "m",
                "lastTimestamp": ts,
            })

        # Notebook + a mixed event set exist BEFORE the first reconcile:
        # priming sees several ts tokens and one int token.
        env.cluster.create(tpu_notebook())
        warn("nb-0.anom", "Anomaly", "2026-07-30T11:59:00Z")
        warn("nb-0.aaa", "Old1", "2026-07-30T11:59:01Z")
        warn("nb-0.bbb", "Old2", "2026-07-30T11:59:02Z")
        env.manager.run_until_idle()  # primes; history not re-emitted
        nb = env.cluster.get("Notebook", "nb", "ns")
        cursor = nb["metadata"]["annotations"][ann.LAST_SEEN_EVENT_RV]
        assert cursor.startswith("."), f"cursor pinned wrong regime: {cursor}"
        # A fresh ts-regime warning after priming surfaces.
        warn("nb-0.ccc", "Fresh", "2026-07-30T12:00:05Z")
        env.manager.run_until_idle()
        assert any(
            e["reason"] == "Fresh"
            for e in events_for(env.cluster, "Notebook", "nb", "ns")
        )
