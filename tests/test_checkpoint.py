"""In-notebook checkpoint/resume: sharded save/restore + preemption replay."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models import llama as L
from kubeflow_tpu.models.train import make_train_step, shard_state
from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh
from kubeflow_tpu.runtime.checkpoint import CheckpointManager, train_with_checkpointing


def _tiny_setup():
    plan = MeshPlan(make_mesh(fsdp=2, tp=2, sp=2, devices=jax.devices()[:8]))
    cfg = L.LLAMA_CONFIGS["tiny"]
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    init_state, step = make_train_step(cfg, plan)
    state = shard_state(plan, init_state(params))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 128), 0, cfg.vocab_size)
    return plan, cfg, state, step, tokens


def test_save_restore_round_trip(tmp_path):
    plan, cfg, state, step, tokens = _tiny_setup()
    state, _ = step(state, tokens)
    ckpt = CheckpointManager(tmp_path / "ckpt")
    assert ckpt.save(1, state)
    ckpt.wait()
    assert ckpt.latest_step() == 1

    # Restore into a fresh sharded template; must match exactly.
    params2 = L.init_params(cfg, jax.random.PRNGKey(42))
    init_state, _ = make_train_step(cfg, plan)
    template = shard_state(plan, init_state(params2))
    restored, at = ckpt.restore_latest(template)
    assert at == 1
    np.testing.assert_array_equal(
        np.asarray(restored["params"]["embed"]),
        np.asarray(state["params"]["embed"]),
    )
    assert int(restored["step"]) == int(state["step"])
    # Restored arrays keep the template's sharding (no host-0 gather).
    assert (
        restored["params"]["layers"]["wq"].sharding
        == template["params"]["layers"]["wq"].sharding
    )
    ckpt.close()


def test_restore_latest_without_checkpoint_returns_template(tmp_path):
    plan, cfg, state, step, tokens = _tiny_setup()
    ckpt = CheckpointManager(tmp_path / "empty")
    restored, at = ckpt.restore_latest(state)
    assert at is None and restored is state
    ckpt.close()


def test_preemption_resume_matches_uninterrupted_run(tmp_path):
    """Train 4 steps straight vs 2 steps + 'preemption' + restore + 2 steps:
    identical final params (determinism is what makes resume trustworthy)."""
    plan, cfg, state, step, tokens = _tiny_setup()

    # Uninterrupted reference run.
    ref = state
    for _ in range(4):
        ref, _ = step(ref, tokens)
    ref_embed = np.asarray(ref["params"]["embed"])

    # Interrupted run: checkpoint every step, die after 2.
    plan2, cfg2, state2, step2, tokens2 = _tiny_setup()
    ckpt = CheckpointManager(tmp_path / "resume")
    state2, _ = train_with_checkpointing(step2, state2, [tokens2, tokens2], ckpt)
    del state2  # the preemption

    # New process: fresh init, restore, continue.
    params3 = L.init_params(cfg2, jax.random.PRNGKey(7))
    init_state, step3 = make_train_step(cfg2, plan2)
    template = shard_state(plan2, init_state(params3))
    resumed, at = ckpt.restore_latest(template)
    assert at == 2
    resumed, _ = train_with_checkpointing(
        step3, resumed, [tokens2, tokens2], ckpt, start_step=at
    )
    np.testing.assert_allclose(
        np.asarray(resumed["params"]["embed"]), ref_embed, rtol=1e-5, atol=1e-6
    )
    assert ckpt.latest_step() == 4
    ckpt.close()


def test_max_to_keep_prunes_old_steps(tmp_path):
    plan, cfg, state, step, tokens = _tiny_setup()
    ckpt = CheckpointManager(tmp_path / "keep", max_to_keep=2)
    for s in range(1, 5):
        state, _ = step(state, tokens)
        ckpt.save(s, state)
    ckpt.wait()
    assert ckpt.latest_step() == 4
    steps = sorted(int(p.name) for p in (tmp_path / "keep").iterdir() if p.name.isdigit())
    assert len(steps) <= 2 and 4 in steps
    ckpt.close()


def test_quantized_tree_round_trip(tmp_path):
    """Quantized serving weights (pure-array {"q","s"} trees, int8 AND
    group-wise int4) checkpoint and restore — the serving-restart path."""
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models import llama as L
    from kubeflow_tpu.models.quant import quantize_params

    cfg = L.LLAMA_CONFIGS["tiny"]
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    for bits, group in ((8, 128), (4, 32)):
        q = quantize_params(params, bits=bits, group=group)
        ckpt = CheckpointManager(tmp_path / f"ckpt{bits}")
        assert ckpt.save(1, q, force=True)
        ckpt.wait()
        template = jax.tree_util.tree_map(jnp.zeros_like, q)
        restored, at = ckpt.restore_latest(template)
        assert at == 1
        wq = restored["layers"]["wq"]
        assert wq["q"].dtype == (jnp.int8 if bits == 8 else jnp.int4)
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (1, 8), 0, cfg.vocab_size
        )
        ref = L.forward(q, cfg, tokens)
        got = L.forward(restored, cfg, tokens)
        assert float(jnp.max(jnp.abs(ref - got))) == 0.0
        ckpt.close()
