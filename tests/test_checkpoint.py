"""In-notebook checkpoint/resume: sharded save/restore + preemption replay,
plus the durability protocol (atomic commit, validated restore/quarantine,
SIGKILL/SIGTERM crash paths, exact data-loader cursor resume)."""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import threading
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models import llama as L
from kubeflow_tpu.models.train import (
    make_tiny_trainer,
    make_train_step,
    shard_state,
)
from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh
from kubeflow_tpu.runtime.checkpoint import (
    CORRUPT_PREFIX,
    CheckpointIO,
    CheckpointManager,
    _load_validated,
    resume_start_batch,
    train_with_checkpointing,
)

REPO_ROOT = Path(__file__).resolve().parent.parent


def _counter(counter) -> float:
    for metric in counter.collect():
        for sample in metric.samples:
            if sample.name.endswith("_total"):
                return sample.value
    return 0.0


def _run_losses(step_fn, state, batches):
    losses = []
    for batch in batches:
        state, loss = step_fn(state, batch)
        losses.append(float(loss))
    return state, losses


@pytest.fixture(scope="module")
def tiny_trainer():
    """One shared single-device trainer: the durability tests compare loss
    curves bit-for-bit, which needs every run to share one jitted step."""
    return make_tiny_trainer()


def _tiny_setup():
    plan = MeshPlan(make_mesh(fsdp=2, tp=2, sp=2, devices=jax.devices()[:8]))
    cfg = L.LLAMA_CONFIGS["tiny"]
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    init_state, step = make_train_step(cfg, plan)
    state = shard_state(plan, init_state(params))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 128), 0, cfg.vocab_size)
    return plan, cfg, state, step, tokens


def test_save_restore_round_trip(tmp_path):
    plan, cfg, state, step, tokens = _tiny_setup()
    state, _ = step(state, tokens)
    ckpt = CheckpointManager(tmp_path / "ckpt")
    assert ckpt.save(1, state)
    ckpt.wait()
    assert ckpt.latest_step() == 1

    # Restore into a fresh sharded template; must match exactly.
    params2 = L.init_params(cfg, jax.random.PRNGKey(42))
    init_state, _ = make_train_step(cfg, plan)
    template = shard_state(plan, init_state(params2))
    restored, at = ckpt.restore_latest(template)
    assert at == 1
    np.testing.assert_array_equal(
        np.asarray(restored["params"]["embed"]),
        np.asarray(state["params"]["embed"]),
    )
    assert int(restored["step"]) == int(state["step"])
    # Restored arrays keep the template's sharding (no host-0 gather).
    assert (
        restored["params"]["layers"]["wq"].sharding
        == template["params"]["layers"]["wq"].sharding
    )
    ckpt.close()


def test_restore_latest_without_checkpoint_returns_template(tmp_path):
    plan, cfg, state, step, tokens = _tiny_setup()
    ckpt = CheckpointManager(tmp_path / "empty")
    restored, at = ckpt.restore_latest(state)
    assert at is None and restored is state
    ckpt.close()


def test_preemption_resume_matches_uninterrupted_run(tmp_path):
    """Train 4 steps straight vs 2 steps + 'preemption' + restore + 2 steps:
    identical final params (determinism is what makes resume trustworthy)."""
    plan, cfg, state, step, tokens = _tiny_setup()

    # Uninterrupted reference run.
    ref = state
    for _ in range(4):
        ref, _ = step(ref, tokens)
    ref_embed = np.asarray(ref["params"]["embed"])

    # Interrupted run: checkpoint every step, die after 2.
    plan2, cfg2, state2, step2, tokens2 = _tiny_setup()
    ckpt = CheckpointManager(tmp_path / "resume")
    state2, _ = train_with_checkpointing(step2, state2, [tokens2, tokens2], ckpt)
    del state2  # the preemption

    # New process: fresh init, restore, continue.
    params3 = L.init_params(cfg2, jax.random.PRNGKey(7))
    init_state, step3 = make_train_step(cfg2, plan2)
    template = shard_state(plan2, init_state(params3))
    resumed, at = ckpt.restore_latest(template)
    assert at == 2
    resumed, _ = train_with_checkpointing(
        step3, resumed, [tokens2, tokens2], ckpt, start_step=at
    )
    np.testing.assert_allclose(
        np.asarray(resumed["params"]["embed"]), ref_embed, rtol=1e-5, atol=1e-6
    )
    assert ckpt.latest_step() == 4
    ckpt.close()


def test_max_to_keep_prunes_old_steps(tmp_path):
    plan, cfg, state, step, tokens = _tiny_setup()
    ckpt = CheckpointManager(tmp_path / "keep", max_to_keep=2)
    for s in range(1, 5):
        state, _ = step(state, tokens)
        ckpt.save(s, state)
    ckpt.wait()
    assert ckpt.latest_step() == 4
    steps = sorted(int(p.name) for p in (tmp_path / "keep").iterdir() if p.name.isdigit())
    assert len(steps) <= 2 and 4 in steps
    ckpt.close()


def test_quantized_tree_round_trip(tmp_path):
    """Quantized serving weights (pure-array {"q","s"} trees, int8 AND
    group-wise int4) checkpoint and restore — the serving-restart path."""
    import jax
    import jax.numpy as jnp

    from kubeflow_tpu.models import llama as L
    from kubeflow_tpu.models.quant import quantize_params

    cfg = L.LLAMA_CONFIGS["tiny"]
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    for bits, group in ((8, 128), (4, 32)):
        q = quantize_params(params, bits=bits, group=group)
        ckpt = CheckpointManager(tmp_path / f"ckpt{bits}")
        assert ckpt.save(1, q, force=True)
        ckpt.wait()
        template = jax.tree_util.tree_map(jnp.zeros_like, q)
        restored, at = ckpt.restore_latest(template)
        assert at == 1
        wq = restored["layers"]["wq"]
        assert wq["q"].dtype == (jnp.int8 if bits == 8 else jnp.int4)
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (1, 8), 0, cfg.vocab_size
        )
        ref = L.forward(q, cfg, tokens)
        got = L.forward(restored, cfg, tokens)
        assert float(jnp.max(jnp.abs(ref - got))) == 0.0
        ckpt.close()


# ---------------------------------------------------------------------------
# Durability: atomic commit, validated restore, crash paths


class _SimulatedKill(Exception):
    """Models SIGKILL between file writes: save() contains only OSError,
    so this abandons the staging dir exactly as a dead process would."""


def test_torn_save_invisible_and_resume_matches_uninterrupted(
    tmp_path, tiny_trainer
):
    """A crash mid-save (after the previous step committed) must leave the
    torn step invisible: restore falls back to the last committed step with
    NO quarantine, and the resumed loss curve equals the uninterrupted
    run's exactly."""
    step_fn, fresh_state, batches = tiny_trainer
    _, ref_losses = _run_losses(step_fn, fresh_state(0), batches)

    class KillerIO(CheckpointIO):
        armed = False
        writes = 0

        def write_file(self, path, data):
            if self.armed:
                self.writes += 1
                if self.writes > 2:
                    raise _SimulatedKill(path.name)
            super().write_file(path, data)

    io = KillerIO()
    ckpt = CheckpointManager(tmp_path / "torn", max_to_keep=10, io=io)
    state = fresh_state(0)
    with pytest.raises(_SimulatedKill):
        for i, batch in enumerate(batches):
            state, _ = step_fn(state, batch)
            if i + 1 == 3:
                io.armed = True
            ckpt.save(i + 1, state)
    torn = [p.name for p in (tmp_path / "torn").iterdir()
            if p.name.startswith(".tmp-")]
    assert torn, "the simulated kill must leave a torn staging dir"

    # "Restart": fresh manager, DIFFERENT init — only the checkpoint bytes
    # can make the resumed curve match.
    from kubeflow_tpu.metrics import Metrics

    m = Metrics()
    mgr2 = CheckpointManager(tmp_path / "torn", max_to_keep=10, metrics=m)
    assert mgr2.latest_step() == 2
    restored, at = mgr2.restore_latest(fresh_state(7))
    assert at == 2
    assert _counter(m.checkpoint_corrupt_total) == 0
    _, resumed = _run_losses(step_fn, restored, batches[at:])
    assert resumed == ref_losses[at:]


def test_restore_corrupt_newest_quarantines_and_falls_back(
    tmp_path, tiny_trainer
):
    """Bit-rot on the newest step: restore must quarantine it (counted by
    tpu_checkpoint_corrupt_total), restore the previous valid step, and
    resume with zero loss-curve divergence."""
    from kubeflow_tpu.metrics import Metrics

    step_fn, fresh_state, batches = tiny_trainer
    _, ref_losses = _run_losses(step_fn, fresh_state(0), batches)
    workdir = tmp_path / "rot"
    ckpt = CheckpointManager(workdir, max_to_keep=10)
    state = fresh_state(0)
    for i, batch in enumerate(batches):
        state, _ = step_fn(state, batch)
        ckpt.save(i + 1, state)
    newest = workdir / str(len(batches))
    victim = sorted(newest.glob("*.bin"))[0]
    blob = bytearray(victim.read_bytes())
    blob[len(blob) // 2] ^= 0xFF
    victim.write_bytes(bytes(blob))

    m = Metrics()
    mgr2 = CheckpointManager(workdir, max_to_keep=10, metrics=m)
    restored, at = mgr2.restore_latest(fresh_state(7))
    assert at == len(batches) - 1
    assert _counter(m.checkpoint_corrupt_total) == 1
    quarantined = [p.name for p in workdir.iterdir()
                   if p.name.startswith(CORRUPT_PREFIX)]
    assert len(quarantined) == 1
    assert quarantined[0].startswith(f"{CORRUPT_PREFIX}{len(batches)}-")
    assert not (workdir / str(len(batches))).exists()
    _, resumed = _run_losses(step_fn, restored, batches[at:])
    assert resumed == ref_losses[at:]


_SIGKILL_CHILD = """
import sys, time
sys.path.insert(0, sys.argv[2])
import numpy as np
from kubeflow_tpu.runtime.checkpoint import CheckpointIO, CheckpointManager

class SlowIO(CheckpointIO):
    def write_file(self, path, data):
        time.sleep(0.05)
        super().write_file(path, data)

mgr = CheckpointManager(
    sys.argv[1], io=SlowIO(), async_save=True, max_to_keep=100
)
step = 0
while True:
    step += 1
    state = {
        "b": np.full((8,), step * 0.5),
        "w": np.full((32, 32), float(step)),
    }
    mgr.save(step, state)
    time.sleep(0.01)
"""


@pytest.mark.skipif(os.name != "posix", reason="needs SIGKILL")
def test_sigkill_during_async_save_leaves_valid_latest(tmp_path):
    """The real thing: a child process checkpointing asynchronously is
    SIGKILLed mid-stream. EVERY committed step must still validate
    (manifest sizes + CRC32s), and restore must hand back a consistent
    state — w == full(step), b == step * 0.5 — for the step it reports."""
    ckpt_dir = tmp_path / "sigkill"
    child = subprocess.Popen(
        [sys.executable, "-c", _SIGKILL_CHILD, str(ckpt_dir), str(REPO_ROOT)],
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    try:
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if ckpt_dir.exists():
                committed = [
                    p for p in ckpt_dir.iterdir()
                    if p.name.isdigit() and (p / "manifest.json").exists()
                ]
                if len(committed) >= 2:
                    break
            if child.poll() is not None:
                raise AssertionError(
                    f"child died early (rc={child.returncode})"
                )
            time.sleep(0.01)
        else:
            raise AssertionError("child never committed 2 checkpoints")
        time.sleep(0.02)  # land the kill mid-write of a later step
    finally:
        child.kill()
        child.wait()

    # Every committed dir must validate whole — the atomic-commit claim.
    committed = sorted(
        int(p.name) for p in ckpt_dir.iterdir()
        if p.name.isdigit() and (p / "manifest.json").exists()
    )
    assert committed, "at least one committed step must exist"
    for step in committed:
        _load_validated(ckpt_dir / str(step))  # raises if torn

    from kubeflow_tpu.metrics import Metrics

    m = Metrics()
    mgr = CheckpointManager(ckpt_dir, max_to_keep=100, metrics=m)
    template = {"b": np.zeros((8,)), "w": np.zeros((32, 32))}
    restored, at = mgr.restore_latest(template)
    assert at == committed[-1]
    assert _counter(m.checkpoint_corrupt_total) == 0
    np.testing.assert_array_equal(restored["w"], np.full((32, 32), float(at)))
    np.testing.assert_array_equal(restored["b"], np.full((8,), at * 0.5))


def test_save_interval_skips_but_records_pending(tmp_path):
    # Orbax-compatible cadence: multiples of the interval commit, the
    # first call always commits, everything else is skipped-but-pending.
    ckpt = CheckpointManager(tmp_path / "iv", save_interval_steps=2)
    assert ckpt.save(1, {"w": np.zeros(4)})  # first call
    assert ckpt.save(2, {"w": np.ones(4)})
    assert not ckpt.save(3, {"w": np.full(4, 3.0)})
    assert ckpt.save(4, {"w": np.full(4, 4.0)})
    assert ckpt.latest_step() == 4
    # The skipped step was still recorded for the emergency path.
    assert not ckpt.save(5, {"w": np.full(4, 5.0)})
    assert ckpt.emergency_save()
    assert ckpt.latest_step() == 5


def test_sigterm_emergency_save_commits_then_skips_fresh(tmp_path):
    """bootstrap.install_preemption_handler: SIGTERM triggers one final
    synchronous save of the newest pending state, chains to the previous
    handler, and a second SIGTERM with nothing new to save skips."""
    from kubeflow_tpu.metrics import Metrics
    from kubeflow_tpu.runtime.bootstrap import install_preemption_handler

    m = Metrics()
    ckpt = CheckpointManager(
        tmp_path / "em", save_interval_steps=100, metrics=m
    )
    assert ckpt.save(1, {"w": np.arange(16.0)})
    latest_state = {"w": np.arange(16.0) * 2}
    assert not ckpt.save(2, latest_state)  # interval-skipped, but pending

    received = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: received.append(s))
    try:
        uninstall = install_preemption_handler(
            ckpt, env={"TPU_CHECKPOINT_GRACE_S": "60"}
        )
        signal.raise_signal(signal.SIGTERM)
        assert ckpt.latest_step() == 2
        assert _counter(m.checkpoint_emergency_total) == 1
        assert received == [signal.SIGTERM], "must chain to prior handler"
        restored, at = ckpt.restore_latest({"w": np.zeros(16)})
        assert at == 2
        np.testing.assert_array_equal(restored["w"], latest_state["w"])

        signal.raise_signal(signal.SIGTERM)  # fresh save exists -> skip
        assert _counter(m.checkpoint_emergency_total) == 1
        assert received == [signal.SIGTERM, signal.SIGTERM]

        uninstall()
        signal.raise_signal(signal.SIGTERM)  # handler restored: no saves
        assert received == [signal.SIGTERM] * 3
        assert _counter(m.checkpoint_emergency_total) == 1
    finally:
        signal.signal(signal.SIGTERM, prev)


def test_emergency_save_skips_when_budget_too_small(tmp_path):
    """A save that cannot finish inside the grace budget is SKIPPED —
    starting a save SIGKILL will tear only wastes the window."""
    ckpt = CheckpointManager(tmp_path / "budget", save_interval_steps=100)
    assert ckpt.save(1, {"w": np.zeros(4)})
    ckpt._last_save_duration = 999.0  # a save this size takes "forever"
    assert not ckpt.save(2, {"w": np.ones(4)})
    assert ckpt.emergency_save(grace_s=1.0) is False
    assert ckpt.latest_step() == 1


class _FakeClock:
    """Injectable monotonic clock: advances only when the test says so."""

    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def test_injected_clock_is_the_single_time_source(tmp_path):
    """Every freshness/duration figure comes off the injected clock, so a
    wall-clock jump (NTP step, suspend/resume — preemption windows love
    these) cannot skew them."""
    clk = _FakeClock()
    ckpt = CheckpointManager(tmp_path / "clk", clock=clk)
    assert ckpt.last_commit_age() == float("inf")  # nothing committed yet
    assert ckpt.save(1, {"w": np.zeros(4)})
    assert ckpt.last_commit_age() == 0.0
    clk.advance(12.5)
    assert ckpt.last_commit_age() == 12.5
    # The save's measured duration is fake-clock elapsed (zero), even
    # though real wall time passed while the bytes hit disk.
    assert ckpt._last_save_duration == 0.0
    clk.advance(-100.0)  # monotonic source misused backwards: clamp, not negative
    assert ckpt.last_commit_age() == 0.0


def test_emergency_budget_counts_on_injected_clock(tmp_path):
    """Grace accounting reads ONLY the injected clock. The real wall clock
    advances by orders of magnitude more than this 1ms budget while the
    save runs, so if any budget arithmetic still read the wall clock the
    save would be mis-skipped as over budget."""
    clk = _FakeClock()
    ckpt = CheckpointManager(
        tmp_path / "jump", save_interval_steps=100, clock=clk
    )
    assert ckpt.save(1, {"w": np.zeros(4)})
    assert not ckpt.save(2, {"w": np.ones(4)})  # gated by interval; pending
    assert ckpt.emergency_save(grace_s=0.001) is True
    assert ckpt.latest_step() == 2


def test_inherited_step_age_is_unknown_until_restore(tmp_path):
    """A step found on disk at construction has no trustworthy monotonic
    age (mtimes are wall time): last_commit_age() says +inf so freshness-
    gated callers save rather than trust. A validating restore is the
    moment the bytes are vouched for, and stamps freshness."""
    d = tmp_path / "inherit"
    first = CheckpointManager(d)
    assert first.save(1, {"w": np.arange(4.0)})

    clk = _FakeClock(1000.0)
    second = CheckpointManager(d, clock=clk)
    assert second.latest_step() == 1
    assert second.last_commit_age() == float("inf")
    state, step = second.restore_latest({"w": np.zeros(4)})
    assert step == 1
    assert second.last_commit_age() == 0.0
    clk.advance(3.0)
    assert second.last_commit_age() == 3.0


def test_save_failure_is_contained_and_recovers(tmp_path):
    """ENOSPC mid-training: save() returns False (never raises), cleans
    its staging dir, keeps the previous step restorable, and commits again
    once space returns."""
    import errno

    class FullDiskIO(CheckpointIO):
        full = False

        def write_file(self, path, data):
            if self.full:
                raise OSError(errno.ENOSPC, "No space left on device")
            super().write_file(path, data)

    io = FullDiskIO()
    ckpt = CheckpointManager(tmp_path / "enospc", io=io)
    assert ckpt.save(1, {"w": np.zeros(8)})
    io.full = True
    assert ckpt.save(2, {"w": np.ones(8)}) is False
    assert ckpt.save_failures == 1
    assert ckpt.last_save_error is not None
    assert ckpt.latest_step() == 1
    assert not [p for p in (tmp_path / "enospc").iterdir()
                if p.name.startswith(".tmp-")]
    io.full = False
    assert ckpt.save(3, {"w": np.full(8, 3.0)})
    assert ckpt.latest_step() == 3


def test_train_loop_flushes_async_saves_on_exception(tmp_path):
    """An exception mid-loop must not strand enqueued async saves: the
    finally-wait flushes step 1 before the exception propagates."""

    class Boom(RuntimeError):
        pass

    def step_fn(state, batch):
        if batch == "boom":
            raise Boom()
        return state + 1, np.float32(batch)

    ckpt = CheckpointManager(tmp_path / "flush", async_save=True)
    with pytest.raises(Boom):
        train_with_checkpointing(step_fn, 0, [1.0, "boom", 3.0], ckpt)
    assert ckpt.latest_step() == 1
    ckpt.close()


def test_train_loop_tolerates_empty_batches(tmp_path):
    def step_fn(state, batch):  # pragma: no cover - never called
        raise AssertionError("no batches, no steps")

    ckpt = CheckpointManager(tmp_path / "empty")
    state, losses = train_with_checkpointing(step_fn, 5, [], ckpt)
    assert state == 5 and losses == []


def test_async_worker_survives_unserializable_metadata(tmp_path):
    """A save whose metadata json.dumps cannot serialize must not kill the
    worker thread: the failure is recorded, the queue still drains (wait()
    and close() never hang), and the NEXT save commits."""
    ckpt = CheckpointManager(tmp_path / "poison", async_save=True)
    assert ckpt.save(1, {"w": np.zeros(4)}, metadata={"bad": object()})
    assert ckpt.wait(timeout=30)
    assert ckpt.save_failures == 1
    assert isinstance(ckpt.last_save_error, TypeError)
    assert ckpt.latest_step() is None
    assert ckpt.save(2, {"w": np.ones(4)})
    assert ckpt.wait(timeout=30)
    assert ckpt.latest_step() == 2
    ckpt.close()


def _dead_thread():
    t = threading.Thread(target=lambda: None)
    t.start()
    t.join()
    return t


def test_ensure_worker_restarts_dead_worker(tmp_path):
    """Belt and braces for worker death _drain cannot guard: save() must
    restart the worker instead of enqueueing to nobody."""
    ckpt = CheckpointManager(tmp_path / "dead", async_save=True)
    assert ckpt.save(1, {"w": np.zeros(4)})
    assert ckpt.wait(timeout=30)
    ckpt.close()  # retire the real worker; then fake one that died
    ckpt._worker = _dead_thread()
    assert ckpt.save(2, {"w": np.ones(4)})
    assert ckpt.wait(timeout=30)
    assert ckpt.latest_step() == 2
    ckpt.close()


def test_wait_reports_dead_worker_instead_of_hanging(tmp_path):
    """wait()/close() on a queue nobody drains must fail fast, not block
    forever in queue.join()."""
    import queue as queue_mod

    ckpt = CheckpointManager(tmp_path / "wedge", async_save=True)
    assert ckpt.save(1, {"w": np.zeros(4)})
    assert ckpt.wait(timeout=30)
    ckpt.close()  # retire the real worker, then fake a wedged state:
    ckpt._queue = queue_mod.Queue()
    ckpt._queue.put((2, [("['w']", np.ones(4))], {}))  # nobody drains this
    ckpt._worker = _dead_thread()
    t0 = time.monotonic()
    assert ckpt.wait(timeout=30) is False
    assert ckpt.wait() is False
    assert time.monotonic() - t0 < 5.0
    ckpt.close()  # must not hang either


def test_emergency_save_survives_held_queue_mutex(tmp_path):
    """SIGTERM can land while the interrupted thread is INSIDE
    queue.Queue.put, holding the queue's non-reentrant mutex. The
    emergency drain is time-bounded, so the newest pending state still
    commits well inside the grace budget."""
    ckpt = CheckpointManager(
        tmp_path / "mutex", async_save=True, save_interval_steps=100
    )
    assert ckpt.save(1, {"w": np.zeros(4)})
    assert ckpt.wait(timeout=30)
    assert not ckpt.save(2, {"w": np.ones(4)})  # pending only
    t0 = time.monotonic()
    with ckpt._queue.mutex:  # what an interrupted put() looks like
        assert ckpt.emergency_save(grace_s=4.0)
    assert time.monotonic() - t0 < 4.0
    assert ckpt.latest_step() == 2
    ckpt.close()


def test_sigterm_handler_defers_save_to_thread(tmp_path):
    """install_preemption_handler must not run queue operations in signal
    context: with the queue mutex held by the 'interrupted' code, the
    deferred emergency save still commits and the handler still chains."""
    from kubeflow_tpu.runtime.bootstrap import install_preemption_handler

    ckpt = CheckpointManager(
        tmp_path / "sig", async_save=True, save_interval_steps=100
    )
    assert ckpt.save(1, {"w": np.zeros(4)})
    assert ckpt.wait(timeout=30)
    assert not ckpt.save(2, {"w": np.full(4, 2.0)})
    received = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: received.append(s))
    try:
        uninstall = install_preemption_handler(
            ckpt, env={"TPU_CHECKPOINT_GRACE_S": "4"}
        )
        with ckpt._queue.mutex:
            signal.raise_signal(signal.SIGTERM)
        assert received == [signal.SIGTERM], "must chain to prior handler"
        assert ckpt.latest_step() == 2
        uninstall()
    finally:
        signal.signal(signal.SIGTERM, prev)
    ckpt.close()


def test_ml_dtypes_round_trip_and_unknown_dtype_is_corruption(tmp_path):
    """bfloat16 resolves through the lazy ml_dtypes fallback (numpy's
    string lookup raises TypeError on it), and a manifest naming a dtype
    nobody knows is CORRUPTION — quarantine + fall back, never a crash."""
    import ml_dtypes

    from kubeflow_tpu.metrics import Metrics

    workdir = tmp_path / "mldt"
    ckpt = CheckpointManager(workdir)
    assert ckpt.save(1, {"w": np.arange(8, dtype=ml_dtypes.bfloat16)})
    assert ckpt.save(2, {"w": np.ones(8, dtype=ml_dtypes.bfloat16)}, force=True)
    restored, at = ckpt.restore_latest(
        {"w": np.zeros(8, dtype=ml_dtypes.bfloat16)}
    )
    assert at == 2
    assert restored["w"].dtype == ml_dtypes.bfloat16

    manifest_path = workdir / "2" / "manifest.json"
    blob = json.loads(manifest_path.read_text())
    for entry in blob["files"]:
        entry["dtype"] = "definitely-not-a-dtype"
    manifest_path.write_text(json.dumps(blob))
    m = Metrics()
    mgr2 = CheckpointManager(workdir, metrics=m)
    restored, at = mgr2.restore_latest(
        {"w": np.zeros(8, dtype=ml_dtypes.bfloat16)}
    )
    assert at == 1
    assert _counter(m.checkpoint_corrupt_total) == 1
    np.testing.assert_array_equal(
        np.asarray(restored["w"], dtype=np.float32),
        np.arange(8, dtype=np.float32),
    )


def test_restored_numpy_leaves_are_writable(tmp_path):
    """np.frombuffer views are read-only; the restored state must be as
    mutable as the state that was saved."""
    ckpt = CheckpointManager(tmp_path / "rw")
    assert ckpt.save(1, {"w": np.arange(4.0)})
    restored, at = ckpt.restore_latest({"w": np.zeros(4)})
    assert at == 1
    assert restored["w"].flags.writeable
    restored["w"] += 1.0
    np.testing.assert_array_equal(restored["w"], np.arange(4.0) + 1.0)


# ---------------------------------------------------------------------------
# Multi-host: per-process roots, addressable-shard serialization


class _FakeGlobalArray:
    """A jax.Array spanning non-addressable devices, as one process sees
    it: np.asarray on it is exactly the multi-host crash the snapshot
    must never trigger."""

    is_fully_addressable = False

    def __init__(self, arr):
        self._arr = arr
        self.shape = arr.shape
        self.dtype = arr.dtype

    @property
    def addressable_shards(self):
        return self._arr.addressable_shards

    def __array__(self, *args, **kwargs):
        raise RuntimeError("np.asarray on a non-addressable jax.Array")


def _sharded_test_array():
    from jax.sharding import NamedSharding, PartitionSpec

    mesh = jax.sharding.Mesh(np.array(jax.devices()[:8]), ("x",))
    data = np.arange(64, dtype=np.float32).reshape(8, 8)
    arr = jax.device_put(data, NamedSharding(mesh, PartitionSpec("x", None)))
    return data, arr


def test_multihost_sharded_save_and_restore_via_sharding_tree(tmp_path):
    """Non-fully-addressable leaves are saved as this process's
    addressable shards — never gathered to one host — and restored
    straight into the template's sharding via
    make_array_from_single_device_arrays."""
    data, arr = _sharded_test_array()
    root = tmp_path / "mh"
    managers = [
        CheckpointManager(root, process_index=k, process_count=2)
        for k in range(2)
    ]
    state = {"step": np.int64(3), "w": _FakeGlobalArray(arr)}
    for mgr in managers:
        assert mgr.save(1, state)
    assert (root / "proc0" / "1" / "manifest.json").exists()
    assert (root / "proc1" / "1" / "manifest.json").exists()

    template = {
        "step": np.int64(0),
        "w": jax.device_put(np.zeros_like(data), arr.sharding),
    }
    restored, at = managers[0].restore_latest(template)
    assert at == 1
    assert restored["w"].sharding == arr.sharding
    np.testing.assert_array_equal(np.asarray(restored["w"]), data)
    assert int(restored["step"]) == 3

    # A plain template assembles a dense host array (validation tooling).
    dense, at = managers[1].restore_latest(
        {"step": np.int64(0), "w": np.zeros_like(data)}
    )
    assert at == 1
    np.testing.assert_array_equal(dense["w"], data)
    for mgr in managers:
        mgr.close()


def test_multihost_step_requires_every_process_commit(tmp_path):
    """A step only one host committed (the other died mid-save) is NOT
    restorable: latest_step/restore intersect across the proc roots, so
    every survivor falls back to the same fully-committed step."""
    root = tmp_path / "partial"
    m0 = CheckpointManager(root, process_index=0, process_count=2)
    m1 = CheckpointManager(root, process_index=1, process_count=2)
    assert m0.save(1, {"w": np.arange(4.0)})
    assert m1.save(1, {"w": np.arange(4.0)})
    assert m0.save(2, {"w": np.ones(4)})  # host 1 "died" before step 2
    assert m0.latest_step() == 1 and m1.latest_step() == 1
    for mgr in (m0, m1):
        restored, at = mgr.restore_latest({"w": np.zeros(4)})
        assert at == 1
        np.testing.assert_array_equal(restored["w"], np.arange(4.0))


def test_multihost_quarantine_breaks_global_commit(tmp_path):
    """Bit-rot on one host's copy quarantines it there AND removes the
    step from every later restore's intersection — no cross-host
    divergence on the fallback step."""
    root = tmp_path / "mq"
    m0 = CheckpointManager(root, process_index=0, process_count=2)
    m1 = CheckpointManager(root, process_index=1, process_count=2)
    for s in (1, 2):
        assert m0.save(s, {"w": np.full(4, float(s))}, force=True)
        assert m1.save(s, {"w": np.full(4, float(s))}, force=True)
    victim = next((root / "proc0" / "2").glob("*.bin"))
    blob = bytearray(victim.read_bytes())
    blob[0] ^= 0xFF
    victim.write_bytes(bytes(blob))

    restored, at = m0.restore_latest({"w": np.zeros(4)})
    assert at == 1
    restored, at = m1.restore_latest({"w": np.zeros(4)})
    assert at == 1
    np.testing.assert_array_equal(restored["w"], np.full(4, 1.0))


def test_single_process_manager_rejects_nonaddressable_state(tmp_path):
    """Without multi-host identity, saving a non-addressable array must
    fail with instructions — not crash later inside np.asarray."""
    data, arr = _sharded_test_array()
    ckpt = CheckpointManager(tmp_path / "lone")
    with pytest.raises(RuntimeError, match="process_count"):
        ckpt.save(1, {"w": _FakeGlobalArray(arr)})


def test_process_identity_from_webhook_env(tmp_path):
    """The webhook's TPU env contract places each host in its own proc
    root without the notebook passing anything explicitly."""
    env = {"TPU_WORKER_ID": "1", "TPU_WORKER_HOSTNAMES": "h0,h1"}
    ckpt = CheckpointManager(tmp_path / "envd", env=env)
    assert (ckpt.process_index, ckpt.process_count) == (1, 2)
    assert ckpt.save(1, {"w": np.zeros(2)})
    assert (tmp_path / "envd" / "proc1" / "1" / "manifest.json").exists()
    # Not restorable until proc0 commits the step too.
    assert ckpt.latest_step() is None


def test_checkpoint_metadata_carries_loader_cursor(tmp_path, tiny_trainer):
    """train_with_checkpointing persists {"start_batch": step}; restore
    hands it back so sharded_loader(start_batch=...) resumes exactly."""
    step_fn, fresh_state, batches = tiny_trainer
    ckpt = CheckpointManager(tmp_path / "cursor")
    train_with_checkpointing(step_fn, fresh_state(0), batches[:2], ckpt)

    mgr2 = CheckpointManager(tmp_path / "cursor")
    _, at = mgr2.restore_latest(fresh_state(7))
    assert at == 2
    assert mgr2.restored_metadata == {"start_batch": 2}
    assert resume_start_batch(mgr2, at) == 2
    # A checkpoint without the cursor (older writer) falls back to the
    # restored step — the one-batch-per-step convention.
    empty = CheckpointManager(tmp_path / "other")
    assert resume_start_batch(empty, 5) == 5
