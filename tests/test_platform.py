"""Platform-reconciler integration tests (ODH tier: reference
odh notebook_controller_test.go ~7.1k LoC of Ginkgo specs, distilled)."""

import base64
import json

import pytest

from kubeflow_tpu.api import annotations as ann
from kubeflow_tpu.controller.platform import FINALIZER
from kubeflow_tpu.k8s import objects as obj_util

from tests.harness import cpu_notebook, make_env, tpu_notebook

CENTRAL = "opendatahub"


def make_platform_env(**kw):
    return make_env(webhooks=True, platform=True, **kw)


class TestLifecycle:
    def test_finalizer_added_and_lock_released(self):
        env = make_platform_env()
        env.cluster.create(tpu_notebook())
        env.manager.run_until_idle()
        nb = env.cluster.get("Notebook", "nb", "ns")
        assert FINALIZER in nb["metadata"]["finalizers"]
        # Lock released once platform resources exist → slice started.
        assert ann.STOP not in nb["metadata"].get("annotations", {})
        assert env.cluster.get("StatefulSet", "nb", "ns")["spec"]["replicas"] == 4
        assert nb["status"]["tpu"]["sliceHealth"] == "Healthy"

    def test_lock_held_until_pull_secret_minted(self):
        """Reference notebook_controller.go:155-186: the lock must not
        release before the pod ServiceAccount carries its image-pull
        secret — releasing early races the registry pull against the
        token controller and lands in ImagePullBackOff."""
        env = make_platform_env(sa_pull_secrets=False)
        env.cluster.create(tpu_notebook())
        env.manager.run_until_idle()
        nb = env.cluster.get("Notebook", "nb", "ns")
        # No "default" SA with a pull secret exists: lock stays held and
        # the slice stays stopped.
        assert nb["metadata"]["annotations"][ann.STOP] == (
            ann.RECONCILIATION_LOCK_VALUE
        )
        assert env.cluster.get("StatefulSet", "nb", "ns")["spec"]["replicas"] == 0

        # Token controller catches up: SA appears with its pull secret.
        env.cluster.create({
            "apiVersion": "v1",
            "kind": "ServiceAccount",
            "metadata": {"name": "default", "namespace": "ns"},
            "imagePullSecrets": [{"name": "default-dockercfg"}],
        })
        env.manager.tick(3.0)  # fire the pull-secret requeue
        nb = env.cluster.get("Notebook", "nb", "ns")
        assert ann.STOP not in nb["metadata"].get("annotations", {})
        assert env.cluster.get("StatefulSet", "nb", "ns")["spec"]["replicas"] == 4

    def test_user_stop_annotation_survives_platform_reconcile(self):
        env = make_platform_env()
        env.cluster.create(tpu_notebook())
        env.manager.run_until_idle()
        nb = env.cluster.get("Notebook", "nb", "ns")
        obj_util.annotations_of(nb)[ann.STOP] = "2026-07-29T10:00:00Z"
        env.cluster.update(nb)
        env.manager.run_until_idle()
        nb = env.cluster.get("Notebook", "nb", "ns")
        # A user stop (timestamp value) must NOT be treated as the lock.
        assert nb["metadata"]["annotations"][ann.STOP] == "2026-07-29T10:00:00Z"
        assert env.cluster.get("StatefulSet", "nb", "ns")["spec"]["replicas"] == 0


class TestRouting:
    def test_httproute_in_central_namespace(self):
        env = make_platform_env()
        env.cluster.create(cpu_notebook())
        env.manager.run_until_idle()
        route = env.cluster.get("HTTPRoute", "nb-ns-nb", CENTRAL)
        rule = route["spec"]["rules"][0]
        assert rule["matches"][0]["path"]["value"] == "/notebook/ns/nb"
        assert rule["backendRefs"][0] == {"name": "nb", "namespace": "ns", "port": 80}
        assert route["spec"]["parentRefs"][0]["name"] == "data-science-gateway"

    def test_reference_grant_created_per_namespace(self):
        env = make_platform_env()
        env.cluster.create(cpu_notebook())
        env.manager.run_until_idle()
        grant = env.cluster.get("ReferenceGrant", "notebook-httproute-access", "ns")
        assert grant["spec"]["from"][0]["namespace"] == CENTRAL
        assert grant["spec"]["to"][0]["kind"] == "Service"

    def test_route_recreated_if_deleted(self):
        env = make_platform_env()
        env.cluster.create(cpu_notebook())
        env.manager.run_until_idle()
        env.cluster.delete("HTTPRoute", "nb-ns-nb", CENTRAL)
        env.manager.run_until_idle()
        assert env.cluster.exists("HTTPRoute", "nb-ns-nb", CENTRAL)


class TestAuthMode:
    def test_auth_bundle_created(self):
        env = make_platform_env()
        env.cluster.create(cpu_notebook(annotations={ann.INJECT_AUTH: "true"}))
        env.manager.run_until_idle()
        assert env.cluster.exists("ServiceAccount", "nb-auth-proxy", "ns")
        svc = env.cluster.get("Service", "nb-kube-rbac-proxy", "ns")
        assert svc["metadata"]["annotations"][
            "service.beta.openshift.io/serving-cert-secret-name"
        ] == "nb-tls"
        cm = env.cluster.get("ConfigMap", "nb-kube-rbac-proxy-config", "ns")
        config = json.loads(cm["data"]["config-file.yaml"])
        attrs = config["authorization"]["resourceAttributes"]
        assert attrs["resource"] == "notebooks"
        assert attrs["name"] == "nb"
        crb = env.cluster.get("ClusterRoleBinding", "ns-nb-auth-delegator")
        assert crb["roleRef"]["name"] == "system:auth-delegator"
        route = env.cluster.get("HTTPRoute", "nb-ns-nb", CENTRAL)
        assert route["spec"]["rules"][0]["backendRefs"][0]["port"] == 8443

    def test_mode_switch_auth_to_plain_cleans_up(self):
        env = make_platform_env()
        env.cluster.create(cpu_notebook(annotations={ann.INJECT_AUTH: "true"}))
        env.manager.run_until_idle()
        nb = env.cluster.get("Notebook", "nb", "ns")
        del nb["metadata"]["annotations"][ann.INJECT_AUTH]
        env.cluster.update(nb)
        env.manager.run_until_idle()
        assert not env.cluster.exists("ServiceAccount", "nb-auth-proxy", "ns")
        assert not env.cluster.exists("Service", "nb-kube-rbac-proxy", "ns")
        assert not env.cluster.exists("ClusterRoleBinding", "ns-nb-auth-delegator")
        route = env.cluster.get("HTTPRoute", "nb-ns-nb", CENTRAL)
        assert route["spec"]["rules"][0]["backendRefs"][0]["port"] == 80


class TestNetworkPolicies:
    def test_policies_for_multi_host_slice(self):
        env = make_platform_env()
        env.cluster.create(tpu_notebook())
        env.manager.run_until_idle()
        ctrl = env.cluster.get("NetworkPolicy", "nb-ctrl-np", "ns")
        ingress = ctrl["spec"]["ingress"][0]
        assert ingress["ports"][0]["port"] == 8888
        assert (
            ingress["from"][0]["namespaceSelector"]["matchLabels"][
                "kubernetes.io/metadata.name"
            ]
            == CENTRAL
        )
        assert env.cluster.exists("NetworkPolicy", "nb-kube-rbac-proxy-np", "ns")
        slice_np = env.cluster.get("NetworkPolicy", "nb-slice-np", "ns")
        peer = slice_np["spec"]["ingress"][0]["from"][0]
        assert peer["podSelector"]["matchLabels"]["statefulset"] == "nb"

    def test_no_slice_policy_for_cpu_notebook(self):
        env = make_platform_env()
        env.cluster.create(cpu_notebook())
        env.manager.run_until_idle()
        assert not env.cluster.exists("NetworkPolicy", "nb-slice-np", "ns")


class TestDeletion:
    def test_full_cleanup_on_delete(self):
        env = make_platform_env()
        env.cluster.create(cpu_notebook(annotations={ann.INJECT_AUTH: "true"}))
        env.manager.run_until_idle()
        # Legacy OAuthClient from a pre-3.0 install.
        env.cluster.create(
            {
                "apiVersion": "oauth.openshift.io/v1",
                "kind": "OAuthClient",
                "metadata": {"name": "nb-ns-oauth-client"},
            }
        )
        env.cluster.delete("Notebook", "nb", "ns")
        env.manager.run_until_idle()
        assert not env.cluster.exists("Notebook", "nb", "ns")
        assert not env.cluster.exists("HTTPRoute", "nb-ns-nb", CENTRAL)
        assert not env.cluster.exists("ReferenceGrant", "notebook-httproute-access", "ns")
        assert not env.cluster.exists("ClusterRoleBinding", "ns-nb-auth-delegator")
        assert not env.cluster.exists("OAuthClient", "nb-ns-oauth-client")

    def test_reference_grant_kept_while_other_notebook_lives(self):
        env = make_platform_env()
        env.cluster.create(cpu_notebook(name="nb-a"))
        env.cluster.create(cpu_notebook(name="nb-b"))
        env.manager.run_until_idle()
        env.cluster.delete("Notebook", "nb-a", "ns")
        env.manager.run_until_idle()
        assert env.cluster.exists("ReferenceGrant", "notebook-httproute-access", "ns")
        env.cluster.delete("Notebook", "nb-b", "ns")
        env.manager.run_until_idle()
        assert not env.cluster.exists("ReferenceGrant", "notebook-httproute-access", "ns")


class TestCaBundle:
    def test_bundle_built_from_sources_with_pem_validation(self):
        env = make_platform_env()
        pem = (
            "-----BEGIN CERTIFICATE-----\nMIIBBB==\n-----END CERTIFICATE-----"
        )
        env.cluster.create(
            {
                "apiVersion": "v1",
                "kind": "ConfigMap",
                "metadata": {"name": "odh-trusted-ca-bundle", "namespace": CENTRAL},
                "data": {"ca-bundle.crt": pem + "\ngarbage-not-pem"},
            }
        )
        env.cluster.create(cpu_notebook())
        env.manager.run_until_idle()
        cm = env.cluster.get("ConfigMap", "workbench-trusted-ca-bundle", "ns")
        assert pem in cm["data"]["ca-bundle.crt"]
        assert "garbage" not in cm["data"]["ca-bundle.crt"]
        # Webhook mounts it on the next notebook update (stopped or created).
        env.cluster.create(cpu_notebook(name="nb2"))
        env.manager.run_until_idle()
        from kubeflow_tpu.api.notebook import Notebook

        nb2 = Notebook(env.cluster.get("Notebook", "nb2", "ns"))
        mounts = nb2.primary_container().get("volumeMounts", [])
        assert any(m["name"] == "trusted-ca" for m in mounts)


class TestRuntimeImagesAndPipelines:
    def _runtime_imagestream(self, env):
        env.cluster.create(
            {
                "apiVersion": "image.openshift.io/v1",
                "kind": "ImageStream",
                "metadata": {
                    "name": "datascience-runtime",
                    "namespace": CENTRAL,
                    "labels": {"opendatahub.io/runtime-image": "true"},
                    "annotations": {
                        "opendatahub.io/runtime-image-name": "Data Science 2026a"
                    },
                },
                "status": {
                    "tags": [
                        {"tag": "latest", "items": [{"dockerImageReference": "reg/rt@sha256:1"}]}
                    ]
                },
            }
        )

    def test_runtime_images_synced_to_user_namespace(self):
        env = make_platform_env()
        self._runtime_imagestream(env)
        env.cluster.create(cpu_notebook())
        env.manager.run_until_idle()
        cm = env.cluster.get("ConfigMap", "pipeline-runtime-images", "ns")
        key = "data-science-2026a.json"
        assert key in cm["data"]
        assert json.loads(cm["data"][key])["metadata"]["image_name"] == "reg/rt@sha256:1"

    def test_elyra_secret_from_dspa(self):
        from kubeflow_tpu.controller.platform import PlatformConfig

        env = make_platform_env(
            platform_config=PlatformConfig(set_pipeline_secret=True)
        )
        env.cluster.create(
            {
                "apiVersion": "datasciencepipelinesapplications.opendatahub.io/v1",
                "kind": "DataSciencePipelinesApplication",
                "metadata": {"name": "dspa", "namespace": "ns"},
                "spec": {
                    "objectStorage": {
                        "externalStorage": {
                            "host": "s3.example",
                            "bucket": "pipelines",
                            "s3CredentialsSecret": {"secretName": "s3-creds"},
                        }
                    }
                },
            }
        )
        env.cluster.create(
            {
                "apiVersion": "v1",
                "kind": "Secret",
                "metadata": {"name": "s3-creds", "namespace": "ns"},
                "data": {
                    "AWS_ACCESS_KEY_ID": base64.b64encode(b"ak").decode(),
                    "AWS_SECRET_ACCESS_KEY": base64.b64encode(b"sk").decode(),
                },
            }
        )
        env.cluster.create(cpu_notebook())
        env.manager.run_until_idle()
        secret = env.cluster.get("Secret", "ds-pipeline-config", "ns")
        config = json.loads(secret["stringData"]["odh_dsp.json"])
        assert config["metadata"]["cos_bucket"] == "pipelines"
        assert config["schema_name"] == "kfp"
        # Owned by the DSPA, not the notebook (survives notebook deletion).
        owner = secret["metadata"]["ownerReferences"][0]
        assert owner["kind"] == "DataSciencePipelinesApplication"

    def test_pipeline_rbac_when_role_exists(self):
        from kubeflow_tpu.controller.platform import PlatformConfig

        env = make_platform_env(platform_config=PlatformConfig(set_pipeline_rbac=True))
        env.cluster.create(
            {
                "apiVersion": "rbac.authorization.k8s.io/v1",
                "kind": "Role",
                "metadata": {"name": "ds-pipeline-user-access-dspa", "namespace": "ns"},
            }
        )
        env.cluster.create(cpu_notebook())
        env.manager.run_until_idle()
        rb = env.cluster.get("RoleBinding", "elyra-pipelines-nb", "ns")
        assert rb["roleRef"]["name"] == "ds-pipeline-user-access-dspa"


class TestMlflow:
    def test_requeues_until_cluster_role_appears(self):
        from kubeflow_tpu.controller.platform import PlatformConfig

        env = make_platform_env(platform_config=PlatformConfig(mlflow_enabled=True))
        env.cluster.create(
            cpu_notebook(annotations={ann.MLFLOW_INSTANCE: "tracking"})
        )
        env.manager.run_until_idle()
        assert not env.cluster.exists("RoleBinding", "mlflow-nb", "ns")
        assert env.manager.next_requeue_in() is not None
        env.cluster.create(
            {
                "apiVersion": "rbac.authorization.k8s.io/v1",
                "kind": "ClusterRole",
                "metadata": {"name": "mlflow-operator-mlflow-integration"},
            }
        )
        env.manager.tick(31.0)
        assert env.cluster.exists("RoleBinding", "mlflow-nb", "ns")


class TestReviewRegressions:
    def test_deleted_proxy_service_drift_repaired(self):
        """Platform owns Service: deleting the rbac-proxy Service re-creates it."""
        env = make_platform_env()
        env.cluster.create(cpu_notebook(annotations={ann.INJECT_AUTH: "true"}))
        env.manager.run_until_idle()
        env.cluster.delete("Service", "nb-kube-rbac-proxy", "ns")
        env.manager.run_until_idle()
        assert env.cluster.exists("Service", "nb-kube-rbac-proxy", "ns")

    def test_platform_config_namespace_propagates_to_routes(self):
        from kubeflow_tpu.controller.platform import PlatformConfig

        env = make_platform_env(
            platform_config=PlatformConfig(controller_namespace="my-ctrl")
        )
        env.cluster.create(cpu_notebook())
        env.manager.run_until_idle()
        assert env.cluster.exists("HTTPRoute", "nb-ns-nb", "my-ctrl")
        grant = env.cluster.get("ReferenceGrant", "notebook-httproute-access", "ns")
        assert grant["spec"]["from"][0]["namespace"] == "my-ctrl"


class TestIntegrationRegressions:
    def test_elyra_secret_decodes_s3_credentials(self):
        from kubeflow_tpu.controller.platform import PlatformConfig

        env = make_platform_env(
            platform_config=PlatformConfig(set_pipeline_secret=True)
        )
        env.cluster.create(
            {
                "apiVersion": "datasciencepipelinesapplications.opendatahub.io/v1",
                "kind": "DataSciencePipelinesApplication",
                "metadata": {"name": "dspa", "namespace": "ns"},
                "spec": {
                    "objectStorage": {
                        "externalStorage": {
                            "host": "s3.example",
                            "bucket": "b",
                            "s3CredentialsSecret": {"secretName": "s3-creds"},
                        }
                    }
                },
            }
        )
        env.cluster.create(
            {
                "apiVersion": "v1",
                "kind": "Secret",
                "metadata": {"name": "s3-creds", "namespace": "ns"},
                "data": {
                    "AWS_ACCESS_KEY_ID": base64.b64encode(b"my-access-key").decode(),
                    "AWS_SECRET_ACCESS_KEY": base64.b64encode(b"my-secret").decode(),
                },
            }
        )
        env.cluster.create(cpu_notebook())
        env.manager.run_until_idle()
        secret = env.cluster.get("Secret", "ds-pipeline-config", "ns")
        config = json.loads(secret["stringData"]["odh_dsp.json"])
        assert config["metadata"]["cos_username"] == "my-access-key"
        assert config["metadata"]["cos_password"] == "my-secret"

    def test_runtime_images_cm_deleted_when_sources_gone(self):
        env = make_platform_env()
        env.cluster.create(
            {
                "apiVersion": "image.openshift.io/v1",
                "kind": "ImageStream",
                "metadata": {
                    "name": "rt",
                    "namespace": CENTRAL,
                    "labels": {"opendatahub.io/runtime-image": "true"},
                },
                "status": {
                    "tags": [{"tag": "l", "items": [{"dockerImageReference": "r/i@sha"}]}]
                },
            }
        )
        env.cluster.create(cpu_notebook())
        env.manager.run_until_idle()
        assert env.cluster.exists("ConfigMap", "pipeline-runtime-images", "ns")
        env.cluster.delete("ImageStream", "rt", CENTRAL)
        # Touch the notebook so the platform re-reconciles.
        nb = env.cluster.get("Notebook", "nb", "ns")
        obj_util.annotations_of(nb)["touch"] = "1"
        env.cluster.update(nb)
        env.manager.run_until_idle()
        assert not env.cluster.exists("ConfigMap", "pipeline-runtime-images", "ns")

    def test_ctrl_netpol_admits_gateway_namespace(self):
        env = make_platform_env()
        env.cluster.create(cpu_notebook())
        env.manager.run_until_idle()
        np_obj = env.cluster.get("NetworkPolicy", "nb-ctrl-np", "ns")
        selectors = [
            p["namespaceSelector"]["matchLabels"]["kubernetes.io/metadata.name"]
            for p in np_obj["spec"]["ingress"][0]["from"]
        ]
        assert CENTRAL in selectors
        assert "openshift-ingress" in selectors
