"""Shared envtest-style harness: fake cluster + manager + controllers.

The analog of the reference's suite_test.go bootstrap (reference
components/notebook-controller/controllers/suite_test.go:50-110): a live
"API server" (FakeCluster), a manager with the controllers under test, and a
fake kubelet + TPU node pools so StatefulSets become Ready pods.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from kubeflow_tpu import k8s
from kubeflow_tpu.api.notebook import TPUSpec, new_notebook
from kubeflow_tpu.controller.culling import CullerConfig, CullingReconciler, HostActivity
from kubeflow_tpu.controller.notebook import ControllerConfig, NotebookReconciler
from kubeflow_tpu.controller.platform import PlatformConfig, PlatformReconciler
from kubeflow_tpu.controller.preemption import RecoveryConfig, SliceHealthReconciler
from kubeflow_tpu.controller.slicepool import SlicePoolReconciler
from kubeflow_tpu.k8s.manager import FakeClock, Manager
from kubeflow_tpu.metrics import Metrics
from kubeflow_tpu.webhook import (
    NotebookMutatingWebhook,
    NotebookValidatingWebhook,
    WebhookConfig,
)


class FakeTokenController:
    """kube-controller-manager token-controller analog: mints an
    image-pull secret onto every ServiceAccount that lacks one — the
    thing platform.py's pull-secret wait (reference
    notebook_controller.go:155-186) polls for before releasing the
    reconciliation lock."""

    def __init__(self, client):
        self.client = client

    def register(self, manager: Manager) -> None:
        manager.register(self, for_kind="ServiceAccount",
                         name="TokenController")

    def reconcile(self, req):
        from kubeflow_tpu.k8s.errors import NotFoundError
        from kubeflow_tpu.k8s.manager import Result

        try:
            sa = self.client.get("ServiceAccount", req.name, req.namespace)
        except NotFoundError:
            return Result()
        if not sa.get("imagePullSecrets"):
            sa["imagePullSecrets"] = [{"name": f"{req.name}-dockercfg"}]
            self.client.update(sa)
        return Result()


class FakeProber:
    """Scriptable ActivityProber."""

    def __init__(self):
        self.activities: list[HostActivity] = []
        self.probe_count = 0

    def set_idle(self, hosts: int = 1, last_activity: Optional[float] = None):
        self.activities = [
            HostActivity(host=f"h{i}", busy=False, last_activity=last_activity)
            for i in range(hosts)
        ]

    def set_busy(self, hosts: int = 1, busy_host: int = 0):
        self.activities = [
            HostActivity(host=f"h{i}", busy=(i == busy_host)) for i in range(hosts)
        ]

    def set_unreachable(self, hosts: int = 1):
        """Every probe errors (network partition / NetPol misconfig)."""
        self.activities = [
            HostActivity(host=f"h{i}", reachable=False) for i in range(hosts)
        ]

    def probe(self, nb, hosts):
        self.probe_count += 1
        return list(self.activities)


@dataclass
class Env:
    cluster: k8s.FakeCluster
    manager: Manager
    clock: FakeClock
    kubelet: k8s.FakeKubelet
    reconciler: NotebookReconciler
    culler: Optional[CullingReconciler]
    prober: Optional[FakeProber]
    slice_health: Optional[SliceHealthReconciler]
    metrics: Metrics
    webhook: Optional[NotebookMutatingWebhook] = None
    slicepool: Optional[SlicePoolReconciler] = None


def make_env(
    culling: bool = False,
    cull_idle_min: int = 30,
    check_period_min: int = 1,
    slice_health: bool = True,
    node_pools: tuple = (("tpu-v5-lite-podslice", "4x4", 4, 4),),
    cpu_nodes: int = 1,
    webhooks: bool = False,
    webhook_config: Optional[WebhookConfig] = None,
    platform: bool = False,
    platform_config: Optional[PlatformConfig] = None,
    cluster: Optional[k8s.FakeCluster] = None,
    controller_config: Optional[ControllerConfig] = None,
    recovery_config: Optional[RecoveryConfig] = None,
    sa_pull_secrets: bool = True,
) -> Env:
    """Build a controller environment. Passing an existing ``cluster``
    simulates a controller-process restart: fresh manager/reconcilers/
    metrics over the surviving cluster state."""
    reuse = cluster is not None
    clock = cluster._clock if reuse else FakeClock()  # type: ignore[union-attr]
    cluster = cluster if reuse else k8s.FakeCluster(clock=clock)
    manager = Manager(cluster, clock=clock)
    metrics = Metrics(cluster)

    kubelet = k8s.FakeKubelet(cluster)
    for i in range(cpu_nodes):
        if not reuse:
            k8s.add_cpu_node(cluster, f"cpu-node-{i}")
    if not reuse:
        for accel_label, topo, hosts, chips in node_pools:
            k8s.add_tpu_node_pool(
                cluster, accel_label, topo, hosts=hosts, chips_per_host=chips
            )
    if sa_pull_secrets:
        # The namespace "default" SA with its pull secret already minted
        # (pods without a template serviceAccountName run as it), plus a
        # token controller for SAs created later (auth sidecar SAs) —
        # platform.py holds the reconciliation lock until the pod SA
        # carries an imagePullSecrets entry. Disable via
        # sa_pull_secrets=False to observe the wait itself.
        if not reuse and not cluster.exists("ServiceAccount", "default", "ns"):
            cluster.create({
                "apiVersion": "v1",
                "kind": "ServiceAccount",
                "metadata": {"name": "default", "namespace": "ns"},
                "imagePullSecrets": [{"name": "default-dockercfg"}],
            })
        FakeTokenController(cluster).register(manager)

    # Controllers register before the kubelet: within one event batch they
    # dispatch first, so transient pod states (Failed → recreated) are
    # observable by the slice-health controller before cleanup.
    reconciler = NotebookReconciler(
        cluster, controller_config or ControllerConfig(), metrics=metrics,
        clock=clock
    )
    reconciler.register(manager)

    pool_rec = SlicePoolReconciler(cluster, metrics=metrics, clock=clock)
    pool_rec.register(manager)

    culler_rec = None
    prober = None
    if culling:
        prober = FakeProber()
        prober.set_idle()
        culler_rec = CullingReconciler(
            cluster,
            CullerConfig(
                enable_culling=True,
                cull_idle_time_min=cull_idle_min,
                idleness_check_period_min=check_period_min,
            ),
            prober=prober,
            metrics=metrics,
            clock=clock,
        )
        culler_rec.register(manager)

    health = None
    if slice_health:
        health = SliceHealthReconciler(
            cluster, metrics=metrics, clock=clock,
            config=recovery_config or RecoveryConfig(),
        )
        health.register(manager)

    if platform:
        PlatformReconciler(cluster, platform_config or PlatformConfig()).register(
            manager
        )

    kubelet.register(manager)

    webhook = None
    if webhooks:
        webhook = NotebookMutatingWebhook(cluster, webhook_config or WebhookConfig())
        webhook.register(cluster)
        NotebookValidatingWebhook(cluster).register(cluster)

    return Env(
        cluster, manager, clock, kubelet, reconciler, culler_rec, prober, health,
        metrics, webhook, pool_rec,
    )


def tpu_notebook(name="nb", namespace="ns", accelerator="v5e", topology="4x4", **kw):
    return new_notebook(
        name, namespace, image="jax-notebook:latest",
        tpu=TPUSpec(accelerator=accelerator, topology=topology), **kw,
    )


def cpu_notebook(name="nb", namespace="ns", **kw):
    return new_notebook(name, namespace, image="jupyter-minimal:latest", **kw)
