"""Unit tests for the shared interprocedural substrate: call-graph
construction/resolution (kubeflow_tpu.analysis.callgraph) and the lock
model (kubeflow_tpu.analysis.concurrency.LockModel).

Each test builds a tiny throwaway corpus in tmp_path and constructs the
graph directly — no kubeflow_tpu modules in the index, so dispatch
candidate counts and class-name lookups are fully controlled.
"""

import textwrap

from kubeflow_tpu.analysis import config
from kubeflow_tpu.analysis.concurrency import LockModel
from kubeflow_tpu.analysis.core import load_module
from kubeflow_tpu.analysis.index import RepoIndex


def make_graph(tmp_path, sources: dict):
    index = RepoIndex(tmp_path)
    for name, src in sources.items():
        path = tmp_path / f"{name}.py"
        path.write_text(textwrap.dedent(src))
        index.add(load_module(path, f"{name}.py", name))
    return index.callgraph()


def fn_named(graph, qualname):
    for fn in graph.functions.values():
        if fn.qualname == qualname:
            return fn
    raise AssertionError(f"no function {qualname!r} in graph")


class TestResolution:
    def test_bare_name_resolves_to_local_def(self, tmp_path):
        graph = make_graph(tmp_path, {"m": """
            def helper():
                pass

            def caller():
                helper()
        """})
        targets = [t.qualname for _, t in graph.edges[fn_named(graph, "caller").key]]
        assert targets == ["helper"]

    def test_self_method_resolves_through_base_class(self, tmp_path):
        graph = make_graph(tmp_path, {"m": """
            class Base:
                def shared(self):
                    pass

            class Child(Base):
                def go(self):
                    self.shared()
        """})
        targets = [t.qualname for _, t in graph.edges[fn_named(graph, "Child.go").key]]
        assert targets == ["Base.shared"]

    def test_attr_call_resolves_through_learned_type(self, tmp_path):
        graph = make_graph(tmp_path, {"m": """
            class Pool:
                def drain_all(self):
                    pass

            class Engine:
                def __init__(self):
                    self.pool = Pool()

                def go(self):
                    self.pool.drain_all()
        """})
        targets = [t.qualname for _, t in graph.edges[fn_named(graph, "Engine.go").key]]
        assert targets == ["Pool.drain_all"]

    def test_cross_module_import_resolves(self, tmp_path):
        graph = make_graph(tmp_path, {
            "a": """
                from b import remote_work

                def caller():
                    remote_work()
            """,
            "b": """
                def remote_work():
                    pass
            """,
        })
        targets = [t.qualname for _, t in graph.edges[fn_named(graph, "caller").key]]
        assert targets == ["remote_work"]


class TestDynamicDispatch:
    def test_untyped_receiver_falls_back_when_under_cap(self, tmp_path):
        graph = make_graph(tmp_path, {"m": """
            class A:
                def frobnicate(self):
                    pass

            class B:
                def frobnicate(self):
                    pass

            def use(x):
                x.frobnicate()
        """})
        targets = sorted(
            t.qualname for _, t in graph.edges[fn_named(graph, "use").key]
        )
        assert targets == ["A.frobnicate", "B.frobnicate"]

    def test_over_cap_contributes_no_edges(self, tmp_path):
        classes = "\n".join(
            f"class C{i}:\n    def frobnicate(self):\n        pass\n"
            for i in range(config.DISPATCH_CAP + 1)
        )
        graph = make_graph(
            tmp_path, {"m": classes + "\ndef use(x):\n    x.frobnicate()\n"}
        )
        assert graph.edges[fn_named(graph, "use").key] == []

    def test_ubiquitous_names_never_dispatch(self, tmp_path):
        graph = make_graph(tmp_path, {"m": """
            class Store:
                def get(self):
                    pass

            def use(x):
                x.get()
        """})
        assert graph.edges[fn_named(graph, "use").key] == []

    def test_lock_protocol_methods_never_dispatch(self, tmp_path):
        graph = make_graph(tmp_path, {"m": """
            class Claimer:
                def acquire(self):
                    pass

            def use(x):
                x.acquire()
        """})
        assert graph.edges[fn_named(graph, "use").key] == []

    def test_lockish_receiver_contributes_no_edges(self, tmp_path):
        graph = make_graph(tmp_path, {"m": """
            class Claimer:
                def grab_slice(self):
                    pass

            def use(self_lock):
                self_lock.grab_slice()
        """})
        assert graph.edges[fn_named(graph, "use").key] == []


class TestReachability:
    def test_recursion_terminates_and_visits_once(self, tmp_path):
        graph = make_graph(tmp_path, {"m": """
            def ping():
                pong()

            def pong():
                ping()
        """})
        visited = [
            fn.qualname
            for fn, _, _ in graph.reachable(fn_named(graph, "ping"))
        ]
        assert visited == ["ping", "pong"]

    def test_depth_bound_cuts_the_walk(self, tmp_path):
        graph = make_graph(tmp_path, {"m": """
            def f0():
                f1()

            def f1():
                f2()

            def f2():
                f3()

            def f3():
                pass
        """})
        at_2 = {
            fn.qualname
            for fn, _, _ in graph.reachable(fn_named(graph, "f0"), max_depth=2)
        }
        assert at_2 == {"f0", "f1", "f2"}

    def test_witness_path_renders_hops(self, tmp_path):
        graph = make_graph(tmp_path, {"m": """
            def outer():
                inner()

            def inner():
                leaf()

            def leaf():
                pass
        """})
        for fn, depth, path in graph.reachable(fn_named(graph, "outer")):
            if fn.qualname == "leaf":
                assert depth == 2
                rendered = graph.render_path(path, fn)
                assert rendered == "outer (m.py:3) -> inner (m.py:6) -> leaf"
                break
        else:
            raise AssertionError("leaf not reached")


class TestLockModel:
    def test_class_and_module_locks_get_canonical_ids(self, tmp_path):
        graph = make_graph(tmp_path, {"m": """
            import threading

            _MOD_LOCK = threading.Lock()


            class Owner:
                def __init__(self):
                    self._lock = threading.RLock()
        """})
        model = LockModel(graph)
        assert model.class_locks["Owner"]["_lock"] == "Owner._lock"
        assert model.kinds["Owner._lock"] == "RLock"
        assert model.module_locks["m"]["_MOD_LOCK"] == "m:_MOD_LOCK"

    def test_condition_aliases_to_wrapped_lock(self, tmp_path):
        graph = make_graph(tmp_path, {"m": """
            import threading


            class Waiter:
                def __init__(self):
                    self._cond = threading.Condition(self._lock)
                    self._lock = threading.Lock()
        """})
        model = LockModel(graph)
        # Two-pass build: the alias resolves even though the Condition is
        # assigned before the lock it wraps.
        assert model.class_locks["Waiter"]["_cond"] == "Waiter._lock"

    def test_with_regions_track_held_sets(self, tmp_path):
        graph = make_graph(tmp_path, {"m": """
            import threading


            class Owner:
                def __init__(self):
                    self._alock = threading.Lock()
                    self._block = threading.Lock()

                def nested(self):
                    with self._alock:
                        with self._block:
                            self.flush()

                def flush(self):
                    pass
        """})
        model = LockModel(graph)
        scan = model.scan(fn_named(graph, "Owner.nested"))
        acq = {lock_id: held for _, lock_id, held in scan.acquisitions}
        assert acq["Owner._alock"] == frozenset()
        assert acq["Owner._block"] == frozenset({"Owner._alock"})
        (call, held), = [
            (c, h) for c, h in scan.calls
            if getattr(c.func, "attr", "") == "flush"
        ]
        assert held == frozenset({"Owner._alock", "Owner._block"})

    def test_unresolvable_lockish_expr_is_anonymous(self, tmp_path):
        graph = make_graph(tmp_path, {"m": """
            def f(busy_lock):
                with busy_lock:
                    pass
        """})
        model = LockModel(graph)
        scan = model.scan(fn_named(graph, "f"))
        (_, lock_id, _), = scan.acquisitions
        assert lock_id == "~busy_lock"
        assert LockModel.is_anonymous(lock_id)

    def test_bare_acquire_release_is_deliberately_untracked(self, tmp_path):
        graph = make_graph(tmp_path, {"m": """
            import threading


            class Owner:
                def __init__(self):
                    self._lock = threading.Lock()

                def manual(self):
                    self._lock.acquire(timeout=5)
                    self.flush()
                    self._lock.release()

                def flush(self):
                    pass
        """})
        model = LockModel(graph)
        scan = model.scan(fn_named(graph, "Owner.manual"))
        assert scan.acquisitions == []
        assert all(held == frozenset() for _, held in scan.calls)
