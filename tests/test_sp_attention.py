"""Sequence-parallel attention completeness: q_offset / sliding-window /
kv_mask parity with the dense XLA reference for BOTH SP strategies (ring,
Ulysses), plus the split-KV SP decode path.

These close the round-2 gap where SP impls rejected window/kv_mask/q_offset
outright (old ops/attention.py:83-88, parallel/ring_attention.py:99-106).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import pytest

from kubeflow_tpu.models import llama as L
from kubeflow_tpu.models.train import make_train_step, shard_state
from kubeflow_tpu.ops.attention import flash_attention
from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh
from kubeflow_tpu.parallel.ring_attention import (
    make_sharded_ring_attention,
    make_sharded_sp_decode,
)
from kubeflow_tpu.parallel.ulysses import make_sharded_ulysses_attention


def _qkv(heads=4, sq=128, sk=None, d=32, batch=2, seed=0):
    sk = sq if sk is None else sk
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (
        jax.random.normal(ks[0], (batch, heads, sq, d)),
        jax.random.normal(ks[1], (batch, heads, sk, d)),
        jax.random.normal(ks[2], (batch, heads, sk, d)),
    )


def _close(a, b, tol=1e-4):
    assert float(jnp.max(jnp.abs(a - b))) < tol


MAKERS = {
    "ring": make_sharded_ring_attention,
    "ulysses": make_sharded_ulysses_attention,
}


class TestChunkedRingStep:
    """The inner flash-style sub-block scan (long-context memory lever)
    must be bit-compatible with the single-block path; a small _RING_BLOCK
    forces nblk > 1 without long-sequence test cost."""

    @pytest.mark.parametrize("window,kv_mask", [(0, False), (48, True)])
    def test_chunked_matches_dense(self, monkeypatch, window, kv_mask):
        import importlib

        # parallel/__init__ re-exports the ring_attention FUNCTION,
        # shadowing the submodule attribute; resolve the module.
        R = importlib.import_module("kubeflow_tpu.parallel.ring_attention")
        monkeypatch.setattr(R, "_RING_BLOCK", 16)  # sk_local 32 → 2 blocks
        mesh = make_mesh(dp=2, sp=4)
        q, k, v = _qkv(heads=4, sq=128)  # sk_local = 32 per shard
        mask = None
        if kv_mask:
            mask = jnp.ones((2, 128), bool).at[:, :16].set(False)
        ring = make_sharded_ring_attention(mesh)
        got = ring(q, k, v, causal=True, window=window, kv_mask=mask)
        ref = flash_attention(
            q, k, v, causal=True, window=window, kv_mask=mask, impl="xla"
        )
        _close(got, ref)

    def test_gradients_flow_through_chunked_scan(self, monkeypatch):
        import importlib

        R = importlib.import_module("kubeflow_tpu.parallel.ring_attention")
        monkeypatch.setattr(R, "_RING_BLOCK", 16)
        mesh = make_mesh(dp=2, sp=4)
        q, k, v = _qkv(heads=4, sq=128)
        ring = make_sharded_ring_attention(mesh)

        def loss(q, k, v):
            return jnp.sum(ring(q, k, v, causal=True) ** 2)

        def ref_loss(q, k, v):
            return jnp.sum(
                flash_attention(q, k, v, causal=True, impl="xla") ** 2
            )

        got = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
        for g, r in zip(got, ref):
            _close(g, r, tol=1e-3)


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
class TestSPMaskingParity:
    def test_sliding_window(self, impl):
        mesh = make_mesh(dp=2, sp=4)
        q, k, v = _qkv(heads=4, sq=128)
        ref = flash_attention(q, k, v, causal=True, window=40, impl="xla")
        out = MAKERS[impl](mesh)(q, k, v, window=40)
        _close(out, ref)

    def test_q_offset_cached_continuation(self, impl):
        """q is a later chunk of a longer cached K/V sequence."""
        mesh = make_mesh(dp=2, sp=4)
        q, k, v = _qkv(heads=4, sq=64, sk=128)
        ref = flash_attention(q, k, v, causal=True, q_offset=64, impl="xla")
        out = MAKERS[impl](mesh)(q, k, v, q_offset=64)
        _close(out, ref)

    def test_kv_mask(self, impl):
        mesh = make_mesh(dp=2, sp=4)
        q, k, v = _qkv(heads=4, sq=128)
        # Left-padding style: first 24 keys of batch row 0 invalid.
        kv_mask = jnp.ones((2, 128), bool).at[0, :24].set(False)
        ref = flash_attention(
            q, k, v, causal=True, kv_mask=kv_mask, impl="xla"
        )
        out = MAKERS[impl](mesh)(q, k, v, kv_mask=kv_mask)
        _close(out, ref)

    def test_window_offset_mask_combined(self, impl):
        mesh = make_mesh(dp=2, sp=4)
        q, k, v = _qkv(heads=4, sq=64, sk=128)
        kv_mask = jnp.ones((2, 128), bool).at[1, :16].set(False)
        ref = flash_attention(
            q, k, v, causal=True, q_offset=64, window=50, kv_mask=kv_mask,
            impl="xla",
        )
        out = MAKERS[impl](mesh)(
            q, k, v, q_offset=64, window=50, kv_mask=kv_mask
        )
        _close(out, ref)


class TestSPDecode:
    def test_matches_dense_single_token(self):
        mesh = make_mesh(dp=2, sp=4)
        q, k, v = _qkv(heads=4, sq=1, sk=128)
        pos = 77
        ref = flash_attention(q, k, v, causal=True, q_offset=pos, impl="xla")
        out = make_sharded_sp_decode(mesh)(q, k, v, pos)
        _close(out, ref)

    def test_windowed_decode(self):
        mesh = make_mesh(sp=8)
        q, k, v = _qkv(heads=8, sq=1, sk=128)
        pos = 100
        ref = flash_attention(
            q, k, v, causal=True, q_offset=pos, window=30, impl="xla"
        )
        out = make_sharded_sp_decode(mesh)(q, k, v, pos, window=30)
        _close(out, ref)

    def test_chunked_decode_vector_positions(self):
        """K>1 queries at consecutive positions (speculative verification)."""
        mesh = make_mesh(dp=2, sp=4)
        q, k, v = _qkv(heads=4, sq=4, sk=128)
        positions = jnp.asarray([60, 61, 62, 63])
        ref = flash_attention(q, k, v, causal=True, q_offset=60, impl="xla")
        out = make_sharded_sp_decode(mesh)(q, k, v, positions)
        _close(out, ref)

    def test_decode_kv_mask(self):
        mesh = make_mesh(dp=2, sp=4)
        q, k, v = _qkv(heads=4, sq=1, sk=128)
        kv_mask = jnp.ones((2, 128), bool).at[0, :32].set(False)
        pos = 90
        ref = flash_attention(
            q, k, v, causal=True, q_offset=pos, kv_mask=kv_mask, impl="xla"
        )
        out = make_sharded_sp_decode(mesh)(q, k, v, pos, kv_mask=kv_mask)
        _close(out, ref)

    def test_jits_inside_one_program(self):
        mesh = make_mesh(dp=2, sp=4)
        q, k, v = _qkv(heads=4, sq=1, sk=128)
        decode = make_sharded_sp_decode(mesh)

        @jax.jit
        def step(q, k, v):
            return decode(q, k, v, 50)

        out = step(q, k, v)
        ref = flash_attention(q, k, v, causal=True, q_offset=50, impl="xla")
        _close(out, ref)


class TestWindowedSPTraining:
    def test_mistral_style_window_trains_under_sp(self):
        """Sliding-window config (the Mistral family gate that round 2
        could not train under sp) — loss matches the dense mesh."""
        import dataclasses

        cfg = dataclasses.replace(
            L.LLAMA_CONFIGS["tiny"], sliding_window=48
        )
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (4, 128), 0, cfg.vocab_size
        )
        losses = {}
        for name, mesh in (
            ("sp", make_mesh(dp=2, sp=4)),
            ("dense", make_mesh(dp=4, tp=2)),
        ):
            plan = MeshPlan(mesh)
            params = L.init_params(cfg, jax.random.PRNGKey(0))
            init_state, step = make_train_step(cfg, plan)
            state = shard_state(plan, init_state(params))
            _, loss = step(state, tokens)
            losses[name] = float(loss)
        assert abs(losses["sp"] - losses["dense"]) < 1e-3

    def test_ulysses_windowed_matches_ring(self):
        import dataclasses

        cfg = dataclasses.replace(
            L.LLAMA_CONFIGS["tiny"], sliding_window=32
        )
        tokens = jax.random.randint(
            jax.random.PRNGKey(2), (4, 128), 0, cfg.vocab_size
        )
        losses = {}
        for impl in ("ring", "ulysses"):
            plan = MeshPlan(make_mesh(dp=2, sp=4))
            params = L.init_params(cfg, jax.random.PRNGKey(0))
            init_state, step = make_train_step(cfg, plan, sp_impl=impl)
            state = shard_state(plan, init_state(params))
            _, loss = step(state, tokens)
            losses[impl] = float(loss)
        assert abs(losses["ring"] - losses["ulysses"]) < 1e-3


class TestSPDecodeInt8Scales:
    """int8-cache decode through the sp split-KV merge: the scale shards
    ride with their values, folded into the f32 score/probability
    epilogues exactly as the dense _gqa_decode_attention does."""

    def _quantized(self, heads=4, sk=128, d=32, batch=2, seed=3):
        ks = jax.random.split(jax.random.PRNGKey(seed), 3)
        q = jax.random.normal(ks[0], (batch, heads, 1, d), jnp.bfloat16)
        k = jax.random.normal(ks[1], (batch, heads, sk, d), jnp.bfloat16)
        v = jax.random.normal(ks[2], (batch, heads, sk, d), jnp.bfloat16)
        kq, kscale = L._kv_quantize(k)
        vq, vscale = L._kv_quantize(v)
        return q, kq, vq, kscale, vscale

    def test_matches_dense_int8_decode(self):
        mesh = make_mesh(dp=2, sp=4)
        q, kq, vq, ks, vs = self._quantized()
        pos = 77
        ref = L._gqa_decode_attention(q, kq, vq, jnp.asarray(pos),
                                      k_scale=ks, v_scale=vs)
        out = make_sharded_sp_decode(mesh)(q, kq, vq, pos,
                                           k_scale=ks, v_scale=vs)
        _close(out.astype(jnp.float32), ref.astype(jnp.float32), tol=2e-2)

    def test_scales_compose_with_kv_mask_and_window(self):
        mesh = make_mesh(sp=4, devices=jax.devices()[:4])
        q, kq, vq, ks, vs = self._quantized(heads=4, batch=2)
        # Masked keys INSIDE the attention window (pos=90, window=40 →
        # visible range 51..90), so the kv_mask measurably changes the
        # output and a path that dropped it under int8 scales would fail.
        kv_mask = jnp.ones((2, 128), bool).at[0, 60:70].set(False)
        pos = 90
        ref = L._gqa_decode_attention(q, kq, vq, jnp.asarray(pos),
                                      window=40, kv_mask=kv_mask,
                                      k_scale=ks, v_scale=vs)
        out = make_sharded_sp_decode(mesh)(q, kq, vq, pos, window=40,
                                           kv_mask=kv_mask,
                                           k_scale=ks, v_scale=vs)
        _close(out.astype(jnp.float32), ref.astype(jnp.float32), tol=2e-2)

    def test_scale_pair_required_together(self):
        mesh = make_mesh(sp=2, devices=jax.devices()[:2])
        q, kq, vq, ks, _ = self._quantized()
        with pytest.raises(ValueError, match="together"):
            make_sharded_sp_decode(mesh)(q, kq, vq, 10, k_scale=ks)
