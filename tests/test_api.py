"""Unit tests for the Notebook API types and conversion."""

import pytest

from kubeflow_tpu.api import annotations as ann
from kubeflow_tpu.api.notebook import (
    HUB_VERSION,
    Notebook,
    TPUSpec,
    convert,
    new_notebook,
)


class TestNotebookAccessors:
    def test_basic(self):
        obj = new_notebook("my-nb", "team-a")
        nb = Notebook(obj)
        assert nb.name == "my-nb"
        assert nb.namespace == "team-a"
        assert nb.tpu is None
        assert not nb.stopped
        assert nb.primary_container()["name"] == "my-nb"

    def test_tpu_spec_roundtrip(self):
        spec = TPUSpec(accelerator="v5e", topology="4x4", spot=True)
        obj = new_notebook("nb", "ns", tpu=spec)
        nb = Notebook(obj)
        assert nb.tpu == spec
        topo = nb.tpu.slice_topology()
        assert topo.hosts == 4

    def test_primary_container_falls_back_to_first(self):
        obj = new_notebook("nb", "ns")
        obj["spec"]["template"]["spec"]["containers"][0]["name"] = "other"
        nb = Notebook(obj)
        assert nb.primary_container()["name"] == "other"

    def test_stopped_and_lock(self):
        obj = new_notebook(
            "nb", "ns", annotations={ann.STOP: ann.RECONCILIATION_LOCK_VALUE}
        )
        nb = Notebook(obj)
        assert nb.stopped
        assert nb.lock_held
        obj2 = new_notebook("nb2", "ns", annotations={ann.STOP: "2026-07-29T00:00:00Z"})
        assert Notebook(obj2).stopped
        assert not Notebook(obj2).lock_held


class TestConversion:
    def test_rewrites_api_version(self):
        obj = new_notebook("nb", "ns", version="v1")
        hub = convert(obj, HUB_VERSION)
        assert hub["apiVersion"] == "kubeflow.org/v1beta1"
        assert hub["spec"] == obj["spec"]

    def test_roundtrip_preserves_tpu(self):
        obj = new_notebook("nb", "ns", tpu=TPUSpec("v5e", "2x2"))
        out = convert(convert(obj, "v1alpha1"), "v1")
        assert out["spec"]["tpu"] == {"accelerator": "v5e", "topology": "2x2"}

    def test_unknown_version(self):
        with pytest.raises(ValueError):
            convert(new_notebook("nb", "ns"), "v2")

    def test_wrong_group(self):
        with pytest.raises(ValueError):
            convert({"apiVersion": "apps/v1", "kind": "Deployment"}, "v1")
