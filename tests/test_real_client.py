"""RealClient ↔ EnvtestServer: the production client against a live HTTP
apiserver (the envtest integration tier, reference suite_test.go:50-110 —
here the apiserver is the FakeCluster served over the Kubernetes REST
dialect instead of kube-apiserver binaries)."""

from __future__ import annotations

import threading
import time

import pytest

from kubeflow_tpu import k8s
from kubeflow_tpu.api.notebook import TPUSpec, new_notebook
from kubeflow_tpu.k8s import rest
from kubeflow_tpu.k8s.envtest import EnvtestServer
from kubeflow_tpu.k8s.errors import (
    AlreadyExistsError,
    ConflictError,
    NotFoundError,
    WebhookDeniedError,
)
from kubeflow_tpu.k8s.real import ClusterConfig, RealClient


@pytest.fixture
def server():
    srv = EnvtestServer().start()
    yield srv
    srv.stop()


@pytest.fixture
def client(server):
    c = RealClient(server.client_config())
    yield c
    c.stop()


def _cm(name="c1", ns="ns", data=None, labels=None):
    obj = {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": {"name": name, "namespace": ns},
        "data": data or {"k": "v"},
    }
    if labels:
        obj["metadata"]["labels"] = labels
    return obj


class TestRestMapping:
    def test_core_and_group_paths(self):
        assert rest.collection_path("Pod", "ns") == "/api/v1/namespaces/ns/pods"
        assert rest.collection_path("Node") == "/api/v1/nodes"
        assert (
            rest.object_path("StatefulSet", "s", "ns")
            == "/apis/apps/v1/namespaces/ns/statefulsets/s"
        )
        assert (
            rest.collection_path("Notebook", "u")
            == "/apis/kubeflow.org/v1beta1/namespaces/u/notebooks"
        )
        assert rest.status_path("Notebook", "n", "u").endswith("/notebooks/n/status")

    def test_unknown_kind_raises(self):
        with pytest.raises(rest.UnknownKindError):
            rest.collection_path("Gadget")

    def test_label_selector_query(self):
        q = rest.list_query(label_selector={"a": "1", "b": "2"})
        assert q == "?labelSelector=a%3D1%2Cb%3D2"


class TestCrud:
    def test_create_get_roundtrip(self, client):
        created = client.create(_cm())
        assert created["metadata"]["uid"]
        got = client.get("ConfigMap", "c1", "ns")
        assert got["data"] == {"k": "v"}
        assert got["kind"] == "ConfigMap"  # filled in even on list items

    def test_get_missing_raises_notfound(self, client):
        with pytest.raises(NotFoundError):
            client.get("ConfigMap", "nope", "ns")

    def test_create_duplicate_raises_already_exists(self, client):
        client.create(_cm())
        with pytest.raises(AlreadyExistsError):
            client.create(_cm())

    def test_list_with_label_selector(self, client):
        client.create(_cm("a", labels={"app": "x"}))
        client.create(_cm("b", labels={"app": "y"}))
        names = [o["metadata"]["name"]
                 for o in client.list("ConfigMap", "ns", {"app": "x"})]
        assert names == ["a"]

    def test_stale_update_conflicts(self, client):
        created = client.create(_cm())
        fresh = client.get("ConfigMap", "c1", "ns")
        fresh["data"] = {"k": "v2"}
        client.update(fresh)
        created["data"] = {"k": "v3"}  # still carries the old RV
        with pytest.raises(ConflictError):
            client.update(created)

    def test_status_subresource_is_isolated(self, client):
        nb = new_notebook("nb", "u", image="img")
        client.create(nb)
        stored = client.get("Notebook", "nb", "u")
        stored["status"] = {"readyReplicas": 3}
        client.update_status(stored)
        # A spec update must not clobber status…
        stored = client.get("Notebook", "nb", "u")
        stored["spec"]["template"]["spec"]["containers"][0]["image"] = "img2"
        stored["status"] = {}
        client.update(stored)
        assert client.get("Notebook", "nb", "u")["status"]["readyReplicas"] == 3

    def test_merge_patch(self, client):
        client.create(_cm())
        client.patch("ConfigMap", "c1", "ns", {"data": {"extra": "1"}})
        assert client.get("ConfigMap", "c1", "ns")["data"] == {
            "k": "v", "extra": "1",
        }

    def test_delete(self, client):
        client.create(_cm())
        client.delete("ConfigMap", "c1", "ns")
        assert not client.exists("ConfigMap", "c1", "ns")
        with pytest.raises(NotFoundError):
            client.delete("ConfigMap", "c1", "ns")

    def test_cluster_scoped_kind(self, client):
        client.create({"apiVersion": "v1", "kind": "Namespace",
                       "metadata": {"name": "team-a"}})
        assert [n["metadata"]["name"] for n in client.list("Namespace")] == ["team-a"]


class TestAuth:
    def test_bearer_token_required_when_configured(self):
        srv = EnvtestServer(token="sekrit").start()
        try:
            good = RealClient(srv.client_config())
            good.create(_cm())
            bad_cfg = srv.client_config()
            bad_cfg.token = "wrong"
            bad = RealClient(bad_cfg)
            with pytest.raises(Exception) as exc_info:
                bad.get("ConfigMap", "c1", "ns")
            assert getattr(exc_info.value, "code", None) == 401
            good.stop()
            bad.stop()
        finally:
            srv.stop()


class TestAdmission:
    def test_webhook_denial_maps_to_typed_error(self, server, client):
        from kubeflow_tpu.k8s.fake import AdmissionRequest

        def deny(req: AdmissionRequest):
            raise WebhookDeniedError("nope: policy")

        server.cluster.register_validating_webhook("ConfigMap", deny)
        with pytest.raises(WebhookDeniedError, match="policy"):
            client.create(_cm())


class TestWatch:
    def test_list_seed_then_live_events(self, server, client):
        with server.lock:
            server.cluster.create(_cm("pre"))
        client.start_watches(["ConfigMap"])
        assert client.wait_for_events(0, timeout=5)
        events, cursor = client.drain_events(0)
        assert [(e.type, e.name) for e in events] == [("ADDED", "pre")]

        writer = RealClient(server.client_config())
        writer.create(_cm("live"))
        assert client.wait_for_events(cursor, timeout=5)
        events, cursor = client.drain_events(cursor)
        assert ("ADDED", "live") in [(e.type, e.name) for e in events]

        live = writer.get("ConfigMap", "live", "ns")
        live["data"] = {"k": "v2"}
        writer.update(live)
        assert client.wait_for_events(cursor, timeout=5)
        events, cursor = client.drain_events(cursor)
        assert ("MODIFIED", "live") in [(e.type, e.name) for e in events]

        writer.delete("ConfigMap", "live", "ns")
        assert client.wait_for_events(cursor, timeout=5)
        events, _ = client.drain_events(cursor)
        assert ("DELETED", "live") in [(e.type, e.name) for e in events]
        writer.stop()

    def test_watch_survives_server_side_timeout(self, server, client):
        # timeoutSeconds-bounded watch connections must resume seamlessly.
        for w in client._watchers:
            w.stop()
        client._watchers.clear()
        client.start_watches(["ConfigMap"])
        time.sleep(0.1)
        writer = RealClient(server.client_config())
        writer.create(_cm("one"))
        assert client.wait_for_events(0, timeout=5)
        writer.stop()


class TestWatchResilience:
    """client-go reflector semantics: bounded recovery from dead peers,
    resume-without-reseed on reconnects, relist only on 410 Gone."""

    def _drain_all(self, client, cursor, settle=0.3):
        """Drain until no new events arrive for ``settle`` seconds."""
        out = []
        while client.wait_for_events(cursor, timeout=settle):
            events, cursor = client.drain_events(cursor)
            out.extend(events)
        return out, cursor

    def test_watch_requests_carry_timeout_seconds(self):
        # Every watch request must ask the server for a bounded stream.
        from kubeflow_tpu.k8s import rest as restmod
        from kubeflow_tpu.k8s.real import _Watcher

        assert _Watcher.WATCH_TIMEOUT_SECONDS > 0
        q = restmod.list_query(
            watch=True, resource_version="5", allow_bookmarks=True,
            timeout_seconds=_Watcher.WATCH_TIMEOUT_SECONDS,
        )
        assert f"timeoutSeconds={_Watcher.WATCH_TIMEOUT_SECONDS}" in q

    def test_reconnect_resumes_without_reseed(self, server, client):
        with server.lock:
            server.cluster.create(_cm("pre1"))
            server.cluster.create(_cm("pre2"))
        client.start_watches(["ConfigMap"])
        events, cursor = self._drain_all(client, 0)
        assert sorted(e.name for e in events) == ["pre1", "pre2"]

        # Kill the live watch connection (NAT drop / server restart).
        watcher = client._watchers[0]
        assert watcher._conn is not None
        watcher._conn.close()

        writer = RealClient(server.client_config())
        writer.create(_cm("post"))
        assert client.wait_for_events(cursor, timeout=10)
        events, cursor = self._drain_all(client, cursor)
        # The rv was still valid: ONLY the new object arrives — no
        # duplicate-ADDED reseed of pre1/pre2.
        assert [(e.type, e.name) for e in events] == [("ADDED", "post")]
        writer.stop()

    def test_410_gone_triggers_relist(self, server, client):
        with server.lock:
            server.cluster.create(_cm("keeper"))
        client.start_watches(["ConfigMap"])
        events, cursor = self._drain_all(client, 0)
        assert [e.name for e in events] == ["keeper"]

        # Sever the watch, then advance + compact the log past its rv.
        watcher = client._watchers[0]
        watcher._conn.close()
        with server.lock:
            server.cluster.create(_cm("during-outage"))
            server.cluster.compact_events(0)  # horizon beyond watcher's rv

        assert client.wait_for_events(cursor, timeout=10)
        events, cursor = self._drain_all(client, cursor)
        # Relist reseeds the full current state (both objects) — proving
        # the 410 path ran through the live HTTP serve loop.
        names = sorted(e.name for e in events if e.type == "ADDED")
        assert names == ["during-outage", "keeper"]

    def test_half_open_socket_bounded_by_read_deadline(self, monkeypatch):
        """A peer that accepts the watch then goes silent forever must not
        wedge the watcher: the socket read deadline surfaces it."""
        import socket as socketmod

        from kubeflow_tpu.k8s.real import _Watcher

        silent = socketmod.socket()
        silent.bind(("127.0.0.1", 0))
        silent.listen(1)
        host, port = silent.getsockname()

        monkeypatch.setattr(_Watcher, "WATCH_TIMEOUT_SECONDS", 1)
        monkeypatch.setattr(_Watcher, "SOCKET_DEADLINE_SLACK", 0.5)
        cfg = ClusterConfig(host=host, port=port, scheme="http")
        client = RealClient(cfg)
        watcher = _Watcher(client, "ConfigMap", "")
        t0 = time.monotonic()
        with pytest.raises(Exception):
            watcher._watch_from("1")
        elapsed = time.monotonic() - t0
        assert elapsed < 5, f"half-open socket wedged the watcher for {elapsed}s"
        client.stop()
        silent.close()

    def test_apiserver_restart_recovers(self, tmp_path):
        """Kill the apiserver mid-watch; a replacement on the same port is
        picked up within the relist backoff."""
        srv = EnvtestServer().start()
        host, port = srv.host, srv.port
        cluster = srv.cluster
        with srv.lock:
            cluster.create(_cm("existing"))
        client = RealClient(srv.client_config())
        client.start_watches(["ConfigMap"])
        assert client.wait_for_events(0, timeout=5)
        _, cursor = client.drain_events(0)

        srv.stop()  # hard outage
        time.sleep(0.3)
        srv2 = EnvtestServer(cluster=cluster, host=host, port=port).start()
        try:
            with srv2.lock:
                cluster.create(_cm("after-restart"))
            assert client.wait_for_events(cursor, timeout=15)
            events, _ = client.drain_events(cursor)
            assert "after-restart" in [e.name for e in events]
        finally:
            client.stop()
            srv2.stop()


class TestSchemaEnforcement:
    """The façade enforces the generated CRD schema the way a real
    apiserver does (422 Invalid) — reference gets this from envtest."""

    def test_bad_topology_pattern_422(self, client):
        from kubeflow_tpu.k8s.errors import InvalidError

        nb = new_notebook("nb", "u", image="img",
                          tpu=TPUSpec(accelerator="v5e", topology="4x4"))
        nb["spec"]["tpu"]["topology"] = "4by4"  # violates ^\d+x\d+(x\d+)?$
        with pytest.raises(InvalidError, match="pattern"):
            client.create(nb)

    def test_unknown_accelerator_enum_422(self, client):
        from kubeflow_tpu.k8s.errors import InvalidError

        nb = new_notebook("nb", "u", image="img",
                          tpu=TPUSpec(accelerator="v5e", topology="4x4"))
        nb["spec"]["tpu"]["accelerator"] = "h100"
        with pytest.raises(InvalidError, match="not one of"):
            client.create(nb)

    def test_update_validated_too(self, client):
        from kubeflow_tpu.k8s.errors import InvalidError

        nb = new_notebook("nb", "u", image="img",
                          tpu=TPUSpec(accelerator="v5e", topology="4x4"))
        client.create(nb)
        stored = client.get("Notebook", "nb", "u")
        stored["spec"]["tpu"]["topology"] = "not-a-grid"
        with pytest.raises(InvalidError):
            client.update(stored)

    def test_valid_notebook_passes(self, client):
        nb = new_notebook("nb", "u", image="img",
                          tpu=TPUSpec(accelerator="v5e", topology="2x2x2"))
        created = client.create(nb)
        assert created["metadata"]["uid"]


class TestKubeconfig:
    def test_from_kubeconfig_http(self, server, tmp_path):
        kubeconfig = tmp_path / "config"
        kubeconfig.write_text(
            f"""
apiVersion: v1
kind: Config
current-context: envtest
contexts:
- name: envtest
  context: {{cluster: envtest, user: dev, namespace: team-a}}
clusters:
- name: envtest
  cluster: {{server: "http://{server.host}:{server.port}"}}
users:
- name: dev
  user: {{token: ""}}
"""
        )
        cfg = ClusterConfig.from_kubeconfig(str(kubeconfig))
        assert (cfg.host, cfg.port, cfg.scheme) == (server.host, server.port, "http")
        assert cfg.namespace == "team-a"
        c = RealClient(cfg)
        c.create(_cm())
        assert c.exists("ConfigMap", "c1", "ns")
        c.stop()

    def test_from_env_prefers_in_cluster(self, tmp_path):
        sa = tmp_path / "sa"
        sa.mkdir()
        (sa / "token").write_text("tok123")
        (sa / "namespace").write_text("kubeflow")
        cfg = ClusterConfig.in_cluster(
            env={"KUBERNETES_SERVICE_HOST": "10.0.0.1"}, sa_dir=str(sa)
        )
        assert cfg.host == "10.0.0.1"
        assert cfg.bearer_token() == "tok123"
        assert cfg.namespace == "kubeflow"

    def test_from_env_no_config_raises(self, tmp_path, monkeypatch):
        from kubeflow_tpu.k8s.real import ConfigError

        with pytest.raises(ConfigError):
            ClusterConfig.from_env(env={"HOME": str(tmp_path)})
