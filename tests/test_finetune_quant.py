"""Sampling, LoRA fine-tuning, and int8 weight-only quantization."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models import llama as L
from kubeflow_tpu.models.lora import (
    LoraConfig,
    init_lora_params,
    lora_param_count,
    make_lora_train_step,
    merge_lora,
)
from kubeflow_tpu.models.quant import (
    dequantize_weight,
    quantize_params,
    quantize_weight,
    quantized_bytes,
)


@pytest.fixture(scope="module")
def tiny():
    cfg = L.LLAMA_CONFIGS["tiny"]
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


class TestSampling:
    def test_greedy_temperature_zero_matches_generate(self, tiny):
        cfg, params = tiny
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab_size)
        greedy = L.generate(params, cfg, prompt, steps=6, cache_len=16)
        sampled = L.sample(
            params, cfg, prompt, jax.random.PRNGKey(2), steps=6,
            cache_len=16, temperature=0.0,
        )
        np.testing.assert_array_equal(np.asarray(greedy), np.asarray(sampled))

    def test_sampling_is_stochastic_but_reproducible(self, tiny):
        cfg, params = tiny
        prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, cfg.vocab_size)
        a = L.sample(params, cfg, prompt, jax.random.PRNGKey(3), steps=16,
                     cache_len=32, temperature=1.0)
        b = L.sample(params, cfg, prompt, jax.random.PRNGKey(3), steps=16,
                     cache_len=32, temperature=1.0)
        c = L.sample(params, cfg, prompt, jax.random.PRNGKey(4), steps=16,
                     cache_len=32, temperature=1.0)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert not np.array_equal(np.asarray(a), np.asarray(c))

    def test_top_k_restricts_support(self):
        logits = jnp.asarray([[0.0, 1.0, 2.0, 3.0, 4.0]])
        keys = jax.random.split(jax.random.PRNGKey(0), 64)
        draws = {
            int(L.sample_logits(logits, k, temperature=1.0, top_k=2)[0])
            for k in keys
        }
        assert draws <= {3, 4}
        assert len(draws) == 2  # both survivors actually reachable

    def test_top_p_keeps_nucleus_only(self):
        # softmax of [10, 9, 0, 0, 0]: top-2 carry ~99.99% of the mass.
        logits = jnp.asarray([[10.0, 9.0, 0.0, 0.0, 0.0]])
        keys = jax.random.split(jax.random.PRNGKey(0), 64)
        draws = {
            int(L.sample_logits(logits, k, temperature=1.0, top_p=0.9)[0])
            for k in keys
        }
        assert draws <= {0, 1}

    def test_top_p_always_keeps_best_token(self):
        logits = jnp.asarray([[5.0, 0.0]])
        tok = L.sample_logits(
            logits, jax.random.PRNGKey(0), temperature=1.0, top_p=0.01
        )
        assert int(tok[0]) == 0


class TestLora:
    def test_init_is_identity(self, tiny):
        """b=0 ⇒ merged == base, the standard LoRA start."""
        cfg, params = tiny
        lcfg = LoraConfig(rank=4)
        lora = init_lora_params(cfg, lcfg, jax.random.PRNGKey(1))
        merged = merge_lora(params, lora, lcfg)
        tokens = jax.random.randint(jax.random.PRNGKey(2), (1, 8), 0, cfg.vocab_size)
        np.testing.assert_allclose(
            np.asarray(L.forward(merged, cfg, tokens)),
            np.asarray(L.forward(params, cfg, tokens)),
            rtol=1e-5, atol=1e-5,
        )

    def test_training_decreases_loss_and_freezes_base(self, tiny):
        cfg, params = tiny
        lcfg = LoraConfig(rank=4, targets=("wq", "wv"))
        lora = init_lora_params(cfg, lcfg, jax.random.PRNGKey(1), dtype=jnp.float32)
        init_state, step = make_lora_train_step(cfg, lcfg, learning_rate=1e-2)
        state = init_state(lora)
        tokens = jax.random.randint(jax.random.PRNGKey(2), (4, 32), 0, cfg.vocab_size)
        base_before = jax.tree_util.tree_map(lambda x: np.asarray(x), params)
        first = last = None
        for _ in range(8):
            state, loss = step(state, params, tokens)
            first = first if first is not None else float(loss)
            last = float(loss)
        assert last < first
        # Base weights untouched.
        for a, b in zip(
            jax.tree_util.tree_leaves(base_before),
            jax.tree_util.tree_leaves(params),
        ):
            np.testing.assert_array_equal(a, np.asarray(b))
        # Adapters actually moved.
        assert float(jnp.abs(state["lora"]["wq"]["b"]).max()) > 0

    def test_param_count_is_small(self):
        cfg = L.LLAMA_CONFIGS["llama-2-7b"]
        lcfg = LoraConfig(rank=8)
        # q + v adapters at rank 8: ~0.1% of the base model.
        assert lora_param_count(cfg, lcfg) < cfg.param_count() * 0.002

    def test_unknown_target_rejected(self, tiny):
        cfg, _ = tiny
        with pytest.raises(ValueError, match="unknown LoRA targets"):
            init_lora_params(cfg, LoraConfig(targets=("embed",)),
                             jax.random.PRNGKey(0))

    def test_sharded_lora_training_on_mesh(self, tiny):
        """plan is honored: the step runs over the mesh with a sharded
        batch and the loss still decreases."""
        from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh
        from kubeflow_tpu.models.train import shard_state

        cfg, _ = tiny
        plan = MeshPlan(make_mesh(dp=2, fsdp=2, tp=2))
        params = plan.shard_params(L.init_params(cfg, jax.random.PRNGKey(0)))
        lcfg = LoraConfig(rank=4)
        lora = init_lora_params(cfg, lcfg, jax.random.PRNGKey(1), dtype=jnp.float32)
        init_state, step = make_lora_train_step(
            cfg, lcfg, plan=plan, learning_rate=1e-2
        )
        state = init_state(lora)
        tokens = jax.random.randint(jax.random.PRNGKey(2), (4, 32), 0, cfg.vocab_size)
        first = last = None
        for _ in range(4):
            state, loss = step(state, params, tokens)
            first = first if first is not None else float(loss)
            last = float(loss)
        assert last < first


class TestTiedEmbeddings:
    def test_tied_init_has_single_storage(self):
        cfg = L.LlamaConfig(vocab_size=64, dim=32, n_layers=1, n_heads=2,
                            n_kv_heads=2, ffn_hidden=64, tie_embeddings=True)
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        assert "lm_head" not in params
        assert L.forward(params, cfg, jnp.zeros((1, 4), jnp.int32)).shape == (
            1, 4, 64,
        )

    def test_tied_training_keeps_weights_tied(self):
        """Gradients from the lookup AND the projection land in the one
        embed leaf — an aliased two-leaf layout would silently untie."""
        from kubeflow_tpu.models.train import make_train_step, shard_state
        from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh

        cfg = L.LlamaConfig(vocab_size=64, dim=32, n_layers=1, n_heads=2,
                            n_kv_heads=2, ffn_hidden=64, tie_embeddings=True,
                            dtype=jnp.float32)
        plan = MeshPlan(make_mesh(dp=8))
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        init_state, step = make_train_step(cfg, plan)
        state = shard_state(plan, init_state(params))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64)
        state, _ = step(state, tokens)
        assert "lm_head" not in state["params"]


class TestQuantization:
    def test_weight_round_trip_error_bounded(self):
        w = jax.random.normal(jax.random.PRNGKey(0), (2, 64, 32), jnp.float32)
        qw = quantize_weight(w, axis=1)
        assert qw["q"].dtype == jnp.int8
        back = dequantize_weight(qw, jnp.float32)
        # Per-channel symmetric int8: max error ≤ scale/2 per channel.
        err = jnp.abs(back - w)
        assert float(err.max() / jnp.abs(w).max()) < 1.0 / 127

    def test_quantized_forward_close_to_dense(self, tiny):
        cfg, params = tiny
        qparams = quantize_params(params)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, cfg.vocab_size)
        dense = np.asarray(L.forward(params, cfg, tokens))
        quant = np.asarray(L.forward(qparams, cfg, tokens))
        # Logit-level agreement: same argmax on nearly every position.
        agree = (dense.argmax(-1) == quant.argmax(-1)).mean()
        assert agree > 0.9
        cos = (dense * quant).sum() / (
            np.linalg.norm(dense) * np.linalg.norm(quant)
        )
        assert cos > 0.99

    def test_quantized_generation_runs_fused(self, tiny):
        cfg, params = tiny
        qparams = quantize_params(params)
        prompt = jax.random.randint(jax.random.PRNGKey(2), (1, 8), 0, cfg.vocab_size)
        toks = L.generate(qparams, cfg, prompt, steps=8, cache_len=16)
        assert toks.shape == (1, 8)

    def test_bytes_roughly_halved(self, tiny):
        cfg, params = tiny
        bf16 = jax.tree_util.tree_map(lambda x: x.astype(jnp.bfloat16), params)
        q = quantize_params(bf16)
        # Projections dominate tiny's embed less than 7B's, so just assert
        # a real reduction.
        assert quantized_bytes(q) < quantized_bytes(bf16) * 0.8


class TestInt4Quantization:
    def test_round_trip_error_bounded(self):
        from kubeflow_tpu.models.quant import quantize_weight_int4

        w = jax.random.normal(jax.random.PRNGKey(0), (2, 64, 32), jnp.float32)
        qw = quantize_weight_int4(w, axis=1, group=16)
        assert qw["q"].dtype == jnp.int4
        assert qw["s"].shape == (2, 4, 32)  # 64 // 16 groups
        back = dequantize_weight(qw, jnp.float32)
        # Group-wise symmetric int4: error ≤ group_scale/2, i.e. ≤ 1/14 of
        # the group max — much tighter than a per-channel int4 would be.
        err = jnp.abs(back - w)
        grouped = jnp.abs(w).reshape(2, 4, 16, 32).max(axis=2, keepdims=True)
        bound = jnp.broadcast_to(grouped / 14.0 * 1.01, (2, 4, 16, 32))
        assert bool(jnp.all(err.reshape(2, 4, 16, 32) <= bound))

    def test_forward_exactly_matches_dequantized_tree(self, tiny):
        """The fused int4 matmul path must equal running the model on the
        explicitly-dequantized weights — the strong correctness property
        (a random-init tiny model's argmax is too noise-sensitive for
        agreement bounds; real trained models tolerate int4 far better)."""
        cfg, params = tiny
        qparams = quantize_params(params, bits=4, group=32)
        deq = dict(qparams)
        deq["layers"] = {
            k: (dequantize_weight(v) if isinstance(v, dict) else v)
            for k, v in qparams["layers"].items()
        }
        if isinstance(deq.get("lm_head"), dict):
            deq["lm_head"] = dequantize_weight(deq["lm_head"])
        tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0, cfg.vocab_size)
        quant = np.asarray(L.forward(qparams, cfg, tokens))
        ref = np.asarray(L.forward(deq, cfg, tokens))
        assert np.abs(quant - ref).max() < 1e-5
        # Loose sanity vs the unquantized model.
        dense = np.asarray(L.forward(params, cfg, tokens))
        cos = (dense * quant).sum() / (
            np.linalg.norm(dense) * np.linalg.norm(quant)
        )
        assert cos > 0.9

    def test_generation_runs_fused(self, tiny):
        cfg, params = tiny
        qparams = quantize_params(params, bits=4, group=32)
        prompt = jax.random.randint(jax.random.PRNGKey(2), (1, 8), 0, cfg.vocab_size)
        toks = L.generate(qparams, cfg, prompt, steps=8, cache_len=16)
        assert toks.shape == (1, 8)

    def test_group_must_divide_and_fit(self):
        from kubeflow_tpu.models.quant import quantize_weight_int4

        w = jax.random.normal(jax.random.PRNGKey(0), (64, 32))
        with pytest.raises(ValueError, match="divisible"):
            quantize_weight_int4(w, axis=0, group=48)
        with pytest.raises(ValueError, match="must be in"):
            quantize_weight_int4(w, axis=0, group=64)  # == contraction dim
        with pytest.raises(ValueError, match="must be in"):
            quantize_weight_int4(w, axis=0, group=1)  # shape-ambiguous

    def test_free_source_validates_before_deleting(self, tiny):
        """A bad group must fail BEFORE any bf16 buffer is deleted."""
        cfg, params = tiny
        with pytest.raises(ValueError):
            quantize_params(params, bits=4, group=48, free_source=True)
        # The source tree survived intact and still runs.
        tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, cfg.vocab_size)
        assert L.forward(params, cfg, tokens).shape == (1, 8, cfg.vocab_size)
