"""good: the one deliberate per-step readback is bound to a
host_-prefixed local (the engines' budgeted-sync convention); the
device-side math never leaks a hidden sync.
"""
import jax.numpy as jnp
import numpy as np


def drive_once(batch):
    logits = jnp.matmul(batch, batch)
    host_probs = np.asarray(logits)
    return host_probs


def _step(state):
    out = jnp.add(state, 1)
    return jnp.maximum(out, 0)
