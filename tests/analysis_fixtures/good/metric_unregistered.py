"""GOOD: references a registered family (and a Histogram series suffix)."""

EXPECTED_SERIES = "tpu_slice_preemptions_total"
EXPECTED_HISTOGRAM_SERIES = "tpu_slice_recovery_seconds_count"
