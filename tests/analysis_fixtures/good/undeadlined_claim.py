"""GOOD: every wait on the recovery/migration path carries an explicit
bound, so a blown budget becomes a fallback instead of a hang."""

import http.client
import time

from kubeflow_tpu.controller.slicepool import claim_warm_slice


def escalate_recovery(client, namespace, topo):
    return claim_warm_slice(
        client, namespace, topo, deadline=time.perf_counter() + 5.0
    )


def probe_new_slice(host, port):
    conn = http.client.HTTPConnection(host, port, timeout=2.0)
    conn.request("GET", "/healthz")
    return conn.getresponse().status
