"""GOOD: imports the name from its contract home instead of re-typing it."""

from kubeflow_tpu.webhook.tpu_env import TPU_TOPOLOGY


def topology_var():
    return TPU_TOPOLOGY
