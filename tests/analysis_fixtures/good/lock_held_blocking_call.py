"""GOOD: the critical section only copies state; the wait happens outside."""

import threading
import time

_lock = threading.Lock()
_pending = []


def flush():
    with _lock:
        batch = list(_pending)
        _pending.clear()
    time.sleep(0.01)
    return batch
