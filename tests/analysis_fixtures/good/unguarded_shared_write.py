"""good: every mutation of the shared counter goes through the same
lock, whichever thread performs it.
"""
import threading


class StreamTally:
    def __init__(self):
        self._wlock = threading.Lock()
        self.completed = 0

    def run(self):
        while True:
            with self._wlock:
                self.completed += 1

    def note_done(self):
        with self._wlock:
            self.completed += 1
