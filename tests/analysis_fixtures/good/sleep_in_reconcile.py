"""GOOD: reconcile hands time back to the manager's requeue heap."""


def reconcile(obj):
    return {"requeue_after": 30.0}
