"""GOOD: every ring fan-out bounds the peer set at the loop header (or
breaks on a fanout counter) and gives each hop its own timeout, so a
walk costs at most fanout x hop_timeout."""

import http.client


def probe_some_peers(peers, keys, fanout):
    matched = {}
    for ep in peers[:fanout]:
        host, port = ep.rsplit(":", 1)
        conn = http.client.HTTPConnection(host, int(port), timeout=1.0)
        conn.request("POST", "/kv/probe", keys)
        matched[ep] = conn.getresponse().read()
    return matched


def walk_with_budget(ring, key, budget):
    out = []
    for ep in ring.successors(key, budget):
        host, port = ep.rsplit(":", 1)
        conn = http.client.HTTPConnection(host, int(port), timeout=0.5)
        conn.request("GET", "/healthz")
        out.append((ep, conn.getresponse().status))
    return out


def counter_bounded_walk(peers, fanout):
    probed = 0
    for ep in peers:
        host, port = ep.rsplit(":", 1)
        conn = http.client.HTTPConnection(host, int(port), timeout=0.5)
        conn.request("GET", "/healthz")
        probed += 1
        if probed >= fanout:
            break
    return probed
