"""good (peer): callback runs after the local lock is released.

reconcile() snapshots what it needs under TierLedgerB._block, exits the
with-block, and only then calls credit() — so TierLedgerB._block is
never held while SliceLedgerA._alock is acquired.
"""
import threading

from lock_order_cycle import SliceLedgerA


class TierLedgerB:
    def __init__(self):
        self._block = threading.Lock()
        self.owner = SliceLedgerA()
        self.pending = 0

    def settle(self):
        with self._block:
            self.pending = 0

    def reconcile(self):
        with self._block:
            due = self.pending
        for _ in range(due):
            self.owner.credit()
