"""GOOD: reads through the contract constant; the name resolves to a
declared ENV_CONTRACT key."""

import os

from kubeflow_tpu.webhook import tpu_env as contract


def worker_id():
    return os.environ.get(contract.TPU_WORKER_ID)
