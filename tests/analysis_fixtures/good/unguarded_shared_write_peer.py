"""good (peer): same cross-file spawn; harmless now that every write in
unguarded_shared_write.py shares one lock.
"""
import threading

from unguarded_shared_write import StreamTally


def start_tally() -> StreamTally:
    tally = StreamTally()
    threading.Thread(target=tally.run, daemon=True).start()
    return tally
