"""GOOD: tpu_* family name (also registered, so no unregistered finding)."""

from prometheus_client import Counter

OK = Counter("tpu_slice_preemptions_total", "Scheme-conformant family")
