"""GOOD: every thread either declares daemon= or is joined with a bound."""

import threading


def start_daemon(fn):
    worker = threading.Thread(target=fn, daemon=True)
    worker.start()
    return worker


def run_bounded(fn):
    worker = threading.Thread(target=fn)
    worker.start()
    worker.join(timeout=5.0)
    return worker
