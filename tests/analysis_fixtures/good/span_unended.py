"""GOOD: the context-manager form, the try/finally form, and the
begin_span cross-thread form (exempt by design — another thread ends it)."""

from kubeflow_tpu.observability.tracing import get_tracer


def handle(payload):
    with get_tracer("fixture").start_span("handle") as span:
        span.set_attribute("size", len(payload))
        return do_work(payload)


def drive(payload):
    span = get_tracer("fixture").start_span("drive")
    try:
        return do_work(payload)
    finally:
        if span is not None:
            span.end()


def submit(payload, registry):
    registry["queue_wait"] = get_tracer("fixture").begin_span("queue_wait")
    return do_work(payload)


def do_work(payload):
    return payload
