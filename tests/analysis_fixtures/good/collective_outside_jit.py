"""good: every collective lives under a trace. _step is jit-decorated;
drive_once is jit-wrapped at module level, which traces _fused through
the call-graph closure; _merge only ever runs through a shard_map wrap
(the engines' pattern for sp/tp bodies). The axis names are always
bound when these bodies execute.
"""
import functools

import jax
import jax.numpy as jnp

from kubeflow_tpu.parallel.compat import shard_map


@functools.partial(jax.jit, static_argnames=())
def _step(state):
    out = jnp.add(state, 1)
    return jax.lax.psum(out, "tp")


def _fused(batch):
    return jax.lax.psum(jnp.matmul(batch, batch), "tp")


def drive_once(batch):
    return _fused(batch)


step = jax.jit(drive_once)


def _merge(parts):
    return jax.lax.psum(parts, "tp")


def build_merge(mesh, specs):
    return shard_map(
        functools.partial(_merge),
        mesh=mesh, in_specs=specs, out_specs=specs,
    )
