"""GOOD: every serving/gateway family registered here has a
STATS_PARITY entry, and every STATS_PARITY key is registered in this
module."""

from prometheus_client import CollectorRegistry, Counter

REGISTRY = CollectorRegistry()

STATS_PARITY = {
    "tpu_serving_requests_shed_total": "requests_shed",
    "tpu_gateway_shed_total": "shed",
}

shed = Counter(
    "tpu_serving_requests_shed_total",
    "fixture mirror of the real shed family",
    registry=REGISTRY,
)

gateway_shed = Counter(
    "tpu_gateway_shed_total",
    "fixture mirror of the gateway shed family",
    ["tenant"],
    registry=REGISTRY,
)
