"""GOOD: emits on registered attributes, including the getattr form."""


def emit(metrics):
    metrics.slice_preemptions_total.inc()
    counter = getattr(metrics, "checkpoint_emergency_total", None)
    if counter is not None:
        counter.inc()
