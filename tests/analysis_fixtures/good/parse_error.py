"""GOOD: parseable module."""


def fine():
    return 1
