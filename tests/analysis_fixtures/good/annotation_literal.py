"""GOOD: annotation keys come from the api/ vocabulary."""

from kubeflow_tpu.api import annotations as ann

PREPULL_KEY = ann.PREPULL_LABEL
