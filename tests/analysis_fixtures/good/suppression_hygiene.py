"""GOOD: a justified suppression — the finding exists but is suppressed."""

import time


def reconcile(obj):
    time.sleep(0.01)  # kftpu-lint: disable=sleep-in-reconcile — fixture: demonstrates the justified-suppression syntax
