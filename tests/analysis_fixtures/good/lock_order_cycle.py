"""good: the same two modules with one fleet-wide acquisition order.

A's lock is always taken before B's (checkout -> settle), and the
peer's reconcile() drops its own lock before calling back into
credit() — no opposite-order path exists, so no cycle.
"""
import threading

from lock_order_cycle_peer import TierLedgerB


class SliceLedgerA:
    def __init__(self):
        self._alock = threading.Lock()
        self.peer = TierLedgerB()
        self.total = 0

    def checkout(self):
        with self._alock:
            self.peer.settle()

    def credit(self):
        with self._alock:
            self.total += 1
