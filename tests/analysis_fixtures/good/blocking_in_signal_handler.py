"""GOOD: handler only flips an Event; the blocking work lives on a thread."""

import signal
import threading

_stop = threading.Event()


def _handler(signum, frame):
    _stop.set()


def install():
    signal.signal(signal.SIGTERM, _handler)
