"""good: the slow work runs outside the critical section; the lock is
re-taken only to publish the result.
"""
import threading
import urllib.request


class WarmPoolView:
    def __init__(self):
        self._plock = threading.Lock()
        self.cached = None

    def refresh(self):
        payload = self._fetch()
        with self._plock:
            self.cached = payload

    def _fetch(self):
        return urllib.request.urlopen("http://pool/status").read()
