"""BAD: HTTP fan-out over ring members with no fanout bound and no
per-hop timeout — one walk can visit the whole fleet, and the first
half-dead peer hangs the entire walk."""

import http.client
import urllib.request


def probe_all_peers(peers, keys):
    matched = {}
    for ep in peers:
        host, port = ep.rsplit(":", 1)
        conn = http.client.HTTPConnection(host, int(port), timeout=1.0)
        conn.request("POST", "/kv/probe", keys)
        matched[ep] = conn.getresponse().read()
    return matched


def walk_whole_ring(ring, key):
    for ep in ring.successors(key, len(ring)):
        urllib.request.urlopen(f"http://{ep}/healthz")


def hang_on_first_corpse(peers, fanout):
    for ep in peers[:fanout]:
        host, port = ep.rsplit(":", 1)
        conn = http.client.HTTPConnection(host, int(port))
        conn.request("GET", "/healthz")
