"""BAD: reads a TPU_* env var no producer declares (ENV_CONTRACT miss)."""

import os


def phantom_setting():
    return os.environ.get("TPU_TOTALLY_UNDECLARED")
