"""BAD: sleeping inside reconcile wedges every queued object."""

import time


def reconcile(obj):
    time.sleep(5.0)
    return None
