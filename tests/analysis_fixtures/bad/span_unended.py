"""BAD: span started, .end() only on the happy path — an exception in
do_work leaks the span and leaves it current on the handler thread."""

from kubeflow_tpu.observability.tracing import get_tracer


def handle(payload):
    span = get_tracer("fixture").start_span("handle")
    result = do_work(payload)
    span.end()
    return result


def do_work(payload):
    return payload
