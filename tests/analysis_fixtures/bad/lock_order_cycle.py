"""bad: two-module lock-order cycle (kftpu-lock-order-cycle).

checkout() holds SliceLedgerA._alock and calls into the peer module,
where settle() takes TierLedgerB._block — while the peer's reconcile()
holds TierLedgerB._block and calls back into credit(), which takes
SliceLedgerA._alock. Opposite orders across two files: threads
interleaving checkout() and reconcile() deadlock.
"""
import threading

from lock_order_cycle_peer import TierLedgerB


class SliceLedgerA:
    def __init__(self):
        self._alock = threading.Lock()
        self.peer = TierLedgerB()
        self.total = 0

    def checkout(self):
        with self._alock:
            self.peer.settle()

    def credit(self):
        with self._alock:
            self.total += 1
