"""BAD: registers a family outside the tpu_* naming scheme."""

from prometheus_client import Counter

ROGUE = Counter("weird_unprefixed_total", "A family dashboards cannot select")
