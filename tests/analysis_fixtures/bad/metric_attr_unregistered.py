"""BAD: emits on a Metrics attribute __init__ never defines."""


def emit(metrics):
    metrics.totally_unknown_counter.inc()
