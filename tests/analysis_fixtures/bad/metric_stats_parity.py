"""BAD: registers serving AND gateway families no STATS_PARITY entry
surfaces (and lists a family the module never registers)."""

from prometheus_client import CollectorRegistry, Counter

REGISTRY = CollectorRegistry()

STATS_PARITY = {
    "tpu_serving_requests_shed_total": "requests_shed",
}

orphan = Counter(
    "tpu_serving_orphan_widgets_total",
    "registered but absent from STATS_PARITY",
    registry=REGISTRY,
)

gateway_orphan = Counter(
    "tpu_gateway_orphan_hops_total",
    "gateway family registered but absent from STATS_PARITY",
    registry=REGISTRY,
)
