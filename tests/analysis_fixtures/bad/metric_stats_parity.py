"""BAD: registers a serving family no STATS_PARITY entry surfaces (and
lists a family the module never registers)."""

from prometheus_client import CollectorRegistry, Counter

REGISTRY = CollectorRegistry()

STATS_PARITY = {
    "tpu_serving_requests_shed_total": "requests_shed",
}

orphan = Counter(
    "tpu_serving_orphan_widgets_total",
    "registered but absent from STATS_PARITY",
    registry=REGISTRY,
)
