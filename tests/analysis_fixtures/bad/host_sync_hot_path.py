"""bad: hidden device->host syncs in the engine-step hot set
(kftpu-host-sync-in-hot-path).

drive_once and _step are hot-path roots; np.asarray of a device value
and float() of a device array each force a blocking readback that
serializes the dispatch pipeline.
"""
import jax.numpy as jnp
import numpy as np


def drive_once(batch):
    logits = jnp.matmul(batch, batch)
    probs = np.asarray(logits)
    return probs


def _step(state):
    out = jnp.add(state, 1)
    return float(out)
