"""BAD: thread with neither daemon= nor a join() story outlives SIGTERM."""

import threading


def start_worker(fn):
    worker = threading.Thread(target=fn)
    worker.start()
    return worker
