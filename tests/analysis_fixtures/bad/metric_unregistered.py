"""BAD: references a metric family metrics.py never registers."""

EXPECTED_SERIES = "tpu_nonexistent_series_total"
