"""BAD: time.sleep while holding a lock stalls every other thread."""

import threading
import time

_lock = threading.Lock()


def flush():
    with _lock:
        time.sleep(1.0)
