"""BAD: unbounded waits on a recovery/migration path — the claim walk
can loop under contention and the cross-slice probe can hang on a
half-dead host; either wedges the pipeline that exists to beat a
deadline."""

import http.client

from kubeflow_tpu.controller.slicepool import claim_warm_slice


def escalate_recovery(client, namespace, topo):
    return claim_warm_slice(client, namespace, topo)


def probe_new_slice(host, port):
    conn = http.client.HTTPConnection(host, port)
    conn.request("GET", "/healthz")
    return conn.getresponse().status
