"""bad: an eager jax.lax collective on the serving hot set
(kftpu-collective-outside-jit).

drive_once and _step are hot-path roots and neither is jit/shard_map-
wrapped, so the psum/all_gather axis names are unbound at call time —
the tp collective must live inside the jitted step body.
"""
import jax
import jax.numpy as jnp


def drive_once(batch):
    logits = jnp.matmul(batch, batch)
    return jax.lax.psum(logits, "tp")


def _step(state):
    out = jnp.add(state, 1)
    gathered = jax.lax.all_gather(out, "tp")
    return gathered
