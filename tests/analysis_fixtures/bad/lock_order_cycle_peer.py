"""bad (peer): the second half of the two-module lock-order cycle.

reconcile() holds TierLedgerB._block across a call back into the other
module's credit(), which acquires SliceLedgerA._alock — the reverse of
the order checkout() uses. The circular import is harmless to the
linter: analysis is pure ast, nothing here is executed.
"""
import threading

from lock_order_cycle import SliceLedgerA


class TierLedgerB:
    def __init__(self):
        self._block = threading.Lock()
        self.owner = SliceLedgerA()

    def settle(self):
        with self._block:
            pass

    def reconcile(self):
        with self._block:
            self.owner.credit()
