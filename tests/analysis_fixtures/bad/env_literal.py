"""BAD: re-types a contract env var name as a string literal."""

WORKER_ID_VAR = "TPU_WORKER_ID"
