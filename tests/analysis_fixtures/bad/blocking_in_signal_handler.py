"""BAD: blocking queue op inside a signal handler (the PR 3 deadlock shape)."""

import queue
import signal
import time

save_queue = queue.Queue(maxsize=1)


def _handler(signum, frame):
    save_queue.put(("emergency-save", signum))
    time.sleep(0.1)


def install():
    signal.signal(signal.SIGTERM, _handler)
