"""BAD: not Python — the engine must report parse-error, not crash."""
def broken(:
