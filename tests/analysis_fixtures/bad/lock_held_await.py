"""bad: lock held across a call-graph-reachable blocking call
(kftpu-lock-held-await).

refresh() never blocks *directly* — the single-function rule
(lock-held-blocking-call) sees nothing — but the _fetch() it calls
under the lock does network I/O. Every thread needing _plock stalls
for the full HTTP round trip.
"""
import threading
import urllib.request


class WarmPoolView:
    def __init__(self):
        self._plock = threading.Lock()
        self.cached = None

    def refresh(self):
        with self._plock:
            self.cached = self._fetch()

    def _fetch(self):
        return urllib.request.urlopen("http://pool/status").read()
