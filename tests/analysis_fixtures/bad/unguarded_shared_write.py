"""bad: shared counter written from two thread entry paths with no
common lock (kftpu-unguarded-shared-write).

run() is a loop-method entry (spawned as a Thread target from the peer
module — see unguarded_shared_write_peer.py) and bumps the tally
unlocked; note_done() is called by request threads and bumps it under
StreamTally._wlock. Different guards on the same counter: increments
from the two paths can be lost.
"""
import threading


class StreamTally:
    def __init__(self):
        self._wlock = threading.Lock()
        self.completed = 0

    def run(self):
        while True:
            self.completed += 1

    def note_done(self):
        with self._wlock:
            self.completed += 1
