"""BAD: spells a wire-contract annotation key inline."""

MADE_UP_KEY = "notebooks.kubeflow.org/made-up-key"
