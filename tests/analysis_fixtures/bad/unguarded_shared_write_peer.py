"""bad (peer): the cross-file thread spawn that makes StreamTally.run a
second writer thread. The race itself is reported in
unguarded_shared_write.py — this module shows why run() is an entry.
"""
import threading

from unguarded_shared_write import StreamTally


def start_tally() -> StreamTally:
    tally = StreamTally()
    threading.Thread(target=tally.run, daemon=True).start()
    return tally
