"""BAD: suppression without a justification (and it suppresses nothing)."""

import time


def reconcile(obj):
    time.sleep(0.5)  # kftpu-lint: disable=sleep-in-reconcile
