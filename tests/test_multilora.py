"""Multi-LoRA serving (models/multilora.py).

The correctness contract: a batch mixing adapters A, B, and base rows
must emit, per row, EXACTLY the tokens a plain ContinuousBatcher emits
when serving merge_lora(params, that row's adapter) — the stacked
gather + skinny-einsum delta is an implementation detail, never a
numerics change.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models import llama as L
from kubeflow_tpu.models.continuous import ContinuousBatcher
from kubeflow_tpu.models.lora import LoraConfig, init_lora_params, merge_lora
from kubeflow_tpu.models.multilora import MultiLoraBatcher, stack_adapters
from kubeflow_tpu.models.serving import GenerationConfig

CFG = L.LLAMA_CONFIGS["tiny"]
PARAMS = L.init_params(CFG, jax.random.PRNGKey(0))
LCFG = LoraConfig(rank=4, targets=("wq", "wv", "w_down"))


def _adapter(seed: int) -> dict:
    """A NON-trivial adapter: b is zero-init, so fill it with noise —
    a zero delta would make every parity test pass vacuously."""
    ad = init_lora_params(CFG, LCFG, jax.random.PRNGKey(seed))
    return jax.tree_util.tree_map(
        lambda x: x + 0.05 * jax.random.normal(
            jax.random.PRNGKey(seed + 100), x.shape, x.dtype
        ),
        ad,
    )


AD0, AD1 = _adapter(1), _adapter(2)
STACKED = stack_adapters([AD0, AD1], CFG, LCFG)
GEN = GenerationConfig(max_new_tokens=6)
PROMPTS = [[1, 2, 3, 4, 5], [7, 8, 9], [11, 12, 13, 14]]


def _reference(adapter, prompts):
    params = merge_lora(PARAMS, adapter, LCFG) if adapter else PARAMS
    cb = ContinuousBatcher(params, CFG, gen=GEN, slots=2, cache_len=128,
                           prompt_bucket=16)
    rids = [cb.submit(p) for p in prompts]
    out = cb.run()
    return [out[r] for r in rids]


def _multilora(tags, prompts):
    mb = MultiLoraBatcher(PARAMS, CFG, STACKED, LCFG,
                          adapter_names=["a0", "a1"], gen=GEN, slots=2,
                          cache_len=128, prompt_bucket=16)
    rids = [mb.submit(p, adapter=t) for p, t in zip(prompts, tags)]
    out = mb.run()
    return [out[r] for r in rids]


class TestParity:
    def test_adapter_rows_match_merged_server(self):
        got = _multilora(["a0"] * 3, PROMPTS)
        assert got == _reference(AD0, PROMPTS)

    def test_base_rows_match_unmerged_server(self):
        got = _multilora([None] * 3, PROMPTS)
        assert got == _reference(None, PROMPTS)

    def test_mixed_batch_each_row_its_own_adapter(self):
        """The decisive case: rows with DIFFERENT adapters share one
        batch (and slot reuse hands slot 0 to a different adapter than
        its previous occupant)."""
        tags = ["a0", "a1", None]
        got = _multilora(tags, PROMPTS)
        want = [
            _reference(AD0, [PROMPTS[0]])[0],
            _reference(AD1, [PROMPTS[1]])[0],
            _reference(None, [PROMPTS[2]])[0],
        ]
        assert got == want

    def test_adapters_actually_differ(self):
        """Guard against a vacuous suite: the two adapters and base must
        produce three DIFFERENT outputs for the same prompt."""
        p = [PROMPTS[0]]
        outs = {str(_reference(ad, p)[0]) for ad in (AD0, AD1, None)}
        assert len(outs) == 3, "adapter deltas are numerically invisible"


class TestApi:
    def test_adapter_resolution(self):
        mb = MultiLoraBatcher(PARAMS, CFG, STACKED, LCFG,
                              adapter_names=["a0", "a1"], gen=GEN,
                              slots=2, cache_len=128, prompt_bucket=16)
        assert mb.resolve_adapter("a1") == 1
        assert mb.resolve_adapter(0) == 0
        assert mb.resolve_adapter(None) == 2  # the zero/base row
        with pytest.raises(ValueError, match="unknown adapter"):
            mb.resolve_adapter("nope")
        with pytest.raises(ValueError, match="out of range"):
            mb.resolve_adapter(5)
        # non-str/int must be a clean ValueError (HTTP 400), and a float
        # must never silently truncate to a different adapter
        for bad in (["a0"], 1.7, True, {"name": "a0"}):
            with pytest.raises(ValueError, match="adapter"):
                mb.resolve_adapter(bad)

    def test_rejects_unsupported_compositions(self):
        with pytest.raises(ValueError, match="kv_bits"):
            MultiLoraBatcher(PARAMS, CFG, STACKED, LCFG, kv_bits=8)
        with pytest.raises(ValueError, match="attn_kernel"):
            MultiLoraBatcher(PARAMS, CFG, STACKED, LCFG, attn_kernel=True)

    def test_stack_validates_shapes(self):
        other = init_lora_params(CFG, LoraConfig(rank=8, targets=LCFG.targets),
                                 jax.random.PRNGKey(9))
        with pytest.raises(ValueError, match="mismatch"):
            stack_adapters([AD0, other], CFG, LCFG)
        with pytest.raises(ValueError, match="at least one"):
            stack_adapters([], CFG, LCFG)
        # differing TARGET SETS must be a clear error in both orders —
        # silently dropping a target would break the merge_lora parity
        narrower = init_lora_params(
            CFG, LoraConfig(rank=4, targets=("wq",)), jax.random.PRNGKey(9)
        )
        with pytest.raises(ValueError, match="targets"):
            stack_adapters([AD0, narrower], CFG, LCFG)
        with pytest.raises(ValueError, match="targets"):
            stack_adapters([narrower, AD0], CFG, LCFG)

    def test_server_rejects_adapter_named_like_model(self):
        from kubeflow_tpu.models.server import InferenceServer

        mb = MultiLoraBatcher(PARAMS, CFG, STACKED, LCFG,
                              adapter_names=["kubeflow-tpu", "a1"],
                              gen=GEN, slots=2, cache_len=128,
                              prompt_bucket=16)
        with pytest.raises(ValueError, match="collides"):
            InferenceServer(mb, port=0)

    def test_paged_requires_ragged(self):
        from kubeflow_tpu.models.multilora import MultiLoraPagedBatcher

        with pytest.raises(ValueError, match="ragged"):
            MultiLoraPagedBatcher(PARAMS, CFG, STACKED, LCFG,
                                  adapter_names=["a0", "a1"],
                                  num_blocks=40)

    def test_paged_rejects_prefix_sharing(self):
        from kubeflow_tpu.models.multilora import MultiLoraPagedBatcher

        for kw in ({"prefix_cache": True}, {"prompt_cache": True}):
            with pytest.raises(ValueError, match="cache"):
                MultiLoraPagedBatcher(PARAMS, CFG, STACKED, LCFG,
                                      adapter_names=["a0", "a1"],
                                      num_blocks=40, ragged=True, **kw)

    def test_http_server_routes_model_field(self):
        """The HTTP front door's "model" field selects the adapter."""
        import json
        import urllib.request

        from kubeflow_tpu.models.server import InferenceServer

        mb = MultiLoraBatcher(PARAMS, CFG, STACKED, LCFG,
                              adapter_names=["a0", "a1"], gen=GEN,
                              slots=2, cache_len=128, prompt_bucket=16)
        srv = InferenceServer(mb, port=0).start()
        try:
            def post(payload):
                req = urllib.request.Request(
                    f"http://127.0.0.1:{srv.port}/v1/completions",
                    data=json.dumps(payload).encode(),
                )
                with urllib.request.urlopen(req, timeout=120) as resp:
                    return json.loads(resp.read())

            p = PROMPTS[0]
            out = post({"prompt": p, "model": "a0"})
            assert out["choices"][0]["tokens"] == _reference(AD0, [p])[0]
            base = post({"prompt": p})
            assert base["choices"][0]["tokens"] == _reference(None, [p])[0]
            import urllib.error

            with pytest.raises(urllib.error.HTTPError) as err:
                post({"prompt": p, "model": "nope"})
            assert err.value.code == 400
        finally:
            srv.stop()


class TestPagedRaggedParity:
    """MultiLoraBatcher ported onto the paged/ragged engine: per-row
    adapter deltas ride the SAME fused ragged dispatch as base rows, and
    each row's stream must exactly match a plain ragged PagedBatcher
    serving merge_lora(params, that row's adapter).

    Adapter seeds here (1, 5) are chosen off bf16 tie edges: the
    delta-form (x@A@B added) and merged-form (x@(W+AB)) matmuls are
    mathematically equal but not bitwise, and an adapter whose greedy
    path grazes a near-tie legitimately forks across the two forms (the
    same cross-shape standard the serving suites use).
    """

    ADB = _adapter(5)
    STACKED2 = stack_adapters([AD0, ADB], CFG, LCFG)

    def _paged_ref(self, adapter, prompts):
        from kubeflow_tpu.models.paged import PagedBatcher

        params = merge_lora(PARAMS, adapter, LCFG) if adapter else PARAMS
        pb = PagedBatcher(params, CFG, gen=GEN, slots=2, num_blocks=40,
                          block_size=8, prompt_bucket=16,
                          attn_kernel=False, ragged=True, token_budget=16)
        rids = [pb.submit(p) for p in prompts]
        out = pb.run()
        return [out[r] for r in rids]

    def _paged_multilora(self, tags, prompts, **kw):
        from kubeflow_tpu.models.multilora import MultiLoraPagedBatcher

        mb = MultiLoraPagedBatcher(
            PARAMS, CFG, self.STACKED2, LCFG, adapter_names=["a0", "ab"],
            gen=GEN, slots=2, num_blocks=40, block_size=8,
            prompt_bucket=16, attn_kernel=False, ragged=True,
            token_budget=16, **kw,
        )
        rids = [mb.submit(p, adapter=t) for p, t in zip(prompts, tags)]
        out = mb.run()
        return [out[r] for r in rids], mb

    def test_mixed_batch_each_row_its_own_adapter(self):
        """The decisive case: rows with DIFFERENT adapters (and a base
        row) share one fused ragged dispatch, and slot reuse hands a
        freed slot to a different adapter than its previous occupant."""
        got, _ = self._paged_multilora(["a0", "ab", None], PROMPTS)
        want = [
            self._paged_ref(AD0, [PROMPTS[0]])[0],
            self._paged_ref(self.ADB, [PROMPTS[1]])[0],
            self._paged_ref(None, [PROMPTS[2]])[0],
        ]
        assert got == want

    def test_adapters_actually_differ(self):
        p = [PROMPTS[0]]
        outs = {str(self._paged_ref(ad, p)[0])
                for ad in (AD0, self.ADB, None)}
        assert len(outs) == 3, "adapter deltas are numerically invisible"

    def test_hot_cache_counts_churn(self):
        """lora_cache_slots=1 with two adapters in flight: the second
        adapter's load evicts the first — counters expose the thrash the
        gateway's (prefix, adapter) affinity exists to avoid."""
        got, mb = self._paged_multilora(["a0", "ab", "a0"], PROMPTS,
                                        lora_cache_slots=1)
        st = mb.lora_cache_stats()
        assert st["slots"] == 1 and st["resident"] == 1
        assert st["misses"] >= 2 and st["evictions"] >= 1
        # Uncapped residency reports no cache at all.
        _, mb2 = self._paged_multilora(["a0"], [PROMPTS[0]])
        assert mb2.lora_cache_stats() is None

    def test_http_stats_surface_lora_cache(self):
        """/stats grows a ``lora_cache`` block the gateway scrape and
        fleet telemetry key on."""
        import json
        import urllib.request

        from kubeflow_tpu.models.multilora import MultiLoraPagedBatcher
        from kubeflow_tpu.models.server import InferenceServer

        mb = MultiLoraPagedBatcher(
            PARAMS, CFG, self.STACKED2, LCFG, adapter_names=["a0", "ab"],
            gen=GEN, slots=2, num_blocks=40, block_size=8,
            prompt_bucket=16, attn_kernel=False, ragged=True,
            token_budget=16, lora_cache_slots=2,
        )
        srv = InferenceServer(mb, port=0).start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/v1/completions",
                data=json.dumps({"prompt": PROMPTS[0],
                                 "model": "a0"}).encode(),
            )
            with urllib.request.urlopen(req, timeout=300) as resp:
                assert json.loads(resp.read())["choices"][0]["tokens"]
            with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/stats", timeout=30
            ) as resp:
                stats = json.loads(resp.read())
        finally:
            srv.stop()
        assert stats["lora_cache"]["slots"] == 2
        assert stats["lora_cache"]["misses"] >= 1
