"""Fleet KV tier: read-through peer prefix fetch.

Robustness is the product under test: the happy path imports a peer's
chain instead of re-prefilling (fused AND disagg routing), and EVERY
failure mode — dead peer, slow peer, oversized payload, version skew,
validation quarantine, concurrent duplicate fetch, negative-cache
expiry — degrades to local re-prefill with the request still streaming
every token + ``[DONE]``. The tier must also be fully inert when the
fanout knob is unset: zero hot-path cost, zero new sockets.
"""

from __future__ import annotations

import http.client
import http.server
import json
import threading
import time

import jax
import pytest

from kubeflow_tpu.models import llama as L
from kubeflow_tpu.models.gateway import (
    ServingGateway,
    gateway_from_env,
    prompt_chain_keys,
)
from kubeflow_tpu.models.paged import PagedBatcher
from kubeflow_tpu.models.server import InferenceServer
from kubeflow_tpu.models.serving import GenerationConfig

BS = 8
PROMPT_LEN = 20  # → 2 registrable chain blocks


@pytest.fixture(scope="module")
def tiny():
    cfg = L.LLAMA_CONFIGS["tiny"]
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _engine(tiny, **kw):
    cfg, params = tiny
    kw.setdefault("slots", 2)
    kw.setdefault("num_blocks", 32)
    kw.setdefault("prompt_bucket", 32)
    kw.setdefault("prefix_cache", True)
    return PagedBatcher(
        params, cfg, gen=GenerationConfig(max_new_tokens=8, eos_id=-1),
        block_size=BS, **kw,
    )


def _targeted_prompt(gw, endpoint: str, exclude=()) -> list:
    """A prompt whose fused affinity target is ``endpoint`` — the same
    nonce search the chaos catalog uses for victim targeting."""
    for nonce in range(3, 250):
        prompt = [nonce] + list(range(2, PROMPT_LEN + 1))
        if tuple(prompt) in exclude:
            continue
        # The prefix router learns the chain on first sight (a fresh
        # prompt routes by its first block, later calls by its deepest
        # block), so warm it once and target with the stable key the
        # actual request will also compute.
        gw._route_key(prompt)
        cands = gw._candidates(gw._route_key(prompt))
        if cands and cands[0] == endpoint:
            return prompt
    raise AssertionError(f"no prompt routed to {endpoint}")


def _stream(host, port, prompt, max_tokens=6, timeout=120) -> list:
    conn = http.client.HTTPConnection(host, port, timeout=timeout)
    conn.request(
        "POST", "/v1/completions",
        json.dumps({"prompt": prompt, "max_tokens": max_tokens,
                    "stream": True}).encode(),
        {"Content-Type": "application/json"})
    resp = conn.getresponse()
    assert resp.status == 200
    toks, done = [], False
    while True:
        line = resp.fp.readline()
        if not line:
            break
        if line == b"data: [DONE]\n":
            done = True
            break
        if line.startswith(b"data:"):
            body = json.loads(line[5:])
            assert "error" not in body, body
            toks.append(body["token"])
    conn.close()
    assert done, "stream ended without [DONE]"
    return toks


def _reference(tiny, prompt, max_tokens=6) -> list:
    eng = _engine(tiny)
    rid = eng.submit(prompt, max_new_tokens=max_tokens)
    return eng.run()[rid]


def _warm(srv, prompt) -> None:
    """Warm a replica's prefix cache by running the prompt directly on
    it (not through any gateway)."""
    conn = http.client.HTTPConnection(srv.host, srv.port, timeout=60)
    conn.request(
        "POST", "/v1/completions",
        json.dumps({"prompt": prompt, "max_tokens": 2}).encode(),
        {"Content-Type": "application/json"})
    assert conn.getresponse().status == 200
    conn.close()


class _FakePeer:
    """Replica impostor: healthy on /healthz (so it stays in the ring)
    but misbehaves on the peer-fetch endpoints per the injected
    behaviors. ``probe``/``chain`` are dicts to answer with, or None to
    tear the connection (a corpse / mid-export crash)."""

    def __init__(self, probe=None, chain=None, probe_delay=0.0):
        self.probe_hits = 0
        self.chain_hits = 0
        peer = self

        class _H(http.server.BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _json(self, code, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/healthz":
                    self._json(200, {"status": "ok"})
                else:
                    self._json(200, {})

            def do_POST(self):
                self.rfile.read(int(self.headers.get(
                    "Content-Length", 0) or 0))
                if self.path == "/kv/probe":
                    peer.probe_hits += 1
                    if probe_delay:
                        time.sleep(probe_delay)
                    if probe is None:
                        self.connection.close()
                        return
                    self._json(200, probe)
                elif self.path == "/kv/chain":
                    peer.chain_hits += 1
                    if chain is None:
                        self.connection.close()
                        return
                    self._json(200, chain)
                else:
                    self._json(404, {"error": "not found"})

        self._srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _H)
        self.host, self.port = self._srv.server_address[:2]
        self.endpoint = f"{self.host}:{self.port}"
        threading.Thread(target=self._srv.serve_forever,
                         daemon=True).start()

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()


def _chain_payload(tiny, prompt):
    """A genuine export of ``prompt``'s registrable chain, for fakes to
    serve (and tests to tamper with)."""
    eng = _engine(tiny)
    eng.submit(prompt, max_new_tokens=1)
    eng.run()
    return eng.export_chain(prompt_chain_keys(prompt, BS))


class TestInertWhenUnset:
    def test_default_gateway_never_touches_the_peer_tier(self, tiny):
        """No fanout knob → zero peer probes, zero chain traffic, and
        the /stats block says so."""
        srvs = [InferenceServer(_engine(tiny), port=0,
                                drain_s=0.5).start() for _ in range(2)]
        gw = ServingGateway(
            [f"{s.host}:{s.port}" for s in srvs], port=0, block_size=BS,
            health_interval_s=30.0,
        ).start()
        gw.probe_once()
        try:
            assert gw.kv_peer_fanout == 0
            prompt = [7] + list(range(2, PROMPT_LEN + 1))
            toks = _stream(gw.host, gw.port, prompt)
            assert len(toks) == 6
            stats = gw.stats()
            assert stats["kv_peer"]["enabled"] is False
            assert stats["kv_peer_fetches"] == 0
            assert stats["kv_peer_fetch_failures"] == 0
            for s in srvs:
                assert s.engine.kv_chain_exports == 0
                assert s.engine.kv_chain_imports == 0
        finally:
            gw.stop()
            for s in srvs:
                s.stop()

    def test_from_env_defaults_inert_and_parses_fail_fast(
            self, monkeypatch):
        monkeypatch.setenv("KUBEFLOW_TPU_GATEWAY_PORT", "0")
        monkeypatch.setenv("KUBEFLOW_TPU_GATEWAY_REPLICAS",
                           "10.0.0.1:8000")
        gw = gateway_from_env()
        assert gw.kv_peer_fanout == 0
        assert gw.kv_peer_timeout_s == 5.0
        assert gw.kv_peer_max_bytes == 64 << 20

        monkeypatch.setenv("KUBEFLOW_TPU_KV_PEER_FANOUT", "3")
        monkeypatch.setenv("KUBEFLOW_TPU_KV_PEER_TIMEOUT_S", "2.5")
        monkeypatch.setenv("KUBEFLOW_TPU_KV_PEER_MAX_BYTES", "1048576")
        gw = gateway_from_env()
        assert gw.kv_peer_fanout == 3
        assert gw.kv_peer_timeout_s == 2.5
        assert gw.kv_peer_max_bytes == 1048576

        # Garbage must raise, not silently disable the tier.
        for name, bad in (
            ("KUBEFLOW_TPU_KV_PEER_FANOUT", "0"),
            ("KUBEFLOW_TPU_KV_PEER_FANOUT", "many"),
            ("KUBEFLOW_TPU_KV_PEER_TIMEOUT_S", "0"),
            ("KUBEFLOW_TPU_KV_PEER_TIMEOUT_S", "fast"),
            ("KUBEFLOW_TPU_KV_PEER_MAX_BYTES", "-1"),
        ):
            with monkeypatch.context() as m:
                m.setenv(name, bad)
                with pytest.raises(ValueError, match=name):
                    gateway_from_env()

    def test_constructor_validates_knobs(self):
        with pytest.raises(ValueError, match="kv_peer_fanout"):
            ServingGateway([], port=0, kv_peer_fanout=-1)
        with pytest.raises(ValueError, match="kv_peer_timeout_s"):
            ServingGateway([], port=0, kv_peer_timeout_s=0)
        with pytest.raises(ValueError, match="kv_peer_max_bytes"):
            ServingGateway([], port=0, kv_peer_max_bytes=0)


class TestFusedPeerFetch:
    def test_peer_chain_imported_instead_of_reprefill(self, tiny):
        """Warm the ring NEIGHBOR, stream through the gateway: the
        target imports the neighbor's chain, counts a prefix hit, and
        the tokens match a fresh single-engine reference."""
        srvs = [InferenceServer(_engine(tiny), port=0,
                                drain_s=0.5).start() for _ in range(2)]
        eps = [f"{s.host}:{s.port}" for s in srvs]
        gw = ServingGateway(eps, port=0, block_size=BS,
                            health_interval_s=30.0,
                            kv_peer_fanout=2).start()
        gw.probe_once()  # full ring before nonce-targeting
        try:
            target = eps[0]
            prompt = _targeted_prompt(gw, target)
            peer_srv = srvs[1]
            _warm(peer_srv, prompt)
            toks = _stream(gw.host, gw.port, prompt)
            assert toks == _reference(tiny, prompt)
            stats = gw.stats()
            assert stats["kv_peer_fetches"] == 1
            assert stats["kv_peer_fetch_failures"] == 0
            assert stats["kv_peer_bytes"] > 0
            assert stats["kv_peer_fetch_latency_s"] > 0
            assert stats["kv_peer"]["failure_reasons"] == {}
            assert peer_srv.engine.kv_chain_exports == 1
            assert srvs[0].engine.kv_chain_imports == 1
            assert srvs[0].engine.prefix_hits >= 1
            assert srvs[0].engine.prefix_misses == 0
        finally:
            gw.stop()
            for s in srvs:
                s.stop()

    def test_single_flight_skips_duplicate_fetch(self, tiny):
        """A fetch already in flight for the same tail chain key makes
        the second request SKIP the ladder (straight to re-prefill) —
        no duplicate peer traffic, no waiting."""
        srv = InferenceServer(_engine(tiny), port=0, drain_s=0.5).start()
        gw = ServingGateway([f"{srv.host}:{srv.port}"], port=0,
                            block_size=BS, health_interval_s=30.0,
                            kv_peer_fanout=1).start()
        gw.probe_once()
        try:
            prompt = [5] + list(range(2, PROMPT_LEN + 1))
            tail = prompt_chain_keys(prompt, BS)[-1].hex()
            gw._kv_peer_inflight.add(tail)  # a fetch "in flight"
            toks = _stream(gw.host, gw.port, prompt)
            assert len(toks) == 6  # re-prefilled, stream intact
            stats = gw.stats()
            assert stats["kv_peer"]["single_flight_skips"] == 1
            assert stats["kv_peer_fetches"] == 0
            gw._kv_peer_inflight.discard(tail)
        finally:
            gw.stop()
            srv.stop()


def _one_real_one_fake(tiny, fake, **gw_kw):
    srv = InferenceServer(_engine(tiny), port=0, drain_s=0.5).start()
    eps = [f"{srv.host}:{srv.port}", fake.endpoint]
    gw_kw.setdefault("kv_peer_fanout", 2)
    gw = ServingGateway(eps, port=0, block_size=BS,
                        health_interval_s=30.0, **gw_kw).start()
    gw.probe_once()  # both in the ring before the first request
    return srv, gw


class TestFailureModesDegradeToReprefill:
    """One fleet per failure mode: a real target replica plus a fake
    peer misbehaving in exactly one way. Every test asserts the stream
    still delivered all tokens + [DONE] and the mode landed in the
    failure-reason scoreboard."""

    def _run(self, tiny, fake, reason, gw_kw=None, n=1):
        srv, gw = _one_real_one_fake(tiny, fake, **(gw_kw or {}))
        try:
            used = set()
            for _ in range(n):
                prompt = _targeted_prompt(
                    gw, f"{srv.host}:{srv.port}", exclude=used)
                used.add(tuple(prompt))
                toks = _stream(gw.host, gw.port, prompt)
                assert len(toks) == 6
            stats = gw.stats()
            assert stats["kv_peer_fetches"] == 0
            assert stats["kv_peer"]["failure_reasons"].get(reason, 0) >= 1
            return gw, stats
        finally:
            gw.stop()
            srv.stop()

    def test_dead_peer_negative_cached_and_not_reprobed(self, tiny):
        fake = _FakePeer(probe=None)  # tears every probe connection
        try:
            srv, gw = _one_real_one_fake(tiny, fake)
            try:
                used = set()
                for i in range(2):
                    prompt = _targeted_prompt(
                        gw, f"{srv.host}:{srv.port}", exclude=used)
                    used.add(tuple(prompt))
                    toks = _stream(gw.host, gw.port, prompt)
                    assert len(toks) == 6
                stats = gw.stats()
                # Probed ONCE: the second request hit the negative cache
                # instead of re-probing the corpse.
                assert fake.probe_hits == 1
                assert stats["kv_peer"]["failure_reasons"] == {
                    "dead_peer": 1}
                assert stats["kv_peer"]["negative_cached"] == [
                    fake.endpoint]
                assert stats["kv_peer"]["negative_hits"] >= 1
            finally:
                gw.stop()
                srv.stop()
        finally:
            fake.stop()

    def test_negative_cache_expiry_admits_one_fresh_probe(self, tiny):
        fake = _FakePeer(probe=None)
        try:
            srv, gw = _one_real_one_fake(tiny, fake)
            try:
                real = f"{srv.host}:{srv.port}"
                used = set()
                prompt = _targeted_prompt(gw, real, exclude=used)
                used.add(tuple(prompt))
                _stream(gw.host, gw.port, prompt)
                assert fake.probe_hits == 1
                # Force the hold to expire: the next miss may probe the
                # peer again (it might have healed).
                deadline, fails = gw._kv_peer_negative[fake.endpoint]
                gw._kv_peer_negative[fake.endpoint] = (0.0, fails)
                prompt = _targeted_prompt(gw, real, exclude=used)
                _stream(gw.host, gw.port, prompt)
                assert fake.probe_hits == 2
                # Still dead → backoff escalates, not resets.
                assert gw._kv_peer_negative[fake.endpoint][1] == fails + 1
            finally:
                gw.stop()
                srv.stop()
        finally:
            fake.stop()

    def test_slow_peer_times_out_as_dead(self, tiny):
        fake = _FakePeer(probe={"matched": 2, "payload_bytes": 64},
                         probe_delay=1.5)
        try:
            gw, stats = self._run(
                tiny, fake, "dead_peer",
                gw_kw={"kv_peer_timeout_s": 0.3})
            assert fake.chain_hits == 0
        finally:
            fake.stop()

    def test_oversized_chain_refused_before_pulling(self, tiny):
        """The probe's payload byte advisory is enforced BEFORE the
        transfer: no /kv/chain request ever reaches the peer."""
        fake = _FakePeer(probe={"matched": 2,
                                "payload_bytes": 999 << 20})
        try:
            self._run(tiny, fake, "oversized")
            assert fake.probe_hits == 1
            assert fake.chain_hits == 0
        finally:
            fake.stop()

    def test_peer_dying_mid_export_backs_off(self, tiny):
        """Probe succeeds, the chain pull tears mid-response: the peer
        is treated as dead for the backoff window and the request
        re-prefills."""
        fake = _FakePeer(probe={"matched": 2, "payload_bytes": 64},
                         chain=None)
        try:
            gw, stats = self._run(tiny, fake, "fetch_failed")
            assert fake.chain_hits == 1
            assert stats["kv_peer"]["negative_cached"] == [fake.endpoint]
        finally:
            fake.stop()

    def test_version_skew_quarantined(self, tiny):
        prompt = [3] + list(range(2, PROMPT_LEN + 1))
        payload = _chain_payload(tiny, prompt)
        skewed = {**payload, "version": 2}
        fake = _FakePeer(
            probe={"matched": 2, "payload_bytes": 64},
            chain={"matched": 2, "payload": skewed})
        try:
            gw, stats = self._run(tiny, fake, "quarantined")
            assert stats["kv_peer"]["quarantined"] == 1
            (entry,) = stats["kv_peer"]["quarantine"]
            assert entry["endpoint"] == fake.endpoint
            assert "version" in entry["error"]
        finally:
            fake.stop()

    def test_chain_key_mismatch_quarantined(self, tiny):
        """A peer whose hashing diverged must be quarantined, not
        decoded from: the target validates every key against its own
        prompt tokens."""
        prompt = [3] + list(range(2, PROMPT_LEN + 1))
        payload = _chain_payload(tiny, prompt)
        tampered = json.loads(json.dumps(payload))
        tampered["blocks"][0]["key"] = "00" * 20
        fake = _FakePeer(
            probe={"matched": 2, "payload_bytes": 64},
            chain={"matched": 2, "payload": tampered})
        try:
            gw, stats = self._run(tiny, fake, "quarantined")
            (entry,) = stats["kv_peer"]["quarantine"]
            assert "chain-key mismatch" in entry["error"]
        finally:
            fake.stop()


class TestDisaggPeerFetch:
    def test_decode_tier_warmed_from_sibling_replica(self, tiny):
        """Disagg routing: the probed decode replica is cold but its
        sibling holds the chain — the gateway imports it into the
        target decode replica, the prefill tier ships suffix-only, and
        the stream is token-exact."""
        roles = {}
        srvs = {}
        for name, role in (("prefill", "prefill"), ("d1", "decode"),
                           ("d2", "decode")):
            srvs[name] = InferenceServer(
                _engine(tiny), port=0, drain_s=0.5, tier_role=role,
            ).start()
            roles[f"{srvs[name].host}:{srvs[name].port}"] = role
        gw = ServingGateway(
            list(roles), port=0, block_size=BS, health_interval_s=30.0,
            tier_mode="disagg", tier_roles=roles, kv_peer_fanout=2,
        ).start()
        gw.probe_once()
        try:
            by_ep = {f"{s.host}:{s.port}": s for s in srvs.values()}
            prompt = None
            for nonce in range(3, 250):
                cand = [nonce] + list(range(2, PROMPT_LEN + 1))
                gw._route_key(cand)  # let the prefix router learn it
                decodes = gw._tier_candidates(
                    "decode", gw._route_key(cand))
                if len(decodes) == 2:
                    prompt, target, donor = cand, decodes[0], decodes[1]
                    break
            assert prompt is not None
            _warm(by_ep[donor], prompt)
            toks = _stream(gw.host, gw.port, prompt)
            assert toks == _reference(tiny, prompt)
            stats = gw.stats()
            assert stats["kv_peer_fetches"] == 1
            assert stats["kv_transfers"] == 1
            assert by_ep[donor].engine.kv_chain_exports == 1
            assert by_ep[target].engine.kv_chain_imports == 1
        finally:
            gw.stop()
            for s in srvs.values():
                s.stop()


class TestChainPrimitives:
    """Engine-level export_chain/import_chain: the wire format the HTTP
    hops carry."""

    def test_roundtrip_registers_and_hits(self, tiny):
        prompt = list(range(1, PROMPT_LEN + 1))
        a = _engine(tiny)
        a.submit(prompt, max_new_tokens=1)
        a.run()
        keys = prompt_chain_keys(prompt, BS)
        payload = a.export_chain(keys)
        assert a.kv_chain_exports == 1
        assert len(payload["blocks"]) == 2
        assert all("data" in e for e in payload["blocks"])
        b = _engine(tiny)
        assert b.import_chain(payload, prompt) == 2
        assert b.kv_chain_imports == 1
        rid = b.submit(prompt, max_new_tokens=6)
        got = b.run()[rid]
        # prefix_hits counts per block, and both imported blocks land.
        assert b.prefix_hits == 2
        assert got == _reference(tiny, prompt)

    def test_export_partial_and_empty(self, tiny):
        prompt = list(range(1, PROMPT_LEN + 1))
        a = _engine(tiny)
        a.submit(prompt, max_new_tokens=1)
        a.run()
        keys = prompt_chain_keys(prompt, BS)
        # A foreign tail key truncates the export to the held prefix.
        partial = a.export_chain([keys[0], b"\x00" * 20])
        assert len(partial["blocks"]) == 1
        assert a.export_chain([b"\x00" * 20]) is None
        cold = _engine(tiny)
        assert cold.export_chain(keys) is None

    def test_import_validates(self, tiny):
        prompt = list(range(1, PROMPT_LEN + 1))
        a = _engine(tiny)
        a.submit(prompt, max_new_tokens=1)
        a.run()
        payload = a.export_chain(prompt_chain_keys(prompt, BS))
        b = _engine(tiny)
        with pytest.raises(ValueError, match="version"):
            b.import_chain({**payload, "version": 2}, prompt)
        with pytest.raises(ValueError, match="block_size"):
            b.import_chain({**payload, "block_size": 16}, prompt)
        with pytest.raises(ValueError, match="kv_bits"):
            b.import_chain({**payload, "kv_bits": 8}, prompt)
        tampered = json.loads(json.dumps(payload))
        tampered["blocks"][0]["key"] = "00" * 20
        with pytest.raises(ValueError, match="chain-key mismatch"):
            b.import_chain(tampered, prompt)
        # More chain blocks than the prompt can register → refused.
        with pytest.raises(ValueError, match="chain"):
            b.import_chain(payload, prompt[:9])
        assert b.kv_chain_imports == 0

    def test_requires_prefix_cache(self, tiny):
        plain = _engine(tiny, prefix_cache=False)
        with pytest.raises(RuntimeError, match="prefix_cache"):
            plain.export_chain([b"\x00" * 20])
        with pytest.raises(ValueError, match="prefix_cache"):
            plain.import_chain({"version": 1}, list(range(20)))
