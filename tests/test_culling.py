"""Tests for the slice-aware idle culler (reference culling tier,
culling_controller_test.go:13-142, generalized to multi-host)."""

from kubeflow_tpu.api import annotations as ann
from kubeflow_tpu.controller.culling import HostActivity, _fmt
from kubeflow_tpu.k8s import objects as obj_util
from kubeflow_tpu.k8s.events import events_for

from tests.harness import cpu_notebook, make_env, tpu_notebook


def anns_of(env, name="nb", ns="ns"):
    return env.cluster.get("Notebook", name, ns)["metadata"].get("annotations", {})


class TestActivityTracking:
    def test_annotations_initialized(self):
        env = make_env(culling=True)
        env.cluster.create(cpu_notebook())
        env.manager.run_until_idle()
        a = anns_of(env)
        assert ann.LAST_ACTIVITY in a
        assert ann.LAST_ACTIVITY_CHECK in a

    def test_no_probe_before_period(self):
        env = make_env(culling=True, check_period_min=5)
        env.cluster.create(cpu_notebook())
        env.manager.run_until_idle()
        assert env.prober.probe_count == 0
        env.manager.tick(60.0)  # 1 min < 5 min period
        assert env.prober.probe_count == 0
        env.manager.tick(250.0)  # now past the period
        assert env.prober.probe_count == 1

    def test_busy_kernel_refreshes_activity(self):
        env = make_env(culling=True, cull_idle_min=30, check_period_min=1)
        env.cluster.create(cpu_notebook())
        env.manager.run_until_idle()
        env.prober.set_busy()
        for _ in range(40):  # 40 minutes of busy kernel
            env.manager.tick(60.0)
        a = anns_of(env)
        assert ann.STOP not in a  # never culled
        last = a[ann.LAST_ACTIVITY]
        assert last == _fmt(env.clock.now())  # pinned to "now" while busy

    def test_monotonic_guard(self):
        """Stale probe data must never move last-activity backwards
        (reference compareAnnotationTimeToResource :360-378)."""
        env = make_env(culling=True, check_period_min=1)
        env.cluster.create(cpu_notebook())
        env.manager.run_until_idle()
        t0 = env.clock.now()
        env.prober.set_idle(last_activity=t0 + 100)
        env.manager.tick(120.0)
        assert anns_of(env)[ann.LAST_ACTIVITY] == _fmt(t0 + 100)
        # A later probe reports an OLDER activity (clock skew / restarted hub)
        env.prober.set_idle(last_activity=t0 - 500)
        env.manager.tick(120.0)
        assert anns_of(env)[ann.LAST_ACTIVITY] == _fmt(t0 + 100)  # unchanged


class TestCulling:
    def test_idle_notebook_culled(self):
        env = make_env(culling=True, cull_idle_min=30, check_period_min=1)
        env.cluster.create(cpu_notebook())
        env.manager.run_until_idle()
        for _ in range(35):
            env.manager.tick(60.0)
        a = anns_of(env)
        assert ann.STOP in a
        sts = env.cluster.get("StatefulSet", "nb", "ns")
        assert sts["spec"]["replicas"] == 0
        evs = events_for(env.cluster, "Notebook", "nb", "ns")
        assert any(e["reason"] == "NotebookCulled" for e in evs)

    def test_tpu_slice_culled_atomically_with_chip_metric(self):
        env = make_env(culling=True, cull_idle_min=30, check_period_min=1)
        env.cluster.create(tpu_notebook())  # 16 chips
        env.manager.run_until_idle()
        env.prober.set_idle(hosts=4)
        for _ in range(35):
            env.manager.tick(60.0)
        assert env.cluster.list("Pod", "ns") == []  # whole slice released
        text = env.metrics.expose().decode()
        assert "tpu_chips_reclaimed_total 16.0" in text
        assert "notebook_culling_total 1.0" in text

    def test_any_host_activity_keeps_slice_alive(self):
        """Worker 3 busy (e.g. profiling server) while Jupyter on worker 0
        is idle → slice must NOT be culled (SURVEY.md §7 step 5)."""
        env = make_env(culling=True, cull_idle_min=30, check_period_min=1)
        env.cluster.create(tpu_notebook())
        env.manager.run_until_idle()
        env.prober.set_busy(hosts=4, busy_host=3)
        for _ in range(40):
            env.manager.tick(60.0)
        assert ann.STOP not in anns_of(env)

    def test_unreachable_slice_never_culled(self):
        """THE safety-critical culler rule: a slice whose every host probe
        errors (network partition, NetPol misconfig) must never be culled,
        no matter how long it stays unobservable — idle and unreachable
        are indistinguishable, and releasing a v5p-512 on a probe failure
        is the reference's probe-error posture generalized
        (culling_controller.go:277-322 returns without judging).
        Behavior under test: controller/culling.py:230-235."""
        env = make_env(culling=True, cull_idle_min=30, check_period_min=1)
        env.cluster.create(tpu_notebook())
        env.manager.run_until_idle()
        before = anns_of(env)[ann.LAST_ACTIVITY]
        env.prober.set_unreachable(hosts=4)
        # Far past cull_idle_min with zero successful probes.
        for _ in range(120):
            env.manager.tick(60.0)
        a = anns_of(env)
        assert ann.STOP not in a
        assert env.cluster.list("Pod", "ns") != []  # slice still held
        # Probes kept being attempted (the culler did not give up)...
        assert env.prober.probe_count > 100
        # ...and last-activity was never advanced by unreachable data.
        assert a[ann.LAST_ACTIVITY] == before

    def test_partition_heals_then_idle_cull_resumes(self):
        """After the partition heals, the normal idle clock applies — the
        unreachable window must not have poisoned the state."""
        env = make_env(culling=True, cull_idle_min=30, check_period_min=1)
        env.cluster.create(tpu_notebook())
        env.manager.run_until_idle()
        env.prober.set_unreachable(hosts=4)
        for _ in range(50):
            env.manager.tick(60.0)
        assert ann.STOP not in anns_of(env)
        env.prober.set_idle(hosts=4)  # partition heals, slice idle
        for _ in range(35):
            env.manager.tick(60.0)
        assert ann.STOP in anns_of(env)  # now culled normally

    def test_stopped_notebook_annotations_cleared(self):
        env = make_env(culling=True, cull_idle_min=30)
        env.cluster.create(cpu_notebook())
        env.manager.run_until_idle()
        assert ann.LAST_ACTIVITY in anns_of(env)
        nb = env.cluster.get("Notebook", "nb", "ns")
        obj_util.annotations_of(nb)[ann.STOP] = "t"
        env.cluster.update(nb)
        env.manager.run_until_idle()
        a = anns_of(env)
        assert ann.LAST_ACTIVITY not in a
        assert ann.LAST_ACTIVITY_CHECK not in a

    def test_culling_disabled_no_annotations(self):
        env = make_env(culling=False)
        env.cluster.create(cpu_notebook())
        env.manager.run_until_idle()
        assert ann.LAST_ACTIVITY not in anns_of(env)


class TestPreemptionRecovery:
    def test_preempted_host_marks_interrupted_and_recovers(self):
        env = make_env()
        env.cluster.create(tpu_notebook())
        env.manager.run_until_idle()
        nb = env.cluster.get("Notebook", "nb", "ns")
        assert nb["status"]["tpu"]["sliceHealth"] == "Healthy"

        env.kubelet.preempt_pod("nb-2", "ns")
        env.manager.run_until_idle()

        # The failed host pod was deleted and recreated by the kubelet;
        # recovery then cleared the interruption.
        nb = env.cluster.get("Notebook", "nb", "ns")
        assert nb["status"]["tpu"]["sliceHealth"] == "Healthy"
        assert ann.TPU_SLICE_INTERRUPTED not in nb["metadata"].get("annotations", {})
        evs = events_for(env.cluster, "Notebook", "nb", "ns")
        reasons = {e["reason"] for e in evs}
        assert "SliceInterrupted" in reasons
        assert "SliceRecovered" in reasons
        text = env.metrics.expose().decode()
        assert "tpu_slice_preemptions_total 1.0" in text

    def test_preemption_without_capacity_stays_interrupted(self):
        env = make_env(node_pools=(("tpu-v5-lite-podslice", "4x4", 4, 4),))
        env.cluster.create(tpu_notebook())
        env.manager.run_until_idle()
        # Remove a node so the preempted pod cannot reschedule.
        env.kubelet.auto_ready = True
        env.cluster.delete("Node", "tpu-node-4x4-3")
        env.kubelet.preempt_pod("nb-3", "ns")
        env.manager.run_until_idle()
        nb = env.cluster.get("Notebook", "nb", "ns")
        assert nb["status"]["tpu"]["sliceHealth"] in ("Forming", "Interrupted")
        assert nb["status"]["tpu"]["readyHosts"] == 3
