"""Manager entrypoint layer: flags, leader election, health, TLS, cache.

Reference analog: main() wiring tests — cache transforms are unit-tested in
the reference's odh main_test.go:27+; flag validation mirrors
odh main.go:172-176; leader election mirrors main.go:87-94.
"""

from __future__ import annotations

import json
import urllib.request

import pytest

from kubeflow_tpu import k8s
from kubeflow_tpu.cmd import notebook_manager, platform_manager
from kubeflow_tpu.controller import tls
from kubeflow_tpu.k8s.cache import STRIPPED_MARK, TransformingClient, strip_payload
from kubeflow_tpu.k8s.health import HealthChecks, HealthServer, ServeWatchdog, ping
from kubeflow_tpu.k8s.leader import LeaderElector
from kubeflow_tpu.k8s.manager import FakeClock
from kubeflow_tpu.k8s.serve import serve

from tests.harness import FakeProber, tpu_notebook


# -- flags -----------------------------------------------------------------


def test_notebook_manager_flag_defaults():
    opts = notebook_manager.parse_args([])
    assert opts.metrics_addr == ":8080"
    assert opts.probe_addr == ":8081"
    assert not opts.enable_leader_election


def test_notebook_manager_flags_parse():
    opts = notebook_manager.parse_args(
        ["--metrics-addr", ":9090", "--enable-leader-election", "--burst", "100"]
    )
    assert opts.metrics_addr == ":9090"
    assert opts.enable_leader_election
    assert opts.burst == 100


def test_platform_manager_requires_rbac_proxy_image():
    with pytest.raises(platform_manager.FlagError):
        platform_manager.parse_args([])


def test_platform_manager_flags_parse():
    opts = platform_manager.parse_args(
        ["--kube-rbac-proxy-image", "proxy:v1", "--webhook-port", "9443"]
    )
    assert opts.kube_rbac_proxy_image == "proxy:v1"
    assert opts.webhook_port == 9443


def test_detect_namespace_env_wins(tmp_path):
    ns_file = tmp_path / "namespace"
    ns_file.write_text("from-file")
    assert (
        platform_manager.detect_namespace({"K8S_NAMESPACE": "from-env"}, str(ns_file))
        == "from-env"
    )
    assert platform_manager.detect_namespace({}, str(ns_file)) == "from-file"
    assert (
        platform_manager.detect_namespace({}, str(tmp_path / "absent")) == "opendatahub"
    )


# -- leader election -------------------------------------------------------


def test_leader_election_acquire_and_block():
    clock = FakeClock()
    cluster = k8s.FakeCluster(clock=clock)
    a = LeaderElector(cluster, "lock", "ns", "a", lease_duration=15, clock=clock)
    b = LeaderElector(cluster, "lock", "ns", "b", lease_duration=15, clock=clock)
    assert a.try_acquire()
    assert a.is_leader()
    assert not b.try_acquire()
    assert not b.is_leader()
    # Renewal keeps it held past the original duration.
    clock.advance(10)
    assert a.try_acquire()
    clock.advance(10)
    assert not b.try_acquire()


def test_leader_election_expiry_takeover():
    clock = FakeClock()
    cluster = k8s.FakeCluster(clock=clock)
    a = LeaderElector(cluster, "lock", "ns", "a", lease_duration=15, clock=clock)
    b = LeaderElector(cluster, "lock", "ns", "b", lease_duration=15, clock=clock)
    assert a.try_acquire()
    clock.advance(20)  # a's lease expired without renewal
    assert b.try_acquire()
    assert b.is_leader()
    assert not a.is_leader()
    assert b.transitions == 1


def test_leader_election_release_hands_off_immediately():
    clock = FakeClock()
    cluster = k8s.FakeCluster(clock=clock)
    a = LeaderElector(cluster, "lock", "ns", "a", clock=clock)
    b = LeaderElector(cluster, "lock", "ns", "b", clock=clock)
    assert a.try_acquire()
    a.release()
    assert b.try_acquire()  # no wait for expiry


# -- health ----------------------------------------------------------------


def test_health_checks_pass_and_fail():
    checks = HealthChecks()
    checks.add_healthz_check("healthz", ping)
    checks.add_readyz_check("cache", lambda: (_ for _ in ()).throw(RuntimeError("not synced")))
    code, _ = checks.handle("/healthz")
    assert code == 200
    code, body = checks.handle("/readyz")
    assert code == 500
    assert "not synced" in json.loads(body)["cache"]
    assert checks.handle("/nope")[0] == 404


def test_health_server_serves_http():
    checks = HealthChecks()
    checks.add_healthz_check("healthz", ping)
    checks.add_readyz_check("readyz", ping)
    server = HealthServer(checks)
    server.start()
    try:
        for path in ("/healthz", "/readyz"):
            with urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}{path}"
            ) as resp:
                assert resp.status == 200
    finally:
        server.stop()


def test_serve_watchdog_lifecycle():
    clock = FakeClock()
    dog = ServeWatchdog(window_s=60.0, clock=clock)
    checks = HealthChecks()
    dog.register(checks)

    # Unready until the serve loop completes its first drain cycle.
    code, body = checks.handle("/readyz")
    assert code == 500
    assert "not completed a cycle" in json.loads(body)["serve-loop"]

    dog.beat(cursor=7)
    assert checks.handle("/readyz")[0] == 200

    # Still within the window: a quiet-but-alive loop stays ready.
    clock.advance(59)
    assert checks.handle("/readyz")[0] == 200

    # Window lapses with no beat → wedged loop turns the replica unready,
    # and the error names the last cursor for the postmortem.
    clock.advance(2)
    code, body = checks.handle("/readyz")
    assert code == 500
    assert "stalled" in json.loads(body)["serve-loop"]
    assert "cursor 7" in json.loads(body)["serve-loop"]

    # A late beat recovers readiness (level-triggered, like everything).
    dog.beat(cursor=8)
    assert checks.handle("/readyz")[0] == 200


def test_serve_loop_beats_watchdog():
    """serve() auto-registers a watchdog on the bundle's HealthChecks and
    beats it each completed cycle — readyz flips from 500 to 200 once the
    loop has actually drained."""
    cluster, clock = _cluster_with_nodes()
    bundle = notebook_manager.build(cluster, env={}, clock=clock)
    code, _ = bundle.health.handle("/readyz")  # build() readyz is ping-only
    assert code == 200

    dog = ServeWatchdog(window_s=60.0)
    serve(bundle, cluster, max_iterations=2, max_idle_wait=0.01, watchdog=dog)
    assert dog.last_cursor == bundle.manager.cursor
    assert bundle.health.handle("/readyz")[0] == 200


# -- notebook manager wiring ----------------------------------------------


def _cluster_with_nodes():
    clock = FakeClock()
    cluster = k8s.FakeCluster(clock=clock)
    k8s.add_tpu_node_pool(cluster, "tpu-v5-lite-podslice", "4x4", hosts=4, chips_per_host=4)
    return cluster, clock


def test_build_without_culling_env():
    cluster, clock = _cluster_with_nodes()
    bundle = notebook_manager.build(cluster, env={}, clock=clock)
    assert bundle.culling_reconciler is None


def test_build_with_culling_env():
    cluster, clock = _cluster_with_nodes()
    bundle = notebook_manager.build(
        cluster,
        env={"ENABLE_CULLING": "true", "CULL_IDLE_TIME": "30"},
        clock=clock,
        prober=FakeProber(),
    )
    assert bundle.culling_reconciler is not None
    assert bundle.culling_reconciler.config.cull_idle_time_min == 30


def test_manager_bundle_reconciles_notebook():
    cluster, clock = _cluster_with_nodes()
    bundle = notebook_manager.build(cluster, env={}, clock=clock)
    cluster.create(tpu_notebook(name="nb1"))
    bundle.run_until_idle()
    sts = cluster.get("StatefulSet", "nb1", "ns")
    assert sts["spec"]["replicas"] == 4


def test_leader_gating_blocks_non_leader():
    cluster, clock = _cluster_with_nodes()
    argv = ["--enable-leader-election"]
    leader = notebook_manager.build(
        cluster, env={}, argv=argv, clock=clock, identity="a"
    )
    follower = notebook_manager.build(
        cluster, env={}, argv=argv, clock=clock, identity="b"
    )
    assert leader.elector.try_acquire()
    cluster.create(tpu_notebook(name="nb1"))
    assert follower.run_until_idle() == 0  # not leader: no reconciles
    assert leader.run_until_idle() > 0
    assert cluster.exists("StatefulSet", "nb1", "ns")


# -- platform manager wiring ----------------------------------------------


def test_platform_build_registers_webhooks_and_reconciler():
    cluster, clock = _cluster_with_nodes()
    bundle = platform_manager.build(
        cluster,
        env={"K8S_NAMESPACE": "opendatahub"},
        argv=["--kube-rbac-proxy-image", "proxy:v1"],
        clock=clock,
    )
    assert bundle.tls_profile == tls.INTERMEDIATE  # no APIServer CR → fallback
    nb = tpu_notebook(name="nb1")
    created = cluster.create(nb)
    # Mutating webhook ran on create: reconciliation lock + TPU env present.
    assert created["metadata"]["annotations"]["kubeflow-resource-stopped"]
    bundle.run_until_idle()
    assert cluster.exists("NetworkPolicy", "nb1-ctrl-np", "ns")


def test_platform_webhook_uses_flag_image():
    cluster, clock = _cluster_with_nodes()
    bundle = platform_manager.build(
        cluster,
        env={},
        argv=["--kube-rbac-proxy-image", "proxy:v42"],
        clock=clock,
    )
    assert bundle.mutating_webhook.config.rbac_proxy_image == "proxy:v42"


# -- TLS profile -----------------------------------------------------------


def test_tls_profile_from_apiserver_cr():
    cluster = k8s.FakeCluster()
    cluster.create(
        {
            "apiVersion": "config.openshift.io/v1",
            "kind": "APIServer",
            "metadata": {"name": "cluster"},
            "spec": {"tlsSecurityProfile": {"type": "Modern"}},
        }
    )
    assert tls.fetch_tls_profile(cluster) == tls.MODERN


def test_tls_custom_profile():
    cluster = k8s.FakeCluster()
    cluster.create(
        {
            "apiVersion": "config.openshift.io/v1",
            "kind": "APIServer",
            "metadata": {"name": "cluster"},
            "spec": {
                "tlsSecurityProfile": {
                    "type": "Custom",
                    "custom": {
                        "minTLSVersion": "VersionTLS13",
                        "ciphers": ["TLS_AES_128_GCM_SHA256"],
                    },
                }
            },
        }
    )
    profile = tls.fetch_tls_profile(cluster)
    assert profile.profile_type == "Custom"
    assert profile.min_version == "VersionTLS13"
    assert profile.ciphers == ("TLS_AES_128_GCM_SHA256",)


def test_tls_watcher_requests_restart_on_change():
    cluster, clock = _cluster_with_nodes()
    bundle = platform_manager.build(
        cluster,
        env={},
        argv=["--kube-rbac-proxy-image", "p"],
        clock=clock,
    )
    assert bundle.tls_profile == tls.INTERMEDIATE
    bundle.run_until_idle()
    assert bundle.restart_requested == []
    cluster.create(
        {
            "apiVersion": "config.openshift.io/v1",
            "kind": "APIServer",
            "metadata": {"name": "cluster"},
            "spec": {"tlsSecurityProfile": {"type": "Modern"}},
        }
    )
    bundle.run_until_idle()
    assert bundle.restart_requested == [tls.MODERN]


# -- cache transforms ------------------------------------------------------


def _cm(name, labels=None):
    return {
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": {"name": name, "namespace": "ns", "labels": labels or {}},
        "data": {"k": "v" * 100},
    }


def test_cache_strips_unrelated_configmap():
    stripped = strip_payload(_cm("random-cm"))
    assert "data" not in stripped
    assert stripped["metadata"]["annotations"][STRIPPED_MARK] == "true"


def test_cache_keeps_allowlisted_payloads():
    assert "data" in strip_payload(_cm("odh-trusted-ca-bundle"))
    assert "data" in strip_payload(
        _cm("img", labels={"opendatahub.io/runtime-image": "true"})
    )


def test_transforming_client_round_trip():
    cluster = k8s.FakeCluster()
    cluster.create(_cm("random-cm"))
    client = TransformingClient(cluster)
    assert "data" not in client.get("ConfigMap", "random-cm", "ns")
    assert all("data" not in o for o in client.list("ConfigMap", "ns"))
    # Underlying store untouched (transform models the cache, not etcd).
    assert "data" in cluster.get("ConfigMap", "random-cm", "ns")


def test_loadtest_p95_nearest_rank():
    """One p95 formula serves every spawn artifact field."""
    import importlib

    lt = importlib.import_module("loadtest.start_notebooks")
    # 20 values 1..20 ms in seconds: rank index max(0, int(0.95*20)-1)=18
    # → the 19th value.
    vals = [i / 1000 for i in range(1, 21)]
    assert lt._p95_ms(vals) == 19.0
    assert lt._p95_ms([0.005]) == 5.0
