"""Batched left-padded serving: HF parity and EOS semantics."""

from __future__ import annotations

import jax
import numpy as np
import pytest

from kubeflow_tpu.models import llama as L
from kubeflow_tpu.models.convert import config_from_hf, params_from_hf_state_dict
from kubeflow_tpu.models.serving import (
    GenerationConfig,
    batch_generate,
    left_pad,
)

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


@pytest.fixture(scope="module")
def hf_pair():
    hf_cfg = transformers.LlamaConfig(
        vocab_size=256,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128,
        rms_norm_eps=1e-5,
        attn_implementation="eager",
        pad_token_id=0,
        eos_token_id=2,
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(hf_cfg).eval()
    cfg = config_from_hf(hf_cfg)
    cfg = L.LlamaConfig(**{**cfg.__dict__, "dtype": np.float32})
    params = params_from_hf_state_dict(cfg, model.state_dict(), np.float32)
    return model, cfg, params


class TestLeftPad:
    def test_pads_on_the_left(self):
        tokens, mask = left_pad([[5, 6], [7, 8, 9, 10]], pad_id=0)
        np.testing.assert_array_equal(
            tokens, [[0, 0, 5, 6], [7, 8, 9, 10]]
        )
        np.testing.assert_array_equal(
            mask, [[False, False, True, True], [True] * 4]
        )

    def test_explicit_bucket_length(self):
        tokens, _ = left_pad([[1]], pad_id=9, length=8)
        assert tokens.shape == (1, 8) and tokens[0, -1] == 1

    def test_rejects_too_small_bucket_and_empties(self):
        with pytest.raises(ValueError, match="longest"):
            left_pad([[1, 2, 3]], 0, length=2)
        with pytest.raises(ValueError, match="empty prompt batch"):
            left_pad([], 0)
        with pytest.raises(ValueError, match="prompt 1 is empty"):
            left_pad([[1], []], 0)


class TestHFParity:
    def test_ragged_batch_matches_transformers(self, hf_pair):
        """The core claim: left-padding + static kv_mask + absolute rope
        positions == HF's pad-adjusted position_ids, token for token."""
        model, cfg, params = hf_pair
        rng = np.random.default_rng(0)
        prompts = [
            list(rng.integers(3, 256, size=n)) for n in (5, 11, 8)
        ]
        steps = 10
        tokens, mask = left_pad(prompts, pad_id=0)
        with torch.no_grad():
            ref = model.generate(
                torch.from_numpy(tokens).long(),
                attention_mask=torch.from_numpy(mask).long(),
                max_new_tokens=steps,
                do_sample=False,
                num_beams=1,
                eos_token_id=None,  # force full length for the comparison
                pad_token_id=0,
            ).numpy()[:, tokens.shape[1]:]
        ours = batch_generate(
            params, cfg, prompts,
            GenerationConfig(max_new_tokens=steps, eos_id=-1),
        )
        for row, expected in zip(ours, ref):
            np.testing.assert_array_equal(np.asarray(row), expected)

    def test_batched_matches_single(self, hf_pair):
        """A sequence's output must not depend on its batch neighbors."""
        _, cfg, params = hf_pair
        rng = np.random.default_rng(1)
        prompts = [list(rng.integers(3, 256, size=n)) for n in (4, 9)]
        gen = GenerationConfig(max_new_tokens=8, eos_id=-1)
        batched = batch_generate(params, cfg, prompts, gen)
        singles = [batch_generate(params, cfg, [p], gen)[0] for p in prompts]
        assert batched == singles


class TestEos:
    def test_eos_truncates_per_sequence(self, hf_pair):
        _, cfg, params = hf_pair
        rng = np.random.default_rng(2)
        prompts = [list(rng.integers(3, 256, size=6)) for _ in range(3)]
        # Find what each row greedily generates, then declare one row's
        # second token as "EOS" and check truncation.
        free = batch_generate(
            params, cfg, prompts, GenerationConfig(max_new_tokens=6, eos_id=-1)
        )
        eos = free[1][1]
        out = batch_generate(
            params, cfg, prompts,
            GenerationConfig(max_new_tokens=6, eos_id=int(eos)),
        )
        assert len(out[1]) <= 1  # truncated at its EOS (excluded)
        for i in (0, 2):
            # Other rows unaffected up to their own first eos occurrence.
            expected = free[i]
            cut = expected.index(eos) if eos in expected else len(expected)
            assert out[i] == expected[:cut]

    def test_uniform_batch_skips_mask_and_matches_ragged_path(self, hf_pair):
        """Equal-length prompts drop the kv_mask (keeping the pallas
        prefill on TPU); results must equal the masked path's."""
        _, cfg, params = hf_pair
        rng = np.random.default_rng(3)
        prompts = [list(rng.integers(3, 256, size=7)) for _ in range(2)]
        gen = GenerationConfig(max_new_tokens=6, eos_id=-1)
        uniform = batch_generate(params, cfg, prompts, gen)
        # Same prompts forced through the masked path via a wider bucket
        # (mask has False slots even though content is identical).
        ragged = batch_generate(params, cfg, prompts, gen, pad_to=12)
        assert uniform == ragged

    def test_bucketing_reuses_compiled_program(self, hf_pair):
        _, cfg, params = hf_pair
        gen = GenerationConfig(max_new_tokens=4, eos_id=-1)
        a = batch_generate(params, cfg, [[5, 6, 7]], gen, pad_to=16)
        b = batch_generate(params, cfg, [[9] * 10], gen, pad_to=16)
        assert len(a[0]) == 4 and len(b[0]) == 4
