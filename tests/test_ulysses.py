"""Ulysses (all-to-all) sequence parallelism: parity with dense attention
and with ring attention, plus train-step integration."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import pytest

from kubeflow_tpu.models import llama as L
from kubeflow_tpu.models.train import make_train_step, shard_state
from kubeflow_tpu.ops.attention import flash_attention
from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh
from kubeflow_tpu.parallel.ring_attention import make_sharded_ring_attention
from kubeflow_tpu.parallel.ulysses import make_sharded_ulysses_attention


def _qkv(heads=4, seq=128, d=32, batch=2):
    return (
        jax.random.normal(jax.random.PRNGKey(0), (batch, heads, seq, d)),
        jax.random.normal(jax.random.PRNGKey(1), (batch, heads, seq, d)),
        jax.random.normal(jax.random.PRNGKey(2), (batch, heads, seq, d)),
    )


class TestUlyssesAttention:
    def test_matches_dense_sp4(self):
        mesh = make_mesh(dp=2, sp=4)
        q, k, v = _qkv(heads=4, seq=128)
        ref = flash_attention(q, k, v, causal=True, impl="xla")
        out = make_sharded_ulysses_attention(mesh)(q, k, v)
        assert float(jnp.max(jnp.abs(out - ref))) < 1e-4

    def test_matches_dense_sp8_all_heads_traded(self):
        """sp == heads: each device ends up with exactly one head."""
        mesh = make_mesh(sp=8)
        q, k, v = _qkv(heads=8, seq=128)
        ref = flash_attention(q, k, v, causal=True, impl="xla")
        out = make_sharded_ulysses_attention(mesh)(q, k, v)
        assert float(jnp.max(jnp.abs(out - ref))) < 1e-4

    def test_composes_with_dp_tp(self):
        mesh = make_mesh(dp=2, fsdp=1, tp=2, sp=2)
        q, k, v = _qkv(heads=4, seq=64)
        ref = flash_attention(q, k, v, causal=True, impl="xla")
        out = make_sharded_ulysses_attention(mesh)(q, k, v)
        assert float(jnp.max(jnp.abs(out - ref))) < 1e-4

    def test_matches_ring_attention(self):
        """The two SP strategies are interchangeable numerically."""
        mesh = make_mesh(dp=2, sp=4)
        q, k, v = _qkv(heads=4, seq=128)
        ring = make_sharded_ring_attention(mesh)(q, k, v)
        uly = make_sharded_ulysses_attention(mesh)(q, k, v)
        assert float(jnp.max(jnp.abs(ring - uly))) < 1e-4

    def test_rejects_indivisible_heads(self):
        mesh = make_mesh(sp=8)
        q, k, v = _qkv(heads=4, seq=64)  # 4 heads, sp=8 → impossible
        with pytest.raises(ValueError, match="not divisible by sp"):
            make_sharded_ulysses_attention(mesh)(q, k, v)

    def test_non_causal_matches_dense(self):
        """Bidirectional (encoder-style) attention under SP: parity with
        the dense non-causal path — and with ring attention."""
        mesh = make_mesh(sp=4, dp=2)
        q, k, v = _qkv(heads=4, seq=128)
        ref = flash_attention(q, k, v, causal=False, impl="xla")
        uly = make_sharded_ulysses_attention(mesh)(q, k, v, causal=False)
        assert float(jnp.max(jnp.abs(uly - ref))) < 1e-4
        ring = make_sharded_ring_attention(mesh)(q, k, v, causal=False)
        assert float(jnp.max(jnp.abs(ring - ref))) < 1e-4

    def test_non_causal_with_kv_mask(self):
        mesh = make_mesh(sp=4, dp=2)
        q, k, v = _qkv(heads=4, seq=128)
        kv_mask = jnp.ones((2, 128), bool).at[:, :32].set(False)
        ref = flash_attention(q, k, v, causal=False, impl="xla",
                              kv_mask=kv_mask)
        uly = make_sharded_ulysses_attention(mesh)(
            q, k, v, causal=False, kv_mask=kv_mask
        )
        assert float(jnp.max(jnp.abs(uly - ref))) < 1e-4
        ring = make_sharded_ring_attention(mesh)(
            q, k, v, causal=False, kv_mask=kv_mask
        )
        assert float(jnp.max(jnp.abs(ring - ref))) < 1e-4


class TestUlyssesTraining:
    def test_train_step_with_ulysses_sp(self):
        cfg = L.LLAMA_CONFIGS["tiny"]  # 4 heads
        plan = MeshPlan(make_mesh(dp=2, fsdp=1, tp=2, sp=2))
        params = L.init_params(cfg, jax.random.PRNGKey(0))
        init_state, step = make_train_step(cfg, plan, sp_impl="ulysses")
        state = shard_state(plan, init_state(params))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (4, 64), 0, cfg.vocab_size
        )
        first = last = None
        for _ in range(4):
            state, loss = step(state, tokens)
            first = first if first is not None else float(loss)
            last = float(loss)
        assert last < first

    def test_ring_and_ulysses_losses_match(self):
        cfg = L.LLAMA_CONFIGS["tiny"]
        plan = MeshPlan(make_mesh(dp=2, sp=4))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (4, 64), 0, cfg.vocab_size
        )
        losses = {}
        for impl in ("ring", "ulysses"):
            # Fresh params per impl: the jitted step DONATES its state, so
            # reusing one tree across impls would touch deleted buffers.
            params = L.init_params(cfg, jax.random.PRNGKey(0))
            init_state, step = make_train_step(cfg, plan, sp_impl=impl)
            state = shard_state(plan, init_state(params))
            _, loss = step(state, tokens)
            losses[impl] = float(loss)
        assert abs(losses["ring"] - losses["ulysses"]) < 1e-3

    def test_unknown_sp_impl_rejected(self):
        cfg = L.LLAMA_CONFIGS["tiny"]
        plan = MeshPlan(make_mesh(dp=2, sp=4))
        with pytest.raises(ValueError, match="unknown sp_impl"):
            make_train_step(cfg, plan, sp_impl="nope")
