"""Disaggregated serving: paged-KV export/import handoff.

The prefill→decode transfer must be invisible to the client: byte-exact
KV blocks on the wire (bf16 AND the int8 ``kv_bits=8`` layout), token-
exact decode after the handoff vs a single fused replica, suffix-only
transfer when the decode side already holds the prefix chain, and the
gateway's ``kv_transfer`` span stitched between the prefill tier's
``prefill`` span and the decode tier's ``first_decode``.
"""

from __future__ import annotations

import base64
import http.client
import json

import jax
import numpy as np
import pytest

from kubeflow_tpu.models import llama as L
from kubeflow_tpu.models.gateway import chain_key, prompt_chain_keys
from kubeflow_tpu.models.paged import PagedBatcher, pool_blocks_from_hbm
from kubeflow_tpu.models.serving import GenerationConfig

BS = 8
PROMPT = [5, 9, 17, 33, 2, 11, 44, 3, 8, 21]  # 10 tokens → 2 blocks


@pytest.fixture(scope="module")
def tiny():
    cfg = L.LLAMA_CONFIGS["tiny"]
    params = L.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _engine(tiny, kv_bits=0, max_new=8, slots=2, num_blocks=16,
            bucket=16, prefix_cache=True, swap_bytes=0):
    cfg, params = tiny
    return PagedBatcher(
        params, cfg, gen=GenerationConfig(max_new_tokens=max_new, eos_id=-1),
        slots=slots, num_blocks=num_blocks, block_size=BS,
        prompt_bucket=bucket, prefix_cache=prefix_cache, kv_bits=kv_bits,
        swap_bytes=swap_bytes,
    )


def _prefill_payload(engine, prompt, skip_keys=()):
    """Run ``prompt`` as a prefill-tier request (max_new_tokens=1) and
    export at first-token time — the same moment the server's on_token
    hook exports."""
    out = {}
    engine.on_token = lambda rid, tok: out.setdefault(
        rid, engine.export_blocks(rid, skip_keys=skip_keys))
    rid = engine.submit(prompt, max_new_tokens=1)
    engine.run()
    engine.on_token = None
    return out[rid]


class TestChainKeyParity:
    def test_three_implementations_and_pinned_digest(self):
        """gateway.chain_key, PagedBatcher._chain_key, and
        prompt_chain_keys walk the SAME hash chain — pinned to literal
        digests so no implementation can drift without failing here
        (cross-host handoff depends on byte-identical keys)."""
        prompt = list(range(1, 20))  # 19 tokens → 2 registrable blocks
        keys = prompt_chain_keys(prompt, BS)
        assert [k.hex() for k in keys] == [
            "11e25c6a60ac62686eb6e65c3ae15d0c19e1a458",
            "5cad69e653e820a10b9e816d2cdd6a92f1069b42",
        ]
        k0 = chain_key(None, prompt[:BS])
        k1 = chain_key(k0, prompt[BS:2 * BS])
        assert [k0, k1] == keys
        assert PagedBatcher._chain_key(None, prompt[:BS]) == k0
        assert PagedBatcher._chain_key(k0, prompt[BS:2 * BS]) == k1

    def test_tail_block_excluded(self):
        # 16 tokens = exactly 2 blocks, but the last is the tail block
        # (never registered), so only 1 key is walkable.
        assert len(prompt_chain_keys(list(range(16)), BS)) == 1
        assert prompt_chain_keys([1], BS) == []


class TestExportImport:
    @pytest.mark.parametrize("kv_bits", [0, 8])
    def test_byte_roundtrip(self, tiny, kv_bits):
        """Every exported leaf re-materializes byte-identically in the
        importing pool — bf16 and the int8+scales layout."""
        a = _engine(tiny, kv_bits=kv_bits)
        payload = _prefill_payload(a, PROMPT)
        assert payload["kv_bits"] == kv_bits
        assert payload["pending_token"] >= 0
        b = _engine(tiny, kv_bits=kv_bits)
        rid = b.import_blocks(payload, max_new_tokens=1)
        assert rid is not None
        slot = next(i for i, r in enumerate(b._by_slot)
                    if r is not None and r.rid == rid)
        blocks = b._by_slot[slot].blocks
        for j, ent in enumerate(payload["blocks"]):
            for name, b64 in ent["data"].items():
                got = np.ascontiguousarray(
                    np.asarray(b.pool[name][:, blocks[j]])).tobytes()
                assert got == base64.b64decode(b64), (kv_bits, j, name)

    @pytest.mark.parametrize("kv_bits", [0, 8])
    def test_decode_after_handoff_token_exact(self, tiny, kv_bits):
        """Handoff decode == single-replica decode, token for token."""
        a = _engine(tiny, kv_bits=kv_bits)
        payload = _prefill_payload(a, PROMPT)
        b = _engine(tiny, kv_bits=kv_bits)
        rid = b.import_blocks(payload, max_new_tokens=8)
        got = b.run()[rid]
        c = _engine(tiny, kv_bits=kv_bits)
        r = c.submit(PROMPT, max_new_tokens=8)
        ref = c.run()[r]
        assert got == ref
        assert len(got) == 8
        assert a.kv_exports == 1 and b.kv_imports == 1

    def test_suffix_only_transfer_reuses_cached_chain(self, tiny):
        """A decode replica already holding the prefix chain receives
        stubs for those blocks and reuses its cached copies — still
        token-exact."""
        skip = [k.hex() for k in prompt_chain_keys(PROMPT, BS)]
        b = _engine(tiny)
        b.submit(PROMPT, max_new_tokens=8)
        b.run()  # warms b's chain for the registrable prefix block
        a = _engine(tiny)
        payload = _prefill_payload(a, PROMPT, skip_keys=skip)
        stubs = ["data" not in e for e in payload["blocks"]]
        assert stubs == [True, False]  # prefix stubbed, tail ships
        rid = b.import_blocks(payload, max_new_tokens=8)
        got = b.run()[rid]
        assert b.kv_import_blocks_reused == 1
        assert b.kv_import_blocks_written == 1
        c = _engine(tiny)
        r = c.submit(PROMPT, max_new_tokens=8)
        assert got == c.run()[r]

    def test_import_returns_none_when_no_slot_or_blocks(self, tiny):
        a = _engine(tiny)
        payload = _prefill_payload(a, PROMPT)
        # No free slot: both slots occupied by live requests.
        b = _engine(tiny, slots=1)
        b.submit([1, 2, 3], max_new_tokens=32)
        b.drive_once()  # admits into the only slot
        assert b.import_blocks(payload, max_new_tokens=4) is None
        # No free blocks: pool too small for the payload's 2 blocks.
        c = _engine(tiny, num_blocks=2)  # block 0 reserved → 1 usable
        assert c.import_blocks(payload, max_new_tokens=4) is None
        assert c.free_blocks == 1  # refusal leaked nothing

    def test_import_validates_payload(self, tiny):
        a = _engine(tiny)
        payload = _prefill_payload(a, PROMPT)
        b = _engine(tiny)
        with pytest.raises(ValueError, match="version"):
            b.import_blocks({**payload, "version": 2})
        with pytest.raises(ValueError, match="block_size"):
            b.import_blocks({**payload, "block_size": 16})
        with pytest.raises(ValueError, match="kv_bits"):
            b.import_blocks({**payload, "kv_bits": 8})
        # Chain-key mismatch: replicas whose hashing diverged must be
        # refused loudly, not decode garbage.
        tampered = json.loads(json.dumps(payload))
        tampered["blocks"][0]["key"] = "00" * 20
        with pytest.raises(ValueError, match="chain-key mismatch"):
            b.import_blocks(tampered)
        # A stub for a chain this replica does not hold → KeyError (the
        # suffix-only transfer raced an eviction; caller falls back).
        stub = json.loads(json.dumps(payload))
        del stub["blocks"][0]["data"]
        with pytest.raises(KeyError, match="stub"):
            b.import_blocks(stub)

    def test_export_requires_prefix_cache_and_live_slot(self, tiny):
        plain = _engine(tiny, prefix_cache=False)
        rid = plain.submit(PROMPT, max_new_tokens=1)
        plain.run()
        with pytest.raises(RuntimeError, match="prefix_cache"):
            plain.export_blocks(rid)
        cached = _engine(tiny)
        rid = cached.submit(PROMPT, max_new_tokens=1)
        cached.run()  # retired: slot released
        with pytest.raises(KeyError, match="holds no slot"):
            cached.export_blocks(rid)


class TestSwapInterop:
    """Disagg handoff × host-RAM swap: a `/kv/probe` advisory hit on a
    swap-resident chain must be honorable — the probe counts it and the
    import PROMOTES it instead of refusing the stubbed payload."""

    def test_import_promotes_swap_resident_stub(self, tiny):
        """Replica B demoted its prefix chain to host RAM; a suffix-only
        payload whose stubs name those keys restores them from swap and
        decodes token-exact."""
        skip = [k.hex() for k in prompt_chain_keys(PROMPT, BS)]
        b = _engine(tiny, swap_bytes=1 << 22)
        b.submit(PROMPT, max_new_tokens=8)
        b.run()
        while b._evict_prefix_leaf():
            pass
        (key,) = prompt_chain_keys(PROMPT, BS)
        assert b.swap_contains(key) and not b._prefix_entries
        a = _engine(tiny)
        payload = _prefill_payload(a, PROMPT, skip_keys=skip)
        assert ["data" not in e for e in payload["blocks"]] == [True, False]
        rid = b.import_blocks(payload, max_new_tokens=8)
        assert rid is not None
        got = b.run()[rid]
        assert b.kv_swap_in == 1 and not b.swap_contains(key)
        assert b.kv_import_blocks_reused == 1
        c = _engine(tiny)
        r = c.submit(PROMPT, max_new_tokens=8)
        assert got == c.run()[r]

    def test_stub_missing_from_device_and_swap_still_raises(self, tiny):
        """Swap awareness must not weaken the refusal contract: a stub
        whose chain is in NEITHER tier still raises KeyError."""
        skip = [k.hex() for k in prompt_chain_keys(PROMPT, BS)]
        a = _engine(tiny)
        payload = _prefill_payload(a, PROMPT, skip_keys=skip)
        b = _engine(tiny, swap_bytes=1 << 22)  # swap enabled but empty
        with pytest.raises(KeyError, match="stub"):
            b.import_blocks(payload)

    def test_probe_and_stats_see_swap_tier(self, tiny):
        """HTTP surfacing: /kv/probe counts swap-resident keys as
        matched, /stats carries the kv_swap block and the pool-sizing
        outcome."""
        from kubeflow_tpu.models.server import InferenceServer

        srv = InferenceServer(
            _engine(tiny, swap_bytes=1 << 22), port=0, drain_s=0.5,
        ).start()
        try:
            conn = http.client.HTTPConnection(srv.host, srv.port,
                                              timeout=60)
            conn.request(
                "POST", "/v1/completions",
                json.dumps({"prompt": PROMPT, "max_tokens": 2}).encode(),
                {"Content-Type": "application/json"})
            assert conn.getresponse().status == 200
            keys = prompt_chain_keys(PROMPT, BS)
            with srv._lock:
                while srv.engine._evict_prefix_leaf():
                    pass
                assert srv.engine.swap_contains(keys[0])
            conn.request(
                "POST", "/kv/probe",
                json.dumps({"keys": [k.hex() for k in keys]
                            + ["00" * 20]}).encode(),
                {"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 200
            assert json.loads(resp.read())["matched"] == 1
            conn.request("GET", "/stats")
            stats = json.loads(conn.getresponse().read())
            conn.close()
            assert stats["kv_swap"]["swap_out"] == 1
            assert stats["kv_swap"]["swap_in"] == 0
            assert stats["kv_swap"]["restored_tokens"] == 0
            assert stats["kv_swap"]["swap_bytes"] > 0
            assert stats["kv_swap"]["swap_blocks"] == 1
            assert stats["kv_pool"] == {"num_blocks": 16,
                                        "source": "config"}
        finally:
            srv.stop()

    def test_export_chain_promotes_swap_resident(self, tiny):
        """Peer export must reach through the swap tier: a chain demoted
        to host RAM is promoted back, exported byte-exact vs a
        never-demoted replica, and lands as a prefix hit on the
        importer."""
        b = _engine(tiny, swap_bytes=1 << 22)
        b.submit(PROMPT, max_new_tokens=8)
        b.run()
        while b._evict_prefix_leaf():
            pass
        (key,) = prompt_chain_keys(PROMPT, BS)
        assert b.swap_contains(key) and not b._prefix_entries
        payload = b.export_chain([key])
        assert payload is not None
        assert b.kv_swap_in == 1 and not b.swap_contains(key)
        assert b.kv_chain_exports == 1
        a = _engine(tiny)
        a.submit(PROMPT, max_new_tokens=8)
        a.run()
        ref = a.export_chain([key])
        assert [e["data"] for e in payload["blocks"]] == \
            [e["data"] for e in ref["blocks"]]
        c = _engine(tiny)
        assert c.import_chain(payload, PROMPT) == 1
        rid = c.submit(PROMPT, max_new_tokens=8)
        got = c.run()[rid]
        assert c.prefix_hits == 1
        d = _engine(tiny)
        r = d.submit(PROMPT, max_new_tokens=8)
        assert got == d.run()[r]


class TestPoolFromHbm:
    def test_cpu_falls_back_to_constant(self, tiny):
        cfg, _ = tiny
        # CPU devices have no usable HBM memory_stats → the fallback
        # constant, untouched.
        assert pool_blocks_from_hbm(cfg, BS, fallback=37) == 37

    def test_budget_math_with_fake_device(self, tiny):
        cfg, _ = tiny

        class Dev:
            def memory_stats(self):
                return {"bytes_limit": 1 << 30, "bytes_in_use": 0}

        n = pool_blocks_from_hbm(cfg, BS, fraction=0.5, fallback=7,
                                 device=Dev())
        rows = cfg.n_layers * cfg.n_kv_heads * BS
        per_block = 2 * rows * cfg.head_dim * 2  # bf16 k + v
        assert n == max(2, int(0.5 * (1 << 30)) // per_block)

    def test_fraction_validated(self, tiny):
        cfg, _ = tiny
        with pytest.raises(ValueError):
            pool_blocks_from_hbm(cfg, BS, fraction=0.0)
        with pytest.raises(ValueError):
            pool_blocks_from_hbm(cfg, BS, fraction=1.5)

    def test_engine_accepts_hbm_fraction(self, tiny):
        cfg, params = tiny
        pb = PagedBatcher(params, cfg, slots=1, num_blocks=64,
                          block_size=BS, prompt_bucket=16,
                          hbm_fraction=0.25)
        # On CPU the fraction resolves to the fallback: the passed
        # num_blocks acts as the constant.
        assert pb.num_blocks == 64


class TestGatewayDisagg:
    def test_end_to_end_handoff_span_chain_and_token_parity(self, tiny):
        """One streamed request through a 1-prefill + 1-decode fleet:
        tokens equal the fused replica's, the gateway counts the
        transfer, and ONE trace carries prefill → kv_transfer →
        first_decode (the kv_transfer span bridges the tiers)."""
        from kubeflow_tpu.models.gateway import ServingGateway
        from kubeflow_tpu.models.server import InferenceServer
        from kubeflow_tpu.observability.tracing import (
            InMemoryExporter,
            TracerProvider,
            set_tracer_provider,
        )

        exp = InMemoryExporter()
        set_tracer_provider(TracerProvider(exp))
        servers = {role: InferenceServer(
            _engine(tiny, num_blocks=32, bucket=32), port=0, drain_s=0.5,
            tier_role=role,
        ).start() for role in ("prefill", "decode", "fused")}
        eps = {role: f"{s.host}:{s.port}" for role, s in servers.items()}
        gw = ServingGateway(
            [eps["prefill"], eps["decode"]], port=0, block_size=BS,
            health_interval_s=0.2, tier_mode="disagg",
            tier_roles={eps[r]: r for r in ("prefill", "decode")},
        ).start()
        try:
            def stream(host, port):
                conn = http.client.HTTPConnection(host, port, timeout=120)
                conn.request(
                    "POST", "/v1/completions",
                    json.dumps({"prompt": PROMPT, "max_tokens": 6,
                                "stream": True}).encode(),
                    {"Content-Type": "application/json"})
                resp = conn.getresponse()
                assert resp.status == 200
                toks = []
                while True:
                    line = resp.fp.readline()
                    if not line or line == b"data: [DONE]\n":
                        break
                    if line.startswith(b"data:"):
                        body = json.loads(line[5:])
                        assert "error" not in body, body
                        toks.append(body["token"])
                conn.close()
                return toks

            got = stream(gw.host, gw.port)
            ref = stream(servers["fused"].host, servers["fused"].port)
            assert got == ref and len(got) == 6

            stats = gw.stats()
            assert stats["tier_mode"] == "disagg"
            assert stats["kv_transfers"] == 1
            assert stats["kv_transfer_failures"] == 0
            assert stats["kv_transfer_bytes"] > 0
            assert stats["kv_transfer_latency_s"] > 0
            assert servers["prefill"].engine.kv_exports == 1
            assert servers["decode"].engine.kv_imports == 1

            # The fused reference replica traced its own request too —
            # the handoff trace is the one carrying kv_transfer.
            (tspan,) = exp.by_name("kv_transfer")
            trace = tspan.trace_id
            (pspan,) = [s for s in exp.by_name("prefill")
                        if s.trace_id == trace]
            # Both tiers emit first_decode (the prefill tier's 1-token
            # request delivers its pending token too); the decode
            # tier's is the one that started after the transfer.
            dspan = max((s for s in exp.by_name("first_decode")
                         if s.trace_id == trace),
                        key=lambda s: s.start_time)
            # One distributed trace end to end, ordered prefill →
            # kv_transfer → first_decode.
            assert pspan.end_time <= tspan.end_time
            assert tspan.start_time <= dspan.end_time
            assert [s for s in exp.by_name("kv_import")
                    if s.trace_id == trace]  # decode-side import span
        finally:
            set_tracer_provider(TracerProvider())
            gw.stop()
            for s in servers.values():
                s.stop()

    def test_tier_role_env_and_gateway_env_roundtrip(self, monkeypatch):
        from kubeflow_tpu.models.gateway import gateway_from_env
        from kubeflow_tpu.models.server import tier_role_from_env

        monkeypatch.setenv("KUBEFLOW_TPU_GATEWAY_TIER_ROLE", "prefill")
        assert tier_role_from_env() == "prefill"
        monkeypatch.setenv("KUBEFLOW_TPU_GATEWAY_TIER_ROLE", "bogus")
        with pytest.raises(ValueError):
            tier_role_from_env()
        monkeypatch.delenv("KUBEFLOW_TPU_GATEWAY_TIER_ROLE")

        monkeypatch.setenv("KUBEFLOW_TPU_GATEWAY_TIER_MODE", "disagg")
        monkeypatch.setenv("KUBEFLOW_TPU_GATEWAY_TIER_PREFILL",
                           "10.0.0.1:8000")
        monkeypatch.setenv("KUBEFLOW_TPU_GATEWAY_TIER_DECODE",
                           "10.0.0.2:8000, 10.0.0.3:8000")
        monkeypatch.setenv("KUBEFLOW_TPU_KV_TRANSFER_TIMEOUT_S", "12.5")
        monkeypatch.setenv("KUBEFLOW_TPU_KV_TRANSFER_MAX_BYTES", "1048576")
        gw = gateway_from_env()
        assert gw.tier_mode == "disagg"
        assert gw._tier_roles == {
            "10.0.0.1:8000": "prefill",
            "10.0.0.2:8000": "decode",
            "10.0.0.3:8000": "decode",
        }
        assert gw.kv_transfer_timeout_s == 12.5
        assert gw.kv_transfer_max_bytes == 1048576
        assert set(gw._replicas) == {
            "10.0.0.1:8000", "10.0.0.2:8000", "10.0.0.3:8000"}
        monkeypatch.setenv("KUBEFLOW_TPU_GATEWAY_TIER_DECODE",
                           "10.0.0.1:8000")
        with pytest.raises(ValueError, match="both tiers"):
            gateway_from_env()
        monkeypatch.setenv("KUBEFLOW_TPU_GATEWAY_TIER_DECODE", "")
        monkeypatch.setenv("KUBEFLOW_TPU_GATEWAY_TIER_MODE", "sharded")
        with pytest.raises(ValueError, match="TIER_MODE"):
            gateway_from_env()
