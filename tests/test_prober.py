"""Native concurrent slice prober: correctness + fan-out latency.

Mirrors what the reference tests for its culler HTTP path
(culling_controller.go:244-322) and adds the multi-host guarantees the
reference never needed: per-host independence and O(1 timeout) wall time.
"""

from __future__ import annotations

import http.server
import json
import pathlib
import socket
import subprocess
import threading
import time

import pytest

from kubeflow_tpu.api.notebook import Notebook
from kubeflow_tpu.controller import prober as prober_mod
from kubeflow_tpu.controller.culling import JupyterHTTPProber

NATIVE = pathlib.Path(__file__).resolve().parent.parent / "native"


@pytest.fixture(scope="module")
def native_lib():
    if not (NATIVE / "libkftpu_prober.so").exists():
        build = subprocess.run(
            ["make", "-C", str(NATIVE), "libkftpu_prober.so"],
            capture_output=True,
        )
        if build.returncode != 0:
            pytest.skip("native prober not buildable here")
    lib = prober_mod._load_lib()
    assert lib is not None
    return lib


class _JupyterHandler(http.server.BaseHTTPRequestHandler):
    kernels: list = []
    terminals: list = []
    delay_s: float = 0.0

    def do_GET(self):
        if self.delay_s:
            time.sleep(self.delay_s)
        if self.path.endswith("/api/kernels"):
            payload = self.kernels
        elif self.path.endswith("/api/terminals"):
            payload = self.terminals
        else:
            self.send_response(404)
            self.end_headers()
            return
        body = json.dumps(payload).encode()
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *a):  # noqa: D102 - silence
        pass


def _serve(kernels, terminals, delay_s=0.0):
    handler = type(
        "H",
        (_JupyterHandler,),
        {"kernels": kernels, "terminals": terminals, "delay_s": delay_s},
    )
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def _nb():
    return Notebook(
        {
            "apiVersion": "kubeflow.org/v1beta1",
            "kind": "Notebook",
            "metadata": {"name": "nb", "namespace": "user"},
            "spec": {"template": {"spec": {"containers": [{"name": "nb"}]}}},
        }
    )


BUSY = [{"execution_state": "busy", "last_activity": "2026-07-29T10:00:00.000000Z"}]
IDLE = [{"execution_state": "idle", "last_activity": "2026-07-28T09:00:00.000000Z"}]
TERM = [{"last_activity": "2026-07-29T11:00:00.000000Z"}]


def _dead_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_native_probe_matches_python_prober(native_lib):
    srv = _serve(IDLE, TERM)
    try:
        host = f"127.0.0.1:{srv.server_address[1]}"
        # Both probers hardcode :8888; probe the raw URL layer for the
        # native one and the merged layer via a port-carrying host for the
        # Python one is not possible — so compare at the _raw_probe level
        # plus a full probe through a port-patched URL builder.
        native = prober_mod.NativeFanoutProber(timeout_s=2.0, lib=native_lib)
        nb = _nb()
        base = f"http://{host}/notebook/{nb.namespace}/{nb.name}"
        statuses, bodies = native._raw_probe(
            [f"{base}/api/kernels", f"{base}/api/terminals"]
        )
        assert statuses == [200, 200]
        assert json.loads(bodies[0].decode()) == IDLE
        assert json.loads(bodies[1].decode()) == TERM
    finally:
        srv.shutdown()


def test_native_full_probe_merges_activity(native_lib):
    """Exercises NativeFanoutProber.probe() itself: busy detection and the
    kernel/terminal last_activity max-merge (terminal is newer here)."""
    srv = _serve(BUSY, TERM)
    try:
        native = prober_mod.NativeFanoutProber(
            timeout_s=2.0, lib=native_lib, port=srv.server_address[1]
        )
        acts = native.probe(_nb(), ["127.0.0.1"])
        assert len(acts) == 1
        assert acts[0].reachable and acts[0].busy
        # TERM's 11:00Z beats BUSY's 10:00Z in the max-merge.
        from kubeflow_tpu.controller.culling import _parse_jupyter_time

        assert acts[0].last_activity == _parse_jupyter_time(TERM[0]["last_activity"])
    finally:
        srv.shutdown()


def test_native_full_probe_marks_unreachable_host(native_lib):
    srv = _serve(IDLE, [])
    try:
        native = prober_mod.NativeFanoutProber(
            timeout_s=1.0, lib=native_lib, port=srv.server_address[1]
        )
        acts = native.probe(_nb(), ["127.0.0.1", "10.255.255.1"])
        assert acts[0].reachable and not acts[0].busy
        assert not acts[1].reachable
    finally:
        srv.shutdown()


def test_native_unreachable_host_reports_failure(native_lib):
    native = prober_mod.NativeFanoutProber(timeout_s=0.5, lib=native_lib)
    url = f"http://127.0.0.1:{_dead_port()}/api/kernels"
    statuses, bodies = native._raw_probe([url])
    assert statuses[0] < 0
    assert bodies[0] == b""


def test_native_bad_url_distinct_code(native_lib):
    native = prober_mod.NativeFanoutProber(timeout_s=0.5, lib=native_lib)
    statuses, _ = native._raw_probe(["ftp://nope/x"])
    assert statuses[0] == -2


def test_fanout_wall_time_is_one_timeout_not_n(native_lib):
    """16 unreachable hosts must cost ~one timeout, not 16× (the native
    prober's reason to exist)."""
    native = prober_mod.NativeFanoutProber(timeout_s=0.5, lib=native_lib)
    urls = [f"http://10.255.255.{i}:9/api/kernels" for i in range(1, 17)]
    t0 = time.monotonic()
    statuses, _ = native._raw_probe(urls)
    elapsed = time.monotonic() - t0
    assert all(s < 0 for s in statuses)
    # Sequential would be ≥ 8s; allow generous slack for CI jitter.
    assert elapsed < 4.0


def test_probe_mixed_reachable_and_dead(native_lib):
    srv = _serve(IDLE, [])
    try:
        alive = f"http://127.0.0.1:{srv.server_address[1]}/notebook/u/n/api/kernels"
        dead = f"http://127.0.0.1:{_dead_port()}/api/kernels"
        native = prober_mod.NativeFanoutProber(timeout_s=1.0, lib=native_lib)
        statuses, bodies = native._raw_probe([alive, dead, alive])
        assert statuses[0] == 200 and statuses[2] == 200
        assert statuses[1] < 0
        assert json.loads(bodies[0].decode()) == IDLE
    finally:
        srv.shutdown()


def test_trickling_host_cannot_exceed_overall_deadline(native_lib):
    """A host that drips bytes forever (each gap under the timeout) must
    still be cut off at the OVERALL deadline — per-poll timeout restarts
    would let it hold a worker thread indefinitely."""

    class Trickler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            self.send_response(200)
            self.end_headers()
            try:
                for _ in range(50):  # ~10s of dripping if never cut off
                    self.wfile.write(b"x")
                    self.wfile.flush()
                    time.sleep(0.2)
            except BrokenPipeError:
                pass

        def log_message(self, *a):
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Trickler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        native = prober_mod.NativeFanoutProber(timeout_s=1.0, lib=native_lib)
        url = f"http://127.0.0.1:{srv.server_address[1]}/api/kernels"
        t0 = time.monotonic()
        statuses, _ = native._raw_probe([url])
        elapsed = time.monotonic() - t0
        assert elapsed < 3.0  # 1s budget + slack, nowhere near 10s
        assert statuses[0] == 200  # headers arrived before the cutoff
    finally:
        srv.shutdown()


def test_truncated_kernel_list_reads_as_busy_not_unreachable(
    native_lib, monkeypatch
):
    """A kernels body that overflows _BODY_CAP must mark the host BUSY —
    treating it as unreachable would trip the never-cull-blind rule and
    hold the slice forever (a kernel-leaking notebook is exactly what the
    culler exists to see)."""
    monkeypatch.setattr(prober_mod, "_BODY_CAP", 512)
    huge = [
        {"execution_state": "idle", "last_activity": "2026-07-29T10:00:00.000000Z"}
    ] * 50  # ~4 KB as JSON, far over the patched 512-byte cap
    srv = _serve(huge, [])
    try:
        native = prober_mod.NativeFanoutProber(
            timeout_s=2.0, lib=native_lib, port=srv.server_address[1]
        )
        acts = native.probe(_nb(), ["127.0.0.1"])
        assert acts[0].reachable
        assert acts[0].busy
    finally:
        srv.shutdown()


def test_hung_dns_respects_deadline(native_lib):
    """Name resolution shares the overall budget: an unresolvable name must
    fail within ~timeout, never wedge the worker thread."""
    native = prober_mod.NativeFanoutProber(timeout_s=1.0, lib=native_lib)
    t0 = time.monotonic()
    statuses, _ = native._raw_probe(
        ["http://nonexistent-host.invalid:8888/api/kernels"]
    )
    assert statuses[0] == -1
    assert time.monotonic() - t0 < 5.0


def test_make_prober_falls_back_without_lib(monkeypatch):
    monkeypatch.setattr(prober_mod, "_LIB_PATH", pathlib.Path("/nonexistent.so"))
    p = prober_mod.make_prober()
    assert isinstance(p, JupyterHTTPProber)


def test_make_prober_dev_mode_uses_python_proxy_path():
    p = prober_mod.make_prober(dev_proxy="http://localhost:8001")
    assert isinstance(p, JupyterHTTPProber)
    assert p.dev_proxy == "http://localhost:8001"


# -- JupyterHTTPProber concurrency (pure-Python fallback path) -------------


class _ScriptedHTTPProber(JupyterHTTPProber):
    """JupyterHTTPProber with the network layer replaced by scripted
    per-host delays — exercises the real executor/deadline/fold plumbing
    in probe() without sockets."""

    def __init__(self, delays: dict, **kw):
        super().__init__(**kw)
        self.delays = delays

    def _probe_host(self, nb, host):
        time.sleep(self.delays.get(host, 0.0))
        return IDLE, []


def test_http_prober_fans_out_hosts_concurrently():
    """8 hosts × 0.3s each must cost ~one delay, not 8× — the reason the
    Python prober grew an executor (same property the native prober
    asserts in test_fanout_wall_time_is_one_timeout_not_n)."""
    hosts = [f"h{i}" for i in range(8)]
    prober = _ScriptedHTTPProber(
        {h: 0.3 for h in hosts}, slice_deadline_s=10.0
    )
    t0 = time.monotonic()
    acts = prober.probe(_nb(), hosts)
    elapsed = time.monotonic() - t0
    assert elapsed < 1.5  # sequential would be ≥ 2.4s
    assert [a.host for a in acts] == hosts  # fold order == host order
    assert all(a.reachable and not a.busy for a in acts)


def test_http_prober_slice_deadline_folds_stragglers_unreachable():
    """One host stalls past slice_deadline_s: the reconcile returns at the
    deadline with that host folded unreachable (the culler's never-judge
    state), the healthy hosts intact."""
    prober = _ScriptedHTTPProber(
        {"h0": 0.0, "h1": 5.0, "h2": 0.0}, slice_deadline_s=0.5
    )
    t0 = time.monotonic()
    acts = prober.probe(_nb(), ["h0", "h1", "h2"])
    elapsed = time.monotonic() - t0
    assert elapsed < 2.0  # bounded by the deadline, not the 5s straggler
    assert acts[0].reachable
    assert not acts[1].reachable
    assert acts[2].reachable


def test_http_prober_empty_host_list():
    assert JupyterHTTPProber().probe(_nb(), []) == []
