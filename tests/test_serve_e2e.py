"""Production-wiring e2e: managers over RealClient + HTTP apiserver.

The round-1 verdict's acceptance test: both managers assembled by the SAME
``build()`` that ``main()`` uses, talking to an apiserver over HTTP (the
envtest façade), admission delivered over HTTPS with self-signed serving
certs, a kubelet fixture also living on the far side of HTTP, and a
Notebook CR becoming running pods end-to-end — the reference's KinD
integration flow (reference .github/workflows/
odh_notebook_controller_integration_test.yaml:120-220) without cluster
binaries.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from kubeflow_tpu import k8s
from kubeflow_tpu.api import annotations as ann
from kubeflow_tpu.cmd import notebook_manager, platform_manager
from kubeflow_tpu.k8s.envtest import EnvtestServer
from kubeflow_tpu.k8s.manager import Manager, RealClock
from kubeflow_tpu.k8s.real import RealClient
from kubeflow_tpu.k8s.serve import serve, split_addr
from kubeflow_tpu.metrics.server import MetricsServer
from kubeflow_tpu.webhook.server import MUTATE_PATH, VALIDATE_PATH, WebhookServer

from tests.harness import tpu_notebook

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_certs(cert_dir, cn="webhook.opendatahub.svc") -> str:
    """Self-signed serving cert via the openssl CLI (the KinD workflow's
    cert-generation step). Returns the CA path (== the cert, self-signed)."""
    cert = os.path.join(cert_dir, "tls.crt")
    key = os.path.join(cert_dir, "tls.key")
    subprocess.run(
        [
            "openssl", "req", "-x509", "-newkey", "rsa:2048",
            "-keyout", key, "-out", cert, "-days", "1", "-nodes",
            "-subj", f"/CN={cn}",
            "-addext", "subjectAltName=IP:127.0.0.1,DNS:localhost",
        ],
        check=True, capture_output=True,
    )
    return cert


class _Shim:
    """Minimal bundle for serve(): the kubelet fixture's manager."""

    def __init__(self, manager):
        self.manager = manager

    def run_until_idle(self, max_cycles: int = 200) -> int:
        return self.manager.run_until_idle(max_cycles)

    def tick(self, seconds: float) -> int:
        return self.manager.tick(seconds)


@pytest.fixture
def stack(tmp_path):
    """apiserver + both managers + kubelet, all over the wire."""
    cluster = k8s.FakeCluster()
    k8s.add_tpu_node_pool(
        cluster, "tpu-v5-lite-podslice", "4x4", hosts=4, chips_per_host=4
    )
    server = EnvtestServer(cluster).start()

    clients: list[RealClient] = []

    def new_client() -> RealClient:
        c = RealClient(server.client_config())
        clients.append(c)
        return c

    # Platform manager + HTTPS admission.
    ca_file = make_certs(str(tmp_path))
    platform = platform_manager.build(
        new_client(),
        env={"K8S_NAMESPACE": "opendatahub"},
        argv=["--kube-rbac-proxy-image", "proxy:v1"],
        clock=RealClock(),
    )
    webhook_server = WebhookServer(
        mutating_handler=platform.mutating_webhook.handle,
        validating_handler=platform.validating_webhook.handle,
        cert_dir=str(tmp_path),
        tls_profile=platform.tls_profile,
    )
    webhook_server.start()
    assert webhook_server.tls_enabled
    base = f"https://127.0.0.1:{webhook_server.port}"
    server.add_remote_webhook(
        "Notebook",
        mutate_url=base + MUTATE_PATH,
        validate_url=base + VALIDATE_PATH,
        ca_file=ca_file,
    )

    core = notebook_manager.build(new_client(), env={}, clock=RealClock())

    kubelet_client = new_client()
    kubelet_manager = Manager(kubelet_client, clock=RealClock())
    k8s.FakeKubelet(kubelet_client).register(kubelet_manager)

    stop = threading.Event()
    threads = [
        threading.Thread(target=serve, args=(b, c, stop), daemon=True)
        for b, c in (
            (platform, clients[0]),
            (core, clients[1]),
            (_Shim(kubelet_manager), kubelet_client),
        )
    ]
    for t in threads:
        t.start()

    class Stack:
        pass

    s = Stack()
    s.server, s.cluster, s.core, s.platform = server, cluster, core, platform
    s.webhook_server, s.user = webhook_server, new_client()
    s.tmp_path = tmp_path
    s.clients = clients
    yield s

    stop.set()
    for t in threads:
        t.join(timeout=5)
    webhook_server.stop()
    for c in clients:
        c.stop()
    server.stop()


def _wait_for(fn, timeout=30.0, interval=0.1, desc="condition"):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            last = fn()
            if last:
                return last
        except Exception as err:  # noqa: PERF203 - poll loop
            last = err
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {desc} (last: {last!r})")


@pytest.mark.slow
def test_notebook_becomes_running_pods_over_the_wire(stack):
    nb = tpu_notebook(name="wb")
    created = stack.user.create(nb)
    # HTTPS admission ran: reconciliation lock + TPU env injected.
    assert created["metadata"]["annotations"][ann.STOP] == ann.RECONCILIATION_LOCK_VALUE
    env_names = {
        e["name"]
        for c in created["spec"]["template"]["spec"]["containers"]
        for e in c.get("env", [])
    }
    assert "TPU_WORKER_HOSTNAMES" in env_names

    def slice_ready():
        obj = stack.user.get("Notebook", "wb", "ns")
        return obj if obj.get("status", {}).get("readyReplicas") == 4 else None

    obj = _wait_for(slice_ready, desc="4 ready hosts")
    assert obj["status"]["tpu"]["sliceHealth"] == "Healthy"

    pods = stack.user.list("Pod", "ns", {"notebook-name": "wb"})
    assert len(pods) == 4
    # Platform side converged too (HTTPRoute lives in the central ns).
    _wait_for(
        lambda: stack.user.exists("HTTPRoute", "nb-ns-wb", "opendatahub"),
        desc="HTTPRoute",
    )
    _wait_for(
        lambda: stack.user.exists("NetworkPolicy", "wb-ctrl-np", "ns"),
        desc="NetworkPolicy",
    )

    # Validating webhook over HTTPS: topology change on a running slice denied.
    from kubeflow_tpu.k8s.errors import WebhookDeniedError

    fresh = stack.user.get("Notebook", "wb", "ns")
    fresh["spec"]["tpu"]["topology"] = "2x4"
    with pytest.raises(WebhookDeniedError):
        stack.user.update(fresh)

    # Delete: finalizer-driven cleanup drains everything.
    stack.user.delete("Notebook", "wb", "ns")
    _wait_for(
        lambda: not stack.user.exists("Notebook", "wb", "ns"),
        desc="notebook deletion",
    )
    _wait_for(lambda: stack.user.list("Pod", "ns") == [], desc="pods gone")


@pytest.mark.slow
def test_slicepool_claim_over_the_wire(stack):
    """Warm pool → locked Notebook → lock release → claim, all through the
    production wiring (HTTP apiserver, HTTPS admission, serve loops). Also
    proves the SlicePool CRD schema is enforced over the wire."""
    from kubeflow_tpu.api.notebook import TPUSpec
    from kubeflow_tpu.api.slicepool import CLAIMED_FROM, new_slicepool
    from kubeflow_tpu.k8s.errors import InvalidError

    with pytest.raises(InvalidError):
        stack.user.create(
            new_slicepool("bad", "ns", TPUSpec("v5e", "not-a-topology"))
        )

    stack.user.create(
        new_slicepool("pool", "ns", TPUSpec("v5e", "4x4"), warm_replicas=1)
    )
    _wait_for(
        lambda: stack.user.get("SlicePool", "pool", "ns")
        .get("status", {}).get("readyReplicas") == 1,
        desc="warm placeholder ready",
    )
    from kubeflow_tpu.api.slicepool import STATE_LABEL, STATE_WARM

    def warm_names():
        return {
            s["metadata"]["name"]
            for s in stack.user.list(
                "StatefulSet", "ns", {STATE_LABEL: STATE_WARM}
            )
        }

    before = warm_names()

    nb = tpu_notebook(name="wb3")
    created = stack.user.create(nb)
    # Admission held the slice down; the claim must still happen when the
    # platform reconciler releases the lock (the 0→N transition).
    assert created["metadata"]["annotations"][ann.STOP] == (
        ann.RECONCILIATION_LOCK_VALUE
    )
    _wait_for(
        lambda: stack.user.get("Notebook", "wb3", "ns")["metadata"]
        .get("annotations", {}).get(CLAIMED_FROM) == "pool",
        desc="warm slice claimed",
    )
    _wait_for(
        lambda: stack.user.get("Notebook", "wb3", "ns")
        .get("status", {}).get("readyReplicas") == 4,
        desc="4 ready hosts on claimed capacity",
    )
    # The pool refilled with a NEW generation (warmReplicas alone could be
    # a stale pre-claim status; a different placeholder name cannot).
    _wait_for(
        lambda: warm_names() and warm_names() != before,
        desc="pool refill (regenerated placeholder)",
    )
    stack.user.delete("Notebook", "wb3", "ns")
    _wait_for(
        lambda: not stack.user.exists("Notebook", "wb3", "ns"),
        desc="notebook deletion",
    )
    stack.user.delete("SlicePool", "pool", "ns")


@pytest.mark.slow
def test_metrics_and_cert_rotation(stack):
    # /metrics serves the reference metric set off a live scrape.
    metrics_server = MetricsServer(stack.core.metrics)
    metrics_server.start()
    try:
        stack.user.create(tpu_notebook(name="wb2"))
        _wait_for(
            lambda: stack.user.get("Notebook", "wb2", "ns")
            .get("status", {}).get("readyReplicas") == 4,
            desc="slice ready",
        )
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{metrics_server.port}/metrics", timeout=5
        ).read().decode()
        assert "notebook_running 1.0" in body
        assert "notebook_create_total 1.0" in body
        assert "tpu_chips_in_use 16.0" in body
        assert "tpu_slice_ready_seconds" in body
    finally:
        metrics_server.stop()

    # Cert rotation: regenerate serving certs in place; the reloader picks
    # them up and admission keeps working over HTTPS.
    old_reloads = stack.webhook_server.cert_reloads
    time.sleep(0.05)  # ensure distinct mtime_ns at fs-timestamp granularity
    new_ca = make_certs(str(stack.tmp_path))
    assert stack.webhook_server.poll_certs()
    assert stack.webhook_server.cert_reloads == old_reloads + 1
    # Re-point the apiserver's caBundle at the rotated CA (real clusters
    # rotate both sides the same way) and prove admission still round-trips.
    stack.server.add_remote_webhook(
        "Notebook",
        mutate_url=f"https://127.0.0.1:{stack.webhook_server.port}{MUTATE_PATH}",
        validate_url=f"https://127.0.0.1:{stack.webhook_server.port}{VALIDATE_PATH}",
        ca_file=new_ca,
    )
    created = stack.user.create(tpu_notebook(name="wb3"))
    assert created["metadata"]["annotations"][ann.STOP] == ann.RECONCILIATION_LOCK_VALUE


@pytest.mark.slow
def test_relist_after_410_through_serve_loop(stack):
    """Compact the apiserver's event log past every watcher's position
    (etcd compaction): the production serve loops must hit 410 Gone over
    the wire, relist, and keep reconciling new CRs."""
    stack.user.create(tpu_notebook(name="wb410"))
    _wait_for(
        lambda: stack.user.get("Notebook", "wb410", "ns")
        .get("status", {}).get("readyReplicas") == 4,
        desc="first slice ready",
    )

    # Sever every live watch, then compact the log to zero retained events
    # — every resume rv is now behind the horizon, forcing the 410 path.
    for client in stack.clients:
        for watcher in client._watchers:
            conn = watcher._conn
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass
    with stack.server.lock:
        stack.server.cluster.compact_events(0)

    # Deletion AND a fresh slice must both reconcile post-relist (the node
    # pool only fits one slice, so wb410 must drain before wb411 fits).
    stack.user.delete("Notebook", "wb410", "ns")
    _wait_for(
        lambda: not stack.user.exists("Notebook", "wb410", "ns"),
        desc="post-compaction deletion (410 relist recovery)",
        timeout=60,
    )
    stack.user.create(tpu_notebook(name="wb411"))
    _wait_for(
        lambda: stack.user.get("Notebook", "wb411", "ns")
        .get("status", {}).get("readyReplicas") == 4,
        desc="post-compaction slice ready (410 relist recovery)",
        timeout=60,
    )


def test_webhook_server_fails_closed_without_certs(tmp_path):
    from kubeflow_tpu.webhook.server import CertError

    with pytest.raises(CertError):
        WebhookServer(cert_dir=str(tmp_path))  # empty dir: no tls.crt/key


def test_tls_profile_applied_to_listener(tmp_path):
    import ssl

    from kubeflow_tpu.controller.tls import MODERN

    make_certs(str(tmp_path))
    server = WebhookServer(
        mutating_handler=lambda req: req.object,
        cert_dir=str(tmp_path),
        tls_profile=MODERN,
    )
    server.start()
    try:
        # Modern profile = TLS 1.3 minimum: a 1.2-capped client must fail.
        capped = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        capped.check_hostname = False
        capped.verify_mode = ssl.CERT_NONE
        capped.maximum_version = ssl.TLSVersion.TLSv1_2
        with pytest.raises(ssl.SSLError):
            with socket.create_connection(("127.0.0.1", server.port), 5) as sock:
                with capped.wrap_socket(sock):
                    pass
        # And a 1.3 client succeeds.
        ok = ssl.SSLContext(ssl.PROTOCOL_TLS_CLIENT)
        ok.check_hostname = False
        ok.verify_mode = ssl.CERT_NONE
        with socket.create_connection(("127.0.0.1", server.port), 5) as sock:
            with ok.wrap_socket(sock) as tls:
                assert tls.version() == "TLSv1.3"
    finally:
        server.stop()


@pytest.mark.slow
def test_manager_entrypoint_subprocess(tmp_path):
    """`python -m kubeflow_tpu.cmd.notebook_manager` — the container
    ENTRYPOINT — must serve probes, reconcile, and exit 0 on SIGTERM."""
    cluster = k8s.FakeCluster()
    k8s.add_tpu_node_pool(
        cluster, "tpu-v5-lite-podslice", "4x4", hosts=4, chips_per_host=4
    )
    server = EnvtestServer(cluster).start()

    kubeconfig = tmp_path / "kubeconfig"
    kubeconfig.write_text(
        f"""
apiVersion: v1
kind: Config
current-context: envtest
contexts:
- name: envtest
  context: {{cluster: envtest, user: dev}}
clusters:
- name: envtest
  cluster: {{server: "http://127.0.0.1:{server.port}"}}
users:
- name: dev
  user: {{}}
"""
    )

    def free_port() -> int:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    probe_port, metrics_port = free_port(), free_port()
    env = {
        **os.environ,
        "KUBECONFIG": str(kubeconfig),
        "KUBERNETES_SERVICE_HOST": "",  # force the kubeconfig path
        "PYTHONPATH": REPO_ROOT,
    }
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "kubeflow_tpu.cmd.notebook_manager",
            "--probe-addr", f"127.0.0.1:{probe_port}",
            "--metrics-addr", f"127.0.0.1:{metrics_port}",
        ],
        env=env, cwd=REPO_ROOT,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )
    try:
        def probe_ok():
            try:
                with urllib.request.urlopen(
                    f"http://127.0.0.1:{probe_port}/healthz", timeout=1
                ) as resp:
                    return resp.status == 200
            except OSError:
                return False

        _wait_for(probe_ok, timeout=20, desc="healthz")
        with urllib.request.urlopen(
            f"http://127.0.0.1:{probe_port}/readyz", timeout=2
        ) as resp:
            assert json.loads(resp.read())["readyz"] == "ok"

        # The subprocess manager reconciles a Notebook created via the API.
        user = RealClient(server.client_config())
        user.create(tpu_notebook(name="subp"))
        _wait_for(
            lambda: user.exists("StatefulSet", "subp", "ns"),
            timeout=20, desc="subprocess reconcile",
        )
        with urllib.request.urlopen(
            f"http://127.0.0.1:{metrics_port}/metrics", timeout=2
        ) as resp:
            assert b"notebook_create_total" in resp.read()
        user.stop()

        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=15) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
        out = proc.stdout.read().decode(errors="replace")
        server.stop()
        if proc.returncode not in (0, -signal.SIGKILL):
            raise AssertionError(f"manager exited {proc.returncode}:\n{out}")
