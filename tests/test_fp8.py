"""fp8 training (delayed scaling, OWG meta updates) and fp8 weight-only
serving — models/fp8.py.

Reference parity note: the reference has no ML runtime; this is added
TPU-native scope (ROADMAP "fp8 training + serving"). Numerics run
identically on CPU (XLA upcasts fp8 operands where there are no fp8 MXU
lanes), so everything here is chip-independent.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kubeflow_tpu.models import fp8
from kubeflow_tpu.models import llama as L
from kubeflow_tpu.models.quant import dequantize_weight, quantize_params
from kubeflow_tpu.models.train import make_train_step, shard_state
from kubeflow_tpu.parallel.mesh import MeshPlan, make_mesh

CFG = L.LLAMA_CONFIGS["tiny"]


class TestFp8Matmul:
    def test_matches_dense_for_in_range_values(self):
        """With well-scaled inputs the fp8 matmul must track the dense
        result to e4m3 mantissa precision (~2 decimal digits)."""
        k1, k2 = jax.random.split(jax.random.PRNGKey(0))
        x = jax.random.normal(k1, (4, 32), jnp.float32)
        w = jax.random.normal(k2, (32, 16), jnp.float32)
        meta = fp8.init_meta()
        # Prime the histories so the scales match the data range.
        meta = {
            "x_hist": meta["x_hist"].at[0].set(jnp.max(jnp.abs(x))),
            "w_hist": meta["w_hist"].at[0].set(jnp.max(jnp.abs(w))),
            "g_hist": meta["g_hist"],
        }
        y = fp8.fp8_matmul(x, w, meta)
        dense = x @ w
        # e4m3 has 3 mantissa bits → ~6% worst-case per-element relative
        # error; a K=32 dot product accumulates to a few % of the output
        # magnitude (measured ~4% on this seed).
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(dense), rtol=0.1,
            atol=0.06 * float(np.max(np.abs(np.asarray(dense)))),
        )

    def test_first_step_scale_is_one_not_inf(self):
        """All-zero history (step 0) must scale by 1.0, not divide by 0."""
        x = jnp.ones((2, 8), jnp.float32)
        w = jnp.ones((8, 4), jnp.float32)
        y = fp8.fp8_matmul(x, w, fp8.init_meta())
        assert bool(jnp.all(jnp.isfinite(y)))
        np.testing.assert_allclose(np.asarray(y), 8.0, rtol=0.01)

    def test_grad_carries_next_meta(self):
        """The meta cotangent must be the NEXT meta (OWG): histories
        rolled with the newly observed amaxes, not a descent direction."""
        k1, k2 = jax.random.split(jax.random.PRNGKey(1))
        x = jax.random.normal(k1, (4, 8), jnp.float32) * 3.0
        w = jax.random.normal(k2, (8, 4), jnp.float32) * 0.5

        def loss(x, w, meta):
            return jnp.sum(fp8.fp8_matmul(x, w, meta) ** 2)

        meta = fp8.init_meta()
        dx, dw, dmeta = jax.grad(loss, argnums=(0, 1, 2))(x, w, meta)
        assert float(dmeta["x_hist"][0]) == pytest.approx(
            float(jnp.max(jnp.abs(x))), rel=1e-6
        )
        assert float(dmeta["w_hist"][0]) == pytest.approx(
            float(jnp.max(jnp.abs(w))), rel=1e-6
        )
        # g amax observed in the backward pass
        assert float(dmeta["g_hist"][0]) > 0.0
        # and the weight grad is a real gradient (fp8-rounded dense grad)
        dense_dw = jax.grad(lambda w: jnp.sum((x @ w) ** 2))(w)
        np.testing.assert_allclose(
            np.asarray(dw), np.asarray(dense_dw), rtol=0.2,
            atol=0.06 * float(np.max(np.abs(np.asarray(dense_dw)))),
        )

    def test_overflow_saturates_not_nan(self):
        """Values past the format max (history underestimates the data)
        must clip to ±448, never become NaN (e4m3fn has no inf)."""
        x = jnp.full((2, 4), 1e6, jnp.float32)
        w = jnp.eye(4, dtype=jnp.float32)
        meta = fp8.init_meta()
        meta = {**meta, "x_hist": meta["x_hist"].at[0].set(1.0)}
        y = fp8.fp8_matmul(x, w, meta)
        assert bool(jnp.all(jnp.isfinite(y)))


class TestFp8Params:
    def test_wrap_unwrap_roundtrip(self):
        params = L.init_params(CFG, jax.random.PRNGKey(0))
        wrapped = fp8.wrap_params_fp8(params)
        assert fp8.has_fp8_params(wrapped)
        assert not fp8.has_fp8_params(params)
        # per-layer metas: histories stacked on the layer axis
        assert wrapped["layers"]["wq"]["fp8"]["x_hist"].shape == (
            CFG.n_layers, fp8._HISTORY,
        )
        plain = fp8.unwrap_params_fp8(wrapped)
        for t in ("wq", "w_down"):
            assert plain["layers"][t] is params["layers"][t]
        # norms / embed untouched by wrapping
        assert wrapped["embed"] is params["embed"]

    def test_partition_labels(self):
        wrapped = fp8.wrap_params_fp8(L.init_params(CFG, jax.random.PRNGKey(0)))
        labels = fp8.fp8_partition_labels(wrapped)
        assert labels["layers"]["wq"]["fp8"]["x_hist"] == "fp8_meta"
        assert labels["layers"]["wq"]["hp"] == "default"
        assert labels["embed"] == "default"


class TestFp8Training:
    def test_loss_decreases_and_tracks_bf16(self):
        """5 fp8 steps on a dp×fsdp×tp mesh: loss must fall and stay
        close to the bf16 run on the same data; metas must update."""
        mesh = make_mesh(dp=2, fsdp=2, tp=2)
        plan = MeshPlan(mesh)
        toks = jax.random.randint(
            jax.random.PRNGKey(1), (8, 128), 0, CFG.vocab_size
        )

        init8, step8 = make_train_step(CFG, plan, fp8=True, loss_chunk=64)
        state = shard_state(
            plan, init8(fp8.wrap_params_fp8(L.init_params(CFG, jax.random.PRNGKey(0))))
        )
        first = last = None
        for _ in range(5):
            state, loss = step8(state, toks)
            first = float(loss) if first is None else first
            last = float(loss)
        assert last < first

        init16, step16 = make_train_step(CFG, plan, loss_chunk=64)
        ref = shard_state(plan, init16(L.init_params(CFG, jax.random.PRNGKey(0))))
        for _ in range(5):
            ref, ref_loss = step16(ref, toks)
        # fp8 quantization noise, not divergence
        assert abs(last - float(ref_loss)) < 0.15

        meta = state["params"]["layers"]["wq"]["fp8"]
        assert float(jnp.max(meta["x_hist"])) > 0
        assert float(jnp.max(meta["g_hist"])) > 0
        # master weights stay high precision
        assert state["params"]["layers"]["wq"]["hp"].dtype == jnp.bfloat16

    def test_flag_tree_mismatch_raises(self):
        plan = MeshPlan(make_mesh(dp=8))
        params = L.init_params(CFG, jax.random.PRNGKey(0))
        init8, _ = make_train_step(CFG, plan, fp8=True)
        with pytest.raises(ValueError, match="fp8"):
            init8(params)  # plain tree under fp8 optimizer
        init16, _ = make_train_step(CFG, plan)
        with pytest.raises(ValueError, match="fp8"):
            init16(fp8.wrap_params_fp8(params))  # wrapped tree, no flag

    def test_unwrapped_trained_params_generate(self):
        plan = MeshPlan(make_mesh(dp=4, tp=2))
        init8, step8 = make_train_step(CFG, plan, fp8=True, loss_chunk=64)
        state = init8(fp8.wrap_params_fp8(L.init_params(CFG, jax.random.PRNGKey(0))))
        toks = jax.random.randint(
            jax.random.PRNGKey(1), (4, 128), 0, CFG.vocab_size
        )
        state, _ = step8(state, toks)
        plain = fp8.unwrap_params_fp8(state["params"])
        out = L.greedy_generate(plain, CFG, jnp.array([[1, 2, 3]]), 4)
        assert out.shape == (1, 4)


class TestFp8Serving:
    def test_quantize_params_fp8_logits_close_and_generates(self):
        """Weight-only fp8 serving: logits must stay within e4m3 noise of
        bf16 (token-exactness is NOT asserted — e4m3's 3 mantissa bits are
        a coarser per-element grid than int8's per-channel 127 levels, and
        a random-init model's greedy argmax amplifies ties)."""
        params = L.init_params(CFG, jax.random.PRNGKey(0))
        qp = quantize_params(params, bits="fp8")
        assert qp["layers"]["wq"]["q"].dtype == jnp.float8_e4m3fn
        prompt = jnp.array([[1, 2, 3, 4]])
        lq = np.asarray(L.forward(qp, CFG, prompt)[:, -1])
        ld = np.asarray(L.forward(params, CFG, prompt)[:, -1])
        scale = float(np.max(np.abs(ld)))
        assert np.max(np.abs(lq - ld)) < 0.1 * scale
        # and the generate path executes end to end on the fp8 tree
        out = L.greedy_generate(qp, CFG, prompt, 8)
        assert out.shape == (1, 8)

    def test_dequantize_roundtrip_error_bounded(self):
        w = jax.random.normal(jax.random.PRNGKey(2), (64, 32), jnp.float32)
        q = fp8.quantize_weight_fp8(w, axis=1)
        back = dequantize_weight(q, jnp.float32)
        # e4m3: 3 mantissa bits → per-element relative error ≤ 2^-4
        err = np.max(np.abs(np.asarray(back) - np.asarray(w)))
        assert err < float(jnp.max(jnp.abs(w))) * 0.0725

    def test_env_plumbing_accepts_fp8(self, monkeypatch):
        from kubeflow_tpu.models.quant import quant_bits_from_env

        monkeypatch.setenv("KUBEFLOW_TPU_QUANT", "fp8")
        assert quant_bits_from_env() == "fp8"

    def test_mesh_replicates_fp8_metas(self):
        """param_spec must not hand a weight spec to a meta leaf (the
        substring match sees 'wq' inside 'layers/wq/fp8/x_hist')."""
        plan = MeshPlan(make_mesh(dp=2, tp=2, fsdp=2))
        from jax.sharding import PartitionSpec as P

        assert plan.param_spec(("layers", "wq", "fp8", "x_hist"), 2) == P()
        assert plan.param_spec(("layers", "wq", "hp"), 3) == P(
            None, "fsdp", "tp"
        )
