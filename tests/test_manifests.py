"""Deploy manifests: schema invariants + generator drift check.

Reference analog: ci/generate_code.sh fails CI when generated CRDs drift
from the Go types; ci/kustomize.sh validates every kustomization builds.
"""

from __future__ import annotations

import re
from pathlib import Path

import yaml

from kubeflow_tpu.api.notebook import VERSIONS
from kubeflow_tpu.deploy import manifests as m
from kubeflow_tpu.deploy.render import render_all
from kubeflow_tpu.tpu.topology import ACCELERATORS

REPO_ROOT = Path(__file__).resolve().parent.parent


def test_crd_serves_all_versions_with_v1beta1_storage():
    crd = m.notebook_crd()
    versions = {v["name"]: v for v in crd["spec"]["versions"]}
    assert set(versions) == set(VERSIONS)
    assert [n for n, v in versions.items() if v["storage"]] == ["v1beta1"]
    assert all(v["served"] for v in versions.values())
    assert all("status" in v["subresources"] for v in versions.values())


def test_crd_tpu_schema_matches_topology_catalog():
    crd = m.notebook_crd()
    schema = crd["spec"]["versions"][0]["schema"]["openAPIV3Schema"]
    tpu = schema["properties"]["spec"]["properties"]["tpu"]
    enum = tpu["properties"]["accelerator"]["enum"]
    for name in ACCELERATORS:
        assert name in enum
    pattern = re.compile(tpu["properties"]["topology"]["pattern"])
    assert pattern.match("4x4")
    assert pattern.match("2x2x2")
    assert not pattern.match("4x")
    assert tpu["required"] == ["accelerator", "topology"]


def test_crd_podspec_is_passthrough():
    schema = m.notebook_crd()["spec"]["versions"][0]["schema"]["openAPIV3Schema"]
    template = schema["properties"]["spec"]["properties"]["template"]
    pod_spec = template["properties"]["spec"]
    assert pod_spec["x-kubernetes-preserve-unknown-fields"] is True


def test_samples_validate_against_schema_essentials():
    for sample in (m.sample_cpu_notebook(), m.sample_tpu_notebook()):
        assert sample["kind"] == "Notebook"
        containers = sample["spec"]["template"]["spec"]["containers"]
        assert containers[0]["name"] == sample["metadata"]["name"]
    tpu = m.sample_tpu_notebook()["spec"]["tpu"]
    assert tpu["accelerator"] in ACCELERATORS
    assert re.match(r"^\d+x\d+(x\d+)?$", tpu["topology"])


def test_core_rbac_covers_reconciled_kinds():
    rules = m.core_cluster_role()["rules"]
    covered = {(g, r) for rule in rules for g in rule["apiGroups"] for r in rule["resources"]}
    for need in [
        ("kubeflow.org", "notebooks"),
        ("kubeflow.org", "notebooks/status"),
        ("apps", "statefulsets"),
        ("", "services"),
        ("", "pods"),
        ("", "events"),
        ("coordination.k8s.io", "leases"),
        ("networking.istio.io", "virtualservices"),
    ]:
        assert need in covered, need


def test_core_rbac_grants_slicepool_demand_signal_writes():
    """The notebook spawn path writes demand-signal annotations onto the
    SlicePool MAIN resource (controller/slicepool.py _stamp /
    _clear_demand_annotations via client.update) — with read-only verbs
    every TPU notebook spawn in a namespace with an autoscaled pool would
    403 in a real cluster, which fake-client tests cannot catch."""
    rules = m.core_cluster_role()["rules"]
    for rule in rules:
        if "slicepools" in rule["resources"]:
            assert "update" in rule["verbs"] and "patch" in rule["verbs"]
            break
    else:
        raise AssertionError("no slicepools rule in core ClusterRole")


def test_platform_rbac_covers_reconciled_kinds():
    rules = m.platform_cluster_role()["rules"]
    covered = {(g, r) for rule in rules for g in rule["apiGroups"] for r in rule["resources"]}
    for need in [
        ("gateway.networking.k8s.io", "httproutes"),
        ("gateway.networking.k8s.io", "referencegrants"),
        ("networking.k8s.io", "networkpolicies"),
        ("", "serviceaccounts"),
        ("rbac.authorization.k8s.io", "clusterrolebindings"),
        ("image.openshift.io", "imagestreams"),
        ("config.openshift.io", "apiservers"),
    ]:
        assert need in covered, need


def test_webhook_configurations_register_both_paths():
    mutating, validating = m.webhook_configurations()
    assert (
        mutating["webhooks"][0]["clientConfig"]["service"]["path"]
        == "/mutate-notebook-v1"
    )
    assert (
        validating["webhooks"][0]["clientConfig"]["service"]["path"]
        == "/validate-notebook-v1"
    )
    for cfg in (mutating, validating):
        rule = cfg["webhooks"][0]["rules"][0]
        assert set(rule["apiVersions"]) == set(VERSIONS)
        assert rule["operations"] == ["CREATE", "UPDATE"]


def test_platform_manager_requires_rbac_proxy_image_arg():
    dep = m.platform_manager_deployment()
    args = dep["spec"]["template"]["spec"]["containers"][0]["args"]
    assert any("--kube-rbac-proxy-image" in a for a in args)


def test_rendered_config_tree_has_no_drift():
    """tests-as-CI: config/ on disk must match the generator exactly
    (reference ci/generate_code.sh drift check)."""
    for rel, expected in render_all().items():
        path = REPO_ROOT / rel
        assert path.exists(), f"{rel} missing — run ci/generate_manifests.py"
        assert path.read_text() == expected, (
            f"{rel} drifted — run ci/generate_manifests.py"
        )


def test_rendered_yaml_parses_and_kustomizations_resolve():
    files = render_all()
    parsed: dict[str, list] = {}
    for rel, content in files.items():
        docs = [d for d in yaml.safe_load_all(content) if d]
        assert docs, rel
        parsed[rel] = docs
    # Every kustomization resource path must exist in the tree (or be a dir
    # containing a kustomization).
    dirs = {str(Path(rel).parent) for rel in files}
    for rel, docs in parsed.items():
        for doc in docs:
            if doc.get("kind") != "Kustomization":
                continue
            base = Path(rel).parent
            for res in doc.get("resources", []):
                target = (base / res).resolve().relative_to(REPO_ROOT.resolve())
                assert (
                    str(target) in {str(Path(r)) for r in files}
                    or str(target) in dirs
                ), f"{rel} references missing {res}"


def test_image_prepuller_targets_tpu_nodes_only():
    """The GKE overlay's pre-puller (spawn-latency lever, BASELINE <90s
    north star) must land on TPU nodes and tolerate the TPU taint."""
    from kubeflow_tpu.deploy.manifests import image_prepuller_daemonset

    ds = image_prepuller_daemonset(("img-a:1", "img-b:2"))
    spec = ds["spec"]["template"]["spec"]
    expr = spec["affinity"]["nodeAffinity"][
        "requiredDuringSchedulingIgnoredDuringExecution"
    ]["nodeSelectorTerms"][0]["matchExpressions"][0]
    assert expr == {
        "key": "cloud.google.com/gke-tpu-accelerator",
        "operator": "Exists",
    }
    assert any(t["key"] == "google.com/tpu" for t in spec["tolerations"])
    # First init copies a static no-op out of busybox; the prepull inits
    # run THAT, so distroless/scratch target images (no binaries at all)
    # still exit 0 instead of crash-looping the DaemonSet.
    inits = spec["initContainers"]
    assert inits[0]["image"].startswith("busybox")
    # busybox dispatches applets by argv[0]: the binary must keep its own
    # name and be invoked as "busybox sleep", never renamed (exit 127).
    assert inits[0]["command"][-2:] == ["/bin/busybox", "/prepull-tools/busybox"]
    assert [c["image"] for c in inits[1:]] == ["img-a:1", "img-b:2"]
    for c in inits[1:]:
        assert c["command"][:2] == ["/prepull-tools/busybox", "sleep"]
    # Main container only keeps the pod resident; init containers did the pull.
    assert len(spec["containers"]) == 1


def test_gke_overlay_namespaces_the_prepuller():
    """Overlay-level resources bypass the base's namespace transformer;
    the overlay must set the namespace itself or the DaemonSet lands in
    the nonexistent 'system' namespace."""
    files = render_all()
    overlay = yaml.safe_load(files["config/overlays/gke/kustomization.yaml"])
    assert overlay["namespace"] == "kubeflow-tpu-system"
