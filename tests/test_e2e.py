"""End-to-end lifecycle through the real manager entrypoints.

Reference analog: the e2e suite (reference
components/odh-notebook-controller/e2e/notebook_controller_setup_test.go:
102-128) runs subtests validate-controllers → create → update → delete on a
live cluster; per-notebook checks cover HTTPRoute config, NetworkPolicies,
rbac-proxy sidecar, service connectivity, and culling verification
(notebook_creation_test.go:417-519). Here the cluster is in-process but the
wiring is the production one: both cmd entrypoints, webhooks installed,
leader election on, fake kubelet scheduling onto TPU node pools.
"""

from __future__ import annotations

import pytest

from kubeflow_tpu import k8s
from kubeflow_tpu.api import annotations as ann
from kubeflow_tpu.cmd import notebook_manager, platform_manager
from kubeflow_tpu.k8s.manager import FakeClock

from tests.harness import FakeProber, tpu_notebook


@pytest.fixture
def e2e():
    """Both managers, webhooks, kubelet, culling enabled — production shape."""
    clock = FakeClock()
    cluster = k8s.FakeCluster(clock=clock)
    k8s.add_tpu_node_pool(
        cluster, "tpu-v5-lite-podslice", "4x4", hosts=4, chips_per_host=4
    )
    prober = FakeProber()
    prober.set_idle()
    platform = platform_manager.build(
        cluster,
        env={"K8S_NAMESPACE": "opendatahub"},
        argv=["--kube-rbac-proxy-image", "proxy:v1", "--enable-leader-election"],
        clock=clock,
    )
    core = notebook_manager.build(
        cluster,
        env={"ENABLE_CULLING": "true", "CULL_IDLE_TIME": "30"},
        argv=["--enable-leader-election"],
        clock=clock,
        prober=prober,
    )
    kubelet = k8s.FakeKubelet(cluster)
    kubelet.register(core.manager)
    assert core.elector.try_acquire() and platform.elector.try_acquire()

    class E2E:
        pass

    e = E2E()
    e.cluster, e.clock, e.core, e.platform, e.prober = (
        cluster, clock, core, platform, prober,
    )

    def settle(cycles: int = 6):
        for _ in range(cycles):
            platform.run_until_idle()
            core.run_until_idle()

    e.settle = settle
    return e


def test_full_notebook_lifecycle(e2e):
    # -- create ------------------------------------------------------------
    nb = tpu_notebook(name="wb", annotations={ann.INJECT_AUTH: "true"})
    created = e2e.cluster.create(nb)
    # Webhook ran: reconciliation lock + auth sidecar + TPU env.
    assert created["metadata"]["annotations"][ann.STOP] == ann.RECONCILIATION_LOCK_VALUE
    names = [c["name"] for c in created["spec"]["template"]["spec"]["containers"]]
    assert "kube-rbac-proxy" in names

    e2e.settle()

    # Slice up: 4 ready hosts, status mirrored, coordinator surfaced.
    obj = e2e.cluster.get("Notebook", "wb", "ns")
    assert obj["status"]["readyReplicas"] == 4
    assert obj["status"]["tpu"]["sliceHealth"] == "Healthy"
    assert obj["status"]["tpu"]["jaxCoordinator"]

    # Platform resources (reference e2e per-notebook checks).
    assert e2e.cluster.exists("HTTPRoute", "nb-ns-wb", "opendatahub")
    assert e2e.cluster.exists("ReferenceGrant", "notebook-httproute-access", "ns")
    assert e2e.cluster.exists("NetworkPolicy", "wb-ctrl-np", "ns")
    assert e2e.cluster.exists("NetworkPolicy", "wb-kube-rbac-proxy-np", "ns")
    assert e2e.cluster.exists("ServiceAccount", "wb-auth-proxy", "ns")
    assert e2e.cluster.exists("Service", "wb-kube-rbac-proxy", "ns")

    # -- update (running slice is protected) -------------------------------
    obj = e2e.cluster.get("Notebook", "wb", "ns")
    obj["metadata"]["annotations"][ann.LAST_IMAGE_SELECTION] = "missing:v2"
    e2e.cluster.update(obj)
    e2e.settle()
    obj = e2e.cluster.get("Notebook", "wb", "ns")
    assert obj["status"]["readyReplicas"] == 4  # still running, not restarted

    # -- cull --------------------------------------------------------------
    e2e.prober.set_idle(hosts=4, last_activity=e2e.clock.now())
    for _ in range(40):
        e2e.core.tick(120)
        e2e.platform.run_until_idle()
    obj = e2e.cluster.get("Notebook", "wb", "ns")
    assert obj["metadata"]["annotations"].get(ann.STOP) not in (
        None, ann.RECONCILIATION_LOCK_VALUE,
    ), "idle slice was not culled"
    sts = e2e.cluster.get("StatefulSet", "wb", "ns")
    assert sts["spec"]["replicas"] == 0  # atomic slice release
    assert e2e.cluster.list("Pod", "ns") == []

    # -- resume ------------------------------------------------------------
    obj = e2e.cluster.get("Notebook", "wb", "ns")
    del obj["metadata"]["annotations"][ann.STOP]
    e2e.cluster.update(obj)
    e2e.prober.set_busy(hosts=4)
    e2e.settle()
    assert e2e.cluster.get("Notebook", "wb", "ns")["status"]["readyReplicas"] == 4

    # -- delete ------------------------------------------------------------
    e2e.cluster.delete("Notebook", "wb", "ns")
    e2e.settle()
    assert not e2e.cluster.exists("Notebook", "wb", "ns")
    assert not e2e.cluster.exists("HTTPRoute", "nb-ns-wb", "opendatahub")
    assert not e2e.cluster.exists("ReferenceGrant", "notebook-httproute-access", "ns")
    assert not e2e.cluster.exists("StatefulSet", "wb", "ns")
    assert e2e.cluster.list("Pod", "ns") == []


def test_two_notebooks_share_reference_grant(e2e):
    k8s.add_tpu_node_pool(
        e2e.cluster, "tpu-v5-lite-podslice", "4x4",
        hosts=4, chips_per_host=4, name_prefix="pool2",
    )
    e2e.cluster.create(tpu_notebook(name="wb1"))
    e2e.cluster.create(tpu_notebook(name="wb2"))
    e2e.settle()
    assert e2e.cluster.exists("ReferenceGrant", "notebook-httproute-access", "ns")
    e2e.cluster.delete("Notebook", "wb1", "ns")
    e2e.settle()
    # Grant stays while wb2 lives (reference DeleteReferenceGrantIfLastNotebook).
    assert e2e.cluster.exists("ReferenceGrant", "notebook-httproute-access", "ns")
    e2e.cluster.delete("Notebook", "wb2", "ns")
    e2e.settle()
    assert not e2e.cluster.exists("ReferenceGrant", "notebook-httproute-access", "ns")


def test_preempted_host_recovers_and_surfaces_interruption(e2e):
    e2e.cluster.create(tpu_notebook(name="wb"))
    e2e.settle()
    # Spot preemption: kubelet marks the pod Failed with reason Preempted.
    pod = e2e.cluster.get("Pod", "wb-2", "ns")
    pod["status"] = {"phase": "Failed", "reason": "Preempted"}
    e2e.cluster.update_status(pod)
    e2e.settle()
    obj = e2e.cluster.get("Notebook", "wb", "ns")
    assert obj["status"]["readyReplicas"] == 4, "slice did not recover"
    # Interruption surfaced as Event (the reference's event re-emission
    # machinery is the diagnosis channel) and the annotation cleared once
    # the slice healed.
    events = e2e.cluster.list("Event", "ns")
    reasons = {e.get("reason") for e in events}
    assert "SliceInterrupted" in reasons
    assert "SliceRecovered" in reasons
    assert ann.TPU_SLICE_INTERRUPTED not in obj["metadata"].get("annotations", {})
