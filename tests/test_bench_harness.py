"""Bench-harness robustness: the scoreboard line must survive the
environment it runs in.

Round 3's official artifact was zeroed by a single transient axon-tunnel
hang (BENCH_r03.json: rc=1, "device enumeration hung (> 300s)") even
though the same-day measured headline was 48.9 tok/s. These tests pin the
round-4 posture: the device watchdog RETRIES with backoff, and when every
probe fails the bench emits the last measured headline with explicit
``provenance: cached`` instead of 0.0. Robustness model: the reference
culler never turns a probe error into a verdict
(components/notebook-controller/controllers/culling_controller.go:277-322).
"""

import importlib.util
import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def bench():
    spec = importlib.util.spec_from_file_location(
        "bench_under_test", os.path.join(REPO, "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class _FakeCompleted:
    def __init__(self, rc, stderr=b""):
        self.returncode = rc
        self.stderr = stderr


def test_watchdog_retries_then_succeeds(bench, monkeypatch):
    calls = {"n": 0}
    sleeps = []

    def fake_run(*a, **k):
        calls["n"] += 1
        if calls["n"] < 3:
            raise subprocess.TimeoutExpired(cmd="probe", timeout=k["timeout"])
        return _FakeCompleted(0)

    # subprocess/time are imported inside the function; patch the real ones.
    monkeypatch.setattr(subprocess, "run", fake_run)
    import time as time_mod

    monkeypatch.setattr(time_mod, "sleep", sleeps.append)

    assert bench._device_watchdog(probes=4, timeout_s=1) == ""
    assert calls["n"] == 3  # two hangs, then success — no fourth probe
    assert sleeps == [15, 30]  # backoff between probes


def test_watchdog_reports_last_failure_after_exhaustion(bench, monkeypatch):
    def fake_run(*a, **k):
        raise subprocess.TimeoutExpired(cmd="probe", timeout=k["timeout"])

    monkeypatch.setattr(subprocess, "run", fake_run)
    import time as time_mod

    monkeypatch.setattr(time_mod, "sleep", lambda s: None)
    reason = bench._device_watchdog(probes=3, timeout_s=1)
    assert "hung" in reason and "3/3" in reason


def test_watchdog_distinguishes_probe_error_from_hang(bench, monkeypatch):
    monkeypatch.setattr(
        subprocess, "run",
        lambda *a, **k: _FakeCompleted(1, b"RuntimeError: no TPU found\n"),
    )
    import time as time_mod

    monkeypatch.setattr(time_mod, "sleep", lambda s: None)
    reason = bench._device_watchdog(probes=2, timeout_s=1)
    assert reason.startswith("failed: ")
    assert "no TPU found" in reason


def test_cached_headline_prefers_most_recent_artifact(bench, tmp_path, monkeypatch):
    old = [{"metric": "decode bf16 tokens/sec", "value": 10.0,
            "unit": "tokens/sec/chip", "vs_baseline": 0.3}]
    new = [{"metric": "decode bf16 tokens/sec", "value": 48.9,
            "unit": "tokens/sec/chip", "vs_baseline": 1.6}]
    (tmp_path / "BENCH_FULL_r02.json").write_text(json.dumps(old))
    (tmp_path / "BENCH_FULL_r03.json").write_text(json.dumps(new))
    os.utime(tmp_path / "BENCH_FULL_r02.json", (1_000_000, 1_000_000))
    os.utime(tmp_path / "BENCH_FULL_r03.json", (2_000_000, 2_000_000))
    bench.__file__ = str(tmp_path / "bench.py")
    monkeypatch.chdir(tmp_path)
    entry, src = bench._cached_headline()
    assert src == "BENCH_FULL_r03.json"
    assert entry["value"] == 48.9


def test_cached_headline_breaks_mtime_ties_by_round(bench, tmp_path, monkeypatch):
    """A fresh checkout stamps every committed artifact with the same
    mtime — the round suffix must then decide, so an older round never
    shadows the live headline (VERDICT.md round 5)."""
    old = [{"metric": "decode bf16 tokens/sec", "value": 10.0,
            "unit": "tokens/sec/chip", "vs_baseline": 0.3}]
    new = [{"metric": "decode bf16 tokens/sec", "value": 48.9,
            "unit": "tokens/sec/chip", "vs_baseline": 1.6}]
    (tmp_path / "BENCH_FULL_r03.json").write_text(json.dumps(old))
    (tmp_path / "BENCH_FULL_r05_headline.json").write_text(json.dumps(new))
    for name in ("BENCH_FULL_r03.json", "BENCH_FULL_r05_headline.json"):
        os.utime(tmp_path / name, (1_000_000, 1_000_000))
    bench.__file__ = str(tmp_path / "bench.py")
    monkeypatch.chdir(tmp_path)
    entry, src = bench._cached_headline()
    assert src == "BENCH_FULL_r05_headline.json"
    assert entry["value"] == 48.9


def test_cached_headline_skips_corrupt_and_zero_artifacts(bench, tmp_path, monkeypatch):
    (tmp_path / "BENCH_FULL_bad.json").write_text("{not json")
    (tmp_path / "BENCH_FULL_zero.json").write_text(
        json.dumps([{"metric": "m bf16", "value": 0.0,
                     "unit": "tokens/sec/chip"}])
    )
    good = [{"metric": "m bf16 tokens/sec", "value": 50.3,
             "unit": "tokens/sec/chip", "vs_baseline": 1.7}]
    (tmp_path / "BENCH_FULL_r01.json").write_text(json.dumps(good))
    os.utime(tmp_path / "BENCH_FULL_r01.json", (1, 1))  # oldest on disk
    bench.__file__ = str(tmp_path / "bench.py")
    monkeypatch.chdir(tmp_path)
    entry, src = bench._cached_headline()
    assert src == "BENCH_FULL_r01.json"
    assert entry["value"] == 50.3


def test_cached_headline_rejects_mismatched_quant_config(bench, tmp_path, monkeypatch):
    """An --int8 run that fails must not be credited with a cached bf16
    number (and vice versa): a measurement under a different weight config
    is not this run's result."""
    bf16 = [{"metric": "llama decode (bs=1, bf16, fused loop)",
             "value": 48.9, "unit": "tokens/sec/chip", "vs_baseline": 1.6}]
    (tmp_path / "BENCH_FULL_r03.json").write_text(json.dumps(bf16))
    bench.__file__ = str(tmp_path / "bench.py")
    monkeypatch.chdir(tmp_path)
    entry, src = bench._cached_headline(quant_bits=8)
    assert entry is None and src is None
    entry, _ = bench._cached_headline(quant_bits=0)
    assert entry is not None and entry["value"] == 48.9


def test_cached_headline_searches_cwd_too(bench, tmp_path, monkeypatch):
    """--full artifacts written into the driver's cwd must be visible to a
    later fallback even though the script lives elsewhere."""
    script_dir = tmp_path / "repo"
    run_dir = tmp_path / "cwd"
    script_dir.mkdir(), run_dir.mkdir()
    art = [{"metric": "decode bf16", "value": 51.0,
            "unit": "tokens/sec/chip", "vs_baseline": 1.7}]
    (run_dir / "BENCH_FULL.json").write_text(json.dumps(art))
    bench.__file__ = str(script_dir / "bench.py")
    monkeypatch.chdir(run_dir)
    entry, src = bench._cached_headline()
    assert src == "BENCH_FULL.json" and entry["value"] == 51.0


def test_emit_cached_provenance_line(bench, tmp_path, capsys, monkeypatch):
    art = [{"metric": "llama decode bf16 tokens/sec/chip", "value": 48.9,
            "unit": "tokens/sec/chip", "vs_baseline": 1.63}]
    (tmp_path / "BENCH_FULL_r03.json").write_text(json.dumps(art))
    bench.__file__ = str(tmp_path / "bench.py")
    monkeypatch.chdir(tmp_path)

    rc = bench._emit_cached_or_zero("device enumeration hung (> 120s)")
    out = capsys.readouterr().out.strip().splitlines()
    parsed = json.loads(out[-1])
    # rc stays 1: the scoreboard line carries the real capability number,
    # but a dead tunnel must never look like a passing run to exit-status
    # gates.
    assert rc == 1
    assert parsed["value"] == 48.9
    assert parsed["provenance"] == "cached"
    assert parsed["cached_from"] == "BENCH_FULL_r03.json"
    assert "CACHED" in parsed["metric"]
    assert "hung" in parsed["live_failure"]


def test_emit_zero_when_no_cache_exists(bench, tmp_path, capsys, monkeypatch):
    bench.__file__ = str(tmp_path / "bench.py")
    monkeypatch.chdir(tmp_path)
    rc = bench._emit_cached_or_zero("device enumeration hung (> 120s)")
    parsed = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 1
    assert parsed["value"] == 0.0
    assert "no cached artifact" in parsed["metric"]


def test_repo_artifact_is_a_valid_cache_source(bench):
    """The real BENCH_FULL_r03.json in the repo must satisfy the cache
    contract (headline-first list with a tokens/sec value) so the fallback
    has something to emit on day one of round 4."""
    entry, src = bench._cached_headline()
    assert entry is not None and src is not None
    assert entry["value"] > 0
    assert "tokens/sec" in entry["unit"]


def test_cached_headline_matches_full_config_tokens(bench, tmp_path,
                                                    monkeypatch):
    """Weight dtype matches on its FULL token and the KV-cache format must
    agree: a bf16-weights + int8-KV headline must serve neither an --int8
    (weights) run nor a plain bf16 run, and 'int8' alone must not
    false-match the ', int8 KV' label."""
    art = [{"metric": "llama decode (bs=1, bf16, int8 KV, fused loop)",
            "value": 60.0, "unit": "tokens/sec/chip", "vs_baseline": 2.0}]
    (tmp_path / "BENCH_FULL_kv8.json").write_text(json.dumps(art))
    bench.__file__ = str(tmp_path / "bench.py")
    monkeypatch.chdir(tmp_path)
    assert bench._cached_headline(quant_bits=8)[0] is None  # int8 weights
    assert bench._cached_headline(quant_bits=0, kv_bits=0)[0] is None
    entry, _ = bench._cached_headline(quant_bits=0, kv_bits=8)
    assert entry is not None and entry["value"] == 60.0


def test_smoke_mode_headline_runs_on_cpu(bench):
    """BENCH_SMOKE=1 runs the headline on toy shapes/CPU (no watchdog, no
    chip) and exits 0 with a parseable JSON line — the executability
    guard that keeps 'section never ran anywhere' from recurring."""
    import os
    import subprocess
    import sys as _sys

    proc = subprocess.run(
        [_sys.executable, bench.__file__],
        env={**os.environ, "BENCH_SMOKE": "1"},
        capture_output=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr.decode()[-500:]
    line = json.loads(proc.stdout.decode().strip().splitlines()[-1])
    assert line["value"] > 0
    assert line["metric"].startswith("tiny ")


def test_smoke_mode_refuses_artifact(bench):
    """Toy smoke numbers must never enter the cached-headline search
    space: --artifact under BENCH_SMOKE is a usage error."""
    import os
    import subprocess
    import sys as _sys

    proc = subprocess.run(
        [_sys.executable, bench.__file__, "--full", "--artifact", "x.json"],
        env={**os.environ, "BENCH_SMOKE": "1"},
        capture_output=True, timeout=60,
    )
    assert proc.returncode == 2
    assert b"BENCH_SMOKE" in proc.stderr


def test_smoke_flag_falsey_strings(bench, monkeypatch):
    """Explicit BENCH_SMOKE=0/false/no means OFF — an operator forcing a
    real-chip run must not be routed to the CPU toy path."""
    for v in ("0", "false", "False", "no", "", "  "):
        monkeypatch.setenv("BENCH_SMOKE", v)
        assert not bench._smoke_enabled(), repr(v)
    for v in ("1", "true", "yes", "on"):
        monkeypatch.setenv("BENCH_SMOKE", v)
        assert bench._smoke_enabled(), repr(v)


class TestArtifactMerge:
    """Per-section incremental flushes merge with the artifact's PRIOR
    contents (newest wins per metric): wedge windows are shorter than the
    section list, so each window must extend — never reset — the capture."""

    def test_merge_newest_wins_and_carries_old(self, bench):
        new = [{"metric": "headline", "value": 2.0}]
        prev = [{"metric": "headline", "value": 1.0},
                {"metric": "train MFU", "value": 50.0}]
        merged = bench._merge_entries(new, prev)
        assert merged[0] == {"metric": "headline", "value": 2.0}
        assert {"metric": "train MFU", "value": 50.0} in merged
        assert len(merged) == 2

    def test_load_prev_tolerates_missing_corrupt_nonlist(self, bench, tmp_path):
        assert bench._load_prev_entries(str(tmp_path / "absent.json")) == []
        p = tmp_path / "torn.json"
        p.write_text('[{"metric": "x", "va')
        assert bench._load_prev_entries(str(p)) == []
        p.write_text('{"not": "a list"}')
        assert bench._load_prev_entries(str(p)) == []
        p.write_text('[{"metric": "x"}, "stray-string"]')
        assert bench._load_prev_entries(str(p)) == [{"metric": "x"}]
