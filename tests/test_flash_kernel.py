"""Streamed pallas flash kernel: interpret-mode correctness on CPU.

The kernel streams K/V blocks through VMEM on a (bh, q-blocks, k-blocks)
grid with f32 scratch accumulators and a custom_vjp backward (dq and dk/dv
kernels sharing the saved logsumexp). These tests run the SAME kernel code
in pallas interpret mode so CI covers it without TPU hardware; the real
Mosaic lowering is exercised by bench.py / the driver on the TPU chip.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import pytest

from kubeflow_tpu.ops import attention as A

if A.pl is None:  # pragma: no cover
    pytest.skip("pallas unavailable", allow_module_level=True)


def _qkv(sq, sk, h=2, b=1, d=128, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    return (
        jax.random.normal(ks[0], (b, h, sq, d), dtype),
        jax.random.normal(ks[1], (b, h, sk, d), dtype),
        jax.random.normal(ks[2], (b, h, sk, d), dtype),
    )


def _fwd(q, k, v, causal=True, q_offset=0, window=0):
    return A._flash_attention_pallas(
        q, k, v, causal, q_offset, window, interpret=True
    )


CASES = [
    ("causal", dict(causal=True), 256, 256),
    ("noncausal", dict(causal=False), 256, 256),
    ("offset", dict(causal=True, q_offset=256), 256, 512),
    ("window", dict(causal=True, window=100), 384, 384),
    ("window+offset", dict(causal=True, q_offset=128, window=150), 256, 384),
]


@pytest.mark.parametrize("name,kw,sq,sk", CASES, ids=[c[0] for c in CASES])
class TestForwardParity:
    def test_matches_xla(self, name, kw, sq, sk):
        q, k, v = _qkv(sq, sk)
        ref = A.flash_attention(q, k, v, impl="xla", **kw)
        got = _fwd(q, k, v, **{"causal": True, **kw})
        assert float(jnp.max(jnp.abs(ref - got))) < 1e-4


@pytest.mark.parametrize("name,kw,sq,sk", CASES, ids=[c[0] for c in CASES])
class TestBackwardParity:
    def test_grads_match_xla(self, name, kw, sq, sk):
        q, k, v = _qkv(sq, sk)
        # Position-dependent cotangent exercises every block distinctly.
        wgt = (
            jnp.arange(q.shape[0] * q.shape[1] * sq * q.shape[3])
            .reshape(q.shape[0], q.shape[1], sq, q.shape[3])
            .astype(jnp.float32) % 7.0 - 3.0
        )

        def loss(fn):
            return lambda q, k, v: jnp.sum(fn(q, k, v) * wgt)

        gx = jax.grad(
            loss(lambda q, k, v: A.flash_attention(q, k, v, impl="xla", **kw)),
            argnums=(0, 1, 2),
        )(q, k, v)
        gp = jax.grad(
            loss(lambda q, k, v: _fwd(q, k, v, **{"causal": True, **kw})),
            argnums=(0, 1, 2),
        )(q, k, v)
        for ref, got in zip(gx, gp):
            scale = float(jnp.max(jnp.abs(ref))) + 1e-9
            rel = float(jnp.max(jnp.abs(ref - got))) / scale
            assert rel < 1e-4


class TestLseResidual:
    def test_lse_matches_dense_logsumexp(self):
        q, k, v = _qkv(256, 256)
        b, h, sq, d = q.shape
        _, lse = A._fwd_pallas_call(
            q.reshape(b * h, sq, d), k.reshape(b * h, sq, d),
            v.reshape(b * h, sq, d), True, 0, 0, 128, 128, interpret=True,
        )
        import math

        s = jnp.einsum(
            "zqd,zkd->zqk", q.reshape(b * h, sq, d) / math.sqrt(d),
            k.reshape(b * h, sq, d),
        )
        mask = jnp.tril(jnp.ones((sq, sq), bool))
        s = jnp.where(mask, s, A.NEG_INF)
        ref = jax.nn.logsumexp(s, axis=-1)
        assert float(jnp.max(jnp.abs(ref - lse))) < 1e-4


class TestKvMask:
    """Padded-batch (serving) masking on the pallas path."""

    def test_fwd_matches_xla(self):
        q, k, v = _qkv(256, 256, b=2)
        kv_mask = jnp.ones((2, 256), bool).at[0, :64].set(False)
        ref = A.flash_attention(q, k, v, impl="xla", kv_mask=kv_mask)
        got = A._flash_attention_pallas(
            q, k, v, True, 0, 0, interpret=True, kv_mask=kv_mask
        )
        assert float(jnp.max(jnp.abs(ref - got))) < 1e-4

    def test_fwd_with_window_and_mask(self):
        q, k, v = _qkv(256, 384, b=2)
        kv_mask = jnp.ones((2, 384), bool).at[1, :50].set(False)
        ref = A.flash_attention(
            q, k, v, impl="xla", q_offset=128, window=100, kv_mask=kv_mask
        )
        got = A._flash_attention_pallas(
            q, k, v, True, 128, 100, interpret=True, kv_mask=kv_mask
        )
        assert float(jnp.max(jnp.abs(ref - got))) < 1e-4

    def test_grads_match_xla(self):
        q, k, v = _qkv(256, 256, b=2)
        kv_mask = jnp.ones((2, 256), bool).at[0, :32].set(False)

        def loss(fn):
            return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

        gx = jax.grad(
            loss(lambda q, k, v: A.flash_attention(
                q, k, v, impl="xla", kv_mask=kv_mask)),
            argnums=(0, 1, 2),
        )(q, k, v)
        gp = jax.grad(
            loss(lambda q, k, v: A._flash_attention_pallas(
                q, k, v, True, 0, 0, interpret=True, kv_mask=kv_mask)),
            argnums=(0, 1, 2),
        )(q, k, v)
        for ref, got in zip(gx, gp):
            scale = float(jnp.max(jnp.abs(ref))) + 1e-9
            assert float(jnp.max(jnp.abs(ref - got))) / scale < 1e-4


class TestGQANative:
    """K/V enter the kernel at their REAL head count; the index maps fold
    the q-head → kv-head group, so no repeated K/V is materialized."""

    def _gqa_qkv(self, h=8, hkv=2, sq=256, sk=256, b=2, d=128):
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        return (
            jax.random.normal(ks[0], (b, h, sq, d)),
            jax.random.normal(ks[1], (b, hkv, sk, d)),
            jax.random.normal(ks[2], (b, hkv, sk, d)),
        )

    def test_fwd_matches_repeated_xla(self):
        q, k, v = self._gqa_qkv()
        ref = A.flash_attention(q, k, v, impl="xla")  # broadcasts internally
        got = A._flash_attention_pallas(q, k, v, True, 0, 0, interpret=True)
        assert float(jnp.max(jnp.abs(ref - got))) < 1e-4

    def test_fwd_windowed(self):
        q, k, v = self._gqa_qkv(h=4, hkv=2, sq=256, sk=384)
        ref = A.flash_attention(q, k, v, impl="xla", q_offset=128, window=90)
        got = A._flash_attention_pallas(
            q, k, v, True, 128, 90, interpret=True
        )
        assert float(jnp.max(jnp.abs(ref - got))) < 1e-4

    def test_grads_match_repeated_xla(self):
        """dk/dv must sum over each kv head's whole q-head group."""
        q, k, v = self._gqa_qkv(h=4, hkv=2)

        def loss(fn):
            return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

        gx = jax.grad(
            loss(lambda q, k, v: A.flash_attention(q, k, v, impl="xla")),
            argnums=(0, 1, 2),
        )(q, k, v)
        gp = jax.grad(
            loss(lambda q, k, v: A._flash_attention_pallas(
                q, k, v, True, 0, 0, interpret=True)),
            argnums=(0, 1, 2),
        )(q, k, v)
        for ref, got in zip(gx, gp):
            assert ref.shape == got.shape
            scale = float(jnp.max(jnp.abs(ref))) + 1e-9
            assert float(jnp.max(jnp.abs(ref - got))) / scale < 1e-4

    def test_grads_gqa_window_offset_combined(self):
        """The dkv kernel's hardest path: GQA group sweep + sliding-window
        clamps + cached-continuation offset, all at once."""
        q, k, v = self._gqa_qkv(h=4, hkv=2, sq=256, sk=384)

        def loss(fn):
            return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

        gx = jax.grad(
            loss(lambda q, k, v: A.flash_attention(
                q, k, v, impl="xla", q_offset=128, window=120)),
            argnums=(0, 1, 2),
        )(q, k, v)
        gp = jax.grad(
            loss(lambda q, k, v: A._flash_attention_pallas(
                q, k, v, True, 128, 120, interpret=True)),
            argnums=(0, 1, 2),
        )(q, k, v)
        for ref, got in zip(gx, gp):
            scale = float(jnp.max(jnp.abs(ref))) + 1e-9
            assert float(jnp.max(jnp.abs(ref - got))) / scale < 1e-4

    def test_gqa_with_kv_mask(self):
        q, k, v = self._gqa_qkv(h=4, hkv=2)
        kv_mask = jnp.ones((2, 256), bool).at[0, :48].set(False)
        ref = A.flash_attention(q, k, v, impl="xla", kv_mask=kv_mask)
        got = A._flash_attention_pallas(
            q, k, v, True, 0, 0, interpret=True, kv_mask=kv_mask
        )
        assert float(jnp.max(jnp.abs(ref - got))) < 1e-4


class TestDispatch:
    def test_unaligned_lengths_fall_back(self):
        q, k, v = _qkv(100, 100)
        with pytest.raises(ValueError, match="128-aligned"):
            A._flash_attention_pallas(q, k, v, True, 0, 0, interpret=True)

    def test_mismatched_heads_rejected(self):
        q, _, _ = _qkv(256, 256)
        k = jax.random.normal(jax.random.PRNGKey(1), (1, 3, 256, 128))
        with pytest.raises(ValueError, match="not a multiple"):
            A.flash_attention(q, k, k)  # 2 q heads, 3 kv heads


class TestWholeKVVariant:
    """The forward dispatches to the whole-KV single-fetch kernel when K+V
    fit VMEM (_whole_kv_ok) and to the streamed grid otherwise. Both
    variants must agree with XLA — and with each other — since the
    streamed path is no longer exercised at small S by the tests above."""

    @pytest.mark.parametrize("name,kw,sq,sk", CASES, ids=[c[0] for c in CASES])
    def test_streamed_matches_xla_when_forced(self, name, kw, sq, sk,
                                              monkeypatch):
        monkeypatch.setattr(A, "_WHOLE_KV_MAX_BYTES", 0)  # force streaming
        q, k, v = _qkv(sq, sk)
        ref = A.flash_attention(q, k, v, impl="xla", **kw)
        got = _fwd(q, k, v, **{"causal": True, **kw})
        assert float(jnp.max(jnp.abs(ref - got))) < 1e-4

    def test_whole_and_streamed_agree(self, monkeypatch):
        q, k, v = _qkv(384, 384)
        whole = _fwd(q, k, v, causal=True)
        monkeypatch.setattr(A, "_WHOLE_KV_MAX_BYTES", 0)
        streamed = _fwd(q, k, v, causal=True)
        assert float(jnp.max(jnp.abs(whole - streamed))) < 1e-5

    def test_whole_kv_gqa_with_mask(self):
        ks = jax.random.split(jax.random.PRNGKey(3), 3)
        q = jax.random.normal(ks[0], (2, 4, 256, 128))
        k = jax.random.normal(ks[1], (2, 2, 256, 128))
        v = jax.random.normal(ks[2], (2, 2, 256, 128))
        kv_mask = jnp.ones((2, 256), bool).at[0, :64].set(False)
        ref = A.flash_attention(q, k, v, causal=True, impl="xla",
                                kv_mask=kv_mask)
        got = A._flash_attention_pallas(q, k, v, True, 0, interpret=True,
                                        kv_mask=kv_mask)
        assert float(jnp.max(jnp.abs(ref - got))) < 1e-4

    def test_dispatch_threshold(self):
        # bf16 K+V at S=8192, D=128 is exactly 4 MiB -> whole-KV eligible;
        # one step past the threshold must stream.
        assert A._whole_kv_ok(8192, 128, 2)
        assert not A._whole_kv_ok(8192 + 512, 128, 2)
