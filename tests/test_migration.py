"""runtime/migration.py: the deadline-budgeted live-migration pipeline.

The acceptance bar from the issue: forced failure of ANY single step
(save timeout, claim exhaustion, restore corruption, flip conflict)
must degrade to the reactive ladder — never hang, never silently lose
the notebook — and every attempt must read as one complete `migration`
trace with per-step spans.
"""

import threading

import pytest

from kubeflow_tpu.k8s.events import EventRecorder
from kubeflow_tpu.k8s.fake import FakeCluster
from kubeflow_tpu.metrics import Metrics
from kubeflow_tpu.observability import tracing
from kubeflow_tpu.observability.signals import FleetTelemetry, SignalsConfig
from kubeflow_tpu.runtime.migration import (
    MIGRATION_STEPS,
    MigrationConfig,
    MigrationOrchestrator,
    migration_from_env,
)


class _FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class _FakeCheckpoint:
    """Just enough CheckpointManager surface for the save step."""

    def __init__(self, age=float("inf"), latest=None, commit_ok=True):
        self.age = age
        self.latest = latest
        self.commit_ok = commit_ok
        self.emergency_calls = []

    def last_commit_age(self):
        return self.age

    def latest_step(self):
        return self.latest

    def emergency_save(self, grace_s):
        self.emergency_calls.append(grace_s)
        if self.commit_ok:
            self.latest = (self.latest or 0) + 1
            return True
        return False


def _orchestrator(clock=None, exporter=None, **kw):
    """A fully-wired orchestrator whose steps all succeed by default."""
    clock = clock or _FakeClock()
    kw.setdefault("checkpoint", _FakeCheckpoint(latest=3))
    kw.setdefault("claim_fn", lambda claimant, deadline: "pool-a")
    kw.setdefault("restore_fn", lambda deadline: {"step": 3, "start_batch": 4})
    kw.setdefault("flip_fn", lambda deadline: True)
    fallbacks = []
    kw.setdefault("fallback_fn", lambda step, reason: fallbacks.append((step, reason)))
    orch = MigrationOrchestrator(
        kw.pop("config", MigrationConfig()), clock=clock, **kw
    )
    orch._test_fallbacks = fallbacks
    return orch


@pytest.fixture()
def exporter():
    exp = tracing.InMemoryExporter()
    tracing.set_tracer_provider(tracing.TracerProvider(exporter=exp))
    yield exp
    tracing.set_tracer_provider(tracing.TracerProvider())


class TestPipeline:
    def test_happy_path_completes_with_full_trace(self, exporter):
        orch = _orchestrator()
        report = orch.migrate("preemption-notice")
        assert report.completed and not report.fell_back
        assert report.pool == "pool-a"
        assert report.restored_step == 3 and report.start_batch == 4
        assert set(report.steps) == set(MIGRATION_STEPS)
        assert all(s["ok"] for s in report.steps.values())
        # One complete trace: the root span plus one child per step.
        roots = exporter.by_name("migration")
        assert len(roots) == 1
        root = roots[0]
        assert root.attributes["completed"] is True
        for step in MIGRATION_STEPS:
            spans = exporter.by_name(f"migration.{step}")
            assert len(spans) == 1, f"missing span for step {step}"
            assert spans[0].parent_id == root.span_id
            assert spans[0].attributes["budget_s"] > 0

    def test_save_skipped_when_commit_is_fresh(self, exporter):
        ckpt = _FakeCheckpoint(age=1.0, latest=7)
        orch = _orchestrator(checkpoint=ckpt)
        report = orch.migrate("operator")
        assert report.completed
        assert ckpt.emergency_calls == []  # fresh → no redundant save
        assert "skipped" in report.steps["save"]["detail"]

    def test_stale_commit_forces_emergency_save(self):
        ckpt = _FakeCheckpoint(age=120.0, latest=7)
        orch = _orchestrator(checkpoint=ckpt)
        report = orch.migrate("operator")
        assert report.completed
        assert len(ckpt.emergency_calls) == 1
        # The save grace handed down is the step budget (minus epsilon).
        assert 0 < ckpt.emergency_calls[0] <= MigrationConfig().save_budget_s

    def test_concurrent_trigger_does_not_double_claim(self):
        claims = []
        release = threading.Event()

        def slow_claim(claimant, deadline):
            claims.append(claimant)
            release.wait(timeout=5.0)
            return "pool-a"

        orch = _orchestrator(claim_fn=slow_claim)
        t = threading.Thread(target=orch.migrate, args=("preemption-notice",),
                             daemon=True)
        t.start()
        while not claims:  # first migration is inside the claim step
            pass
        second = orch.migrate("operator")
        release.set()
        t.join(timeout=5.0)
        assert not t.is_alive()
        assert second.reason == "migration already in progress"
        assert not second.completed and not second.fell_back
        assert len(claims) == 1


class TestForcedStepFailures:
    """Each step's failure mode degrades to the ladder: fallback_fn is
    invoked with the failing step, the report says which step, the trace
    records the error — and nothing hangs or raises."""

    def _assert_fell_back(self, orch, report, step):
        assert report.fell_back and not report.completed
        assert report.failed_step == step
        assert orch._test_fallbacks and orch._test_fallbacks[0][0] == step
        stats = orch.stats()
        assert stats["migrations_started"] == 1
        assert stats["migrations_fell_back"] == 1
        assert stats["migrations_completed"] == 0
        assert stats["last_failed_step"] == step

    def test_save_timeout_falls_back(self, exporter):
        clock = _FakeClock()
        ckpt = _FakeCheckpoint(age=120.0, latest=None, commit_ok=False)

        def slow_save(grace_s):
            clock.advance(MigrationConfig().save_budget_s + 1.0)
            return False

        ckpt.emergency_save = slow_save
        orch = _orchestrator(clock=clock, checkpoint=ckpt)
        report = orch.migrate("preemption-notice")
        self._assert_fell_back(orch, report, "save")
        root = exporter.by_name("migration")[0]
        assert root.attributes["failed_step"] == "save"
        # The claim step never ran: no slice was leaked on a failed save.
        assert not exporter.by_name("migration.claim")

    def test_save_with_nothing_durable_falls_back(self):
        ckpt = _FakeCheckpoint(age=float("inf"), latest=None, commit_ok=False)
        orch = _orchestrator(checkpoint=ckpt)
        report = orch.migrate("preemption-notice")
        self._assert_fell_back(orch, report, "save")
        assert "none on disk" in report.reason

    def test_claim_exhaustion_falls_back(self):
        orch = _orchestrator(claim_fn=lambda claimant, deadline: None)
        report = orch.migrate("preemption-notice")
        self._assert_fell_back(orch, report, "claim")
        assert "exhausted" in report.reason

    def test_restore_corruption_falls_back(self):
        def corrupt_restore(deadline):
            raise RuntimeError("checksum mismatch: quarantined corrupt-3")

        orch = _orchestrator(restore_fn=corrupt_restore)
        report = orch.migrate("preemption-notice")
        self._assert_fell_back(orch, report, "restore")
        assert "checksum mismatch" in report.reason

    def test_flip_conflict_falls_back(self):
        orch = _orchestrator(flip_fn=lambda deadline: False)
        report = orch.migrate("preemption-notice")
        self._assert_fell_back(orch, report, "flip")
        assert "conflict" in report.reason or "refused" in report.reason

    def test_budget_blowout_mid_step_falls_back(self, exporter):
        clock = _FakeClock()

        def slow_restore(deadline):
            clock.advance(MigrationConfig().restore_budget_s + 5.0)
            return {"step": 3, "start_batch": 4}

        orch = _orchestrator(clock=clock, restore_fn=slow_restore)
        report = orch.migrate("preemption-notice")
        self._assert_fell_back(orch, report, "restore")
        assert "budget blown" in report.reason
        # Flip never ran: routing was not touched after the blowout.
        assert not exporter.by_name("migration.flip")

    def test_fallback_hook_crash_is_contained(self):
        def bad_hook(step, reason):
            raise RuntimeError("ladder hook exploded")

        orch = _orchestrator(claim_fn=lambda c, d: None, fallback_fn=bad_hook)
        report = orch.migrate("preemption-notice")  # must not raise
        assert report.fell_back and report.failed_step == "claim"


class TestObservability:
    def test_events_and_metrics_and_signals(self):
        client = FakeCluster()
        recorder = EventRecorder(client, component="migration")
        metrics = Metrics(client)
        telemetry = FleetTelemetry(SignalsConfig(window_s=60.0, windows=10))
        nb = {"apiVersion": "kubeflow.org/v1", "kind": "Notebook",
              "metadata": {"name": "nb", "namespace": "ns"}}
        orch = _orchestrator(metrics=metrics, telemetry=telemetry,
                             recorder=recorder, notebook=nb)
        report = orch.migrate("preemption-notice")
        assert report.completed
        reasons = {e["reason"] for e in client.list("Event", "ns")}
        assert "MigrationProgress" in reasons
        assert "MigrationCompleted" in reasons
        text = metrics.expose().decode()
        assert "tpu_migration_started_total 1.0" in text
        assert "tpu_migration_completed_total 1.0" in text
        assert "tpu_migration_fallback_total 0.0" in text
        snap = telemetry.snapshot()
        assert snap["fleet"]["migration_started_per_s"] > 0
        assert snap["fleet"]["migration_completed_per_s"] > 0
        assert snap["fleet"]["migration_fell_back_per_s"] == 0

    def test_fallback_emits_warning_event_and_counter(self):
        client = FakeCluster()
        recorder = EventRecorder(client, component="migration")
        metrics = Metrics(client)
        nb = {"apiVersion": "kubeflow.org/v1", "kind": "Notebook",
              "metadata": {"name": "nb", "namespace": "ns"}}
        orch = _orchestrator(metrics=metrics, recorder=recorder, notebook=nb,
                             claim_fn=lambda c, d: None)
        orch.migrate("idle-cull")
        events = client.list("Event", "ns")
        fell = [e for e in events if e["reason"] == "MigrationFellBack"]
        assert fell and fell[0]["type"] == "Warning"
        assert "reactive recovery ladder takes over" in fell[0]["message"]
        text = metrics.expose().decode()
        assert "tpu_migration_fallback_total 1.0" in text

    def test_stats_block_keys(self):
        orch = _orchestrator()
        orch.migrate("operator")
        stats = orch.stats()
        # Key literals double as the STATS_PARITY surface.
        for key in ("migrations_started", "migrations_completed",
                    "migrations_fell_back", "migration_last_s"):
            assert key in stats


class TestConfig:
    def test_validation_rejects_nonpositive_budgets(self):
        with pytest.raises(ValueError):
            MigrationConfig(claim_budget_s=0)
        with pytest.raises(ValueError):
            MigrationConfig(fresh_within_s=-1)

    def test_env_off_by_default(self):
        assert migration_from_env({}) is None
        assert migration_from_env({"KUBEFLOW_TPU_MIGRATE_ENABLE": "0"}) is None

    def test_env_opt_in_with_overrides(self):
        cfg = migration_from_env({
            "KUBEFLOW_TPU_MIGRATE_ENABLE": "true",
            "KUBEFLOW_TPU_MIGRATE_SAVE_BUDGET_S": "12",
            "KUBEFLOW_TPU_MIGRATE_FRESH_WITHIN_S": "0",
        })
        assert cfg is not None
        assert cfg.save_budget_s == 12.0
        assert cfg.fresh_within_s == 0.0
        assert cfg.claim_budget_s == MigrationConfig().claim_budget_s

    def test_env_fail_fast_on_garbage(self):
        with pytest.raises(ValueError):
            migration_from_env({"KUBEFLOW_TPU_MIGRATE_ENABLE": "yes"})
        with pytest.raises(ValueError):
            migration_from_env({
                "KUBEFLOW_TPU_MIGRATE_ENABLE": "1",
                "KUBEFLOW_TPU_MIGRATE_CLAIM_BUDGET_S": "banana",
            })
        with pytest.raises(ValueError):
            migration_from_env({
                "KUBEFLOW_TPU_MIGRATE_ENABLE": "1",
                "KUBEFLOW_TPU_MIGRATE_FLIP_BUDGET_S": "0.1",
            })
